# Test entry points. JAX_PLATFORMS=cpu matches tests/conftest.py's virtual
# 8-device CPU setup (and keeps a TPU plugin from grabbing the chip).

PY ?= python

.PHONY: test smoke bench-byzantine bench-churn bench-robust-scale

# Full fast suite (tier-1 shape, minus --continue-on-collection-errors:
# local runs should fail loudly on broken collection).
test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

# Fast robustness smoke: fault-injection + churn + Byzantine + gather-
# aggregation suites, first failure stops, strict collection (no marker
# typos, no swallowed import errors).
smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest -q -m 'not slow' -x \
		tests/test_faults.py tests/test_churn.py tests/test_byzantine.py \
		tests/test_robust_gather.py

# Regenerate the Byzantine breakdown evidence (docs/perf/byzantine.json).
bench-byzantine:
	JAX_PLATFORMS=cpu $(PY) examples/bench_byzantine.py

# Regenerate the correlated-failure evidence (docs/perf/churn.json).
bench-churn:
	JAX_PLATFORMS=cpu $(PY) examples/bench_churn.py

# Regenerate the degree-bounded robust-aggregation scaling evidence
# (docs/perf/robust_scale.json: gather-vs-dense e2e, asserted >= 5x floor
# at N=256 ring + crossover cells behind the robust_impl auto gate).
bench-robust-scale:
	JAX_PLATFORMS=cpu $(PY) examples/bench_robust_scale.py
