# Test entry points. JAX_PLATFORMS=cpu matches tests/conftest.py's virtual
# 8-device CPU setup (and keeps a TPU plugin from grabbing the chip).

PY ?= python

.PHONY: test smoke serve-smoke serve-restart-smoke observatory-smoke \
	scenarios-smoke fleet-smoke perf-diff bench-byzantine bench-churn \
	bench-robust-scale bench-sweep bench-compute bench-telemetry \
	bench-fused bench-serving bench-serving-load bench-fleet \
	bench-federated \
	bench-async bench-async-faults bench-observatory bench-mesh \
	bench-mesh-scale bench-scenarios bench-monitors

# Full fast suite (tier-1 shape, minus --continue-on-collection-errors:
# local runs should fail loudly on broken collection).
test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

# Fast robustness smoke: fault-injection + churn + Byzantine + gather-
# aggregation + replica-batched-parity + telemetry + serving +
# observatory suites, first failure stops, strict collection (no marker
# typos, no swallowed import errors); then the end-to-end observatory
# smoke (daemon up -> run -> scrape /metrics -> stream progress ->
# observatory compare + perf-diff self-check) over real HTTP.
smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest -q -m 'not slow' -x \
		tests/test_faults.py tests/test_churn.py tests/test_byzantine.py \
		tests/test_robust_gather.py tests/test_fused_robust.py \
		tests/test_compressed_gossip.py tests/test_batch.py \
		tests/test_telemetry.py tests/test_serving.py \
		tests/test_federated.py tests/test_async.py \
		tests/test_async_faults.py \
		tests/test_matrix_free_faults.py tests/test_observatory.py \
		tests/test_monitors.py tests/test_worker_mesh.py \
		tests/test_mesh_scale.py \
		tests/test_scenarios.py tests/test_scenario_chaos.py \
		tests/test_fleet.py
	$(MAKE) observatory-smoke
	$(MAKE) scenarios-smoke
	$(MAKE) serve-restart-smoke
	$(MAKE) fleet-smoke

# End-to-end scenario-engine smoke (docs/SCENARIOS.md): a seeded sample
# over a mixed axis bank (validity agreement + per-cell invariants +
# warm-replay identity through the real serving layer), then one
# operational chaos kill/restart cycle served warm from the surviving
# executable cache.
scenarios-smoke:
	JAX_PLATFORMS=cpu $(PY) examples/scenarios_smoke.py

# End-to-end live-observatory smoke over real HTTP (docs/OBSERVABILITY.md):
# boot the daemon, stream /v1/progress while a run executes, scrape
# /metrics mid-run (consistent-histogram check), then drive the
# observatory CLI (list/compare) over the served manifests and self-check
# make perf-diff against the committed docs/perf tree.
observatory-smoke:
	JAX_PLATFORMS=cpu $(PY) examples/observatory_smoke.py

# Perf-regression checker (ISSUE-10): re-check bench JSON in FRESH
# against the committed docs/perf within per-artifact tolerances
# (observability/observatory.py PERF_TOLERANCES; exit 1 on regression).
# Default FRESH=docs/perf is the self-check; point FRESH at a regen
# output directory to guard a new measurement session:
#   bash examples/regen_perf_artifacts.sh && make perf-diff FRESH=docs/perf
FRESH ?= docs/perf
perf-diff:
	$(PY) -m distributed_optimization_tpu.observatory perf-diff \
		--fresh $(FRESH) --committed docs/perf

# End-to-end serving smoke over real HTTP (docs/SERVING.md): boot the
# daemon, submit 3 requests (2 structurally identical -> ONE compile via
# one coalesced cohort, 1 outlier), assert cache/cohort facts + served
# responses match a direct run, shut down cleanly over the wire.
serve-smoke:
	JAX_PLATFORMS=cpu $(PY) examples/serve_smoke.py

# Full-process restart over the persistent executable store (ISSUE-15
# restart-warm gate): daemon A serves cold + writes through, SIGKILL,
# daemon B over the same store replays with 0 compile seconds and a
# bitwise-identical final gap.
serve-restart-smoke:
	JAX_PLATFORMS=cpu $(PY) examples/serve_restart_smoke.py

# Self-healing fleet chaos gate (docs/SCENARIOS.md, docs/SERVING.md
# "Self-healing"): each remediation policy and the autoscaler proven
# by its dedicated chaos mode — divergence halt + quarantine, store
# corruption quarantine + cold recompile, SIGKILL storm, burst/idle
# autoscale cycle — plus real worker-pool scale_up/scale_down.
fleet-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest -q -x -m slow tests/test_fleet.py

# Regenerate the Byzantine breakdown evidence (docs/perf/byzantine.json).
bench-byzantine:
	JAX_PLATFORMS=cpu $(PY) examples/bench_byzantine.py

# Regenerate the correlated-failure evidence (docs/perf/churn.json).
bench-churn:
	JAX_PLATFORMS=cpu $(PY) examples/bench_churn.py

# Regenerate the degree-bounded robust-aggregation scaling evidence
# (docs/perf/robust_scale.json: gather-vs-dense e2e, asserted >= 5x floor
# at N=256 ring + crossover cells behind the robust_impl auto gate).
bench-robust-scale:
	JAX_PLATFORMS=cpu $(PY) examples/bench_robust_scale.py

# Regenerate the replica-batched sweep-throughput evidence
# (docs/perf/sweep.json: run_batch aggregate vs sequential baseline per
# R, asserted regime-dependent floor — 8x at R=32 on accelerators, 2.5x
# steady on CPU hosts).
bench-sweep:
	JAX_PLATFORMS=cpu $(PY) examples/bench_sweep.py

# Regenerate the compute-bound tier evidence with its published MFU-floor
# gate (docs/perf/compute_bound.json; meaningful numbers need the real
# chip — on CPU containers set BENCH_NO_RANGE_CHECK=1).
bench-compute:
	$(PY) examples/bench_compute_bound.py

# Regenerate the flight-recorder overhead evidence
# (docs/perf/telemetry.json: telemetry off vs on, asserted <=10%
# steady-state ceiling + bitwise off/on trajectory gate).
bench-telemetry:
	JAX_PLATFORMS=cpu $(PY) examples/bench_telemetry.py

# Regenerate the fused-robust-kernel + compressed-gossip evidence
# (docs/perf/fused_robust.json: fused vs gather per rule with the
# compiled-path floor gated to accelerators + honest fused_loses flags,
# and bytes-vs-gap envelopes for {none,top_k,qsgd} x {dsgd,gt}).
bench-fused:
	JAX_PLATFORMS=cpu $(PY) examples/bench_fused_robust.py

# Regenerate the federated-regime evidence (docs/perf/federated.json:
# local-steps floats-to-eps reduction >= 2x floor, participation-rate
# convergence curves + q^2 cost model, matrix-free throughput/memory
# cells with the N=10k completion asserted).
bench-federated:
	JAX_PLATFORMS=cpu $(PY) examples/bench_federated.py

# Regenerate the asynchronous-gossip evidence (docs/perf/async.json:
# sync vs async iters/wall-clock-to-eps on a shared simulated latency
# realization — heavy-tail speedup floors, the constant-latency
# degenerate gate asserted == sync one-peer <= 1e-12, oracle parity).
bench-async:
	JAX_PLATFORMS=cpu $(PY) examples/bench_async.py

# Regenerate the event-clock fault evidence (docs/perf/async_faults.json:
# crash-free all-up injection asserted BITWISE vs the PR 9 async scan,
# gradient-tracking telescoping residual <= 1e-9 at any staleness with
# the staleness-vs-final-gap degradation curve, churn-vs-thinning
# no-free-lunch envelope at matched availability, and the >= 2x
# wall-clock-to-eps barrier floor surviving the fault composition).
bench-async-faults:
	JAX_PLATFORMS=cpu $(PY) examples/bench_async_faults.py

# Regenerate the serving-layer evidence (docs/perf/serving.json:
# executable-cache warm-vs-cold submit->start latency >= 10x floor,
# coalesced-cohort throughput >= 2.5x one-at-a-time on this CPU
# container, mixed-workload replay stats, f64 parity re-check).
bench-serving:
	JAX_PLATFORMS=cpu $(PY) examples/bench_serving.py

# Regenerate the sustained-load serving evidence
# (docs/perf/serving_load.json: scenario-sampled mixed traffic through
# the multi-worker daemon + persistent store — warm p50/p99 latency,
# saturation >= the PR-7 coalesced baseline, shed + fairness cells,
# restart-warm ratio, worker-plane f64 parity).
bench-serving-load:
	JAX_PLATFORMS=cpu $(PY) examples/bench_serving_load.py

# Self-healing fleet soak (docs/SERVING.md "Self-healing"): mixed
# traffic with chaos injections (planted divergence, worker SIGKILL,
# store corruption, burst/idle autoscale cycle) through the fleet
# reflex layer; every injection must come back remediated.
bench-fleet:
	JAX_PLATFORMS=cpu $(PY) examples/bench_fleet.py

# Regenerate the live-observatory evidence (docs/perf/observatory.json:
# heartbeat-on vs off steady-state overhead <= 3% ceiling + off/on
# bitwise gate, async-path cell, /metrics scrape p95 under load).
bench-observatory:
	JAX_PLATFORMS=cpu $(PY) examples/bench_observatory.py

# Regenerate the anomaly-sentinel evidence (docs/perf/monitors.json:
# ≤5% monitor overhead on the sequential + async paths, monitors-on
# bitwise, planted f>b divergence onset within 2 eval windows, early
# halt with attacker-naming incident — all gated).
bench-monitors:
	JAX_PLATFORMS=cpu $(PY) examples/bench_monitors.py

# Regenerate the scenario-matrix golden corpus (docs/perf/scenarios.json:
# validity-table agreement over a seeded 700-cell sample, the
# 34-composition golden matrix with per-cell invariants + warm replay,
# bitwise checkpoint-resume cells, and the operational chaos gates;
# forces 4 host devices itself for the worker-mesh cells).
bench-scenarios:
	$(PY) examples/bench_scenarios.py

# Regenerate the sharded worker-mesh evidence (docs/perf/worker_mesh.json:
# sharded-vs-unsharded bitwise parity, the N=100k completion over 4
# forced host devices, flat per-device memory at matched rows/device,
# N-independent ring ICI bytes — the script forces the 4-device host
# platform itself).
bench-mesh:
	$(PY) examples/bench_worker_mesh.py

# Regenerate the million-worker mesh evidence (docs/perf/mesh_scale.json:
# N=1M ring/torus sharded completions over 16 forced host devices, flat
# per-device memory at matched rows/device, the O(N·k_max) sparse ER
# build at 1M, the <=50% compressed-halo wire cut inside the 2.5x gap
# envelope, and the measured overlap ratio — the script forces the
# 16-device host platform itself).
bench-mesh-scale:
	$(PY) examples/bench_mesh_scale.py
