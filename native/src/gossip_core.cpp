// Native host-simulator core: the reference-semantics training loops in C++.
//
// The reference's hot path is T x N Python-level worker iterations with a
// full-dataset objective evaluation every iteration (reference
// trainer.py:41-71 centralized, trainer.py:161-193 decentralized). The numpy
// oracle backend reproduces those semantics faithfully but pays the Python
// interpreter per iteration; this core implements the reference's two
// algorithms (centralized SGD, D-SGD with an arbitrary dense mixing matrix)
// PLUS matrix/node-form recursions of the exact methods (DIGing gradient
// tracking, EXTRA, DLM decentralized ADMM) as tight C++ loops behind a
// plain C ABI, loaded via ctypes — the framework's native runtime tier for
// hosts (the TPU tier is XLA; see backends/cpp_backend.py).
//
// Semantics notes:
// - Batch sampling is without replacement via partial Fisher-Yates on a
//   SplitMix64/xoshiro256** stream seeded from (seed, t, worker): the numpy
//   oracle's exact batch sequence is not reproducible (different RNG), which
//   matches the framework-wide stance that cross-backend parity is
//   statistical unless batches are injected (SURVEY.md §7 hard part a).
// - Objectives/gradients use the same closed forms and stability guards as
//   ops/losses_np.py (stable softplus for logistic).
// - float64 throughout, like the numpy oracle.

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// ----------------------------------------------------------------- RNG
struct SplitMix64 {
  uint64_t s;
  explicit SplitMix64(uint64_t seed) : s(seed) {}
  uint64_t next() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
    return z ^ (z >> 31);
  }
};

struct Xoshiro256ss {
  uint64_t s[4];
  explicit Xoshiro256ss(uint64_t seed) {
    SplitMix64 sm(seed);
    for (auto &x : s) x = sm.next();
  }
  static uint64_t rotl(uint64_t x, int k) { return (x << k) | (x >> (64 - k)); }
  uint64_t next() {
    uint64_t result = rotl(s[1] * 5, 7) * 9;
    uint64_t t = s[1] << 17;
    s[2] ^= s[0];
    s[3] ^= s[1];
    s[1] ^= s[2];
    s[0] ^= s[3];
    s[2] ^= t;
    s[3] = rotl(s[3], 45);
    return result;
  }
  // Unbiased bounded draw (Lemire-style rejection).
  uint64_t bounded(uint64_t n) {
    uint64_t x, r;
    do {
      x = next();
      r = x % n;
    } while (x - r > UINT64_MAX - n + 1);
    return r;
  }
};

// Partial Fisher-Yates: first b entries of a shuffled [0, n) index range.
void sample_without_replacement(Xoshiro256ss &rng, int64_t n, int64_t b,
                                std::vector<int64_t> &scratch,
                                std::vector<int64_t> &out) {
  scratch.resize(n);
  for (int64_t i = 0; i < n; ++i) scratch[i] = i;
  out.resize(b);
  for (int64_t i = 0; i < b; ++i) {
    int64_t j = i + static_cast<int64_t>(rng.bounded(n - i));
    std::swap(scratch[i], scratch[j]);
    out[i] = scratch[i];
  }
}

// ------------------------------------------------------------- objectives
constexpr int kLogistic = 0;
constexpr int kQuadratic = 1;
constexpr int kHuber = 2;
constexpr int kSoftmax = 3;
// The Huber transition point delta is a run_simulation argument (single
// source: config.DEFAULT_HUBER_DELTA on the Python side) — no baked-in copy.
// Softmax (round 5): multinomial logistic with a [d, K] weight matrix
// stored flat row-major (w[j*K + k], matching the Python tiers'
// w.reshape(d, K)); labels are class indices carried in the y doubles
// (exact in float64). The MODEL length is d*K while data rows stay d —
// the driver below threads both (`dm` vs `d`).

inline double dot(const double *a, const double *b, int64_t d) {
  double acc = 0.0;
  for (int64_t k = 0; k < d; ++k) acc += a[k] * b[k];
  return acc;
}

// logits[k] = sum_j x[j] * w[j*K + k]; returns nothing, fills K slots.
inline void softmax_logits(const double *xi, const double *w, int64_t d,
                           int64_t K, double *logits) {
  std::memset(logits, 0, sizeof(double) * K);
  for (int64_t j = 0; j < d; ++j) {
    const double xj = xi[j];
    if (xj == 0.0) continue;
    const double *wj = w + j * K;
    for (int64_t k = 0; k < K; ++k) logits[k] += xj * wj[k];
  }
}

// Full-dataset objective: mean loss + (reg/2)||w||^2 (losses_np parity).
// `dm` = model length (d for scalar GLMs, d*K for softmax).
double full_objective(int problem, const double *X, const double *y,
                      int64_t n, int64_t d, int64_t n_classes, int64_t dm,
                      const double *w, double reg, double huber_delta) {
  double acc = 0.0;
  if (problem == kSoftmax) {
    const int64_t K = n_classes;
#pragma omp parallel
    {
      std::vector<double> logits(K);
#pragma omp for reduction(+ : acc) schedule(static)
      for (int64_t i = 0; i < n; ++i) {
        softmax_logits(X + i * d, w, d, K, logits.data());
        double m = logits[0];
        for (int64_t k = 1; k < K; ++k) m = std::max(m, logits[k]);
        double se = 0.0;
        for (int64_t k = 0; k < K; ++k) se += std::exp(logits[k] - m);
        const auto yi = static_cast<int64_t>(y[i]);
        acc += m + std::log(se) - logits[yi];
      }
    }
  } else {
#pragma omp parallel for reduction(+ : acc) schedule(static)
    for (int64_t i = 0; i < n; ++i) {
      double z = dot(X + i * d, w, d);
      if (problem == kLogistic) {
        double yz = y[i] * z;
        // stable log(1 + exp(-yz)) = max(0, -yz) + log1p(exp(-|yz|))
        double m = yz < 0.0 ? -yz : 0.0;
        acc += m + std::log1p(std::exp(-std::fabs(yz)));
      } else if (problem == kQuadratic) {
        double r = z - y[i];
        acc += 0.5 * r * r;
      } else {  // kHuber
        double r = z - y[i];
        double a = std::fabs(r);
        acc += a <= huber_delta ? 0.5 * r * r
                                : huber_delta * (a - 0.5 * huber_delta);
      }
    }
  }
  double obj = acc / static_cast<double>(n);
  obj += 0.5 * reg * dot(w, w, dm);
  return obj;
}

// Stochastic gradient over batch rows `idx` of one worker's shard.
// g_out has `dm` slots; `logits` is caller-provided [K] scratch (softmax).
void stochastic_gradient(int problem, const double *Xs, const double *ys,
                         int64_t d, int64_t n_classes, int64_t dm,
                         const std::vector<int64_t> &idx,
                         const double *w, double reg, double huber_delta,
                         std::vector<double> &logits, double *g_out) {
  std::memset(g_out, 0, sizeof(double) * dm);
  const auto b = static_cast<int64_t>(idx.size());
  if (b == 0) {
    for (int64_t k = 0; k < dm; ++k) g_out[k] = reg * w[k];
    return;
  }
  for (int64_t t = 0; t < b; ++t) {
    const double *xi = Xs + idx[t] * d;
    if (problem == kSoftmax) {
      const int64_t K = n_classes;
      softmax_logits(xi, w, d, K, logits.data());
      double m = logits[0];
      for (int64_t k = 1; k < K; ++k) m = std::max(m, logits[k]);
      double se = 0.0;
      for (int64_t k = 0; k < K; ++k) {
        logits[k] = std::exp(logits[k] - m);
        se += logits[k];
      }
      const double inv_se = 1.0 / se;
      for (int64_t k = 0; k < K; ++k) logits[k] *= inv_se;  // now P
      logits[static_cast<int64_t>(ys[idx[t]])] -= 1.0;      // P - onehot
      for (int64_t j = 0; j < d; ++j) {
        const double xj = xi[j];
        if (xj == 0.0) continue;
        double *gj = g_out + j * K;
        for (int64_t k = 0; k < K; ++k) gj[k] += xj * logits[k];
      }
      continue;
    }
    double z = dot(xi, w, d);
    double coef;
    if (problem == kLogistic) {
      double yz = ys[idx[t]] * z;
      // -y * sigmoid(-yz)
      double s = 1.0 / (1.0 + std::exp(yz));
      coef = -ys[idx[t]] * s;
    } else if (problem == kQuadratic) {
      coef = z - ys[idx[t]];
    } else {  // kHuber: clip(r, -delta, delta)
      double r = z - ys[idx[t]];
      coef = r > huber_delta ? huber_delta
                             : (r < -huber_delta ? -huber_delta : r);
    }
    for (int64_t k = 0; k < d; ++k) g_out[k] += coef * xi[k];
  }
  double inv_b = 1.0 / static_cast<double>(b);
  for (int64_t k = 0; k < dm; ++k) g_out[k] = g_out[k] * inv_b + reg * w[k];
}

}  // namespace

extern "C" {

// Shared driver for all six algorithms.
//
// X, y: concatenated per-worker shards, [n_total, d] row-major / [n_total]
//       (softmax labels are class indices carried in the y doubles);
// n_classes: 1 for the scalar GLMs; K >= 2 for softmax (problem 3), whose
//       model rows are flat [d*K] matrices (out_models is then
//       [n_workers, d*K]);
// offsets: [n_workers + 1] shard boundaries into X/y rows;
// W: [n_workers, n_workers] dense mixing matrix (ignored when centralized);
// algorithm: 0 = centralized (parameter-server SGD), 1 = D-SGD,
//            2 = gradient tracking (DIGing), 3 = EXTRA, 4 = decentralized
//            linearized ADMM (DLM, Ling et al. '15), 5 = CHOCO-SGD
//            (Koloskova et al. '19 Alg. 2, deterministic compressors),
//            6 = push-sum SGP (Nedić-Olshevsky '16 / Assran et al. '19
//            Alg. 1; W is then COLUMN-stochastic — the caller passes the
//            directed topology's uniform-out-weight matrix) —
//            2..6 are the recursions the numpy oracle also implements
//            (backends/numpy_backend.py), for cross-tier verification.
//            ADMM derives the 0/1 adjacency and degrees from W's
//            off-diagonal support (MH weights are strictly positive on
//            edges) and uses constant penalties (admm_c, admm_rho) — eta0
//            and sqrt_decay are ignored for it. CHOCO uses
//            (compression, comp_k, choco_gamma): compression 0 = identity,
//            1 = per-row top-k by magnitude with ties broken toward the
//            lower index (a stable descending sort — matches lax.top_k and
//            the numpy oracle);
// sqrt_decay: 1 = eta0/sqrt(t+1), 0 = constant eta0;
// huber_delta: Huber transition point (problem 2 only; must be > 0) — the
//            caller passes config.huber_delta so all three tiers share one
//            source (config.DEFAULT_HUBER_DELTA is the default);
// out_models: [n_workers, d] final per-worker models (centralized: rows equal);
// collect_metrics: 0 skips all objective/consensus evaluation (pure
//            iteration throughput; out_gap/out_cons left untouched);
// out_gap:   [T / eval_every] full-data objective values (NOT gap; caller
//            subtracts f_opt host-side);
// out_cons:  [T / eval_every] consensus error, untouched when centralized;
// out_times: [T / eval_every] MEASURED wall-clock seconds since run start at
//            each eval boundary (always filled — the numpy oracle and the
//            jax measured-timestamps path record the same thing, reference
//            trainer.py:63,181).
// Returns 0 on success, nonzero on invalid arguments.
int run_simulation(const double *X, const double *y, const int64_t *offsets,
                   int64_t n_workers, int64_t d, int64_t n_classes,
                   const double *W,
                   int algorithm, int problem, int64_t T,
                   int64_t batch_size, double eta0, int sqrt_decay,
                   double reg, double huber_delta,
                   double admm_c, double admm_rho,
                   int compression, int64_t comp_k, double choco_gamma,
                   uint64_t seed,
                   int64_t eval_every, int collect_metrics,
                   double *out_models, double *out_gap, double *out_cons,
                   double *out_times) {
  constexpr int kCentralized = 0, kDsgd = 1, kGT = 2, kExtra = 3, kAdmm = 4,
                kChoco = 5, kPushSum = 6;
  if (n_workers <= 0 || d <= 0 || T < 0 || eval_every <= 0 ||
      T % eval_every != 0 || batch_size < 0) {
    return 1;
  }
  if (problem < kLogistic || problem > kSoftmax) return 2;
  if (problem == kHuber && huber_delta <= 0.0) return 2;
  if (problem == kSoftmax && n_classes < 2) return 2;
  if (problem != kSoftmax && n_classes != 1) return 2;
  if (problem == kSoftmax) {
    // Labels index the [K] logits buffer; an out-of-range label would be
    // an out-of-bounds write in the gradient kernel. Validate up front
    // (the numpy tier raises IndexError for the same input).
    const int64_t nt = offsets[n_workers];
    for (int64_t i = 0; i < nt; ++i) {
      const auto yi = static_cast<int64_t>(y[i]);
      if (yi < 0 || yi >= n_classes) return 2;
    }
  }
  if (algorithm < kCentralized || algorithm > kPushSum) return 3;
  const bool centralized = algorithm == kCentralized;
  const int64_t n_total = offsets[n_workers];
  // Model row length: d for scalar GLMs, the flat d*K matrix for softmax
  // (data rows stay d wide — only the objective/gradient kernels bridge
  // the two shapes; every algorithm recursion is elementwise/mixing over
  // model coordinates, so it runs unchanged over dm).
  const int64_t dm = problem == kSoftmax ? d * n_classes : d;
  const int64_t nd = n_workers * dm;
  if (algorithm == kAdmm && (admm_c <= 0.0 || admm_rho <= 0.0)) return 4;
  if (algorithm == kChoco &&
      (choco_gamma <= 0.0 || compression < 0 || compression > 1 ||
       (compression == 1 && (comp_k <= 0 || comp_k > dm)))) {
    return 5;
  }

  std::vector<double> models(nd, 0.0);
  std::vector<double> grads(nd, 0.0);
  std::vector<double> mixed(nd, 0.0);
  std::vector<double> avg(dm, 0.0);
  // Extension state (allocated only when used).
  std::vector<double> y_trk, g_prev, x_prev, Wx_prev, Wy;
  std::vector<double> adj, deg, alpha, nbr;
  if (algorithm == kGT) {
    y_trk.assign(nd, 0.0);
    g_prev.assign(nd, 0.0);
    Wy.assign(nd, 0.0);
  } else if (algorithm == kExtra) {
    x_prev.assign(nd, 0.0);
    Wx_prev.assign(nd, 0.0);
    g_prev.assign(nd, 0.0);
  } else if (algorithm == kAdmm) {
    // 0/1 adjacency + degrees from W's off-diagonal support (MH weights
    // are strictly positive exactly on edges).
    adj.assign(n_workers * n_workers, 0.0);
    deg.assign(n_workers, 0.0);
    for (int64_t i = 0; i < n_workers; ++i) {
      for (int64_t j = 0; j < n_workers; ++j) {
        if (i != j && W[i * n_workers + j] > 0.0) {
          adj[i * n_workers + j] = 1.0;
          deg[i] += 1.0;
        }
      }
    }
    alpha.assign(nd, 0.0);
    nbr.assign(nd, 0.0);  // A x_0 = 0 for x_0 = 0 (matches algorithms/admm.py)
  }
  std::vector<double> xhat, x_half, Wxhat;
  if (algorithm == kChoco) {
    xhat.assign(nd, 0.0);
    x_half.assign(nd, 0.0);
    Wxhat.assign(nd, 0.0);
  }
  // Push-sum state: `models` holds the de-biased estimates z (so the shared
  // metric/output blocks see the meaningful quantity, matching the other
  // tiers); num/wmass carry the recursion, wmass_0 = 1.
  std::vector<double> num, wmass, wmass_next;
  if (algorithm == kPushSum) {
    num.assign(nd, 0.0);
    wmass.assign(n_workers, 1.0);
    wmass_next.assign(n_workers, 0.0);
  }

  // grads <- per-worker stochastic gradient at `at` (row i per worker, or
  // the shared row 0 when `shared`), batches keyed by (seed, t, worker) —
  // the counter-based-key design of ops/sampling.py, host-side.
  auto compute_grads = [&](const double *at, bool shared, int64_t t) {
#pragma omp parallel
    {
      std::vector<int64_t> scratch, idx;
      std::vector<double> logits(problem == kSoftmax ? n_classes : 0);
#pragma omp for schedule(static)
      for (int64_t i = 0; i < n_workers; ++i) {
        const int64_t lo = offsets[i], hi = offsets[i + 1];
        const int64_t ni = hi - lo;
        const int64_t b = batch_size < ni ? batch_size : ni;
        Xoshiro256ss rng(seed ^ (0x9e3779b97f4a7c15ULL * (uint64_t)(t + 1)) ^
                         (0xbf58476d1ce4e5b9ULL * (uint64_t)(i + 1)));
        if (ni > 0 && b > 0) {
          sample_without_replacement(rng, ni, b, scratch, idx);
        } else {
          idx.clear();
        }
        const double *params = shared ? at : at + i * dm;
        stochastic_gradient(problem, X + lo * d, y + lo, d, n_classes,
                            dm, idx, params, reg, huber_delta,
                            logits, grads.data() + i * dm);
      }
    }
  };

  // out <- mat @ in ([N, d] row-major; mat is [N, N] row-major).
  auto apply_mat = [&](const double *mat, const std::vector<double> &in,
                       std::vector<double> &out) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n_workers; ++i) {
      double *oi = out.data() + i * dm;
      std::memset(oi, 0, sizeof(double) * dm);
      for (int64_t j = 0; j < n_workers; ++j) {
        const double w_ij = mat[i * n_workers + j];
        if (w_ij == 0.0) continue;
        const double *xj = in.data() + j * dm;
        for (int64_t k = 0; k < dm; ++k) oi[k] += w_ij * xj[k];
      }
    }
  };
  auto apply_W = [&](const std::vector<double> &in, std::vector<double> &out) {
    apply_mat(W, in, out);
  };

  const auto run_start = std::chrono::steady_clock::now();

  for (int64_t t = 0; t < T; ++t) {
    const double eta =
        sqrt_decay ? eta0 / std::sqrt(static_cast<double>(t) + 1.0) : eta0;

    if (algorithm == kCentralized) {
      compute_grads(models.data(), /*shared=*/true, t);
      // psum-mean of worker gradients, step the (shared) row-0 model.
      for (int64_t i = 1; i < n_workers; ++i)
        for (int64_t k = 0; k < dm; ++k) grads[k] += grads[i * dm + k];
      const double inv_n = 1.0 / static_cast<double>(n_workers);
      for (int64_t k = 0; k < dm; ++k)
        models[k] -= eta * grads[k] * inv_n;
    } else if (algorithm == kDsgd) {
      // D-PSGD: grads at local x_t (pre-mix), x_{t+1} = W x_t - eta g_t.
      compute_grads(models.data(), /*shared=*/false, t);
      apply_W(models, mixed);
#pragma omp parallel for schedule(static)
      for (int64_t i = 0; i < n_workers; ++i) {
        double *mi = mixed.data() + i * dm;
        const double *gi = grads.data() + i * dm;
        for (int64_t k = 0; k < dm; ++k) mi[k] -= eta * gi[k];
      }
      models.swap(mixed);
    } else if (algorithm == kGT) {
      // DIGing: x_{t+1} = W x_t - eta y_t; y_{t+1} = W y_t + g_{t+1} - g_t
      // (y_0 = g_prev = 0 -> pure gossip first step). Matches
      // numpy_backend's matrix form and the jax step rule.
      apply_W(models, mixed);
      for (int64_t r = 0; r < nd; ++r) mixed[r] -= eta * y_trk[r];
      models.swap(mixed);
      compute_grads(models.data(), /*shared=*/false, t);
      apply_W(y_trk, Wy);
      for (int64_t r = 0; r < nd; ++r) {
        y_trk[r] = Wy[r] + grads[r] - g_prev[r];
        g_prev[r] = grads[r];
      }
    } else if (algorithm == kChoco) {
      // CHOCO-SGD (Koloskova et al. '19 Alg. 2):
      //   x_half = x − η g(x)
      //   x̂    += Q(x_half − x̂)        ← the only bits transmitted
      //   x      = x_half + γ (W x̂ − x̂)
      // Q = identity or per-row top-k by |v| (stable descending order, ties
      // toward the lower index — the numpy oracle's _topk_rows exactly).
      compute_grads(models.data(), /*shared=*/false, t);
#pragma omp parallel
      {
        std::vector<int64_t> order;
#pragma omp for schedule(static)
        for (int64_t i = 0; i < n_workers; ++i) {
          double *hi = x_half.data() + i * dm;
          const double *xi = models.data() + i * dm;
          const double *gi = grads.data() + i * dm;
          double *xh = xhat.data() + i * dm;
          for (int64_t k = 0; k < dm; ++k) hi[k] = xi[k] - eta * gi[k];
          if (compression == 0) {
            for (int64_t k = 0; k < dm; ++k) xh[k] = hi[k];
          } else {
            order.resize(dm);
            for (int64_t k = 0; k < dm; ++k) order[k] = k;
            // Stable descending sort by |x_half − x̂|; take the first k.
            std::stable_sort(order.begin(), order.end(),
                             [&](int64_t a, int64_t b) {
                               return std::fabs(hi[a] - xh[a]) >
                                      std::fabs(hi[b] - xh[b]);
                             });
            for (int64_t r = 0; r < comp_k; ++r)
              xh[order[r]] = hi[order[r]];  // x̂ += (x_half − x̂) on support
          }
        }
      }
      apply_W(xhat, Wxhat);
#pragma omp parallel for schedule(static)
      for (int64_t i = 0; i < n_workers; ++i) {
        double *xi = models.data() + i * dm;
        const double *hi = x_half.data() + i * dm;
        const double *wi = Wxhat.data() + i * dm;
        const double *xh = xhat.data() + i * dm;
        for (int64_t k = 0; k < dm; ++k)
          xi[k] = hi[k] + choco_gamma * (wi[k] - xh[k]);
      }
    } else if (algorithm == kPushSum) {
      // Push-sum SGP (Nedić-Olshevsky '16; Assran et al. '19 Alg. 1), W
      // column-stochastic:
      //   num <- W (num − η g(z));  wmass <- W wmass;  z = num / wmass
      // Gradients at the de-biased z (= `models`). Matches the numpy
      // oracle's matrix form and the jax step rule leaf-for-leaf.
      compute_grads(models.data(), /*shared=*/false, t);
#pragma omp parallel for schedule(static)
      for (int64_t r = 0; r < nd; ++r) num[r] -= eta * grads[r];
      apply_W(num, mixed);
      num.swap(mixed);
      for (int64_t i = 0; i < n_workers; ++i) {
        double acc = 0.0;
        for (int64_t j = 0; j < n_workers; ++j) {
          acc += W[i * n_workers + j] * wmass[j];
        }
        wmass_next[i] = acc;
      }
      wmass.swap(wmass_next);
#pragma omp parallel for schedule(static)
      for (int64_t i = 0; i < n_workers; ++i) {
        const double inv_w = 1.0 / wmass[i];
        double *zi = models.data() + i * dm;
        const double *ni = num.data() + i * dm;
        for (int64_t k = 0; k < dm; ++k) zi[k] = ni[k] * inv_w;
      }
    } else if (algorithm == kAdmm) {
      // DLM (Ling et al. '15), node form — same recursion as
      // algorithms/admm.py and numpy_backend's half-Laplacian matrix form:
      //   x_{k+1} = (rho x + c/2 (deg x + A x) - g - alpha) / (rho + c deg)
      //   nbr     = A x_{k+1}
      //   alpha  += c/2 (deg x_{k+1} - nbr)
      // `nbr` carries A x across iterations (one exchange per step).
      compute_grads(models.data(), /*shared=*/false, t);
#pragma omp parallel for schedule(static)
      for (int64_t i = 0; i < n_workers; ++i) {
        const double di = deg[i];
        const double inv_denom = 1.0 / (admm_rho + admm_c * di);
        double *mi = mixed.data() + i * dm;
        const double *xi = models.data() + i * dm;
        const double *gi = grads.data() + i * dm;
        const double *ai = alpha.data() + i * dm;
        const double *ni = nbr.data() + i * dm;
        for (int64_t k = 0; k < dm; ++k) {
          mi[k] = (admm_rho * xi[k] + 0.5 * admm_c * (di * xi[k] + ni[k]) -
                   gi[k] - ai[k]) *
                  inv_denom;
        }
      }
      models.swap(mixed);
      apply_mat(adj.data(), models, nbr);
#pragma omp parallel for schedule(static)
      for (int64_t i = 0; i < n_workers; ++i) {
        const double di = deg[i];
        double *ai = alpha.data() + i * dm;
        const double *xi = models.data() + i * dm;
        const double *ni = nbr.data() + i * dm;
        for (int64_t k = 0; k < dm; ++k)
          ai[k] += 0.5 * admm_c * (di * xi[k] - ni[k]);
      }
    } else {  // kExtra
      // EXTRA: x_1 = W x_0 - eta g(x_0);
      // x_{t+1} = x_t + W x_t - (x_{t-1} + W x_{t-1})/2 - eta (g_t - g_{t-1}).
      // Wx_prev carries the previous iteration's mix (one mix per step).
      compute_grads(models.data(), /*shared=*/false, t);
      apply_W(models, mixed);  // mixed = W x_t
      if (t == 0) {
        for (int64_t r = 0; r < nd; ++r) {
          x_prev[r] = models[r];
          Wx_prev[r] = mixed[r];
          g_prev[r] = grads[r];
          models[r] = mixed[r] - eta * grads[r];
        }
      } else {
        for (int64_t r = 0; r < nd; ++r) {
          const double x_new = models[r] + mixed[r] -
                               0.5 * (x_prev[r] + Wx_prev[r]) -
                               eta * (grads[r] - g_prev[r]);
          x_prev[r] = models[r];
          Wx_prev[r] = mixed[r];
          g_prev[r] = grads[r];
          models[r] = x_new;
        }
      }
    }

    if ((t + 1) % eval_every == 0) {
      const int64_t row = (t + 1) / eval_every - 1;
      if (!collect_metrics) {
        // objective/consensus evaluation skipped; timestamp still stamped
      } else if (centralized) {
        out_gap[row] = full_objective(problem, X, y, n_total, d, n_classes,
                                      dm, models.data(), reg, huber_delta);
      } else {  // decentralized metrics
        std::memset(avg.data(), 0, sizeof(double) * dm);
        for (int64_t i = 0; i < n_workers; ++i)
          for (int64_t k = 0; k < dm; ++k) avg[k] += models[i * dm + k];
        const double inv_n = 1.0 / static_cast<double>(n_workers);
        for (int64_t k = 0; k < dm; ++k) avg[k] *= inv_n;
        out_gap[row] = full_objective(problem, X, y, n_total, d, n_classes,
                                      dm, avg.data(), reg, huber_delta);
        double ce = 0.0;
        for (int64_t i = 0; i < n_workers; ++i) {
          const double *xi = models.data() + i * dm;
          for (int64_t k = 0; k < dm; ++k) {
            const double diff = xi[k] - avg[k];
            ce += diff * diff;
          }
        }
        out_cons[row] = ce * inv_n;
      }
      // Stamp AFTER the metrics computation, matching the numpy oracle and
      // the jax chunked path (both include the eval cost in the boundary's
      // timestamp) — stamping before would bias cross-backend time-to-eps
      // comparisons by one full-data eval per boundary.
      out_times[row] = std::chrono::duration<double>(
                           std::chrono::steady_clock::now() - run_start)
                           .count();
    }
  }

  if (centralized) {
    for (int64_t i = 0; i < n_workers; ++i)
      std::memcpy(out_models + i * dm, models.data(), sizeof(double) * dm);
  } else {
    std::memcpy(out_models, models.data(), sizeof(double) * n_workers * dm);
  }
  return 0;
}

}  // extern "C"
