"""Flight-recorder overhead bench (ISSUE-5 headline artifact;
docs/OBSERVABILITY.md).

Telemetry must be cheap enough to leave on for real experiments: the trace
buffers ride the fused scan's stacked outputs (one extra minibatch-gradient
probe + norms/counters per recorded row — the carry and the step dataflow
are untouched). This bench measures that cost honestly at eval-cadence
recording, on the SAME interleaved-cycles protocol the other benches use:

- BENIGN cell: D-SGD ring N=16, T=3000, eval_every=50 — telemetry off vs
  on, 3 interleaved cycles, median steady-state iters/sec each.
- FAULTY+BYZANTINE cell: edge drops + sign-flip + trimmed-mean screening —
  the expensive trace path (liveness gathers + the robust-activity probe).

Asserted gate: steady-state overhead ≤ OVERHEAD_CEILING (10%) per cell on
this container, with the standard ``BENCH_NO_RANGE_CHECK`` escape hatch and
an honest ``overhead_ok`` flag recorded per cell either way. Also asserts
the off-path is bitwise-unperturbed (objective equality across the off/on
runs of each cell) — the structural no-cost claim, measured end to end.

Writes ``docs/perf/telemetry.json`` plus its provenance sidecar
(``telemetry.manifest.json``; every bench emits one — telemetry.py).

Usage:  python examples/bench_telemetry.py [--out PATH] [--cycles 3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OVERHEAD_CEILING = 0.10  # asserted steady-state overhead bound per cell


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/perf/telemetry.json")
    ap.add_argument("--cycles", type=int, default=3)
    args = ap.parse_args()

    import jax
    import numpy as np

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.telemetry import write_bench_manifest
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import (
        compute_reference_optimum,
    )
    from distributed_optimization_tpu.utils.profiling import PhaseTimer

    timer = PhaseTimer()
    base = ExperimentConfig(
        n_workers=16, n_samples=1600, n_features=20,
        n_informative_features=12, problem_type="quadratic",
        algorithm="dsgd", topology="ring", n_iterations=3000,
        eval_every=50, local_batch_size=16,
    )
    cells_cfg = {
        "benign": base,
        "faulty_byzantine": base.replace(
            edge_drop_prob=0.2, attack="sign_flip", n_byzantine=1,
            aggregation="trimmed_mean", robust_b=1, partition="shuffled",
        ),
    }

    with timer.phase("data_gen"):
        ds = generate_synthetic_dataset(base)
    with timer.phase("oracle"):
        _, f_opt = compute_reference_optimum(ds, base.reg_param)

    skip = os.environ.get("BENCH_NO_RANGE_CHECK", "").lower() not in (
        "", "0", "false"
    )
    cells = {}
    gates = {}
    with timer.phase("run"):
        for name, cfg in cells_cfg.items():
            # Interleave off/on cycles so co-tenant drift hits both arms
            # equally; median steady-state ips per arm.
            ips = {False: [], True: []}
            last = {}
            for _ in range(args.cycles):
                for tele in (False, True):
                    r = jax_backend.run(
                        cfg.replace(telemetry=tele), ds, f_opt
                    )
                    ips[tele].append(r.history.iters_per_second)
                    last[tele] = r
            off = float(np.median(ips[False]))
            on = float(np.median(ips[True]))
            overhead = max(0.0, 1.0 - on / off)
            bitwise = bool(np.array_equal(
                last[False].history.objective, last[True].history.objective
            ))
            tr = last[True].history.trace
            cells[name] = {
                "ips_off_median": off,
                "ips_on_median": on,
                "ips_off_raw": [float(v) for v in ips[False]],
                "ips_on_raw": [float(v) for v in ips[True]],
                "overhead_frac": overhead,
                "overhead_ok": overhead <= OVERHEAD_CEILING,
                "off_on_bitwise_objective": bitwise,
                "trace_rows": int(np.asarray(tr["grad_norm"]).shape[0]),
                "mean_clip_frac": float(np.mean(tr["clip_frac"])),
                "cost_analysis": last[True].history.cost,
            }
            assert bitwise, (
                f"{name}: telemetry perturbed the trajectory — the "
                "structural no-cost claim is broken"
            )
            if not skip:
                assert overhead <= OVERHEAD_CEILING, (
                    f"{name}: measured telemetry overhead "
                    f"{overhead:.1%} exceeds the {OVERHEAD_CEILING:.0%} "
                    "ceiling (set BENCH_NO_RANGE_CHECK=1 on non-canonical "
                    "hardware)"
                )
    gates["overhead_ceiling"] = OVERHEAD_CEILING
    gates["all_cells_within_ceiling"] = all(
        c["overhead_ok"] for c in cells.values()
    )
    gates["off_on_bitwise_objective"] = all(
        c["off_on_bitwise_objective"] for c in cells.values()
    )

    payload = {
        "device": str(jax.devices()[0]),
        "platform": jax.devices()[0].platform,
        "protocol": (
            f"N=16 ring quadratic T=3000 eval_every=50; telemetry off vs on "
            f"interleaved x{args.cycles} cycles, median steady-state "
            "iters/sec per arm (compile excluded); benign + "
            "faulty/Byzantine (p=0.2 drops, sign-flip b=1, trimmed mean) "
            "cells"
        ),
        "note": (
            "Trace buffers ride the scan's stacked outputs: the carry and "
            "step dataflow are untouched, asserted bitwise on the recorded "
            "objective per cell. The recorded cost is one minibatch-"
            "gradient probe + norms/counters per inline-eval row; the "
            "faulty cell adds the liveness gather and the robust-activity "
            "probe. overhead_ok flags are honest per-cell verdicts against "
            "the asserted ceiling."
        ),
        "cells": cells,
        "gates": gates,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    write_bench_manifest(path, config=base, phases=timer)
    print(json.dumps({
        "metric": "telemetry_overhead_frac",
        "value": max(c["overhead_frac"] for c in cells.values()),
    }))


if __name__ == "__main__":
    main()
