"""Self-healing fleet soak bench (the ISSUE-16 tentpole evidence).

Drives sustained mixed traffic through the PRODUCTION serving topology
(HTTP daemon + worker processes) with the fleet reflex layer attached —
the remediation policy engine and the queue-driven autoscaler — and
injects operational chaos MID-TRAFFIC:

1. **Planted divergence attack** (``divergence``): an over-budget ALIE
   cell (f > b, the anomaly sentinel's breakdown recipe) submitted by an
   ``attacker`` tenant while healthy traffic flows. Gate: the incident
   fires, the offender fails with a policy-attributed error (never a
   silently served diverged result), the (tenant, structural class) pair
   quarantines, and healthy traffic is untouched.
2. **SIGKILL mid-burst** (``worker_kill``): a worker process executing
   part of the backlog is killed. Gate: the dead-worker policy records a
   remediation, the pool respawns to target, and every in-flight request
   still completes — zero stuck requests.
3. **Burst backlog then idle** (``autoscale``): a closed-loop burst
   drives the backlog over the autoscaler's high band (scale-up
   observed); the post-traffic lull drains it below the low band
   (scale-down observed, fleet back at ``min_workers``).
4. **Corrupted store artifact** (``store``): the chaos harness's
   fleet_store_remediation mode — a damaged persistent-store artifact is
   quarantined on load, the class recompiles cold, a fresh artifact is
   re-saved (``scenarios/chaos.py``).

Asserted floors (bench.py convention, BENCH_NO_RANGE_CHECK escape):
warm p99 submit→result ≤ 15 s (shared CPU container; the committed value
is the honest SLO surface and the perf-diff checker envelopes it), zero
stuck requests, EVERY injected incident remediated (divergence + dead
worker + store corruption, each with a ``remediated`` outcome in the
engine's records and a remediation block in the incident JSONL), and a
full scale-up/scale-down cycle observed.

Writes ``docs/perf/fleet.json`` (+ manifest sidecar).

Usage: python examples/bench_fleet.py [--out PATH] [--requests 18]
         [--rate 2.0] [--burst 8]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import numpy as np

WARM_P99_CEILING_S = 15.0  # warm submit->result, shared CPU container

BASE = {
    "n_workers": 8, "n_samples": 160, "n_features": 6,
    "n_informative_features": 4, "problem_type": "quadratic",
    "n_iterations": 40, "eval_every": 20, "local_batch_size": 8,
    "dtype": "float64",
}

# Mixed structural classes for the healthy stream (distinct compiled
# programs); eta/seed ride the coalescable axes.
STRUCTURE = [
    {},
    {"algorithm": "gradient_tracking"},
    {"straggler_prob": 0.15},
]


def _spec():
    from distributed_optimization_tpu.scenarios.spec import parse_spec

    return parse_spec({
        "name": "fleet-soak-traffic", "seed": 16, "mode": "sample",
        "sample": 12, "base": dict(BASE),
        "axes": {
            "structure": STRUCTURE,
            "eta": [{}, {"learning_rate_eta0": 0.08}],
            "seed": [{}, {"seed": 2}, {"seed": 3}],
        },
    })


def _pct(xs, q):
    return float(np.percentile(np.asarray(xs, dtype=np.float64), q))


def _submit_then_fetch(client, ex, cfg, *, tenant=None, timeout=600.0):
    t0 = time.perf_counter()
    code, sub = client.submit(cfg.to_dict(), tenant=tenant)
    assert code == 202, (code, sub)
    rid = sub["id"]

    def fetch():
        code, m = client.result(rid, timeout=timeout)
        return time.perf_counter() - t0, code, m

    return ex.submit(fetch)


def _kill_active_worker(pool, deadline_s=120.0):
    """SIGKILL a worker that is EXECUTING a task (falls back to any
    alive worker near the deadline); returns the victim id or None."""
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        with pool._lock:
            busy = sorted({
                t.worker_id for t in pool._tasks.values()
                if t.worker_id is not None
            })
            victim = busy[0] if busy else None
            proc = pool._procs.get(victim) if victim is not None else None
        if proc is not None and proc.is_alive():
            os.kill(proc.pid, signal.SIGKILL)
            return victim
        time.sleep(0.05)
    # Fallback: any alive worker (still exercises the death policy).
    with pool._lock:
        for wid, proc in pool._procs.items():
            if proc.is_alive():
                os.kill(proc.pid, signal.SIGKILL)
                return wid
    return None


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/perf/fleet.json")
    ap.add_argument("--requests", type=int, default=18,
                    help="paced healthy-stream length (sampled cells "
                         "repeat cyclically)")
    ap.add_argument("--rate", type=float, default=2.0,
                    help="paced-phase arrival rate (requests/sec)")
    ap.add_argument("--burst", type=int, default=8,
                    help="closed-loop burst size (the scale-up driver "
                         "and the worker-kill window)")
    args = ap.parse_args()

    import jax

    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.scenarios.chaos import (
        chaos_fleet_store_corruption,
        diverging_chaos_config,
    )
    from distributed_optimization_tpu.scenarios.engine import sample_traffic
    from distributed_optimization_tpu.serving.cache import ExecutableCache
    from distributed_optimization_tpu.serving.client import RetryingClient
    from distributed_optimization_tpu.serving.daemon import ServingDaemon
    from distributed_optimization_tpu.serving.fleet import (
        POLICY_DIVERGENCE,
        POLICY_STORE,
        POLICY_WORKER,
        AutoscaleOptions,
        FleetOptions,
        OUTCOME_REMEDIATED,
        QueueAutoscaler,
        RemediationEngine,
    )
    from distributed_optimization_tpu.observability.monitors import (
        read_incidents,
    )
    from distributed_optimization_tpu.serving.service import (
        ServingOptions,
        SimulationService,
    )
    from distributed_optimization_tpu.utils.profiling import PhaseTimer

    dev = jax.devices()[0]
    platform = dev.platform
    print(f"[fleet] device={dev} platform={platform}", file=sys.stderr)
    timer = PhaseTimer()
    incident_log = Path(tempfile.mkdtemp(prefix="dopt-fleet-")) / (
        "fleet.incidents.jsonl"
    )

    # ---- 0. traffic ----------------------------------------------------
    with timer.phase("traffic"):
        cells = sample_traffic(_spec())
        stream = [cells[i % len(cells)] for i in range(args.requests)]
        burst_cfgs = [cells[i % len(cells)].replace(seed=100 + i)
                      for i in range(args.burst)]
        attack = diverging_chaos_config()
    traffic = {
        "sampled_cells": len(cells),
        "structural_classes": len(STRUCTURE),
        "paced_requests": len(stream),
        "burst_requests": len(burst_cfgs),
        "composition": "scenario sample over structure x eta x seed, "
                       "repeated cyclically; one planted ALIE "
                       "divergence cell as the attacker tenant",
    }

    svc = SimulationService(
        ServingOptions(window_s=0.05, max_cohort=4, workers=1,
                       max_workers=2, progress_every=1),
        cache=ExecutableCache(),
    )
    engine = RemediationEngine(FleetOptions(
        quarantine_ttl_s=600.0, incident_log=str(incident_log),
    )).attach(svc)
    scaler = QueueAutoscaler(svc, AutoscaleOptions(
        min_workers=1, max_workers=2, high_depth=1, low_depth=0,
        up_polls=2, down_polls=10, poll_s=0.1,
    ))
    daemon = ServingDaemon("127.0.0.1", 0, service=svc)
    daemon.start()
    scaler.start()
    client = RetryingClient(daemon.url, max_retries=6, seed=0)
    probe = RetryingClient(daemon.url, max_retries=0)
    ex = ThreadPoolExecutor(max_workers=64)
    stuck = 0
    try:
        # ---- 1. warmup: one serve per structural class ----------------
        with timer.phase("warmup"):
            for over in STRUCTURE:
                cfg = ExperimentConfig(**{**BASE, **over})
                code, m = client.run(cfg.to_dict(), timeout=600.0)
                assert code == 200, (code, m)

        # ---- 2. soak: paced traffic + divergence attack mid-stream ----
        with timer.phase("soak"):
            futs = []
            attack_fut = None
            t_start = time.perf_counter()
            for i, cfg in enumerate(stream):
                target = t_start + i / args.rate
                now = time.perf_counter()
                if target > now:
                    time.sleep(target - now)
                if i == len(stream) // 3:
                    # The chaos injection rides the live stream.
                    attack_fut = _submit_then_fetch(
                        client, ex, attack, tenant="attacker",
                    )
                futs.append(_submit_then_fetch(client, ex, cfg))
            paced = []
            for f in futs:
                lat, code, m = f.result()
                if code != 200:
                    stuck += 1  # healthy traffic must serve
                    continue
                paced.append((lat, m))
            a_lat, a_code, a_body = attack_fut.result()
        assert a_code == 500, (
            f"the planted divergence served as {a_code}: {a_body}"
        )
        a_detail = a_body.get("detail", "")
        assert POLICY_DIVERGENCE in a_detail, a_body
        # The attacker's class is quarantined for the attacker ONLY
        # (single unretried probe: a 429 is the asserted answer here,
        # not a fault to retry through).
        code, body = probe._once(
            "POST", "/v1/submit",
            {"config": attack.replace(seed=9).to_dict(),
             "tenant": "attacker"},
            30.0,
        )
        assert code == 429 and body.get("reason") == "quarantined", (
            code, body,
        )
        warm = [lat for lat, m in paced
                if m["health"]["serving"]["cache_hit"]]
        cold_n = len(paced) - len(warm)
        assert warm, "no warm serves in the soak phase"
        divergence = {
            "attack_latency_s": round(a_lat, 2),
            "policy_error_attributed": POLICY_DIVERGENCE in a_detail,
            "quarantine_shed_reason": body.get("reason"),
            "healthy_served": len(paced),
        }
        print(
            f"[fleet] soak: {len(paced)} healthy served "
            f"({cold_n} cold), attack halted by {POLICY_DIVERGENCE} "
            f"in {a_lat:.1f}s", file=sys.stderr,
        )

        # ---- 3. burst backlog: scale-up window + worker SIGKILL -------
        with timer.phase("burst_kill"):
            bursts = [_submit_then_fetch(client, ex, cfg)
                      for cfg in burst_cfgs]
            victim = _kill_active_worker(svc._pool)
            for f in bursts:
                lat, code, m = f.result()
                if code != 200:
                    stuck += 1
        pool_stats = svc._pool.stats()
        worker_recs = [r for r in engine.records
                       if r["policy"] == POLICY_WORKER
                       and r["outcome"] == OUTCOME_REMEDIATED]
        assert victim is not None, "no worker could be killed"
        assert worker_recs, "the dead-worker policy never recorded"
        worker_kill = {
            "victim": victim,
            "remediations": len(worker_recs),
            "restarts": pool_stats["restarts"],
            "burst_served": len(burst_cfgs),
        }
        print(
            f"[fleet] worker kill: victim {victim}, "
            f"{len(worker_recs)} remediation(s), pool restarts "
            f"{pool_stats['restarts']}", file=sys.stderr,
        )

        # ---- 4. idle: the scale-down half of the cycle ----------------
        with timer.phase("scale_down"):
            deadline = time.time() + 120.0
            while time.time() < deadline:
                if (scaler.n_scale_down >= 1
                        and svc._pool.stats()["workers"]
                        == scaler.options.min_workers):
                    break
                time.sleep(0.2)
        assert scaler.n_scale_up >= 1, "burst backlog never scaled up"
        assert scaler.n_scale_down >= 1, "idle fleet never scaled down"
        final_pool = svc._pool.stats()
        autoscale = {
            "scale_ups": scaler.n_scale_up,
            "scale_downs": scaler.n_scale_down,
            "retired": final_pool["retired"],
            "final_workers": final_pool["workers"],
            "min_workers": scaler.options.min_workers,
            "max_workers": scaler.options.max_workers,
        }
        print(
            f"[fleet] autoscale: {scaler.n_scale_up} up / "
            f"{scaler.n_scale_down} down, fleet back at "
            f"{final_pool['workers']}", file=sys.stderr,
        )
        fleet_status = svc.stats()["fleet"]
    finally:
        try:
            probe.shutdown()
        except Exception:
            pass
        daemon.stop()
        ex.shutdown(wait=False)

    # ---- 5. store corruption (the chaos harness's fleet mode) ---------
    with timer.phase("store"):
        store_rec = chaos_fleet_store_corruption()
    assert store_rec.passed, store_rec.detail
    print(
        f"[fleet] store: artifact quarantined + recompiled cold "
        f"({store_rec.detail.get('store', {})})", file=sys.stderr,
    )

    # ---- incident ledger: every injection remediated -------------------
    incs = read_incidents(incident_log) if incident_log.exists() else []
    by_policy = {}
    for i in incs:
        rem = i.get("remediation") or {}
        by_policy.setdefault(rem.get("policy"), []).append(
            rem.get("outcome")
        )
    injected = {
        POLICY_DIVERGENCE: divergence["policy_error_attributed"],
        POLICY_WORKER: bool(worker_recs),
        POLICY_STORE: store_rec.passed,
    }
    all_remediated = (
        all(injected.values())
        and all(
            o == OUTCOME_REMEDIATED
            for outs in by_policy.values() for o in outs
        )
        and {POLICY_DIVERGENCE, POLICY_WORKER} <= set(by_policy)
    )
    incidents = {
        "log_records": len(incs),
        "remediation_outcomes": {
            str(k): sorted(set(v)) for k, v in by_policy.items()
        },
    }

    latency = {
        "rate_hz": args.rate,
        "healthy_requests": len(paced),
        "warm_requests": len(warm),
        "warm_p50_s": round(_pct(warm, 50), 4),
        "warm_p99_s": round(_pct(warm, 99), 4),
    }

    # ---- asserted floors (BENCH_NO_RANGE_CHECK escape hatch) ----------
    skip = os.environ.get("BENCH_NO_RANGE_CHECK", "").lower() not in (
        "", "0", "false"
    )
    if skip:
        print(
            "[fleet] BENCH_NO_RANGE_CHECK set: skipping the floor gates "
            "(non-canonical hardware mode)", file=sys.stderr,
        )
    else:
        assert latency["warm_p99_s"] <= WARM_P99_CEILING_S, (
            f"warm p99 {latency['warm_p99_s']}s exceeds the "
            f"{WARM_P99_CEILING_S}s ceiling"
        )
        assert stuck == 0, f"{stuck} accepted request(s) never served"
        assert all_remediated, (
            f"unremediated injections: injected={injected} "
            f"ledger={incidents}"
        )
    gates = {
        "applied": not skip,
        "warm_p99_ceiling_s": WARM_P99_CEILING_S,
        "measured_warm_p99_s": latency["warm_p99_s"],
        "zero_stuck": stuck == 0,
        "divergence_remediated": injected[POLICY_DIVERGENCE],
        "worker_remediated": injected[POLICY_WORKER],
        "store_remediated": injected[POLICY_STORE],
        "all_injections_remediated": all_remediated,
        "scale_up_observed": autoscale["scale_ups"] >= 1,
        "scale_down_observed": autoscale["scale_downs"] >= 1,
    }

    payload = {
        "device": str(dev),
        "platform": platform,
        "protocol": (
            "Mixed scenario-sampled traffic through ServingDaemon with "
            "the fleet reflex layer attached (RemediationEngine + "
            "QueueAutoscaler, workers autoscaled 1..2). Injections "
            "mid-traffic: a planted ALIE f>b divergence cell as the "
            "attacker tenant (halt + quarantine asserted through the "
            "wire), a SIGKILL of an executing worker during a "
            f"{args.burst}-deep closed-loop burst (respawn + zero stuck "
            "asserted), the burst/idle autoscale cycle (up AND down "
            "observed), and the chaos harness's corrupted-store mode "
            "(artifact quarantined, cold recompile, fresh re-save). "
            "The incident JSONL is read back and every remediation "
            "block must say 'remediated'."
        ),
        "note": (
            "CPU-container numbers: the wall-clock cell (warm p99) is "
            "envelope-checked, not pinned — the load-bearing evidence "
            "is the boolean gates (every injected incident remediated, "
            "zero stuck requests, full scale cycle)."
        ),
        "traffic": traffic,
        "latency": latency,
        "divergence": divergence,
        "worker_kill": worker_kill,
        "autoscale": autoscale,
        "store": store_rec.to_dict(),
        "incidents": incidents,
        "fleet_status": {
            "policies": fleet_status["remediation"]["policies"],
            "remediations_total":
                fleet_status["remediation"]["remediations"]["total"],
            "quarantines": fleet_status["remediation"]["quarantines"],
        },
        "stuck_requests": stuck,
        "gates": gates,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(
        path, config=ExperimentConfig(**BASE), phases=timer,
    )

    print(json.dumps({
        "metric": "fleet_soak_remediation_and_scale",
        "warm_p99_s": latency["warm_p99_s"],
        "stuck": stuck,
        "all_injections_remediated": all_remediated,
        "scale_ups": autoscale["scale_ups"],
        "scale_downs": autoscale["scale_downs"],
    }))


if __name__ == "__main__":
    main()
