#!/usr/bin/env bash
# Regenerate every committed performance artifact on the real chip.
#
# Each script is independent and idempotent; together they rebuild all of
# docs/perf/*.json, docs/figures/scaling.png, and the numbers quoted in
# docs/PERF.md. Budget ~2-2.5 h of chip time end to end (the shared
# tunnel's co-tenant load makes absolute numbers vary 2-3x between runs;
# every script interleaves its variants so within-artifact comparisons
# stay meaningful). NEVER run two of these concurrently: overlapping chip
# jobs produced physically impossible timings in round 5
# (docs/ROUND5_NOTES.md, measurement hygiene).
set -euo pipefail
cd "$(dirname "$0")/.."

python examples/bench_mixing.py            # -> docs/perf/mixing_bench.json
python examples/bench_pallas_regimes.py    # -> docs/perf/pallas_regimes.json
python examples/bench_breakdown.py         # -> docs/perf/breakdown.json
python examples/bench_scaling.py           # -> docs/perf/scaling.json + figure
python examples/bench_presets.py           # -> docs/perf/presets.json
python examples/bench_faults.py            # -> docs/perf/faults.json
python examples/bench_churn.py             # -> docs/perf/churn.json
python examples/bench_byzantine.py         # -> docs/perf/byzantine.json
python examples/bench_robust_scale.py      # -> docs/perf/robust_scale.json
python examples/bench_sparse_mixing.py     # -> docs/perf/sparse_mixing.json
python examples/bench_compute_bound.py     # -> docs/perf/compute_bound.json (MFU-floor gated)
python examples/bench_eval_cadence.py      # -> docs/perf/eval_cadence.json
python examples/bench_sweep.py             # -> docs/perf/sweep.json (replica-batch floor gated)
python examples/bench_telemetry.py         # -> docs/perf/telemetry.json (overhead-ceiling gated)
python examples/bench_fused_robust.py      # -> docs/perf/fused_robust.json (compiled-path floor gated)
python examples/bench_serving.py           # -> docs/perf/serving.json (latency/throughput floors gated)
python examples/bench_serving_load.py      # -> docs/perf/serving_load.json (sustained-load warm-p99/saturation/fairness floors + restart-warm + shed gates; multi-worker daemon + persistent store)
python examples/bench_fleet.py            # -> docs/perf/fleet.json (self-healing soak: every injected incident remediated + zero stuck + autoscale cycle gated; fleet reflex layer over the multi-worker daemon)
python examples/bench_observatory.py       # -> docs/perf/observatory.json (heartbeat-overhead ceiling incl. async segment-fused cell + /metrics scrape gated)
python examples/bench_monitors.py          # -> docs/perf/monitors.json (anomaly-sentinel overhead/onset/halt gated)
python examples/bench_federated.py         # -> docs/perf/federated.json (floats-to-eps floor + N=10k completion gated)
python examples/bench_async.py             # -> docs/perf/async.json (wall-clock-to-eps floors + degenerate sync gate)
python examples/bench_async_faults.py      # -> docs/perf/async_faults.json (crash-free bitwise gate + tracking-invariant bound + matched-availability envelope + under-faults barrier floor)
python examples/bench_worker_mesh.py       # -> docs/perf/worker_mesh.json (sharded parity bitwise + N=100k completion incl. sparse-sampled ER + flat per-device memory gated; forces 4 host devices itself)
python examples/bench_mesh_scale.py        # -> docs/perf/mesh_scale.json (N=1M ring/torus sharded completions + flat per-device memory + sparse-ER 1M build + compressed-halo wire cut + overlap ratio gated; forces 16 host devices itself)
python examples/bench_scenarios.py         # -> docs/perf/scenarios.json (validity-agreement + per-cell invariant + warm-replay + chaos gates; forces 4 host devices itself)
python examples/reproduce_report.py --json docs/perf/report_reproduction.json
python examples/northstar_consensus.py --ring-full  # -> docs/perf/northstar_consensus.json
python bench.py                            # headline JSON line (stdout)
# docs/perf/anomaly_rootcause.json is a one-off investigation record
# (round-3 nested-scan root cause), not regenerated here.
