"""Measured evidence for the failure-injection subsystem (SURVEY.md §5.3).

Runs the N=64 ring config on the chip under the full fault/schedule
matrix — fault-free, 20% iid edge drops, 10% stragglers, one-peer
randomized gossip, deterministic round-robin matchings — and records, per
variant: throughput, the convergence outcome, and the REALIZED
floats-transmitted accounting next to the fault-free analytic count (the
honest-bandwidth property the fault machinery exists to provide).

Both fault-tolerant algorithm families are measured: D-SGD and gradient
tracking (the two whose time-varying-gossip analyses cover failure
injection — see tests/test_faults.py for the GT tracking-invariant
evidence; EXTRA/ADMM/CHOCO are rejected by construction).

Variants are interleaved round-robin per cycle (shared-chip protocol).
Writes ``docs/perf/faults.json``.

Usage:  python examples/bench_faults.py [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/perf/faults.json")
    ap.add_argument("--cycles", type=int, default=3)
    args = ap.parse_args()

    import jax

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.metrics import iterations_to_threshold
    from distributed_optimization_tpu.utils.data import generate_synthetic_dataset
    from distributed_optimization_tpu.utils.oracle import compute_reference_optimum

    base = ExperimentConfig(
        problem_type="logistic", algorithm="dsgd", topology="ring",
        n_workers=64, n_iterations=20_000,
    )
    ds = generate_synthetic_dataset(base)
    _, f_opt = compute_reference_optimum(ds, base.reg_param)

    variants = {
        "fault_free": base,
        "edge_drop_20pct": base.replace(edge_drop_prob=0.2),
        "stragglers_10pct": base.replace(straggler_prob=0.1),
        "edge20_straggler10": base.replace(edge_drop_prob=0.2,
                                           straggler_prob=0.1),
        "one_peer_gossip": base.replace(gossip_schedule="one_peer"),
        "round_robin_matchings": base.replace(gossip_schedule="round_robin"),
    }
    # Gradient tracking under the same fault matrix (2·Σdeg·d per iteration
    # fault-free: it gossips x AND y over the realized edges).
    gt = base.replace(algorithm="gradient_tracking")
    variants.update({
        "gt_fault_free": gt,
        "gt_edge_drop_20pct": gt.replace(edge_drop_prob=0.2),
        "gt_stragglers_10pct": gt.replace(straggler_prob=0.1),
    })
    # Push-sum over the DIRECTED ring under the directed fault model
    # (round 5): independent one-way link drops with column-stochastic
    # renormalization of surviving out-weights (parallel/faults.py). The
    # d+1 payload (model + mass scalar) flows into both the analytic
    # denominator and the realized accounting.
    ps = base.replace(algorithm="push_sum", topology="directed_ring")
    variants.update({
        "ps_fault_free": ps,
        "ps_edge_drop_20pct": ps.replace(edge_drop_prob=0.2),
        "ps_stragglers_10pct": ps.replace(straggler_prob=0.1),
    })

    runs: dict[str, list] = {name: [] for name in variants}
    results: dict[str, dict] = {}
    for c in range(args.cycles):
        for name, cfg in variants.items():
            r = jax_backend.run(cfg, ds, f_opt)
            runs[name].append(float(r.history.iters_per_second))
            if c == 0:
                h = r.history
                results[name] = {
                    "final_gap": round(float(h.objective[-1]), 6),
                    "iterations_to_eps": int(iterations_to_threshold(
                        h.objective, cfg.suboptimality_threshold,
                        h.eval_iterations)),
                    "final_consensus": round(float(h.consensus_error[-1]), 8),
                    "floats_transmitted": float(h.total_floats_transmitted),
                }
    # Analytic fault-free denominator gossip_rounds·2|E|·d·T per variant
    # (GT gossips x and y, so its denominator is 2× D-SGD's), computed
    # independently of the backend's accounting. The fault-free rows equal
    # it by construction (no fault machinery ⇒ the backend uses the same
    # closed form — a consistency check, not evidence). The REALIZED
    # accounting path is pinned by the round-robin row below: each phase of
    # the even-ring schedule is a perfect matching, so the realized degree
    # sum is exactly N per iteration against the fault-free 2|E| = 2N — the
    # realized count must equal HALF the analytic, deterministically.
    from distributed_optimization_tpu.algorithms import get_algorithm
    from distributed_optimization_tpu.parallel import build_topology

    def _analytic(cfg):
        topo = build_topology(cfg.topology, cfg.n_workers)
        algo = get_algorithm(cfg.algorithm)
        payload = (
            algo.comm_payload(cfg, ds.n_features)
            if algo.comm_payload is not None
            else ds.n_features * algo.gossip_rounds
        )
        return float(topo.floats_per_iteration * payload * cfg.n_iterations)

    analytic = {name: _analytic(cfg) for name, cfg in variants.items()}
    for name in ("fault_free", "gt_fault_free", "ps_fault_free"):
        assert results[name]["floats_transmitted"] == analytic[name], (
            f"{name}: fault-free floats diverge from the analytic closed form"
        )
    assert (
        results["round_robin_matchings"]["floats_transmitted"]
        == 0.5 * analytic["round_robin_matchings"]
    ), "round-robin realized accounting must be exactly half of 2|E|dT"
    for name, row in results.items():
        row["iters_per_sec_median"] = round(statistics.median(runs[name]), 1)
        row["floats_vs_fault_free"] = round(
            row["floats_transmitted"] / analytic[name], 4)
        print(f"[faults] {name:24s} {row['iters_per_sec_median']:>9.0f} "
              f"iters/sec  gap {row['final_gap']:.4f}  iters->eps "
              f"{row['iterations_to_eps']:>6d}  floats x"
              f"{row['floats_vs_fault_free']}", file=sys.stderr)

    payload = {
        "device": str(jax.devices()[0]),
        "config": "logistic N=64 T=20k (dsgd/gt on the undirected ring, "
                  "push_sum on the directed ring), interleaved medians of "
                  f"{args.cycles}",
        "note": "floats_vs_fault_free: realized (fault-accounted) floats "
                "over the ANALYTIC fault-free count (fault-free runs "
                "asserted equal; 2|E|dT undirected, |E_dir|(d+1)T for "
                "push_sum's model+mass payload) — edge drops at p=0.2 "
                "should realize ~0.8, one-peer at most 1/deg_sum per node "
                "pair, round-robin exactly 1/2 on an even ring. Convergence "
                "under drops/stragglers degrades gracefully (time-varying "
                "doubly stochastic W_t, Koloskova et al. '20, for the "
                "undirected rows; time-varying column-stochastic chains, "
                "Nedić-Olshevsky '16, for the ps_* rows).",
        "runs": results,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(path)

    print(json.dumps({"metric": "fault_variants_measured",
                      "value": len(results)}))


if __name__ == "__main__":
    main()
