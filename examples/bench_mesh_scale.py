"""Million-worker mesh evidence (ISSUE 18) -> docs/perf/mesh_scale.json.

Runs under a FORCED 16-device host platform (XLA_FLAGS, set below before
jax initializes). Four measured claims, each gated:

1. **1M completion** — N = 1,000,000 ring AND torus runs COMPLETE
   sharded over 16 devices (10× the N=100k headroom worker_mesh.json
   recorded), with per-device resident bytes probed from live array
   shards mid-run. The 250k/P=4 cell pairs with 1M/P=16 at identical
   rows/device (62,500), so the sharded per-device footprint must come
   out flat — the O(N/P) memory law at the million scale.
2. **Sparse ER at 1M** — the O(N·k_max) sampler builds a connected
   G(10^6, 20/10^6) neighbor table + 16-shard halo plan in seconds
   (build time recorded), where the dense-stream sampler's O(N²) replay
   is ~hours. The optimizer run is NOT claimed at this cell: a uniform
   random graph sharded 16 ways has no block locality — nearly every
   neighbor is remote, so the halo degenerates toward a full gather and
   the honest run evidence stays at worker_mesh.json's ER cells (10k
   dense-sampled, 100k sparse-sampled).
3. **Compressed halo cut** — top_k (2k = 8 floats/row) prices ≤ 50% of
   the uncompressed halo bytes on the wire (telemetry.ici_summary over
   the same static plan that drives the collectives), and the compressed
   run's final gap stays within the 2.5× envelope of the uncompressed
   run at equal iterations (the fused_robust.json convention).
4. **Overlap** — halo_overlap='double_buffer' is measured against 'off'
   at matched config. On this single-stream CPU host the ppermute/
   compute overlap has no hardware to exploit, so the ratio is reported
   with an honest ``overlap_loses`` flag rather than asserted >= 1; the
   load-bearing gate is bitwise-off parity (tests/test_mesh_scale.py).

CPU-container numbers: absolute iters/sec is not chip evidence; the
load-bearing content is the completions, the flat footprint, the wire
accounting, and the honest flags.
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

# Must precede any jax import, including in spawn-context subprocesses
# (they re-import this module's top level).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=16"
    ).strip()

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

OUT = REPO / "docs" / "perf" / "mesh_scale.json"

SCALE_T = 10
# (label, topology, n, worker_mesh) — one subprocess per cell, 1 sample
# per worker (the model state, not the data, is the 1M-scale object).
# 250k/P=4 pairs with 1M/P=16: 62,500 rows/device each, so sharded
# per-device bytes must be flat.
SCALE_CELLS = (
    ("ring_250k_p4", "ring", 250_000, 4),
    ("ring_1m_p16", "ring", 1_000_000, 16),
    ("torus_1m_p16", "grid", 1_000_000, 16),
)

ER_N = 1_000_000
ER_MEAN_DEGREE = 20.0  # above the ln(N) ≈ 13.8 connectivity threshold

COMPRESS_N = 4096
COMPRESS_T = 400

OVERLAP_N = 100_000
OVERLAP_T = 30


def _mesh_cfg(topology, n, mesh_p, **extra):
    from distributed_optimization_tpu.config import ExperimentConfig

    return ExperimentConfig(
        n_workers=n, n_samples=n, n_features=16, n_informative_features=10,
        problem_type="quadratic", topology=topology, algorithm="dsgd",
        local_batch_size=1, n_iterations=SCALE_T, eval_every=SCALE_T,
        topology_impl="neighbor", mixing_impl="gather",
        worker_mesh=mesh_p, **extra,
    )


def _scale_cell(args):
    """One sharded scale cell in a fresh subprocess (honest peak RSS +
    per-device resident bytes probed at the first progress heartbeat)."""
    label, topology, n, mesh_p = args
    import collections
    import resource
    import time

    import jax

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.telemetry import ici_summary
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )

    cfg = _mesh_cfg(topology, n, mesh_p)
    t0 = time.perf_counter()
    ds = generate_synthetic_dataset(cfg)
    data_seconds = time.perf_counter() - t0

    per_device: dict[str, int] = {}

    def probe(_event):
        # Live per-device resident bytes mid-run: every live jax array's
        # realized shard sizes, summed per device. Device 0 additionally
        # holds the replicated leaves (keys, scalars); devices outside
        # the P-device mesh hold nothing and never appear.
        if per_device:
            return
        acc = collections.Counter()
        for a in jax.live_arrays():
            for s in a.addressable_shards:
                acc[str(s.device)] += s.data.nbytes
        per_device.update(acc)

    t0 = time.perf_counter()
    r = jax_backend.run(cfg, ds, 0.0, progress_cb=probe, progress_every=1)
    wall = time.perf_counter() - t0
    gap = float(r.history.objective[-1])
    assert gap == gap, f"{label}: NaN gap"
    return {
        "label": label,
        "topology": topology,
        "n_workers": n,
        "worker_mesh": mesh_p,
        "rows_per_device": n // mesh_p,
        "iters_per_second": float(r.history.iters_per_second),
        "compile_seconds": float(r.history.compile_seconds),
        "wall_seconds": wall,
        "data_seconds": data_seconds,
        "final_gap": gap,
        "peak_rss_mb": resource.getrusage(
            resource.RUSAGE_SELF
        ).ru_maxrss / 1024.0,
        "sharded_bytes_per_device": (
            min(per_device.values()) if per_device else None
        ),
        "ici": ici_summary(cfg),
    }


def _er_plan_cell(_):
    """Sparse-sampler build + halo-plan cell (no optimizer run — see
    module docstring): the O(N·k_max) claim measured at N=10^6."""
    import resource
    import time

    import numpy as np

    from distributed_optimization_tpu.parallel.topology import (
        build_halo_plan,
        build_neighbor_topology,
        neighbor_tables_for,
    )

    p = ER_MEAN_DEGREE / ER_N
    t0 = time.perf_counter()
    topo = build_neighbor_topology(
        "erdos_renyi", ER_N, erdos_renyi_p=p, seed=3, sampler="sparse"
    )
    build_seconds = time.perf_counter() - t0
    t0 = time.perf_counter()
    plan = build_halo_plan(*neighbor_tables_for(topo), 16, sampler="sparse")
    plan_seconds = time.perf_counter() - t0
    assert topo.sampler == "sparse"
    return {
        "n_workers": ER_N,
        "erdos_renyi_p": p,
        "sampler": "sparse",
        "build_seconds": build_seconds,
        "plan_seconds": plan_seconds,
        "k_max": int(topo.nbr_idx.shape[1]),
        "mean_degree": float(topo.degrees.mean()),
        "table_mb": float(
            (topo.nbr_idx.nbytes + topo.nbr_mask.nbytes) / 1e6
        ),
        "halo_rows_per_device_max": int(
            max(len(h) for h in plan.halo_idx)
        ),
        "wire_rows_per_device": int(
            np.sum([st.send_idx.shape[1] for st in plan.steps])
        ),
        "peak_rss_mb": resource.getrusage(
            resource.RUSAGE_SELF
        ).ru_maxrss / 1024.0,
        "run_skipped": (
            "a uniform random graph sharded 16 ways has no block "
            "locality — nearly every neighbor is remote, the halo "
            "degenerates toward a full gather; run evidence for ER stays "
            "at worker_mesh.json (N=10k dense-sampled, N=100k "
            "sparse-sampled), this cell carries the O(N·k_max) build"
        ),
    }


def bench_compression():
    import numpy as np

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.telemetry import ici_summary
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import (
        compute_reference_optimum,
    )

    base = dict(
        n_workers=COMPRESS_N, n_samples=4 * COMPRESS_N, n_features=16,
        n_informative_features=10, problem_type="quadratic",
        topology="ring", algorithm="dsgd", local_batch_size=8,
        dtype="float64", n_iterations=COMPRESS_T,
        eval_every=COMPRESS_T // 4, topology_impl="neighbor",
        mixing_impl="gather", worker_mesh=4,
    )
    cfg_plain = ExperimentConfig(**base)
    cfg_topk = ExperimentConfig(**{
        **base, "compression": "top_k", "compression_k": 4,
        "choco_gamma": 0.5,
    })
    ds = generate_synthetic_dataset(cfg_plain)
    _, f_opt = compute_reference_optimum(ds, cfg_plain.reg_param)
    r_plain = jax_backend.run(cfg_plain, ds, f_opt)
    r_topk = jax_backend.run(cfg_topk, ds, f_opt)
    gap_plain = float(r_plain.history.objective[-1])
    gap_topk = float(r_topk.history.objective[-1])
    ici_plain = ici_summary(cfg_plain)
    ici_topk = ici_summary(cfg_topk)
    bytes_ratio = (
        ici_topk["bytes_per_device_per_round_max"]
        / ici_plain["bytes_per_device_per_round_max"]
    )
    gap_ratio = gap_topk / gap_plain
    assert bytes_ratio <= 0.5, bytes_ratio
    assert gap_ratio <= 2.5, gap_ratio
    print(f"[compress] wire bytes ratio {bytes_ratio:.3f}, "
          f"gap ratio {gap_ratio:.3f}")
    return {
        "n_workers": COMPRESS_N,
        "n_iterations": COMPRESS_T,
        "worker_mesh": 4,
        "compression": "top_k",
        "compression_k": 4,
        "floats_per_row_plain": ici_plain["payload_floats_per_row"],
        "floats_per_row_topk": ici_topk["payload_floats_per_row"],
        "bytes_per_device_per_round_plain": ici_plain[
            "bytes_per_device_per_round_max"],
        "bytes_per_device_per_round_topk": ici_topk[
            "bytes_per_device_per_round_max"],
        "wire_bytes_ratio": bytes_ratio,
        "final_gap_plain": gap_plain,
        "final_gap_topk": gap_topk,
        "gap_ratio": gap_ratio,
        "models_match_unsharded": bool(np.array_equal(
            np.asarray(r_topk.final_models),
            np.asarray(jax_backend.run(
                cfg_topk.replace(worker_mesh=0), ds, f_opt, use_mesh=False
            ).final_models),
        )),
    }


def bench_overlap():
    import time

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )

    cfg_off = _mesh_cfg("ring", OVERLAP_N, 4).replace(
        n_iterations=OVERLAP_T, eval_every=OVERLAP_T
    )
    cfg_db = cfg_off.replace(halo_overlap="double_buffer")
    ds = generate_synthetic_dataset(cfg_off)
    cells = {}
    for label, cfg in (("off", cfg_off), ("double_buffer", cfg_db)):
        t0 = time.perf_counter()
        r = jax_backend.run(cfg, ds, 0.0)
        cells[label] = {
            "iters_per_second": float(r.history.iters_per_second),
            "compile_seconds": float(r.history.compile_seconds),
            "wall_seconds": time.perf_counter() - t0,
            "final_gap": float(r.history.objective[-1]),
        }
        print(f"[overlap] {label}: "
              f"{cells[label]['iters_per_second']:.1f} iters/s")
    ratio = (cells["double_buffer"]["iters_per_second"]
             / cells["off"]["iters_per_second"])
    return {
        "n_workers": OVERLAP_N,
        "n_iterations": OVERLAP_T,
        "worker_mesh": 4,
        "cells": cells,
        "double_buffer_speedup": ratio,
        "overlap_loses": bool(ratio < 1.0),
        "note": (
            "single-stream CPU host: ppermute and the in-block partial "
            "sum serialize, so the restructured body can only tie or "
            "lose here — the flag is reported honestly, not asserted; "
            "the accelerator rationale is the issued-first ppermute the "
            "double_buffer body hands XLA's latency-hiding scheduler"
        ),
    }


def main() -> None:
    import multiprocessing as mp
    from concurrent import futures

    import jax

    from distributed_optimization_tpu.telemetry import write_bench_manifest
    from distributed_optimization_tpu.utils.profiling import PhaseTimer

    assert len(jax.devices()) >= 16, (
        "mesh-scale bench needs the forced 16-device host platform; do "
        "not pre-set XLA_FLAGS without xla_force_host_platform_device_count"
    )
    timer = PhaseTimer()
    ctx = mp.get_context("spawn")
    cells = []
    with timer.phase("scale"):
        for job in SCALE_CELLS:  # sequential: no interference
            with futures.ProcessPoolExecutor(1, mp_context=ctx) as pool:
                cell = pool.submit(_scale_cell, job).result()
            cells.append(cell)
            print(f"[scale] {cell['label']}: "
                  f"{cell['iters_per_second']:.1f} iters/s, "
                  f"{cell['sharded_bytes_per_device'] / 1e6:.1f} MB/device, "
                  f"peak RSS {cell['peak_rss_mb']:.0f} MB")
    with timer.phase("er_plan"):
        with futures.ProcessPoolExecutor(1, mp_context=ctx) as pool:
            er_plan = pool.submit(_er_plan_cell, None).result()
        print(f"[er] build {er_plan['build_seconds']:.1f}s, "
              f"k_max {er_plan['k_max']}, "
              f"plan {er_plan['plan_seconds']:.1f}s")
    with timer.phase("compression"):
        compression = bench_compression()
    with timer.phase("overlap"):
        overlap = bench_overlap()

    by_label = {c["label"]: c for c in cells}
    big = by_label["ring_1m_p16"]
    pair_ratio = (
        big["sharded_bytes_per_device"]
        / by_label["ring_250k_p4"]["sharded_bytes_per_device"]
    )
    assert 0.8 <= pair_ratio <= 1.25, pair_ratio
    assert (big["ici"]["bytes_per_device_per_round_max"]
            == by_label["ring_250k_p4"]["ici"][
                "bytes_per_device_per_round_max"])
    assert compression["models_match_unsharded"]

    payload = {
        "device": jax.devices()[0].device_kind,
        "platform": jax.devices()[0].platform,
        "protocol": {
            "devices": (
                "forced 16-device CPU host platform (XLA_FLAGS), real "
                "shard_map/ppermute collectives"
            ),
            "scale": (
                "ring 250k/P=4 + ring 1M/P=16 + torus 1M/P=16, dsgd "
                f"T={SCALE_T}, 1 sample/worker, one subprocess per cell; "
                "per-device resident bytes probed from live array shards "
                "at the first progress heartbeat; the 250k/P=4 and "
                "1M/P=16 cells hold rows/device fixed at 62,500"
            ),
            "er": (
                "O(N·k_max) sparse sampler at N=10^6, mean degree "
                f"{ER_MEAN_DEGREE:.0f} (> ln N), seed-pure; build + "
                "16-shard halo plan timed, run honestly skipped (see "
                "er_plan.run_skipped)"
            ),
            "compression": (
                f"ring N={COMPRESS_N}, P=4, top_k k=4 (8 of 17 floats/"
                "row) vs plain at equal T; wire bytes from "
                "telemetry.ici_summary over the same static plan the "
                "collectives execute; gap envelope 2.5x per the "
                "fused_robust.json convention; sharded-vs-unsharded "
                "bitwise parity asserted on the compressed cell"
            ),
            "overlap": (
                f"ring N={OVERLAP_N}, P=4, halo_overlap off vs "
                "double_buffer at matched config, measured iters/sec"
            ),
        },
        "scale": {
            "n_iterations": SCALE_T,
            "cells": cells,
            "per_device_flat_pair": {
                "cells": ["ring_250k_p4", "ring_1m_p16"],
                "rows_per_device_each": 62_500,
                "sharded_bytes_ratio": pair_ratio,
            },
        },
        "er_plan": er_plan,
        "compression": compression,
        "overlap": overlap,
        "gates": {
            "n1m_ring_completed_sharded": True,
            "n1m_torus_completed_sharded": True,
            "per_device_flat_at_matched_rows": bool(
                0.8 <= pair_ratio <= 1.25
            ),
            "ring_ici_bytes_per_device_flat_in_n": True,
            "er_1m_sparse_plan_built": True,
            "topk_wire_bytes_ratio": compression["wire_bytes_ratio"],
            "topk_wire_bytes_halved": bool(
                compression["wire_bytes_ratio"] <= 0.5
            ),
            "topk_gap_within_envelope": bool(
                compression["gap_ratio"] <= 2.5
            ),
            "compressed_models_match_unsharded": compression[
                "models_match_unsharded"],
            "overlap_measured": True,
            "overlap_loses": overlap["overlap_loses"],
        },
        "note": (
            "CPU-container numbers: absolute iters/sec is not chip "
            "evidence; the load-bearing content is the 1M sharded "
            "completions, the flat per-device footprint at matched "
            "rows/device, the <= 50% compressed wire bytes inside the "
            "2.5x gap envelope, and the honest overlap_loses flag. "
            "Bitwise guarantees live in tests/test_mesh_scale.py."
        ),
    }
    OUT.parent.mkdir(parents=True, exist_ok=True)
    OUT.write_text(json.dumps(payload, indent=1, sort_keys=True) + "\n")
    print(f"wrote {OUT}")
    write_bench_manifest(
        OUT,
        config=_mesh_cfg("ring", 1_000_000, 16),
        phases=timer,
    )


if __name__ == "__main__":
    main()
