"""Breakdown-point demonstration for the Byzantine subsystem
(docs/BYZANTINE.md; acceptance rows for the robust-aggregation rules).

One config — logistic, **N=64 ring**, IID ('shuffled') partition, T=4k —
swept over the attack/defense matrix. (The bench ran at N=16 fully
connected until PR 3: the dense robust path was O(N²·d·log N), so a
ring-at-scale sweep was unaffordable. The degree-bounded gather path —
``robust_impl='auto'`` routes to it on the ring, k_max=2 ≪ N — makes the
degree-bounded regime the headline, which is also where the screening
budget semantics are per-NEIGHBORHOOD, not global: b=1 per closed ring
neighborhood of 3.)

- ATTACK-FREE: plain gossip, each robust rule at budget b=1 (defense
  cost), and a zero-budget robust run ASSERTED bitwise-equal to plain
  (robust_b=0 degrades to the plain path by construction);
- SIGN-FLIP at a tolerated placement (f=6 of 64, scale 5 — for this
  seed every honest ring neighborhood holds ≤ 1 = b attackers): plain
  gossip must diverge (NaN) or stall ≥10× above the attack-free gap;
  trimmed mean, median, and clipped gossip must land within 2× of it —
  both asserted;
- ALIE and LARGE-NOISE rows at the same placement (table rows, no hard
  gate — ALIE is designed to slip through screens, so its damage is
  bounded but nonzero on BOTH the plain and the screened path);
- BREAKDOWN SWEEP: trimmed mean at fixed budget b=1 against f ∈ {3, 10}
  attackers. Breakdown on a sparse graph is about PLACEMENT, not the
  global fraction: f=10 (seed 203) puts BOTH ring neighbors of two
  honest nodes in the Byzantine set, so their trimmed windows are
  attacker-bracketed — past the per-neighborhood budget even though
  10/64 < 5/16.

The IID partition is load-bearing, not cosmetic: screened aggregation
pays a bias ∝ attack fraction × gradient heterogeneity (He-Karimireddy-
Jaggi 2022), so under the study's sorted non-IID split the same rules
stall far above the attack-free gap — the sweep records that row too so
the limitation is measured, not hidden.

Writes ``docs/perf/byzantine.json``.

Usage:  python examples/bench_byzantine.py [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/perf/byzantine.json")
    args = ap.parse_args()

    import jax
    import numpy as np

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.metrics import iterations_to_threshold
    from distributed_optimization_tpu.utils.data import generate_synthetic_dataset
    from distributed_optimization_tpu.utils.oracle import compute_reference_optimum

    base = ExperimentConfig(
        problem_type="logistic", algorithm="dsgd", topology="ring",
        n_workers=64, n_samples=6400, n_features=10,
        n_informative_features=6, n_iterations=4000, local_batch_size=100,
        eval_every=500, partition="shuffled",
    )
    # This artifact documents the GATHER robust path's breakdown table
    # (PR 3); pin it explicitly — since PR 6 a bare 'auto' on these
    # static-ring cells promotes to the fused pallas kernel, which would
    # silently change what a regen measures vs what the config string
    # claims (the fused path's own evidence is docs/perf/fused_robust.json).
    ROBUST_IMPL = "gather"
    # Attackers, per-neighborhood budget (ring min degree 2 => b <= 1),
    # sign-flip scale. f=6 under seed 203 places <= 1 attacker in every
    # honest closed ring neighborhood — within the b=1 budget everywhere;
    # f=10 sandwiches two honest nodes (both neighbors Byzantine), the
    # past-breakdown placement the sweep demonstrates.
    F, B, S = 6, 1, 5.0

    def attacked(attack, scale=S, f=F, **kw):
        if kw.get("robust_b", 0) > 0:
            kw.setdefault("robust_impl", ROBUST_IMPL)
        return base.replace(
            attack=attack, n_byzantine=f, attack_scale=scale, **kw
        )

    def defended(**kw):
        return base.replace(robust_impl=ROBUST_IMPL, **kw)

    variants = {
        "attack_free": base,
        "tm_b1_no_attack": defended(aggregation="trimmed_mean", robust_b=B),
        "median_b1_no_attack": defended(aggregation="median", robust_b=B),
        "clip_b1_no_attack": defended(
            aggregation="clipped_gossip", robust_b=B
        ),
        "tm_b0_no_attack": base.replace(aggregation="trimmed_mean", robust_b=0),
        "signflip_plain": attacked("sign_flip"),
        "signflip_tm": attacked(
            "sign_flip", aggregation="trimmed_mean", robust_b=B
        ),
        "signflip_median": attacked("sign_flip", aggregation="median", robust_b=B),
        "signflip_clip": attacked(
            "sign_flip", aggregation="clipped_gossip", robust_b=B
        ),
        "alie_plain": attacked("alie", scale=1.0),
        "alie_tm": attacked(
            "alie", scale=1.0, aggregation="trimmed_mean", robust_b=B
        ),
        "noise_plain": attacked("large_noise", scale=10.0),
        "noise_tm": attacked(
            "large_noise", scale=10.0, aggregation="trimmed_mean", robust_b=B
        ),
        # Breakdown sweep: fixed budget, placement past the neighborhood
        # budget (see module docstring — f=10 sandwiches honest nodes).
        "breakdown_tm_f3": attacked(
            "sign_flip", f=3, aggregation="trimmed_mean", robust_b=B
        ),
        "breakdown_tm_f10": attacked(
            "sign_flip", f=10, aggregation="trimmed_mean", robust_b=B
        ),
        "breakdown_plain_f3": attacked("sign_flip", f=3),
        # The measured non-IID limitation row (sorted partition).
        "signflip_tm_sorted": attacked(
            "sign_flip", aggregation="trimmed_mean", robust_b=B,
            partition="sorted",
        ),
    }

    # One dataset per partition flavor; f_opt from the same oracle path the
    # simulator uses.
    data = {}
    for part in ("shuffled", "sorted"):
        ds = generate_synthetic_dataset(base.replace(partition=part))
        _, f_opt = compute_reference_optimum(ds, base.reg_param)
        data[part] = (ds, f_opt)

    results: dict[str, dict] = {}
    trajectories: dict[str, list] = {}
    for name, cfg in variants.items():
        ds, f_opt = data[cfg.partition]
        r = jax_backend.run(cfg, ds, f_opt)
        h = r.history
        gap = float(h.objective[-1])
        results[name] = {
            "final_gap": None if np.isnan(gap) else round(gap, 6),
            "diverged": bool(np.isnan(gap)),
            "iterations_to_eps": int(iterations_to_threshold(
                h.objective, cfg.suboptimality_threshold, h.eval_iterations
            )),
            "final_honest_consensus": (
                None if np.isnan(h.consensus_error[-1])
                else round(float(h.consensus_error[-1]), 8)
            ),
        }
        trajectories[name] = [
            None if np.isnan(v) else round(float(v), 6)
            for v in h.objective
        ]
        print(f"[byzantine] {name:22s} gap {results[name]['final_gap']}",
              file=sys.stderr)

    clean = results["attack_free"]["final_gap"]
    for name, row in results.items():
        row["gap_vs_attack_free"] = (
            None if row["diverged"] or row["final_gap"] is None
            else round(row["final_gap"] / clean, 3)
        )

    # --- acceptance gates (the breakdown-point demonstration) ---
    # Zero-budget robust == plain gossip to accumulation roundoff (the
    # backend short-circuit makes it bitwise; assert the documented bound).
    zb = np.asarray(trajectories["tm_b0_no_attack"], dtype=np.float64)
    pl = np.asarray(trajectories["attack_free"], dtype=np.float64)
    assert np.max(np.abs(zb - pl)) <= 1e-12, (
        "zero-budget robust run must match plain gossip trajectories"
    )
    # Plain gossip under the in-budget sign-flip: divergent or >= 10x.
    sp = results["signflip_plain"]
    assert sp["diverged"] or sp["final_gap"] >= 10.0 * clean, (
        "plain gossip must diverge or stall >= 10x above attack-free"
    )
    # Robust rules under the same attack: within 2x of attack-free.
    for name in ("signflip_tm", "signflip_median", "signflip_clip"):
        row = results[name]
        assert not row["diverged"] and row["final_gap"] <= 2.0 * clean, (
            f"{name} must converge within 2x of the attack-free run"
        )
    # Past the breakdown point (a sandwiched neighborhood, f=10 placement)
    # the defense visibly degrades.
    assert (
        results["breakdown_tm_f10"]["diverged"]
        or results["breakdown_tm_f10"]["final_gap"]
        > 3.0 * results["breakdown_tm_f3"]["final_gap"]
    ), "past-budget placement should sit far above the tolerated rows"

    payload = {
        "device": str(jax.devices()[0]),
        "config": (
            "logistic N=64 ring T=4k shuffled partition (gather robust "
            f"path, robust_impl={ROBUST_IMPL!r} pinned — since PR 6 "
            "'auto' promotes these static cells to the fused kernel, "
            f"whose evidence is fused_robust.json); f={F} Byzantine of "
            f"64, per-neighborhood budget b={B}, sign-flip scale {S}"
        ),
        "note": (
            "final honest-suboptimality gap f(x_bar_honest) - f* per "
            "variant; gap_vs_attack_free is the breakdown criterion "
            "(plain diverges under the tolerated-placement sign-flip "
            "while trimmed mean/median/clipped gossip land within 2x of "
            "attack-free; trimmed mean under the f=10 placement — two "
            "honest nodes with BOTH ring neighbors Byzantine — sits past "
            "the per-neighborhood breakdown point). signflip_tm_sorted "
            "records the measured non-IID cost: screening bias scales "
            "with gradient heterogeneity, so the sorted partition lands "
            "above the IID row."
        ),
        "runs": results,
        "trajectories": trajectories,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(path)

    print(json.dumps({"metric": "byzantine_variants_measured",
                      "value": len(results)}))


if __name__ == "__main__":
    main()
