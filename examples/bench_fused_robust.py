"""Fused robust gather path + compressed gossip evidence (ISSUE-6).

Two measurements, one artifact (``docs/perf/fused_robust.json``):

1. **fused vs gather** — e2e throughput of the single-kernel pallas form
   (``robust_impl='fused'``: gather + screen + mix + SGD in one
   VMEM-resident pass, the [N, k_max, d] neighbor stack never
   materialized in HBM) against the multi-op gather path, per rule, on
   the N=256 ring headline shape of robust_scale.json. The fusion claim
   is a COMPILED-path claim: on real TPU the kernel lowers through
   Mosaic and the asserted floor applies (fused ≥ 1.1× gather for the
   count rules); on CPU hosts pallas runs in INTERPRETER mode — not the
   claimed artifact — so cells carry honest per-cell ``fused_loses``
   flags instead of a gate (same convention as robust_scale.json's
   crossover cells and sweep.json's CPU floor; as it happens the fused
   form measured a ~2.4× WIN here even interpreted — see the committed
   note). ``BENCH_NO_RANGE_CHECK`` escapes the accelerator gate for
   non-canonical hardware.

2. **bytes-vs-gap** — the compressed-gossip production currency:
   {none, top_k, qsgd} × {dsgd, gradient_tracking} error-feedback runs
   at MATCHED round counts, reporting floats moved per round next to the
   suboptimality-gap curve. ASSERTED: every compressed cell moves < 45%
   of the uncompressed bytes AND lands within the convergence envelope
   (final gap ≤ 3× the uncompressed final gap) — compression that met
   bandwidth targets by not converging would be a silent lie.

Protocol: variants interleave per cycle (shared-machine convention),
median across cycles, compile excluded.

Usage:  python examples/bench_fused_robust.py [--out PATH] [--cycles 2]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FLOOR_COMPILED = 1.1  # fused ≥ this × gather on accelerators (count rules)
BYTES_CEILING = 0.45  # compressed cells must move < this × full bytes
GAP_ENVELOPE = 3.0    # ... while landing ≤ this × the uncompressed gap


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cycles", type=int, default=2)
    ap.add_argument("--out", default="docs/perf/fused_robust.json")
    args = ap.parse_args()

    import jax

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.telemetry import comms_summary
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import (
        compute_reference_optimum,
    )

    dev = jax.devices()[0]
    platform = dev.platform
    on_accelerator = platform != "cpu"
    print(f"[fused_robust] device={dev}", file=sys.stderr)

    # --- 1. fused vs gather: N=256 ring, the robust_scale headline ------
    D_FEAT = 40
    N, T = 256, 150

    def robust_cfg(rule, impl):
        return ExperimentConfig(
            problem_type="logistic", algorithm="dsgd", topology="ring",
            n_workers=N, n_samples=N * 50, n_features=D_FEAT,
            n_informative_features=20, n_iterations=T, local_batch_size=16,
            eval_every=T // 2, partition="shuffled", aggregation=rule,
            robust_b=1, robust_impl=impl,
        )

    ds_robust = generate_synthetic_dataset(robust_cfg("trimmed_mean", "auto"))

    def ips(cfg, ds):
        r = jax_backend.run(cfg, ds, 0.0, measure_compile=False,
                            use_mesh=False)
        return float(r.history.iters_per_second)

    rules = ("trimmed_mean", "median", "clipped_gossip")
    fused_vs_gather = {
        rule: {"fused_ips": [], "gather_ips": []} for rule in rules
    }
    for c in range(args.cycles):
        for rule, row in fused_vs_gather.items():
            for impl in ("fused", "gather"):
                row[f"{impl}_ips"].append(
                    ips(robust_cfg(rule, impl), ds_robust)
                )
            print(
                f"[fused_robust] cycle {c + 1} {rule}: fused "
                f"{row['fused_ips'][-1]:.0f} gather "
                f"{row['gather_ips'][-1]:.0f}",
                file=sys.stderr,
            )
    for rule, row in fused_vs_gather.items():
        for impl in ("fused", "gather"):
            raw = row[f"{impl}_ips"]
            row[f"{impl}_ips_raw"] = [round(v, 1) for v in raw]
            row[f"{impl}_ips"] = round(statistics.median(raw), 1)
        row["fused_over_gather"] = round(
            row["fused_ips"] / row["gather_ips"], 2
        )
        row["fused_loses"] = row["fused_over_gather"] < 1.0
        row["pallas_mode"] = "mosaic" if on_accelerator else "interpret"

    # --- 2. bytes vs gap at matched rounds ------------------------------
    T2 = 600
    base = ExperimentConfig(
        problem_type="quadratic", algorithm="dsgd", topology="ring",
        n_workers=16, n_samples=1600, n_features=40,
        n_informative_features=25, n_iterations=T2, local_batch_size=16,
        eval_every=T2 // 6, partition="shuffled",
    )
    ds2 = generate_synthetic_dataset(base)
    _, f_opt = compute_reference_optimum(ds2, base.reg_param)
    d_model = ds2.n_features

    def comp_cfg(algo, comp):
        kw = {}
        if comp == "top_k":
            # keep d/5 coordinates: 2k = 16 floats/edge vs d_model = 41.
            kw = dict(compression="top_k", compression_k=8,
                      choco_gamma=0.15)
        elif comp == "qsgd":
            # 4-bit stochastic quantization: d·5/32 + 1 floats/edge.
            kw = dict(compression="qsgd", compression_k=4,
                      choco_gamma=0.3)
        return base.replace(algorithm=algo, **kw)

    bytes_vs_gap: dict = {}
    for c in range(args.cycles):
        for algo in ("dsgd", "gradient_tracking"):
            for comp in ("none", "top_k", "qsgd"):
                cfg = comp_cfg(algo, comp)
                r = jax_backend.run(cfg, ds2, f_opt, measure_compile=False,
                                    use_mesh=False)
                cell = bytes_vs_gap.setdefault(f"{algo}/{comp}", {
                    "gap_curve": [round(float(v), 6)
                                  for v in r.history.objective],
                    "eval_iterations": [int(v)
                                        for v in r.history.eval_iterations],
                    "floats_total": float(
                        r.history.total_floats_transmitted
                    ),
                    "floats_per_iteration": comms_summary(cfg, r.history)[
                        "floats_per_iteration_mean"
                    ],
                    "ips": [],
                })
                cell["ips"].append(float(r.history.iters_per_second))
                print(
                    f"[fused_robust] cycle {c + 1} {algo}/{comp}: gap "
                    f"{cell['gap_curve'][-1]:.4f} floats/iter "
                    f"{cell['floats_per_iteration']:.0f}",
                    file=sys.stderr,
                )
    for key, cell in bytes_vs_gap.items():
        cell["ips_raw"] = [round(v, 1) for v in cell["ips"]]
        cell["ips"] = round(statistics.median(cell["ips"]), 1)
    for algo in ("dsgd", "gradient_tracking"):
        full = bytes_vs_gap[f"{algo}/none"]
        for comp in ("top_k", "qsgd"):
            cell = bytes_vs_gap[f"{algo}/{comp}"]
            cell["bytes_fraction_of_full"] = round(
                cell["floats_total"] / full["floats_total"], 4
            )
            cell["gap_over_uncompressed"] = round(
                cell["gap_curve"][-1] / full["gap_curve"][-1], 3
            )

    # --- gates -----------------------------------------------------------
    skip = os.environ.get("BENCH_NO_RANGE_CHECK", "").lower() not in (
        "", "0", "false"
    )
    gates = {
        "compiled_floor": FLOOR_COMPILED,
        "bytes_ceiling": BYTES_CEILING,
        "gap_envelope": GAP_ENVELOPE,
        "floor_applied": bool(on_accelerator and not skip),
    }
    if on_accelerator and not skip:
        for rule in ("trimmed_mean", "median"):
            ratio = fused_vs_gather[rule]["fused_over_gather"]
            assert ratio >= FLOOR_COMPILED, (
                f"{rule}: fused must be >= {FLOOR_COMPILED}x gather on the "
                f"compiled (Mosaic) path, got {ratio}x — the fusion is not "
                "paying for its kernel"
            )
    elif not on_accelerator:
        print(
            "[fused_robust] CPU host: pallas runs interpreted — recording "
            "honest fused_loses flags, compiled-path floor not applicable",
            file=sys.stderr,
        )
    # Bytes-vs-gap gates apply on every platform: convergence math does
    # not depend on the chip.
    for algo in ("dsgd", "gradient_tracking"):
        for comp in ("top_k", "qsgd"):
            cell = bytes_vs_gap[f"{algo}/{comp}"]
            assert cell["bytes_fraction_of_full"] < BYTES_CEILING, (
                f"{algo}/{comp} moved {cell['bytes_fraction_of_full']:.0%} "
                f"of the uncompressed bytes (ceiling {BYTES_CEILING:.0%})"
            )
            assert cell["gap_over_uncompressed"] <= GAP_ENVELOPE, (
                f"{algo}/{comp} final gap is "
                f"{cell['gap_over_uncompressed']}x the uncompressed gap at "
                f"matched rounds (envelope {GAP_ENVELOPE}x) — bandwidth "
                "bought with non-convergence"
            )

    payload = {
        "device": str(dev),
        "platform": platform,
        "protocol": (
            f"Part 1: e2e jax-backend throughput, pure-defense robust runs "
            f"(robust_b=1, no adversary), N={N} ring, logistic d={D_FEAT}, "
            f"T={T}, fused (single pallas kernel) vs gather (multi-op), "
            f"median of {args.cycles} interleaved cycles, compile "
            f"excluded. Part 2: error-feedback compressed gossip, "
            f"quadratic N=16 ring d={d_model}, T={T2}, matched rounds per "
            "cell; floats accounting from the run's own realized totals."
        ),
        "note": (
            "fused_over_gather is the ISSUE-6 kernel criterion; the "
            "asserted floor is a COMPILED-path (Mosaic/TPU) claim and is "
            "gated to accelerator platforms. On CPU hosts pallas runs in "
            "interpreter mode — each cell records pallas_mode=interpret "
            "and an honest per-cell fused_loses flag instead of a gate "
            "(robust_scale.json crossover convention). Measured on this "
            "CPU container the fused form WINS anyway (~2.4x for the "
            "count rules): the interpret path still executes as one XLA "
            "region, and the width-(k_max+1) transposition sort network + "
            "one-hot rank selection beat the general jnp.sort + "
            "take_along_axis sequence at ring degree — but that is a CPU "
            "observation, not the artifact's claim. bytes_vs_gap is "
            "platform-independent: bytes_fraction_of_full < "
            f"{BYTES_CEILING} and gap_over_uncompressed <= {GAP_ENVELOPE} "
            "are asserted everywhere."
        ),
        "gates": gates,
        "fused_vs_gather": fused_vs_gather,
        "bytes_vs_gap": bytes_vs_gap,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(path, config=base)

    print(json.dumps({
        "metric": "compressed_dsgd_topk_bytes_fraction",
        "value": bytes_vs_gap["dsgd/top_k"]["bytes_fraction_of_full"],
    }))


if __name__ == "__main__":
    main()
