"""Worker-count scaling study on the real chip (VERDICT r1 item 8).

How does the framework scale in N — the honest scaling axis for this problem
family (SURVEY.md §5.7: the worker graph is the structural analog of sequence
parallelism)? Sweeps N ∈ {25, 64, 256, 1024, 4096} on the headline config (D-SGD,
ring, logistic, T=10k, parity eval cadence k=1) and records

- **iters/sec** (fused scan, best-of-2 per N, interleaved to blunt co-tenant
  noise on the shared tunneled chip),
- **consensus decay** over the horizon (first→last consensus error and the
  topology's spectral gap, which sets the rate), and
- the CPU reference-semantics simulator's iters/sec at the same N (the
  baseline the ≥50x north star is measured against), for N ≤ 256 (the numpy
  loop at N ≥ 1024 would take minutes for no additional insight; it scales
  ~1/N).

Artifacts: ``docs/perf/scaling.json`` + ``docs/figures/scaling.png`` + a
table in ``docs/PERF.md``. Usage: ``python examples/bench_scaling.py``.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_optimization_tpu.backends import jax_backend, numpy_backend
from distributed_optimization_tpu.config import ExperimentConfig
from distributed_optimization_tpu.utils.data import generate_synthetic_dataset
from distributed_optimization_tpu.utils.oracle import compute_reference_optimum

NS = (25, 64, 256, 1024, 4096)
T = 10_000
CYCLES = 2


def main() -> None:
    root = pathlib.Path(__file__).resolve().parents[1]
    setups = {}
    for n in NS:
        cfg = ExperimentConfig(
            problem_type="logistic", algorithm="dsgd", topology="ring",
            n_workers=n, n_iterations=T,
        )
        ds = generate_synthetic_dataset(cfg)
        _, f_opt = compute_reference_optimum(ds, cfg.reg_param)
        setups[n] = (cfg, ds, f_opt)

    rows = {n: {"iters_per_sec": 0.0} for n in NS}
    # Interleave cycles so chip-load swings hit every N comparably.
    for _ in range(CYCLES):
        for n, (cfg, ds, f_opt) in setups.items():
            res = jax_backend.run(cfg, ds, f_opt)
            h = res.history
            r = rows[n]
            r["iters_per_sec"] = max(
                r["iters_per_sec"], float(h.iters_per_second)
            )
            r["spectral_gap"] = h.spectral_gap
            r["final_gap"] = float(h.objective[-1])
            r["consensus_first"] = float(h.consensus_error[0])
            r["consensus_last"] = float(h.consensus_error[-1])

    # CPU reference-semantics baseline (200 iters is enough for steady rate).
    for n in NS:
        if n <= 256:
            cfg, ds, f_opt = setups[n]
            base = numpy_backend.run(
                cfg.replace(n_iterations=200), ds, f_opt
            )
            rows[n]["numpy_iters_per_sec"] = round(
                float(base.history.iters_per_second), 1
            )
            rows[n]["speedup_vs_numpy"] = round(
                rows[n]["iters_per_sec"] / base.history.iters_per_second, 1
            )

    for n in NS:
        rows[n]["iters_per_sec"] = round(rows[n]["iters_per_sec"], 1)
        print(f"[scaling] N={n}: {rows[n]}", file=sys.stderr, flush=True)

    out = {
        "config": f"dsgd ring logistic T={T} eval_every=1 (parity cadence)",
        "device": str(jax_backend.jax.devices()[0]),
        "rows": {str(n): rows[n] for n in NS},
    }
    perf_dir = root / "docs" / "perf"
    perf_dir.mkdir(parents=True, exist_ok=True)
    (perf_dir / "scaling.json").write_text(json.dumps(out, indent=2) + "\n")
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(perf_dir / "scaling.json")


    # Figure: iters/sec vs N and consensus decay vs N, same visual language
    # as the repo's report figures (log-scale, matplotlib defaults).
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(11, 4))
    ns = list(NS)
    ax1.plot(ns, [rows[n]["iters_per_sec"] for n in ns], "o-",
             label="TPU jax backend")
    cpu_ns = [n for n in ns if "numpy_iters_per_sec" in rows[n]]
    ax1.plot(cpu_ns, [rows[n]["numpy_iters_per_sec"] for n in cpu_ns], "s--",
             label="CPU reference-semantics")
    ax1.set_xscale("log", base=2)
    ax1.set_yscale("log")
    ax1.set_xlabel("workers N")
    ax1.set_ylabel("iterations / second")
    ax1.set_title("Throughput vs worker count (T=10k, ring)")
    ax1.grid(True, which="both", alpha=0.3)
    ax1.legend()

    ax2.plot(ns, [rows[n]["consensus_last"] for n in ns], "o-",
             label="consensus error @ T=10k")
    ax2.plot(ns, [rows[n]["spectral_gap"] for n in ns], "s--",
             label="ring spectral gap 1−ρ")
    ax2.set_xscale("log", base=2)
    ax2.set_yscale("log")
    ax2.set_xlabel("workers N")
    ax2.set_title("Consensus vs worker count")
    ax2.grid(True, which="both", alpha=0.3)
    ax2.legend()
    fig.tight_layout()
    fig_path = root / "docs" / "figures" / "scaling.png"
    fig_path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(fig_path, dpi=130)
    print(json.dumps({"wrote": ["docs/perf/scaling.json",
                                "docs/figures/scaling.png"]}))


if __name__ == "__main__":
    main()
