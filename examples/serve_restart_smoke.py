"""``make serve-restart-smoke``: a FULL process restart over the
persistent executable store (the ISSUE-15 restart-warm gate).

The in-process variants of this gate live in tier-1
(tests/test_exec_store.py) and the chaos suite (``store_restart`` mode);
this smoke is the operational proof with nothing shared but the disk:

1. spawns daemon A as a real subprocess (``python -m
   distributed_optimization_tpu.serve --store DIR --port 0
   --port-file F``), waits for the port file;
2. serves one config cold over the wire — a compile happens, and the
   executable is written through to the store;
3. SIGKILLs daemon A (no drain, no atexit — the crash case);
4. spawns daemon B over the SAME store directory, replays the SAME
   config, and asserts the restart-warm contract: ``cache_hit`` true,
   ``compile_seconds == 0.0``, and a bitwise-identical final gap;
5. shuts daemon B down cleanly.

Exit code 0 = all assertions passed.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

BOOT_DEADLINE_S = 180.0  # subprocess jax import + daemon bind


def _spawn_daemon(store: str, port_file: str) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    ).rstrip(os.pathsep)
    return subprocess.Popen(
        [
            sys.executable, "-m", "distributed_optimization_tpu.serve",
            "--port", "0", "--port-file", port_file,
            "--store", store, "--window-ms", "0", "--quiet",
        ],
        env=env, cwd=str(REPO),
    )


def _wait_port(port_file: str, proc: subprocess.Popen) -> str:
    deadline = time.perf_counter() + BOOT_DEADLINE_S
    while time.perf_counter() < deadline:
        if proc.poll() is not None:
            raise SystemExit(
                f"daemon died during boot (exit {proc.returncode})"
            )
        try:
            text = Path(port_file).read_text().strip()
        except OSError:
            text = ""
        if text:
            return f"http://{text}"
        time.sleep(0.1)
    raise SystemExit("daemon did not write its port file in time")


def main() -> int:
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.serving.client import RetryingClient

    cfg = ExperimentConfig(
        n_workers=8, n_samples=160, n_features=6,
        n_informative_features=4, problem_type="quadratic",
        n_iterations=60, eval_every=20, local_batch_size=8,
        dtype="float64",
    )
    with tempfile.TemporaryDirectory(prefix="dopt-restart-smoke-") as tmp:
        store = os.path.join(tmp, "store")

        # --- daemon A: cold serve, write-through to the store ----------
        pf_a = os.path.join(tmp, "port_a")
        proc_a = _spawn_daemon(store, pf_a)
        try:
            url_a = _wait_port(pf_a, proc_a)
            print(f"[restart-smoke] daemon A at {url_a}", file=sys.stderr)
            client_a = RetryingClient(url_a, max_retries=8, seed=0)
            code, m1 = client_a.run(cfg.to_dict(), timeout=300.0)
            assert code == 200, (code, m1)
            serving1 = m1["health"]["serving"]
            assert serving1["cache_hit"] is False, serving1
            assert m1["compile_seconds"] > 0.0, m1["compile_seconds"]
            gap1 = m1["health"]["final_gap"]
            artifacts = list(Path(store).glob("*.dopt-exec"))
            assert artifacts, "no executable persisted to the store"
            print(
                f"[restart-smoke] cold serve: compile "
                f"{m1['compile_seconds']:.2f}s, {len(artifacts)} "
                f"artifact(s) on disk",
                file=sys.stderr,
            )
        finally:
            # --- the crash: SIGKILL, nothing flushed -------------------
            if proc_a.poll() is None:
                proc_a.send_signal(signal.SIGKILL)
            proc_a.wait(timeout=30.0)
        print("[restart-smoke] daemon A SIGKILLed", file=sys.stderr)

        # --- daemon B: same store, must start warm ---------------------
        pf_b = os.path.join(tmp, "port_b")
        proc_b = _spawn_daemon(store, pf_b)
        try:
            url_b = _wait_port(pf_b, proc_b)
            print(f"[restart-smoke] daemon B at {url_b}", file=sys.stderr)
            client_b = RetryingClient(url_b, max_retries=8, seed=0)
            code, m2 = client_b.run(cfg.to_dict(), timeout=300.0)
            assert code == 200, (code, m2)
            serving2 = m2["health"]["serving"]
            assert serving2["cache_hit"] is True, serving2
            assert m2["compile_seconds"] == 0.0, (
                f"restart replay recompiled "
                f"({m2['compile_seconds']}s) — the store did not warm "
                "the new process"
            )
            gap2 = m2["health"]["final_gap"]
            assert gap1 is not None and gap1 == gap2, (
                f"restart replay is not bitwise: {gap1!r} vs {gap2!r}"
            )
            print(
                "[restart-smoke] restart replay: 0 compile seconds, "
                f"bitwise final gap {gap2:.6e}",
                file=sys.stderr,
            )
            code, body = client_b.shutdown()
            assert code == 200 and body["status"] == "shutting_down"
            proc_b.wait(timeout=60.0)
        finally:
            if proc_b.poll() is None:
                proc_b.send_signal(signal.SIGKILL)
                proc_b.wait(timeout=30.0)
    print("[restart-smoke] PASS")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
