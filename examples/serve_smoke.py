"""``make serve-smoke``: boot the daemon, exercise it over the wire, shut
down cleanly.

The CI-sized end-to-end check of the serving subsystem (docs/SERVING.md):

1. boots ``ServingDaemon`` on an ephemeral port (real HTTP, real threads);
2. submits THREE requests over the wire — two structurally identical
   (eta0 variants of one config: must coalesce into ONE run_batch cohort
   and therefore ONE compile) and one structural outlier (its own
   compile);
3. asserts exactly 2 compiles for the 3 requests, the cohort/coalescing
   facts in the returned manifests, and response correctness (the served
   final gap equals a direct in-process ``jax_backend.run`` of the same
   config over the same dataset);
4. POSTs ``/v1/shutdown`` and verifies the server actually stopped.

Exit code 0 = all assertions passed.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.serving.cache import ExecutableCache
    from distributed_optimization_tpu.serving.client import (
        RetriesExhaustedError,
        RetryingClient,
    )
    from distributed_optimization_tpu.serving.daemon import ServingDaemon
    from distributed_optimization_tpu.serving.service import (
        ServingOptions,
        SimulationService,
    )

    base = ExperimentConfig(
        n_workers=8, n_samples=400, n_features=10,
        n_informative_features=6, problem_type="logistic",
        n_iterations=60, eval_every=20, local_batch_size=8,
        dtype="float64",
    )
    # A window long enough that the two structurally identical requests
    # land in the same scheduling cut over real HTTP round-trips.
    opts = ServingOptions(window_s=0.3, max_cohort=32)
    daemon = ServingDaemon(
        "127.0.0.1", 0, opts,
        service=SimulationService(opts, cache=ExecutableCache()),
    )
    daemon.start()
    url = daemon.url
    # The documented serving client (ISSUE-12 satellite): bounded retry
    # with backoff + jitter on 429 backpressure and connection resets.
    client = RetryingClient(url, max_retries=4, seed=0)
    print(f"[serve-smoke] daemon at {url}", file=sys.stderr)
    try:
        # --- submit 3 requests over the wire (2 structurally identical) --
        code_a, sub_a = client.submit(base.to_dict())
        code_b, sub_b = client.submit(
            base.replace(learning_rate_eta0=0.11).to_dict()
        )
        code_c, sub_c = client.submit(
            base.replace(topology="fully_connected").to_dict()
        )
        assert (code_a, code_b, code_c) == (202, 202, 202), "submit failed"

        manifests = {}
        for sub in (sub_a, sub_b, sub_c):
            code, m = client.result(sub["id"], timeout=300)
            assert code == 200 and m["kind"] == "run_trace", (code, m)
            manifests[sub["id"]] = m

        # --- one compile for the identical pair, one for the outlier ----
        sa = manifests[sub_a["id"]]["health"]["serving"]
        sb = manifests[sub_b["id"]]["health"]["serving"]
        sc = manifests[sub_c["id"]]["health"]["serving"]
        assert sa["cohort_size"] == 2 and sa["coalesced"], sa
        assert sb["cohort_size"] == 2 and sb["coalesced"], sb
        assert sc["cohort_size"] == 1 and not sc["coalesced"], sc
        code, st = client.status()
        assert code == 200
        misses = st["cache"]["misses"]
        assert misses == 2, (
            f"expected exactly 2 compiles for 3 requests "
            f"(coalesced pair + outlier), cache recorded {misses}"
        )
        print(
            f"[serve-smoke] 3 requests -> {misses} compiles "
            f"(pair coalesced at R=2), queue stats {st['cohorts']}",
            file=sys.stderr,
        )

        # --- correctness over the wire: served gap == direct run --------
        from distributed_optimization_tpu.backends import jax_backend
        from distributed_optimization_tpu.utils.data import (
            generate_synthetic_dataset,
        )
        from distributed_optimization_tpu.utils.oracle import (
            compute_reference_optimum,
        )

        ds = generate_synthetic_dataset(base)
        _, f_opt = compute_reference_optimum(ds, base.reg_param)
        direct = jax_backend.run(base, ds, f_opt, executable_cache=False)
        served_gap = manifests[sub_a["id"]]["health"]["final_gap"]
        dev = abs(served_gap - float(direct.history.objective[-1]))
        assert dev <= 1e-12, (
            f"served final gap deviates from the direct run by {dev}"
        )
        print(f"[serve-smoke] parity OK (|dev| = {dev:.2e})", file=sys.stderr)

        # --- clean shutdown over the wire -------------------------------
        code, body = client.shutdown()
        assert code == 200 and body["status"] == "shutting_down"
        # A no-retry probe must see the daemon actually gone (the
        # retrying client would keep trying — exactly what we do NOT
        # want when asserting death).
        probe = RetryingClient(url, max_retries=0)
        deadline = time.perf_counter() + 10.0
        stopped = False
        while time.perf_counter() < deadline:
            try:
                probe.status(timeout=1.0)
            except RetriesExhaustedError:
                stopped = True
                break
            time.sleep(0.1)
        assert stopped, "daemon still answering after /v1/shutdown"
        print("[serve-smoke] clean shutdown confirmed", file=sys.stderr)
    finally:
        daemon.stop()
    print("[serve-smoke] PASS")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
