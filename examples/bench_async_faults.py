"""Faults on the event clock: robustness evidence (ISSUE 17 headline
artifact; docs/ASYNC.md "Faults on the event clock").

PR 9 put the optimizer on the event clock; PR 17 puts the FAULT MODEL
there too (``parallel/events.py::realize_event_faults``). This bench pins
the four contracts that make event-indexed faults trustworthy:

- CRASH-FREE BITWISE GATE: threading all-up fault masks through the
  fault-aware program must realize the IDENTICAL trajectory as the plain
  PR 9 async scan — asserted bitwise (f64) on final models and the
  objective history, on the jax backend.
- TRACKING-INVARIANT BOUND + DEGRADATION CURVE: the per-event tracker
  telescoping keeps the DIGing identity mean(y) == mean(g_prev) EXACT at
  any staleness, faults included — asserted <= 1e-9 (f64) on every
  gradient-tracking cell, including the composed crash × thinning cell.
  What staleness does cost is recorded as the degradation curve: final
  optimality gap vs realized p90 staleness across matched-mean latency
  tails (constant / exponential / lognormal 0.75 / lognormal 1.25).
- NO-FREE-LUNCH ENVELOPE AT MATCHED AVAILABILITY: event churn at
  mttf/(mttf+mttr) = a and participation thinning at rate a remove the
  same fraction of events; neither may beat the healthy run (floor
  0.8x), and the two faulty finals must sit within a 2x envelope of each
  other — losing availability costs the same whether events die
  mid-flight or are thinned before launch.
- WALL-CLOCK-TO-ε UNDER FAULTS: on the SAME latency draws and the SAME
  churn chains, the synchronous barrier pays max-of-N per round while
  async is paced by mean latency — asserted >= 2x simulated
  wall-clock-to-ε speedup at a matched ε under heavy-tail latency with
  crash churn live.

Writes ``docs/perf/async_faults.json`` (gate outcomes, degradation
curve, realized availabilities, crossing times, honest per-cell flags).

Usage:  python examples/bench_async_faults.py [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/perf/async_faults.json")
    args = ap.parse_args()

    import jax
    import numpy as np

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.backends.async_scan import (
        event_faults_for,
        run_async,
        timeline_for,
    )
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.parallel import build_topology
    from distributed_optimization_tpu.parallel.events import (
        staleness_histogram,
        sync_round_times,
    )
    from distributed_optimization_tpu.parallel.faults import (
        FaultTimeline,
        _edge_list,
    )
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import (
        compute_reference_optimum,
    )

    base = ExperimentConfig(
        problem_type="quadratic", algorithm="dsgd", topology="ring",
        n_workers=16, n_samples=1600, n_features=10,
        n_informative_features=6, n_iterations=800, local_batch_size=16,
        eval_every=50, execution="async", latency_model="lognormal",
        latency_mean=1.0, latency_tail=1.25, seed=7,
    )
    N, T, EVERY = base.n_workers, base.n_iterations, base.eval_every
    ds = generate_synthetic_dataset(base)
    _, f_opt = compute_reference_optimum(ds, base.reg_param)

    def topo_for(cfg):
        return build_topology(
            cfg.topology, cfg.n_workers, erdos_renyi_p=cfg.erdos_renyi_p,
            seed=cfg.resolved_topology_seed(),
        )

    def first_crossing(gaps, clocks, eps):
        hit = np.nonzero(np.asarray(gaps) <= eps)[0]
        return float(clocks[hit[0]]) if hit.size else None

    results: dict[str, dict] = {}
    gates: dict[str, object] = {}

    # --- 1. crash-free bitwise gate --------------------------------------
    # All-up masks thread the fault-aware scan; the realized trajectory
    # must be bitwise the PR 9 program (f64, small cell — this is a
    # program-identity statement, not a statistics statement).
    bw_cfg = base.replace(
        n_workers=8, n_iterations=200, n_samples=800, dtype="float64",
        latency_tail=0.5,
    )
    bw_ds = generate_synthetic_dataset(bw_cfg)
    _, bw_f = compute_reference_optimum(bw_ds, bw_cfg.reg_param)
    bw_topo = topo_for(bw_cfg)
    bw_edges = _edge_list(bw_topo)
    t8, n8 = bw_cfg.n_iterations, bw_cfg.n_workers
    all_up = FaultTimeline(
        horizon=t8, directed=False, edge_index=bw_edges,
        edge_up=np.ones((t8, len(bw_edges)), bool),
        node_up=np.ones((t8, n8), bool),
        rejoin=np.zeros((t8, n8), bool),
        part_up=np.ones((t8, n8), bool),
    )
    plain = run_async(bw_cfg, bw_ds, bw_f)
    forced = run_async(bw_cfg, bw_ds, bw_f, _fault_timeline=all_up)
    crash_free_bitwise = bool(
        np.array_equal(np.array(plain.final_models),
                       np.array(forced.final_models))
        and np.array_equal(np.array(plain.history.objective),
                           np.array(forced.history.objective))
    )
    assert crash_free_bitwise, (
        "all-up fault masks must realize the PR 9 async program bitwise"
    )
    results["crash_free_gate"] = {
        "cell": "N=8 T=200 f64 lognormal(0.5)",
        "bitwise_final_models": crash_free_bitwise,
        "bitwise_objective_history": crash_free_bitwise,
    }
    print("[gate] crash-free all-up injection: BITWISE", file=sys.stderr)

    # --- 2. tracking invariant + degradation curve vs p90 staleness ------
    # The telescoping identity is exact at any staleness; what staleness
    # DOES cost shows up in the final gap. Sweep matched-mean tails on
    # gradient tracking (f64 so the invariant bound is a real number, not
    # a float32 artifact), plus one composed-fault cell.
    GT_CELLS = [
        ("constant", "constant", 0.0, None),
        ("exponential", "exponential", 0.0, None),
        ("lognormal_0.75", "lognormal", 0.75, None),
        ("lognormal_1.25", "lognormal", 1.25, None),
        ("lognormal_1.25_churn", "lognormal", 1.25, dict(
            mttf=12.0, mttr=4.0, participation_rate=0.9,
        )),
    ]
    curve = []
    invariant_bound = 1e-9
    max_residual = 0.0
    for name, model, tail, faults in GT_CELLS:
        c = base.replace(
            algorithm="gradient_tracking", latency_model=model,
            latency_tail=tail, dtype="float64", **(faults or {}),
        )
        r = run_async(c, ds, f_opt, return_state=True)
        state = r.final_state
        residual = float(np.max(np.abs(
            np.asarray(state["y"]).mean(axis=0)
            - np.asarray(state["g_prev"]).mean(axis=0)
        )))
        max_residual = max(max_residual, residual)
        _, tl = timeline_for(c)
        s = np.asarray(tl.staleness)
        p90 = float(np.percentile(s, 90))
        p99 = float(np.percentile(s, 99))
        row = {
            "latency_model": model, "latency_tail": tail,
            "faults": faults or None,
            "p90_staleness": p90,
            "p99_staleness": p99,
            "max_staleness": int(s.max()),
            "staleness": staleness_histogram(tl),
            "tracking_residual": residual,
            "final_gap": round(float(r.history.objective[-1]), 6),
        }
        if faults:
            _, real, _ = event_faults_for(c, topo_for(c), tl)
            row["availability"] = round(float(real.availability), 4)
        results[f"gt_{name}"] = row
        curve.append({
            "cell": name, "p90_staleness": p90, "p99_staleness": p99,
            "max_staleness": int(s.max()),
            "final_gap": row["final_gap"],
            "tracking_residual": residual,
        })
        assert residual < invariant_bound, (
            f"{name}: tracker residual {residual} breaks the telescoping "
            f"identity bound {invariant_bound}"
        )
        print(
            f"[gt]   {name:22s} p99/max staleness {p99:4.1f}/"
            f"{int(s.max()):3d}  residual {residual:.2e}  "
            f"final {row['final_gap']:.3f}",
            file=sys.stderr,
        )
    # Fresh-read pin: at constant latency staleness never exceeds the
    # intra-round tie (max 1), so the constant cell's residual is the
    # strictest invariant statement — keep it separately visible.
    assert results["gt_constant"]["max_staleness"] <= 1
    results["degradation_curve"] = curve

    # --- 3. no-free-lunch envelope at matched availability ---------------
    # Churn at mttf/(mttf+mttr) = 0.75 vs participation thinning at rate
    # 0.75: same expected event loss, two different mechanisms.
    healthy = jax_backend.run(base, ds, f_opt)
    churn_cfg = base.replace(mttf=12.0, mttr=4.0)
    thin_cfg = base.replace(participation_rate=0.75)
    runs = {}
    for name, c in (("churn", churn_cfg), ("thinning", thin_cfg)):
        r = jax_backend.run(c, ds, f_opt)
        _, tl = timeline_for(c)
        _, real, _ = event_faults_for(c, topo_for(c), tl)
        runs[name] = {
            "final_gap": round(float(r.history.objective[-1]), 6),
            "availability": round(float(real.availability), 4),
            "n_inflight_lost": int(real.n_inflight_lost),
            "n_thinned": int(real.n_thinned),
            "matched_fired": int(real.matched_fired.sum()),
            "realized_floats": float(r.history.total_floats_transmitted),
        }
    g_h = float(healthy.history.objective[-1])
    g_c = runs["churn"]["final_gap"]
    g_t = runs["thinning"]["final_gap"]
    envelope = max(g_c, g_t) / min(g_c, g_t)
    no_free_lunch = bool(g_c >= 0.8 * g_h and g_t >= 0.8 * g_h)
    matched_envelope_holds = bool(envelope <= 2.0)
    results["matched_availability"] = {
        "healthy_final_gap": round(g_h, 6),
        "churn": runs["churn"],
        "thinning": runs["thinning"],
        "faulty_vs_faulty_envelope": round(envelope, 4),
        "no_free_lunch": no_free_lunch,
        "matched_envelope_holds": matched_envelope_holds,
    }
    assert no_free_lunch, (
        f"a faulty run beat healthy past the noise floor: churn {g_c}, "
        f"thinning {g_t}, healthy {g_h}"
    )
    assert matched_envelope_holds, (
        f"matched-availability mechanisms diverge {envelope:.2f}x — churn "
        "and thinning at the same rate should cost about the same"
    )
    print(
        f"[nfl]  healthy {g_h:.3f}  churn {g_c:.3f} "
        f"(avail {runs['churn']['availability']})  thinning {g_t:.3f} "
        f"(avail {runs['thinning']['availability']})  envelope "
        f"{envelope:.2f}x",
        file=sys.stderr,
    )

    # --- 4. wall-clock-to-ε under faults ---------------------------------
    # Same latency draws (sync_round_times prices the barrier on the
    # async timeline's durations), same churn chains (same config seed):
    # the barrier tax survives the fault composition.
    # The sync twin drops the latency knobs (they shape only the event
    # schedule); its churn chains come from the SAME config seed.
    sync_cfg = churn_cfg.replace(
        execution="sync", latency_model="constant", latency_mean=1.0,
        latency_tail=0.0,
    )
    r_sync = jax_backend.run(sync_cfg, ds, f_opt)
    r_async = jax_backend.run(churn_cfg, ds, f_opt)
    gaps_sync = r_sync.history.objective
    gaps_async = r_async.history.objective
    _, tl = timeline_for(churn_cfg)
    vt_async = tl.t_virtual[EVERY * N - 1:: EVERY * N]
    vt_sync = sync_round_times(tl)[EVERY - 1:: EVERY]
    eps = 1.3 * max(float(gaps_async[-1]), float(gaps_sync[-1]))
    t_async = first_crossing(gaps_async, vt_async, eps)
    t_sync = first_crossing(gaps_sync, vt_sync, eps)
    speedup = t_sync / t_async if t_async and t_sync else None
    results["wall_clock_under_faults"] = {
        "cell": "lognormal(1.25) x churn mttf=12 mttr=4",
        "eps": round(eps, 6),
        "final_gap": {
            "async": round(float(gaps_async[-1]), 6),
            "sync": round(float(gaps_sync[-1]), 6),
        },
        "wall_clock_to_eps": {"async": t_async, "sync": t_sync},
        "wall_clock_speedup": (
            round(speedup, 3) if speedup is not None else None
        ),
        "async_loses_final_gap": bool(
            float(gaps_async[-1]) > 2.0 * float(gaps_sync[-1])
        ),
    }
    assert speedup is not None and speedup >= 2.0, (
        f"wall-clock-to-eps speedup {speedup} under the 2x floor — the "
        "barrier tax should survive the fault composition"
    )
    print(
        f"[wall] eps {eps:.3f}  async {t_async:.1f}  sync {t_sync:.1f}  "
        f"speedup {speedup:.2f}x",
        file=sys.stderr,
    )

    gates.update({
        "crash_free_bitwise": crash_free_bitwise,
        "tracking_invariant_bound": invariant_bound,
        "tracking_residual_max": max_residual,
        "tracking_residual_staleness_zero": (
            results["gt_constant"]["tracking_residual"]
        ),
        "no_free_lunch_floor": 0.8,
        "no_free_lunch_holds": no_free_lunch,
        "matched_availability_envelope": 2.0,
        "matched_availability_envelope_holds": matched_envelope_holds,
        "wall_clock_speedup_floor_under_faults": 2.0,
        "wall_clock_speedup_under_faults": (
            round(speedup, 3) if speedup is not None else None
        ),
    })

    payload = {
        "device": str(jax.devices()[0]),
        "config": (
            f"quadratic N={N} ring T={T} async lognormal(1.25); crash-free "
            "bitwise gate at N=8 T=200 f64; gradient-tracking staleness "
            "sweep (constant / exponential / lognormal 0.75 / 1.25 / "
            "composed churn) f64; matched-availability churn "
            "(mttf=12, mttr=4) vs thinning (rate 0.75); sync barrier "
            "priced on the SAME draws via sync_round_times"
        ),
        "note": (
            "Faults live on the EVENT axis: a crashed worker's in-flight "
            "event is a no-op, a dead partner degrades the exchange to a "
            "self-loop, participation thins events at the matched rate. "
            "The crash-free gate proves the fault-aware program IS the "
            "PR 9 program when nothing fails (bitwise). The tracking "
            "residual shows the per-event telescoping is exact at any "
            "staleness — staleness costs final-gap (the degradation "
            "curve), never the invariant. Matched availability costs "
            "about the same whether events die mid-flight or are thinned "
            "pre-launch (no free lunch, both directions). The barrier "
            "tax survives churn: sync pays max-of-N on the same draws "
            "and the same outage chains."
        ),
        "gates": gates,
        "runs": results,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(path, config=base)

    print(json.dumps({
        "metric": "async_fault_wall_clock_speedup",
        "value": gates["wall_clock_speedup_under_faults"],
    }))


if __name__ == "__main__":
    main()
