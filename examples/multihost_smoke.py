"""Two-process ``jax.distributed`` smoke test for the multihost path
(VERDICT r1 item 6).

The ``--multihost`` CLI flag and the ``process_allgather`` fetch in
``jax_backend._fetch_to_host`` are the first things that would break on a
real pod slice; this script exercises them without one: it launches TWO
localhost processes (each contributing 4 virtual CPU devices, 8 global),
wires them with ``jax.distributed.initialize``, runs an identical tiny
D-SGD config through ``jax_backend.run`` on the global 8-device mesh, and
verifies both processes fetch identical final models and metric histories.

Launcher mode (no args): spawns the two workers, waits, compares outputs.
Worker mode (``--process-id I --coordinator ADDR --out FILE``): runs the
experiment and dumps results as JSON.

Used by ``tests/test_multihost.py``; also runnable standalone:
``python examples/multihost_smoke.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

N_PROCESSES = 2
DEVICES_PER_PROCESS = 4


def worker(process_id: int, coordinator: str, out_path: str) -> None:
    # Env (JAX_PLATFORMS / XLA_FLAGS) is set by the launcher BEFORE python
    # starts, so jax initializes the virtual CPU devices correctly here.
    # ``process_id == -1`` is the single-process ground-truth run: the same
    # config on one process holding all 8 devices, no jax.distributed.
    import jax

    # The axon TPU plugin's sitecustomize pins jax_platforms via jax.config,
    # which overrides the env var; re-pin CPU before any backend initializes
    # (same workaround as tests/conftest.py).
    jax.config.update("jax_platforms", "cpu")
    if process_id >= 0:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=N_PROCESSES,
            process_id=process_id,
        )
        assert jax.process_count() == N_PROCESSES
    assert len(jax.devices()) == N_PROCESSES * DEVICES_PER_PROCESS

    import numpy as np

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.utils.data import generate_synthetic_dataset
    from distributed_optimization_tpu.utils.oracle import compute_reference_optimum

    cfg = ExperimentConfig(
        n_workers=8,
        n_samples=320,
        n_features=10,
        n_informative_features=6,
        n_iterations=40,
        local_batch_size=8,
        problem_type="quadratic",
        algorithm="dsgd",
        topology="ring",
        eval_every=10,
    )
    # Deterministic host-side generation: every process builds the same data.
    ds = generate_synthetic_dataset(cfg)
    _, f_opt = compute_reference_optimum(ds, cfg.reg_param)
    res = jax_backend.run(cfg, ds, f_opt)

    with open(out_path, "w") as f:
        json.dump(
            {
                "process_id": process_id,
                "process_count": jax.process_count(),
                "global_devices": len(jax.devices()),
                "final_models": np.asarray(res.final_models).tolist(),
                "objective": np.asarray(res.history.objective).tolist(),
                "consensus": np.asarray(res.history.consensus_error).tolist(),
                "total_floats": res.history.total_floats_transmitted,
            },
            f,
        )


def launch() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    coordinator = f"localhost:{port}"

    tmp = tempfile.mkdtemp(prefix="multihost_smoke_")
    outs = [os.path.join(tmp, f"proc{i}.json") for i in range(N_PROCESSES)]
    single_out = os.path.join(tmp, "proc_single.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={DEVICES_PER_PROCESS}"
    )
    # Scrub any inherited single-controller/TPU plugin state.
    env.pop("JAX_PLATFORM_NAME", None)

    # Single-process ground truth: all 8 devices in ONE process, same
    # config. The two distributed processes agreeing with EACH OTHER could
    # hide a correlated multi-process error; agreeing with this run cannot.
    env_single = dict(env)
    env_single["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count="
        f"{N_PROCESSES * DEVICES_PER_PROCESS}"
    )

    procs = [
        subprocess.Popen(
            [
                sys.executable, os.path.abspath(__file__),
                "--process-id", str(i),
                "--coordinator", coordinator,
                "--out", outs[i],
            ],
            env=env,
            cwd=REPO_ROOT,
        )
        for i in range(N_PROCESSES)
    ] + [
        subprocess.Popen(
            [
                sys.executable, os.path.abspath(__file__),
                "--process-id", "-1",
                "--coordinator", "unused",
                "--out", single_out,
            ],
            env=env_single,
            cwd=REPO_ROOT,
        )
    ]
    try:
        # Shorter than the pytest wrapper's 540 s timeout, so a hung worker
        # is reaped here rather than orphaned when the wrapper kills only
        # this launcher.
        rcs = [p.wait(timeout=420) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(rc != 0 for rc in rcs):
        print(f"[multihost_smoke] worker exit codes: {rcs}", file=sys.stderr)
        return 1

    results = [json.load(open(o)) for o in outs]
    import numpy as np

    a, b = results
    assert a["process_count"] == b["process_count"] == N_PROCESSES
    assert a["global_devices"] == b["global_devices"] == 8
    np.testing.assert_array_equal(
        np.asarray(a["final_models"]), np.asarray(b["final_models"]),
        err_msg="process_allgather fetch disagrees across processes",
    )
    np.testing.assert_array_equal(
        np.asarray(a["objective"]), np.asarray(b["objective"])
    )
    np.testing.assert_array_equal(
        np.asarray(a["consensus"]), np.asarray(b["consensus"])
    )
    assert a["total_floats"] == b["total_floats"]
    assert np.all(np.isfinite(np.asarray(a["objective"])))
    # Cross-execution-topology equivalence: the 2-process run must match
    # the single-process 8-device ground truth (same global mesh/sharding,
    # different process boundaries; f32 tolerance for collective-order
    # differences).
    s = json.load(open(single_out))
    np.testing.assert_allclose(
        np.asarray(a["final_models"]), np.asarray(s["final_models"]),
        rtol=1e-5, atol=1e-6,
        err_msg="2-process run diverges from the single-process ground truth",
    )
    np.testing.assert_allclose(
        np.asarray(a["objective"]), np.asarray(s["objective"]),
        rtol=1e-4, atol=1e-6,
    )
    assert a["total_floats"] == s["total_floats"]
    print(
        "[multihost_smoke] OK: 2 processes x 4 devices, identical fetched "
        "results, matching the single-process ground truth; final gap "
        f"{a['objective'][-1]:.6f}"
    )
    return 0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--coordinator", type=str, default=None)
    ap.add_argument("--out", type=str, default=None)
    args = ap.parse_args()
    if args.process_id is None:
        raise SystemExit(launch())
    worker(args.process_id, args.coordinator, args.out)


if __name__ == "__main__":
    main()
