"""Measure every BASELINE.json target config end to end on the chip
(VERDICT r2 item 7): the five CLI presets, plus the 256-worker stretch
realizations — synthetic at N=256 (the scale BASELINE names; 12,500
samples support it) and the real-data digits set at N=256 (included for
completeness WITH its caveat: 1,797 real samples / 256 workers = ~7 per
worker, statistically degenerate — which is why the supported preset is
``digits-64``).

Writes ``docs/perf/presets.json``: per config — iters/sec, final
suboptimality gap, iterations-to-ε, consensus, floats transmitted.
Configs are not compared against each other, so runs are sequential (the
2-3× co-tenant swing caveat applies to the absolute iters/sec numbers,
not to the convergence results).

Usage:  python examples/bench_presets.py [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/perf/presets.json")
    args = ap.parse_args()

    import jax

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.cli import PRESETS
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.metrics import iterations_to_threshold
    from distributed_optimization_tpu.utils.data import (
        generate_digits_dataset,
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import compute_reference_optimum

    runs = {name: dict(overrides) for name, overrides in PRESETS.items()}
    # The stretch scale BASELINE.json names (256 workers) — synthetic data
    # at the size that supports it, and digits with the degeneracy caveat.
    # T=30k so the N=256 ring crosses ε within its horizon (measured
    # crossing ≈ iteration 22.5k). NOT the bench.py headline horizon:
    # round 4 moved the headline to T=300k to amortize fixed per-run
    # overhead, so these preset rows are convergence evidence, not
    # numbers comparable to the headline throughput.
    runs["stretch-synthetic-256"] = dict(
        problem_type="logistic", algorithm="dsgd", topology="ring",
        n_workers=256, n_iterations=30_000)
    runs["stretch-digits-256-degenerate"] = dict(
        problem_type="logistic", algorithm="dsgd", topology="ring",
        n_workers=256, n_iterations=30_000, dataset="digits")

    out_rows = {}
    for name, overrides in runs.items():
        dataset_kind = overrides.pop("dataset", "synthetic")
        cfg = ExperimentConfig(**overrides)
        ds = (generate_digits_dataset(cfg) if dataset_kind == "digits"
              else generate_synthetic_dataset(cfg))
        # Thread the problem-binding knobs like simulator.py does: the
        # softmax oracle must solve the CONFIGURED K (inferring max(y)+1
        # from a draw with unrealized classes would yield a smaller-K
        # optimum and wrong gaps), and huber's optimum depends on delta.
        _, f_opt = compute_reference_optimum(
            ds, cfg.reg_param, huber_delta=cfg.huber_delta,
            n_classes=cfg.n_classes,
        )
        r = jax_backend.run(cfg, ds, f_opt)
        h = r.history
        crossed = iterations_to_threshold(
            h.objective, cfg.suboptimality_threshold, h.eval_iterations)
        out_rows[name] = {
            "config": {k: overrides[k] for k in sorted(overrides)},
            "dataset": dataset_kind,
            "n_samples": int(ds.X_full.shape[0]),
            "samples_per_worker": round(ds.X_full.shape[0] / cfg.n_workers, 1),
            "T": cfg.n_iterations,
            "iters_per_sec": round(float(h.iters_per_second), 1),
            "compile_seconds": round(float(h.compile_seconds), 1),
            "initial_gap": round(float(h.objective[0]), 6),
            "final_gap": round(float(h.objective[-1]), 6),
            "iterations_to_eps": int(crossed),
            "final_consensus": (round(float(h.consensus_error[-1]), 8)
                                if h.consensus_error is not None else None),
            "floats_transmitted": float(h.total_floats_transmitted),
        }
        print(f"[presets] {name:32s} {out_rows[name]['iters_per_sec']:>9.0f} "
              f"iters/sec  gap {out_rows[name]['initial_gap']:.4f} -> "
              f"{out_rows[name]['final_gap']:.4f}  iters->eps "
              f"{out_rows[name]['iterations_to_eps']}", file=sys.stderr)

    payload = {
        "device": str(jax.devices()[0]),
        "note": "all five BASELINE.json target configs (CLI presets) plus "
                "the 256-worker stretch realizations, measured end to end "
                "on the chip at their default horizons (T=10k). The "
                "digits-256 row exists to document WHY the supported real-"
                "data preset is digits-64: 1,797 real samples over 256 "
                "workers is ~7/worker. Absolute iters/sec carries the "
                "shared chip's 2-3x co-tenant swing.",
        "runs": out_rows,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(path)

    print(json.dumps({"metric": "presets_measured", "value": len(out_rows)}))


if __name__ == "__main__":
    main()
