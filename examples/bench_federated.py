"""Federated-scale execution evidence (ISSUE 8) -> docs/perf/federated.json.

Three measured claims, each gated by an assertion so regressions fail the
regen run loudly:

1. **Local steps buy communication** — τ gradient steps per gossip round at
   UNCHANGED per-round comms: floats-to-ε drops ≥ 2× for some τ > 1 cell
   vs τ = 1 at a matched final-gap envelope (every τ > 1 cell ends at or
   below the τ = 1 final gap). The cost model is trivial and exact here:
   floats/round is constant in τ, so the reduction IS the rounds-to-ε
   ratio.
2. **Participation trades convergence for per-round cost** — client
   sampling at rate q realizes ≈ q²·Σdeg·d floats/round (both endpoints
   must be sampled in; measured against the analytic model per cell) with
   monotone convergence degradation across ≥ 3 rates.
3. **The matrix-free path lifts the worker axis to N ≥ 10k** — the
   neighbor-table route completes (throughput + peak RSS recorded, each
   cell in its own subprocess so peaks don't mask each other) where the
   dense representation is skipped-by-arithmetic at N = 10k, with honest
   per-cell ``matrix_free_loses`` flags where dense is measured faster on
   this CPU container.

CPU-container honesty: throughput numbers here are CPU numbers; the
within-artifact comparisons (τ ratios, rate curves, dense-vs-neighbor
flags) are the load-bearing content, same convention as the other benches.
"""

from __future__ import annotations

import json
import os
import resource
import sys
import time
from concurrent import futures
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

EPS = 10.0
OUT = REPO / "docs" / "perf" / "federated.json"

BASE = dict(
    n_workers=32, n_samples=3200, n_features=16, n_informative_features=10,
    problem_type="quadratic", topology="ring", algorithm="dsgd",
    local_batch_size=16, partition="shuffled", n_iterations=2000,
    eval_every=20,
)

TAUS = (1, 2, 4, 8)
RATES = (1.0, 0.5, 0.25)

# (n, topology_impl) scale cells; every cell runs in its own subprocess so
# per-cell peak RSS is honest. The graph is a sparse Erdős–Rényi draw at
# mean degree ~12 (p = 12/N) — the irregular-graph case where the dense
# route really is an [N, N] matmul per round and gather is the only
# matrix-free mixing (ring/torus stencils are already matrix-free either
# way). Dense at N = 10k is skipped by arithmetic: the [N, N] float64
# adjacency+mixing pair alone is ~1.6 GB before a single iteration runs —
# exactly the cap the matrix-free path removes.
SCALE_N = (1024, 4096, 10_000)
SCALE_MEAN_DEGREE = 12.0
SCALE_T = 100
DENSE_SKIP_N = 10_000


def _problem(cfg):
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import (
        compute_reference_optimum,
    )

    ds = generate_synthetic_dataset(cfg)
    _, f_opt = compute_reference_optimum(ds, cfg.reg_param)
    return ds, f_opt


def _run(cfg, ds, f_opt):
    from distributed_optimization_tpu.backends import jax_backend

    return jax_backend.run(cfg, ds, f_opt, use_mesh=False)


def bench_local_steps():
    import numpy as np

    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.metrics import iterations_to_threshold

    cfg0 = ExperimentConfig(**BASE)
    ds, f_opt = _problem(cfg0)
    cells = []
    for tau in TAUS:
        cfg = cfg0.replace(local_steps=tau)
        r = _run(cfg, ds, f_opt)
        floats_per_round = (
            r.history.total_floats_transmitted / cfg.n_iterations
        )
        rounds = iterations_to_threshold(
            r.history.objective, EPS, r.history.eval_iterations
        )
        cells.append({
            "local_steps": tau,
            "rounds_to_eps": rounds,
            "grad_steps_to_eps": rounds * tau if rounds > 0 else -1,
            "floats_to_eps": (
                rounds * floats_per_round if rounds > 0 else None
            ),
            "floats_per_round": floats_per_round,
            "final_gap": float(r.history.objective[-1]),
        })
        print(f"[local_steps] tau={tau}: rounds->eps={rounds}, "
              f"final gap={cells[-1]['final_gap']:.4g}")
    base_cell = cells[0]
    assert base_cell["floats_to_eps"] is not None, (
        "tau=1 baseline never reached eps; raise EPS or the horizon"
    )
    best = None
    for c in cells[1:]:
        # Matched final-gap envelope: a tau cell only counts if it ends at
        # or below the tau=1 final gap (communication saved, accuracy not
        # traded away).
        if c["floats_to_eps"] is None:
            continue
        if c["final_gap"] > base_cell["final_gap"] * 1.05:
            continue
        ratio = base_cell["floats_to_eps"] / c["floats_to_eps"]
        c["floats_reduction_vs_tau1"] = ratio
        if best is None or ratio > best:
            best = ratio
    assert best is not None and best >= 2.0, (
        f"no tau>1 cell achieved the >=2x floats-to-eps reduction at a "
        f"matched final-gap envelope (best={best})"
    )
    print(f"[local_steps] best floats-to-eps reduction: {best:.1f}x")
    return {
        "config": cfg0.to_dict(),
        "eps": EPS,
        "cells": cells,
        "best_floats_reduction": best,
        "asserted_floor": 2.0,
    }, cfg0


def bench_participation():
    import numpy as np

    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.metrics import iterations_to_threshold

    cfg0 = ExperimentConfig(**BASE)
    ds, f_opt = _problem(cfg0)
    d_payload = ds.n_features  # gossiped model dimension (d+1 bias column)
    cells = []
    for rate in RATES:
        cfg = cfg0.replace(participation_rate=rate)
        r = _run(cfg, ds, f_opt)
        realized = r.history.total_floats_transmitted / cfg.n_iterations
        # Cost model: an edge is realized iff BOTH endpoints are sampled
        # in -> E[floats/round] = q^2 * sum(deg) * d.
        analytic = rate * rate * 2.0 * cfg.n_workers * d_payload
        obj = np.asarray(r.history.objective, dtype=np.float64)
        cells.append({
            "participation_rate": rate,
            "final_gap": float(obj[-1]),
            "rounds_to_eps": iterations_to_threshold(
                obj, EPS, r.history.eval_iterations
            ),
            "floats_per_round_realized": realized,
            "floats_per_round_analytic": analytic,
            "gap_curve_every_200": obj[9::10].tolist(),
        })
        print(f"[participation] rate={rate}: final gap={obj[-1]:.4g}, "
              f"floats/round {realized:.1f} (model {analytic:.1f})")
    gaps = [c["final_gap"] for c in cells]
    assert all(g == g and g != float("inf") for g in gaps), gaps
    # Monotone degradation with sampling rate (rates are listed densest
    # first): fewer participating clients per round converge no faster.
    assert all(gaps[i] <= gaps[i + 1] * 1.05 for i in range(len(gaps) - 1)), (
        f"convergence not monotone in participation rate: {gaps}"
    )
    for c in cells:
        # The quadratic cost model holds to sampling noise.
        ratio = (
            c["floats_per_round_realized"] / c["floats_per_round_analytic"]
        )
        assert 0.9 < ratio < 1.1, (c["participation_rate"], ratio)
    return {
        "config": cfg0.to_dict(),
        "eps": EPS,
        "rates": list(RATES),
        "cells": cells,
        "note": (
            "gap_curve_every_200 rows are the convergence-vs-"
            "participation-rate curves (suboptimality at rounds 200, 400, "
            "..., 2000); floats/round realized matches the q^2*sum(deg)*d "
            "cost model within 10% per cell (asserted)"
        ),
    }, cfg0


def _scale_cell(args):
    """One (n, impl) throughput+memory cell; runs in a fresh subprocess so
    peak RSS is per-cell, not a running max over the whole bench."""
    n, impl = args
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np  # noqa: F401

    from distributed_optimization_tpu.config import ExperimentConfig

    cfg = ExperimentConfig(
        n_workers=n, n_samples=2 * n, n_features=16,
        n_informative_features=10, problem_type="quadratic",
        topology="erdos_renyi", erdos_renyi_p=SCALE_MEAN_DEGREE / n,
        algorithm="dsgd", local_batch_size=4,
        n_iterations=SCALE_T, eval_every=SCALE_T, topology_impl=impl,
    )
    ds, f_opt = _problem(cfg)
    t0 = time.perf_counter()
    r = _run(cfg, ds, f_opt)
    wall = time.perf_counter() - t0
    return {
        "n_workers": n,
        "topology_impl": impl,
        "resolved_impl": cfg.resolved_topology_impl(),
        "iters_per_second": float(r.history.iters_per_second),
        "compile_seconds": float(r.history.compile_seconds),
        "wall_seconds": wall,
        "peak_rss_mb": resource.getrusage(
            resource.RUSAGE_SELF
        ).ru_maxrss / 1024.0,
        "final_gap": float(r.history.objective[-1]),
    }


def bench_scale():
    jobs = []
    for n in SCALE_N:
        jobs.append((n, "neighbor"))
        if n < DENSE_SKIP_N:
            jobs.append((n, "dense"))
    cells = []
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    for job in jobs:  # sequential: no co-tenant interference between cells
        with futures.ProcessPoolExecutor(1, mp_context=ctx) as pool:
            cell = pool.submit(_scale_cell, job).result()
        cells.append(cell)
        print(f"[scale] N={cell['n_workers']} impl={cell['topology_impl']}: "
              f"{cell['iters_per_second']:.0f} iters/s, "
              f"{cell['peak_rss_mb']:.0f} MB peak")
    by_key = {(c["n_workers"], c["topology_impl"]): c for c in cells}
    for n in SCALE_N:
        nb = by_key.get((n, "neighbor"))
        dn = by_key.get((n, "dense"))
        if nb and dn:
            # Honest per-cell flag, same convention as robust_scale.json.
            nb["matrix_free_loses"] = (
                nb["iters_per_second"] < dn["iters_per_second"]
            )
            nb["speedup_vs_dense"] = (
                nb["iters_per_second"] / dn["iters_per_second"]
            )
    big = by_key[(DENSE_SKIP_N, "neighbor")]
    assert big["final_gap"] == big["final_gap"], "N=10k run produced NaN gap"
    assert big["iters_per_second"] > 0, big
    return {
        "cells": cells,
        "dense_skipped_at": {
            "n_workers": DENSE_SKIP_N,
            "reason": (
                "dense adjacency+mixing at N=10k is ~1.6 GB float64 before "
                "one iteration runs (plus O(N^2 d) per-round work) — the "
                "axis cap the matrix-free path removes; skipped by "
                "arithmetic, not measured"
            ),
        },
        "asserted": (
            f"the N={DENSE_SKIP_N} matrix-free cell completed with finite "
            "gap, recorded throughput and per-cell peak RSS"
        ),
    }


def main() -> None:
    from distributed_optimization_tpu.telemetry import write_bench_manifest
    from distributed_optimization_tpu.utils.profiling import PhaseTimer

    import jax

    timer = PhaseTimer()
    with timer.phase("local_steps"):
        local_steps, cfg0 = bench_local_steps()
    with timer.phase("participation"):
        participation, _ = bench_participation()
    with timer.phase("scale"):
        scale = bench_scale()

    payload = {
        "device": jax.devices()[0].device_kind,
        "platform": jax.devices()[0].platform,
        "protocol": {
            "eps": EPS,
            "local_steps": (
                "tau in {1,2,4,8} local SGD steps per gossip round (dsgd, "
                "N=32 ring, shuffled partition), per-round comms constant; "
                "floats-to-eps = rounds-to-eps x floats/round; >=2x "
                "reduction for some tau>1 at a matched final-gap envelope "
                "is asserted"
            ),
            "participation": (
                "client sampling at rates {1.0,0.5,0.25}, fixed horizon; "
                "convergence curves recorded, monotone degradation and "
                "the q^2*sum(deg)*d floats/round cost model asserted"
            ),
            "scale": (
                "throughput + per-cell-subprocess peak RSS for the "
                "neighbor-table (matrix-free) path vs dense at N in "
                "{1024, 4096, 10000} (sparse Erdős–Rényi, mean degree "
                "~12, T=100 — the irregular-graph case where dense is an "
                "[N,N] matmul per round and gather the only matrix-free "
                "mixing); dense at N=10k is skipped by arithmetic with "
                "the reason recorded"
            ),
        },
        "local_steps": local_steps,
        "participation": participation,
        "scale": scale,
        "gates": {
            "floats_to_eps_reduction_floor": 2.0,
            "best_floats_to_eps_reduction": local_steps[
                "best_floats_reduction"
            ],
            "participation_rates_measured": len(participation["cells"]),
            "max_n_completed_matrix_free": max(
                c["n_workers"] for c in scale["cells"]
                if c["topology_impl"] == "neighbor"
            ),
        },
        "note": (
            "CPU-container numbers: absolute iters/sec is not chip "
            "evidence; the load-bearing content is the within-artifact "
            "ratios (tau reductions, rate curves, dense-vs-neighbor "
            "flags) and the N=10k completion itself. tau=1 / "
            "participation=1.0 bitwise-reduction guarantees live in "
            "tests/test_federated.py, not here."
        ),
    }
    OUT.parent.mkdir(parents=True, exist_ok=True)
    OUT.write_text(json.dumps(payload, indent=1, sort_keys=True) + "\n")
    print(f"wrote {OUT}")
    write_bench_manifest(OUT, config=cfg0, phases=timer)


if __name__ == "__main__":
    main()
