"""Event-driven asynchronous gossip evidence (ISSUE 9 headline artifact;
docs/ASYNC.md).

Bulk-synchronous gossip pays the BARRIER: every round costs the MAX of N
per-worker compute-time draws, which under heavy-tailed latency grows like
the distribution's extreme value while the mean stays put. The
scan-over-events path (AD-PSGD-style, ``parallel/events.py`` +
``backends/async_scan.py``) removes the barrier — each worker fires at its
own pace, pairings ride on the initiator's clock — so progress is paced by
MEAN latency. This bench pins that trade on a shared latency realization:

- LATENCY SWEEP: D-SGD, ring N=32, T=2000 rounds, sync one-peer vs async
  under matched-MEAN latency models (constant / exponential / lognormal
  sigma=1.25 / pareto alpha=1.3). Sync and async are priced on the SAME
  per-(round, worker) duration draws (``sync_round_times``), so the
  wall-clock-to-ε ratio isolates the barrier. Asserted: simulated
  wall-clock-to-ε speedup >= 2x (exponential) and >= 3x (lognormal, the
  headline heavy-tail cell) at a matched final-gap envelope; the pareto
  extreme-tail cell must also clear 3x but its final-gap envelope is
  recorded honestly (very stale laggards drag the mean model; the
  ``async_loses`` flags say exactly where).
- DEGENERATE SYNC-REDUCTION GATE: at constant latency the event schedule
  realizes x' = 0.5(I + P_t)x − η_t G(x) on the IDENTICAL matching draws
  the synchronous one-peer path samples. Asserted: equal virtual clocks
  (zero straggler tax, speedup exactly 1), matched final gap, and — on a
  shared injected batch schedule, f64 — trajectory agreement <= 1e-12
  with realized comms EXACTLY equal.
- ORACLE PARITY: jax vs numpy per-event twins on one injected schedule,
  f64, asserted <= 1e-12.

Writes ``docs/perf/async.json`` (per-cell trajectories, virtual clocks,
staleness histograms, clock skew, iters/wall-clock-to-ε, speedups, all
gate outcomes and honest per-cell flags).

Usage:  python examples/bench_async.py [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/perf/async.json")
    args = ap.parse_args()

    import jax
    import numpy as np

    from distributed_optimization_tpu.backends import (
        jax_backend,
        numpy_backend,
    )
    from distributed_optimization_tpu.backends.async_scan import timeline_for
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.parallel.events import (
        clock_skew,
        staleness_histogram,
        sync_round_times,
    )
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import (
        compute_reference_optimum,
    )

    base = ExperimentConfig(
        problem_type="quadratic", algorithm="dsgd", topology="ring",
        n_workers=32, n_samples=1600, n_features=10,
        n_informative_features=6, n_iterations=2000, local_batch_size=16,
        eval_every=50,
    )
    N, T, EVERY = base.n_workers, base.n_iterations, base.eval_every
    # (model, tail knob, asserted wall-clock-to-ε floor, asserted
    # final-gap envelope — None = recorded honestly, flagged, not gated).
    CELLS = [
        ("constant", 0.0, None, 1.25),
        ("exponential", 0.0, 2.0, 1.3),
        ("lognormal", 1.25, 3.0, 2.0),   # the headline heavy-tail cell
        ("pareto", 1.3, 3.0, None),      # extreme tail: envelope flagged
    ]

    ds = generate_synthetic_dataset(base)
    _, f_opt = compute_reference_optimum(ds, base.reg_param)

    # --- synchronous baselines (latency-independent trajectories) --------
    # One-peer matching is the comms-matched baseline (the async schedule
    # realizes the SAME matchings); full synchronous gossip rides along as
    # the classical reference row. Virtual clocks attach per latency cell.
    sync_peer = jax_backend.run(
        base.replace(gossip_schedule="one_peer"), ds, f_opt
    )
    sync_full = jax_backend.run(base, ds, f_opt)
    gaps_sync = sync_peer.history.objective

    results: dict[str, dict] = {
        "sync_one_peer": {
            "final_gap": round(float(gaps_sync[-1]), 6),
            "objective": [round(float(v), 6) for v in gaps_sync],
            "realized_floats": float(
                sync_peer.history.total_floats_transmitted
            ),
        },
        "sync_full_gossip": {
            "final_gap": round(float(sync_full.history.objective[-1]), 6),
            "objective": [
                round(float(v), 6) for v in sync_full.history.objective
            ],
            "realized_floats": float(
                sync_full.history.total_floats_transmitted
            ),
        },
    }
    gates: dict[str, object] = {}
    all_floors_hold = True

    def first_crossing(gaps, clocks, eps):
        hit = np.nonzero(np.asarray(gaps) <= eps)[0]
        return float(clocks[hit[0]]) if hit.size else None

    for model, tail, floor, envelope in CELLS:
        cfg = base.replace(
            execution="async", latency_model=model, latency_tail=tail,
        )
        r = jax_backend.run(cfg, ds, f_opt)
        gaps_async = r.history.objective
        _, tl = timeline_for(cfg)
        # Virtual clocks at the shared eval cadence: async from the event
        # schedule, sync from the barrier (max-of-N) on the SAME draws.
        vt_async = tl.t_virtual[EVERY * N - 1:: EVERY * N]
        vt_sync = sync_round_times(tl)[EVERY - 1:: EVERY]
        # Matched-ε: the loosest of the two finals with 30% headroom, so
        # both runs cross it and the comparison is a crossing-time
        # statement, not an extrapolation.
        eps = 1.3 * max(float(gaps_async[-1]), float(gaps_sync[-1]))
        t_async = first_crossing(gaps_async, vt_async, eps)
        t_sync = first_crossing(gaps_sync, vt_sync, eps)
        it_async = first_crossing(gaps_async, np.arange(EVERY, T + 1, EVERY), eps)
        it_sync = first_crossing(gaps_sync, np.arange(EVERY, T + 1, EVERY), eps)
        speedup = t_sync / t_async if t_async and t_sync else None
        gap_ratio = float(gaps_async[-1]) / float(gaps_sync[-1])
        row = {
            "latency_model": model,
            "latency_tail": tail,
            "final_gap": round(float(gaps_async[-1]), 6),
            "final_gap_ratio_vs_sync_one_peer": round(gap_ratio, 4),
            "objective": [round(float(v), 6) for v in gaps_async],
            "virtual_time": [round(float(v), 3) for v in vt_async],
            "sync_virtual_time": [round(float(v), 3) for v in vt_sync],
            "eps": round(eps, 6),
            "wall_clock_to_eps": {"async": t_async, "sync": t_sync},
            "iters_to_eps": {"async": it_async, "sync": it_sync},
            "wall_clock_speedup": (
                round(speedup, 3) if speedup is not None else None
            ),
            "realized_floats": float(r.history.total_floats_transmitted),
            "staleness": staleness_histogram(tl),
            "virtual_clock_skew": clock_skew(tl),
            # Honest per-cell flags: where async does NOT win.
            "async_loses": {
                "wall_clock": bool(speedup is not None and speedup < 1.0),
                "iters_to_eps": bool(
                    it_async is not None and it_sync is not None
                    and it_async > it_sync
                ),
                "final_gap_envelope": bool(
                    gap_ratio > (envelope if envelope is not None else 2.0)
                ),
            },
        }
        results[f"async_{model}"] = row
        print(
            f"[async] {model:12s} final {row['final_gap']:>10.3f} "
            f"(x{gap_ratio:.2f} sync)  vt->eps {t_async!s:>8}/{t_sync!s:>8}"
            f"  speedup {row['wall_clock_speedup']}",
            file=sys.stderr,
        )
        if floor is not None:
            ok = speedup is not None and speedup >= floor
            all_floors_hold &= ok
            assert ok, (
                f"{model}: wall-clock-to-eps speedup "
                f"{speedup} under the {floor}x floor — the barrier tax "
                "should dominate at this tail"
            )
        if envelope is not None:
            assert gap_ratio <= envelope, (
                f"{model}: async final gap {gap_ratio:.2f}x sync exceeds "
                f"the {envelope}x matched-gap envelope"
            )

    # --- degenerate sync-reduction gate ----------------------------------
    const = results["async_constant"]
    assert const["virtual_time"] == const["sync_virtual_time"], (
        "constant latency must realize the synchronous clock exactly "
        "(zero straggler tax)"
    )
    assert const["wall_clock_speedup"] == 1.0, const["wall_clock_speedup"]
    assert const["virtual_clock_skew"]["rel_spread"] == 0.0
    # Same matchings ⇒ same realized comms, exactly.
    assert (
        const["realized_floats"] == results["sync_one_peer"]["realized_floats"]
    ), "constant-latency async must move exactly the one-peer floats"

    # Exact trajectory equivalence on shared injected batches (f64): the
    # event sweep at constant latency IS the synchronous one-peer round on
    # the identical matching draws; only XLA program shape differs.
    eq_cfg = base.replace(
        n_workers=16, n_iterations=200, eval_every=50, n_samples=800,
        dtype="float64",
    )
    eq_ds = generate_synthetic_dataset(eq_cfg)
    _, eq_f = compute_reference_optimum(eq_ds, eq_cfg.reg_param)
    rng = np.random.default_rng(0)
    sizes = [eq_ds.shard(i)[0].shape[0] for i in range(eq_cfg.n_workers)]
    sync_sched = np.stack([
        np.stack([
            rng.integers(0, sizes[i], size=eq_cfg.local_batch_size)
            for i in range(eq_cfg.n_workers)
        ])
        for _ in range(eq_cfg.n_iterations)
    ])
    a_cfg = eq_cfg.replace(execution="async")
    _, eq_tl = timeline_for(a_cfg)
    async_sched = sync_sched[eq_tl.local_step, eq_tl.worker]
    r_a = jax_backend.run(a_cfg, eq_ds, eq_f, batch_schedule=async_sched)
    r_s = jax_backend.run(
        eq_cfg.replace(gossip_schedule="one_peer"), eq_ds, eq_f,
        batch_schedule=sync_sched,
    )
    degenerate_dev = float(np.max(np.abs(r_a.final_models - r_s.final_models)))
    assert degenerate_dev < 1e-12, degenerate_dev
    assert (
        r_a.history.total_floats_transmitted
        == r_s.history.total_floats_transmitted
    )

    # --- jax-vs-numpy per-event oracle parity -----------------------------
    r_n = numpy_backend.run(a_cfg, eq_ds, eq_f, batch_schedule=async_sched)
    parity_dev = float(np.max(np.abs(r_a.final_models - r_n.final_models)))
    assert parity_dev < 1e-12, parity_dev

    gates.update({
        "wall_clock_speedup_floors": {
            m: f for m, _, f, _ in CELLS if f is not None
        },
        "final_gap_envelopes": {
            m: e for m, _, _, e in CELLS if e is not None
        },
        "all_speedup_floors_hold": bool(all_floors_hold),
        "degenerate_constant_equals_sync_one_peer": {
            "zero_straggler_tax": True,
            "realized_floats_equal": True,
            "shared_batch_trajectory_max_dev_f64": degenerate_dev,
        },
        "jax_vs_numpy_per_event_parity_max_dev_f64": parity_dev,
    })

    payload = {
        "device": str(jax.devices()[0]),
        "config": (
            f"quadratic N={N} ring T={T}; matched-mean latency sweep "
            "(constant / exponential / lognormal s=1.25 / pareto a=1.3), "
            "sync one-peer + full-gossip baselines priced on the SAME "
            "duration draws via sync_round_times; degenerate gate at "
            "N=16 T=200 f64 with shared injected batches"
        ),
        "note": (
            "Wall-clock is the SIMULATED virtual clock of the shared "
            "latency realization: a synchronous round costs the max of N "
            "draws (the barrier), an asynchronous worker is paced by its "
            "own draws. Matched-mean models make the comparison a pure "
            "barrier statement. Async pairings are the one-peer matching "
            "draws themselves (initiator = pair min), so per-round comms "
            "is identical to sync one-peer; at constant latency the "
            "schedules coincide exactly (asserted <= 1e-12 on shared "
            "batches). Heavy tails buy wall-clock at some final-gap cost "
            "(staleness + clock skew drag laggards' rows) — recorded "
            "honestly per cell in async_loses; the pareto extreme-tail "
            "cell exceeds the 2x gap envelope and says so rather than "
            "hiding it."
        ),
        "gates": gates,
        "runs": results,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(path, config=base)

    print(json.dumps({
        "metric": "async_wall_clock_speedup_lognormal",
        "value": results["async_lognormal"]["wall_clock_speedup"],
    }))


if __name__ == "__main__":
    main()
