"""Serving-layer traffic bench (the ISSUE-7 tentpole evidence).

Replays a synthetic mixed workload — repeat configs, sweep variants,
structural outliers — through ``serving.SimulationService`` and measures
the two amortizations the subsystem exists for:

1. **Executable-cache latency** (``latency`` cell): submit→start latency
   of a request whose structural class is already compiled (cache hit:
   queue wait + executable lookup) vs a cold structural class (queue wait
   + the whole-run XLA compile, docs/PERF.md §3). Submit→done wall times
   are recorded alongside, ungated — they include the run itself, which
   serving cannot amortize.
2. **Coalescing throughput** (``throughput`` cell): R eta0-variant
   requests submitted together (one ``run_batch`` cohort, one program
   execution) vs the same R requests submitted one-at-a-time (R warm
   program executions), both through the service with warm caches — the
   pure coalescing gain, the serving twin of docs/perf/sweep.json's
   replica-batching measurement.

Asserted floors (bench.py convention, BENCH_NO_RANGE_CHECK escape):

- warm cache-hit submit→start must be ≥ 10× lower than cold-compile
  submit→start (hardware-independent: a dict lookup vs a multi-second
  XLA compile);
- coalesced requests/sec at cohort R ≥ 8 must be ≥ 2.5× one-at-a-time on
  this CPU container (the SIMD-fill floor bench_sweep measured for the
  replica axis; accelerator platforms inherit the sweep bench's ≥ 8×
  expectation), with an honest ``coalescing_loses`` flag either way.

The served-vs-standalone parity gate (bitwise/≤ 1e-12) runs in tier-1
(tests/test_serving.py); this bench re-checks it on a small f64 cohort
and records the realized max deviation.

Writes ``docs/perf/serving.json`` (+ manifest sidecar).

Usage:  python examples/bench_serving.py [--out PATH] [--cohort 16]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

FLOOR_WARM_VS_COLD = 10.0   # submit->start, cache hit vs cold compile
FLOOR_COALESCED_CPU = 2.5   # requests/sec, cohort R>=8 vs one-at-a-time
PARITY_TOL = 1e-12          # served vs standalone, f64


def _mk_service(window_s=0.0, max_cohort=32):
    from distributed_optimization_tpu.serving.cache import ExecutableCache
    from distributed_optimization_tpu.serving.service import (
        ServingOptions,
        SimulationService,
    )

    return SimulationService(
        ServingOptions(window_s=window_s, max_cohort=max_cohort),
        cache=ExecutableCache(),
    )


def _submit_and_drain(svc, configs):
    ids = [svc.submit(c) for c in configs]
    svc.drain()
    return [svc.result(i, timeout=600) for i in ids]


def _start_latency(req) -> float:
    """Submit→start: queue wait plus program acquisition (the compile on a
    miss, the cache lookup on a hit). The run itself is excluded — serving
    amortizes compiles, not gradient math."""
    return float(req.queue_wait_s) + float(req.result.history.compile_seconds)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/perf/serving.json")
    ap.add_argument("--cohort", type=int, default=16,
                    help="coalesced-throughput cohort size (>= 8)")
    args = ap.parse_args()
    if args.cohort < 8:
        raise SystemExit("--cohort must be >= 8 (the gated regime)")

    import jax

    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.utils.profiling import PhaseTimer

    dev = jax.devices()[0]
    platform = dev.platform
    print(f"[serving] device={dev} platform={platform}", file=sys.stderr)
    timer = PhaseTimer()

    # The flagship decentralized shape (reference main.py defaults) at a
    # bench-scale horizon — the same cell family bench_sweep measures, so
    # the coalescing numbers compose with the replica-batching numbers.
    base = ExperimentConfig(
        problem_type="logistic", algorithm="dsgd", topology="ring",
        n_iterations=500, eval_every=100,
    )

    # ---- 1. latency: cold compile vs warm cache hit -------------------
    with timer.phase("latency"):
        svc = _mk_service()
        cold = _submit_and_drain(svc, [base])[0]
        warm = _submit_and_drain(svc, [base])[0]
        # A sweep VARIANT of the warm class also hits (the structural-hash
        # contract) — recorded to show reuse is class-wide, not repeat-only.
        variant = _submit_and_drain(
            svc, [base.replace(learning_rate_eta0=0.11)]
        )[0]
    assert cold.cache_hit is False and warm.cache_hit is True
    assert variant.cache_hit is True
    cold_start = _start_latency(cold)
    warm_start = _start_latency(warm)
    latency = {
        "cold_submit_to_start_s": round(cold_start, 4),
        "warm_hit_submit_to_start_s": round(warm_start, 4),
        "variant_hit_submit_to_start_s": round(_start_latency(variant), 4),
        "cold_submit_to_done_s": round(
            cold.queue_wait_s + cold.run_wall_s, 4
        ),
        "warm_submit_to_done_s": round(
            warm.queue_wait_s + warm.run_wall_s, 4
        ),
        "cold_compile_s": round(cold.result.history.compile_seconds, 4),
        "speedup_submit_to_start": round(cold_start / warm_start, 1),
    }
    print(
        f"[serving] latency: cold start {cold_start:.3f}s vs warm "
        f"{warm_start * 1e3:.1f}ms ({latency['speedup_submit_to_start']}x)",
        file=sys.stderr,
    )

    # ---- 2. throughput: coalesced cohort vs one-at-a-time -------------
    R = args.cohort
    etas = [0.02 + 0.01 * i for i in range(R)]
    variants = [base.replace(learning_rate_eta0=e) for e in etas]
    with timer.phase("throughput"):
        svc = _mk_service()
        # Warm both program shapes out of the measured window: the R=1
        # program (one-at-a-time path) and the R-cohort program.
        _submit_and_drain(svc, [base])
        _submit_and_drain(svc, variants)

        t0 = time.perf_counter()
        for cfg in variants:
            _submit_and_drain(svc, [cfg])  # submit, wait, submit, ...
        seq_wall = time.perf_counter() - t0

        t0 = time.perf_counter()
        reqs = _submit_and_drain(svc, variants)  # one coalesced cut
        coal_wall = time.perf_counter() - t0
    assert all(r.cohort_size == R for r in reqs), "cohort did not coalesce"
    assert all(r.cache_hit for r in reqs), "throughput cells must be warm"
    seq_rps = R / seq_wall
    coal_rps = R / coal_wall
    throughput = {
        "cohort_R": R,
        "sequential_requests_per_s": round(seq_rps, 2),
        "coalesced_requests_per_s": round(coal_rps, 2),
        "sequential_wall_s": round(seq_wall, 2),
        "coalesced_wall_s": round(coal_wall, 2),
        "speedup": round(coal_rps / seq_rps, 2),
        "coalescing_loses": coal_rps < seq_rps,
    }
    print(
        f"[serving] throughput R={R}: {coal_rps:.2f} coalesced vs "
        f"{seq_rps:.2f} sequential req/s ({throughput['speedup']}x)",
        file=sys.stderr,
    )

    # ---- 3. mixed-workload replay (stats snapshot, ungated) -----------
    with timer.phase("workload"):
        svc = _mk_service()
        stream = (
            [base] * 4                                         # repeats
            + [base.replace(learning_rate_eta0=e)
               for e in (0.03, 0.07, 0.09, 0.13)]              # sweep variants
            + [base.replace(seed=base.seed + i) for i in (1, 2)]  # seed variants
            + [base.replace(topology="fully_connected"),
               base.replace(eval_every=50)]                    # outliers
        )
        t0 = time.perf_counter()
        _submit_and_drain(svc, stream)
        stream_wall = time.perf_counter() - t0
        st = svc.stats()
    workload = {
        "requests": len(stream),
        "wall_s": round(stream_wall, 2),
        "requests_per_s": round(len(stream) / stream_wall, 2),
        "cohorts": st["cohorts"],
        "cache": {
            k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in st["cache"].items()
        },
        "composition": "4 repeats + 4 eta0 variants + 2 seed variants "
                       "+ 2 structural outliers",
    }
    print(
        f"[serving] workload: {len(stream)} requests, "
        f"{st['cache']['misses']} compiles, "
        f"{st['cache']['hits']} cache hits, "
        f"{st['cohorts']['count']} cohorts",
        file=sys.stderr,
    )

    # ---- 4. parity re-check (f64; the tier-1 gate's convention) -------
    from distributed_optimization_tpu.backends import jax_backend

    with timer.phase("parity"):
        svc = _mk_service()
        pcfg = base.replace(
            dtype="float64", n_iterations=200, eval_every=50,
        )
        pvariants = [pcfg.replace(learning_rate_eta0=e)
                     for e in (0.05, 0.09, 0.05)]
        preqs = _submit_and_drain(svc, pvariants)
        ds, f_opt = svc._dataset_for(pcfg)
        max_dev = 0.0
        for req in preqs:
            seq = jax_backend.run(
                req.config, ds, f_opt, executable_cache=False
            )
            max_dev = max(
                max_dev,
                float(np.max(np.abs(
                    req.result.history.objective - seq.history.objective
                ))),
                float(np.max(np.abs(
                    req.result.final_models - seq.final_models
                ))),
            )
    assert preqs[0].cohort_size == len(pvariants)
    assert max_dev <= PARITY_TOL, (
        f"served-vs-standalone deviation {max_dev} exceeds {PARITY_TOL}"
    )
    parity = {
        "cohort_R": len(pvariants),
        "max_abs_deviation_f64": max_dev,
        "tol": PARITY_TOL,
        "tier1_gate": "tests/test_serving.py::"
                      "test_served_cohort_matches_standalone_run",
    }
    print(f"[serving] parity: max dev {max_dev:.2e} (f64)", file=sys.stderr)

    # ---- asserted floors (BENCH_NO_RANGE_CHECK escape hatch) ----------
    skip = os.environ.get("BENCH_NO_RANGE_CHECK", "").lower() not in (
        "", "0", "false"
    )
    ratio = cold_start / warm_start
    if skip:
        print(
            "[serving] BENCH_NO_RANGE_CHECK set: skipping the floor gates "
            "(non-canonical hardware mode)",
            file=sys.stderr,
        )
    else:
        assert ratio >= FLOOR_WARM_VS_COLD, (
            f"warm cache-hit submit->start is only {ratio:.1f}x below "
            f"cold compile (floor {FLOOR_WARM_VS_COLD}x) — the executable "
            "cache is not amortizing the compile; investigate before "
            "publishing"
        )
        assert throughput["speedup"] >= FLOOR_COALESCED_CPU, (
            f"coalesced throughput {throughput['speedup']}x is below the "
            f"{FLOOR_COALESCED_CPU}x floor at R={R} — request coalescing "
            "is not paying for itself; investigate before publishing"
        )
    gates = {
        "warm_vs_cold_submit_to_start_floor": FLOOR_WARM_VS_COLD,
        "coalesced_throughput_floor_cpu_r8plus": FLOOR_COALESCED_CPU,
        "applied": not skip,
        "measured_warm_vs_cold": round(ratio, 1),
        "measured_coalesced_speedup": throughput["speedup"],
        "parity_max_abs_deviation_f64": max_dev,
    }

    payload = {
        "device": str(dev),
        "platform": platform,
        "protocol": (
            "SimulationService over the flagship N=25 ring logistic cell "
            "(T=500). latency: submit->start = queue wait + program "
            "acquisition (cold = XLA compile, warm = executable-cache "
            "lookup; the run itself is excluded and reported separately "
            "as submit->done). throughput: R eta0-variant requests as one "
            "coalesced run_batch cohort vs the same R submitted "
            "one-at-a-time, both warm (pure coalescing gain; the replica "
            "axis's SIMD-fill regime measured in docs/perf/sweep.json). "
            "workload: a mixed stream (repeats/sweeps/seed variants/"
            "structural outliers) with the service's own cohort+cache "
            "counters. parity: served-vs-standalone max |dev| in f64, "
            "asserted <= 1e-12 here and gated in tier-1."
        ),
        "note": (
            "Floors are regime-honest: the 10x latency floor is hardware-"
            "independent (dict lookup vs multi-second compile); the 2.5x "
            "throughput floor is this single-core CPU container's "
            "SIMD-fill regime (bench_sweep's measured 3.5-4.6x at R=32 "
            "bounds what coalescing can recover here) — on accelerators "
            "the replica axis's >= 8x regime applies and coalescing "
            "inherits it. coalescing_loses flags any measured inversion."
        ),
        "workload": workload,
        "latency": latency,
        "throughput": throughput,
        "parity": parity,
        "gates": gates,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(path, config=base, phases=timer)

    print(json.dumps({
        "metric": "serving_warm_vs_cold_and_coalesced_speedup",
        "warm_vs_cold": gates["measured_warm_vs_cold"],
        "coalesced_speedup": gates["measured_coalesced_speedup"],
    }))


if __name__ == "__main__":
    main()
