"""Replica-batched sweep throughput (the PR-4 tentpole evidence).

Every seed replicate, suite row, and bench variant used to execute as its
own sequential compiled scan (``bench_byzantine.py``, ``bench_churn.py``,
``simulator.run_suite``): R replicates cost R compiles + R program
dispatches + R runs. ``jax_backend.run_batch`` vmaps the whole run over a
leading [R] replica axis — one compile, one program, [R, N, d] state —
so a sweep's aggregate iters/sec is bounded by how much idle capacity the
single run leaves, not by R.

Two cells, measured end to end through real backend runs:

1. **flagship_n25** — the reference study's flagship decentralized config
   (logistic, N=25, ring): per-R table for R ∈ {1, 2, 4, 8, 16, 32},
   batched aggregate vs the sequential single-run baseline, both as
   steady-state (compile excluded) and end-to-end (compile included —
   what a sequential sweep actually pays, since each ``run()`` call
   re-traces and re-compiles; see bench.py's protocol notes).
2. **northstar_n256** — the BASELINE.json north-star shape (N=256 ring):
   the heavier per-replica cell, where batching's gain is SMALLER on a
   compute-bound host (less idle capacity to fill) — the honest
   crossover direction, flagged per row via ``batching_loses``.

Plus an eta0-sweep demo row (the hyperparameter axis riding the same
batched program).

Asserted floors (same convention as bench.py's published-range gate,
BENCH_NO_RANGE_CHECK escape hatch included):

- **accelerator platforms** (the canonical latency/dispatch-bound regime
  this tentpole targets — BENCH_r05 measured the [256, 81] hot loop at
  ~103k iters/sec with the vector lanes mostly idle): aggregate at R=32
  must be ≥ 8× the sequential single-run baseline.
- **CPU hosts** (this container: single core, every config compute-bound
  — SIMD lane-filling is the only headroom, measured ~3.5–4.6×):
  aggregate at R=32 must be ≥ 2.5× steady-state. The 8× claim is an
  accelerator-regime claim; asserting it on a 1-core host would gate on
  hardware this machine does not have, and writing 8× into the artifact
  without measuring it would be exactly the silent-docs-drift failure
  bench.py exists to kill. The artifact records which floor applied.

Writes ``docs/perf/sweep.json``.

Usage:  python examples/bench_sweep.py [--out PATH] [--seq-cycles 3]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

R_TABLE = (1, 2, 4, 8, 16, 32)
FLOOR_ACCELERATOR = 8.0   # aggregate/single at R=32, e2e or steady
FLOOR_CPU_STEADY = 2.5    # measured-here SIMD-fill floor at R=32


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-cycles", type=int, default=3,
                    help="sequential-baseline repetitions (median)")
    ap.add_argument("--out", default="docs/perf/sweep.json")
    args = ap.parse_args()

    import jax

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )

    dev = jax.devices()[0]
    platform = dev.platform
    print(f"[sweep] device={dev} platform={platform}", file=sys.stderr)

    cells_cfg = {
        # The reference study's flagship decentralized row (main.py
        # defaults: N=25 ring logistic d=80 b=16), shortened to a
        # bench-scale horizon.
        "flagship_n25": (
            ExperimentConfig(
                problem_type="logistic", algorithm="dsgd", topology="ring",
                n_iterations=2000, eval_every=500,
            ),
            R_TABLE,
        ),
        # The north-star scale shape ([256, 81] model stack) — the
        # heavier per-replica cell; two R points bound its scaling.
        "northstar_n256": (
            ExperimentConfig(
                problem_type="logistic", algorithm="dsgd", topology="ring",
                n_workers=256, n_iterations=400, eval_every=100,
            ),
            (8, 32),
        ),
    }

    cells = {}
    for name, (cfg, r_points) in cells_cfg.items():
        ds = generate_synthetic_dataset(cfg)
        T = cfg.n_iterations
        # Sequential baseline: median over fresh run() calls, each paying
        # its own trace + compile — what a sweep WITHOUT the serving
        # layer's executable cache pays per replicate. The process cache
        # (docs/SERVING.md) would now skip that re-compile for repeat
        # programs, so this baseline opts out explicitly to keep the
        # protocol's meaning; the cached regime is measured in
        # docs/perf/serving.json.
        seq_e2e, seq_steady = [], []
        for c in range(args.seq_cycles):
            t0 = time.perf_counter()
            r = jax_backend.run(
                cfg.replace(seed=cfg.seed + c), ds, 0.0,
                executable_cache=False,
            )
            seq_e2e.append(time.perf_counter() - t0)
            seq_steady.append(float(r.history.iters_per_second))
        single = {
            "steady_ips": round(statistics.median(seq_steady), 1),
            "e2e_ips": round(T / statistics.median(seq_e2e), 1),
            "e2e_wall_s": round(statistics.median(seq_e2e), 2),
        }
        rows = {}
        for R in r_points:
            t0 = time.perf_counter()
            batch = jax_backend.run_batch(
                cfg, ds, 0.0, seeds=[cfg.seed + i for i in range(R)]
            )
            wall = time.perf_counter() - t0
            assert np.all(np.isfinite(batch.objective)), (
                f"{name} R={R}: non-finite batched metrics"
            )
            agg_steady = batch.aggregate_iters_per_second
            agg_e2e = R * T / wall
            rows[str(R)] = {
                "aggregate_steady_ips": round(agg_steady, 1),
                "aggregate_e2e_ips": round(agg_e2e, 1),
                "compile_s": round(batch.compile_seconds, 2),
                "run_s": round(batch.run_seconds, 2),
                "speedup_steady": round(
                    agg_steady / single["steady_ips"], 2
                ),
                "speedup_e2e": round(agg_e2e / single["e2e_ips"], 2),
                # Honest crossover flag: a row where the batch delivers
                # LESS aggregate throughput than sequential runs would.
                "batching_loses": agg_steady < single["steady_ips"],
            }
            print(
                f"[sweep] {name} R={R}: agg {agg_steady:.0f} steady / "
                f"{agg_e2e:.0f} e2e ips "
                f"({rows[str(R)]['speedup_steady']}x / "
                f"{rows[str(R)]['speedup_e2e']}x)",
                file=sys.stderr,
            )
        cells[name] = {"single_run": single, "batched": rows}

    # --- hyperparameter axis demo: eta0 sweep through the same program --
    demo_cfg, _ = cells_cfg["flagship_n25"]
    demo_cfg = demo_cfg.replace(n_iterations=1000, eval_every=250)
    etas = [0.01, 0.02, 0.05, 0.08, 0.1, 0.15, 0.2, 0.3]
    demo_ds = generate_synthetic_dataset(demo_cfg)
    demo = jax_backend.run_batch(
        demo_cfg, demo_ds, 0.0, seeds=[demo_cfg.seed] * len(etas),
        sweep={"learning_rate_eta0": etas},
    )
    assert np.all(np.isfinite(demo.objective)), "eta-sweep NaNs"
    eta_demo = {
        "learning_rate_eta0": etas,
        "aggregate_steady_ips": round(demo.aggregate_iters_per_second, 1),
        "final_objective_per_replica": [
            round(float(v), 5) for v in demo.objective[:, -1]
        ],
    }
    print(
        f"[sweep] eta0 sweep x{len(etas)}: "
        f"{eta_demo['aggregate_steady_ips']:.0f} aggregate ips",
        file=sys.stderr,
    )

    # --- asserted floor (bench.py convention, incl. the escape hatch) ---
    head = cells["flagship_n25"]["batched"]["32"]
    best_32 = max(head["speedup_steady"], head["speedup_e2e"])
    on_accelerator = platform != "cpu"
    floor = FLOOR_ACCELERATOR if on_accelerator else FLOOR_CPU_STEADY
    skip = os.environ.get("BENCH_NO_RANGE_CHECK", "").lower() not in (
        "", "0", "false"
    )
    if skip:
        print(
            "[sweep] BENCH_NO_RANGE_CHECK set: skipping the speedup-floor "
            "gate (non-canonical hardware mode)",
            file=sys.stderr,
        )
    else:
        assert best_32 >= floor, (
            f"flagship R=32 aggregate speedup {best_32}x is below the "
            f"{'accelerator' if on_accelerator else 'cpu'} floor "
            f"({floor}x) — the replica axis is not paying for itself; "
            "investigate before publishing (docs/PERF.md sweep section)"
        )

    payload = {
        "device": str(dev),
        "platform": platform,
        "protocol": (
            "aggregate sweep throughput of run_batch (one vmapped "
            "compiled program, [R, N, d] state) vs the sequential "
            "single-run baseline, per R; steady = compile excluded, "
            f"e2e = compile included (each sequential run() re-traces "
            f"and re-compiles — bench.py's documented behavior); "
            f"sequential baseline = median of {args.seq_cycles} runs; "
            "metrics on (gap + consensus per eval cadence)"
        ),
        "note": (
            "The asserted floor is regime-dependent and recorded in "
            "'floors': >= 8x at R=32 on accelerator platforms (the "
            "latency/dispatch-bound regime the tentpole targets — the "
            "chip idles its vector lanes at the [256, 81] hot-loop "
            "shape, BENCH_r05), >= 2.5x steady on CPU hosts, where this "
            "container's single core makes every config compute-bound "
            "and SIMD lane-filling is the only headroom (measured "
            "3.5-4.6x at R=32; the northstar_n256 cell shows the "
            "heavier-compute direction at ~1.9-3.8x). batching_loses "
            "flags any row where the batch underperforms sequential."
        ),
        "floors": {
            "accelerator_speedup_at_r32": FLOOR_ACCELERATOR,
            "cpu_steady_speedup_at_r32": FLOOR_CPU_STEADY,
            "applied": None if skip else floor,
            "measured_best_speedup_at_r32": best_32,
        },
        "cells": cells,
        "eta_sweep_demo": eta_demo,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(path)

    print(json.dumps({
        "metric": "replica_batch_speedup_flagship_r32",
        "value": best_32,
    }))


if __name__ == "__main__":
    main()
