"""Compute-bound regime demonstration (round 5, VERDICT r4 item 1).

Every number the repo measured through round 4 lives in the latency-bound
d<=1024 scalar-GLM regime — ~1-7 MFLOP per iteration against a chip that
does ~10^5x that per millisecond, MFU <= 0.5%, MXU idle (docs/PERF.md §3).
This bench runs the tier TPUs are built for: the SOFTMAX family
(models/softmax.py), whose per-worker gradient is two real matmuls
(forward [b,d]x[d,K], backward [d,b]x[b,K]) — 4·N·b·d·K FLOPs per
iteration through the same D-SGD ring pipeline as the headline.

Reported per cell: steady-state iters/sec (fused scan, metrics off, AOT
compile excluded), achieved TFLOP/s from the analytic FLOP count, MFU
against the chip's bf16 peak, and the minimum HBM traffic (X re-read + 3x
weight traffic per iteration) as achieved GB/s. Cells interleave across
cycles (shared-chip protocol); the aggregate is the MEDIAN of cycles whose
reading is physically possible (achieved <= 95% of peak — the tunneled
runtime intermittently returns from the FIRST execution of a freshly
compiled large program in ~1 ms, implying thousands of times the chip's
peak; raw readings are recorded, impossible ones excluded). dtype/
precision cells re-judge the round-3 "bf16 no win" verdict — a
latency-bound statement — where FLOPs dominate.

FLOP accounting is the dominant matmul pair only (4NbdK); softmax/one-hot/
mixing/sampling are O(N·b·K + N·d·K) lower-order terms left out of the
numerator, so MFU is slightly UNDERstated — the conservative direction.

Peak numbers: TPU v5e (v5 lite) = 197 TFLOP/s bf16, 819 GB/s HBM
(public spec). Override with BENCH_PEAK_TFLOPS / BENCH_PEAK_GBPS for other
chips; f32 'highest' runs 6 bf16 passes per matmul (its effective ceiling
is peak/6 — reported MFU stays relative to the bf16 peak so cells share
one denominator).

Data is generated directly (random standardized X, uniform labels) rather
than through sklearn: throughput does not depend on learnability, and
make_classification at d=8192 costs minutes the measurement does not need.
Correctness/convergence of the family is pinned at small shapes in
tests/test_softmax.py.

Writes ``docs/perf/compute_bound.json``.

Usage:  python examples/bench_compute_bound.py [--out PATH] [--cycles 3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

PEAK_TFLOPS = float(os.environ.get("BENCH_PEAK_TFLOPS", "197"))
PEAK_GBPS = float(os.environ.get("BENCH_PEAK_GBPS", "819"))


def _random_dataset(n_workers: int, b: int, d_feat: int, n_classes: int):
    """HostDataset with random standardized features + uniform labels; each
    worker's shard is exactly its batch (full-batch local gradients)."""
    from distributed_optimization_tpu.utils.data import HostDataset

    rng = np.random.default_rng(0)
    n = n_workers * b
    X = rng.standard_normal((n, d_feat)).astype(np.float64)
    X = np.hstack([X, np.ones((n, 1))])
    y = rng.integers(0, n_classes, size=n).astype(np.float64)
    shard_indices = [np.arange(i * b, (i + 1) * b) for i in range(n_workers)]
    return HostDataset(X_full=X, y_full=y, shard_indices=shard_indices,
                       problem_type="softmax")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cycles", type=int, default=3)
    ap.add_argument("--iters", type=int, default=2000)
    ap.add_argument("--out", default="docs/perf/compute_bound.json")
    args = ap.parse_args()

    import jax

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.config import ExperimentConfig

    dev = jax.devices()[0]
    print(f"[compute_bound] device={dev} peak={PEAK_TFLOPS}TF/s "
          f"{PEAK_GBPS}GB/s", file=sys.stderr)

    # Published-floor pre-flight (round 6 — VERDICT r5 item 2: the 33-36%
    # MFU number had no protecting assert). The floor lives in the
    # COMMITTED artifact this bench regenerates, exactly like bench.py's
    # published_range_ips: read it before any chip work, enforce it after
    # measuring, and write it back into the new payload so the gate
    # survives regeneration. Loosening it is a committed, deliberate act.
    prev_path = Path(args.out)
    skip_gate = os.environ.get("BENCH_NO_RANGE_CHECK", "").lower() not in (
        "", "0", "false"
    )
    mfu_floor = None
    if prev_path.exists():
        prev = json.loads(prev_path.read_text())
        mfu_floor = prev.get("published_mfu_floor")
        if mfu_floor is None and not skip_gate:
            raise SystemExit(
                f"{prev_path} exists but carries no published_mfu_floor — "
                "the compute-bound tier must stay gated; add the floor "
                "(best bf16 cell's median MFU with honest margin) before "
                "regenerating, or set BENCH_NO_RANGE_CHECK=1 on "
                "non-canonical hardware"
            )
    if mfu_floor is None:
        # Bootstrap (fresh --out path or escape hatch): the regenerated
        # artifact will carry published_mfu_floor: null, i.e. an UNGATED
        # tier — say so loudly rather than disarming the gate silently.
        print(
            "[compute_bound] WARNING: no published_mfu_floor available — "
            "this run is ungated and the written artifact will carry "
            "published_mfu_floor: null; set a floor in the committed "
            "artifact to restore the regression gate",
            file=sys.stderr,
        )

    N, K, b = 8, 512, 2048
    T = args.iters
    # (label, d_feat, dtype, matmul_precision). 'highest' is the framework
    # default (parity-sensitive math: 6-pass bf16 ~ f32 accuracy); 'default'
    # is the 1-pass bf16-data-path XLA uses when precision is not forced.
    cells = [
        ("d4096_f32_highest", 4096, "float32", "highest"),
        ("d4096_f32_default", 4096, "float32", "default"),
        ("d4096_bf16", 4096, "bfloat16", "default"),
        ("d8192_f32_default", 8192, "float32", "default"),
        ("d8192_bf16", 8192, "bfloat16", "default"),
    ]

    runs: dict[str, list] = {label: [] for label, *_ in cells}
    setups = {}
    for label, d_feat, dtype, prec in cells:
        cfg = ExperimentConfig(
            problem_type="softmax", n_classes=K, algorithm="dsgd",
            topology="ring", n_workers=N, local_batch_size=b,
            n_samples=N * b, n_features=d_feat,
            n_informative_features=64, n_iterations=T, eval_every=T,
            dtype=dtype, matmul_precision=prec, record_consensus=False,
            # Pin the stencil (what auto resolves to on a ring): the cells
            # measure the gradient matmuls, and pinning keeps the mixing
            # term identical across cells by construction.
            mixing_impl="stencil",
            # At ~1 ms/iter the unroll's dispatch savings are irrelevant and
            # unrolled bodies multiply live [N, b, d] buffers; keep the scan
            # rolled so peak memory stays ~2 batches.
            scan_unroll=1,
        )
        ds = _random_dataset(N, b, d_feat, K)
        setups[label] = (cfg, ds, d_feat)

    for c in range(args.cycles):
        for label, (cfg, ds, d_feat) in setups.items():
            r = jax_backend.run(cfg, ds, 0.0, collect_metrics=False,
                                measure_compile=(c == 0))
            ips = float(r.history.iters_per_second)
            runs[label].append(ips)
            print(f"[compute_bound] cycle {c + 1}/{args.cycles} {label:20s} "
                  f"{ips:8.1f} iters/sec "
                  f"(compile {r.history.compile_seconds:.1f}s)",
                  file=sys.stderr)

    import statistics

    results = {}
    for label, (cfg, ds, d_feat) in setups.items():
        d = d_feat + 1  # bias column
        flops_per_iter = 4.0 * N * b * d * K
        # Median of physically-possible readings: nothing exceeds peak.
        cap_ips = 0.95 * PEAK_TFLOPS * 1e12 / flops_per_iter
        ok = [r for r in runs[label] if 0 < r <= cap_ips]
        ips = statistics.median(ok if ok else runs[label])
        bytes_el = 2 if cfg.dtype == "bfloat16" else 4
        # Minimum HBM traffic: X re-read twice (fwd+bwd) + W read twice /
        # written once per worker per iteration. Logits/softmax intermediates
        # assumed fused (XLA does); this is a LOWER bound on real traffic.
        bytes_per_iter = (2 * N * b * d + 3 * N * d * K) * bytes_el
        achieved_tf = flops_per_iter * ips / 1e12
        results[label] = {
            "d_model": d * K,
            "dtype": cfg.dtype,
            "matmul_precision": cfg.matmul_precision,
            "iters_per_sec_median_possible": round(ips, 1),
            "iters_per_sec_cycles_raw": [round(x, 1) for x in runs[label]],
            "readings_excluded_impossible": len(runs[label]) - len(ok),
            "gflops_per_iter": round(flops_per_iter / 1e9, 2),
            "achieved_tflops": round(achieved_tf, 1),
            "mfu_vs_bf16_peak": round(achieved_tf / PEAK_TFLOPS, 3),
            "min_hbm_gbps": round(bytes_per_iter * ips / 1e9, 1),
            "hbm_util_lower_bound": round(
                bytes_per_iter * ips / 1e9 / PEAK_GBPS, 3
            ),
        }
        row = results[label]
        print(f"[compute_bound] {label:20s} {row['achieved_tflops']:6.1f} "
              f"TF/s  MFU {row['mfu_vs_bf16_peak'] * 100:5.1f}%  HBM>= "
              f"{row['min_hbm_gbps']:5.0f} GB/s "
              f"({row['hbm_util_lower_bound'] * 100:.0f}%)", file=sys.stderr)

    # --- published-floor gate (the compute tier's bench-regression gate;
    # BENCH_NO_RANGE_CHECK = bench.py's non-canonical-hardware escape:
    # on another chip generation or a CPU container an out-of-floor MFU
    # means "different machine", not a regression) ---
    best_mfu = max(r["mfu_vs_bf16_peak"] for r in results.values())
    if skip_gate:
        print(
            "[compute_bound] BENCH_NO_RANGE_CHECK set: skipping the "
            "published MFU-floor gate (non-canonical hardware mode)",
            file=sys.stderr,
        )
    elif mfu_floor is not None and best_mfu < mfu_floor:
        raise SystemExit(
            f"best-cell MFU {best_mfu:.3f} is below the published floor "
            f"{mfu_floor} ({prev_path.name}) — the compute-bound tier "
            "regressed (or this is non-canonical hardware: set "
            "BENCH_NO_RANGE_CHECK=1). Re-derive the floor in a commit if "
            "the regression is real and explained."
        )
    elif mfu_floor is not None:
        print(
            f"[compute_bound] MFU gate OK: best cell {best_mfu:.3f} >= "
            f"published floor {mfu_floor}",
            file=sys.stderr,
        )

    payload = {
        "device": str(dev),
        "published_mfu_floor": mfu_floor,
        "peak_tflops_bf16": PEAK_TFLOPS,
        "peak_hbm_gbps": PEAK_GBPS,
        "workload": (
            f"softmax D-SGD ring N={N}, K={K}, b={b} (full local batch), "
            f"T={T}, fused scan, metrics off; FLOPs/iter = 4NbdK (dominant "
            "matmuls only, lower-order terms excluded => MFU conservative); "
            f"median of {args.cycles} interleaved cycles passing the "
            "physical cap (raw cycles recorded; first-execution "
            "bogus-fast readings excluded)"
        ),
        "cells": results,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(path)

    print(json.dumps({
        "metric": "compute_bound_median_mfu_best_cell",
        "value": max(r["mfu_vs_bf16_peak"] for r in results.values()),
    }))


if __name__ == "__main__":
    main()
