"""Reproduce the reference study's Tables I & II end to end.

Runs the exact experiment matrix of the reference report
(`Distributed_Optimization_Final_Report.pdf` §III; reference ``main.py``
defaults: N=25, T=10,000, b=16, eta_t=0.05/sqrt(t+1), lambda=1e-4, non-IID
sorted partition) for BOTH problems on the selected backend, and prints the
measured iterations-to-threshold / floats-transmitted table next to the
published values (BASELINE.md). Batch draws use different RNG streams than
the reference, so iteration counts match statistically (same curves, a few
tens of iterations of jitter), while float counts must match EXACTLY.

    python examples/reproduce_report.py             # full, TPU backend
    python examples/reproduce_report.py --quick     # T=1000 smoke version
    python examples/reproduce_report.py --backend numpy
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Published values: PDF Tables I/II via BASELINE.md.
PUBLISHED = {
    ("logistic", "Centralized SGD"): (9_641, 4.050e7),
    ("logistic", "D-SGD (ring)"): (9_927, 4.050e7),
    ("logistic", "D-SGD (grid)"): (9_636, 8.100e7),
    ("logistic", "D-SGD (fully connected)"): (9_596, 4.860e8),
    ("quadratic", "Centralized SGD"): (5_425, 4.050e7),
    ("quadratic", "D-SGD (ring)"): (7_214, 4.050e7),
    ("quadratic", "D-SGD (grid)"): (5_666, 8.100e7),
    ("quadratic", "D-SGD (fully connected)"): (5_549, 4.860e8),
}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default="jax", choices=("jax", "numpy", "cpp"))
    ap.add_argument("--quick", action="store_true",
                    help="T=1000 smoke run (threshold not reachable)")
    ap.add_argument("--plot-prefix", default=None,
                    help="save <prefix>_logistic.png / _quadratic.png")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write the measured-vs-published table as a JSON "
                         "artifact (docs/perf/report_reproduction.json is "
                         "the committed location)")
    args = ap.parse_args()

    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.simulator import Simulator

    T = 1_000 if args.quick else 10_000
    rows = []
    for problem in ("logistic", "quadratic"):
        cfg = ExperimentConfig(
            problem_type=problem, backend=args.backend, n_iterations=T
        )
        sim = Simulator(cfg)
        sim.run_all(verbose=True)
        for rec in sim.records:
            if rec.skipped_reason is not None:
                continue
            pub_iters, pub_floats = PUBLISHED[(problem, rec.label)]
            rows.append((
                problem, rec.label,
                rec.summary.iterations_to_threshold, pub_iters,
                rec.summary.total_transmission_floats, pub_floats,
                rec.summary.iters_per_second,
            ))
        if args.plot_prefix:
            sim.plot_results(path=f"{args.plot_prefix}_{problem}.png")

    print()
    hdr = (f"{'problem':<11}{'run':<26}{'iters→ε':>9}{'published':>11}"
           f"{'floats':>11}{'published':>11}{'iters/s':>10}")
    print(hdr)
    print("-" * len(hdr))
    floats_ok = True
    for problem, label, iters, pub_i, fl, pub_f, ips in rows:
        mark = "" if args.quick else ("  ✓" if fl == pub_f else "  ✗")
        floats_ok &= (fl == pub_f) or args.quick
        itxt = str(iters) if iters > 0 else "never"
        print(f"{problem:<11}{label:<26}{itxt:>9}{pub_i:>11}"
              f"{fl:>11.3e}{pub_f:>11.3e}{ips:>10.0f}{mark}")
    if args.json:
        import json

        payload = {
            "config": "reference main.py defaults: N=25, T=%d, b=16, "
                      "eta_t=0.05/sqrt(t+1), lambda=1e-4, non-IID sorted "
                      "partition; eps=0.08" % T,
            "backend": args.backend,
            "note": "batch RNG streams differ from the reference by design "
                    "(counter-based keys vs one global numpy stream, "
                    "SURVEY.md §3.4), so iteration counts match "
                    "statistically; float counts must match exactly",
            "rows": [
                {
                    "problem": problem,
                    "run": label,
                    "iterations_to_eps_measured": int(iters),
                    "iterations_to_eps_published": int(pub_i),
                    "deviation_pct": round(100.0 * (iters - pub_i) / pub_i, 2)
                    if iters > 0 else None,
                    "floats_transmitted_measured": fl,
                    "floats_transmitted_published": pub_f,
                    "floats_exact_match": fl == pub_f,
                    "iters_per_second": round(ips, 1),
                }
                for problem, label, iters, pub_i, fl, pub_f, ips in rows
            ],
        }
        with open(args.json, "w") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")
        print(f"[reproduce] wrote {args.json}", file=sys.stderr)
    if not args.quick and not floats_ok:
        print("FLOAT ACCOUNTING MISMATCH vs published tables", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
