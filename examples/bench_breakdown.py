"""Where the time goes at the N=256 headline config (VERDICT r1 item 5).

Three measurements on the real chip, one JSON artifact
(``docs/perf/breakdown.json``) + a summary table in ``docs/PERF.md``:

1. **Component attribution.** The headline step has three cost centers —
   per-worker minibatch gradients, the gossip mix, and the every-eval
   full-dataset objective. Measure throughput of the full config, then with
   metrics off (no full-dataset eval), then centralized (no gossip, same
   gradient work), then with eval_every=100 (eval amortized 100x). The deltas
   attribute steady-state time to each component without needing an XProf GUI
   (the raw trace is also captured to ``docs/perf/trace/`` when
   ``--trace`` is passed).

2. **eval_every sensitivity.** The reference evaluates the full-dataset
   objective EVERY iteration (reference ``trainer.py:67,189``) — parity mode
   k=1. Sweep k ∈ {1, 10, 100} + metrics-off to show what the parity
   constraint costs and what a production cadence buys.

3. **scan_unroll sweep.** ``config.scan_unroll`` defaults to 8 on
   accelerators; round 1 justified it with an unrecorded measurement. Sweep
   {1, 2, 4, 8, 16, 32} and record throughput + compile time so the default
   is evidence, not folklore.

Every row is best-of-2 of an identical workload (shared-tunnel chip noise).
Usage: ``python examples/bench_breakdown.py [--trace]``.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_optimization_tpu.backends import jax_backend
from distributed_optimization_tpu.config import ExperimentConfig
from distributed_optimization_tpu.utils.data import generate_synthetic_dataset
from distributed_optimization_tpu.utils.oracle import compute_reference_optimum

T = 10_000
BASE = dict(
    problem_type="logistic", algorithm="dsgd", topology="ring",
    n_workers=256, n_iterations=T,
)


def measure(cfg, ds, f_opt, repeats=2, **kw):
    # This bench's protocol records the PER-CALL compile cost (the
    # scan_unroll section quotes it), so it opts out of the process
    # executable cache — a repeat would otherwise hit the cache and
    # record 0.0s compile (docs/SERVING.md; the cached regime is measured
    # in docs/perf/serving.json).
    best = 0.0
    compile_s = 0.0
    for _ in range(repeats):
        res = jax_backend.run(cfg, ds, f_opt, executable_cache=False, **kw)
        best = max(best, float(res.history.iters_per_second))
        compile_s = float(res.history.compile_seconds)
    return best, compile_s


def measure_group(variants, ds, f_opt, cycles=3):
    """Round-robin measurement of several variants: every cycle runs each
    variant once, best-of-cycles per variant. Interleaving means co-tenant
    load swings hit all variants roughly equally, so the DELTAS between rows
    are meaningful — sequential best-of-2 per row was dominated by chip noise
    between rows.
    """
    best = {name: 0.0 for name in variants}
    for _ in range(cycles):
        for name, (cfg, kw) in variants.items():
            res = jax_backend.run(
                cfg, ds, f_opt, executable_cache=False, **kw
            )
            best[name] = max(best[name], float(res.history.iters_per_second))
    return best


def main() -> None:
    trace = "--trace" in sys.argv
    root = pathlib.Path(__file__).resolve().parents[1]
    out_dir = root / "docs" / "perf"
    out_dir.mkdir(parents=True, exist_ok=True)

    cfg = ExperimentConfig(**BASE)
    ds = generate_synthetic_dataset(cfg)
    _, f_opt = compute_reference_optimum(ds, cfg.reg_param)
    results: dict = {"config": "dsgd ring logistic N=256 T=10k", "device": str(
        jax_backend.jax.devices()[0])}

    # --- 1. component attribution (round-robin interleaved) ---
    cent = cfg.replace(algorithm="centralized", topology="fully_connected")
    rows = measure_group(
        {
            "full (parity k=1)": (cfg, {}),
            "metrics off (no full-data eval)": (
                cfg, {"collect_metrics": False}
            ),
            "centralized (no gossip)": (cent, {"collect_metrics": False}),
        },
        ds, f_opt,
    )
    results["attribution_iters_per_sec"] = {
        k: round(v, 1) for k, v in rows.items()
    }
    ips_full = rows["full (parity k=1)"]
    ips_noeval = rows["metrics off (no full-data eval)"]
    ips_nogossip = rows["centralized (no gossip)"]
    us = lambda ips: 1e6 / ips  # noqa: E731
    results["attribution_us_per_iter"] = {
        "total (k=1)": round(us(ips_full), 2),
        "full-data eval": round(us(ips_full) - us(ips_noeval), 2),
        "gossip (mix+consensus-free delta)": round(
            us(ips_noeval) - us(ips_nogossip), 2
        ),
        "gradients+step+dispatch": round(us(ips_nogossip), 2),
    }
    print(f"[breakdown] attribution: {results['attribution_us_per_iter']}",
          file=sys.stderr)

    # --- 2. eval_every sensitivity (round-robin interleaved) ---
    sweep_rows = measure_group(
        {str(k): (cfg.replace(eval_every=k), {}) for k in (1, 10, 100)},
        ds, f_opt,
    )
    sweep = {k: round(v, 1) for k, v in sweep_rows.items()}
    sweep["inf (metrics off)"] = round(ips_noeval, 1)
    results["eval_every_iters_per_sec"] = sweep
    print(f"[breakdown] eval_every: {sweep}", file=sys.stderr)

    # --- 3. scan_unroll sweep (at the parity cadence k=1, interleaved) ---
    compile_secs = {}
    unroll_cfgs = {}
    for u in (1, 2, 4, 8, 16, 32):
        ucfg = cfg.replace(scan_unroll=u)
        _, comp = measure(ucfg, ds, f_opt, repeats=1)  # record compile cost
        compile_secs[str(u)] = comp
        unroll_cfgs[str(u)] = (ucfg, {})
    unroll_ips = measure_group(unroll_cfgs, ds, f_opt, cycles=2)
    unroll = {
        u: {"iters_per_sec": round(unroll_ips[u], 1),
            "compile_seconds": round(compile_secs[u], 1)}
        for u in unroll_cfgs
    }
    results["scan_unroll"] = unroll
    print(f"[breakdown] scan_unroll: {unroll}", file=sys.stderr)

    # --- 4. sampling_impl: gather vs dense weighted-gradient form ---
    # (the measurement behind config.resolved_sampling_impl's auto rule)
    samp = {}
    for n in (25, 256, 1024):
        ncfg = ExperimentConfig(**{**BASE, "n_workers": n,
                                   "n_iterations": 4000})
        if n == cfg.n_workers:
            # Same data as the main config (generation depends only on the
            # problem/sample knobs + N) — skip the redundant oracle solve.
            nds, nf = ds, f_opt
        else:
            nds = generate_synthetic_dataset(ncfg)
            _, nf = compute_reference_optimum(nds, ncfg.reg_param)
        L = max(len(i) for i in nds.shard_indices)
        res = measure_group(
            {impl: (ncfg.replace(sampling_impl=impl), {})
             for impl in ("gather", "dense")},
            nds, nf, cycles=2,
        )
        samp[f"N={n} (L={L})"] = {k: round(v, 1) for k, v in res.items()}
    results["sampling_impl_iters_per_sec"] = samp
    print(f"[breakdown] sampling: {samp}", file=sys.stderr)

    if trace:
        import jax

        trace_dir = out_dir / "trace"
        with jax.profiler.trace(str(trace_dir)):
            jax_backend.run(
                cfg.replace(n_iterations=1000), ds, f_opt,
                measure_compile=False,
            )
        results["trace_dir"] = str(trace_dir.relative_to(root))
        print(f"[breakdown] trace written to {trace_dir}", file=sys.stderr)

    path = out_dir / "breakdown.json"
    path.write_text(json.dumps(results, indent=2) + "\n")
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(path)

    print(json.dumps({"wrote": str(path.relative_to(root))}))


if __name__ == "__main__":
    main()
