"""Eval-cadence form measurements (round 5, VERDICT r4 item 6).

Round 3 established that the flat fused scan computes the full-dataset
objective INLINE every micro-chunk and that this is measured-free at the
headline scale (n_samples=12.5k). That statement is n_samples-bound: the
inline eval scales with the dataset while the step does not. Round 5 adds
an exact-cadence HOISTED form (eval-free flat scans with evals between
them, one XLA program — jax_backend.py) and this script measures when each
form wins, plus the host-driven chunk loop for reference:

1. coarse cadence across n_samples: hoisted (forced via the public
   measure_timestamps=False + EVAL_HOIST gates) vs inline — locates
   HOISTED_MIN_RATIO, the eval-dominance ratio where hoisting starts
   paying. The hoisted form is NOT free: on the tunneled chip each extra
   scan region in the program costs ~180 ms of dispatch/sync, so hoisting
   only wins once the discarded inline evals cost more than the extra
   regions.
2. one maximally eval-dominated cell (S=2M, eval_every=100) comparing
   inline / hoisted / chunk loop three ways: the chunk loop pays one
   host round-trip per eval (~300 ms on the tunneled chip — measured
   311 vs 78,077 iters/sec at the headline scale in the round-5 session),
   so it is never the routing answer here; it exists for real per-eval
   timestamps, not throughput.

Datasets are random (labels irrelevant to throughput; sklearn generation
at n=2M costs minutes the measurement does not need). Variants interleave
per cycle (shared-chip protocol). Aggregation is the MEDIAN of cycles
that pass a physical floor: at the S=2M cell the tunneled runtime
intermittently returned from a hoisted-program execution in ~1 ms
(implying millions of iters/sec — hundreds of times above the HBM bound
for even ONE of the program's 40 full-dataset evals), so any reading
whose implied run time is below n_evals x (one full-dataset pass at peak
HBM bandwidth) is recorded raw but excluded from the aggregate. Stalled
readings (co-tenant pauses, e.g. a 59 iters/sec outlier against a ~4k
median) are handled by the median itself.

Writes ``docs/perf/eval_cadence.json``.

Usage:  python examples/bench_eval_cadence.py [--out PATH] [--cycles 3]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
from pathlib import Path

HBM_GBPS = 819e9  # v5e peak; the floor only needs the right order of magnitude


def _aggregate(readings, T, n_evals, S, d):
    """Median of physically-possible readings (see module docstring)."""
    floor_seconds = n_evals * (S * (d + 1) * 4 / HBM_GBPS)
    ok = [r for r in readings if r > 0 and T / r >= floor_seconds]
    kept = ok if ok else readings
    return round(statistics.median(kept), 1), len(readings) - len(ok)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _random_logistic_dataset(n_samples: int, n_workers: int, d_feat: int):
    from distributed_optimization_tpu.utils.data import HostDataset

    rng = np.random.default_rng(0)
    X = rng.standard_normal((n_samples, d_feat)).astype(np.float64)
    X = np.hstack([X, np.ones((n_samples, 1))])
    y = rng.choice([-1.0, 1.0], size=n_samples)
    shard_indices = [
        np.asarray(s) for s in np.array_split(np.arange(n_samples), n_workers)
    ]
    return HostDataset(X_full=X, y_full=y, shard_indices=shard_indices,
                       problem_type="logistic")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cycles", type=int, default=3)
    ap.add_argument("--out", default="docs/perf/eval_cadence.json")
    args = ap.parse_args()

    import jax

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.config import ExperimentConfig

    dev = jax.devices()[0]
    print(f"[eval_cadence] device={dev}", file=sys.stderr)
    N, b, d = 256, 16, 80

    def run_form(cfg, ds, form):
        """Force one execution form via run()'s per-run gate kwargs (the
        module globals are immutable defaults — nothing to save/restore)."""
        if form == "inline":
            r = jax_backend.run(cfg, ds, 0.0, measure_compile=False,
                                measure_timestamps=False, eval_hoist_limit=0)
        elif form == "hoisted":
            r = jax_backend.run(cfg, ds, 0.0, measure_compile=False,
                                measure_timestamps=False,
                                hoisted_min_ratio=0.0)
        else:  # chunked
            r = jax_backend.run(cfg, ds, 0.0, measure_compile=False,
                                measure_timestamps=True)
        return float(r.history.iters_per_second)

    # --- 1. coarse cadence: hoisted vs inline across n_samples ------------
    # T=20k, eval_every=4k (n_evals=5, micro=8): ratio = S / (2*8*N*b).
    coarse = {}
    setups = {}
    for S in (12_500, 200_000, 400_000, 700_000, 1_000_000):
        cfg = ExperimentConfig(
            problem_type="logistic", algorithm="dsgd", topology="ring",
            n_workers=N, local_batch_size=b, n_samples=S, n_features=d,
            n_iterations=20_000, eval_every=4_000,
        )
        setups[S] = (cfg, _random_logistic_dataset(S, N, d))
        coarse[f"S{S}"] = {
            "eval_dominance_ratio": round(S / (2.0 * 8 * N * b), 2),
            "hoisted_ips": [], "inline_ips": [],
        }
    for c in range(args.cycles):
        for S, (cfg, ds) in setups.items():
            coarse[f"S{S}"]["hoisted_ips"].append(
                run_form(cfg, ds, "hoisted"))
            coarse[f"S{S}"]["inline_ips"].append(run_form(cfg, ds, "inline"))
            print(f"[eval_cadence] cycle {c + 1} S={S}: hoisted "
                  f"{coarse[f'S{S}']['hoisted_ips'][-1]:.0f} inline "
                  f"{coarse[f'S{S}']['inline_ips'][-1]:.0f}", file=sys.stderr)
    for S, row in zip(setups, coarse.values()):
        for form in ("hoisted", "inline"):
            raw = row[f"{form}_ips"]
            row[f"{form}_ips_raw"] = [round(r, 1) for r in raw]
            row[f"{form}_ips"], dropped = _aggregate(
                raw, 20_000, 5, S, 80)
            if dropped:
                row[f"{form}_readings_excluded"] = dropped
        row["hoisted_over_inline"] = round(
            row["hoisted_ips"] / row["inline_ips"], 2)

    # --- 2. the maximally eval-dominated cell, three ways -----------------
    S2 = 2_000_000
    cfg2 = ExperimentConfig(
        problem_type="logistic", algorithm="dsgd", topology="ring",
        n_workers=N, local_batch_size=b, n_samples=S2, n_features=d,
        n_iterations=4_000, eval_every=100,  # n_evals=40, micro=5
    )
    ds2 = _random_logistic_dataset(S2, N, d)
    demo = {
        "eval_dominance_ratio": round(S2 / (2.0 * 5 * N * b), 2),
        "inline_ips": [], "hoisted_ips": [], "chunked_ips": [],
    }
    for c in range(args.cycles):
        for form in ("inline", "hoisted", "chunked"):
            demo[f"{form}_ips"].append(run_form(cfg2, ds2, form))
        print(f"[eval_cadence] cycle {c + 1} demo: "
              + " ".join(f"{f} {demo[f'{f}_ips'][-1]:.0f}"
                         for f in ("inline", "hoisted", "chunked")),
              file=sys.stderr)
    for form in ("inline", "hoisted", "chunked"):
        raw = demo[f"{form}_ips"]
        demo[f"{form}_ips_raw"] = [round(r, 1) for r in raw]
        demo[f"{form}_ips"], dropped = _aggregate(raw, 4_000, 40, S2, 80)
        if dropped:
            demo[f"{form}_readings_excluded"] = dropped

    payload = {
        "device": str(dev),
        "protocol": (
            f"N={N} ring logistic d={d} b={b}; median of {args.cycles} "
            "interleaved cycles passing the physical floor (see script "
            "docstring; raw readings recorded), compile excluded. "
            "Section 1: T=20k, eval_every=4k (n_evals=5), hoisted forced "
            "via run(hoisted_min_ratio=0) vs inline forced via "
            "run(eval_hoist_limit=0); eval_dominance_ratio = n_samples / "
            "(2*micro*N*b) is the quantity HOISTED_MIN_RATIO gates on. "
            "Section 2: S=2M, eval_every=100 (n_evals=40), the three "
            "forms head-to-head."
        ),
        "coarse_cadence_hoisted_vs_inline": coarse,
        "eval_dominated_demo_three_forms": demo,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(path)

    print(json.dumps({"metric": "eval_cadence_cells", "value": len(coarse) + 1}))


if __name__ == "__main__":
    main()
