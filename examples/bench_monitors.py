"""Anomaly-sentinel bench (ISSUE-13 headline artifact;
docs/OBSERVABILITY.md "Monitors & incidents").

Monitoring must be cheap enough to leave on for every served run, and
the detectors must actually catch the pathology the north star pays for
finding. Four cells:

- OVERHEAD cell: D-SGD ring N=32 d=40, T=3000, eval_every=50 — monitors
  OFF vs ON (``MonitorBank`` with ``halt_on='fatal'``, nothing firing)
  at ``progress_every=15`` (the heartbeat-cell protocol of
  docs/perf/observatory.json), 3 interleaved cycles, median steady-state
  iters/sec. Asserted: overhead ≤ 5% and off/on bitwise objective
  equality — watching a healthy run costs a few host syncs and changes
  nothing.
- ASYNC cell: the event path under monitors at ``progress_every=6``
  (4 heartbeats/run over 24 eval chunks — the segment-fused execution
  the ISSUE-13 satellite moved the async progress path onto). Asserted
  ≤ 5% and bitwise.
- DIVERGENCE cell: the planted f > b run — ALIE with 3 attackers
  against a b=1 trimmed mean on a ring (per-neighborhood budget
  exceeded, the sharp breakdown regime of docs/perf/byzantine.json) at
  a learning rate whose attack-free twin CONVERGES (asserted). The
  divergence detector must fire with onset within 2 eval windows of the
  measured degradation onset (first eval where the gap exceeds the best
  seen).
- HALT cell: the same run under ``halt_on='fatal'`` must stop at a
  chunk boundary well before the horizon (asserted ≥ half the horizon
  saved), with the executed prefix bitwise the full run's, and the
  incident bundle must name the attacker context (payload, Byzantine
  node set, over-budget flag).

Writes ``docs/perf/monitors.json`` + provenance sidecar; registered in
the drift guard, ``PERF_TOLERANCES``, and
``examples/regen_perf_artifacts.sh``; ``make perf-diff`` re-checks
regenerated copies against the committed one.

Usage:  python examples/bench_monitors.py [--out PATH] [--cycles 3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MONITOR_OVERHEAD_CEILING = 0.05   # asserted, sequential AND async cells
MIN_HORIZON_SAVED_FRAC = 0.5      # the halt must save at least this much
ONSET_WINDOW_EVALS = 2            # detector onset vs measured degradation


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/perf/monitors.json")
    ap.add_argument("--cycles", type=int, default=3)
    args = ap.parse_args()

    import jax
    import numpy as np

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.observability.monitors import (
        MonitorBank,
    )
    from distributed_optimization_tpu.telemetry import write_bench_manifest
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import (
        compute_reference_optimum,
    )
    from distributed_optimization_tpu.utils.profiling import PhaseTimer

    timer = PhaseTimer()
    base = ExperimentConfig(
        n_workers=32, n_samples=3200, n_features=40,
        n_informative_features=20, problem_type="quadratic",
        algorithm="dsgd", topology="ring", n_iterations=3000,
        eval_every=50, local_batch_size=32,
    )
    with timer.phase("data_gen"):
        ds = generate_synthetic_dataset(base)
    with timer.phase("oracle"):
        _, f_opt = compute_reference_optimum(ds, base.reg_param)

    skip = os.environ.get("BENCH_NO_RANGE_CHECK", "").lower() not in (
        "", "0", "false"
    )

    # ------------------------------------------------- overhead cell (seq)
    with timer.phase("overhead"):
        ips = {"off": [], "on": []}
        last = {}
        # One untimed warmup per arm: the first segmented/one-shot
        # executions pay their compiles and first-dispatch noise before
        # the interleaved measurement cycles start.
        for warmup in (True, False):
            for _ in range(1 if warmup else args.cycles):
                for arm in ("off", "on"):
                    kw = {}
                    if arm == "on":
                        kw = dict(
                            monitors=MonitorBank(base, halt_on="fatal"),
                            progress_every=15,
                        )
                    r = jax_backend.run(base, ds, f_opt, **kw)
                    if warmup:
                        continue
                    ips[arm].append(r.history.iters_per_second)
                    last[arm] = (r, kw.get("monitors"))
        off = float(np.median(ips["off"]))
        on = float(np.median(ips["on"]))
        overhead = max(0.0, 1.0 - on / off)
        bitwise = bool(np.array_equal(
            last["off"][0].history.objective,
            last["on"][0].history.objective,
        ))
        assert last["on"][1].anomalies == [], (
            "monitors fired on the healthy overhead cell: "
            f"{last['on'][1].anomalies}"
        )
        assert bitwise, (
            "monitors-on perturbed the trajectory — observation must ride "
            "the bitwise segmented-progress machinery"
        )
        overhead_cell = {
            "ips_off_median": off,
            "ips_on_median": on,
            "ips_off_raw": [float(v) for v in ips["off"]],
            "ips_on_raw": [float(v) for v in ips["on"]],
            "overhead_frac": overhead,
            "overhead_ok": overhead <= MONITOR_OVERHEAD_CEILING,
            "off_on_bitwise_objective": bitwise,
            "progress_every": 15,
        }
        if not skip:
            assert overhead <= MONITOR_OVERHEAD_CEILING, (
                f"monitor overhead {overhead:.1%} exceeds the "
                f"{MONITOR_OVERHEAD_CEILING:.0%} ceiling (set "
                "BENCH_NO_RANGE_CHECK=1 on non-canonical hardware)"
            )

    # ---------------------------------------------------------- async cell
    with timer.phase("async"):
        acfg = base.replace(
            execution="async", latency_model="exponential",
            latency_mean=1.0, n_iterations=1200, eval_every=50,
        )
        a_ips = {"off": [], "on": []}
        a_last = {}
        for warmup in (True, False):
            for _ in range(1 if warmup else args.cycles):
                for arm in ("off", "on"):
                    kw = {}
                    if arm == "on":
                        kw = dict(
                            monitors=MonitorBank(acfg, halt_on="fatal"),
                            progress_every=6,
                        )
                    r = jax_backend.run(acfg, ds, f_opt, **kw)
                    if warmup:
                        continue
                    a_ips[arm].append(r.history.iters_per_second)
                    a_last[arm] = r
        a_off = float(np.median(a_ips["off"]))
        a_on = float(np.median(a_ips["on"]))
        a_overhead = max(0.0, 1.0 - a_on / a_off)
        a_bitwise = bool(np.array_equal(
            a_last["off"].history.objective,
            a_last["on"].history.objective,
        ))
        assert a_bitwise, "async monitors perturbed the trajectory"
        async_cell = {
            "ips_off_median": a_off,
            "ips_on_median": a_on,
            "overhead_frac": a_overhead,
            "overhead_ok": a_overhead <= MONITOR_OVERHEAD_CEILING,
            "off_on_bitwise_objective": a_bitwise,
            "progress_every": 6,
        }
        if not skip:
            assert a_overhead <= MONITOR_OVERHEAD_CEILING, (
                f"async monitor overhead {a_overhead:.1%} exceeds the "
                f"{MONITOR_OVERHEAD_CEILING:.0%} ceiling (set "
                "BENCH_NO_RANGE_CHECK=1 on non-canonical hardware)"
            )

    # ----------------------------------------------- planted f > b cells
    # Small planted instance (the tests' shape): 8-ring, quadratic,
    # eta0=0.3 — the attack-free twin converges, the over-budget ALIE
    # diverges geometrically from early on.
    planted = ExperimentConfig(
        n_workers=8, n_samples=400, n_features=10,
        n_informative_features=6, problem_type="quadratic",
        algorithm="dsgd", topology="ring", n_iterations=600,
        eval_every=20, local_batch_size=16, learning_rate_eta0=0.3,
        attack="alie", n_byzantine=3, attack_scale=1.5,
        aggregation="trimmed_mean", robust_b=1,
    )
    with timer.phase("divergence"):
        pds = generate_synthetic_dataset(planted)
        _, p_opt = compute_reference_optimum(pds, planted.reg_param)
        twin = planted.replace(
            attack="none", n_byzantine=0,
            attack_scale=ExperimentConfig().attack_scale,
        )
        twin_r = jax_backend.run(twin, pds, p_opt)
        twin_converges = bool(
            twin_r.history.objective[-1] < twin_r.history.objective[0]
        )
        assert twin_converges, (
            "the attack-free twin did not converge — the planted cell "
            "would prove nothing about the attack"
        )

        full = jax_backend.run(planted, pds, p_opt)
        gaps = full.history.objective
        evals = full.history.eval_iterations
        best = np.minimum.accumulate(gaps)
        degraded = np.flatnonzero(gaps[1:] > best[:-1])
        measured_onset = int(evals[degraded[0] + 1])

        bank = MonitorBank(planted, halt_on="never")
        jax_backend.run(planted, pds, p_opt, monitors=bank)
        div = [a for a in bank.anomalies if a.detector == "divergence"]
        assert div, f"divergence did not fire: {bank.anomalies}"
        onset = int(div[0].onset_iteration)
        onset_err_windows = abs(onset - measured_onset) / planted.eval_every
        assert onset_err_windows <= ONSET_WINDOW_EVALS, (
            f"detector onset {onset} is {onset_err_windows:.1f} eval "
            f"windows from the measured degradation at {measured_onset}"
        )
        divergence_cell = {
            "final_gap_attacked": float(gaps[-1]),
            "final_gap_attack_free": float(twin_r.history.objective[-1]),
            "measured_degradation_onset": measured_onset,
            "detector_onset": onset,
            "onset_error_eval_windows": float(onset_err_windows),
            "anomalies": [a.to_dict() for a in bank.anomalies],
        }

    with timer.phase("halt"):
        bank_h = MonitorBank(planted, halt_on="fatal")
        part = jax_backend.run(planted, pds, p_opt, monitors=bank_h)
        n_done = len(part.history.objective)
        n_total = len(gaps)
        saved_frac = 1.0 - n_done / n_total
        prefix_bitwise = bool(np.array_equal(
            part.history.objective, gaps[:n_done]
        ))
        assert bank_h.halted_at is not None and n_done < n_total, (
            "halt_on=fatal did not end the planted run early"
        )
        assert prefix_bitwise, (
            "the halted run's executed prefix is not the full run's "
            "prefix — the continuation contract broke"
        )
        assert saved_frac >= MIN_HORIZON_SAVED_FRAC, (
            f"halt saved only {saved_frac:.0%} of the horizon"
        )
        incident = next(
            i for i in bank_h.incidents(label="bench-planted-alie")
            if i["detector"] == "divergence"
        )
        attack_ctx = incident["context"]["attack"]
        names_attacker = bool(
            attack_ctx["attack"] == "alie"
            and attack_ctx["over_budget"] is True
            and len(attack_ctx["byzantine_nodes"])
            == planted.n_byzantine
        )
        assert names_attacker, f"incident context incomplete: {attack_ctx}"
        halt_cell = {
            "halted_at_iteration": int(bank_h.halted_at),
            "executed_evals": int(n_done),
            "horizon_evals": int(n_total),
            "horizon_saved_frac": float(saved_frac),
            "prefix_bitwise": prefix_bitwise,
            "incident_detector": incident["detector"],
            "incident_attack_context": attack_ctx,
        }

    gates = {
        "monitor_overhead_ceiling": MONITOR_OVERHEAD_CEILING,
        "seq_within_ceiling": overhead_cell["overhead_ok"],
        "async_within_ceiling": async_cell["overhead_ok"],
        "off_on_bitwise_objective": (
            overhead_cell["off_on_bitwise_objective"]
            and async_cell["off_on_bitwise_objective"]
        ),
        "attack_free_twin_converges": twin_converges,
        "divergence_fired": True,
        "onset_within_2_eval_windows": (
            divergence_cell["onset_error_eval_windows"]
            <= ONSET_WINDOW_EVALS
        ),
        "halt_early": halt_cell["horizon_saved_frac"]
        >= MIN_HORIZON_SAVED_FRAC,
        "halt_prefix_bitwise": halt_cell["prefix_bitwise"],
        "incident_names_attacker": names_attacker,
    }
    payload = {
        "device": str(jax.devices()[0]),
        "platform": jax.devices()[0].platform,
        "protocol": (
            f"overhead: N=32 d=40 ring quadratic T=3000 eval_every=50, "
            f"monitors off vs on (halt_on=fatal, progress_every=15) "
            f"interleaved x{args.cycles} cycles, median steady-state "
            "iters/sec, ≤5% asserted + bitwise. async: T=1200 events "
            "path, progress_every=6 segment-fused heartbeats, same "
            "gates. divergence: planted over-budget ALIE (f=3 > b=1 "
            "trimmed mean, 8-ring) whose attack-free twin converges; "
            "detector onset within 2 eval windows of measured "
            "degradation asserted. halt: halt_on=fatal ends the planted "
            "run at a chunk boundary, ≥50% of the horizon saved, prefix "
            "bitwise, incident bundle names the attacker context."
        ),
        "note": (
            "Monitors ride the segmented-progress machinery: observation "
            "is a Python callback per heartbeat, so monitors-on with "
            "nothing firing is bitwise monitors-off on every path. The "
            "async cell runs the ISSUE-13 segment-fused progress form "
            "(one host sync per heartbeat, not per eval chunk)."
        ),
        "overhead": overhead_cell,
        "async": async_cell,
        "divergence": divergence_cell,
        "halt": halt_cell,
        "gates": gates,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    write_bench_manifest(path, config=base, phases=timer)
    print(json.dumps({
        "metric": "monitor_overhead_frac",
        "value": overhead_cell["overhead_frac"],
        "async_overhead_frac": async_cell["overhead_frac"],
        "onset_error_eval_windows": (
            divergence_cell["onset_error_eval_windows"]
        ),
        "horizon_saved_frac": halt_cell["horizon_saved_frac"],
    }))


if __name__ == "__main__":
    main()
