"""``make observatory-smoke``: the live observatory end to end over HTTP.

The CI-sized check of ISSUE-10's four layers against a REAL daemon:

1. boot ``ServingDaemon`` on an ephemeral port, submit a run;
2. stream ``GET /v1/progress/<id>`` while it executes — assert lifecycle
   ordering (queued → running → … → done), at least one chunk heartbeat
   with a finite gap, and monotone iteration indices;
3. scrape ``GET /metrics`` mid-run and after — assert Prometheus text
   with the executable-cache and serving families present and a
   consistent histogram (bucket total == count) in the SAME scrape;
4. pull the finished manifest, write it (plus a second run's) to a temp
   dir, and drive the ``observatory`` CLI over it: ``list`` finds both,
   ``compare`` reports the config diff, and ``perf-diff`` self-checks
   the committed ``docs/perf`` tree (exit 0).

Exit code 0 = all assertions passed.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.serving.cache import ExecutableCache
    from distributed_optimization_tpu.serving.client import RetryingClient
    from distributed_optimization_tpu.serving.daemon import ServingDaemon
    from distributed_optimization_tpu.serving.service import (
        ServingOptions,
        SimulationService,
    )
    from distributed_optimization_tpu.observability.observatory import main as obs_main

    base = ExperimentConfig(
        n_workers=8, n_samples=400, n_features=10,
        n_informative_features=6, problem_type="quadratic",
        n_iterations=200, eval_every=10, local_batch_size=8,
    )
    opts = ServingOptions(window_s=0.05, progress_every=2)
    daemon = ServingDaemon(
        "127.0.0.1", 0, opts,
        service=SimulationService(opts, cache=ExecutableCache()),
    )
    daemon.start()
    url = daemon.url
    # The retrying serving client (ISSUE-12 satellite) drives the whole
    # smoke: submits, status polls, /metrics scrapes, progress streams.
    client = RetryingClient(url, max_retries=4, seed=0)
    print(f"[observatory-smoke] daemon at {url}", file=sys.stderr)
    try:
        # --- submit and stream progress WHILE it runs -------------------
        code, sub = client.submit(base.to_dict(), timeout=30)
        assert code == 202, (code, sub)
        rid = sub["id"]

        # /metrics is scraped MID-RUN: on the first chunk heartbeat (the
        # run is provably in flight), a second connection scrapes while
        # this one keeps streaming — the torn-histogram check below runs
        # on that snapshot.
        mid_scrapes = []
        events = []
        with client.progress_stream(rid, timeout=300) as resp:
            assert resp.headers["Content-Type"].startswith(
                "application/x-ndjson"
            ), resp.headers["Content-Type"]
            for line in resp:
                if not line.strip():
                    continue
                events.append(json.loads(line))
                if events[-1]["kind"] == "chunk" and not mid_scrapes:
                    mid_scrapes.append(client.metrics_text(timeout=30))

        statuses = [e.get("status") for e in events if e.get("status")]
        assert statuses[0] == "queued" and statuses[-1] == "done", statuses
        chunks = [e for e in events if e["kind"] == "chunk"]
        assert chunks, f"no chunk heartbeats streamed: {events}"
        iters = [e["iteration"] for e in chunks]
        assert iters == sorted(iters) and iters[-1] == base.n_iterations, iters
        assert any(
            isinstance(e.get("gap"), (int, float)) for e in chunks
        ), chunks
        seqs = [e["seq"] for e in events]
        assert seqs == sorted(seqs), seqs
        print(
            f"[observatory-smoke] streamed {len(events)} events "
            f"({len(chunks)} chunk heartbeats), lifecycle {statuses}",
            file=sys.stderr,
        )

        # --- /metrics: families present + consistent histogram ----------
        assert mid_scrapes and not mid_scrapes[0].startswith("ERROR"), (
            mid_scrapes
        )
        text = mid_scrapes[0]
        for family in (
            "dopt_exec_cache_hits_total",
            "dopt_serving_queue_depth",
            "dopt_serving_cohort_size",
            "dopt_progress_heartbeats_total",
        ):
            assert family in text, f"/metrics missing {family}\n{text}"
        # No torn histogram: within ONE scrape, the +Inf cumulative bucket
        # must equal the count line for every histogram series.
        import re

        for name in re.findall(r"# TYPE (\S+) histogram", text):
            infs = {
                m.group(1) or "": int(m.group(2))
                for m in re.finditer(
                    rf'^{name}_bucket\{{(.*?,)?le="\+Inf"\}} (\d+)$',
                    text, re.M,
                )
            }
            counts = re.findall(rf"^{name}_count(?:\{{.*\}})? (\d+)$", text, re.M)
            if counts and infs:
                assert sum(infs.values()) == sum(int(c) for c in counts), (
                    f"torn histogram {name}: {infs} vs {counts}"
                )

        # --- status: counters always present + bounded history ----------
        code, st = client.status()
        assert code == 200
        assert {"hits", "misses", "compile_seconds_saved"} <= set(st["cache"])
        assert st["history"]["bound"] == opts.max_done
        assert st["history"]["retained"] >= 1

        # --- observatory CLI over the served manifests -------------------
        code, m1 = client.result(rid, timeout=60)
        assert code == 200 and m1["kind"] == "run_trace"
        assert m1["provenance"]["jax_version"], m1["provenance"]
        assert m1["spans"], "manifest carries no spans"
        code, m2 = client.run(
            base.replace(learning_rate_eta0=0.11).to_dict(), timeout=300,
        )
        assert code == 200, (code, m2)

        with tempfile.TemporaryDirectory() as td:
            a = Path(td) / "a.json"
            b = Path(td) / "b.json"
            a.write_text(json.dumps(m1))
            b.write_text(json.dumps(m2))
            assert obs_main(["list", td]) == 0
            assert obs_main(["compare", str(a), str(b)]) == 0
        repo = Path(__file__).resolve().parent.parent
        rc = obs_main([
            "perf-diff",
            "--fresh", str(repo / "docs" / "perf"),
            "--committed", str(repo / "docs" / "perf"),
        ])
        assert rc == 0, "perf-diff self-check failed"
        print("[observatory-smoke] PASS", file=sys.stderr)
        return 0
    finally:
        daemon.stop()


if __name__ == "__main__":
    raise SystemExit(main())
