"""Scenario-matrix golden corpus (ISSUE 12) -> docs/perf/scenarios.json.

Runs under a FORCED 4-device host platform (set before jax initializes,
the tests/conftest.py mechanism) so the worker-mesh cells execute real
multi-device halo collectives on this CPU container. Four gated claims:

1. **Agreement** — the validity table and ``ExperimentConfig``
   construction agree verdict-for-verdict on a seeded >= 500-cell sample
   spanning all 10 composition axes (zero divergences, asserted).
2. **Matrix** — the committed golden spec's >= 30 valid cells (all 10
   axes: algorithm, topology/impl, faults, Byzantine, compression, local
   steps, participation, execution, replicas, worker_mesh) run through
   the serving layer and EVERY applicable per-cell invariant passes: GT
   tracking, robust-envelope containment, B̂/degradation, the
   burst/churn/zero-budget bitwise reductions, explicit-default
   identity, replica-cohort coalescing.
3. **Checkpoint** — a dedicated 3-cell spec (plain, GT, faulty) passes
   bitwise interrupt+resume (split out of the main matrix because the
   invariant costs three segmented compiles per cell).
4. **Chaos** — the operational suite degrades gracefully: poisoned
   cohort isolated, daemon kill/restart served warm from the surviving
   executable cache, truncated checkpoint chunk survived bitwise, broken
   progress callback contained.

The committed JSON is guarded by the perf-diff checker
(``observability/observatory.py`` PERF_TOLERANCES): every gate boolean
and the cell/axis counts must reproduce exactly on regen.
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

# Must precede any jax import.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4"
    ).strip()

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

OUT = REPO / "docs" / "perf" / "scenarios.json"

BASE = {
    "n_workers": 8, "n_samples": 400, "n_features": 10,
    "n_informative_features": 6, "problem_type": "quadratic",
    "n_iterations": 120, "eval_every": 20, "local_batch_size": 8,
    "dtype": "float64",
}

# The golden matrix: 41 curated compositions × 2 learning rates = 82
# cells, every one VALID by construction (the spec is committed evidence
# that these compositions run, not a sampler exercise — the sampler's
# valid/invalid frontier is gated by the agreement block instead).
SCENARIOS = [
    {},
    {"algorithm": "centralized"},
    {"algorithm": "gradient_tracking"},
    {"algorithm": "extra"},
    {"algorithm": "admm"},
    {"algorithm": "choco"},
    {"algorithm": "push_sum", "topology": "directed_ring"},
    {"topology": "grid", "n_workers": 16},
    {"topology": "erdos_renyi", "topology_seed": 7},
    {"topology": "chain", "gossip_schedule": "round_robin"},
    {"topology_impl": "neighbor"},
    {"gossip_schedule": "one_peer"},
    {"dtype": "float32"},
    {"backend": "numpy"},
    {"edge_drop_prob": 0.2},
    {"edge_drop_prob": 0.2, "burst_len": 4.0},
    {"straggler_prob": 0.15},
    {"mttf": 40.0, "mttr": 15.0},
    {"mttf": 40.0, "mttr": 15.0, "rejoin": "neighbor_restart"},
    {"attack": "sign_flip", "n_byzantine": 1,
     "aggregation": "trimmed_mean", "robust_b": 1,
     "partition": "shuffled"},
    {"attack": "alie", "n_byzantine": 1, "aggregation": "median",
     "robust_b": 1, "partition": "shuffled"},
    {"aggregation": "clipped_gossip", "robust_b": 1, "clip_tau": 0.5},
    {"compression": "top_k", "compression_k": 4},
    {"algorithm": "gradient_tracking", "compression": "qsgd",
     "compression_k": 4},
    {"local_steps": 4},
    {"algorithm": "gradient_tracking", "local_steps": 2},
    # Degenerate knobs spelled explicitly at their off points: must name
    # the exact experiment of the bare baseline cell (coalescing
    # identity, reduction_explicit_defaults).
    {"local_steps": 1, "participation_rate": 1.0, "burst_len": 0.0},
    {"participation_rate": 0.5},
    {"local_steps": 2, "participation_rate": 0.5, "mttf": 40.0,
     "mttr": 15.0},
    {"execution": "async", "latency_model": "exponential"},
    {"execution": "async", "latency_model": "pareto",
     "latency_tail": 1.5},
    # Async-faulty cells (ISSUE-17): faults realized on the EVENT axis —
    # compositions the validity table rejected before the event-clock
    # fault substrate landed. Each exercises a deleted rejection rule:
    # churn, participation thinning, gradient tracking's per-event
    # telescoping, τ fused per event, straggler-churn collapse, rejoin.
    {"execution": "async", "latency_model": "lognormal",
     "latency_tail": 0.5, "mttf": 40.0, "mttr": 15.0},
    {"execution": "async", "latency_model": "exponential",
     "participation_rate": 0.5},
    {"execution": "async", "latency_model": "lognormal",
     "latency_tail": 0.5, "algorithm": "gradient_tracking"},
    {"execution": "async", "latency_model": "exponential",
     "local_steps": 2},
    {"execution": "async", "latency_model": "exponential",
     "straggler_prob": 0.15},
    {"execution": "async", "latency_model": "exponential",
     "mttf": 40.0, "mttr": 15.0, "rejoin": "neighbor_restart"},
    {"replicas": 3},
    {"worker_mesh": 2},
    {"worker_mesh": 2, "straggler_prob": 0.15},
    {"worker_mesh": 2, "attack": "sign_flip", "n_byzantine": 1,
     "aggregation": "trimmed_mean", "robust_b": 1,
     "partition": "shuffled"},
]

# The agreement sample's axis bank (weighted toward each axis's 'off'
# setting so the sample hits the valid region too — unweighted, the
# product of ~10 mostly-incompatible axes is < 1% valid).
def agreement_axes():
    return {
        "algorithm": (
            [{}] * 2
            + [{"algorithm": a} for a in
               ("centralized", "dsgd", "gradient_tracking", "extra",
                "admm", "choco", "push_sum")]
        ),
        "topology": (
            [{"topology": "ring"}] * 4 + [
                {"topology": "grid", "n_workers": 16},
                {"topology": "fully_connected"},
                {"topology": "erdos_renyi"}, {"topology": "chain"},
                {"topology": "star"}, {"topology": "directed_ring"},
                {"topology": "ring", "topology_impl": "neighbor"},
                {"topology": "ring", "gossip_schedule": "one_peer"},
                {"topology": "chain", "gossip_schedule": "round_robin"},
            ]
        ),
        "faults": (
            [{}] * 6 + [
                {"edge_drop_prob": 0.2},
                {"edge_drop_prob": 0.2, "burst_len": 4.0},
                {"straggler_prob": 0.15}, {"mttf": 40.0, "mttr": 15.0},
                {"mttf": 40.0, "mttr": 15.0,
                 "rejoin": "neighbor_restart"},
                {"burst_len": 3.0}, {"mttf": 40.0},
            ]
        ),
        "byzantine": (
            [{}] * 8 + [
                {"attack": "sign_flip", "n_byzantine": 1},
                {"attack": "sign_flip", "n_byzantine": 1,
                 "aggregation": "trimmed_mean", "robust_b": 1},
                {"aggregation": "median", "robust_b": 1},
                {"aggregation": "clipped_gossip", "robust_b": 1,
                 "clip_tau": 0.5},
                {"attack": "alie", "n_byzantine": 2,
                 "aggregation": "median", "robust_b": 2},
                {"robust_impl": "fused"},
                {"aggregation": "trimmed_mean"}, {"n_byzantine": 3},
            ]
        ),
        "compression": (
            [{}] * 3 + [
                {"compression": "top_k", "compression_k": 4},
                {"compression": "qsgd", "compression_k": 4},
                {"compression": "top_k"},
            ]
        ),
        "local_steps": [{}, {}, {"local_steps": 2}, {"local_steps": 4}],
        "participation": [
            {}, {}, {"participation_rate": 0.5},
            {"participation_rate": 1.0},
        ],
        "execution": (
            [{}] * 6 + [
                {"execution": "async", "latency_model": "exponential"},
                {"execution": "async", "latency_model": "lognormal",
                 "latency_tail": 0.5},
                {"execution": "async", "latency_model": "pareto",
                 "latency_tail": 1.5},
                {"execution": "async"}, {"latency_model": "exponential"},
                {"execution": "async", "latency_model": "exponential",
                 "backend": "numpy"},
            ]
        ),
        "replicas": [{}, {}, {"replicas": 4}],
        "worker_mesh": (
            [{}] * 3 + [
                {"worker_mesh": 2}, {"worker_mesh": 3},
                {"tp_degree": 2, "problem_type": "softmax"},
            ]
        ),
    }


def axes_coverage(report) -> dict:
    """Which of the 10 orthogonal axes the VALID cells exercise
    non-trivially (beyond the default setting)."""
    cells = [r for r in report["cells"] if r.get("valid")]

    def has(pred):
        return any(pred(r["overrides"]) for r in cells)

    return {
        "algorithm": len(
            {r["overrides"].get("algorithm", "dsgd") for r in cells}
        ) >= 5,
        "topology": has(lambda o: o.get("topology") not in (None, "ring"))
        and has(lambda o: o.get("topology_impl") == "neighbor"),
        "faults": has(lambda o: o.get("edge_drop_prob", 0) > 0)
        and has(lambda o: o.get("burst_len", 0) > 1)
        and has(lambda o: o.get("straggler_prob", 0) > 0)
        and has(lambda o: o.get("mttf", 0) > 0),
        "byzantine": has(lambda o: o.get("attack", "none") != "none"),
        "compression": has(
            lambda o: o.get("compression", "none") != "none"
        ),
        "local_steps": has(lambda o: o.get("local_steps", 1) > 1),
        "participation": has(
            lambda o: o.get("participation_rate", 1.0) < 1.0
        ),
        "execution": has(lambda o: o.get("execution") == "async"),
        # ISSUE-17: the event clock carries a fault process — churn or
        # thinning composed WITH execution='async' in one valid cell.
        "async_faults": has(
            lambda o: o.get("execution") == "async" and (
                o.get("mttf", 0) > 0
                or o.get("participation_rate", 1.0) < 1.0
                or o.get("straggler_prob", 0) > 0
            )
        ),
        "replicas": has(lambda o: o.get("replicas", 1) > 1),
        "worker_mesh": has(lambda o: o.get("worker_mesh", 0) >= 2),
    }


def main() -> int:
    from distributed_optimization_tpu.scenarios import validity
    from distributed_optimization_tpu.scenarios.chaos import run_chaos_suite
    from distributed_optimization_tpu.scenarios.engine import run_scenarios
    from distributed_optimization_tpu.scenarios.generator import generate
    from distributed_optimization_tpu.scenarios.spec import parse_spec
    from distributed_optimization_tpu.telemetry import (
        provenance,
        write_bench_manifest,
    )
    from distributed_optimization_tpu.utils.profiling import PhaseTimer

    timer = PhaseTimer()

    # ---- 1. agreement: validity table vs construction -----------------
    with timer.phase("agreement"):
        sample = generate(parse_spec({
            "name": "agreement", "seed": 11, "mode": "sample",
            "sample": 700, "base": dict(BASE), "axes": agreement_axes(),
        }))
        divergences = [
            msg for cell in sample.cells
            if (msg := validity.cross_check(cell.fields)) is not None
        ]
        agreement = {
            "cells": len(sample.cells),
            "counts": sample.counts(),
            "divergences": divergences,
        }
    assert len(sample.cells) >= 500, "agreement sample too small"
    assert not divergences, divergences[:5]
    assert agreement["counts"]["valid"] >= 20
    print(
        f"[scenarios-bench] agreement: {agreement['cells']} cells, "
        f"{agreement['counts']['valid']} valid, 0 divergences"
    )

    # ---- 2. the golden matrix -----------------------------------------
    with timer.phase("matrix"):
        report = run_scenarios(parse_spec({
            "name": "golden-matrix", "seed": 12, "mode": "enumerate",
            "base": dict(BASE),
            "axes": {
                "learning_rate_eta0": [0.05, 0.08],
                "scenario": SCENARIOS,
            },
            # checkpoint_resume runs in its own small spec below: it
            # costs three segmented compiles per eligible cell, which at
            # 60+ cells would triple this bench's wall time for a claim
            # three representative cells already pin.
            "invariants": [
                "finite_gap", "gt_tracking", "robust_envelope",
                "bhat_degradation", "reduction_burst", "reduction_churn",
                "reduction_zero_budget", "reduction_explicit_defaults",
                "replica_cohort",
            ],
        }))
    coverage = axes_coverage(report)
    n_valid = report["counts"]["valid"]
    print(
        f"[scenarios-bench] matrix: {n_valid} valid cells, "
        f"{report['invariants']['checks']} checks, "
        f"{report['invariants']['failures']} failures, "
        f"{report['wall_seconds']:.1f}s"
    )
    assert n_valid >= 30, f"golden corpus needs >= 30 valid cells, {n_valid}"
    assert report["counts"]["rejected"] == 0, (
        "the golden spec is curated: every cell must be valid"
    )
    assert all(coverage.values()), f"axis coverage incomplete: {coverage}"
    assert report["gates"]["all_cells_completed"], report["cells"]
    assert report["gates"]["all_invariants_passed"], report["invariants"]
    assert report["gates"]["warm_replay_ok"], report["warm_replay"]
    assert report["serving"]["any_coalesced_cohort"]

    # ---- 3. checkpoint-resume cells ------------------------------------
    with timer.phase("checkpoint"):
        ck_report = run_scenarios(parse_spec({
            "name": "golden-checkpoint", "seed": 12, "mode": "enumerate",
            "base": dict(BASE),
            "axes": {"scenario": [
                {}, {"algorithm": "gradient_tracking"},
                {"edge_drop_prob": 0.2, "burst_len": 4.0},
            ]},
            "invariants": ["checkpoint_resume"],
        }))
    assert ck_report["gates"]["all_invariants_passed"], (
        ck_report["invariants"]
    )
    print("[scenarios-bench] checkpoint: 3 cells bitwise resume OK")

    # ---- 4. operational chaos ------------------------------------------
    with timer.phase("chaos"):
        chaos = run_chaos_suite()
    assert all(chaos["gates"].values()), chaos
    print(f"[scenarios-bench] chaos: {chaos['gates']}")

    # ---- artifact -------------------------------------------------------
    def compact(rows):
        out = []
        for r in rows:
            if not r.get("valid"):
                continue
            out.append({
                "overrides": r["overrides"],
                "structural_hash": r["structural_hash"],
                "cohort_size": (r.get("serving") or {}).get("cohort_size"),
                "invariants": {
                    i["name"]: i["passed"] for i in r.get("invariants", [])
                },
            })
        return out

    prov = provenance()
    payload = {
        "device": prov.get("device_kind"),
        "platform": "cpu",
        "protocol": (
            "agreement: seeded 700-cell sample over the weighted 10-axis "
            "bank, validity-table verdict vs ExperimentConfig "
            "construction, zero divergences required. matrix: the "
            "committed 35-composition × 2-eta golden spec served through "
            "SimulationService (coalescing + executable cache live), all "
            "applicable invariants asserted per cell, plus a warm replay "
            "of one structural class (bitwise + zero-compile required). "
            "checkpoint: 3 cells, interrupt+resume bitwise vs the "
            "equally-segmented uninterrupted run. chaos: poisoned "
            "cohort / daemon kill+restart / truncated checkpoint chunk / "
            "broken progress callback, graceful degradation asserted."
        ),
        "spec": {
            "base": BASE,
            "n_scenarios": len(SCENARIOS),
            "etas": [0.05, 0.08],
        },
        "agreement": {
            "cells": agreement["cells"],
            "valid": agreement["counts"]["valid"],
            "rejected": agreement["counts"]["rejected"],
            "rejected_by_rule": agreement["counts"]["rejected_by_rule"],
            "divergences": agreement["divergences"],
        },
        "matrix": {
            "counts": report["counts"],
            "invariants": report["invariants"],
            "serving": report["serving"],
            "warm_replay": report["warm_replay"],
            "cells": compact(report["cells"]),
        },
        "checkpoint": {
            "invariants": ck_report["invariants"],
        },
        "chaos": chaos,
        "gates": {
            "agreement_zero_divergences": not divergences,
            "agreement_cells": agreement["cells"],
            # The composition-closure number (ISSUE-17): the FIXED seeded
            # sample's valid fraction. Every deleted async rejection rule
            # moves cells from rejected to valid, so this committed
            # fraction must strictly increase whenever closure grows —
            # and must reproduce exactly on regen (perf-diff guarded).
            "agreement_valid_cells": agreement["counts"]["valid"],
            "agreement_valid_fraction": round(
                agreement["counts"]["valid"] / agreement["cells"], 4
            ),
            "matrix_n_valid_cells": n_valid,
            "matrix_axes_covered": all(coverage.values()),
            "matrix_all_cells_completed": report["gates"][
                "all_cells_completed"],
            "matrix_all_invariants_passed": report["gates"][
                "all_invariants_passed"],
            "matrix_warm_replay_ok": report["gates"]["warm_replay_ok"],
            "matrix_any_coalesced_cohort": report["serving"][
                "any_coalesced_cohort"],
            "checkpoint_bitwise_resume": ck_report["gates"][
                "all_invariants_passed"],
            **chaos["gates"],
        },
        "note": (
            "CPU-container corpus: the load-bearing content is the "
            "boolean gates (validity agreement, per-cell invariants, "
            "warm replay, chaos degradation) and the exact cell/axis "
            "counts — per-cell gap values are platform-deterministic "
            "but not cross-platform evidence. The worker-mesh cells run "
            "over 4 forced host devices (real ppermute halo exchange)."
        ),
    }
    OUT.parent.mkdir(parents=True, exist_ok=True)
    OUT.write_text(json.dumps(payload, indent=1, sort_keys=True) + "\n")
    print(f"wrote {OUT}")
    from distributed_optimization_tpu.config import ExperimentConfig

    write_bench_manifest(
        OUT, config=ExperimentConfig(**{**BASE}), phases=timer,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
