"""Sustained-load serving bench (the ISSUE-15 tentpole evidence).

Drives scenario-engine-sampled mixed traffic — eta/seed sweeps, faulty
and Byzantine structural classes — through the PRODUCTION serving
topology (HTTP daemon + multi-worker execution plane + persistent
executable store) and measures the four things the serving plane is for:

1. **Sustained latency** (``latency``): open-loop paced submits at a
   controlled rate; p50/p99 submit→result wall time, split by the
   manifest's own ``cache_hit`` flag (warm serves are the SLO surface;
   cold compiles of a not-yet-seen cohort shape are counted separately —
   mixed traffic legitimately contains them).
2. **Saturation throughput** (``saturation``): the full stream submitted
   closed-loop as fast as the wire accepts, requests/sec over the burst
   — gated against the PR-7 coalesced baseline
   (docs/perf/serving.json: 7.99 req/s), which a mixed-class stream
   through real worker processes must not regress.
3. **Admission control** (``shed`` + ``fairness``): a noisy tenant
   hammering a capped daemon gets machine-readable 429s (shed rate
   recorded, accepted work still completes); an adversarial tenant with
   a deep backlog cannot starve a victim tenant — the victim's p99
   under attack stays within a bounded factor of its solo p99
   (weighted-fair scheduling, ``cut_budget``).
4. **Restart warmness** (``restart``): a fresh service over the SAME
   store directory replays every structural-class representative with 0
   compile seconds and bitwise-identical objectives — the executables
   were serialized by the *worker processes*, so this is the
   cross-process store contract, not a same-process cache hit. (The
   full SIGKILL-subprocess variant is ``make serve-restart-smoke``.)

Plus the PR-7 parity gate re-checked through the worker plane: served
results (including the Byzantine and edge-dropping classes) match direct
in-process ``jax_backend.run`` to ≤ 1e-12 in float64.

Asserted floors (bench.py convention, BENCH_NO_RANGE_CHECK escape):
warm p99 submit→result ≤ 10 s (generous: this is a shared CPU
container; the committed value is the honest SLO surface and the
perf-diff checker envelopes it), saturation ≥ 7.99 req/s, victim p99
ratio ≤ 8×, restart replay 100% warm + bitwise, parity ≤ 1e-12.

Writes ``docs/perf/serving_load.json`` (+ manifest sidecar).

Usage: python examples/bench_serving_load.py [--out PATH]
         [--requests 360] [--rate 4.0] [--workers 2]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import numpy as np

FLOOR_SATURATION_RPS = 7.99   # PR-7 coalesced baseline (serving.json)
WARM_P99_CEILING_S = 10.0     # warm submit->result, shared CPU container
FAIR_RATIO_CEILING = 8.0      # victim p99 under attack vs solo
PARITY_TOL = 1e-12

BASE = {
    "n_workers": 8, "n_samples": 160, "n_features": 6,
    "n_informative_features": 4, "problem_type": "quadratic",
    "n_iterations": 40, "eval_every": 20, "local_batch_size": 8,
    "dtype": "float64",
}

# Structural-class axis: the distinct compiled programs mixed traffic
# cycles through. eta / seed / edge_drop_prob ride the SWEEPABLE axes
# (same program, coalescable); the attack / straggler / algorithm /
# topology entries are genuinely different programs.
STRUCTURE = [
    {}, {},
    {"algorithm": "gradient_tracking"},
    {"topology": "fully_connected"},
    {"attack": "sign_flip", "n_byzantine": 1,
     "aggregation": "trimmed_mean", "robust_b": 1,
     "partition": "shuffled"},
    {"straggler_prob": 0.15},
    {"edge_drop_prob": 0.2},
]


def _spec():
    from distributed_optimization_tpu.scenarios.spec import parse_spec

    return parse_spec({
        "name": "serving-load-traffic", "seed": 5, "mode": "sample",
        "sample": 60, "base": dict(BASE),
        "axes": {
            "structure": STRUCTURE,
            "eta": [{}, {"learning_rate_eta0": 0.08},
                    {"learning_rate_eta0": 0.12}],
            "seed": [{}, {"seed": 2}, {"seed": 3}],
        },
    })


def _class_reps():
    """One representative config per distinct structural class (the
    parity + restart-replay set)."""
    from distributed_optimization_tpu.config import ExperimentConfig

    seen, reps = set(), []
    for over in STRUCTURE:
        key = tuple(sorted(over.items()))
        if key in seen:
            continue
        seen.add(key)
        reps.append(ExperimentConfig(**{**BASE, **over}))
    return reps


def _pct(xs, q):
    return float(np.percentile(np.asarray(xs, dtype=np.float64), q))


def _submit_then_fetch(client, ex, cfg, *, tenant=None, priority=None,
                       timeout=600.0):
    """Submit now; fetch the result on the executor. Returns a future
    resolving to (latency_s, manifest)."""
    t0 = time.perf_counter()
    code, sub = client.submit(
        cfg.to_dict(), tenant=tenant, priority=priority,
    )
    assert code == 202, (code, sub)
    rid = sub["id"]

    def fetch():
        code, m = client.result(rid, timeout=timeout)
        assert code == 200, (code, m)
        return time.perf_counter() - t0, m

    return ex.submit(fetch)


def _paced(client, ex, configs, rate_hz, **kw):
    """Open-loop arrivals at ``rate_hz``; returns [(latency, manifest)]."""
    futs = []
    t_start = time.perf_counter()
    for i, cfg in enumerate(configs):
        target = t_start + i / rate_hz
        now = time.perf_counter()
        if target > now:
            time.sleep(target - now)
        futs.append(_submit_then_fetch(client, ex, cfg, **kw))
    return [f.result() for f in futs]


def _burst(client, ex, configs, **kw):
    """Closed-loop burst; returns (wall_s, [(latency, manifest)])."""
    t0 = time.perf_counter()
    futs = [_submit_then_fetch(client, ex, cfg, **kw) for cfg in configs]
    out = [f.result() for f in futs]
    return time.perf_counter() - t0, out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/perf/serving_load.json")
    ap.add_argument("--requests", type=int, default=360,
                    help="sustained/saturation stream length (the "
                         "sampled cells repeat cyclically)")
    ap.add_argument("--rate", type=float, default=4.0,
                    help="paced-phase arrival rate (requests/sec)")
    ap.add_argument("--workers", type=int, default=2,
                    help="worker processes behind the main daemon")
    args = ap.parse_args()

    import jax

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.scenarios.engine import sample_traffic
    from distributed_optimization_tpu.serving.cache import ExecutableCache
    from distributed_optimization_tpu.serving.client import RetryingClient
    from distributed_optimization_tpu.serving.daemon import ServingDaemon
    from distributed_optimization_tpu.serving.service import (
        ServingOptions,
        SimulationService,
    )
    from distributed_optimization_tpu.serving.store import (
        PersistentExecutableStore,
    )
    from distributed_optimization_tpu.utils.profiling import PhaseTimer

    dev = jax.devices()[0]
    platform = dev.platform
    print(f"[load] device={dev} platform={platform}", file=sys.stderr)
    timer = PhaseTimer()
    tmp = tempfile.mkdtemp(prefix="dopt-load-store-")
    # The single wiring point: spawned workers inherit it, so every
    # worker's process cache writes through to the one store.
    os.environ["DOPT_EXEC_STORE"] = tmp

    # ---- 0. traffic: scenario-engine-sampled mixed stream -------------
    with timer.phase("traffic"):
        cells = sample_traffic(_spec())
        stream = [cells[i % len(cells)] for i in range(args.requests)]
        reps = _class_reps()
    traffic = {
        "sampled_cells": len(cells),
        "structural_classes": len(reps),
        "requests": len(stream),
        "composition": "scenario sample over structure x eta x seed "
                       "(attack/straggler/edge-drop classes included), "
                       "repeated cyclically",
    }
    print(
        f"[load] traffic: {len(cells)} sampled cells -> "
        f"{len(stream)} requests over {len(reps)} structural classes",
        file=sys.stderr,
    )

    svc = SimulationService(
        ServingOptions(window_s=0.05, max_cohort=8, workers=args.workers),
        cache=ExecutableCache(store=PersistentExecutableStore(tmp)),
    )
    daemon = ServingDaemon("127.0.0.1", 0, service=svc)
    daemon.start()
    client = RetryingClient(daemon.url, max_retries=6, seed=0)
    ex = ThreadPoolExecutor(max_workers=64)
    rep_arrays = {}
    try:
        # ---- 1. warmup: class reps one-at-a-time, then one burst ------
        with timer.phase("warmup"):
            for i, cfg in enumerate(reps):
                code, sub = client.submit(cfg.to_dict())
                assert code == 202, (code, sub)
                code, m = client.result(sub["id"], timeout=600.0)
                assert code == 200, (code, m)
                # The full arrays (the wire manifest only carries the
                # final gap) — the daemon's service is in-process here.
                req = svc.result(sub["id"], timeout=30)
                rep_arrays[i] = req.result.history.objective.copy()
            _burst(client, ex, stream)
        st0 = svc.stats()
        print(
            f"[load] warmup: {st0['cache']['misses']} compiles, "
            f"{st0['cache'].get('store', {})} store",
            file=sys.stderr,
        )

        # ---- 2. sustained paced latency -------------------------------
        with timer.phase("sustained"):
            paced = _paced(client, ex, stream, args.rate)
        warm = [lat for lat, m in paced
                if m["health"]["serving"]["cache_hit"]]
        cold = [lat for lat, m in paced
                if not m["health"]["serving"]["cache_hit"]]
        assert warm, "no warm serves in the sustained phase"
        latency = {
            "rate_hz": args.rate,
            "requests": len(paced),
            "warm_requests": len(warm),
            "cold_requests": len(cold),
            "warm_p50_s": round(_pct(warm, 50), 4),
            "warm_p99_s": round(_pct(warm, 99), 4),
            "all_p50_s": round(_pct([l for l, _ in paced], 50), 4),
            "all_p99_s": round(_pct([l for l, _ in paced], 99), 4),
            "cold_p99_s": round(_pct(cold, 99), 4) if cold else None,
        }
        print(
            f"[load] sustained @ {args.rate}/s: warm p50 "
            f"{latency['warm_p50_s']}s p99 {latency['warm_p99_s']}s "
            f"({len(cold)} cold serves excluded from the SLO cell)",
            file=sys.stderr,
        )

        # ---- 3. saturation: closed-loop burst -------------------------
        misses_before = svc.stats()["cache"]["misses"]
        with timer.phase("saturation"):
            wall, done = _burst(client, ex, stream)
        sat_rps = len(done) / wall
        saturation = {
            "requests": len(done),
            "wall_s": round(wall, 2),
            "requests_per_s": round(sat_rps, 2),
            "cold_compiles_in_burst":
                svc.stats()["cache"]["misses"] - misses_before,
            "pr7_coalesced_baseline_rps": FLOOR_SATURATION_RPS,
            "saturation_loses": sat_rps < FLOOR_SATURATION_RPS,
        }
        print(
            f"[load] saturation: {len(done)} requests in {wall:.1f}s = "
            f"{sat_rps:.2f} req/s "
            f"({saturation['cold_compiles_in_burst']} cold compiles)",
            file=sys.stderr,
        )

        # ---- 4. parity through the worker plane -----------------------
        with timer.phase("parity"):
            max_dev = 0.0
            for i, cfg in enumerate(reps):
                ds, f_opt = svc.dataset_for(cfg)
                direct = jax_backend.run(
                    cfg, ds, f_opt, executable_cache=False,
                )
                max_dev = max(max_dev, float(np.max(np.abs(
                    rep_arrays[i] - direct.history.objective
                ))))
        assert max_dev <= PARITY_TOL, (
            f"served-vs-direct deviation {max_dev} through the worker "
            f"plane exceeds {PARITY_TOL}"
        )
        parity = {
            "classes": len(reps),
            "max_abs_deviation_f64": max_dev,
            "tol": PARITY_TOL,
            "includes": "byzantine (sign_flip/trimmed_mean) and "
                        "edge-drop/straggler classes",
        }
        print(f"[load] parity: max dev {max_dev:.2e} (f64)", file=sys.stderr)
    finally:
        try:
            client.shutdown()
        except Exception:
            pass
        daemon.stop()
        ex.shutdown(wait=False)

    # ---- 5. shed: per-tenant caps under a hammering tenant ------------
    with timer.phase("shed"):
        shed_svc = SimulationService(
            ServingOptions(window_s=0.0, max_cohort=1,
                           max_pending_per_tenant=6),
            cache=ExecutableCache(store=PersistentExecutableStore(tmp)),
        )
        shed_daemon = ServingDaemon("127.0.0.1", 0, service=shed_svc)
        shed_daemon.start()
        try:
            raw = RetryingClient(shed_daemon.url, max_retries=0)
            # A structural class nothing warmed: its compile is the
            # plug that lets the noisy tenant's backlog build.
            from distributed_optimization_tpu.config import ExperimentConfig

            plug = ExperimentConfig(**{**BASE, "topology": "star"})
            accepted, sheds = [], 0
            for i in range(30):
                code, body = raw._once(
                    "POST", "/v1/submit",
                    {"config": plug.replace(seed=10 + i).to_dict(),
                     "tenant": "noisy"},
                    30.0,
                )
                if code == 202:
                    accepted.append(body["id"])
                else:
                    assert code == 429 and body["reason"] == "tenant_cap", (
                        code, body,
                    )
                    sheds += 1
            assert sheds > 0, "the tenant cap never shed"
            # Accepted work still completes — shedding protects the
            # queue, it does not poison it.
            for rid in accepted:
                code, m = raw.result(rid, timeout=600.0)
                assert code == 200, (code, m)
            scrape = raw.metrics_text()
            assert "dopt_serving_shed_total" in scrape
        finally:
            shed_daemon.stop()
    shed = {
        "attempts": 30,
        "accepted": len(accepted),
        "tenant_cap_sheds": sheds,
        "shed_rate": round(sheds / 30.0, 3),
        "tenant_cap": 6,
    }
    print(
        f"[load] shed: {sheds}/30 submits shed at cap 6, "
        f"{len(accepted)} accepted all completed",
        file=sys.stderr,
    )

    # ---- 6. fairness: adversarial tenant vs paced victim --------------
    with timer.phase("fairness"):
        fair_svc = SimulationService(
            ServingOptions(window_s=0.0, max_cohort=1, cut_budget=2),
            cache=ExecutableCache(store=PersistentExecutableStore(tmp)),
        )
        fair_daemon = ServingDaemon("127.0.0.1", 0, service=fair_svc)
        fair_daemon.start()
        fex = ThreadPoolExecutor(max_workers=96)
        try:
            fc = RetryingClient(fair_daemon.url, max_retries=6, seed=1)
            victim_cfgs = [reps[0].replace(seed=40 + i) for i in range(8)]
            adversary_cfgs = [reps[1].replace(seed=60 + i)
                              for i in range(60)]
            # Warm both classes' R=1 programs (store hits, no compile).
            fc.run(victim_cfgs[0].to_dict(), timeout=600.0)
            fc.run(adversary_cfgs[0].to_dict(), timeout=600.0)

            solo = _paced(fc, fex, victim_cfgs, 0.8, tenant="victim")
            solo_p99 = _pct([l for l, _ in solo], 99)

            adv_futs = [
                _submit_then_fetch(fc, fex, cfg, tenant="adversary")
                for cfg in adversary_cfgs
            ]
            attacked = _paced(fc, fex, victim_cfgs, 0.8, tenant="victim")
            attacked_p99 = _pct([l for l, _ in attacked], 99)
            for f in adv_futs:  # adversary work still completes
                f.result()
        finally:
            fair_daemon.stop()
            fex.shutdown(wait=False)
    ratio = attacked_p99 / solo_p99
    fairness = {
        "victim_requests": len(victim_cfgs),
        "adversary_backlog": len(adversary_cfgs),
        "victim_solo_p99_s": round(solo_p99, 4),
        "victim_attacked_p99_s": round(attacked_p99, 4),
        "victim_p99_ratio": round(ratio, 2),
        "ratio_ceiling": FAIR_RATIO_CEILING,
        "fairness_loses": ratio > FAIR_RATIO_CEILING,
    }
    print(
        f"[load] fairness: victim p99 {solo_p99:.2f}s solo vs "
        f"{attacked_p99:.2f}s under a {len(adversary_cfgs)}-deep "
        f"adversary ({ratio:.1f}x)",
        file=sys.stderr,
    )

    # ---- 7. restart: fresh process-state over the same store ----------
    with timer.phase("restart"):
        cache_r = ExecutableCache(store=PersistentExecutableStore(tmp))
        restart_svc = SimulationService(
            ServingOptions(window_s=0.0), cache=cache_r,
        )
        warm_replays, bitwise = 0, True
        for i, cfg in enumerate(reps):
            rid = restart_svc.submit(cfg)
            restart_svc.drain()
            req = restart_svc.result(rid, timeout=600)
            if (req.cache_hit
                    and req.result.history.compile_seconds == 0.0):
                warm_replays += 1
            if not np.array_equal(
                req.result.history.objective, rep_arrays[i]
            ):
                bitwise = False
        store_stats = cache_r.stats().get("store", {})
    shutil.rmtree(tmp, ignore_errors=True)
    warm_ratio = warm_replays / len(reps)
    assert warm_ratio == 1.0, (
        f"only {warm_replays}/{len(reps)} classes replayed warm from "
        "the store after a restart"
    )
    assert bitwise, "restart replay is not bitwise vs the served run"
    restart = {
        "classes": len(reps),
        "warm_replays": warm_replays,
        "warm_ratio": warm_ratio,
        "bitwise": bitwise,
        "store": {k: (round(v, 3) if isinstance(v, float) else v)
                  for k, v in store_stats.items()},
        "subprocess_variant": "make serve-restart-smoke "
                              "(SIGKILL + new process, same gate)",
    }
    print(
        f"[load] restart: {warm_replays}/{len(reps)} classes warm from "
        f"the store, bitwise={bitwise}",
        file=sys.stderr,
    )

    # ---- asserted floors (BENCH_NO_RANGE_CHECK escape hatch) ----------
    skip = os.environ.get("BENCH_NO_RANGE_CHECK", "").lower() not in (
        "", "0", "false"
    )
    if skip:
        print(
            "[load] BENCH_NO_RANGE_CHECK set: skipping the floor gates "
            "(non-canonical hardware mode)",
            file=sys.stderr,
        )
    else:
        assert latency["warm_p99_s"] <= WARM_P99_CEILING_S, (
            f"warm p99 {latency['warm_p99_s']}s exceeds the "
            f"{WARM_P99_CEILING_S}s ceiling"
        )
        assert sat_rps >= FLOOR_SATURATION_RPS, (
            f"saturation {sat_rps:.2f} req/s is below the PR-7 "
            f"coalesced baseline {FLOOR_SATURATION_RPS} req/s"
        )
        assert ratio <= FAIR_RATIO_CEILING, (
            f"victim p99 degrades {ratio:.1f}x under the adversary "
            f"(ceiling {FAIR_RATIO_CEILING}x) — fairness regressed"
        )
    gates = {
        "applied": not skip,
        "warm_p99_ceiling_s": WARM_P99_CEILING_S,
        "measured_warm_p99_s": latency["warm_p99_s"],
        "saturation_floor_rps": FLOOR_SATURATION_RPS,
        "measured_saturation_rps": saturation["requests_per_s"],
        "fairness_ratio_ceiling": FAIR_RATIO_CEILING,
        "measured_fairness_ratio": fairness["victim_p99_ratio"],
        "restart_all_warm": warm_ratio == 1.0,
        "restart_bitwise": bitwise,
        "shed_observed": sheds > 0,
        "parity_max_abs_deviation_f64": max_dev,
    }

    payload = {
        "device": str(dev),
        "platform": platform,
        "protocol": (
            "Mixed traffic sampled from a scenario spec (structure x eta "
            "x seed; Byzantine, straggler and edge-drop classes "
            f"included) through ServingDaemon with {args.workers} worker "
            "processes and a persistent executable store. latency: "
            f"open-loop paced submits at {args.rate}/s, p50/p99 "
            "submit->result split by the manifest's cache_hit flag. "
            "saturation: the same stream closed-loop, req/s gated "
            "against docs/perf/serving.json's coalesced baseline. shed: "
            "a noisy tenant at a 6-deep per-tenant cap, 429 reason "
            "asserted, accepted work completing. fairness: a 60-deep "
            "adversarial backlog vs an 8-request paced victim on a "
            "cut_budget=2 weighted-fair scheduler, victim p99 ratio "
            "bounded. restart: a fresh service over the same store "
            "replays every structural class with 0 compile seconds, "
            "bitwise. parity: served (worker-plane) vs direct run, f64."
        ),
        "note": (
            "CPU-container numbers: wall-clock cells (latencies, req/s) "
            "are envelope-checked, not pinned — the load-bearing "
            "evidence is the boolean gates (restart warm+bitwise, shed "
            "observed, fairness bounded, parity) plus the committed "
            "floor constants. The warm-p99 SLO cell excludes cold "
            "serves honestly surfaced by mixed traffic (counted in "
            "cold_requests); saturation_loses / fairness_loses flag "
            "any measured inversion per repo convention."
        ),
        "traffic": traffic,
        "latency": latency,
        "saturation": saturation,
        "shed": shed,
        "fairness": fairness,
        "restart": restart,
        "parity": parity,
        "gates": gates,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(
        path, config=ExperimentConfig(**BASE), phases=timer,
    )

    print(json.dumps({
        "metric": "serving_load_warm_p99_and_saturation",
        "warm_p99_s": latency["warm_p99_s"],
        "saturation_rps": saturation["requests_per_s"],
        "fairness_ratio": fairness["victim_p99_ratio"],
        "restart_warm_ratio": warm_ratio,
    }))


if __name__ == "__main__":
    main()
