"""``make scenarios-smoke``: the scenario engine + chaos harness, CI-sized.

The fast end-to-end check of ISSUE-12 (docs/SCENARIOS.md):

1. a seeded PROPERTY SAMPLE over a mixed axis bank — validity-table
   verdicts must agree with config construction on every drawn cell
   (the generator enforces it; a divergence aborts loudly);
2. the sample's valid cells run through the serving layer with the full
   auto-selected invariant catalog minus the slow checkpoint one, plus
   the warm-replay identity — all gates must pass;
3. ONE operational chaos cycle: the daemon kill/restart mode (submit,
   abrupt kill, restart over the same executable cache, warm
   re-serve via the retrying client).

Exit code 0 = all gates passed.
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from distributed_optimization_tpu.scenarios.chaos import (
        chaos_daemon_kill_restart,
    )
    from distributed_optimization_tpu.scenarios.engine import run_scenarios
    from distributed_optimization_tpu.scenarios.spec import parse_spec

    spec = parse_spec({
        # sample == the matrix size: the seeded sampler walks the whole
        # (small) matrix in draw order — still the property-sampling code
        # path, with every assertion below deterministic.
        "name": "scenarios-smoke", "seed": 7, "mode": "sample",
        "sample": 14,
        "base": {
            "n_workers": 8, "n_samples": 300, "n_features": 8,
            "n_informative_features": 5, "n_iterations": 60,
            "eval_every": 20, "local_batch_size": 8, "dtype": "float64",
        },
        "axes": {
            "learning_rate_eta0": [0.05, 0.08],
            "scenario": [
                {},
                {"algorithm": "gradient_tracking"},
                {"edge_drop_prob": 0.2},
                {"straggler_prob": 0.15},
                {"attack": "sign_flip", "n_byzantine": 1,
                 "aggregation": "trimmed_mean", "robust_b": 1,
                 "partition": "shuffled"},
                {"replicas": 3},
                # One INVALID composition on purpose: the smoke must see
                # the validity table reject (and agree with construction).
                {"algorithm": "extra", "local_steps": 4},
            ],
        },
        "invariants": [
            "finite_gap", "gt_tracking", "robust_envelope",
            "bhat_degradation", "reduction_burst", "reduction_churn",
            "reduction_explicit_defaults", "replica_cohort",
        ],
    })
    report = run_scenarios(spec)
    counts = report["counts"]
    print(
        f"[scenarios-smoke] {counts['cells']} cells sampled: "
        f"{counts['valid']} valid, {counts['rejected']} rejected "
        f"({list(counts['rejected_by_rule'])}), "
        f"{report['invariants']['checks']} invariant checks, "
        f"{report['invariants']['failures']} failures",
        file=sys.stderr,
    )
    assert counts["rejected"] >= 1, (
        "the smoke spec plants an invalid composition; the sampler "
        "missed it"
    )
    assert counts["rejected_by_rule"].get("local_steps×algorithm"), (
        counts["rejected_by_rule"]
    )
    assert all(report["gates"].values()), report["gates"]

    record = chaos_daemon_kill_restart()
    print(
        f"[scenarios-smoke] chaos kill/restart: warm resubmit "
        f"cache_hit={record.detail.get('resubmit_cache_hit')} "
        f"compile={record.detail.get('resubmit_compile_seconds')}s",
        file=sys.stderr,
    )
    assert record.passed, record.detail
    print("[scenarios-smoke] PASS")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
