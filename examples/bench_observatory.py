"""Live-observatory overhead bench (ISSUE-10 headline artifact;
docs/OBSERVABILITY.md).

Progress streaming must be cheap enough to leave on for served traffic:
with a callback installed the fused scan executes as SEGMENTS of the same
compiled program split at eval boundaries (one host sync + one callback
per heartbeat — the trajectory itself is bitwise-unchanged, asserted here
end to end). This bench measures that cost on the interleaved-cycles
protocol the other benches use, plus the ``/metrics`` scrape cost under a
serving daemon with live work:

- HEARTBEAT cell: D-SGD ring N=32 d=40, T=3000, eval_every=50 — progress
  off vs on at ``progress_every=15`` (4 heartbeats/run), 3 interleaved
  cycles, median steady-state iters/sec per arm. Asserted: overhead ≤
  OVERHEAD_CEILING (3%, the PR 5 telemetry convention) and off/on
  objective bitwise equality. The finer cadences are recorded
  UNASSERTED for honesty: every-5-evals measured ~4% and every-eval
  ~14% on this container — each segment boundary costs one host
  dispatch+sync (~1 ms here), which is pure latency this single-core
  CPU cannot hide; pick the cadence for the run length (the serving
  default is 5).
- ASYNC cell: the event path's heartbeats (staleness quantiles
  included) at ``progress_every=6`` — 4 heartbeats/run over 24
  eval chunks (T=1200), the
  heartbeat-cell protocol. Since ISSUE-13 the async progress path
  executes as fused outer-scan SEGMENTS split at heartbeat boundaries
  (one host sync per heartbeat, not per eval chunk — the original
  per-chunk loop measured an honest ``overhead_ok: false`` at 12.3%
  here), so the cell now carries a REAL asserted gate:
  ``ASYNC_OVERHEAD_CEILING`` (5%).
- SCRAPE cell: boot the serving daemon, keep a request in flight, and
  measure ``GET /metrics`` latency (p50/p95 over 50 scrapes) — the
  consistent-snapshot lock must not make scrapes expensive. Asserted
  p95 ≤ SCRAPE_P95_CEILING_MS.

Writes ``docs/perf/observatory.json`` + provenance sidecar; registered in
the drift guard and ``examples/regen_perf_artifacts.sh``; ``make
perf-diff`` re-checks regenerated copies against the committed one.

Usage:  python examples/bench_observatory.py [--out PATH] [--cycles 3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OVERHEAD_CEILING = 0.03       # asserted heartbeat-on steady-state overhead
# The async cell's asserted ceiling (ISSUE-13 satellite): looser than the
# sequential cell's because each async heartbeat also computes staleness
# percentiles over the executed window, but a HARD gate — the segment-
# fused execution replaced the per-chunk host loop that forced the old
# honest overhead_ok=false at 12.3%.
ASYNC_OVERHEAD_CEILING = 0.05
SCRAPE_P95_CEILING_MS = 100.0  # asserted /metrics p95 under live load


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/perf/observatory.json")
    ap.add_argument("--cycles", type=int, default=3)
    args = ap.parse_args()

    import jax
    import numpy as np

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.telemetry import write_bench_manifest
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import (
        compute_reference_optimum,
    )
    from distributed_optimization_tpu.utils.profiling import PhaseTimer

    timer = PhaseTimer()
    base = ExperimentConfig(
        n_workers=32, n_samples=3200, n_features=40,
        n_informative_features=20, problem_type="quadratic",
        algorithm="dsgd", topology="ring", n_iterations=3000,
        eval_every=50, local_batch_size=32,
    )
    with timer.phase("data_gen"):
        ds = generate_synthetic_dataset(base)
    with timer.phase("oracle"):
        _, f_opt = compute_reference_optimum(ds, base.reg_param)

    skip = os.environ.get("BENCH_NO_RANGE_CHECK", "").lower() not in (
        "", "0", "false"
    )

    def _noop(_ev):
        pass

    # ---------------------------------------------- heartbeat overhead cell
    with timer.phase("heartbeat"):
        ips = {"off": [], "on": [], "on_every5": [], "on_every_eval": []}
        last = {}
        arms = (
            ("off", {}),
            ("on", {"progress_cb": _noop, "progress_every": 15}),
            ("on_every5", {"progress_cb": _noop, "progress_every": 5}),
            ("on_every_eval", {"progress_cb": _noop, "progress_every": 1}),
        )
        for _ in range(args.cycles):
            for arm, kw in arms:
                r = jax_backend.run(base, ds, f_opt, **kw)
                ips[arm].append(r.history.iters_per_second)
                last[arm] = r
        off = float(np.median(ips["off"]))
        on = float(np.median(ips["on"]))
        on5 = float(np.median(ips["on_every5"]))
        on1 = float(np.median(ips["on_every_eval"]))
        overhead = max(0.0, 1.0 - on / off)
        bitwise = all(
            np.array_equal(
                last["off"].history.objective, last[arm].history.objective
            )
            for arm in ("on", "on_every5", "on_every_eval")
        )
        heartbeat = {
            "ips_off_median": off,
            "ips_on_median": on,
            "ips_on_every5_median": on5,
            "ips_on_every_eval_median": on1,
            "ips_off_raw": [float(v) for v in ips["off"]],
            "ips_on_raw": [float(v) for v in ips["on"]],
            "overhead_frac": overhead,
            "overhead_frac_every5": max(0.0, 1.0 - on5 / off),
            "overhead_frac_every_eval": max(0.0, 1.0 - on1 / off),
            "overhead_ok": overhead <= OVERHEAD_CEILING,
            "off_on_bitwise_objective": bool(bitwise),
            "progress_every": 15,
            "heartbeats_per_run": 4,
        }
        assert bitwise, (
            "progress streaming perturbed the trajectory — the segmented "
            "execution is supposed to be bitwise the one-shot program"
        )
        if not skip:
            assert overhead <= OVERHEAD_CEILING, (
                f"heartbeat overhead {overhead:.1%} exceeds the "
                f"{OVERHEAD_CEILING:.0%} ceiling (set BENCH_NO_RANGE_CHECK=1 "
                "on non-canonical hardware)"
            )

    # -------------------------------------------------------- async cell
    with timer.phase("async"):
        acfg = base.replace(
            execution="async", latency_model="exponential",
            latency_mean=1.0, n_iterations=1200, eval_every=50,
        )
        a_ips = {"off": [], "on": []}
        a_last = {}
        for _ in range(args.cycles):
            for arm, kw in (
                ("off", {}),
                ("on", {"progress_cb": _noop, "progress_every": 6}),
            ):
                r = jax_backend.run(acfg, ds, f_opt, **kw)
                a_ips[arm].append(r.history.iters_per_second)
                a_last[arm] = r
        a_off = float(np.median(a_ips["off"]))
        a_on = float(np.median(a_ips["on"]))
        a_overhead = max(0.0, 1.0 - a_on / a_off)
        a_bitwise = bool(np.array_equal(
            a_last["off"].history.objective, a_last["on"].history.objective
        ))
        assert a_bitwise, "async progress perturbed the trajectory"
        async_cell = {
            "ips_off_median": a_off,
            "ips_on_median": a_on,
            "overhead_frac": a_overhead,
            # A REAL gate since ISSUE-13 (segment-fused execution): one
            # host sync per heartbeat, 4 heartbeats over 12 eval chunks.
            "overhead_ok": a_overhead <= ASYNC_OVERHEAD_CEILING,
            "off_on_bitwise_objective": a_bitwise,
            "progress_every": 6,
        }
        if not skip:
            assert a_overhead <= ASYNC_OVERHEAD_CEILING, (
                f"async heartbeat overhead {a_overhead:.1%} exceeds the "
                f"{ASYNC_OVERHEAD_CEILING:.0%} ceiling (set "
                "BENCH_NO_RANGE_CHECK=1 on non-canonical hardware)"
            )

    # ----------------------------------------------- /metrics scrape cell
    with timer.phase("scrape"):
        import threading
        import urllib.request

        from distributed_optimization_tpu.serving.cache import ExecutableCache
        from distributed_optimization_tpu.serving.daemon import ServingDaemon
        from distributed_optimization_tpu.serving.service import (
            ServingOptions,
            SimulationService,
        )

        opts = ServingOptions(window_s=0.01)
        daemon = ServingDaemon(
            "127.0.0.1", 0, opts,
            service=SimulationService(opts, cache=ExecutableCache()),
        )
        daemon.start()
        url = daemon.url
        try:
            # Keep the daemon busy: a background submitter feeds runs while
            # the scrape loop measures.
            stop = threading.Event()

            def _feed():
                i = 0
                while not stop.is_set():
                    body = json.dumps(
                        base.replace(
                            n_iterations=1000,
                            learning_rate_eta0=0.05 + 0.001 * (i % 5),
                        ).to_dict()
                    ).encode()
                    req = urllib.request.Request(
                        url + "/v1/run?timeout=120", data=body,
                        headers={"Content-Type": "application/json"},
                        method="POST",
                    )
                    try:
                        urllib.request.urlopen(req, timeout=120).read()
                    except Exception:
                        return
                    i += 1

            feeder = threading.Thread(target=_feed, daemon=True)
            feeder.start()
            time.sleep(0.5)  # let work start
            lat_ms = []
            for _ in range(50):
                t0 = time.perf_counter()
                with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
                    body = r.read()
                lat_ms.append((time.perf_counter() - t0) * 1e3)
            stop.set()
            text = body.decode()
            scrape = {
                "n_scrapes": len(lat_ms),
                "p50_ms": float(np.percentile(lat_ms, 50)),
                "p95_ms": float(np.percentile(lat_ms, 95)),
                "max_ms": float(max(lat_ms)),
                "families_exposed": sum(
                    1 for ln in text.splitlines() if ln.startswith("# TYPE")
                ),
                "cache_counters_present": (
                    "dopt_exec_cache_hits_total" in text
                ),
                "progress_counters_present": (
                    "dopt_progress_heartbeats_total" in text
                ),
            }
            if not skip:
                assert scrape["p95_ms"] <= SCRAPE_P95_CEILING_MS, (
                    f"/metrics p95 {scrape['p95_ms']:.1f} ms exceeds the "
                    f"{SCRAPE_P95_CEILING_MS:.0f} ms ceiling"
                )
            assert scrape["cache_counters_present"], (
                "/metrics is missing the executable-cache counter family"
            )
        finally:
            daemon.stop()

    gates = {
        "overhead_ceiling": OVERHEAD_CEILING,
        "async_overhead_ceiling": ASYNC_OVERHEAD_CEILING,
        "scrape_p95_ceiling_ms": SCRAPE_P95_CEILING_MS,
        "heartbeat_within_ceiling": heartbeat["overhead_ok"],
        "async_within_ceiling": async_cell["overhead_ok"],
        "off_on_bitwise_objective": (
            heartbeat["off_on_bitwise_objective"]
            and async_cell["off_on_bitwise_objective"]
        ),
        "scrape_within_ceiling": scrape["p95_ms"] <= SCRAPE_P95_CEILING_MS,
    }
    payload = {
        "device": str(jax.devices()[0]),
        "platform": jax.devices()[0].platform,
        "protocol": (
            f"N=32 d=40 ring quadratic T=3000 eval_every=50; progress off "
            f"vs on (progress_every=15 -> 4 heartbeats/run asserted; "
            f"every-5 and every-eval arms recorded unasserted) interleaved "
            f"x{args.cycles} cycles, median steady-state iters/sec; async "
            "cell T=1200 events path at progress_every=6 (segment-fused, "
            "≤5% asserted); /metrics p50/p95 over 50 scrapes against a "
            "daemon with a background submitter keeping cohorts in flight"
        ),
        "note": (
            "Progress on executes the SAME compiled scan as segments split "
            "at eval boundaries (continuation machinery), so trajectories "
            "are asserted bitwise off==on; the cost is one host sync + "
            "callback per heartbeat. The async cell runs the ISSUE-13 "
            "segment-fused form (segments of progress_every chunks per "
            "compiled call) — the old per-chunk host loop's honest "
            "overhead_ok=false at 12.3% is replaced by a real ≤5% gate. "
            "Scrapes render the whole registry under one lock "
            "(consistent snapshot)."
        ),
        "heartbeat": heartbeat,
        "async": async_cell,
        "scrape": scrape,
        "gates": gates,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    write_bench_manifest(path, config=base, phases=timer)
    print(json.dumps({
        "metric": "heartbeat_overhead_frac",
        "value": heartbeat["overhead_frac"],
        "scrape_p95_ms": scrape["p95_ms"],
    }))


if __name__ == "__main__":
    main()
