"""Temporally-correlated-failure demonstration (ISSUE 2 headline artifact;
docs/CHURN.md).

The iid fault model answers "how many edges fail?"; the time-varying-gossip
rates (Koloskova et al. '20) depend on "how long can the network stay
effectively partitioned?" — windowed union-graph connectivity, B̂.  This
bench pins the difference with a matched-marginal burst sweep plus a
crash-recovery churn study:

- BURST SWEEP: D-SGD, ring N=16, per-edge drop rate FIXED at p=0.3 while
  the Gilbert-Elliott mean burst length sweeps 1x/4x/16x/48x the iid
  chain's.  Asserted: (a) ``burst_len=1`` matches the iid-fault baseline
  trajectory BITWISE (same draws, same thresholds, different code path);
  (b) consensus error degrades MONOTONELY with burst length at the same
  marginal drop rate; (c) the measured B̂ diagnostic grows monotonely with
  burst length — the mechanism behind (b).
- CHURN + GT INVARIANT: gradient tracking under crash-recovery churn
  (MTTF/MTTR holding times) composed with bursty links, float64, frozen
  rejoin.  Asserted: the tracking invariant mean(y) = mean(g_prev) holds
  to accumulation roundoff through whole outages — staleness does not
  break the bias correction.
- REJOIN POLICY: D-SGD under rare-but-long outages (MTTF 400, MTTR 150
  rounds), ``frozen`` vs ``neighbor_restart`` on the SAME fault timeline.
  Asserted: the warm restart ends at-or-below the stale-state policy's
  consensus error after the outages.

Writes ``docs/perf/churn.json`` (trajectories, availability/staleness
diagnostics, B̂ per burst level, all gate outcomes).

Usage:  python examples/bench_churn.py [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/perf/churn.json")
    args = ap.parse_args()

    import jax
    import numpy as np

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.parallel import build_topology
    from distributed_optimization_tpu.parallel.faults import (
        build_fault_timeline,
        node_downtime,
        outage_stats,
        windowed_connectivity,
    )
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import (
        compute_reference_optimum,
    )

    base = ExperimentConfig(
        problem_type="quadratic", algorithm="dsgd", topology="ring",
        n_workers=16, n_samples=1600, n_features=10,
        n_informative_features=6, n_iterations=3000, local_batch_size=16,
        eval_every=100,
    )
    P = 0.3  # matched marginal per-edge drop rate for the whole sweep
    BURSTS = (1.0, 4.0, 16.0, 48.0)

    ds = generate_synthetic_dataset(base)
    _, f_opt = compute_reference_optimum(ds, base.reg_param)
    topo = build_topology(base.topology, base.n_workers)

    results: dict[str, dict] = {}

    def record(name, cfg, r):
        h = r.history
        results[name] = {
            "final_gap": round(float(h.objective[-1]), 8),
            "mean_consensus": round(
                float(np.mean(h.consensus_error)), 10
            ),
            "final_consensus": round(
                float(h.consensus_error[-1]), 10
            ),
            "realized_floats": float(h.total_floats_transmitted),
            "objective": [round(float(v), 8) for v in h.objective],
            "consensus": [
                round(float(v), 10) for v in h.consensus_error
            ],
        }
        print(
            f"[churn] {name:22s} gap {results[name]['final_gap']:.2e}  "
            f"mean-cons {results[name]['mean_consensus']:.3e}",
            file=sys.stderr,
        )
        return results[name]

    # --- burst sweep at matched marginal drop rate -----------------------
    iid = record("iid_p03", base, jax_backend.run(
        base.replace(edge_drop_prob=P), ds, f_opt
    ))
    bhat = {}
    for B in BURSTS:
        cfg = base.replace(edge_drop_prob=P, burst_len=B)
        row = record(f"burst_{B:g}", cfg, jax_backend.run(cfg, ds, f_opt))
        tl = build_fault_timeline(
            topo, base.n_iterations, base.seed, edge_drop_prob=P,
            burst_len=B,
        )
        row["marginal_drop_rate"] = round(float(1.0 - tl.edge_up.mean()), 5)
        row["windowed_connectivity_Bhat"] = windowed_connectivity(tl, topo)
        bhat[B] = row["windowed_connectivity_Bhat"]

    # Gate 1: burst_len=1 is the iid baseline, bitwise (timeline path vs
    # the on-the-fly sampler path — same draws, same thresholds).
    assert results["burst_1"]["objective"] == results["iid_p03"]["objective"]
    assert results["burst_1"]["consensus"] == results["iid_p03"]["consensus"]
    assert (
        results["burst_1"]["realized_floats"]
        == results["iid_p03"]["realized_floats"]
    ), "matched marginal must also match realized comms"

    # Gate 2: monotone degradation with burst length at MATCHED marginal —
    # the iid model's blind spot, measured.
    cons = [results[f"burst_{B:g}"]["mean_consensus"] for B in BURSTS]
    assert all(a < b for a, b in zip(cons, cons[1:])), (
        f"consensus error must degrade monotonely with burst length: {cons}"
    )
    gaps = [results[f"burst_{B:g}"]["final_gap"] for B in BURSTS]
    assert all(a < b for a, b in zip(gaps, gaps[1:])), (
        f"final gap must degrade monotonely with burst length: {gaps}"
    )
    # Marginal drop rate stays matched across the sweep (within sampling
    # noise), so the degradation is attributable to correlation alone.
    for B in BURSTS:
        assert abs(
            results[f"burst_{B:g}"]["marginal_drop_rate"] - P
        ) < 0.02, B
    # The mechanism: windowed connectivity B̂ grows with burstiness.
    bvals = [bhat[B] for B in BURSTS]
    assert all(a <= b for a, b in zip(bvals, bvals[1:])) and bvals[0] < bvals[-1], (
        f"B-hat must grow with burst length: {bvals}"
    )

    # --- churn: GT tracking invariant through whole outages --------------
    gt_cfg = base.replace(
        algorithm="gradient_tracking", lr_schedule="constant",
        learning_rate_eta0=0.02, dtype="float64", n_iterations=1000,
        eval_every=100, edge_drop_prob=0.2, burst_len=8.0,
        mttf=60.0, mttr=25.0,
    )
    r_gt = jax_backend.run(gt_cfg, ds, f_opt, return_state=True)
    gt_row = record("gt_churn_frozen", gt_cfg, r_gt)
    resid = float(np.abs(
        r_gt.final_state["y"].mean(axis=0)
        - r_gt.final_state["g_prev"].mean(axis=0)
    ).max())
    gt_row["tracking_invariant_residual"] = resid
    tl_gt = build_fault_timeline(
        topo, gt_cfg.n_iterations, gt_cfg.seed, edge_drop_prob=0.2,
        burst_len=8.0, mttf=60.0, mttr=25.0,
    )
    gt_row["node_downtime"] = [round(float(v), 4) for v in
                               node_downtime(tl_gt)]
    gt_row["outages"] = outage_stats(tl_gt)
    # Gate 3: the invariant survives churn with frozen rejoin.
    assert gt_row["outages"]["n_outages"] > 0, "churn produced no outages"
    assert resid < 1e-9, (
        f"GT tracking invariant must survive churn (residual {resid:.2e})"
    )

    # --- rejoin policy after long outages --------------------------------
    outage_cfg = base.replace(
        n_iterations=2000, eval_every=100, mttf=400.0, mttr=150.0,
    )
    frozen = record("outage_frozen", outage_cfg,
                    jax_backend.run(outage_cfg, ds, f_opt))
    restart_cfg = outage_cfg.replace(rejoin="neighbor_restart")
    restart = record("outage_neighbor_restart", restart_cfg,
                     jax_backend.run(restart_cfg, ds, f_opt))
    tl_out = build_fault_timeline(
        topo, outage_cfg.n_iterations, outage_cfg.seed, mttf=400.0,
        mttr=150.0,
    )
    stats = outage_stats(tl_out)
    frozen["outages"] = restart["outages"] = stats
    # Gate 4: after long outages, the warm restart ends at-or-below the
    # stale-state policy's consensus error.
    assert stats["max_outage_rounds"] >= 50, (
        "seed produced no long outage; the comparison would be vacuous"
    )
    assert (
        restart["final_consensus"] <= frozen["final_consensus"]
    ), (
        f"neighbor_restart ({restart['final_consensus']:.3e}) must end "
        f"<= frozen ({frozen['final_consensus']:.3e}) after long outages"
    )

    payload = {
        "device": str(jax.devices()[0]),
        "config": (
            f"quadratic N=16 ring T=3000; matched marginal edge drop "
            f"p={P}, Gilbert-Elliott burst sweep x{BURSTS}; GT churn "
            "mttf=60/mttr=25 (f64, frozen); rejoin study mttf=400/mttr=150"
        ),
        "note": (
            "Matched-marginal burst sweep: every burst level drops the "
            "same ~30% of edge-rounds, yet consensus error degrades "
            "monotonely with burst length because the windowed-union-"
            "connectivity diagnostic B-hat (the quantity the time-varying-"
            "gossip rates actually depend on) stretches with correlation. "
            "burst_1 is asserted bitwise-equal to the iid baseline; the "
            "GT tracking invariant is asserted to survive crash-recovery "
            "churn with frozen rejoin; neighbor_restart is asserted to "
            "end at-or-below frozen on consensus error after long "
            "outages."
        ),
        "gates": {
            "burst1_bitwise_iid": True,
            "monotone_consensus_degradation": cons,
            "monotone_gap_degradation": gaps,
            "bhat_by_burst": {f"{k:g}": v for k, v in bhat.items()},
            "gt_tracking_invariant_residual": resid,
            "rejoin_final_consensus": {
                "frozen": frozen["final_consensus"],
                "neighbor_restart": restart["final_consensus"],
            },
        },
        "runs": results,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(path)

    print(json.dumps({"metric": "churn_variants_measured",
                      "value": len(results)}))


if __name__ == "__main__":
    main()
