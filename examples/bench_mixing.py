"""Mixing-implementation microbenchmark on real hardware (VERDICT r1 item 3).

Measures, for the north-star N=256 ring-logistic configuration (reference
``main.py:6-21`` scaled to 256 workers per BASELINE.json):

1. **Op-level**: K back-to-back applications of each compiled mixing operator
   (x -> W x on the ``[N, d]`` model stack) under one ``lax.scan`` — isolates
   the gossip primitive itself (reference ``trainer.py:173``'s ``W @ models``).
2. **End-to-end**: full ``jax_backend.run`` throughput (iters/sec) for each
   ``mixing_impl``, identical workload, best of ``--repeats`` runs (the
   shared-tunnel chip's throughput varies with co-tenant load).

Implementations compared: ``stencil`` (jnp.roll stencil, XLA-fused),
``pallas`` (hand-written VMEM kernels incl. the fused W x − ηg step),
``dense`` ([N,N] matmul — the reference's own formulation, on the MXU),
``shard_map`` (explicit ppermute collectives; degenerate on a single chip —
included for completeness, flagged in the output).

Writes a JSON artifact (default ``docs/perf/mixing_bench.json``) consumed by
docs/PERF.md; the measured winner is what ``mixing_impl='auto'`` encodes.

Usage:  python examples/bench_mixing.py [--iters 3000] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def _time_op(fn, x, k: int = 2000, repeats: int = 3) -> float:
    """Best-of-``repeats`` seconds for ``k`` chained applications of ``fn``."""

    @jax.jit
    def chained(x0):
        return jax.lax.scan(lambda c, _: (fn(c), None), x0, None, length=k)[0]

    chained(x).block_until_ready()  # compile
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        chained(x).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    ap = argparse.ArgumentParser()
    # Default matches the committed artifact (docs/perf/mixing_bench.json was
    # produced at T=10k) so regen_perf_artifacts.sh reproduces it.
    ap.add_argument("--iters", type=int, default=10000)
    ap.add_argument("--n-workers", type=int, default=256)
    ap.add_argument("--op-chain", type=int, default=2000)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--out", default="docs/perf/mixing_bench.json")
    args = ap.parse_args()

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.ops.mixing import make_mixing_op
    from distributed_optimization_tpu.parallel.collectives import (
        make_shard_map_mixing_op,
    )
    from distributed_optimization_tpu.parallel.mesh import make_worker_mesh
    from distributed_optimization_tpu.parallel.topology import build_topology
    from distributed_optimization_tpu.utils.data import generate_synthetic_dataset
    from distributed_optimization_tpu.utils.oracle import compute_reference_optimum

    dev = jax.devices()[0]
    n = args.n_workers
    platform = dev.platform
    print(f"[bench_mixing] device={dev} platform={platform} N={n}", file=sys.stderr)

    topo = build_topology("ring", n)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((n, 81)),
                    dtype=jnp.float32)

    # --- 1. op-level: K chained W-applications -----------------------------
    op_results = {}
    mesh = make_worker_mesh(n)
    impls = {
        "stencil": make_mixing_op(topo, impl="stencil").apply,
        "pallas": make_mixing_op(topo, impl="pallas").apply,
        "dense": make_mixing_op(topo, impl="dense").apply,
        "shard_map": make_shard_map_mixing_op(topo, mesh).apply,
    }
    for name, fn in impls.items():
        try:
            sec = _time_op(fn, x, k=args.op_chain, repeats=args.repeats)
            per_apply_us = sec / args.op_chain * 1e6
            op_results[name] = round(per_apply_us, 3)
            print(f"[bench_mixing] op {name:10s}: {per_apply_us:8.2f} us/apply",
                  file=sys.stderr)
        except Exception as e:  # pragma: no cover - informational
            op_results[name] = f"FAIL: {type(e).__name__}: {e}"[:200]
            print(f"[bench_mixing] op {name}: FAILED {e}", file=sys.stderr)

    # --- 2. end-to-end: full backend runs ---------------------------------
    cfg0 = ExperimentConfig(
        problem_type="logistic", algorithm="dsgd", topology="ring",
        n_workers=n, n_iterations=args.iters,
    )
    ds = generate_synthetic_dataset(cfg0)
    _, f_opt = compute_reference_optimum(ds, cfg0.reg_param)

    # Variants are INTERLEAVED round-robin across repeat cycles so co-tenant
    # load swings on the shared chip hit every impl comparably — sequential
    # per-impl repeats let a single busy window sink one impl's numbers.
    e2e = {}
    best: dict[str, float] = {}
    gaps: dict[str, float] = {}
    for _ in range(args.repeats):
        for impl in ("stencil", "pallas", "dense", "shard_map"):
            if impl in e2e:  # already failed; don't retry every cycle
                continue
            cfg = cfg0.replace(mixing_impl=impl)
            kwargs = {"mesh": mesh} if impl == "shard_map" else {}
            try:
                r = jax_backend.run(cfg, ds, f_opt, **kwargs)
                best[impl] = max(best.get(impl, 0.0),
                                 float(r.history.iters_per_second))
                gaps[impl] = float(r.history.objective[-1])
            except Exception as e:  # pragma: no cover - informational
                e2e[impl] = {"error": f"{type(e).__name__}: {e}"[:200]}
                print(f"[bench_mixing] e2e {impl}: FAILED {e}", file=sys.stderr)
    for impl, ips in best.items():
        if impl in e2e:  # failed in a later cycle: the error record stands
            continue
        e2e[impl] = {"iters_per_sec": round(ips, 1),
                     "final_gap": round(gaps[impl], 6)}
        print(f"[bench_mixing] e2e {impl:10s}: {ips:9.0f} iters/sec "
              f"(gap {gaps[impl]:.4f})", file=sys.stderr)

    # shard_map on one chip is a degenerate lower bound (its ppermutes never
    # cross a device boundary) and can't be what 'auto' picks single-chip, so
    # it is excluded from the winner the artifact reports.
    ok = {k: v["iters_per_sec"] for k, v in e2e.items()
          if "iters_per_sec" in v and k != "shard_map"}
    winner = max(ok, key=ok.get) if ok else None
    out = {
        "device": str(dev), "platform": platform, "n_workers": n,
        "d": 81, "iters": args.iters, "op_chain": args.op_chain,
        "op_us_per_apply": op_results, "end_to_end": e2e, "winner": winner,
        "note": ("shard_map on a single chip has no cross-device collectives; "
                 "its number is a degenerate lower bound on collective cost"),
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(out, indent=2) + "\n")
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(path)

    print(f"[bench_mixing] winner={winner} -> {path}", file=sys.stderr)
    print(json.dumps({"metric": "mixing_bench_winner", "value": winner}))


if __name__ == "__main__":
    main()
