"""Sparse (CSR segment-sum) vs dense mixing on irregular graphs (round 5,
VERDICT r4 item 2).

The reference realizes gossip as a dense ``W @ models`` matmul for EVERY
graph (reference ``trainer.py:173``); this framework adds an O(E·d)
edge-list contraction (``ops/mixing.py`` impl='sparse') for the irregular
topologies with no stencil form — ER/chain/star and their directed
variants — where asymptotically the [N, N] matrix is overwhelmingly zeros
the matmul still pays for.

MEASURED VERDICT: dense wins every cell (this artifact). On TPU the dense
contraction rides the MXU at a ~40-90 µs latency floor through N=4096
while the gather+segment_sum form pays per-row DMA scaling with E (and
catastrophically with density — 200x slower at 40%); XLA:CPU's matmul
beats it too at every realistic cell. ``mixing_impl='auto'`` therefore
keeps DENSE for irregular graphs and 'sparse' is explicit opt-in for
regimes beyond this envelope (N >> 4096). A padded neighbor-GATHER variant
(no scatter) was also tried interactively and also lost to dense at every
cell — the finding is about scatter/gather latency vs a free systolic
N², not about one sparse formulation.

Protocol: for each (topology, N) cell, K chained applications of the
compiled operator x -> W x on the [N, 81] model stack (81 = the headline
model dimension), dense and sparse INTERLEAVED within each repeat cycle so
co-tenant swings on the shared chip hit both sides; reported value is the
best-of-cycles per-apply microseconds and the dense/sparse ratio. One
end-to-end row confirms the op-level verdict inside the full training
loop.

Writes ``docs/perf/sparse_mixing.json``.

Usage:  python examples/bench_sparse_mixing.py [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def _chained(fn, k: int):
    @jax.jit
    def run(x0):
        return jax.lax.scan(lambda c, _: (fn(c), None), x0, None, length=k)[0]

    return run


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--op-chain", type=int, default=2000)
    ap.add_argument("--cycles", type=int, default=3)
    ap.add_argument("--d", type=int, default=81)
    ap.add_argument("--out", default="docs/perf/sparse_mixing.json")
    args = ap.parse_args()

    from distributed_optimization_tpu.ops.mixing import make_mixing_op
    from distributed_optimization_tpu.parallel.topology import build_topology

    dev = jax.devices()[0]
    print(f"[sparse_mixing] device={dev} d={args.d}", file=sys.stderr)

    # (label, topology, N, kwargs): constant-degree graphs (chain deg<=2,
    # star, ER at mean degree 12) plus fixed-density ER at 10% and 40% —
    # the last is the BASELINE.json ADMM config's density, where dense
    # should win back. Directed ER exercises the column-stochastic path.
    rng_cells = []
    for n in (256, 1024, 4096):
        rng_cells += [
            (f"chain_N{n}", "chain", n, {}),
            (f"star_N{n}", "star", n, {}),
            (f"er_deg12_N{n}", "erdos_renyi", n,
             {"erdos_renyi_p": min(12.0 / n, 0.9)}),
            (f"directed_er_deg12_N{n}", "directed_erdos_renyi", n,
             {"erdos_renyi_p": min(12.0 / n, 0.9)}),
            (f"er_p10_N{n}", "erdos_renyi", n, {"erdos_renyi_p": 0.1}),
        ]
        if n <= 1024:  # p=0.4 at N=4096 builds a 6.7M-edge list; dense wins
            rng_cells.append(
                (f"er_p40_N{n}", "erdos_renyi", n, {"erdos_renyi_p": 0.4})
            )

    k = args.op_chain
    results: dict[str, dict] = {}
    compiled: dict[str, tuple] = {}
    for label, name, n, kw in rng_cells:
        topo = build_topology(name, n, seed=5, **kw)
        x = jnp.asarray(
            np.random.default_rng(0).standard_normal((n, args.d)),
            dtype=jnp.float32,
        )
        dense = _chained(make_mixing_op(topo, impl="dense").apply, k)
        sparse = _chained(make_mixing_op(topo, impl="sparse").apply, k)
        dense(x).block_until_ready()  # compile outside the timed cycles
        sparse(x).block_until_ready()
        compiled[label] = (dense, sparse, x)
        results[label] = {
            "n": n,
            "edges": int(np.count_nonzero(topo.adjacency)),
            "density": round(
                float(np.count_nonzero(topo.adjacency)) / n**2, 5
            ),
            "dense_us_per_apply": [],
            "sparse_us_per_apply": [],
        }

    for _ in range(args.cycles):
        for label, (dense, sparse, x) in compiled.items():
            for key, fn in (("dense_us_per_apply", dense),
                            ("sparse_us_per_apply", sparse)):
                t0 = time.perf_counter()
                fn(x).block_until_ready()
                results[label][key].append(
                    (time.perf_counter() - t0) / k * 1e6
                )

    for label, row in results.items():
        row["dense_us_per_apply"] = round(min(row["dense_us_per_apply"]), 3)
        row["sparse_us_per_apply"] = round(min(row["sparse_us_per_apply"]), 3)
        row["dense_over_sparse"] = round(
            row["dense_us_per_apply"] / row["sparse_us_per_apply"], 2
        )
        print(
            f"[sparse_mixing] {label:24s} density {row['density']:.4f}  "
            f"dense {row['dense_us_per_apply']:8.2f} us  sparse "
            f"{row['sparse_us_per_apply']:8.2f} us  ratio "
            f"x{row['dense_over_sparse']}",
            file=sys.stderr,
        )

    # --- end-to-end sanity row: the op-level win must survive the loop ----
    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import (
        compute_reference_optimum,
    )

    cfg = ExperimentConfig(
        problem_type="logistic", algorithm="dsgd", topology="erdos_renyi",
        erdos_renyi_p=12.0 / 1024, n_workers=1024, n_iterations=3000,
        eval_every=3000,
    )
    ds = generate_synthetic_dataset(cfg)
    _, f_opt = compute_reference_optimum(ds, cfg.reg_param)
    e2e: dict[str, list] = {"dense": [], "sparse": []}
    for _ in range(args.cycles):
        for impl in ("dense", "sparse"):
            r = jax_backend.run(
                cfg.replace(mixing_impl=impl), ds, f_opt,
                measure_compile=False,
            )
            e2e[impl].append(float(r.history.iters_per_second))
    e2e_row = {
        "config": "dsgd er_deg12 N=1024 T=3000 logistic",
        "dense_iters_per_sec": round(max(e2e["dense"]), 1),
        "sparse_iters_per_sec": round(max(e2e["sparse"]), 1),
    }
    print(f"[sparse_mixing] e2e {e2e_row}", file=sys.stderr)

    payload = {
        "device": str(dev),
        "protocol": (
            f"{k} chained W-applications on [N, {args.d}] float32, dense and "
            f"sparse interleaved per cycle, best of {args.cycles} cycles; "
            "compile excluded. e2e: full jax_backend.run, best of "
            f"{args.cycles} interleaved."
        ),
        "note": (
            "dense_over_sparse > 1 would mean the CSR segment-sum "
            "contraction wins; measured: dense wins every cell (MXU makes "
            "the N^2 contraction a latency-floor op through N=4096 while "
            "scatter pays per-row DMA scaling with E), so the auto rule "
            "(ops/mixing.py make_mixing_op) keeps dense for irregular "
            "graphs and 'sparse' is explicit opt-in."
        ),
        "op_level": results,
        "end_to_end": e2e_row,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(path)

    print(json.dumps({"metric": "sparse_mixing_cells", "value": len(results)}))


if __name__ == "__main__":
    main()
