"""Sharded worker-mesh evidence (ISSUE 11) -> docs/perf/worker_mesh.json.

Runs under a FORCED 4-device host platform (XLA_FLAGS, set below before
jax initializes) — the same mechanism tests/conftest.py uses — so the
halo-exchange collectives execute as real multi-device ppermutes on this
CPU container. Three measured claims, each gated by an assertion:

1. **Parity** — sharded (worker_mesh=4) and unsharded trajectories at
   matched N are BITWISE identical on the final models (ring and ER via
   halo gather); the objective eval sits within the repo's f64
   cross-program-shape convention (GSPMD reduce-tree order).
2. **Scale** — the N = 100,000 matrix-free ring run COMPLETES sharded
   over 4 devices (the explicit beyond-RAM headroom PR 8 left open at
   N=10k), with measured per-device resident bytes: the worker-sharded
   footprint scales as N/P — doubling N while doubling P leaves
   per-device bytes flat (the 50k/P=2 vs 100k/P=4 pair, asserted), and
   each cell runs in its own subprocess so peak RSS is honest.
3. **Bytes over ICI** — the static halo plan prices the real collective
   traffic exactly: a ring round ships 2 boundary rows per device
   REGARDLESS of N (asserted flat across the ring cells — Lian et al.'s
   O(deg)-per-worker claim made measurable), next to the analytic
   simulated-floats accounting in the same report.

ER at N=100k runs via the O(N·k_max) SPARSE sampler
(topology_sampler='sparse', ISSUE 18): the dense-stream sampler
intentionally replays the dense sampler's exact Generator stream for
bit-identical graphs (PR 8's parity contract), which is O(N^2) draws —
~35 min at N=100k for the build alone, the recorded reason this cell was
skipped through PR 17 (see scale.er_at_100k_history). The sparse sampler
draws a DIFFERENT realization of the same G(n, p) law in seconds, so the
N=10,000 dense-sampled cell stays as the bitwise-contract reference
while the 100k cell carries the irregular-graph completion.
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

# Must precede any jax import, including in spawn-context subprocesses
# (they re-import this module's top level).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4"
    ).strip()

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

OUT = REPO / "docs" / "perf" / "worker_mesh.json"

PARITY_N = 64
PARITY_T = 200

SCALE_T = 50
# (label, topology, n, worker_mesh, extra-config) — each cell in its own
# subprocess. The 50k/P=2 row pairs with 100k/P=4: same rows per device,
# so per-device resident bytes must come out flat.
SCALE_CELLS = (
    ("ring_25k_p4", "ring", 25_000, 4, {}),
    ("ring_50k_p4", "ring", 50_000, 4, {}),
    ("ring_50k_p2", "ring", 50_000, 2, {}),
    ("ring_100k_p4", "ring", 100_000, 4, {}),
    ("er_10k_p4", "erdos_renyi", 10_000, 4,
     {"erdos_renyi_p": 8.0 / 10_000, "topology_seed": 1}),
    # mean degree 16 > ln(100k) ≈ 11.5: the connected draw lands in O(1)
    # tries of the sparse sampler.
    ("er_100k_p4_sparse", "erdos_renyi", 100_000, 4,
     {"erdos_renyi_p": 16.0 / 100_000, "topology_seed": 1,
      "topology_sampler": "sparse"}),
)


def _problem(cfg):
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import (
        compute_reference_optimum,
    )

    ds = generate_synthetic_dataset(cfg)
    _, f_opt = compute_reference_optimum(ds, cfg.reg_param)
    return ds, f_opt


def bench_parity():
    import numpy as np

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.config import ExperimentConfig

    base = dict(
        n_workers=PARITY_N, n_samples=4 * PARITY_N, n_features=16,
        n_informative_features=10, problem_type="quadratic",
        algorithm="dsgd", local_batch_size=8, dtype="float64",
        n_iterations=PARITY_T, eval_every=20,
        topology_impl="neighbor", mixing_impl="gather",
    )
    cells = {}
    max_obj_dev = 0.0
    for name, kw in (
        ("ring", {"topology": "ring"}),
        ("erdos_renyi", {"topology": "erdos_renyi",
                         "erdos_renyi_p": 0.15, "topology_seed": 7}),
    ):
        cfg_u = ExperimentConfig(**{**base, **kw})
        cfg_s = cfg_u.replace(worker_mesh=4)
        ds, f_opt = _problem(cfg_u)
        r_u = jax_backend.run(cfg_u, ds, f_opt, use_mesh=False)
        r_s = jax_backend.run(cfg_s, ds, f_opt)
        bitwise = bool(np.array_equal(
            np.asarray(r_u.final_models), np.asarray(r_s.final_models)
        ))
        obj_dev = float(np.max(np.abs(
            np.asarray(r_u.history.objective, dtype=np.float64)
            - np.asarray(r_s.history.objective, dtype=np.float64)
        )) / max(1.0, float(np.max(np.abs(r_u.history.objective)))))
        max_obj_dev = max(max_obj_dev, obj_dev)
        assert bitwise, f"{name}: sharded final models diverged bitwise"
        cells[name] = {
            "models_bitwise": bitwise,
            "objective_max_rel_deviation_f64": obj_dev,
            "final_gap": float(r_u.history.objective[-1]),
        }
        print(f"[parity] {name}: models bitwise={bitwise}, "
              f"obj rel dev={obj_dev:.2e}")
    assert max_obj_dev <= 1e-12, max_obj_dev
    return {
        "n_workers": PARITY_N,
        "n_iterations": PARITY_T,
        "worker_mesh": 4,
        "cells": cells,
        "max_objective_rel_deviation_f64": max_obj_dev,
        "note": (
            "final models are BITWISE equal sharded-vs-unsharded; the "
            "objective eval reduces over the worker axis whose GSPMD "
            "reduction tree differs from the single-device linear order "
            "— the repo's documented <=1e-12 f64 cross-program-shape "
            "convention, asserted"
        ),
    }


def _scale_cell(args):
    """One sharded scale cell in a fresh subprocess (honest peak RSS +
    per-device resident bytes probed at the first progress heartbeat)."""
    label, topology, n, mesh_p, extra = args
    import collections
    import resource
    import time

    import jax

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.telemetry import ici_summary
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )

    cfg = ExperimentConfig(
        n_workers=n, n_samples=2 * n, n_features=16,
        n_informative_features=10, problem_type="quadratic",
        topology=topology, algorithm="dsgd", local_batch_size=4,
        n_iterations=SCALE_T, eval_every=SCALE_T // 2,
        topology_impl="neighbor", mixing_impl="gather",
        worker_mesh=mesh_p, **extra,
    )
    t0 = time.perf_counter()
    ds = generate_synthetic_dataset(cfg)
    data_seconds = time.perf_counter() - t0

    per_device: dict[str, int] = {}

    def probe(_event):
        # Live per-device resident bytes mid-run: every live jax array's
        # realized shard sizes, summed per device. Device 0 additionally
        # holds the replicated leaves (keys, scalars), so the sharded
        # footprint is read off devices 1..P-1.
        if per_device:
            return
        acc = collections.Counter()
        for a in jax.live_arrays():
            for s in a.addressable_shards:
                acc[str(s.device)] += s.data.nbytes
        per_device.update(acc)

    t0 = time.perf_counter()
    r = jax_backend.run(cfg, ds, 0.0, progress_cb=probe, progress_every=1)
    wall = time.perf_counter() - t0
    gap = float(r.history.objective[-1])
    assert gap == gap, f"{label}: NaN gap"
    ici = ici_summary(cfg)
    return {
        "label": label,
        "topology": topology,
        "n_workers": n,
        "worker_mesh": mesh_p,
        "rows_per_device": n // mesh_p,
        "iters_per_second": float(r.history.iters_per_second),
        "compile_seconds": float(r.history.compile_seconds),
        "wall_seconds": wall,
        "data_seconds": data_seconds,
        "final_gap": gap,
        "peak_rss_mb": resource.getrusage(
            resource.RUSAGE_SELF
        ).ru_maxrss / 1024.0,
        "per_device_resident_bytes": dict(per_device),
        "sharded_bytes_per_device": (
            min(per_device.values()) if per_device else None
        ),
        "ici": ici,
    }


def bench_scale():
    import multiprocessing as mp
    from concurrent import futures

    cells = []
    ctx = mp.get_context("spawn")
    for job in SCALE_CELLS:  # sequential: no interference between cells
        with futures.ProcessPoolExecutor(1, mp_context=ctx) as pool:
            cell = pool.submit(_scale_cell, job).result()
        cells.append(cell)
        print(f"[scale] {cell['label']}: {cell['iters_per_second']:.0f} "
              f"iters/s, {cell['sharded_bytes_per_device'] / 1e6:.1f} "
              f"MB/device sharded, peak RSS {cell['peak_rss_mb']:.0f} MB, "
              f"ICI {cell['ici']['bytes_per_device_per_round_max']} "
              f"B/dev/round")
    by_label = {c["label"]: c for c in cells}

    big = by_label["ring_100k_p4"]
    assert big["final_gap"] == big["final_gap"] and big["iters_per_second"] > 0

    # Flat per-device memory: same rows/device (50k over 2 vs 100k over
    # 4) -> same sharded per-device footprint, within allocator noise.
    pair_ratio = (
        big["sharded_bytes_per_device"]
        / by_label["ring_50k_p2"]["sharded_bytes_per_device"]
    )
    assert 0.8 <= pair_ratio <= 1.25, pair_ratio

    # Ring ICI traffic is O(boundary) = 2 rows/device/round at EVERY N.
    ring_ici = [
        by_label[k]["ici"]["bytes_per_device_per_round_max"]
        for k in ("ring_25k_p4", "ring_50k_p4", "ring_100k_p4")
    ]
    assert len(set(ring_ici)) == 1, ring_ici
    return {
        "n_iterations": SCALE_T,
        "cells": cells,
        "per_device_flat_pair": {
            "cells": ["ring_50k_p2", "ring_100k_p4"],
            "rows_per_device_each": 25_000,
            "sharded_bytes_ratio": pair_ratio,
        },
        "er_at_100k_history": (
            "skipped through PR 17: the dense-stream ER sampler replays "
            "the dense sampler's exact Generator stream for bit-identical "
            "graphs (PR 8 parity contract) — O(N^2) draws, ~35 min of "
            "host sampling at N=100k before the mesh runs at all. Runs "
            "since ISSUE 18 via topology_sampler='sparse' (O(N·k_max) "
            "draws, a different realization of the same law); the "
            "N=10,000 dense-sampled cell remains the bitwise-contract "
            "reference"
        ),
    }


def main() -> None:
    from distributed_optimization_tpu.telemetry import write_bench_manifest
    from distributed_optimization_tpu.utils.profiling import PhaseTimer

    import jax

    from distributed_optimization_tpu.config import ExperimentConfig

    assert len(jax.devices()) >= 4, (
        "worker-mesh bench needs the forced 4-device host platform; do "
        "not pre-set XLA_FLAGS without xla_force_host_platform_device_count"
    )
    timer = PhaseTimer()
    with timer.phase("parity"):
        parity = bench_parity()
    with timer.phase("scale"):
        scale = bench_scale()

    big = next(
        c for c in scale["cells"] if c["label"] == "ring_100k_p4"
    )
    ring_ici_flat = len({
        c["ici"]["bytes_per_device_per_round_max"]
        for c in scale["cells"] if c["topology"] == "ring"
        and c["worker_mesh"] == 4
    }) == 1
    payload = {
        "device": jax.devices()[0].device_kind,
        "platform": jax.devices()[0].platform,
        "protocol": {
            "devices": (
                "forced 4-device CPU host platform (XLA_FLAGS), real "
                "shard_map/ppermute collectives — the same mechanism the "
                "shard_map stencil tests use"
            ),
            "parity": (
                f"matched-N ({PARITY_N}) sharded worker_mesh=4 vs "
                "unsharded, ring + ER halo gather, f64: final models "
                "bitwise asserted, objective within the <=1e-12 "
                "cross-program-shape convention"
            ),
            "scale": (
                "ring N in {25k, 50k, 100k} over 4 devices + the "
                "50k/P=2 flat-memory pair + ER N=10k (dense-sampled "
                "bitwise reference) + ER N=100k (sparse-sampled, "
                "ISSUE 18), dsgd T=50, one "
                "subprocess per cell; per-device resident bytes probed "
                "from live array shards at the first progress heartbeat"
            ),
            "ici": (
                "bytes-over-ICI from the static halo plan "
                "(telemetry.ici_summary — identical numbers feed the "
                "report line and the /metrics per-device gauges); ring "
                "flatness across N asserted"
            ),
        },
        "parity": parity,
        "scale": scale,
        "gates": {
            "parity_models_bitwise_ring": parity["cells"]["ring"][
                "models_bitwise"],
            "parity_models_bitwise_er": parity["cells"]["erdos_renyi"][
                "models_bitwise"],
            "parity_max_objective_rel_deviation_f64": parity[
                "max_objective_rel_deviation_f64"],
            "n100k_ring_completed_sharded": True,
            "er_halo_completed": True,
            "er_100k_sparse_completed": True,
            "per_device_flat_at_matched_rows": bool(
                0.8 <= scale["per_device_flat_pair"][
                    "sharded_bytes_ratio"] <= 1.25
            ),
            "ring_ici_bytes_per_device_flat_in_n": ring_ici_flat,
            "n100k_ici_bytes_per_device_per_round": big["ici"][
                "bytes_per_device_per_round_max"],
        },
        "note": (
            "CPU-container numbers: absolute iters/sec is not chip "
            "evidence; the load-bearing content is the bitwise parity "
            "gates, the N=100k sharded completion, the flat per-device "
            "footprint at matched rows/device, and the N-independent "
            "ring ICI traffic. Bitwise guarantees per composed feature "
            "(churn, participation, Byzantine screening, resume) live in "
            "tests/test_worker_mesh.py, not here."
        ),
    }
    OUT.parent.mkdir(parents=True, exist_ok=True)
    OUT.write_text(json.dumps(payload, indent=1, sort_keys=True) + "\n")
    print(f"wrote {OUT}")
    write_bench_manifest(
        OUT,
        config=ExperimentConfig(
            n_workers=100_000, n_samples=200_000, n_features=16,
            n_informative_features=10, problem_type="quadratic",
            topology="ring", algorithm="dsgd", local_batch_size=4,
            n_iterations=SCALE_T, eval_every=SCALE_T // 2,
            topology_impl="neighbor", mixing_impl="gather", worker_mesh=4,
        ),
        phases=timer,
    )


if __name__ == "__main__":
    main()
