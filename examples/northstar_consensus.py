"""North-star demonstration: a 256-worker decentralized run that actually
reaches 1e-4 consensus, with MEASURED wall-clock (VERDICT r1 item 2).

``BASELINE.json`` defines the metric as "iters/sec to 1e-4 consensus;
wall-clock to target loss" (consensus definition: reference
``trainer.py:184-186``, (1/N) Σ_i ||x_i - x̄||²). Round 1 benchmarked
throughput at T=10k on the N=256 ring, where the spectral gap (2.0e-4)
makes 1e-4 consensus unreachable on any affordable horizon — under the
η₀/√(t+1) schedule consensus decays ~1/t once gossip equilibrates, putting
the ring's crossing at ~3e7 iterations (measured + extrapolated in the
artifact). This script demonstrates the metric literally on the N=256
**16x16 toroidal grid** (spectral gap 0.030, same worker count, same
objective/data/schedule), which crosses 1e-4 within a few thousand
iterations, and records the ring's measured trajectory plus its 1/t
extrapolation for honesty.

Runs use ``measure_timestamps=True`` — every eval boundary carries a real
``perf_counter`` sample (one host sync per ``eval_every`` iterations), so
"seconds to consensus 1e-4" and "seconds to gap<=0.08" are measured, not
interpolated.

Artifact: ``docs/perf/northstar_consensus.json`` (+ summary in
``docs/PERF.md``). Run on the real TPU chip: ``python
examples/northstar_consensus.py``.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_optimization_tpu.backends import jax_backend
from distributed_optimization_tpu.config import ExperimentConfig
from distributed_optimization_tpu.utils.data import generate_synthetic_dataset
from distributed_optimization_tpu.utils.oracle import compute_reference_optimum

CONSENSUS_TARGET = 1e-4
GAP_TARGET = 0.08  # the reference study's suboptimality threshold (PDF §III-A)


def first_crossing(values: np.ndarray, threshold: float) -> int:
    """First index with values[i] <= threshold, or -1."""
    hit = np.nonzero(values <= threshold)[0]
    return int(hit[0]) if hit.size else -1


def run_one(topology: str, n_iterations: int, eval_every: int) -> dict:
    cfg = ExperimentConfig(
        problem_type="logistic",
        algorithm="dsgd",
        topology=topology,
        n_workers=256,
        n_iterations=n_iterations,
        eval_every=eval_every,
    )
    ds = generate_synthetic_dataset(cfg)
    _, f_opt = compute_reference_optimum(ds, cfg.reg_param)
    res = jax_backend.run(cfg, ds, f_opt, measure_timestamps=True)
    h = res.history
    assert h.time_measured, "demonstration requires measured timestamps"
    cons = h.consensus_error
    gaps = h.objective
    iters = h.eval_iterations

    entry = {
        "topology": topology,
        "n_workers": 256,
        "n_iterations": n_iterations,
        "eval_every": eval_every,
        "spectral_gap": h.spectral_gap,
        "iters_per_second": round(float(h.iters_per_second), 1),
        "compile_seconds": round(float(h.compile_seconds), 2),
        "time_measured": True,
        "final_gap": float(gaps[-1]),
        "final_consensus": float(cons[-1]),
    }
    ci = first_crossing(cons, CONSENSUS_TARGET)
    gi = first_crossing(gaps, GAP_TARGET)
    entry["consensus_1e4"] = (
        {
            "iteration": int(iters[ci]),
            "seconds_measured": round(float(h.time[ci]), 3),
        }
        if ci >= 0
        else None
    )
    entry["gap_008"] = (
        {
            "iteration": int(iters[gi]),
            "seconds_measured": round(float(h.time[gi]), 3),
        }
        if gi >= 0
        else None
    )
    if ci < 0:
        # Consensus under the sqrt-decay schedule behaves ~ C/t once mixing
        # equilibrates; extrapolate the crossing from the last sample.
        t_last, c_last = float(iters[-1]), float(cons[-1])
        entry["consensus_1e4_extrapolated_iteration"] = int(
            t_last * c_last / CONSENSUS_TARGET
        )
    return entry


def main() -> None:
    ring_full = "--ring-full" in sys.argv
    t0 = time.perf_counter()
    results = {
        "metric": "iters/sec to 1e-4 consensus; wall-clock to target loss",
        "consensus_definition": "(1/N) sum_i ||x_i - xbar||^2  (reference trainer.py:184-186)",
        "device": str(jax_backend.jax.devices()[0]),
        "runs": [],
    }

    # The demonstration: N=256 grid crosses 1e-4 consensus AND the 0.08
    # suboptimality threshold inside T=100k. The measured-timestamps path
    # pays one host round-trip per eval chunk — substantial over the tunneled
    # chip — so the cadence is 500 (200 chunks): crossing resolution of 500
    # iterations with a real timestamp at each eval.
    grid = run_one("grid", n_iterations=100_000, eval_every=500)
    results["runs"].append(grid)
    print(f"[northstar] grid: {json.dumps(grid)}", file=sys.stderr, flush=True)

    # The headline ring at a 1M horizon: shows the measured trajectory and
    # the 1/t extrapolation to the 1e-4 crossing (~3e7 iterations).
    ring = run_one("ring", n_iterations=1_000_000, eval_every=5000)
    results["runs"].append(ring)
    print(f"[northstar] ring: {json.dumps(ring)}", file=sys.stderr, flush=True)

    if ring_full:
        # --ring-full: run the ring all the way THROUGH the 1e-4 crossing
        # (~3e7 iterations — affordable since the dense-sampling path landed;
        # ~5-10 min on the real chip depending on co-tenant load). Removes
        # the extrapolation caveat on the headline topology itself.
        ring_x = run_one("ring", n_iterations=40_000_000, eval_every=100_000)
        results["runs"].append(ring_x)
        print(f"[northstar] ring-full: {json.dumps(ring_x)}",
              file=sys.stderr, flush=True)

    results["total_wall_seconds"] = round(time.perf_counter() - t0, 1)

    out = pathlib.Path(__file__).resolve().parents[1] / "docs" / "perf"
    out.mkdir(parents=True, exist_ok=True)
    path = out / "northstar_consensus.json"
    path.write_text(json.dumps(results, indent=2) + "\n")
    print(f"[northstar] wrote {path}", file=sys.stderr)

    ok = grid["consensus_1e4"] is not None and grid["gap_008"] is not None
    print(
        json.dumps(
            {
                "demonstrated": ok,
                "grid_consensus_1e4": grid["consensus_1e4"],
                "grid_gap_008": grid["gap_008"],
            }
        )
    )
    if not ok:
        raise SystemExit("grid run failed to demonstrate the north-star metric")


if __name__ == "__main__":
    main()
