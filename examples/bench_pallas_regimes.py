"""Settle the pallas mixing tier with data (VERDICT r2 item 8).

Post-dense-sampling, pallas and the XLA roll-stencil tied within chip noise
at the headline shape (d=81, f32 — docs/perf/mixing_bench.json), leaving
``auto``'s pallas pick justified only by a gather-era measurement. This
script measures the regimes where a hand-fused VMEM kernel could plausibly
pull ahead — larger model dimension (more bytes per gossip round) and
bfloat16 (half the bytes, VPU-friendly) — at both the op level and end to
end, all variants interleaved round-robin per cycle so co-tenant swings hit
every cell comparably.

Matrix (round 5 — VERDICT r4 item 3 adds the crossover dims):
{stencil, pallas} × d ∈ {81, 128, 256, 384, 512, 768, 1024} × float32,
plus bfloat16 at the two anchor dims (81, 1024 — bf16 pallas failing to
compile is itself the datum; Mosaic's dynamic_rotate is 32-bit-only).

MEASURED OUTCOME (the artifact this produced): NO reproducible pallas win
at any dimension — e2e pallas/stencil ratios bounce 0.78–1.29 with no
trend across adjacent dims (co-tenant noise), and round 3's single-session
d=1024 win does not replicate (0.78 here). The round-3 "crossover bracket"
was noise; there is no crossover to gate on, so 'auto' (resolved by
``ops/mixing.py make_mixing_op``; the former jax_backend resolver is
deleted) never picks pallas and the VMEM kernels are explicit opt-in
(``mixing_impl='pallas'``).
Writes ``docs/perf/pallas_regimes.json``; whatever wins is what
``mixing_impl='auto'`` must encode.

Usage:  python examples/bench_pallas_regimes.py [--iters 10000] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def _time_op(fn, x, k: int, repeats: int) -> float:
    @jax.jit
    def chained(x0):
        return jax.lax.scan(lambda c, _: (fn(c), None), x0, None, length=k)[0]

    chained(x).block_until_ready()
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        chained(x).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=10_000)
    ap.add_argument("--n-workers", type=int, default=256)
    ap.add_argument("--op-chain", type=int, default=2000)
    ap.add_argument("--cycles", type=int, default=3)
    ap.add_argument("--out", default="docs/perf/pallas_regimes.json")
    args = ap.parse_args()

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.ops.mixing import make_mixing_op
    from distributed_optimization_tpu.parallel.topology import build_topology
    from distributed_optimization_tpu.utils.data import generate_synthetic_dataset
    from distributed_optimization_tpu.utils.oracle import compute_reference_optimum

    dev = jax.devices()[0]
    n = args.n_workers
    topo = build_topology("ring", n)
    print(f"[pallas_regimes] device={dev} N={n}", file=sys.stderr)

    # f32 sweeps the full dim grid (locating any crossover worth gating
    # on); bf16 only at the anchors — its pallas cells fail by
    # construction.
    DIMS = (81, 128, 256, 384, 512, 768, 1024)
    CELLS = [(d, "float32") for d in DIMS] + [
        (81, "bfloat16"), (1024, "bfloat16")
    ]

    # --- 1. op level: W x across d × dtype --------------------------------
    op_rows = {}
    rng = np.random.default_rng(0)
    for d, dt in CELLS:
        x = jnp.asarray(rng.standard_normal((n, d)), dtype=dt)
        for impl in ("stencil", "pallas"):
            key = f"d{d}_{dt}_{impl}"
            try:
                fn = make_mixing_op(topo, impl=impl, dtype=x.dtype).apply
                sec = _time_op(fn, x, args.op_chain, repeats=3)
                op_rows[key] = round(sec / args.op_chain * 1e6, 3)
                print(f"[pallas_regimes] op {key:26s} "
                      f"{op_rows[key]:8.3f} us/apply", file=sys.stderr)
            except Exception as e:  # a failing regime IS the datum
                op_rows[key] = f"FAIL: {type(e).__name__}: {e}"[:160]
                print(f"[pallas_regimes] op {key}: FAILED "
                      f"{str(e)[:120]}", file=sys.stderr)

    # --- 2. end to end: full runs across d × dtype ------------------------
    variants = {}
    for d, dt in CELLS:
        cfg = ExperimentConfig(
            problem_type="logistic", algorithm="dsgd", topology="ring",
            n_workers=n, n_iterations=args.iters,
            n_features=d - 1, n_informative_features=min(60, d - 21),
            dtype=dt,
        )
        for impl in ("stencil", "pallas"):
            variants[f"d{d}_{dt}_{impl}"] = (cfg.replace(mixing_impl=impl))

    # One dataset per distinct feature count (generation depends on d).
    data_cache = {}
    for name, cfg in variants.items():
        if cfg.n_features not in data_cache:
            ds = generate_synthetic_dataset(cfg)
            _, f_opt = compute_reference_optimum(ds, cfg.reg_param)
            data_cache[cfg.n_features] = (ds, f_opt)

    runs: dict[str, list[float]] = {name: [] for name in variants}
    failed: dict[str, str] = {}
    for c in range(args.cycles):
        for name, cfg in variants.items():
            if name in failed:
                continue
            ds, f_opt = data_cache[cfg.n_features]
            try:
                r = jax_backend.run(cfg, ds, f_opt)
                runs[name].append(float(r.history.iters_per_second))
            except Exception as e:
                failed[name] = f"{type(e).__name__}: {e}"[:160]
                print(f"[pallas_regimes] e2e {name}: FAILED "
                      f"{str(e)[:120]}", file=sys.stderr)
    e2e = {}
    for name, vals in runs.items():
        if name in failed:
            e2e[name] = {"error": failed[name]}
            continue
        e2e[name] = {
            "iters_per_sec_median": round(statistics.median(vals), 1),
            "runs": [round(v) for v in vals],
        }
        print(f"[pallas_regimes] e2e {name:26s} median "
              f"{e2e[name]['iters_per_sec_median']:9.0f}", file=sys.stderr)

    # Per-regime verdict. Round-5 rule: with per-cell run swings of 2-3x on
    # the shared chip, a >10%-of-median test labels co-tenant noise a win —
    # require the run RANGES to separate (worst pallas run > 1.10x best
    # stencil run) before calling a winner outside noise.
    verdicts = {}
    for d, dt in CELLS:
        s = e2e[f"d{d}_{dt}_stencil"].get("iters_per_sec_median")
        p = e2e[f"d{d}_{dt}_pallas"].get("iters_per_sec_median")
        s_runs = e2e[f"d{d}_{dt}_stencil"].get("runs") or []
        p_runs = e2e[f"d{d}_{dt}_pallas"].get("runs") or []
        verdicts[f"d{d}_{dt}"] = {
            "stencil": s, "pallas": p,
            "pallas_over_stencil": (round(p / s, 3)
                                    if p and s else "ratio unavailable"),
            "pallas_wins_outside_noise": bool(
                s_runs and p_runs and min(p_runs) > 1.10 * max(s_runs)
            ),
        }
    out = {
        "device": str(dev), "n_workers": n, "iters": args.iters,
        "cycles": args.cycles,
        "op_us_per_apply": op_rows,
        "end_to_end": e2e,
        "verdicts": verdicts,
        "note": "interleaved round-robin per cycle; medians reported. The "
                "'auto' mixing rule must match these verdicts "
                "(ops/mixing.py make_mixing_op — round 5: no reproducible "
                "pallas win, auto never picks it). Verdict rule: run RANGES "
                "must separate (min pallas run > 1.10x max stencil run) — "
                "a >10%-of-median test would label the shared chip's 2-3x "
                "co-tenant swings as wins.",
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(out, indent=2) + "\n")
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(path)

    print(json.dumps({"metric": "pallas_regimes",
                      "value": {k: v["pallas_wins_outside_noise"]
                                for k, v in verdicts.items()}}))


if __name__ == "__main__":
    main()
