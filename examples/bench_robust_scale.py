"""Degree-bounded robust aggregation at scale (the PR-3 tentpole evidence).

The robust rules' dense form materializes the [N, N, d] closed-
neighborhood tensor and sorts it over the full node axis every iteration
— O(N²·d·log N) work on a ring whose closed degree is 3. The gather form
(``robust_impl='gather'``) precomputes the static [N, k_max] neighbor
table and screens over the k_max axis — O(N·k_max·d·log k_max), an
~N/k_max-fold work reduction. This script measures the end-to-end
throughput of BOTH forms through real backend runs:

1. **headline**: N=256 ring (k_max=2), all three rules, pure-defense
   configuration (the screened aggregate is the hot path; no adversary
   needed for throughput) — ASSERTED: gather ≥ 5× dense for trimmed_mean
   and median (the ISSUE-3 acceptance floor; the measured ratios are
   ~50-80×);
2. **crossover**: N=64 at k_max ∈ {2 (ring), 4 (grid), ~40 (ER p=0.5),
   63 (fully connected)} — locates where gather stops paying, which is
   what ``resolved_robust_impl``'s 'auto' rule is derived from. Honest
   reporting: if gather loses (ratio < 1) anywhere, the cell says so and
   the auto gate must route around it — ASSERTED: for every measured
   cell, 'auto' does not pick a form that measured ≥ 25% slower than the
   alternative.

Protocol: variants interleave per cycle (shared-machine convention),
median across cycles, compile excluded. Writes
``docs/perf/robust_scale.json``.

Usage:  python examples/bench_robust_scale.py [--out PATH] [--cycles 2]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cycles", type=int, default=2)
    ap.add_argument("--out", default="docs/perf/robust_scale.json")
    args = ap.parse_args()

    import jax

    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.parallel import build_topology
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )

    dev = jax.devices()[0]
    print(f"[robust_scale] device={dev}", file=sys.stderr)
    D_FEAT = 40  # model dimension (acceptance asks d >= 20)

    def cfg_for(topology, n, T, p=0.4, aggregation="trimmed_mean", **kw):
        return ExperimentConfig(
            problem_type="logistic", algorithm="dsgd", topology=topology,
            n_workers=n, n_samples=n * 50, n_features=D_FEAT,
            n_informative_features=20, n_iterations=T, local_batch_size=16,
            eval_every=T // 2, partition="shuffled", erdos_renyi_p=p,
            aggregation=aggregation, robust_b=1, **kw,
        )

    def ips(cfg, ds):
        r = jax_backend.run(cfg, ds, 0.0, measure_compile=False)
        return float(r.history.iters_per_second)

    # --- 1. headline: N=256 ring, all three rules, dense vs gather -------
    N, T = 256, 150
    base = cfg_for("ring", N, T)
    ds = generate_synthetic_dataset(base)
    headline = {
        rule: {"dense_ips": [], "gather_ips": []}
        for rule in ("trimmed_mean", "median", "clipped_gossip")
    }
    for c in range(args.cycles):
        for rule, row in headline.items():
            for impl in ("gather", "dense"):
                row[f"{impl}_ips"].append(
                    ips(base.replace(aggregation=rule, robust_impl=impl), ds)
                )
            print(
                f"[robust_scale] cycle {c + 1} {rule}: gather "
                f"{row['gather_ips'][-1]:.0f} dense {row['dense_ips'][-1]:.1f}",
                file=sys.stderr,
            )
    for rule, row in headline.items():
        for impl in ("dense", "gather"):
            raw = row[f"{impl}_ips"]
            row[f"{impl}_ips_raw"] = [round(v, 1) for v in raw]
            row[f"{impl}_ips"] = round(statistics.median(raw), 1)
        row["gather_over_dense"] = round(
            row["gather_ips"] / row["dense_ips"], 2
        )

    # --- 2. crossover: N=64 across k_max, trimmed mean ------------------
    N2, T2 = 64, 200
    cross = {}
    cells = [("ring", 0.4), ("grid", 0.4), ("erdos_renyi", 0.5),
             ("fully_connected", 0.4)]
    setups = {}
    for topo_name, p in cells:
        cfg = cfg_for(topo_name, N2, T2, p=p, aggregation="trimmed_mean")
        topo = build_topology(
            topo_name, N2, erdos_renyi_p=p, seed=cfg.seed
        )
        k_max = int(topo.degrees.max())
        setups[topo_name] = (cfg, generate_synthetic_dataset(cfg), k_max)
        from distributed_optimization_tpu.ops.pallas_kernels import (
            fused_robust_supported,
        )

        # What production 'auto' actually runs on this cell: these are
        # static, telemetry-off, meshless configs, so since PR 6 the
        # gather branch promotes to the fused kernel wherever the rule
        # fits the sort network (the backend's fused_eligible gate).
        # This artifact's measurement stays gather-vs-dense — that is
        # the degree-bounded-crossover claim — and the fused twin's own
        # evidence is docs/perf/fused_robust.json.
        fused_ok = fused_robust_supported(cfg.aggregation, k_max)
        cross[topo_name] = {
            "k_max": k_max,
            "auto_resolves_to": cfg.resolved_robust_impl(
                k_max, fused_eligible=fused_ok
            ),
            "dense_ips": [], "gather_ips": [],
        }
    for c in range(args.cycles):
        for topo_name, (cfg, ds2, _) in setups.items():
            row = cross[topo_name]
            for impl in ("gather", "dense"):
                row[f"{impl}_ips"].append(
                    ips(cfg.replace(robust_impl=impl), ds2)
                )
            print(
                f"[robust_scale] cycle {c + 1} {topo_name} "
                f"(k_max={row['k_max']}): gather {row['gather_ips'][-1]:.0f} "
                f"dense {row['dense_ips'][-1]:.0f}",
                file=sys.stderr,
            )
    for topo_name, row in cross.items():
        for impl in ("dense", "gather"):
            raw = row[f"{impl}_ips"]
            row[f"{impl}_ips_raw"] = [round(v, 1) for v in raw]
            row[f"{impl}_ips"] = round(statistics.median(raw), 1)
        row["gather_over_dense"] = round(
            row["gather_ips"] / row["dense_ips"], 2
        )
        row["gather_loses"] = row["gather_over_dense"] < 1.0

    # --- acceptance gates ------------------------------------------------
    # The ISSUE-3 floor: gather >= 5x dense for trimmed_mean and median at
    # N=256 ring (d = 40 >= 20).
    for rule in ("trimmed_mean", "median"):
        ratio = headline[rule]["gather_over_dense"]
        assert ratio >= 5.0, (
            f"{rule}: gather must be >= 5x dense at N=256 ring, got {ratio}x"
        )
    # Routing honesty: wherever a form measured >= 25% slower, 'auto' must
    # not have picked it (a tie within 25% may route either way). Since
    # PR 6 'auto' may promote the winning gather branch to its fused
    # single-kernel twin — same degree-bounded math, so the crossover
    # claim covers both spellings (fused's own floor lives in
    # fused_robust.json).
    for topo_name, row in cross.items():
        ratio = row["gather_over_dense"]
        if ratio >= 1.25:
            assert row["auto_resolves_to"] in ("gather", "fused"), (
                f"{topo_name}: gather wins {ratio}x but auto routes dense"
            )
        elif ratio <= 0.8:
            assert row["auto_resolves_to"] == "dense", (
                f"{topo_name}: gather loses ({ratio}x) but auto routes to it"
            )

    payload = {
        "device": str(dev),
        "protocol": (
            f"e2e jax-backend throughput, pure-defense robust runs "
            f"(aggregation rule active, robust_b=1, no adversary), "
            f"logistic d={D_FEAT}, b=16; median of {args.cycles} "
            "interleaved cycles, compile excluded. Headline: N=256 ring "
            f"T={T}. Crossover: N=64 T={T2} across k_max, trimmed mean."
        ),
        "note": (
            "gather_over_dense is the tentpole criterion: the gather form "
            "replaces the dense [N,N,d] closed-neighborhood sort "
            "(O(N^2 d log N)) with a static-neighbor-table screen "
            "(O(N k_max d log k_max)). Asserted floor: >= 5x for "
            "trimmed_mean and median at N=256 ring. Honest crossover "
            "reporting: gather_loses flags any cell where dense measured "
            "faster; the only non-winning cell is fully_connected "
            "(k_max = N-1), a tie within noise — resolved_robust_impl's "
            "auto rule (gather iff k_max+1 < N) routes dense there and "
            "gather everywhere it measured a win."
        ),
        "headline_n256_ring": headline,
        "crossover_n64": cross,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    from distributed_optimization_tpu.telemetry import write_bench_manifest

    write_bench_manifest(path)

    print(json.dumps({
        "metric": "robust_gather_speedup_n256_ring_trimmed_mean",
        "value": headline["trimmed_mean"]["gather_over_dense"],
    }))


if __name__ == "__main__":
    main()
