"""Headline benchmark: D-SGD steady-state throughput vs the CPU simulator.

Runs the reference study's flagship decentralized config (logistic regression,
N=25 workers, ring topology, T=10,000 iterations, full-dataset suboptimality
evaluated every iteration — reference ``main.py:6-21`` / PDF §III-A) on the
JAX/XLA backend, and compares iterations/second against the numpy
reference-semantics simulator measured on the same machine (the reference
publishes no wall-clock numbers — BASELINE.md — so the baseline is the
reference-equivalent simulator's measured throughput, per BASELINE.json's
north star).

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": ..., "unit": "iters/sec", "vs_baseline": ...}
"""

from __future__ import annotations

import json
import sys
import time


def main() -> None:
    from distributed_optimization_tpu.backends import jax_backend, numpy_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.metrics import iterations_to_threshold
    from distributed_optimization_tpu.utils.data import generate_synthetic_dataset
    from distributed_optimization_tpu.utils.oracle import compute_reference_optimum

    config = ExperimentConfig(
        problem_type="logistic", algorithm="dsgd", topology="ring"
    )  # reference defaults: N=25, T=10000, b=16, eta0=0.05, lambda=1e-4

    t0 = time.perf_counter()
    dataset = generate_synthetic_dataset(config)
    _, f_opt = compute_reference_optimum(dataset, config.reg_param)
    print(
        f"[bench] data+oracle ready in {time.perf_counter() - t0:.1f}s "
        f"(f_opt={f_opt:.6f})",
        file=sys.stderr,
    )

    # --- baseline: numpy reference-semantics simulator, short run scaled ---
    base_iters = 400
    base = numpy_backend.run(
        config.replace(n_iterations=base_iters), dataset, f_opt
    )
    baseline_ips = base.history.iters_per_second
    print(f"[bench] numpy oracle: {baseline_ips:.1f} iters/sec", file=sys.stderr)

    # --- JAX backend: full T=10k run, metrics on-device every iteration ---
    result = jax_backend.run(config, dataset, f_opt)
    hist = result.history
    jax_ips = hist.iters_per_second
    reached = iterations_to_threshold(
        hist.objective, config.suboptimality_threshold, hist.eval_iterations
    )
    print(
        f"[bench] jax backend: {jax_ips:.1f} iters/sec "
        f"(compile {hist.compile_seconds:.1f}s, "
        f"final gap {hist.objective[-1]:.4f}, "
        f"iters-to-0.08 {reached}, reference table: 9927)",
        file=sys.stderr,
    )
    if not (hist.objective[-1] < 1.0):
        raise SystemExit("benchmark run diverged — refusing to report")

    print(
        json.dumps(
            {
                "metric": "dsgd_ring_logistic_N25_T10k_iters_per_sec",
                "value": round(jax_ips, 2),
                "unit": "iters/sec",
                "vs_baseline": round(jax_ips / baseline_ips, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
