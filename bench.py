"""Headline benchmark: the BASELINE.json north-star configuration.

Protocol (round 4 — VERDICT r3 item 1): two changes over the round-3
interleaved median-of-5 protocol.

1. **Amortized horizon.** The throughput cycles run T=300,000 (round 3 ran
   T=30,000). At T=30k the fixed per-run overhead (~240 ms of tunnel /
   dispatch / host sync against ~164 ms of device time — ROUND3_NOTES
   "Headline amortization") ate ~60% of the measured wall-clock, so the
   published number undersold steady-state throughput ~2× and inherited the
   full variance of the overhead term (the round-3 published range 634–1,223×
   failed to contain the round-3 driver capture of 470×). At T=300k the
   overhead is <10% of wall-clock; same-session spread measured ~11% at the
   protocol change (vs ~1.7–1.9× at T=30k). The eval cadence stays
   eval_every=1 — the SAME per-iteration full-dataset objective eval the
   reference performs (reference ``trainer.py:189``) and the numpy baseline
   pays, so the comparison stays apples-to-apples.

2. **Self-validating range.** The published headline range now lives in ONE
   committed artifact — ``docs/perf/headline_sessions.json`` — that the docs
   cite and this script LOADS AND ENFORCES: if the measured median lands
   outside ``published_range_ips``, the bench fails loudly instead of letting
   the docs go silently stale (which happened three rounds running). Widening
   the range is a deliberate, committed act, never a drift.

Interleaving (unchanged from round 3): the shared tunneled chip swings with
co-tenant load, so each of the five cycles pairs one numpy-simulator segment
with one full jax run, and the reported value is the MEDIAN of the five jax
measurements over the MEDIAN of the five numpy measurements.

Two measurements, one JSON line:

1. **Parity check** (stderr): the reference study's flagship decentralized
   config — logistic, N=25, ring, T=10,000, full-dataset suboptimality every
   iteration (reference ``main.py:6-21`` / PDF §III-A) — must converge to
   ε ≤ 0.08 in an iteration count consistent with the published Table I
   (9,927). Guards against benchmarking a broken optimizer.

2. **Headline** (stdout JSON): the north-star scale config named in
   BASELINE.json — 256-worker decentralized logistic regression on a ring —
   at T=300,000, a horizon the run crosses the study's ε ≤ 0.08 threshold
   well within (measured crossing ≈ iteration 22.5k). Gates: finite metrics,
   the ε-crossing itself, bounded consensus, and the published-range check.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": ..., "unit": "iters/sec", "vs_baseline": ...}
"""

from __future__ import annotations

import json
import os
import pathlib
import statistics
import sys
import time

_SESSIONS_ARTIFACT = pathlib.Path(__file__).parent / "docs/perf/headline_sessions.json"


def main() -> None:
    import numpy as np

    from distributed_optimization_tpu.backends import jax_backend, numpy_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.metrics import iterations_to_threshold
    from distributed_optimization_tpu.utils.data import generate_synthetic_dataset
    from distributed_optimization_tpu.utils.oracle import compute_reference_optimum

    # The two configs of the protocol: the reference-parity check and the
    # headline. The headline cfg is built ONCE here and used for both the
    # artifact pre-flight below and the measured run, so they cannot drift.
    parity_cfg = ExperimentConfig(
        problem_type="logistic", algorithm="dsgd", topology="ring"
    )  # reference defaults: N=25, T=10000, b=16, eta0=0.05, lambda=1e-4
    cfg = parity_cfg.replace(n_workers=256, n_iterations=300_000)

    # Validate the published-range artifact BEFORE any chip work: a stale
    # metric name or malformed range must not cost a full benchmark session.
    published = json.loads(_SESSIONS_ARTIFACT.read_text())
    if published.get("metric") != _metric_name(cfg):
        raise SystemExit(
            f"headline_sessions.json records metric {published.get('metric')!r} "
            f"but this bench measures {_metric_name(cfg)!r} — "
            "update the artifact to the current protocol"
        )
    try:
        lo, hi = (float(x) for x in published["published_range_ips"])
        floor_ratio = float(published["published_floor_ratio_vs_numpy"])
        if not (0 < lo < hi):
            raise ValueError(f"empty or inverted range [{lo}, {hi}]")
    except (KeyError, TypeError, ValueError) as e:
        raise SystemExit(
            f"headline_sessions.json is malformed ({e!r}) — it must carry "
            "published_range_ips=[lo, hi] (numeric, lo < hi) and "
            "published_floor_ratio_vs_numpy"
        )

    # --- 1. reference-parity convergence check (N=25, published config) ---
    t0 = time.perf_counter()
    parity_ds = generate_synthetic_dataset(parity_cfg)
    _, parity_f_opt = compute_reference_optimum(parity_ds, parity_cfg.reg_param)
    parity = jax_backend.run(parity_cfg, parity_ds, parity_f_opt)
    reached = iterations_to_threshold(
        parity.history.objective,
        parity_cfg.suboptimality_threshold,
        parity.history.eval_iterations,
    )
    print(
        f"[bench] parity N=25 ring logistic: {parity.history.iters_per_second:.0f} "
        f"iters/sec, iters-to-0.08 = {reached} (reference Table I: 9927), "
        f"final gap {parity.history.objective[-1]:.4f} "
        f"[{time.perf_counter() - t0:.0f}s]",
        file=sys.stderr,
    )
    if not (0 < reached <= parity_cfg.n_iterations):
        raise SystemExit(
            "parity config failed to reach the reference's suboptimality "
            "threshold — refusing to report throughput for a broken optimizer"
        )

    # --- 2. north-star scale config: N=256 decentralized logistic ---
    # T=300k amortizes fixed per-run overhead to <10% of wall-clock; the run
    # crosses the study's ε ≤ 0.08 within the horizon (≈ iter 22.5k).
    ds = generate_synthetic_dataset(cfg)
    _, f_opt = compute_reference_optimum(ds, cfg.reg_param)

    # Interleaved median-of-5: numpy segment, then jax run, x5. The numpy
    # simulator is steady-state (same per-iteration work every iteration),
    # so a 400-iteration segment per cycle samples its rate honestly; the
    # jax run is the full T=300k workload. Each run() call re-traces and
    # re-compiles (the jit cache is keyed on the per-call closures), so the
    # persistent compilation cache is enabled first: the warmup run pays
    # the XLA compile once and every measured cycle deserializes it in
    # ~100 ms — without this, each cycle would insert a multi-second
    # compile window of different co-tenant load between its numpy and jax
    # samples, exactly the chip-window drift interleaving exists to kill.
    # (Throughput numbers exclude compile either way; this is about keeping
    # the paired samples adjacent.) The warmup's metrics drive the
    # convergence gates below.
    import tempfile

    import jax

    cache_dir = tempfile.mkdtemp(prefix="bench_xla_cache_")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    CYCLES = 5
    BASE_SEGMENT_ITERS = 400
    warm = jax_backend.run(cfg, ds, f_opt)
    hist = warm.history

    base_cfg = cfg.replace(n_iterations=BASE_SEGMENT_ITERS)
    numpy_ips: list[float] = []
    jax_ips: list[float] = []
    for cycle in range(CYCLES):
        b = numpy_backend.run(base_cfg, ds, f_opt)
        numpy_ips.append(float(b.history.iters_per_second))
        r = jax_backend.run(cfg, ds, f_opt, measure_compile=False)
        jax_ips.append(float(r.history.iters_per_second))
        print(
            f"[bench] cycle {cycle + 1}/{CYCLES}: numpy "
            f"{numpy_ips[-1]:.1f}, jax {jax_ips[-1]:.0f} iters/sec",
            file=sys.stderr,
        )

    jax_median = statistics.median(jax_ips)
    numpy_median = statistics.median(numpy_ips)
    print(
        f"[bench] N=256 T=300k jax: median {jax_median:.0f} iters/sec "
        f"(spread {min(jax_ips):.0f}-{max(jax_ips):.0f}); numpy "
        f"reference-semantics: median {numpy_median:.1f} "
        f"(spread {min(numpy_ips):.1f}-{max(numpy_ips):.1f}); compile "
        f"{hist.compile_seconds:.1f}s, final gap {hist.objective[-1]:.4f}, "
        f"consensus {hist.consensus_error[-1]:.2e}",
        file=sys.stderr,
    )

    if not np.all(np.isfinite(hist.objective)):
        raise SystemExit("north-star run produced non-finite metrics")
    # The run must cross the study's own suboptimality threshold within its
    # horizon — the headline is the throughput of a run that actually
    # converges to ε, not of a truncated transient.
    crossed = iterations_to_threshold(
        hist.objective, cfg.suboptimality_threshold, hist.eval_iterations
    )
    if not (0 < crossed <= cfg.n_iterations):
        raise SystemExit(
            f"north-star run never reached ε ≤ {cfg.suboptimality_threshold} "
            f"within T={cfg.n_iterations} (final gap {hist.objective[-1]:.4f})"
            " — refusing to report throughput"
        )
    print(
        f"[bench] north-star ε-crossing at iteration {crossed} "
        f"(threshold {cfg.suboptimality_threshold})",
        file=sys.stderr,
    )
    # Consensus must stay bounded (gossip contraction active). The N=256
    # ring's consensus is still in its slow ~1/t phase at this horizon
    # (spectral gap 2e-4); boundedness, not a small absolute value, is the
    # honest gate here (see docs/PERF.md §2 for the full consensus story).
    cons = hist.consensus_error
    if not (np.all(np.isfinite(cons)) and cons[-1] < 1.0):
        raise SystemExit(
            "north-star consensus error is unbounded — refusing to report "
            f"throughput (consensus {cons[0]:.3e} -> {cons[-1]:.3e})"
        )

    # --- 3. self-check against the PUBLISHED range (VERDICT r3 item 1b) ---
    # The range the docs quote lives in docs/perf/headline_sessions.json and
    # is enforced here: a capture outside it means either the chip regressed
    # /improved beyond every recorded session or the docs are stale — both
    # demand a committed, deliberate range update, not silent drift.
    session_line = {
        "jax_median_ips": round(jax_median, 2),
        "jax_cycles_ips": [round(x, 2) for x in jax_ips],
        "numpy_median_ips": round(numpy_median, 2),
        "ratio": round(jax_median / numpy_median, 2),
    }
    print(f"[bench] session record: {json.dumps(session_line)}", file=sys.stderr)
    # Escape hatch (round-5 advisor fix): the range/floor gates encode the
    # CANONICAL chip's recorded sessions; on different hardware (another TPU
    # generation, a CI host, heavy co-tenancy being diagnosed) an out-of-range
    # capture means "different machine", not "docs went stale". Setting
    # BENCH_NO_RANGE_CHECK=1 skips ONLY these two gates — convergence gates
    # above still apply and the session record is still printed.
    if os.environ.get("BENCH_NO_RANGE_CHECK", "").lower() not in ("", "0", "false"):
        print(
            "[bench] BENCH_NO_RANGE_CHECK set: skipping published-range and "
            "floor-ratio gates (non-canonical hardware mode)",
            file=sys.stderr,
        )
    elif not (lo <= jax_median <= hi):
        raise SystemExit(
            f"measured median {jax_median:.0f} iters/sec is OUTSIDE the "
            f"published range [{lo}, {hi}] from {_SESSIONS_ARTIFACT.name} — "
            "the published claim no longer contains reality. Append the "
            "session record above to the artifact, widen published_range_ips "
            "to contain every recorded session, and update the docs that "
            "cite it (docs/PERF.md, README.md, docs/ARCHITECTURE.md)."
        )
    elif jax_median / numpy_median < floor_ratio:
        raise SystemExit(
            f"measured ratio {jax_median / numpy_median:.0f}x vs the "
            f"same-session numpy baseline is below the published floor "
            f"({floor_ratio:.0f}x, {_SESSIONS_ARTIFACT.name}) — the docs' "
            "ratio claims no longer contain reality; re-derive them in a "
            "commit"
        )
    else:
        print(
            f"[bench] self-check OK: median inside published range "
            f"[{lo}, {hi}], ratio above {floor_ratio:.0f}x floor",
            file=sys.stderr,
        )

    print(
        json.dumps(
            {
                "metric": _metric_name(cfg),
                "value": round(jax_median, 2),
                "unit": "iters/sec",
                "vs_baseline": round(jax_median / numpy_median, 2),
            }
        )
    )


def _metric_name(cfg) -> str:
    # The Nk shorthand silently mislabels horizons that are not multiples of
    # 1000 (T=1500 would print as "T1k"); assert rather than round so a
    # protocol change to an off-k horizon forces an explicit rename here.
    if cfg.n_iterations % 1000 != 0:
        raise ValueError(
            f"metric name uses the T{{N}}k shorthand; horizon "
            f"{cfg.n_iterations} is not a multiple of 1000 — "
            "update _metric_name (and headline_sessions.json) explicitly"
        )
    return (
        f"dsgd_ring_logistic_N{cfg.n_workers}_T{cfg.n_iterations // 1000}k"
        "_iters_per_sec_median5"
    )


if __name__ == "__main__":
    main()
