"""Headline benchmark: the BASELINE.json north-star configuration.

Two measurements, one JSON line:

1. **Parity check** (stderr): the reference study's flagship decentralized
   config — logistic, N=25, ring, T=10,000, full-dataset suboptimality every
   iteration (reference ``main.py:6-21`` / PDF §III-A) — must converge to
   ε ≤ 0.08 in an iteration count consistent with the published Table I
   (9,927). Guards against benchmarking a broken optimizer.

2. **Headline** (stdout JSON): the north-star scale config named in
   BASELINE.json — 256-worker decentralized logistic regression on a ring —
   JAX/TPU backend iterations/second vs the CPU reference-semantics simulator
   measured on this same machine (the reference publishes no wall-clock
   numbers — BASELINE.md; the stated target is ≥50× the CPU simulator).

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": ..., "unit": "iters/sec", "vs_baseline": ...}
"""

from __future__ import annotations

import json
import sys
import time


def main() -> None:
    from distributed_optimization_tpu.backends import jax_backend, numpy_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.metrics import iterations_to_threshold
    from distributed_optimization_tpu.utils.data import generate_synthetic_dataset
    from distributed_optimization_tpu.utils.oracle import compute_reference_optimum

    # --- 1. reference-parity convergence check (N=25, published config) ---
    parity_cfg = ExperimentConfig(
        problem_type="logistic", algorithm="dsgd", topology="ring"
    )  # reference defaults: N=25, T=10000, b=16, eta0=0.05, lambda=1e-4
    t0 = time.perf_counter()
    parity_ds = generate_synthetic_dataset(parity_cfg)
    _, parity_f_opt = compute_reference_optimum(parity_ds, parity_cfg.reg_param)
    parity = jax_backend.run(parity_cfg, parity_ds, parity_f_opt)
    reached = iterations_to_threshold(
        parity.history.objective,
        parity_cfg.suboptimality_threshold,
        parity.history.eval_iterations,
    )
    print(
        f"[bench] parity N=25 ring logistic: {parity.history.iters_per_second:.0f} "
        f"iters/sec, iters-to-0.08 = {reached} (reference Table I: 9927), "
        f"final gap {parity.history.objective[-1]:.4f} "
        f"[{time.perf_counter() - t0:.0f}s]",
        file=sys.stderr,
    )
    if not (0 < reached <= parity_cfg.n_iterations):
        raise SystemExit(
            "parity config failed to reach the reference's suboptimality "
            "threshold — refusing to report throughput for a broken optimizer"
        )

    # --- 2. north-star scale config: N=256 decentralized logistic ---
    cfg = parity_cfg.replace(n_workers=256)
    ds = generate_synthetic_dataset(cfg)
    _, f_opt = compute_reference_optimum(ds, cfg.reg_param)

    base_iters = 200
    base = numpy_backend.run(cfg.replace(n_iterations=base_iters), ds, f_opt)
    baseline_ips = base.history.iters_per_second
    print(
        f"[bench] N=256 numpy reference-semantics simulator: "
        f"{baseline_ips:.1f} iters/sec",
        file=sys.stderr,
    )

    # The shared-tunnel chip's throughput varies 2-3x with co-tenant load;
    # report the best of three back-to-back runs to reduce that noise (the
    # convergence gates below use the first run's metrics). Identical
    # workload each time (metrics on) so max() filters only noise.
    result = jax_backend.run(cfg, ds, f_opt)
    hist = result.history
    reps = [float(hist.iters_per_second)]
    for _ in range(2):
        reps.append(float(jax_backend.run(cfg, ds, f_opt).history.iters_per_second))
    jax_ips = max(reps)
    print(
        f"[bench] N=256 jax backend: {jax_ips:.0f} iters/sec best-of-3 "
        f"({'/'.join(f'{r:.0f}' for r in reps)}; "
        f"compile {hist.compile_seconds:.1f}s, final gap "
        f"{hist.objective[-1]:.4f}, consensus {hist.consensus_error[-1]:.2e})",
        file=sys.stderr,
    )
    import numpy as np

    if not np.all(np.isfinite(hist.objective)):
        raise SystemExit("north-star run produced non-finite metrics")
    # Convergence gates on the headline run itself. The N=256 ring cannot
    # reach 1e-4 consensus in 10k iters — its spectral gap (2.0e-4) puts the
    # crossing at ~3e7 iterations, and at this horizon consensus is still in
    # its transient GROWTH phase (~4e-3 → ~0.4, peaking before the ~1/t decay
    # sets in; measured in docs/perf/scaling.json). The literal north-star
    # crossing with measured wall-clock is demonstrated on the N=256 grid by
    # examples/northstar_consensus.py → docs/perf/northstar_consensus.json.
    # Here: the gap must halve (real optimization) and consensus must stay
    # bounded (gossip contraction active, not diverging).
    if not (hist.objective[-1] < 0.5 * hist.objective[0]):
        raise SystemExit(
            "north-star run is not optimizing — refusing to report "
            f"throughput (gap {hist.objective[0]:.4f} -> {hist.objective[-1]:.4f})"
        )
    cons = hist.consensus_error
    if not (np.all(np.isfinite(cons)) and cons[-1] < 1.0):
        raise SystemExit(
            "north-star consensus error is unbounded — refusing to report "
            f"throughput (consensus {cons[0]:.3e} -> {cons[-1]:.3e})"
        )

    print(
        json.dumps(
            {
                "metric": "dsgd_ring_logistic_N256_T10k_iters_per_sec",
                "value": round(jax_ips, 2),
                "unit": "iters/sec",
                "vs_baseline": round(jax_ips / baseline_ips, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
