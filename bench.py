"""Headline benchmark: the BASELINE.json north-star configuration.

Protocol (round 3 — VERDICT r2 item 1): the shared tunneled chip swings
2-3x with co-tenant load, so the jax headline and the CPU baseline are
measured INTERLEAVED — five cycles, each one numpy-simulator segment
followed by one full jax run — and the reported value is the MEDIAN of the
five jax measurements over the MEDIAN of the five numpy measurements, with
the spreads printed alongside. Sequential best-of-N (the round-1/2
protocol) let the two sides sample different chip/host windows and made the
ratio the product of two noisy extremes; medians of interleaved samples
gate out exactly that.

Two measurements, one JSON line:

1. **Parity check** (stderr): the reference study's flagship decentralized
   config — logistic, N=25, ring, T=10,000, full-dataset suboptimality every
   iteration (reference ``main.py:6-21`` / PDF §III-A) — must converge to
   ε ≤ 0.08 in an iteration count consistent with the published Table I
   (9,927). Guards against benchmarking a broken optimizer.

2. **Headline** (stdout JSON): the north-star scale config named in
   BASELINE.json — 256-worker decentralized logistic regression on a ring —
   at T=30,000, a horizon the run actually CROSSES the study's ε ≤ 0.08
   threshold within (measured crossing ≈ iteration 25k,
   docs/perf/northstar_consensus.json; the round-2 T=10k headline ended at
   gap 0.113 > ε, which made "throughput of a converging run" an
   extrapolation). Gates: finite metrics, the ε-crossing itself, and
   bounded consensus.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": ..., "unit": "iters/sec", "vs_baseline": ...}
"""

from __future__ import annotations

import json
import statistics
import sys
import time


def main() -> None:
    import numpy as np

    from distributed_optimization_tpu.backends import jax_backend, numpy_backend
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.metrics import iterations_to_threshold
    from distributed_optimization_tpu.utils.data import generate_synthetic_dataset
    from distributed_optimization_tpu.utils.oracle import compute_reference_optimum

    # --- 1. reference-parity convergence check (N=25, published config) ---
    parity_cfg = ExperimentConfig(
        problem_type="logistic", algorithm="dsgd", topology="ring"
    )  # reference defaults: N=25, T=10000, b=16, eta0=0.05, lambda=1e-4
    t0 = time.perf_counter()
    parity_ds = generate_synthetic_dataset(parity_cfg)
    _, parity_f_opt = compute_reference_optimum(parity_ds, parity_cfg.reg_param)
    parity = jax_backend.run(parity_cfg, parity_ds, parity_f_opt)
    reached = iterations_to_threshold(
        parity.history.objective,
        parity_cfg.suboptimality_threshold,
        parity.history.eval_iterations,
    )
    print(
        f"[bench] parity N=25 ring logistic: {parity.history.iters_per_second:.0f} "
        f"iters/sec, iters-to-0.08 = {reached} (reference Table I: 9927), "
        f"final gap {parity.history.objective[-1]:.4f} "
        f"[{time.perf_counter() - t0:.0f}s]",
        file=sys.stderr,
    )
    if not (0 < reached <= parity_cfg.n_iterations):
        raise SystemExit(
            "parity config failed to reach the reference's suboptimality "
            "threshold — refusing to report throughput for a broken optimizer"
        )

    # --- 2. north-star scale config: N=256 decentralized logistic ---
    # T=30k crosses the study's ε ≤ 0.08 within the horizon (≈ iter 25k).
    cfg = parity_cfg.replace(n_workers=256, n_iterations=30_000)
    ds = generate_synthetic_dataset(cfg)
    _, f_opt = compute_reference_optimum(ds, cfg.reg_param)

    # Interleaved median-of-5: numpy segment, then jax run, x5. The numpy
    # simulator is steady-state (same per-iteration work every iteration),
    # so a 400-iteration segment per cycle samples its rate honestly; the
    # jax run is the full T=30k workload. Each run() call re-traces and
    # re-compiles (the jit cache is keyed on the per-call closures), so the
    # persistent compilation cache is enabled first: the warmup run pays
    # the XLA compile once and every measured cycle deserializes it in
    # ~100 ms — without this, each cycle would insert a multi-second
    # compile window of different co-tenant load between its numpy and jax
    # samples, exactly the chip-window drift interleaving exists to kill.
    # (Throughput numbers exclude compile either way; this is about keeping
    # the paired samples adjacent.) The warmup's metrics drive the
    # convergence gates below.
    import tempfile

    import jax

    cache_dir = tempfile.mkdtemp(prefix="bench_xla_cache_")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    CYCLES = 5
    BASE_SEGMENT_ITERS = 400
    warm = jax_backend.run(cfg, ds, f_opt)
    hist = warm.history

    base_cfg = cfg.replace(n_iterations=BASE_SEGMENT_ITERS)
    numpy_ips: list[float] = []
    jax_ips: list[float] = []
    for cycle in range(CYCLES):
        b = numpy_backend.run(base_cfg, ds, f_opt)
        numpy_ips.append(float(b.history.iters_per_second))
        r = jax_backend.run(cfg, ds, f_opt, measure_compile=False)
        jax_ips.append(float(r.history.iters_per_second))
        print(
            f"[bench] cycle {cycle + 1}/{CYCLES}: numpy "
            f"{numpy_ips[-1]:.1f}, jax {jax_ips[-1]:.0f} iters/sec",
            file=sys.stderr,
        )

    jax_median = statistics.median(jax_ips)
    numpy_median = statistics.median(numpy_ips)
    print(
        f"[bench] N=256 T=30k jax: median {jax_median:.0f} iters/sec "
        f"(spread {min(jax_ips):.0f}-{max(jax_ips):.0f}); numpy "
        f"reference-semantics: median {numpy_median:.1f} "
        f"(spread {min(numpy_ips):.1f}-{max(numpy_ips):.1f}); compile "
        f"{hist.compile_seconds:.1f}s, final gap {hist.objective[-1]:.4f}, "
        f"consensus {hist.consensus_error[-1]:.2e}",
        file=sys.stderr,
    )

    if not np.all(np.isfinite(hist.objective)):
        raise SystemExit("north-star run produced non-finite metrics")
    # The run must cross the study's own suboptimality threshold within its
    # horizon — the headline is the throughput of a run that actually
    # converges to ε, not of a truncated transient.
    crossed = iterations_to_threshold(
        hist.objective, cfg.suboptimality_threshold, hist.eval_iterations
    )
    if not (0 < crossed <= cfg.n_iterations):
        raise SystemExit(
            f"north-star run never reached ε ≤ {cfg.suboptimality_threshold} "
            f"within T={cfg.n_iterations} (final gap {hist.objective[-1]:.4f})"
            " — refusing to report throughput"
        )
    print(
        f"[bench] north-star ε-crossing at iteration {crossed} "
        f"(threshold {cfg.suboptimality_threshold})",
        file=sys.stderr,
    )
    # Consensus must stay bounded (gossip contraction active). The N=256
    # ring's consensus is still in its slow ~1/t phase at T=30k (spectral
    # gap 2e-4); boundedness, not a small absolute value, is the honest
    # gate here (see docs/PERF.md §2 for the full consensus story).
    cons = hist.consensus_error
    if not (np.all(np.isfinite(cons)) and cons[-1] < 1.0):
        raise SystemExit(
            "north-star consensus error is unbounded — refusing to report "
            f"throughput (consensus {cons[0]:.3e} -> {cons[-1]:.3e})"
        )

    print(
        json.dumps(
            {
                "metric": "dsgd_ring_logistic_N256_T30k_iters_per_sec_median5",
                "value": round(jax_median, 2),
                "unit": "iters/sec",
                "vs_baseline": round(jax_median / numpy_median, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
