"""Reference-optimum oracle: f(x*) from sklearn's saga solvers.

Capability parity with reference ``simulator.py:32-69``. The optimum stays a
host-side sklearn computation on purpose — the suboptimality metric needs a
ground truth that is independent of any backend under test.

The load-bearing detail (SURVEY.md §3.5): the study's objective is
*mean* loss + (λ/2)‖w‖², while sklearn penalizes *total* loss, so the sklearn
regularization strength must be α = λ·n_samples (C = 1/α for logistic). The
bias column is stripped before fitting and the intercept re-appended so the
returned w* lives in the same (d+1)-dimensional space as the trained models.
"""

from __future__ import annotations

import numpy as np

from distributed_optimization_tpu.utils.data import HostDataset


def compute_reference_optimum(
    dataset: HostDataset,
    reg_param: float,
    *,
    max_iter: int = 50_000,
    tol: float = 1e-9,
    huber_delta: float | None = None,
    n_classes: int | None = None,
) -> tuple[np.ndarray, float]:
    """Return (w_opt [d], f_opt) for the dataset's problem type.

    ``huber_delta`` sets the Huber transition point (huber only; ``None`` =
    the config default) — the optimum depends on δ, so the oracle must use
    the same δ as the backends under test. ``n_classes`` sets the softmax
    class count (softmax only; ``None`` infers K = max(y) + 1); the
    returned w_opt is the flattened [d·K] parameter, matching the layout
    the backends train.
    """
    from sklearn.linear_model import LogisticRegression, Ridge

    from distributed_optimization_tpu.ops import losses_np

    X_no_bias = dataset.X_full[:, :-1]
    y = dataset.y_full
    n_samples = dataset.X_full.shape[0]
    sklearn_alpha = reg_param * n_samples  # mean-loss λ -> sklearn total-loss α

    if dataset.problem_type == "logistic":
        C = 1.0 / sklearn_alpha if sklearn_alpha > 1e-12 else 1e12
        solver = LogisticRegression(
            C=C,
            fit_intercept=True,
            solver="saga",
            max_iter=max_iter,
            tol=tol,
            random_state=42,
        )
        solver.fit(X_no_bias, y)
        w_opt = np.concatenate([solver.coef_.ravel(), solver.intercept_])
        f_opt = losses_np.logistic_objective(w_opt, dataset.X_full, y, reg_param)
    elif dataset.problem_type == "quadratic":
        solver = Ridge(
            alpha=sklearn_alpha,
            fit_intercept=True,
            solver="saga",
            max_iter=max_iter,
            tol=tol,
            random_state=42,
        )
        solver.fit(X_no_bias, y)
        w_opt = np.concatenate([solver.coef_.ravel(), np.atleast_1d(solver.intercept_)])
        f_opt = losses_np.quadratic_objective(w_opt, dataset.X_full, y, reg_param)
    elif dataset.problem_type == "huber":
        # No sklearn solver minimizes THIS objective (HuberRegressor jointly
        # estimates a scale parameter), so the ground truth is scipy L-BFGS
        # on the float64 numpy twin — still independent of every backend
        # under test (scipy, not jax/cpp; the numpy twin is the shared
        # metric definition all backends are judged against anyway).
        from scipy.optimize import minimize

        from distributed_optimization_tpu.config import DEFAULT_HUBER_DELTA

        delta = DEFAULT_HUBER_DELTA if huber_delta is None else float(huber_delta)
        d = dataset.X_full.shape[1]
        res = minimize(
            lambda w: losses_np.huber_objective(
                w, dataset.X_full, y, reg_param, delta=delta
            ),
            np.zeros(d),
            jac=lambda w: losses_np.huber_gradient(
                w, dataset.X_full, y, reg_param, delta=delta
            ),
            method="L-BFGS-B",
            options={"maxiter": max_iter, "ftol": tol * 1e-2, "gtol": 1e-10},
        )
        w_opt = res.x
        f_opt = losses_np.huber_objective(
            w_opt, dataset.X_full, y, reg_param, delta=delta
        )
    elif dataset.problem_type == "softmax":
        # Multinomial cross-entropy + full-matrix L2: scipy L-BFGS on the
        # float64 numpy twin (like huber — sklearn's multinomial solvers
        # leave one class unpenalized or reparameterize, so they do not
        # minimize THIS objective exactly; the twin is the shared metric
        # definition all backends are judged against anyway). The L2 term
        # makes the objective strictly convex, so the softmax family's
        # usual shift degeneracy is resolved and the optimum is unique.
        from scipy.optimize import minimize

        K = (
            int(n_classes)
            if n_classes is not None
            else int(dataset.y_full.max()) + 1
        )
        d = dataset.X_full.shape[1]
        res = minimize(
            lambda w: losses_np.softmax_objective(
                w, dataset.X_full, y, reg_param
            ),
            np.zeros(d * K),
            jac=lambda w: losses_np.softmax_gradient(
                w, dataset.X_full, y, reg_param
            ),
            method="L-BFGS-B",
            options={"maxiter": max_iter, "ftol": tol * 1e-2, "gtol": 1e-10},
        )
        w_opt = res.x
        f_opt = losses_np.softmax_objective(
            w_opt, dataset.X_full, y, reg_param
        )
    else:
        raise ValueError(f"Unknown problem type: {dataset.problem_type}")

    return w_opt, f_opt
