"""Profiling and phase timing (SURVEY.md §5.1 build target).

The reference's only observability is coarse per-iteration wall-clock deltas
(reference ``trainer.py:35,63,71``). Here:

- ``PhaseTimer`` — named phase accounting (data gen, oracle, compile,
  steady-state run), so compile time never pollutes the iters/sec headline
  (the jax backend already separates AOT compile from execution). The
  ``Simulator`` owns one (``phase_timer``): data-gen and oracle are timed
  at construction, each run splits into compile/run, and the phases land
  in the text report, ``--json``, and the telemetry manifests
  (docs/OBSERVABILITY.md);
- ``trace`` — context manager around ``jax.profiler`` trace collection for
  TensorBoard/XProf on real TPU runs, a no-op when profiling is unavailable.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Iterator, Optional

from distributed_optimization_tpu.log import get_logger

_log = get_logger("profiling")


@dataclasses.dataclass
class PhaseTimer:
    """Accumulates wall-clock seconds per named phase."""

    phases: dict[str, float] = dataclasses.field(default_factory=dict)

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            self.phases[name] = (
                self.phases.get(name, 0.0) + time.perf_counter() - start
            )

    def report(self) -> str:
        total = sum(self.phases.values())
        lines = [f"{'phase':<24}{'seconds':>10}{'share':>8}"]
        for name, secs in sorted(self.phases.items(), key=lambda kv: -kv[1]):
            share = secs / total if total > 0 else 0.0
            lines.append(f"{name:<24}{secs:>10.3f}{share:>7.1%}")
        lines.append(f"{'total':<24}{total:>10.3f}")
        return "\n".join(lines)


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Collect a jax.profiler trace into ``log_dir`` (no-op if None/fails).

    View with TensorBoard's profile plugin / XProf. Failure to start the
    profiler (e.g. unsupported platform) degrades to a no-op rather than
    killing the run.
    """
    if log_dir is None:
        yield
        return
    import jax

    try:
        jax.profiler.start_trace(log_dir)
    except Exception as e:  # pragma: no cover - platform dependent
        _log.warning("trace unavailable: %s", e)
        yield
        return
    try:
        yield
    finally:
        jax.profiler.stop_trace()
