"""Profiling and phase timing (SURVEY.md §5.1 build target).

The reference's only observability is coarse per-iteration wall-clock deltas
(reference ``trainer.py:35,63,71``). Here:

- ``PhaseTimer`` — named phase accounting (data gen, oracle, compile,
  steady-state run), so compile time never pollutes the iters/sec headline
  (the jax backend already separates AOT compile from execution). The
  ``Simulator`` owns one (``phase_timer``): data-gen and oracle are timed
  at construction, each run splits into compile/run, and the phases land
  in the text report, ``--json``, and the telemetry manifests
  (docs/OBSERVABILITY.md). Since ISSUE-10 it IS the hierarchical span
  tracer (``observability/spans.Tracer``): the flat ``{name: seconds}``
  surface is unchanged, and every timed phase is also recorded as a span
  with nesting and timestamps, exportable as a Chrome trace;
- ``trace`` — context manager around ``jax.profiler`` trace collection for
  TensorBoard/XProf on real TPU runs, a no-op when profiling is unavailable.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

from distributed_optimization_tpu.log import get_logger
from distributed_optimization_tpu.observability.spans import Tracer

_log = get_logger("profiling")

# The flat phase accounting grew into hierarchical span tracing
# (ISSUE-10); PhaseTimer remains the name the rest of the repo
# constructs. Tracer is a strict superset: ``.phase(name)`` context
# manager, writable ``.phases`` dict, ``.report()`` — plus ``.span()``
# nesting, ``.add_span()`` post-hoc intervals, and Chrome trace export.
PhaseTimer = Tracer


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Collect a jax.profiler trace into ``log_dir`` (no-op if None/fails).

    View with TensorBoard's profile plugin / XProf. Failure to start the
    profiler (e.g. unsupported platform) degrades to a no-op rather than
    killing the run.
    """
    if log_dir is None:
        yield
        return
    import jax

    try:
        jax.profiler.start_trace(log_dir)
    except Exception as e:  # pragma: no cover - platform dependent
        _log.warning("trace unavailable: %s", e)
        yield
        return
    try:
        yield
    finally:
        jax.profiler.stop_trace()
