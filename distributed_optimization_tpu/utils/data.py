"""Synthetic data generation and non-IID partitioning.

Capability parity with the reference's data layer (reference ``utils.py:5-50``):
sklearn ``make_classification`` / ``make_regression`` with identical
hyperparameters, ``StandardScaler`` standardization, an appended all-ones bias
column (d → d+1), and the *sorted-by-target* partition across workers that
forces label/target heterogeneity (the non-IID knob, ``utils.py:34-38``).

Generation stays host-side numpy on purpose: it is the parity anchor that
makes convergence curves comparable across the numpy oracle backend, the JAX
backend, and the reference's published numbers. The device side gets the data
as *stacked, padded* arrays — ``X [N, L, d]``, ``y [N, L]``, per-worker valid
counts — because N ragged shards would defeat XLA's static-shape compilation;
padding rows carry zero weight everywhere downstream.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class HostDataset:
    """Full dataset + per-worker partition, host-side (numpy, float64)."""

    X_full: np.ndarray  # [n_samples, d] standardized, bias column appended
    y_full: np.ndarray  # [n_samples] (±1 for logistic)
    shard_indices: list[np.ndarray]  # per-worker row indices into X_full
    problem_type: str

    @property
    def n_features(self) -> int:
        return self.X_full.shape[1]

    @property
    def n_workers(self) -> int:
        return len(self.shard_indices)

    def shard(self, i: int) -> tuple[np.ndarray, np.ndarray]:
        idx = self.shard_indices[i]
        return self.X_full[idx], self.y_full[idx]


@dataclasses.dataclass(frozen=True)
class DeviceDataset:
    """Stacked, padded per-worker shards ready for device placement.

    ``X``: [N, L, d], ``y``: [N, L], ``n_valid``: [N] — L is the max shard
    size; rows at index >= n_valid[i] are zero padding.
    """

    X: np.ndarray
    y: np.ndarray
    n_valid: np.ndarray

    @property
    def n_workers(self) -> int:
        return self.X.shape[0]

    @property
    def n_features(self) -> int:
        return self.X.shape[2]


def generate_synthetic_dataset(config) -> HostDataset:
    """Generate the study's synthetic dataset and its non-IID partition.

    Mirrors reference ``utils.py:5-50``: same sklearn generators, same
    hyperparameters (n_redundant = n_features - n_informative,
    n_clusters_per_class=1, flip_y=0.05, random_state=203 by default via
    ``config.resolved_data_seed()`` — ``seed`` unless ``data_seed`` pins the
    problem instance independently; noise=10.0 for regression), labels
    mapped to ±1,
    StandardScaler, bias column, argsort(y) + array_split partition.
    """
    from sklearn.datasets import make_classification, make_regression
    from sklearn.preprocessing import StandardScaler

    if config.problem_type == "logistic":
        X, y = make_classification(
            n_samples=config.n_samples,
            n_features=config.n_features,
            n_informative=config.n_informative_features,
            n_redundant=config.n_features - config.n_informative_features,
            n_clusters_per_class=1,
            flip_y=0.05,
            class_sep=config.classification_sep,
            random_state=config.resolved_data_seed(),
        )
        y = y.astype(np.float64) * 2.0 - 1.0
    elif config.problem_type == "softmax":
        # Same generator as logistic with K classes; labels stay 0..K−1
        # (float-stored class indices — the softmax kernels cast back).
        # The separability constraint is make_classification's, so it lives
        # here with the call, not in config: the digits path has real
        # classes and ignores n_informative_features entirely.
        if config.n_classes > 2**config.n_informative_features:
            raise ValueError(
                f"n_classes ({config.n_classes}) exceeds what "
                f"{config.n_informative_features} informative features can "
                "separate (sklearn make_classification requires n_classes "
                "<= 2^n_informative)"
            )
        X, y = make_classification(
            n_samples=config.n_samples,
            n_features=config.n_features,
            n_informative=config.n_informative_features,
            n_redundant=config.n_features - config.n_informative_features,
            n_classes=config.n_classes,
            n_clusters_per_class=1,
            flip_y=0.05,
            class_sep=config.classification_sep,
            random_state=config.resolved_data_seed(),
        )
        y = y.astype(np.float64)
    elif config.problem_type in ("quadratic", "huber"):
        # Huber shares the regression pipeline (same targets, same noise=10
        # scale its delta is calibrated to).
        X, y = make_regression(
            n_samples=config.n_samples,
            n_features=config.n_features,
            n_informative=config.n_informative_features,
            noise=10.0,
            random_state=config.resolved_data_seed(),
        )
        y = y.astype(np.float64)
    else:
        raise ValueError(f"Unknown problem type: {config.problem_type}")

    X = StandardScaler().fit_transform(X)
    X = np.hstack([X, np.ones((X.shape[0], 1))])  # bias column: d -> d+1

    # Default non-IID partition: sort by target, then split contiguously so
    # each worker sees a narrow slice of the target distribution. The
    # 'shuffled' alternative is the IID counterfactual (seed-deterministic
    # random permutation) — the bounded-heterogeneity regime the Byzantine
    # robust-aggregation analyses assume (docs/BYZANTINE.md), and a control
    # for separating non-IID effects in any experiment.
    if config.partition == "shuffled":
        order = np.random.default_rng(config.resolved_data_seed()).permutation(y.shape[0])
    else:
        order = np.argsort(y)
    shard_indices = [np.asarray(s) for s in np.array_split(order, config.n_workers)]

    return HostDataset(
        X_full=X, y_full=y, shard_indices=shard_indices, problem_type=config.problem_type
    )


def generate_digits_dataset(config) -> HostDataset:
    """Real image-feature dataset (the BASELINE.json "MNIST features" stretch
    config, offline-friendly): sklearn's bundled 8×8 digits (1,797 samples,
    64 pixel features) instead of synthetic data.

    Same preprocessing pipeline as the synthetic path: StandardScaler, bias
    column, sorted-by-target non-IID partition (or the 'shuffled' IID
    control, honoring ``config.partition``). For ``logistic`` the labels
    are binarized to ±1 (digit ≥ 5); for ``quadratic`` the digit value is the
    regression target. ``config.n_samples`` caps the sample count;
    ``n_features`` is ignored (the data has 64).
    """
    from sklearn.datasets import load_digits
    from sklearn.preprocessing import StandardScaler

    X, digit = load_digits(return_X_y=True)
    n = min(config.n_samples, X.shape[0])
    X, digit = X[:n], digit[:n]
    if config.problem_type == "logistic":
        y = np.where(digit >= 5, 1.0, -1.0)
    elif config.problem_type == "softmax":
        # The natural multiclass form of the digits task: the ten digit
        # classes ARE the labels. The config must budget all of them.
        if config.n_classes < 10:
            raise ValueError(
                "digits has 10 classes; softmax needs n_classes >= 10 "
                f"(got {config.n_classes})"
            )
        y = digit.astype(np.float64)
    else:
        y = digit.astype(np.float64)

    X = StandardScaler().fit_transform(X)
    # Constant pixels scale to 0/0; StandardScaler leaves them 0 — fine.
    X = np.hstack([X, np.ones((X.shape[0], 1))])

    if config.partition == "shuffled":
        order = np.random.default_rng(config.resolved_data_seed()).permutation(y.shape[0])
    else:
        order = np.argsort(y, kind="stable")
    shard_indices = [np.asarray(s) for s in np.array_split(order, config.n_workers)]
    return HostDataset(
        X_full=X, y_full=y, shard_indices=shard_indices,
        problem_type=config.problem_type,
    )


def partition_summary(dataset: HostDataset, max_workers: int = 32) -> str:
    """Per-worker shard report, parity with the reference's generation-time
    printout (reference ``utils.py:43-48``): shard size, target range, and
    mean per worker — the lines that make the sorted-partition non-IID skew
    visible — plus the dataset totals line.

    Above ``max_workers`` workers the per-worker lines are truncated to the
    first and last few plus an elision line (the reference prints all N, but
    never runs past N=25; at this repo's sweep scales that would be thousands
    of stderr lines per run).
    """

    def worker_line(i: int) -> str:
        _, yi = dataset.shard(i)
        if len(yi) == 0:
            # n_workers > n_samples leaves trailing shards empty (array_split
            # semantics); runnable downstream, so report rather than crash.
            return f"Worker {i}: 0 samples"
        return (
            f"Worker {i}: {len(yi)} samples, Target y range: "
            f"[{yi.min():.2f}, {yi.max():.2f}], Mean y: {yi.mean():.2f}"
        )

    n = dataset.n_workers
    if n <= max_workers:
        lines = [worker_line(i) for i in range(n)]
    else:
        head, tail = max_workers - 4, 2
        sizes = np.array([len(idx) for idx in dataset.shard_indices])
        lines = [worker_line(i) for i in range(head)]
        lines.append(
            f"... ({n - head - tail} workers elided; shard sizes "
            f"{sizes.min()}-{sizes.max()}) ..."
        )
        lines.extend(worker_line(i) for i in range(n - tail, n))
    lines.append(
        f"Generated {dataset.X_full.shape[0]} samples, "
        f"{dataset.n_features} features"
    )
    return "\n".join(lines)


def stack_shards(dataset: HostDataset, dtype=np.float32) -> DeviceDataset:
    """Stack ragged shards into padded [N, L, d] arrays for the device path.

    Softmax labels are CLASS INDICES and stay int32 regardless of the run
    dtype: under bfloat16 (8-bit significand) every odd index above 256
    would silently round to its even neighbor — at the compute-bound
    tier's K=512 that corrupts ~25% of the labels while throughput looks
    normal. The kernels consume them via ``y.astype(int32)`` either way
    (ops/losses.py softmax section), so only the storage changes.
    """
    n = dataset.n_workers
    d = dataset.n_features
    sizes = np.array([len(idx) for idx in dataset.shard_indices], dtype=np.int32)
    L = int(sizes.max()) if n else 0
    y_dtype = np.int32 if dataset.problem_type == "softmax" else dtype
    X = np.zeros((n, L, d), dtype=dtype)
    y = np.zeros((n, L), dtype=y_dtype)
    for i in range(n):
        Xi, yi = dataset.shard(i)
        X[i, : sizes[i]] = Xi
        y[i, : sizes[i]] = yi
    return DeviceDataset(X=X, y=y, n_valid=sizes)
