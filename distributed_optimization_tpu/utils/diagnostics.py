"""Runtime correctness diagnostics (SURVEY.md §5.2 build target).

The reference's only invariant checking is two asserts on the mixing matrix
(reference ``trainer.py:130-131``). The single-threaded simulator has nothing
to race; on a real collective backend the equivalent hazards are non-finite
propagation, nondeterministic compilation, and mis-wired collectives. Three
checks, all usable as preflight guards or in tests:

- ``nan_debugging`` — scoped ``jax_debug_nans`` so the first NaN-producing
  primitive raises with a traceback instead of silently poisoning a 10k-step
  scan;
- ``check_determinism`` — run a function twice and require bitwise-identical
  outputs (XLA compilations are deterministic given fixed inputs; divergence
  means stray host RNG or nondeterministic collective ordering);
- ``check_collectives`` — ppermute round-trip and psum identities on an
  actual mesh: shifting +1 then −1 along the worker axis must reproduce the
  input exactly, and psum of a one-hot must equal the all-ones vector.
"""

from __future__ import annotations

import contextlib
from functools import partial
from typing import Iterator

import numpy as np


@contextlib.contextmanager
def nan_debugging(enable: bool = True) -> Iterator[None]:
    """Scoped jax_debug_nans: raise at the first NaN-producing op."""
    import jax

    if not enable:
        yield
        return
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def check_determinism(fn, *args, repeats: int = 2) -> None:
    """Require ``fn(*args)`` to be bitwise reproducible across calls.

    Raises AssertionError naming the first differing output leaf.
    """
    import jax

    baseline = jax.tree.map(np.asarray, fn(*args))
    base_leaves, treedef = jax.tree.flatten(baseline)
    for r in range(1, repeats):
        again = jax.tree.map(np.asarray, fn(*args))
        again_leaves, treedef2 = jax.tree.flatten(again)
        if treedef2 != treedef:
            raise AssertionError(
                f"run {r}: output structure changed: {treedef} vs {treedef2}"
            )
        for i, (a, b) in enumerate(zip(base_leaves, again_leaves)):
            if not np.array_equal(a, b, equal_nan=True):
                raise AssertionError(
                    f"run {r}: output leaf {i} is not bitwise reproducible "
                    f"(max abs diff {np.max(np.abs(a - b))})"
                )


def check_collectives(mesh=None) -> None:
    """Verify ppermute round-trip and psum identities on a device mesh.

    Raises AssertionError on any mismatch. Builds an all-device 1-D mesh when
    none is given; a 1-device mesh degenerates gracefully (self-permutes).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributed_optimization_tpu.parallel._compat import shard_map

    from distributed_optimization_tpu.parallel.mesh import WORKER_AXIS, make_worker_mesh

    if mesh is None:
        mesh = make_worker_mesh(len(jax.devices()))
    k = mesh.devices.size
    axis = mesh.axis_names[0] if mesh.axis_names else WORKER_AXIS

    x = np.arange(k * 3, dtype=np.float32).reshape(k, 3)

    @partial(
        shard_map, mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None)
    )
    def roundtrip(block):
        fwd = [(i, (i + 1) % k) for i in range(k)]
        back = [(i, (i - 1) % k) for i in range(k)]
        out = jax.lax.ppermute(block, axis, fwd)
        return jax.lax.ppermute(out, axis, back)

    got = np.asarray(jax.jit(roundtrip)(x))
    if not np.array_equal(got, x):
        raise AssertionError("ppermute +1/-1 round-trip is not the identity")

    @partial(
        shard_map, mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None)
    )
    def total(block):
        return jnp.broadcast_to(
            jax.lax.psum(jnp.sum(block, axis=0, keepdims=True), axis), block.shape
        )

    got = np.asarray(jax.jit(total)(x))
    expect = np.broadcast_to(x.sum(axis=0, keepdims=True), x.shape)
    if not np.allclose(got, expect, rtol=1e-6):
        raise AssertionError("psum over the worker axis disagrees with host sum")
