"""Runtime correctness diagnostics (SURVEY.md §5.2 build target).

The reference's only invariant checking is two asserts on the mixing matrix
(reference ``trainer.py:130-131``). The single-threaded simulator has nothing
to race; on a real collective backend the equivalent hazards are non-finite
propagation, nondeterministic compilation, and mis-wired collectives. Three
checks, all usable as preflight guards or in tests:

- ``nan_debugging`` — scoped ``jax_debug_nans`` so the first NaN-producing
  primitive raises with a traceback instead of silently poisoning a 10k-step
  scan;
- ``check_determinism`` — run a function twice and require bitwise-identical
  outputs (XLA compilations are deterministic given fixed inputs; divergence
  means stray host RNG or nondeterministic collective ordering);
- ``check_collectives`` — ppermute round-trip and psum identities on an
  actual mesh: shifting +1 then −1 along the worker axis must reproduce the
  input exactly, and psum of a one-hot must equal the all-ones vector.
"""

from __future__ import annotations

import contextlib
from functools import partial
from typing import Iterator

import numpy as np


@contextlib.contextmanager
def nan_debugging(enable: bool = True) -> Iterator[None]:
    """Scoped jax_debug_nans: raise at the first NaN-producing op."""
    import jax

    if not enable:
        yield
        return
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def check_determinism(fn, *args, repeats: int = 2) -> None:
    """Require ``fn(*args)`` to be bitwise reproducible across calls.

    Raises AssertionError naming the first differing output leaf.
    """
    import jax

    baseline = jax.tree.map(np.asarray, fn(*args))
    base_leaves, treedef = jax.tree.flatten(baseline)
    for r in range(1, repeats):
        again = jax.tree.map(np.asarray, fn(*args))
        again_leaves, treedef2 = jax.tree.flatten(again)
        if treedef2 != treedef:
            raise AssertionError(
                f"run {r}: output structure changed: {treedef} vs {treedef2}"
            )
        for i, (a, b) in enumerate(zip(base_leaves, again_leaves)):
            if not np.array_equal(a, b, equal_nan=True):
                raise AssertionError(
                    f"run {r}: output leaf {i} is not bitwise reproducible "
                    f"(max abs diff {np.max(np.abs(a - b))})"
                )


def _mesh_and_probe(mesh):
    import jax

    from distributed_optimization_tpu.parallel.mesh import (
        WORKER_AXIS,
        make_worker_mesh,
    )

    if mesh is None:
        mesh = make_worker_mesh(len(jax.devices()))
    k = mesh.devices.size
    axis = mesh.axis_names[0] if mesh.axis_names else WORKER_AXIS
    x = np.arange(k * 3, dtype=np.float32).reshape(k, 3)
    return mesh, k, axis, x


def check_ppermute_roundtrip(mesh=None) -> None:
    """ppermute identity: shifting +1 then −1 along the worker axis must
    reproduce the input exactly. Raises AssertionError on mismatch."""
    import jax
    from jax.sharding import PartitionSpec as P

    from distributed_optimization_tpu.parallel._compat import shard_map

    mesh, k, axis, x = _mesh_and_probe(mesh)

    @partial(
        shard_map, mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None)
    )
    def roundtrip(block):
        fwd = [(i, (i + 1) % k) for i in range(k)]
        back = [(i, (i - 1) % k) for i in range(k)]
        out = jax.lax.ppermute(block, axis, fwd)
        return jax.lax.ppermute(out, axis, back)

    got = np.asarray(jax.jit(roundtrip)(x))
    if not np.array_equal(got, x):
        raise AssertionError("ppermute +1/-1 round-trip is not the identity")


def check_psum_identity(mesh=None) -> None:
    """psum identity: the collective sum over the worker axis must equal the
    host-side sum. Raises AssertionError on mismatch."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributed_optimization_tpu.parallel._compat import shard_map

    mesh, k, axis, x = _mesh_and_probe(mesh)

    @partial(
        shard_map, mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None)
    )
    def total(block):
        return jnp.broadcast_to(
            jax.lax.psum(jnp.sum(block, axis=0, keepdims=True), axis), block.shape
        )

    got = np.asarray(jax.jit(total)(x))
    expect = np.broadcast_to(x.sum(axis=0, keepdims=True), x.shape)
    if not np.allclose(got, expect, rtol=1e-6):
        raise AssertionError("psum over the worker axis disagrees with host sum")


def check_collectives(mesh=None) -> None:
    """Verify ppermute round-trip and psum identities on a device mesh.

    Raises AssertionError on any mismatch. Builds an all-device 1-D mesh when
    none is given; a 1-device mesh degenerates gracefully (self-permutes).
    """
    check_ppermute_roundtrip(mesh)
    check_psum_identity(mesh)


class PreflightError(RuntimeError):
    """One named preflight identity failed; ``check`` is its identity name,
    ``cause`` the underlying assertion/exception."""

    def __init__(self, check: str, cause: BaseException):
        super().__init__(f"preflight check {check!r} failed: {cause}")
        self.check = check
        self.cause = cause


def _determinism_probe() -> None:
    """Bitwise reproducibility of a jit'd program mixing counter-based RNG
    with an MXU matmul and a sort — the op classes whose nondeterministic
    compilation or stray host RNG ``check_determinism`` exists to catch."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def probe(key):
        x = jax.random.normal(key, (16, 16), dtype=jnp.float32)
        return jnp.sum(x @ x.T), jnp.sort(x.ravel())[:4]

    check_determinism(probe, jax.random.key(0))


# The CLI preflight's named identities (--preflight): run in order, fail
# loudly at the FIRST broken one with its identity named (PreflightError).
PREFLIGHT_CHECKS = (
    ("collectives.ppermute_roundtrip", check_ppermute_roundtrip),
    ("collectives.psum_identity", check_psum_identity),
    ("determinism.jit_rng_matmul_sort", lambda mesh=None: _determinism_probe()),
)


def run_preflight(mesh=None) -> list[str]:
    """Run every preflight identity; return the names that passed.

    Raises ``PreflightError`` naming the first failing identity — the CLI
    surfaces it verbatim so a broken runtime is diagnosed before any
    compile/run time is spent on the main experiment.
    """
    passed: list[str] = []
    for name, check in PREFLIGHT_CHECKS:
        try:
            check(mesh)
        except Exception as e:
            raise PreflightError(name, e) from e
        passed.append(name)
    return passed
