"""Host-side utilities: data generation, reference optimum, I/O helpers."""

from distributed_optimization_tpu.utils.data import (  # noqa: F401
    DeviceDataset,
    HostDataset,
    generate_synthetic_dataset,
    stack_shards,
)
from distributed_optimization_tpu.utils.oracle import compute_reference_optimum  # noqa: F401
