"""Checkpoint / resume (orbax-backed).

The reference has no persistence at all — state lives in memory and results
go to stdout / an interactive plot (SURVEY.md §5.4). This subsystem saves the
full restartable run state — the algorithm state pytree (every leaf is an
``[N, d]``-stacked array), the metric histories accumulated so far, and the
chunk cursor — via ``orbax.checkpoint``, so long runs survive preemption
(standard TPU-pod operating reality) and the 256-worker stretch config can
run in installments.

RNG needs no saved state by construction: batch sampling derives keys purely
from (config.seed, iteration, slot) via ``jax.random.fold_in``, so a resumed
run draws exactly the batches the uninterrupted run would have (a
deliberate improvement over the reference's single mutable global numpy
stream, SURVEY.md §3.4).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class CheckpointOptions:
    """Where and how often to checkpoint a run.

    ``every_evals``: save cadence in eval-chunks (one chunk = ``eval_every``
    iterations). ``resume``: restore the latest checkpoint under ``directory``
    and continue from its cursor. ``max_to_keep``: retention.
    """

    directory: str
    every_evals: int = 10
    resume: bool = True
    max_to_keep: int = 3

    def __post_init__(self) -> None:
        if self.every_evals <= 0:
            raise ValueError("every_evals must be positive")


class RunCheckpointer:
    """Thin orbax wrapper for one run directory.

    Layout: ``<directory>/<chunk>/`` orbax PyTree checkpoints of
    ``{"state": pytree, "gap_hist": [k], "cons_hist": [k], "chunk": k}``.
    """

    def __init__(self, options: CheckpointOptions):
        import orbax.checkpoint as ocp

        self.options = options
        self.directory = os.path.abspath(options.directory)
        os.makedirs(self.directory, exist_ok=True)
        self._ckptr = ocp.PyTreeCheckpointer()

    def _step_dir(self, chunk: int) -> str:
        return os.path.join(self.directory, f"{chunk:08d}")

    # A config sidecar guards against resuming state produced by a different
    # experiment (the horizon n_iterations is the one legitimately resumable
    # difference — extending a run).
    _CONFIG_SIDECAR = "run_config.json"
    _RESUMABLE_KEYS = frozenset({"n_iterations"})

    def validate_or_record_config(
        self, config, resumable_keys: Optional[frozenset] = None,
    ) -> None:
        """First save records the config; later runs must match it.

        Raises ValueError naming the mismatched fields when the directory was
        written by a different experiment. ``resumable_keys`` overrides the
        class default: the async event path passes ``frozenset()`` because
        its event schedule is horizon-GLOBAL (events interleave across
        rounds by completion time), so extending ``n_iterations`` would
        replay a different event prefix than the one the saved chunks
        executed — not a legitimate resume.
        """
        import json

        if resumable_keys is None:
            resumable_keys = self._RESUMABLE_KEYS
        path = os.path.join(self.directory, self._CONFIG_SIDECAR)
        current = {
            k: v for k, v in config.to_dict().items()
            if k not in resumable_keys
        }
        if not os.path.exists(path):
            with open(path, "w") as f:
                json.dump(current, f, indent=1)
            return
        with open(path) as f:
            recorded = json.load(f)
        diffs = sorted(
            k for k in set(recorded) | set(current)
            if recorded.get(k) != current.get(k)
        )
        if diffs:
            raise ValueError(
                f"checkpoint directory {self.directory} was written by a "
                f"different experiment (mismatched config fields: {diffs}); "
                "point --checkpoint-dir elsewhere, or pass resume=False "
                "(--no-resume) to clear it and start fresh"
            )

    def reset(
        self, config, resumable_keys: Optional[frozenset] = None,
    ) -> None:
        """Start the directory fresh for a ``resume=False`` run.

        Clears every existing chunk checkpoint (a fresh run that leaves stale
        higher-numbered chunks behind would poison a LATER resume) and
        rewrites the config sidecar, so reusing a directory written by a
        different experiment is allowed when the caller explicitly opted out
        of resuming. ``resumable_keys`` is forwarded to the sidecar write so
        a caller that pins extra fields (the async event path pins
        ``n_iterations``) records them for its own later resumes.
        """
        import contextlib
        import shutil

        for chunk in self.completed_chunks():
            shutil.rmtree(self._step_dir(chunk), ignore_errors=True)
        with contextlib.suppress(FileNotFoundError):
            os.remove(os.path.join(self.directory, self._CONFIG_SIDECAR))
        # First-write path: records.
        self.validate_or_record_config(config, resumable_keys)

    def completed_chunks(self) -> list[int]:
        """Chunk numbers with a plausibly-complete checkpoint directory.

        Robust against crash-mid-save debris: orbax staging directories
        (``<step>.orbax-checkpoint-tmp-<ts>`` and any other non-digit
        name) and empty chunk directories (a crash between mkdir and the
        first write) are skipped. A chunk dir that LOOKS complete but was
        truncated mid-write is caught later by ``restore``'s
        fall-back-to-previous-intact-chunk path — completeness of the
        orbax payload can only be established by reading it.
        """
        out = []
        for name in os.listdir(self.directory):
            path = os.path.join(self.directory, name)
            if not (name.isdigit() and os.path.isdir(path)):
                continue  # sidecar, orbax tmp/staging dirs, foreign files
            try:
                if not os.listdir(path):
                    continue  # crashed between mkdir and first write
            except OSError:
                continue
            out.append(int(name))
        return sorted(out)

    def latest_chunk(self) -> Optional[int]:
        chunks = self.completed_chunks()
        return chunks[-1] if chunks else None

    def save(
        self, chunk: int, state: Any, gap_hist, cons_hist, floats_hist=(),
        time_hist=(),
    ):
        payload = {"state": state, "chunk": np.int64(chunk)}
        # Orbax rejects zero-size arrays; empty histories are simply omitted
        # and default to empty on restore.
        for name, hist in (
            ("gap_hist", gap_hist),
            ("cons_hist", cons_hist),
            ("floats_hist", floats_hist),
            ("time_hist", time_hist),
        ):
            arr = np.asarray(hist, dtype=np.float64)
            if arr.size:
                payload[name] = arr
        path = self._step_dir(chunk)
        self._ckptr.save(path, payload, force=True)
        self._gc()

    def restore(self, chunk: Optional[int] = None):
        """Return (state, gap_hist, cons_hist, floats_hist, time_hist, chunk),
        or None.

        With ``chunk=None`` (the resume path), a latest chunk directory
        that fails to restore — a crash mid-save can leave a
        complete-looking but truncated orbax payload — is skipped with a
        warning and the previous intact chunk is restored instead (the run
        just re-executes the lost chunks; resume-exactness is unaffected
        because all RNG derives from (seed, t)). An EXPLICIT chunk request
        still raises, so callers asking for a specific checkpoint see the
        corruption."""
        if chunk is not None:
            return self._unpack(self._ckptr.restore(self._step_dir(chunk)))
        for c in reversed(self.completed_chunks()):
            try:
                payload = self._ckptr.restore(self._step_dir(c))
            except Exception as e:  # orbax raises various types here
                import warnings

                warnings.warn(
                    f"checkpoint chunk {c} at {self._step_dir(c)} is "
                    f"partial or corrupt ({type(e).__name__}: {e}); "
                    "falling back to the previous intact chunk",
                    stacklevel=2,
                )
                continue
            return self._unpack(payload)
        return None

    @staticmethod
    def _unpack(payload):
        empty = np.empty(0, dtype=np.float64)
        return (
            payload["state"],
            np.asarray(payload.get("gap_hist", empty)),
            np.asarray(payload.get("cons_hist", empty)),
            np.asarray(payload.get("floats_hist", empty)),
            np.asarray(payload.get("time_hist", empty)),
            int(payload["chunk"]),
        )

    def _gc(self) -> None:
        import shutil

        chunks = self.completed_chunks()
        for old in chunks[: -self.options.max_to_keep]:
            shutil.rmtree(self._step_dir(old), ignore_errors=True)
