"""Metrics: suboptimality, consensus error, comms cost, iterations-to-threshold.

These four metrics ARE the product of the reference study (SURVEY.md §5.5) and
are reproduced bit-comparably in definition:

- suboptimality gap  f(x̄_t) − f(x*)  on the FULL dataset every recorded
  iteration (reference ``trainer.py:66-69,188-191``);
- consensus error  (1/N) Σ_i ‖x_i − x̄‖²  (reference ``trainer.py:184-186``);
- total floats transmitted — an *analytic* cost model, kept even though the
  TPU backend performs real collectives, so numbers stay comparable with the
  reference's Tables I/II (closed forms below);
- iterations to reach a suboptimality threshold (reference
  ``simulator.py:73-79``).

On the TPU path the per-iteration values accumulate on device inside the
``lax.scan`` carry/ys and are fetched once per run — no per-iteration host
syncs (the reference pays a full-dataset numpy objective evaluation on the
host every iteration, ``trainer.py:67``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from distributed_optimization_tpu.parallel.topology import Topology



@dataclasses.dataclass
class RunHistory:
    """Per-iteration history of one training run (host numpy arrays)."""

    objective: np.ndarray  # suboptimality gap f(x̄_t) − f(x*), [T_recorded]
    consensus_error: Optional[np.ndarray]  # [T_recorded] or None (centralized)
    time: np.ndarray  # wall-clock seconds since run start, [T_recorded]
    eval_iterations: np.ndarray  # iteration numbers (1-based) the rows refer to
    total_floats_transmitted: float
    iters_per_second: float = float("nan")
    compile_seconds: float = 0.0  # AOT compile time (jax backend; 0 for numpy)
    spectral_gap: Optional[float] = None  # 1 − ρ of the run's mixing matrix
    # True when ``time`` holds real per-eval perf_counter samples (the
    # reference's trainer.py:63,181 measurement); False when it is a linspace
    # interpolation of the total run wall-clock (fully fused scan) — the
    # report marks derived sec→ε values accordingly.
    time_measured: bool = False
    # Flight-recorder buffers (config.telemetry; telemetry.TRACE_FIELDS):
    # dict of per-eval-row health series — [n_evals] scalars and
    # [n_evals, N] per-worker rows, float32 — or None when telemetry is off
    # or the backend records none (cpp).
    trace: Optional[dict] = None
    # XLA cost analysis of the compiled program (telemetry.cost_from_lowered:
    # flops, bytes_accessed, ...); None off the jax path or when telemetry
    # is off.
    cost: Optional[dict] = None

    def as_dict(self) -> dict:
        out = {
            "objective": self.objective.tolist(),
            "time": self.time.tolist(),
            "time_measured": self.time_measured,
        }
        if self.consensus_error is not None:
            out["consensus_error"] = self.consensus_error.tolist()
        return out


def consensus_error(models: np.ndarray) -> float:
    """(1/N) Σ_i ‖x_i − x̄‖² for an [N, d] model stack."""
    mean = models.mean(axis=0)
    return float(np.mean(np.sum((models - mean) ** 2, axis=1)))


def honest_mean(models: np.ndarray, byzantine: np.ndarray) -> np.ndarray:
    """Average model over the honest rows only.

    Under Byzantine injection (docs/BYZANTINE.md) the network-wide mean is
    meaningless — the adversary controls its own rows outright — so every
    reported metric conditions on the honest set: suboptimality becomes
    f(x̄_honest) − f(x*) and consensus becomes the honest spread around
    x̄_honest. ``byzantine`` is the static [N] bool mask from
    ``parallel.adversary.byzantine_mask`` (all-False reduces both to the
    standard definitions).
    """
    return models[~np.asarray(byzantine, dtype=bool)].mean(axis=0)


def honest_consensus_error(models: np.ndarray, byzantine: np.ndarray) -> float:
    """(1/H) Σ_{honest i} ‖x_i − x̄_honest‖² — Byzantine rows excluded."""
    return consensus_error(models[~np.asarray(byzantine, dtype=bool)])


def iterations_to_threshold(objective_history: np.ndarray, threshold: float,
                            eval_iterations: Optional[np.ndarray] = None) -> int:
    """First (1-based) iteration whose suboptimality gap <= threshold, or -1.

    Parity: reference ``simulator.py:73-79``. ``eval_iterations`` maps row
    index -> iteration number when eval_every > 1.
    """
    if objective_history.size == 0:
        return -1
    below = np.nonzero(objective_history <= threshold)[0]
    if below.size == 0:
        return -1
    first = int(below[0])
    if eval_iterations is not None:
        return int(eval_iterations[first])
    return first + 1


def centralized_floats_per_iteration(n_workers: int, n_features: int) -> float:
    """2·N·d floats/iter: N gradient uploads + N model broadcasts.

    Parity: reference ``trainer.py:44-61``. Closed form over T iterations is
    2NdT = 4.05e7 for the report config (BASELINE.md).
    """
    return 2.0 * n_workers * n_features


def decentralized_floats_per_iteration(
    topo: Topology, n_features: int, gossip_rounds: int = 1
) -> float:
    """Σ_i deg_i · d floats per gossip round, times the algorithm's rounds
    (``Algorithm.gossip_rounds``: 2 for gradient tracking, which mixes both
    the model and tracker arrays; 1 otherwise).

    Parity: reference ``trainer.py:169-170``. Closed form ΣdegᵢdT gives
    4.05e7 (ring) / 8.1e7 (grid) / 4.86e8 (fc) for the report config.
    """
    return topo.floats_per_iteration * n_features * gossip_rounds


@dataclasses.dataclass
class ReplicateStats:
    """Seed-variance summary of a replica-batched run (ISSUE-4).

    Every scalar the single-run report quotes becomes a (mean, std) pair
    over the R replicas — the statistical statement a single seed's
    trajectory cannot make. ``iterations_to_threshold_*`` aggregate over
    the replicas that REACHED the threshold (``n_reached`` of
    ``n_replicas``); both are NaN when none did. Stds are population
    (ddof=0) over the replicas actually aggregated.
    """

    n_replicas: int
    seeds: list
    final_gap_mean: float
    final_gap_std: float
    consensus_mean: Optional[float]  # None when consensus was not tracked
    consensus_std: Optional[float]
    iterations_to_threshold_mean: float
    iterations_to_threshold_std: float
    n_reached: int
    per_replica_iterations: list  # -1 = that replica never reached ε
    aggregate_iters_per_second: float


def summarize_replicates(
    objective: np.ndarray,  # [R, n_evals] per-replica suboptimality gaps
    consensus: Optional[np.ndarray],  # [R, n_evals] or None
    eval_iterations: np.ndarray,
    threshold: float,
    seeds: list,
    aggregate_iters_per_second: float,
) -> ReplicateStats:
    """Reduce a batch's [R, n_evals] histories to mean ± std statistics."""
    R = objective.shape[0]
    finals = objective[:, -1]
    per_rep = [
        iterations_to_threshold(objective[r], threshold, eval_iterations)
        for r in range(R)
    ]
    reached = np.asarray([it for it in per_rep if it > 0], dtype=np.float64)
    return ReplicateStats(
        n_replicas=R,
        seeds=list(seeds),
        final_gap_mean=float(np.mean(finals)),
        final_gap_std=float(np.std(finals)),
        consensus_mean=(
            float(np.mean(consensus[:, -1])) if consensus is not None else None
        ),
        consensus_std=(
            float(np.std(consensus[:, -1])) if consensus is not None else None
        ),
        iterations_to_threshold_mean=(
            float(reached.mean()) if reached.size else float("nan")
        ),
        iterations_to_threshold_std=(
            float(reached.std()) if reached.size else float("nan")
        ),
        n_reached=int(reached.size),
        per_replica_iterations=per_rep,
        aggregate_iters_per_second=aggregate_iters_per_second,
    )


@dataclasses.dataclass
class NumericalResult:
    """One row of the experiment report (reference ``simulator.py:88-92``)."""

    label: str
    iterations_to_threshold: int  # -1 = never reached
    total_transmission_floats: float
    avg_worker_transmission_floats: float
    spectral_gap: Optional[float] = None
    iters_per_second: float = float("nan")
    seconds_to_threshold: float = float("nan")  # wall clock; nan = never
    time_measured: bool = False  # sec→ε from real timestamps vs interpolation


def summarize_run(
    label: str,
    history: RunHistory,
    threshold: float,
    n_workers: int,
    spectral_gap: Optional[float] = None,
) -> NumericalResult:
    # One derivation of the threshold-crossing row serves both metrics.
    below = (
        np.nonzero(history.objective <= threshold)[0]
        if history.objective.size else np.empty(0, dtype=int)
    )
    if below.size:
        row = int(below[0])
        iters = int(history.eval_iterations[row])
        seconds = (
            float(history.time[row]) if row < history.time.size else float("nan")
        )
    else:
        iters, seconds = -1, float("nan")
    total = history.total_floats_transmitted
    return NumericalResult(
        label=label,
        iterations_to_threshold=iters,
        total_transmission_floats=total,
        avg_worker_transmission_floats=total / n_workers if n_workers else 0.0,
        spectral_gap=spectral_gap,
        iters_per_second=history.iters_per_second,
        seconds_to_threshold=seconds,
        time_measured=history.time_measured,
    )
