"""Stdlib-only HTTP daemon over ``SimulationService`` (docs/SERVING.md).

``python -m distributed_optimization_tpu.serve`` boots it. No new runtime
dependencies: ``http.server`` + JSON lines. Protocol (all bodies JSON;
manifests are STRICT JSON via the telemetry layer's non-finite sentinel
encoding, so ``jq``/``JSON.parse`` read them even for divergent runs):

- ``POST /v1/submit``  — body: an ExperimentConfig field object (or
  ``{"config": {...}}``). 202 → ``{"id", "status", "queue_depth"}``.
  Malformed JSON / unknown fields / invalid configs → 400 with
  ``{"error", "detail"}`` carrying the config validation message; the
  request never enters the queue and in-flight work is untouched.
- ``POST /v1/run``     — submit AND wait; streams the finished request's
  RunTrace manifest back as one JSONL line (the curl one-liner in
  docs/SERVING.md). ``?timeout=S`` bounds the wait (default 300).
- ``GET /v1/result/<id>[?timeout=S]`` — the manifest once done (200), a
  status object while queued/running (202), 404 for unknown ids, 500
  body with the failure message for failed requests.
- ``GET /v1/progress/<id>[?timeout=S&after=SEQ]`` — LIVE streaming JSONL
  (ISSUE-10): one line per heartbeat (lifecycle events + the backend's
  per-chunk progress — iteration, wall seconds, current gap/consensus,
  live B̂, staleness quantiles on async runs), replayed from ``after``
  and followed until the request finishes or ``timeout`` (default 300 s)
  elapses. The response has no Content-Length and closes when the
  stream ends — read it line by line (``curl -N``).
- ``GET /v1/status``   — service stats: queue depth, cohort/coalescing
  counters, executable-cache hits/misses/compile-seconds-saved (counter
  blocks ALWAYS present, zeros before any work), and the bounded
  last-K finished-request history.
- ``GET /metrics``     — the process metrics registry in Prometheus text
  exposition format (cache, coalescer, queue, progress, async-staleness
  families; one consistent snapshot per scrape).
- ``POST /v1/shutdown`` — drain nothing, stop accepting, exit cleanly.
  ``?drain=1[&deadline=S]`` (ISSUE-15) drains gracefully instead: new
  submissions get 503 while queued + in-flight cohorts finish (bounded
  by the deadline, default 30 s), then the daemon exits; the response
  reports ``drained: true/false``.

Admission (ISSUE-15): the wrapped submit form ``{"config": {...},
"tenant": "acme", "priority": "high"}`` tags the request for the
weighted-fair scheduler; per-tenant caps shed with 429 + a machine-
readable reason. Bare config bodies run as tenant "default".
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from distributed_optimization_tpu.log import get_logger
from distributed_optimization_tpu.serving.service import (
    DONE,
    FAILED,
    DrainingError,
    QueueFullError,
    ServingError,
    ServingOptions,
    SimulationService,
)

_log = get_logger("serving.daemon")

DEFAULT_PORT = 8421
DEFAULT_RUN_TIMEOUT_S = 300.0
MAX_BODY_BYTES = 1_000_000  # a config object is ~1 KB; bound hostile bodies
# Per-connection socket timeout (ISSUE-12 satellite). Without one, a
# client that connects and never completes a request — or opens a
# streaming response and never reads — pins its handler thread FOREVER
# (rfile.readline / wfile.write block indefinitely), and a handful of
# stalled clients exhaust the threaded server. The timeout bounds every
# blocking socket op; on expiry the read loop closes the connection and
# the streaming writers bail out through their OSError handling. It must
# comfortably exceed the heartbeat cadence so live progress streams are
# never cut between events.
DEFAULT_SOCKET_TIMEOUT_S = 75.0


def _strict_json(obj) -> bytes:
    from distributed_optimization_tpu.telemetry import _encode_nonfinite

    return (
        json.dumps(_encode_nonfinite(obj), sort_keys=True, allow_nan=False)
        + "\n"
    ).encode()


class _Handler(BaseHTTPRequestHandler):
    # The service lives on the server object (one per daemon).
    server: "_Server"

    protocol_version = "HTTP/1.1"

    def setup(self) -> None:
        super().setup()
        timeout = self.server.socket_timeout_s
        if timeout and timeout > 0:
            # Bounds EVERY blocking op on this connection (request reads,
            # response and stream writes); http.server's read loop maps
            # the read-side expiry to close_connection itself.
            self.connection.settimeout(timeout)

    def handle(self) -> None:
        try:
            super().handle()
        except (TimeoutError, ConnectionError, OSError) as e:
            # A write-side stall (client stopped reading) surfaces here
            # once the kernel buffer fills and the socket timeout fires:
            # log one debug line instead of a traceback; socketserver
            # tears the connection down on return and the handler thread
            # is reclaimed.
            _log.debug(
                "dropping stalled/broken connection from %s: %s",
                self.client_address, e,
            )

    def log_message(self, fmt, *args):  # route http.server chatter to our log
        _log.debug("%s " + fmt, self.address_string(), *args)

    # ------------------------------------------------------------- helpers
    def _send(self, code: int, payload: dict, *, jsonl: bool = False) -> None:
        body = _strict_json(payload)
        self.send_response(code)
        self.send_header(
            "Content-Type",
            "application/x-ndjson" if jsonl else "application/json",
        )
        self.send_header("Content-Length", str(len(body)))
        if self.close_connection:
            # A route decided the connection cannot be reused (e.g. an
            # oversized body it refused to read); say so on the wire.
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, error: str, detail: str = "") -> None:
        self._send(code, {"error": error, "detail": detail})

    def _read_config(self) -> Optional[tuple]:
        """Parse the request body into ``(config_dict, tenant, priority)``,
        or answer 400 and return None. Structured errors, never a dead
        connection. The admission fields ride the WRAPPED form only —
        ``{"config": {...}, "tenant": "...", "priority": "..."}`` — so a
        bare config object stays exactly the PR-7 protocol."""
        try:
            length = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            length = 0
        if length <= 0:
            self._error(400, "empty_body",
                        "POST a JSON ExperimentConfig object")
            return None
        if length > MAX_BODY_BYTES:
            # Refusing to READ the oversized body would desync a
            # keep-alive connection (the unread bytes would parse as the
            # next request line), so this rejection also closes it.
            self.close_connection = True
            self._error(400, "body_too_large",
                        f"config bodies are capped at {MAX_BODY_BYTES} bytes")
            return None
        raw = self.rfile.read(length)
        try:
            payload = json.loads(raw)
        except json.JSONDecodeError as e:
            self._error(400, "malformed_json", str(e))
            return None
        tenant = priority = None
        if isinstance(payload, dict) and isinstance(
            payload.get("config"), dict
        ):
            tenant = payload.get("tenant")
            priority = payload.get("priority")
            payload = payload["config"]
        if not isinstance(payload, dict):
            self._error(
                400, "invalid_request",
                "body must be a JSON object of ExperimentConfig fields "
                "(optionally wrapped as {\"config\": {...}, "
                "\"tenant\": ..., \"priority\": ...})",
            )
            return None
        return payload, tenant, priority

    def _query(self) -> dict:
        return parse_qs(urlparse(self.path).query)

    def _timeout(self, default: float) -> float:
        q = self._query().get("timeout")
        try:
            return float(q[0]) if q else default
        except ValueError:
            return default

    def _respond_request(self, req) -> None:
        if req.status == DONE:
            self._send(200, req.manifest, jsonl=True)
        elif req.status == FAILED:
            self._send(500, {
                **req.status_dict(),
                "error": "run_failed",
                "detail": req.error,
            })
        else:
            self._send(202, {
                **req.status_dict(),
                "queue_depth": self.server.service.queue_depth(),
            })

    # ------------------------------------------------------------- routes
    def do_POST(self) -> None:  # noqa: N802 (http.server API)
        path = urlparse(self.path).path.rstrip("/")
        service = self.server.service
        if path == "/v1/shutdown":
            q = self._query()
            if q.get("drain", ["0"])[0] in ("1", "true", "yes"):
                # Graceful drain (ISSUE-15 satellite): refuse new
                # submissions (503), finish queued + in-flight cohorts
                # within the deadline, then exit. The response reports
                # whether the drain actually emptied the service so
                # operators can tell a clean stop from a deadline kill.
                try:
                    deadline = float(q.get("deadline", ["30"])[0])
                except ValueError:
                    deadline = 30.0
                service.begin_drain()
                drained = service.wait_drained(timeout=deadline)
                self._send(200, {
                    "status": "shutting_down",
                    "drained": drained,
                })
            else:
                # The PR-7 default, unchanged: drain nothing, stop now.
                self._send(200, {"status": "shutting_down"})
            self.server.initiate_shutdown()
            return
        if path not in ("/v1/submit", "/v1/run"):
            self._error(404, "unknown_endpoint", path)
            return
        parsed = self._read_config()
        if parsed is None:
            return
        payload, tenant, priority = parsed
        try:
            request_id = service.submit(
                payload, tenant=tenant, priority=priority
            )
        except QueueFullError as e:
            # Backpressure is retryable server state, not a bad request —
            # a distinct status so clients can implement retry without
            # string-matching the detail. Shed-load rejections carry the
            # admission reason + tenant for dashboards and tests.
            self._send(429, {
                "error": "queue_full",
                "detail": str(e),
                "reason": e.reason,
                "tenant": e.tenant,
            })
            return
        except DrainingError as e:
            # Retryable by the client contract — the drain precedes a
            # restart that will take the retry. Must be checked before
            # ServingError (it IS one).
            self._error(503, "draining", str(e))
            return
        except ServingError as e:
            # The structured rejection (config validation message included)
            # — a poison submission answers 400 and touches nothing else.
            self._error(400, "invalid_config", str(e))
            return
        if path == "/v1/submit":
            self._send(202, {
                "id": request_id,
                "status": "queued",
                "queue_depth": service.queue_depth(),
            })
            return
        try:
            req = service.result(
                request_id, timeout=self._timeout(DEFAULT_RUN_TIMEOUT_S)
            )
        except TimeoutError as e:
            self._error(504, "timeout", str(e))
            return
        self._respond_request(req)

    def _stream_progress(self, req) -> None:
        """Stream a request's heartbeats as JSONL until it finishes (or
        the timeout elapses). No Content-Length — the body is terminated
        by connection close, so a client reads lines as they arrive
        (``curl -N``); buffered events replay first (``?after=SEQ``
        resumes a reconnect past what it already saw)."""
        q = self._query()
        try:
            after = int(q["after"][0]) if "after" in q else -1
        except ValueError:
            after = -1
        timeout = self._timeout(DEFAULT_RUN_TIMEOUT_S)
        self.close_connection = True
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()
        try:
            for payload in req.progress.follow(after, timeout=timeout):
                self.wfile.write(_strict_json(payload))
                self.wfile.flush()
        except (TimeoutError, ConnectionError, OSError):
            # Client went away mid-stream, or stopped reading long enough
            # for the connection's socket timeout to fire (a stalled
            # reader must not pin this streaming thread): nothing to
            # clean up, the stream just ends.
            pass

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        path = urlparse(self.path).path.rstrip("/")
        service = self.server.service
        if path == "/v1/status":
            self._send(200, {"status": "serving", **service.stats()})
            return
        if path == "/metrics":
            from distributed_optimization_tpu.observability.metrics_registry import (  # noqa: E501
                metrics_registry,
            )

            body = metrics_registry().render().encode()
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if path.startswith("/v1/progress/"):
            request_id = path[len("/v1/progress/"):]
            try:
                req = service.get(request_id)
            except KeyError:
                self._error(404, "unknown_request", request_id)
                return
            self._stream_progress(req)
            return
        if path.startswith("/v1/result/"):
            request_id = path[len("/v1/result/"):]
            try:
                req = service.get(request_id)
            except KeyError:
                self._error(404, "unknown_request", request_id)
                return
            timeout = self._timeout(0.0)
            if timeout > 0:
                req.done.wait(timeout)
            self._respond_request(req)
            return
        self._error(404, "unknown_endpoint", path)


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    # Serving requests block for seconds; keep the accept queue generous.
    request_queue_size = 32

    def __init__(
        self, addr, service: SimulationService,
        socket_timeout_s: float = DEFAULT_SOCKET_TIMEOUT_S,
    ):
        super().__init__(addr, _Handler)
        self.service = service
        self.socket_timeout_s = socket_timeout_s

    def initiate_shutdown(self) -> None:
        # shutdown() must not run on a handler thread (it joins the serve
        # loop); hand it to a one-shot thread.
        threading.Thread(target=self.shutdown, daemon=True).start()


class ServingDaemon:
    """The HTTP daemon: owns a ``SimulationService`` (scheduler started)
    and a threading HTTP server. ``serve_forever()`` blocks (the CLI
    mode); ``start()``/``stop()`` run it on a background thread (tests,
    ``make serve-smoke``)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = DEFAULT_PORT,
        options: Optional[ServingOptions] = None,
        *,
        service: Optional[SimulationService] = None,
        socket_timeout_s: float = DEFAULT_SOCKET_TIMEOUT_S,
    ):
        self.service = service or SimulationService(options)
        self._server = _Server(
            (host, port), self.service, socket_timeout_s=socket_timeout_s,
        )
        self._thread: Optional[threading.Thread] = None
        # Optional fleet autoscaler (ISSUE-16): assigned before
        # serve_forever()/start(), started once the service is up, and
        # stopped by service.close() (which owns the ordering: autoscaler
        # first, then the pool it scales).
        self.autoscaler = None

    @property
    def address(self) -> tuple:
        return self._server.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def serve_forever(self) -> None:
        self.service.start()
        if self.autoscaler is not None:
            self.autoscaler.start()
        host, port = self.address
        _log.info("simulation service listening on http://%s:%s", host, port)
        try:
            self._server.serve_forever(poll_interval=0.2)
        finally:
            self.close()

    def start(self) -> None:
        self.service.start()
        if self.autoscaler is not None:
            self.autoscaler.start()
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="serving-daemon", daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.close()

    def close(self) -> None:
        self.service.close()
        self._server.server_close()


def main(argv=None) -> int:
    """``python -m distributed_optimization_tpu.serve`` entry point."""
    import argparse

    from distributed_optimization_tpu.log import configure as configure_logging

    p = argparse.ArgumentParser(
        prog="distributed_optimization_tpu.serve",
        description=(
            "Simulation-as-a-service daemon: POST ExperimentConfig JSON, "
            "stream RunTrace manifests back; structurally identical "
            "concurrent requests coalesce into one batched XLA program and "
            "repeat programs reuse cached executables (docs/SERVING.md)."
        ),
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=DEFAULT_PORT,
                   help=f"TCP port (default {DEFAULT_PORT}; 0 = ephemeral)")
    p.add_argument("--window-ms", type=float, default=50.0,
                   help="coalescing wait window after work arrives "
                        "(latency traded for batching opportunity)")
    p.add_argument("--max-cohort", type=int, default=32,
                   help="replica-axis cap per coalesced run_batch call")
    p.add_argument("--max-pending", type=int, default=1024,
                   help="queue bound; submits beyond it get a 429")
    p.add_argument("--max-pending-per-tenant", type=int, default=None,
                   help="per-tenant queue depth cap; a tenant at its cap "
                        "gets shed-load 429s (reason=tenant_cap) while "
                        "other tenants keep submitting")
    p.add_argument("--cut-budget", type=int, default=None,
                   help="max requests per scheduler cut (weighted-fair "
                        "across tenants); default: everything pending")
    p.add_argument("--workers", type=int, default=0,
                   help="worker processes for cohort execution (0 = run "
                        "on the scheduler thread); the persistent store "
                        "is their shared warm tier")
    p.add_argument("--store", default=None,
                   help="persistent executable store directory: compiled "
                        "programs are serialized there and reloaded "
                        "across daemon restarts (0 compile seconds for "
                        "previously-served structural classes)")
    p.add_argument("--fleet", action="store_true",
                   help="enable the self-healing fleet remediation "
                        "policies (divergence halt+requeue+quarantine, "
                        "store-corruption quarantine, dead-worker "
                        "respawn attribution); see docs/SERVING.md")
    p.add_argument("--fleet-incidents", default=None, metavar="PATH",
                   help="append remediated incidents (with their "
                        "remediation blocks) to this JSONL file for "
                        "`observatory incidents --remediated`; implies "
                        "--fleet")
    p.add_argument("--quarantine-ttl", type=float, default=300.0,
                   help="seconds a (tenant, structural class) pair stays "
                        "quarantined after a divergence incident")
    p.add_argument("--autoscale-max", type=int, default=None,
                   help="enable the queue-driven autoscaler with this "
                        "worker ceiling (requires --workers >= 1; the "
                        "initial --workers count is the starting fleet)")
    p.add_argument("--autoscale-min", type=int, default=1,
                   help="autoscaler worker floor (default 1)")
    p.add_argument("--port-file", default=None,
                   help="write the bound host:port here once listening "
                        "(for --port 0 orchestration: benches, smokes)")
    p.add_argument("--socket-timeout", type=float,
                   default=DEFAULT_SOCKET_TIMEOUT_S,
                   help="per-connection socket timeout in seconds; a "
                        "client that stalls a read or write longer than "
                        "this is dropped so it cannot pin a handler "
                        "thread (0 disables)")
    p.add_argument("--platform", choices=("tpu", "cpu", "auto"),
                   default="auto",
                   help="force the JAX platform before first use")
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("-q", "--quiet", action="store_true")
    args = p.parse_args(argv)

    configure_logging(1 if args.verbose else (-1 if args.quiet else 0))
    if args.platform != "auto":
        import os as os_mod

        # The env form (not jax.config.update) so spawned worker
        # processes inherit the pin before THEIR jax initializes.
        os_mod.environ["JAX_PLATFORMS"] = args.platform
        import jax

        jax.config.update("jax_platforms", args.platform)
    if args.store:
        # The env var is the single wiring point for the persistent
        # store: the parent's process cache attaches it on first use,
        # and spawned workers inherit it — one shared warm tier.
        import os as os_mod

        os_mod.environ["DOPT_EXEC_STORE"] = args.store

    daemon = ServingDaemon(
        args.host, args.port,
        ServingOptions(
            window_s=args.window_ms / 1000.0,
            max_cohort=args.max_cohort,
            max_pending=args.max_pending,
            max_pending_per_tenant=args.max_pending_per_tenant,
            cut_budget=args.cut_budget,
            workers=args.workers,
        ),
        socket_timeout_s=args.socket_timeout,
    )
    if args.fleet or args.fleet_incidents:
        from distributed_optimization_tpu.serving.fleet import (
            FleetOptions,
            RemediationEngine,
        )

        RemediationEngine(FleetOptions(
            quarantine_ttl_s=args.quarantine_ttl,
            incident_log=args.fleet_incidents,
        )).attach(daemon.service)
    if args.autoscale_max is not None:
        if args.workers < 1:
            p.error("--autoscale-max requires --workers >= 1 "
                    "(an in-process service has nothing to scale)")
        from distributed_optimization_tpu.serving.fleet import (
            AutoscaleOptions,
            QueueAutoscaler,
        )

        daemon.autoscaler = QueueAutoscaler(
            daemon.service,
            AutoscaleOptions(
                min_workers=args.autoscale_min,
                max_workers=args.autoscale_max,
            ),
        )
    if args.port_file:
        host, port = daemon.address
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{host}:{port}\n")
        import os as os_mod

        os_mod.replace(tmp, args.port_file)  # atomic: readers never see ""
    try:
        daemon.serve_forever()
    except KeyboardInterrupt:
        daemon.close()
    return 0
