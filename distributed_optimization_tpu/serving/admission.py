"""Admission control + per-tenant weighted-fair scheduling (ISSUE-15).

PR 7's service kept one FIFO list with one global bound — fine for a
bench harness, hostile for a service: a single tenant scripting 1000
submits owns the queue, every other tenant's requests age behind it, and
the only defense is the global 429. This module replaces that list with
a **deficit-round-robin (DRR) scheduler over per-(tenant, priority)
sub-queues**:

- Every request lands in the sub-queue for its ``(tenant, priority)``
  pair (tenant defaults to ``"default"``, priority to ``"normal"``).
- ``cut(budget)`` visits sub-queues round-robin; each visit adds the
  entity's quantum — tenant weight × priority multiplier — to its
  deficit and dequeues whole requests while deficit allows. An
  adversarial tenant with 1000 queued requests still only drains at its
  weight's share per cut, so a victim tenant's requests reach the
  scheduler within one round regardless of backlog (starvation-free;
  tests/test_admission.py pins the fairness ratio end to end).
- Admission: a full per-tenant depth cap or global cap raises
  ``ShedLoad`` with a machine-readable reason; the daemon maps it to the
  same 429 + Retry-After contract the global bound already spoke
  (``RetryingClient`` retries it transparently), and every shed
  increments ``dopt_serving_shed_total{reason,tenant}``.

All requests in one cut still flow to the SAME coalescer pass, so
cross-tenant requests of one structural class share a cohort — fairness
governs queueing order, never splits compatible work.

Deliberately jax-free and service-free: pure data structure + policy,
unit-testable without a daemon.
"""

from __future__ import annotations

import re
import threading
from collections import OrderedDict, deque
from typing import Optional

# Priority classes scale the tenant's DRR quantum. "high" drains 4× the
# requests per round of "normal"; "low" is background traffic that only
# fills otherwise-idle budget. Class membership never preempts — it is a
# bandwidth share, so "low" still progresses every round (no starvation).
PRIORITY_MULTIPLIERS = {"high": 4.0, "normal": 1.0, "low": 0.25}
DEFAULT_TENANT = "default"
DEFAULT_PRIORITY = "normal"

# Tenant names become metric label values and JSON keys; constrain them
# so a hostile name cannot inject exposition-format syntax or balloon
# the label set with unbounded garbage.
_TENANT_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]{0,63}$")


class AdmissionError(ValueError):
    """Rejected before queueing for a malformed tenant/priority field —
    the daemon maps it to a structured 400."""


class ShedLoad(RuntimeError):
    """Admission refused for load reasons — the daemon maps it to a 429
    with Retry-After (the bounded-queue contract RetryingClient already
    retries)."""

    def __init__(self, reason: str, tenant: str, detail: str):
        super().__init__(detail)
        self.reason = reason  # "tenant_cap" | "global_cap"
        self.tenant = tenant


def validate_tenant(tenant: Optional[str]) -> str:
    if tenant is None:
        return DEFAULT_TENANT
    if not isinstance(tenant, str) or not _TENANT_RE.match(tenant):
        raise AdmissionError(
            "tenant must be 1-64 chars of [A-Za-z0-9_.-] starting "
            f"alphanumeric, got {tenant!r}"
        )
    return tenant


def validate_priority(priority: Optional[str]) -> str:
    if priority is None:
        return DEFAULT_PRIORITY
    if priority not in PRIORITY_MULTIPLIERS:
        raise AdmissionError(
            f"priority must be one of {sorted(PRIORITY_MULTIPLIERS)}, "
            f"got {priority!r}"
        )
    return priority


class WeightedFairQueue:
    """DRR scheduler over per-(tenant, priority) sub-queues.

    Thread-safe. ``push`` admits or sheds; ``cut`` dequeues up to
    ``budget`` requests fairly; ``depths``/``stats`` feed the gauges.
    One quantum unit == one request (requests are near-uniform cost at
    admission time — cohort cost forms only after coalescing), so weights
    read directly as requests-per-round ratios.
    """

    def __init__(
        self,
        *,
        max_pending: int,
        max_pending_per_tenant: Optional[int] = None,
        tenant_weights: Optional[dict] = None,
    ):
        if max_pending < 1:
            raise ValueError(
                f"max_pending must be >= 1, got {max_pending}")
        if max_pending_per_tenant is not None and max_pending_per_tenant < 1:
            raise ValueError(
                "max_pending_per_tenant must be >= 1, got "
                f"{max_pending_per_tenant}")
        self.max_pending = max_pending
        self.max_pending_per_tenant = max_pending_per_tenant
        self.tenant_weights = dict(tenant_weights or {})
        for t, w in self.tenant_weights.items():
            if not (float(w) > 0.0):
                raise ValueError(
                    f"tenant weight must be > 0, got {t}={w!r}")
        self._lock = threading.Lock()
        # Sub-queues in first-seen order; OrderedDict is the DRR ring
        # (rotation = move_to_end). Entities persist across cuts so
        # deficits carry — that carry is what makes DRR exact over time.
        self._queues: "OrderedDict[tuple, deque]" = OrderedDict()
        self._deficits: dict[tuple, float] = {}
        self._total = 0
        self.admitted = 0
        self.dispatched = 0
        self.shed = 0

    # ------------------------------------------------------------ admission
    def _quantum(self, entity: tuple) -> float:
        tenant, priority = entity
        weight = float(self.tenant_weights.get(tenant, 1.0))
        return weight * PRIORITY_MULTIPLIERS[priority]

    def push(self, request, *, tenant: str, priority: str) -> None:
        """Admit one request or raise ``ShedLoad``.

        The per-tenant cap is checked before the global one so a tenant
        at its own cap is named as the reason even when the queue is also
        globally full — the client-visible reason should blame the actor
        that can fix it.
        """
        entity = (tenant, priority)
        with self._lock:
            if self.max_pending_per_tenant is not None:
                tenant_depth = sum(
                    len(q) for (t, _), q in self._queues.items()
                    if t == tenant
                )
                if tenant_depth >= self.max_pending_per_tenant:
                    self.shed += 1
                    raise ShedLoad(
                        "tenant_cap", tenant,
                        f"tenant {tenant!r} has {tenant_depth} pending "
                        f"requests (cap {self.max_pending_per_tenant})",
                    )
            if self._total >= self.max_pending:
                self.shed += 1
                raise ShedLoad(
                    "global_cap", tenant,
                    f"queue full ({self._total} pending, cap "
                    f"{self.max_pending})",
                )
            q = self._queues.get(entity)
            if q is None:
                q = deque()
                self._queues[entity] = q
                self._deficits[entity] = 0.0
            q.append(request)
            self._total += 1
            self.admitted += 1

    # ----------------------------------------------------------- scheduling
    def cut(self, budget: Optional[int] = None) -> list:
        """Dequeue up to ``budget`` requests (all pending when None),
        weighted-fair across entities, FIFO within each entity.

        Classic DRR: visit entities in ring order; each visit grants the
        entity its quantum of deficit, which it spends on whole requests.
        Entities emptied mid-round drop out of the ring (their deficit
        resets — carrying credit for an empty queue would let an idle
        tenant burst past its share later).
        """
        out: list = []
        with self._lock:
            if budget is None:
                budget = self._total
            if budget <= 0 or self._total == 0:
                return out
            # Bound the number of ring sweeps: with the smallest quantum
            # q_min, one request costs at most ceil(1/q_min) visits.
            while len(out) < budget and self._queues:
                for entity in list(self._queues.keys()):
                    if len(out) >= budget:
                        break
                    q = self._queues[entity]
                    self._deficits[entity] += self._quantum(entity)
                    while q and self._deficits[entity] >= 1.0 and (
                        len(out) < budget
                    ):
                        out.append(q.popleft())
                        self._deficits[entity] -= 1.0
                        self._total -= 1
                        self.dispatched += 1
                    if not q:
                        del self._queues[entity]
                        del self._deficits[entity]
                    else:
                        self._queues.move_to_end(entity)
        return out

    # ------------------------------------------------------------ inventory
    def __len__(self) -> int:
        with self._lock:
            return self._total

    def depths(self) -> dict[str, int]:
        """Pending depth per tenant (summed over priorities) — the
        per-tenant gauge family's source of truth."""
        with self._lock:
            out: dict[str, int] = {}
            for (tenant, _), q in self._queues.items():
                out[tenant] = out.get(tenant, 0) + len(q)
            return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "pending": self._total,
                "admitted": int(self.admitted),
                "dispatched": int(self.dispatched),
                "shed": int(self.shed),
                "tenants": len({t for t, _ in self._queues}),
                "max_pending": self.max_pending,
                "max_pending_per_tenant": self.max_pending_per_tenant,
            }
