"""Self-healing serving fleet (ISSUE-16 tentpole; docs/SERVING.md).

PR 13's anomaly sentinel *detects* (a planted over-budget ALIE attack
fires the divergence detector with a forensic incident bundle), and
PR 15's serving plane *survives* (dead workers respawn, corrupt store
artifacts degrade to cold compiles) — but nothing connected detection to
action. This module closes the loop with two cooperating pieces:

**RemediationEngine** — a policy table mapping incident classes to
actions, each rule named and enable/disable-able:

- ``divergence_halt_requeue``: a fatal divergence firing on a served
  request halts that request at the cohort boundary (it fails with a
  structured, policy-attributed error instead of returning a diverged
  trajectory), requeues the cohort's sibling replicas that did NOT fire
  for one clean re-run, and quarantines the offending structural class
  for the submitting tenant (TTL-bounded) — further submissions of that
  class shed with a machine-readable 429 ``reason="quarantined"``.
- ``store_corruption_quarantine``: a corrupt executable-store artifact
  is renamed aside (``*.quarantined``) so the next load is a clean miss
  instead of re-reading the same damage; the cold recompile re-saves a
  fresh artifact through the existing write-through path.
- ``dead_worker_respawn``: the PR-15 requeue-orphans-and-respawn reflex,
  folded into the same policy table — disabling the rule vetoes the
  respawn (the pool shrinks instead), and every death is recorded with
  the same remediation attribution as the other rules.

Every action increments ``dopt_fleet_remediation_total{policy,outcome}``,
appends a structured ``remediation`` block to the incident JSONL (when a
log path is configured), and surfaces in ``/v1/status`` under ``fleet``.

**QueueAutoscaler** — spawns/retires workers off the queue-depth and
shed-rate signals the admission layer already publishes, with hysteresis
bands (consecutive-poll streaks, not instantaneous thresholds) and hard
min/max bounds. Drain-aware twice over: it never scales while the
service drains, and a retiring worker finishes its in-flight cohort
before exiting (the retire sentinel is only read between tasks — the
PR-15 drain contract, per worker). Per-worker liveness gauges are
republished wholesale-atomically every poll (``_Family.replace``), so a
scale-down can never leave a stale worker label on the scrape surface.

Everything here is stdlib-only (the serving daemon's constraint) and
observation-driven: the engine never reaches into a running XLA program;
it acts at the boundaries the serving plane already owns (admission,
cohort completion, artifact load, worker death).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import deque
from typing import Any, Optional

from distributed_optimization_tpu.log import get_logger
from distributed_optimization_tpu.observability.metrics_registry import (
    metrics_registry,
)

_log = get_logger("serving.fleet")

# The policy table: every rule the engine knows, in evaluation order.
POLICY_DIVERGENCE = "divergence_halt_requeue"
POLICY_STORE = "store_corruption_quarantine"
POLICY_WORKER = "dead_worker_respawn"
FLEET_POLICIES = (POLICY_DIVERGENCE, POLICY_STORE, POLICY_WORKER)

# Remediation outcomes (the metric label universe).
OUTCOME_REMEDIATED = "remediated"
OUTCOME_FAILED = "failed"
OUTCOME_SKIPPED = "skipped_disabled"

QUARANTINE_SUFFIX = ".quarantined"


@dataclasses.dataclass
class FleetOptions:
    """Remediation-engine knobs (the daemon exposes them as flags).

    ``policies``: the ENABLED rule names (subset of ``FLEET_POLICIES``);
    a disabled rule records ``skipped_disabled`` instead of acting.
    ``quarantine_ttl_s``: how long a (tenant, structural class) pair
    stays quarantined after a divergence incident. ``incident_log``:
    optional JSONL path remediated incidents (with their ``remediation``
    blocks) are appended to — the forensic record ``observatory
    incidents --remediated`` reads. ``max_records`` bounds the in-memory
    remediation history ``/v1/status`` serves.
    """

    policies: tuple = FLEET_POLICIES
    quarantine_ttl_s: float = 300.0
    incident_log: Optional[str] = None
    max_records: int = 256

    def __post_init__(self) -> None:
        unknown = set(self.policies) - set(FLEET_POLICIES)
        if unknown:
            raise ValueError(
                f"unknown fleet policies {sorted(unknown)}; known policies "
                f"are {list(FLEET_POLICIES)}"
            )
        if self.quarantine_ttl_s <= 0:
            raise ValueError(
                f"quarantine_ttl_s must be > 0, got {self.quarantine_ttl_s}"
            )
        if self.max_records < 1:
            raise ValueError(
                f"max_records must be >= 1, got {self.max_records}"
            )


class RemediationEngine:
    """Incident → action policy engine (module docstring).

    Thread-safe: the service's executor threads call ``review_plan``
    concurrently, the store's load path calls ``on_store_corruption``
    from worker-dispatch threads, and the pool's health monitor calls
    ``on_worker_death`` — each mutation takes the engine's own leaf
    locks, never the service lock.
    """

    def __init__(self, options: Optional[FleetOptions] = None):
        self.options = options or FleetOptions()
        self._policies = {
            name: name in self.options.policies for name in FLEET_POLICIES
        }
        self._lock = threading.Lock()
        # (tenant, structural_hash) -> monotonic expiry.
        self._quarantine: dict[tuple, float] = {}
        self.records: "deque[dict]" = deque(
            maxlen=self.options.max_records
        )
        self.n_remediations = 0
        self._service = None
        reg = metrics_registry()
        self._m_rem = reg.counter(
            "dopt_fleet_remediation_total",
            "Remediation-policy firings by policy and outcome "
            "(remediated/failed/skipped_disabled)",
        )
        reg.gauge_fn(
            "dopt_fleet_quarantined_classes",
            "Structural classes currently quarantined (tenant-scoped, "
            "TTL-bounded) by the divergence remediation policy",
            self.quarantine_count,
        )

    # ---------------------------------------------------------- policy table
    def enabled(self, policy: str) -> bool:
        return bool(self._policies.get(policy))

    def enable(self, policy: str) -> None:
        self._check_policy(policy)
        self._policies[policy] = True

    def disable(self, policy: str) -> None:
        self._check_policy(policy)
        self._policies[policy] = False

    @staticmethod
    def _check_policy(policy: str) -> None:
        if policy not in FLEET_POLICIES:
            raise ValueError(
                f"unknown fleet policy {policy!r}; known policies are "
                f"{list(FLEET_POLICIES)}"
            )

    # -------------------------------------------------------------- wiring
    def attach(self, service) -> "RemediationEngine":
        """Bind this engine to a service: the service consults it at
        admission (quarantine) and cohort completion (review), and the
        engine hooks the service's store and worker pool. Returns self
        for chaining."""
        self._service = service
        service.attach_fleet(self)
        store = getattr(service.cache, "store", None)
        if store is not None:
            store.add_corruption_listener(self.on_store_corruption)
        pool = getattr(service, "_pool", None)
        if pool is not None:
            pool.set_death_hook(self.on_worker_death)
        return self

    # ---------------------------------------------------------- quarantine
    def quarantine(self, tenant: str, structural_hash: str) -> None:
        with self._lock:
            self._quarantine[(tenant, structural_hash)] = (
                time.monotonic() + self.options.quarantine_ttl_s
            )

    def quarantine_count(self) -> int:
        now = time.monotonic()
        with self._lock:
            self._sweep_quarantine(now)
            return len(self._quarantine)

    def active_quarantines(self) -> list[dict]:
        now = time.monotonic()
        with self._lock:
            self._sweep_quarantine(now)
            return [
                {
                    "tenant": t, "structural_hash": h,
                    "expires_in_s": round(exp - now, 1),
                }
                for (t, h), exp in sorted(self._quarantine.items())
            ]

    def _sweep_quarantine(self, now: float) -> None:
        # Caller holds self._lock.
        for key in [k for k, exp in self._quarantine.items() if exp <= now]:
            del self._quarantine[key]

    def quarantine_reason(self, config, tenant: str) -> Optional[str]:
        """The admission-time check: a non-None return is the structured
        shed detail for a (tenant, structural class) pair under an
        active divergence quarantine."""
        shash = config.structural_hash()
        now = time.monotonic()
        with self._lock:
            self._sweep_quarantine(now)
            exp = self._quarantine.get((tenant, shash))
        if exp is None:
            return None
        return (
            f"structural class {shash[:12]} is quarantined for tenant "
            f"{tenant!r} after a divergence incident "
            f"({POLICY_DIVERGENCE}); retry in {exp - now:.0f}s or submit "
            "a corrected config"
        )

    # -------------------------------------------------- divergence policy
    def on_anomaly(self, req, anomaly) -> None:
        """Live hook from the service's heartbeat path: a fatal
        divergence quarantines the class MID-FLIGHT, so sibling traffic
        of the same poisoned class sheds before the cohort even
        finishes."""
        if (
            anomaly.detector == "divergence"
            and anomaly.severity == "fatal"
            and self.enabled(POLICY_DIVERGENCE)
        ):
            self.quarantine(req.tenant, req.config.structural_hash())

    @staticmethod
    def _fatal_divergence(req) -> bool:
        return any(
            i.get("detector") == "divergence"
            and i.get("severity") == "fatal"
            for i in req.incidents
        )

    def review_plan(self, plan, banks: dict) -> dict:
        """Post-execution policy review of one completed plan; returns
        ``{request_id: verdict}`` where a verdict is ``{"action":
        "fail"|"requeue", "error", "remediation"}``. An empty dict means
        the plan passes untouched (the overwhelmingly common case)."""
        offenders = [r for r in plan.requests if self._fatal_divergence(r)]
        if not offenders:
            return {}
        if not self.enabled(POLICY_DIVERGENCE):
            self._record(
                policy=POLICY_DIVERGENCE, trigger="divergence",
                outcome=OUTCOME_SKIPPED,
                actions=[],
                detail={"offenders": [r.id for r in offenders]},
            )
            return {}
        verdicts: dict[str, dict] = {}
        offender_ids = {id(r) for r in offenders}  # identity, not __eq__
        siblings = [
            r for r in plan.requests if id(r) not in offender_ids
        ]
        requeue = [r for r in siblings if getattr(r, "requeues", 0) < 1]
        for r in offenders:
            shash = r.config.structural_hash()
            self.quarantine(r.tenant, shash)
            rem = {
                "policy": POLICY_DIVERGENCE,
                "trigger": "divergence",
                "outcome": OUTCOME_REMEDIATED,
                "actions": [
                    "halt_offender",
                    f"requeue_siblings:{len(requeue)}",
                    "quarantine_class",
                ],
                "request_id": r.id,
                "tenant": r.tenant,
                "structural_hash": shash,
                "quarantine_ttl_s": self.options.quarantine_ttl_s,
            }
            verdicts[r.id] = {
                "action": "fail",
                "error": (
                    f"halted by fleet remediation ({POLICY_DIVERGENCE}): "
                    "fatal divergence fired on this request; the diverged "
                    "result is withheld, sibling replicas were requeued, "
                    f"and structural class {shash[:12]} is quarantined "
                    f"for tenant {r.tenant!r} "
                    f"({self.options.quarantine_ttl_s:.0f}s TTL)"
                ),
                "remediation": rem,
            }
            self._record(
                policy=POLICY_DIVERGENCE, trigger="divergence",
                outcome=OUTCOME_REMEDIATED, actions=rem["actions"],
                detail={
                    "request_id": r.id, "tenant": r.tenant,
                    "structural_hash": shash,
                    "requeued_siblings": [s.id for s in requeue],
                },
            )
            self._append_incidents(self._divergence_incidents(
                r, banks.get(r.id), rem,
            ))
        for r in requeue:
            verdicts[r.id] = {
                "action": "requeue",
                "error": (
                    "sibling requeue shed by admission during "
                    f"{POLICY_DIVERGENCE} remediation"
                ),
                "remediation": {
                    "policy": POLICY_DIVERGENCE,
                    "trigger": "divergence",
                    "outcome": OUTCOME_REMEDIATED,
                    "actions": ["requeued_sibling"],
                    "offender": offenders[0].id,
                },
            }
        return verdicts

    def _divergence_incidents(self, req, bank, remediation) -> list[dict]:
        """Forensic bundles for one offender: the bank's real divergence
        anomalies when monitors ran, a synthesized operational record
        otherwise — either way carrying the remediation block."""
        incs: list[dict] = []
        if bank is not None:
            from distributed_optimization_tpu.observability.monitors import (
                build_incident,
            )

            for a in bank.anomalies:
                if a.detector == "divergence" and a.severity == "fatal":
                    try:
                        incs.append(build_incident(
                            req.config, a, label=req.id,
                            remediation=remediation,
                        ))
                    except Exception:
                        _log.exception(
                            "incident bundling failed for %s", req.id
                        )
        if not incs:
            incs = [self._op_incident(
                "divergence",
                f"fatal divergence on served request {req.id}",
                {"request_id": req.id, "tenant": req.tenant},
                remediation,
            )]
        return incs

    # ----------------------------------------------------- store policy
    def on_store_corruption(self, path: str, detail: str) -> None:
        """Store listener: quarantine the damaged artifact so the next
        load of its key is a clean miss (the cold recompile re-saves a
        fresh artifact through the existing write-through path)."""
        if not self.enabled(POLICY_STORE):
            self._record(
                policy=POLICY_STORE, trigger="store_corruption",
                outcome=OUTCOME_SKIPPED, actions=[],
                detail={"artifact": path, "error": detail},
            )
            return
        qpath = path + QUARANTINE_SUFFIX
        outcome = OUTCOME_REMEDIATED
        try:
            os.replace(path, qpath)
        except FileNotFoundError:
            # Already moved (another listener/process won the race) —
            # the artifact is out of the load path either way.
            pass
        except OSError as e:
            outcome = OUTCOME_FAILED
            detail = f"{detail}; quarantine rename failed: {e}"
        rem = {
            "policy": POLICY_STORE,
            "trigger": "store_corruption",
            "outcome": outcome,
            "actions": ["quarantine_artifact", "recompile_cold"],
            "artifact": path,
            "quarantined_as": qpath,
        }
        self._record(
            policy=POLICY_STORE, trigger="store_corruption",
            outcome=outcome, actions=rem["actions"],
            detail={"artifact": path, "error": detail},
        )
        self._append_incidents([self._op_incident(
            "store_corruption",
            f"corrupt executable-store artifact {path}: {detail}",
            {"artifact": path, "quarantined_as": qpath},
            rem,
        )])

    # ---------------------------------------------------- worker policy
    def on_worker_death(self, worker_id: int, requeued: int,
                        lost: int) -> bool:
        """Pool death hook; the return value gates the respawn."""
        if not self.enabled(POLICY_WORKER):
            self._record(
                policy=POLICY_WORKER, trigger="dead_worker",
                outcome=OUTCOME_SKIPPED, actions=[],
                detail={"worker": worker_id, "requeued": requeued,
                        "lost": lost},
            )
            return False
        rem = {
            "policy": POLICY_WORKER,
            "trigger": "dead_worker",
            "outcome": OUTCOME_REMEDIATED,
            "actions": [f"requeue_inflight:{requeued}", "respawn"],
            "worker": worker_id,
            "tasks_lost": lost,
        }
        self._record(
            policy=POLICY_WORKER, trigger="dead_worker",
            outcome=OUTCOME_REMEDIATED, actions=rem["actions"],
            detail={"worker": worker_id, "requeued": requeued,
                    "lost": lost},
        )
        self._append_incidents([self._op_incident(
            "dead_worker",
            f"worker {worker_id} died with {requeued + lost} task(s) in "
            f"flight ({requeued} requeued, {lost} lost)",
            {"worker": worker_id, "requeued": requeued, "lost": lost},
            rem,
        )])
        return True

    # ------------------------------------------------------------ records
    def _record(self, *, policy, trigger, outcome, actions, detail) -> dict:
        rec = {
            "policy": policy,
            "trigger": trigger,
            "outcome": outcome,
            "actions": list(actions),
            "detail": detail,
            "at_unix": time.time(),
        }
        with self._lock:
            self.records.append(rec)
            self.n_remediations += 1
        self._m_rem.inc(policy=policy, outcome=outcome)
        _log.info(
            "remediation %s (%s) -> %s %s", policy, trigger, outcome,
            actions,
        )
        return rec

    def _op_incident(self, detector, message, evidence,
                     remediation) -> dict:
        """An operational incident bundle (no producing config — the
        subject is the fleet itself) in the same schema the sentinel's
        forensic bundles use, so one JSONL stream and one reader cover
        both."""
        from distributed_optimization_tpu.observability.monitors import (
            INCIDENT_SCHEMA_VERSION,
        )
        from distributed_optimization_tpu.telemetry import provenance

        return {
            "schema_version": INCIDENT_SCHEMA_VERSION,
            "kind": "incident",
            "label": "fleet",
            "detector": detector,
            "severity": "warn",
            "onset_iteration": 0,
            "message": message,
            "config": {},
            "config_hash": None,
            "structural_hash": None,
            "evidence": evidence,
            "context": {"kind": "operational"},
            "provenance": provenance(),
            "remediation": dict(remediation),
        }

    def _append_incidents(self, incidents: list[dict]) -> None:
        path = self.options.incident_log
        if not path or not incidents:
            return
        try:
            from distributed_optimization_tpu.observability.monitors import (
                write_incidents,
            )

            with self._lock:  # serialize appends across executor threads
                write_incidents(path, incidents, append=True)
        except Exception:
            _log.exception("incident log append failed (%s)", path)

    # ------------------------------------------------------------- status
    def status(self) -> dict:
        with self._lock:
            recent = list(self.records)[-16:]
            total = self.n_remediations
        return {
            "policies": dict(self._policies),
            "quarantines": self.active_quarantines(),
            "remediations": {"total": total, "recent": recent},
            "incident_log": self.options.incident_log,
        }


# --------------------------------------------------------------- autoscaler


@dataclasses.dataclass
class AutoscaleOptions:
    """Hysteresis bands and bounds for the queue-driven autoscaler.

    Depth is the service's visible BACKLOG: undispatched queued requests
    plus worker-pool tasks beyond one-per-worker (dispatch moves work
    from the first bucket to the second without shrinking it).

    Pressure (backlog above ``high_depth``, or ANY admission shed
    since the last poll) must persist for ``up_polls`` consecutive polls
    before one worker is added; idleness (depth at/below ``low_depth``
    with nothing in flight) must persist for ``down_polls`` polls before
    one worker retires. The asymmetry is deliberate: scale-up chases a
    visible backlog, scale-down waits out a lull. Between the bands the
    streaks reset — the classic hysteresis dead zone.
    """

    min_workers: int = 1
    max_workers: int = 4
    high_depth: int = 8
    low_depth: int = 0
    up_polls: int = 2
    down_polls: int = 20
    poll_s: float = 0.25

    def __post_init__(self) -> None:
        if self.min_workers < 1:
            raise ValueError(
                f"min_workers must be >= 1, got {self.min_workers}"
            )
        if self.max_workers < self.min_workers:
            raise ValueError(
                f"max_workers ({self.max_workers}) must be >= min_workers "
                f"({self.min_workers})"
            )
        if self.low_depth < 0 or self.high_depth <= self.low_depth:
            raise ValueError(
                f"need high_depth > low_depth >= 0, got "
                f"{self.high_depth}/{self.low_depth}"
            )
        if self.up_polls < 1 or self.down_polls < 1:
            raise ValueError("up_polls and down_polls must be >= 1")
        if self.poll_s <= 0:
            raise ValueError(f"poll_s must be > 0, got {self.poll_s}")


class QueueAutoscaler:
    """Queue-driven worker autoscaling (module docstring).

    ``decide`` is the pure policy core (unit-testable without processes);
    ``poll_once`` reads the live signals and executes the decision;
    ``start`` runs ``poll_once`` on a background thread every
    ``poll_s``."""

    def __init__(self, service, options: Optional[AutoscaleOptions] = None):
        if service.options.workers < 1:
            raise ValueError(
                "the autoscaler needs a worker-pool service "
                "(ServingOptions.workers >= 1); an in-process service "
                "has nothing to scale"
            )
        self.service = service
        service._autoscaler = self  # surfaces in service.stats()["fleet"]
        self.options = options or AutoscaleOptions()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._up_streak = 0
        self._idle_streak = 0
        self._last_shed: Optional[int] = None
        self.n_scale_up = 0
        self.n_scale_down = 0
        self.events: "deque[dict]" = deque(maxlen=256)
        reg = metrics_registry()
        self._m_events = reg.counter(
            "dopt_fleet_scale_events_total",
            "Autoscaler worker fleet changes, by direction (up/down)",
        )
        self._m_target = reg.gauge(
            "dopt_fleet_workers_target",
            "Worker fleet size the autoscaler is currently targeting",
        )
        self._m_worker_up = reg.gauge(
            "dopt_fleet_worker_up",
            "Per-worker fleet membership (1 = in the fleet); the whole "
            "label set is replaced atomically every poll, so retired "
            "workers' series vanish instead of going stale",
        )

    # ------------------------------------------------------------- policy
    def decide(self, *, depth: int, shed_delta: int, target: int,
               in_flight: int, draining: bool) -> int:
        """One poll's scaling decision: +1, -1 or 0. Mutates the
        hysteresis streaks; never scales while draining (streaks reset —
        a drain must end in a quiet fleet, not a rescaled one)."""
        o = self.options
        if draining:
            self._up_streak = self._idle_streak = 0
            return 0
        pressured = depth > o.high_depth or shed_delta > 0
        idle = depth <= o.low_depth and in_flight == 0
        if pressured:
            self._up_streak += 1
            self._idle_streak = 0
        elif idle:
            self._idle_streak += 1
            self._up_streak = 0
        else:  # the dead zone between the bands: hold, reset both
            self._up_streak = self._idle_streak = 0
        if self._up_streak >= o.up_polls and target < o.max_workers:
            self._up_streak = 0
            return 1
        if self._idle_streak >= o.down_polls and target > o.min_workers:
            self._idle_streak = 0
            return -1
        return 0

    # ------------------------------------------------------------ execution
    def poll_once(self) -> int:
        """Read the live signals, decide, act; returns the applied delta."""
        svc = self.service
        svc._ensure_workers()
        pool = svc._pool
        if pool is None:  # workers >= 1 guaranteed by __init__
            return 0
        shed_total = int(svc._queue.stats()["shed"])
        shed_delta = (
            0 if self._last_shed is None
            else max(0, shed_total - self._last_shed)
        )
        self._last_shed = shed_total
        pst = pool.stats()
        # The WFQ queue drains into the pool's task queue at dispatch
        # time, so the visible backlog is BOTH: undispatched requests
        # plus pool tasks beyond one-per-worker (oversubscription).
        backlog = svc.queue_depth() + max(
            0, pst["in_flight"] - pst["workers"]
        )
        delta = self.decide(
            depth=backlog,
            shed_delta=shed_delta,
            target=pst["workers"],
            in_flight=pst["in_flight"],
            draining=svc.draining,
        )
        if delta > 0:
            new_ids = pool.scale_up(1)
            self.n_scale_up += 1
            self._m_events.inc(direction="up")
            self.events.append({
                "direction": "up", "workers": pool.n_workers,
                "spawned": new_ids, "at_unix": time.time(),
            })
            _log.info("autoscaler: +1 worker -> %d", pool.n_workers)
        elif delta < 0:
            pool.scale_down(1)
            self.n_scale_down += 1
            self._m_events.inc(direction="down")
            self.events.append({
                "direction": "down", "workers": pool.n_workers,
                "at_unix": time.time(),
            })
            _log.info("autoscaler: -1 worker -> %d", pool.n_workers)
        self._m_target.set(pool.n_workers)
        self._m_worker_up.replace(
            ({"worker": str(w)}, 1.0) for w in pool.worker_ids()
        )
        return delta

    # ----------------------------------------------------------- lifecycle
    def start(self) -> None:
        """Bring the fleet to ``min_workers`` and start polling."""
        svc = self.service
        svc._ensure_workers()
        pool = svc._pool
        if pool is not None and pool.n_workers < self.options.min_workers:
            pool.scale_up(self.options.min_workers - pool.n_workers)
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-autoscaler", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.options.poll_s):
            try:
                self.poll_once()
            except Exception:  # pragma: no cover - belt and braces
                _log.exception("autoscaler poll failed; continuing")

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
            self._thread = None

    # ------------------------------------------------------------- status
    def status(self) -> dict:
        pool = self.service._pool
        return {
            "min_workers": self.options.min_workers,
            "max_workers": self.options.max_workers,
            "high_depth": self.options.high_depth,
            "low_depth": self.options.low_depth,
            "target": pool.n_workers if pool is not None else None,
            "scale_ups": self.n_scale_up,
            "scale_downs": self.n_scale_down,
            "recent_events": list(self.events)[-16:],
        }
