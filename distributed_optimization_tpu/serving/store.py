"""Persistent on-disk executable store (ISSUE-15 tentpole; docs/SERVING.md).

The in-memory ``ExecutableCache`` dies with the process, so every daemon
restart re-pays the 4–6 s whole-run cold compile (docs/PERF.md §3) for
every structural class it serves — the single largest latency cliff left
in the serving plane. This module makes the compiled programs themselves
durable: each ``CacheEntry`` is serialized through jax's AOT executable
serialization (``jax.experimental.serialize_executable`` — the same
pickled-unloaded-executable machinery the persistent compilation cache
uses) into one file per cache key, and a restarted process deserializes
and *loads* the executable instead of recompiling. A store-warm request
reports ``compile_seconds == 0.0`` and produces bitwise the result the
original compile produced (tests/test_exec_store.py pins both).

Contract decisions, and why:

- **Keyed by the full cache key.** Files are named by the SHA-256 of the
  exact in-memory cache key tuple (``serving/cache.py`` key builders:
  structural hash + sequential full-config hash, dataset/mesh/schedule
  signatures, x64 + device identity). The store never invents its own
  weaker key — anything that would miss the RAM cache also misses the
  store, so the two tiers can never disagree about what "the same
  program" means. The key's repr is stored inside the artifact and
  re-checked on load (a digest collision or a repr-format drift reads as
  a miss, never as the wrong program).
- **Provenance-guarded loads.** An artifact records the producing
  environment — ``jax.__version__`` and device kind from
  ``telemetry.provenance()``, plus the x64 mode — and a mismatched
  artifact is *skipped with one warning*, not deserialized and crashed
  on: serialized XLA executables are not portable across jax versions or
  device kinds, and a redeploy that upgrades jax must degrade to a cold
  compile, not a corrupt-program crash.
- **Corruption degrades to a cold compile.** A truncated, unreadable or
  wrong-schema artifact logs a single warning per file and reads as a
  miss — mirroring the ISSUE-3 checkpoint-fallback contract
  (``RunCheckpointer.restore`` skipping partial chunks). The store never
  raises into the serving path.
- **Atomic writes.** Artifacts are written to a temp file and
  ``os.replace``d into place, so a crash mid-write leaves either the old
  artifact or none — a concurrently restarting worker can never observe
  a half-written program. Multiple worker processes share one store
  directory safely this way (last writer wins; they write identical
  payloads for identical keys).

``DOPT_EXEC_STORE=<dir>`` attaches a store to the process-wide default
cache (``serving/cache.py``) — the env var is how spawned serving workers
inherit the shared warm tier without any plumbing.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import pickle
import tempfile
import threading
import time
from typing import Any, Optional

from distributed_optimization_tpu.log import get_logger

_log = get_logger("serving.store")

STORE_SCHEMA_VERSION = 1
# One file per compiled program; the suffix marks the format so a store
# directory can be swept/inspected without parsing anything else in it.
ARTIFACT_SUFFIX = ".dopt-exec"

_ENV_VAR = "DOPT_EXEC_STORE"


def key_digest(key: tuple) -> str:
    """Stable on-disk name for a cache key: SHA-256 of its repr.

    The key tuples are built from primitives (strings, ints, floats,
    bools, None, nested tuples), whose reprs are deterministic across
    processes — the property the restart-warm gate rides on.
    """
    return hashlib.sha256(repr(key).encode()).hexdigest()


def store_provenance() -> dict:
    """The environment facts an artifact must match to be loadable:
    serialized XLA executables bind the producing jax version, the
    device kind they were compiled for, and the x64 mode (weak-typed
    scalar promotion changes programs)."""
    from distributed_optimization_tpu import telemetry

    prov = telemetry.provenance()
    x64 = None
    try:
        import jax

        x64 = bool(jax.config.jax_enable_x64)
    except Exception:
        pass
    return {
        "jax_version": prov.get("jax_version"),
        "device_kind": prov.get("device_kind"),
        "x64": x64,
    }


@dataclasses.dataclass
class StoreStats:
    """Lifetime counters (all plain ints/floats — JSON-safe)."""

    saves: int = 0
    save_errors: int = 0
    load_hits: int = 0
    load_misses: int = 0
    skipped_provenance: int = 0
    corrupt: int = 0
    load_seconds: float = 0.0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class PersistentExecutableStore:
    """Write-through/load-on-miss disk tier under an ``ExecutableCache``.

    Thread-safe; shared across worker processes via the filesystem (see
    the module docstring for the atomicity argument). All failure paths
    warn once per artifact and degrade to a miss.
    """

    def __init__(self, root) -> None:
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        self._stats = StoreStats()
        self._warned: set[str] = set()  # one warning per artifact file
        self._provenance: Optional[dict] = None  # resolved on first use
        # Corruption observers (ISSUE-16 fleet remediation): called as
        # ``fn(path, detail)`` when an artifact reads as corrupt. The
        # store itself only degrades to a miss; a listener may choose to
        # quarantine the file so the next load is a clean miss instead of
        # re-reading the same damage.
        self._corruption_listeners: list = []
        # Registry families (ISSUE-10 conventions): labeled result
        # counter so a dashboard separates warm loads from provenance
        # skips without scraping logs.
        from distributed_optimization_tpu.observability.metrics_registry import (  # noqa: E501
            metrics_registry,
        )

        reg = metrics_registry()
        self._m_loads = reg.counter(
            "dopt_exec_store_loads_total",
            "Persistent-store load attempts by result "
            "(hit/miss/provenance_mismatch/corrupt)",
        )
        self._m_saves = reg.counter(
            "dopt_exec_store_saves_total",
            "Executables persisted to the on-disk store (error=save "
            "failures, skipped without raising)",
        )

    # ------------------------------------------------------------ plumbing
    def _path(self, key: tuple) -> str:
        return os.path.join(self.root, key_digest(key) + ARTIFACT_SUFFIX)

    def _prov(self) -> dict:
        # Resolved lazily (jax import) and cached: every load/save checks
        # it, and it cannot change within a process.
        if self._provenance is None:
            self._provenance = store_provenance()
        return self._provenance

    def _warn_once(self, path: str, message: str) -> None:
        with self._lock:
            if path in self._warned:
                return
            self._warned.add(path)
        _log.warning("%s — falling back to a cold compile", message)

    def add_corruption_listener(self, fn) -> None:
        """Register ``fn(path, detail)`` to run when an artifact reads as
        corrupt (truncated pickle, schema/key mismatch, undeserializable
        payload). Listener failures are swallowed — remediation must
        never break the degrade-to-miss contract."""
        with self._lock:
            self._corruption_listeners.append(fn)

    def _notify_corrupt(self, path: str, detail: str) -> None:
        with self._lock:
            listeners = list(self._corruption_listeners)
        for fn in listeners:
            try:
                fn(path, detail)
            except Exception:
                pass

    # ------------------------------------------------------------- writing
    def save(self, key: tuple, entry) -> bool:
        """Persist one ``CacheEntry``; returns True on success.

        Serialization failures (exotic executables, full disk) warn once
        and return False — persistence is an optimization, never a
        reason to fail the request that just compiled successfully.
        """
        path = self._path(key)
        try:
            from jax.experimental import serialize_executable

            payload, in_tree, out_tree = serialize_executable.serialize(
                entry.executable
            )
            blob = pickle.dumps({
                "schema": STORE_SCHEMA_VERSION,
                "provenance": self._prov(),
                "key_repr": repr(key),
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
                "cost": entry.cost,
                "compile_seconds": float(entry.compile_seconds),
            }, protocol=pickle.HIGHEST_PROTOCOL)
            fd, tmp = tempfile.mkstemp(
                dir=self.root, suffix=ARTIFACT_SUFFIX + ".tmp"
            )
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                os.replace(tmp, path)  # atomic: old artifact or new, never half
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except Exception as e:
            with self._lock:
                self._stats.save_errors += 1
            self._m_saves.inc(result="error")
            self._warn_once(
                path,
                f"could not persist executable to {path} "
                f"({type(e).__name__}: {e})",
            )
            return False
        with self._lock:
            self._stats.saves += 1
        self._m_saves.inc(result="ok")
        return True

    # ------------------------------------------------------------- loading
    def load(self, key: tuple):
        """Deserialize + load the artifact for ``key``, or None.

        Returns a ``serving.cache.CacheEntry`` ready to execute. Every
        failure mode — missing file, truncated/unreadable pickle, schema
        or key mismatch, provenance mismatch — returns None (a miss) and
        the non-missing ones warn once per file.
        """
        from distributed_optimization_tpu.serving.cache import (
            CacheEntry,
            estimate_executable_bytes,
        )

        path = self._path(key)
        if not os.path.exists(path):
            with self._lock:
                self._stats.load_misses += 1
            self._m_loads.inc(result="miss")
            return None
        t0 = time.perf_counter()
        try:
            with open(path, "rb") as f:
                record = pickle.load(f)
            if not isinstance(record, dict) or record.get("schema") != (
                STORE_SCHEMA_VERSION
            ):
                raise ValueError(
                    f"unsupported store schema "
                    f"{record.get('schema') if isinstance(record, dict) else type(record).__name__!r}"  # noqa: E501
                )
            if record.get("key_repr") != repr(key):
                raise ValueError("stored key does not match (digest collision"
                                 " or key-format drift)")
        except Exception as e:
            with self._lock:
                self._stats.corrupt += 1
                self._stats.load_misses += 1
            self._m_loads.inc(result="corrupt")
            self._warn_once(
                path,
                f"corrupt/unreadable store artifact {path} "
                f"({type(e).__name__}: {e})",
            )
            self._notify_corrupt(path, f"{type(e).__name__}: {e}")
            return None
        stored_prov = record.get("provenance") or {}
        here = self._prov()
        mismatched = {
            k: (stored_prov.get(k), here.get(k))
            for k in ("jax_version", "device_kind", "x64")
            if stored_prov.get(k) != here.get(k)
        }
        if mismatched:
            with self._lock:
                self._stats.skipped_provenance += 1
                self._stats.load_misses += 1
            self._m_loads.inc(result="provenance_mismatch")
            self._warn_once(
                path,
                f"skipping store artifact {path}: provenance mismatch "
                + ", ".join(
                    f"{k} {a!r} (stored) != {b!r} (here)"
                    for k, (a, b) in sorted(mismatched.items())
                ),
            )
            return None
        try:
            from jax.experimental import serialize_executable

            executable = serialize_executable.deserialize_and_load(
                record["payload"], record["in_tree"], record["out_tree"]
            )
        except Exception as e:
            with self._lock:
                self._stats.corrupt += 1
                self._stats.load_misses += 1
            self._m_loads.inc(result="corrupt")
            self._warn_once(
                path,
                f"could not deserialize store artifact {path} "
                f"({type(e).__name__}: {e})",
            )
            self._notify_corrupt(path, f"{type(e).__name__}: {e}")
            return None
        load_s = time.perf_counter() - t0
        with self._lock:
            self._stats.load_hits += 1
            self._stats.load_seconds += load_s
        self._m_loads.inc(result="hit")
        return CacheEntry(
            executable=executable,
            cost=record.get("cost"),
            compile_seconds=float(record.get("compile_seconds", 0.0)),
            est_bytes=estimate_executable_bytes(executable),
        )

    # ----------------------------------------------------------- inventory
    def __len__(self) -> int:
        try:
            return sum(
                1 for n in os.listdir(self.root)
                if n.endswith(ARTIFACT_SUFFIX)
            )
        except OSError:
            return 0

    def disk_bytes(self) -> int:
        total = 0
        try:
            for n in os.listdir(self.root):
                if n.endswith(ARTIFACT_SUFFIX):
                    try:
                        total += os.path.getsize(os.path.join(self.root, n))
                    except OSError:
                        pass
        except OSError:
            pass
        return total

    def stats(self) -> dict:
        with self._lock:
            out: dict[str, Any] = self._stats.as_dict()
        out["root"] = self.root
        out["artifacts"] = len(self)
        out["disk_bytes"] = self.disk_bytes()
        return out


# ----------------------------------------------------- process-wide default

_process_store: Optional[PersistentExecutableStore] = None
_process_store_root: Optional[str] = None
_store_lock = threading.Lock()


def process_store_root() -> Optional[str]:
    """The env-configured store directory (``DOPT_EXEC_STORE``), or None."""
    root = os.environ.get(_ENV_VAR, "").strip()
    return root or None


def process_executable_store() -> Optional[PersistentExecutableStore]:
    """The process-wide store named by ``DOPT_EXEC_STORE`` (None when the
    env var is unset). One instance per configured root — re-pointing the
    env var mid-process builds a fresh instance, which only tests do."""
    root = process_store_root()
    if root is None:
        return None
    global _process_store, _process_store_root
    with _store_lock:
        if _process_store is None or _process_store_root != root:
            _process_store = PersistentExecutableStore(root)
            _process_store_root = root
        return _process_store
