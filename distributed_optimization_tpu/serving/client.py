"""Retrying stdlib HTTP client for the serving daemon.

The daemon documents two RETRYABLE server states — 429 ``queue_full``
(bounded-queue backpressure; ``serving/daemon.py``) and plain connection
failures (a daemon restarting between submit and result — the chaos
harness's kill/restart mode) — but until ISSUE-12 no client implemented
the retry, so every caller either string-matched errors or died on the
first refused connection. ``RetryingClient`` is that client: bounded
retries with exponential backoff and seeded jitter on

- HTTP 429 and 503 (backpressure / transient unavailability), and
- connection-level failures (refused, reset, broken pipe) — the restart
  window.

Everything else — 400 invalid configs, 404 unknown ids, 500 run
failures — is a STRUCTURED answer, not a transport fault: it is returned
as ``(status, payload)`` for the caller to assert on, never retried
(retrying a permanently invalid config would just hammer the daemon) and
never raised as a bare traceback.

The retry policy honors shed BLAME (ISSUE-16): a 429 whose structured
``reason`` says the rejection is tenant-scoped (``tenant_cap`` — this
tenant is at its own cap, or ``quarantined`` — this tenant's structural
class is under a divergence quarantine) backs off
``blame_backoff_factor`` times longer than a global-capacity 429 or a
503, because other tenants are fine and hammering the daemon cannot make
a tenant-scoped rejection clear faster. And a drain 503 is only
transient until it isn't: the client confirms via one unretried
``/v1/status`` probe, and once the daemon reports ``draining: true`` it
stops retrying immediately (the drain precedes an exit; burning the rest
of the backoff budget against it is pure wasted latency).

Stdlib only (urllib), like the daemon itself. Used by the chaos harness
(``scenarios/chaos.py``), ``examples/serve_smoke.py`` and
``examples/observatory_smoke.py``.
"""

from __future__ import annotations

import json
import random
import time
import urllib.error
import urllib.request
from typing import Any, Iterator, Optional

from distributed_optimization_tpu.log import get_logger

_log = get_logger("serving.client")

RETRYABLE_STATUSES = (429, 503)
# Shed reasons that blame THIS tenant rather than global capacity: the
# retry backs off longer on these (module docstring).
TENANT_BLAME_REASONS = ("tenant_cap", "quarantined")


class RetriesExhaustedError(ConnectionError):
    """The bounded retry budget ran out; carries the last failure."""

    def __init__(self, message: str, *, last_status: Optional[int] = None):
        self.last_status = last_status
        super().__init__(message)


class RetryingClient:
    """Bounded-retry HTTP client for one daemon base URL.

    ``max_retries`` counts RE-attempts (0 = single try). Backoff for
    attempt k sleeps ``min(cap, base * 2**k)`` scaled by a jitter factor
    in [0.5, 1.0] drawn from a seeded stream — deterministic in tests,
    and never synchronized across clients in production (the thundering
    herd a fixed schedule would re-create against a restarting daemon).
    """

    def __init__(
        self,
        base_url: str,
        *,
        max_retries: int = 5,
        backoff_s: float = 0.05,
        backoff_cap_s: float = 2.0,
        blame_backoff_factor: float = 4.0,
        timeout_s: float = 300.0,
        seed: Optional[int] = None,
        sleep=time.sleep,
    ):
        self.base_url = base_url.rstrip("/")
        if max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {max_retries}")
        if blame_backoff_factor < 1.0:
            raise ValueError(
                "blame_backoff_factor must be >= 1.0 (tenant-blamed sheds "
                f"never back off SHORTER), got {blame_backoff_factor}"
            )
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self.blame_backoff_factor = blame_backoff_factor
        self.timeout_s = timeout_s
        self._rng = random.Random(seed)
        self._sleep = sleep
        self.n_retries = 0  # lifetime counter (chaos harness reads it)

    # ------------------------------------------------------------ plumbing
    def _delay(self, attempt: int) -> float:
        base = min(self.backoff_cap_s, self.backoff_s * (2.0 ** attempt))
        return base * (0.5 + 0.5 * self._rng.random())

    def _once(self, method: str, path: str, body, timeout: float):
        data = None
        headers = {}
        if body is not None:
            data = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        req = urllib.request.Request(
            self.base_url + path, data=data, headers=headers, method=method,
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            # Structured non-2xx answer: read the daemon's JSON error body.
            try:
                payload = json.loads(e.read())
            except (json.JSONDecodeError, OSError):
                payload = {"error": "http_error", "detail": str(e)}
            return e.code, payload

    def _confirmed_draining(self) -> bool:
        """One UNRETRIED ``/v1/status`` probe after a drain 503: True
        only when the daemon itself reports ``draining: true``. Any
        probe failure returns False — benefit of the doubt, the normal
        retry path keeps going (a restarting daemon also briefly answers
        oddly, and that window IS worth retrying through)."""
        try:
            status, payload = self._once(
                "GET", "/v1/status", None, min(self.timeout_s, 10.0),
            )
        except (urllib.error.URLError, ConnectionError, OSError):
            return False
        return (
            status == 200
            and isinstance(payload, dict)
            and bool(payload.get("draining"))
        )

    def request(
        self, method: str, path: str, body=None,
        timeout: Optional[float] = None,
    ) -> tuple[int, Any]:
        """One request with the retry policy; returns (status, payload)."""
        timeout = self.timeout_s if timeout is None else timeout
        last_status: Optional[int] = None
        last_error: Optional[BaseException] = None
        for attempt in range(self.max_retries + 1):
            blame: Optional[str] = None
            try:
                status, payload = self._once(method, path, body, timeout)
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                # Connection-level failure (refused/reset/daemon gone):
                # the restart window — retryable.
                last_error, last_status = e, None
            else:
                if status not in RETRYABLE_STATUSES:
                    return status, payload
                last_error, last_status = None, status
                if isinstance(payload, dict):
                    blame = payload.get("reason")
                    if (
                        status == 503
                        and payload.get("error") == "draining"
                        and self._confirmed_draining()
                    ):
                        # The daemon confirmed it is draining toward
                        # shutdown: retries cannot land before the exit,
                        # so stop burning the backoff budget now.
                        raise RetriesExhaustedError(
                            f"{method} {self.base_url + path} refused: "
                            "daemon is draining toward shutdown "
                            "(confirmed via /v1/status); not retrying",
                            last_status=status,
                        )
            if attempt == self.max_retries:
                break
            delay = self._delay(attempt)
            if blame in TENANT_BLAME_REASONS:
                # The shed blames THIS tenant (its own cap, or a
                # quarantined structural class) — other tenants are not
                # throttled, so a fast retry only re-sheds. Back off
                # longer (module docstring).
                delay *= self.blame_backoff_factor
            self.n_retries += 1
            _log.debug(
                "retrying %s %s after %s (attempt %d/%d, sleep %.3fs)",
                method, path,
                last_status if last_status is not None else last_error,
                attempt + 1, self.max_retries, delay,
            )
            self._sleep(delay)
        why = (
            f"HTTP {last_status}" if last_status is not None
            else f"{type(last_error).__name__}: {last_error}"
        )
        raise RetriesExhaustedError(
            f"{method} {self.base_url + path} failed after "
            f"{self.max_retries + 1} attempts ({why})",
            last_status=last_status,
        )

    # ---------------------------------------------------------- endpoints
    def submit(
        self, config: dict, timeout: Optional[float] = None,
        *, tenant: Optional[str] = None, priority: Optional[str] = None,
    ):
        body = config
        if tenant is not None or priority is not None:
            # The wrapped form carries the admission fields (ISSUE-15);
            # a pre-wrapped body passes through untouched.
            body = dict(config) if "config" in config else {"config": config}
            if tenant is not None:
                body["tenant"] = tenant
            if priority is not None:
                body["priority"] = priority
        return self.request("POST", "/v1/submit", body, timeout)

    def run(self, config: dict, timeout: Optional[float] = None):
        # The socket timeout gets headroom over the server's long-poll
        # window (like result()): with both equal, a run finishing near
        # the window would look like a connection failure and be RETRIED
        # — re-submitting and re-executing the whole simulation.
        t = self.timeout_s if timeout is None else timeout
        return self.request(
            "POST", f"/v1/run?timeout={t:g}", config, t + 30.0,
        )

    def result(self, request_id: str, timeout: Optional[float] = None):
        t = self.timeout_s if timeout is None else timeout
        return self.request(
            "GET", f"/v1/result/{request_id}?timeout={t:g}", None, t + 30.0,
        )

    def status(self, timeout: Optional[float] = None):
        return self.request("GET", "/v1/status", None, timeout)

    def shutdown(
        self, timeout: Optional[float] = None, *, drain: bool = False,
        deadline: Optional[float] = None,
    ):
        path = "/v1/shutdown"
        if drain:
            path += "?drain=1"
            if deadline is not None:
                path += f"&deadline={deadline:g}"
            if timeout is None:
                # The server holds the request open while it drains;
                # give the socket headroom over the drain deadline.
                timeout = (deadline or 30.0) + 30.0
        return self.request("POST", path, None, timeout)

    def metrics_text(self, timeout: Optional[float] = None) -> str:
        """GET /metrics (Prometheus text, not JSON). Same retry policy
        as ``request``: connection failures and 429/503 retry with
        backoff; any other HTTP error is a structured answer and is
        re-raised untouched (never retried)."""
        timeout = self.timeout_s if timeout is None else timeout
        for attempt in range(self.max_retries + 1):
            try:
                with urllib.request.urlopen(
                    self.base_url + "/metrics", timeout=timeout
                ) as r:
                    return r.read().decode()
            except urllib.error.HTTPError as e:
                # HTTPError subclasses URLError/OSError — it must be
                # classified FIRST or structured 404/500 answers would
                # be hammered through the whole retry budget.
                if e.code not in RETRYABLE_STATUSES:
                    raise
                last = f"HTTP {e.code}"
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                last = f"{type(e).__name__}: {e}"
            if attempt == self.max_retries:
                raise RetriesExhaustedError(
                    f"GET /metrics failed after {attempt + 1} attempts "
                    f"({last})"
                )
            self.n_retries += 1
            self._sleep(self._delay(attempt))
        raise AssertionError("unreachable")

    def progress_stream(
        self, request_id: str, *, after: int = -1,
        timeout: Optional[float] = None,
    ):
        """Open ``/v1/progress/<id>`` and return the RAW response (the
        connection-close-terminated JSONL stream): callers that need the
        headers — e.g. asserting the ``application/x-ndjson`` content
        type — read them here, then iterate lines. The caller owns
        closing it (use as a context manager)."""
        t = self.timeout_s if timeout is None else timeout
        return urllib.request.urlopen(
            f"{self.base_url}/v1/progress/{request_id}"
            f"?timeout={t:g}&after={after}",
            timeout=t + 30.0,
        )

    def progress_events(
        self, request_id: str, *, after: int = -1,
        timeout: Optional[float] = None,
    ) -> Iterator[dict]:
        """Stream ``/v1/progress/<id>`` as decoded JSONL events (no
        mid-stream retry — a reconnect would be a NEW request with
        ``after=`` set)."""
        with self.progress_stream(
            request_id, after=after, timeout=timeout
        ) as resp:
            for line in resp:
                if line.strip():
                    yield json.loads(line)
