"""Simulation-as-a-service (ISSUE-7 tentpole; docs/SERVING.md).

Turns the simulator into a request-driven service built from three layers:

- ``serving.cache`` — the AOT executable cache: compiled XLA programs keyed
  by the config's STRUCTURAL hash (``ExperimentConfig.structural_hash``), so
  sweep/seed variants of one program reuse one ``Lowered``/compiled
  executable instead of paying the multi-second whole-run compile per
  request (docs/PERF.md §3). LRU by entry count + estimated bytes, with
  hit/miss/compile-seconds-saved counters. A process-wide default instance
  is consulted by ``backends/jax_backend.run``/``run_batch`` unless a caller
  opts out.
- ``serving.coalescer`` — groups structurally identical pending requests
  into one ``run_batch`` cohort (per-request sweepable scalars ride the
  replica axis as traced data) and slices each request's trajectory back
  out; unbatchable configs fall back to sequential ``run``.
- ``serving.service`` / ``serving.daemon`` — the front end: a
  ``SimulationService`` Python API (submit/result/stats, wait-window
  coalescing, bounded queue) and a stdlib-only HTTP daemon
  (``python -m distributed_optimization_tpu.serve``) that takes config JSON
  in and streams ``RunTrace`` manifests back.

ISSUE-15 grew the production plane on top:

- ``serving.store`` — the persistent executable store: compiled programs
  serialized to disk (jax AOT executable serialization) under provenance
  guards, so a daemon restart serves previously-compiled structural
  classes with 0 compile seconds (``DOPT_EXEC_STORE=<dir>`` /
  ``--store``).
- ``serving.admission`` — per-tenant weighted-fair scheduling (deficit
  round robin over (tenant, priority) sub-queues), per-tenant depth
  caps, shed-load 429s.
- ``serving.workers`` — N spawned worker processes executing cohorts
  concurrently, health-checked with bounded requeue; the store is their
  shared warm tier.

This ``__init__`` stays import-light on purpose: ``backends/jax_backend``
imports ``serving.cache`` at module load, so pulling the service/daemon
(and through them the backends) in here would be a cycle.
"""

from __future__ import annotations

_LAZY = {
    "ExecutableCache": "distributed_optimization_tpu.serving.cache",
    "process_executable_cache": "distributed_optimization_tpu.serving.cache",
    "structural_group_key": "distributed_optimization_tpu.serving.coalescer",
    "SimulationService": "distributed_optimization_tpu.serving.service",
    "ServingError": "distributed_optimization_tpu.serving.service",
    "ServingOptions": "distributed_optimization_tpu.serving.service",
    "ServingDaemon": "distributed_optimization_tpu.serving.daemon",
    "PersistentExecutableStore": "distributed_optimization_tpu.serving.store",
    "process_executable_store": "distributed_optimization_tpu.serving.store",
    "WeightedFairQueue": "distributed_optimization_tpu.serving.admission",
    "ShedLoad": "distributed_optimization_tpu.serving.admission",
    "WorkerPool": "distributed_optimization_tpu.serving.workers",
    "RetryingClient": "distributed_optimization_tpu.serving.client",
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    if name in _LAZY:
        import importlib

        return getattr(importlib.import_module(_LAZY[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
