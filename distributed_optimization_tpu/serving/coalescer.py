"""Request coalescing: structurally identical configs → one run_batch cohort.

The batched program (``jax_backend.run_batch``) executes R configs as ONE
vmapped XLA program when they differ only in per-replica data: seeds and
the ``SWEEPABLE_FIELDS`` scalars (eta0, clip_tau, edge_drop_prob). This
module decides which pending requests may share such a cohort and builds
the ``run_batch`` call for them:

- **grouping**: requests coalesce iff their ``structural_hash`` matches
  AND they name the same dataset (``resolved_data_seed`` — the dataset is
  a traced input, but one cohort shares one data pytree, so requests that
  generate different data cannot ride the same call; pin ``data_seed`` to
  let seed variants share a problem instance, docs/SERVING.md). Requests
  that differ only in a non-sweepable field hash apart and never coalesce.
- **sweep axes**: eta0 is ALWAYS swept (it is pure data), edge_drop_prob
  is swept iff the structural class runs the fault path (> 0 — the zero
  boundary is structural), clip_tau iff the class runs fixed-radius
  clipping. Always sweeping keeps the traced input pytree — and therefore
  the cached executable — identical across cohorts of the same class and
  size, whether or not this particular cohort's values differ.
- **fallback**: configs ``jax_backend.batch_unsupported_reason`` rejects
  (choco, compressed gossip, shard_map/pallas mixing, fused robust kernel,
  tensor parallelism, non-jax backends) become singleton sequential plans
  executed via ``run_algorithm`` — same rejection logic, no duplicated
  condition list.

Per-request results are the cohort's per-replica ``BackendRunResult``
slices; ``run_batch``'s replica-equivalence contract (replica r ==
``run(cfg_r)`` at ≤ 1e-12 in f64, tests/test_batch.py) is what makes the
served result the standalone result — tests/test_serving.py extends that
assertion to this path end to end.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from distributed_optimization_tpu.config import ExperimentConfig


def structural_group_key(config: ExperimentConfig) -> tuple:
    """The coalescing identity: (structural hash, dataset identity).

    Two requests with equal keys compile to the same program AND consume
    the same generated dataset, so they may share one ``run_batch`` call.
    """
    return (config.structural_hash(), config.resolved_data_seed())


def sweep_fields_for(config: ExperimentConfig) -> tuple[str, ...]:
    """Which sweepable fields ride the replica axis for this structural
    class (see module docstring — the zero boundaries are structural, so
    membership is a class property, not a cohort property)."""
    fields = ["learning_rate_eta0"]
    if config.edge_drop_prob > 0.0:
        fields.append("edge_drop_prob")
    if (
        config.aggregation == "clipped_gossip"
        and config.robust_b > 0
        and config.clip_tau > 0.0
    ):
        fields.append("clip_tau")
    return tuple(fields)


# Shared by ``SimulationService.submit`` (which rejects it up front) and
# ``unbatchable_reason`` (direct plan_cohorts callers) — one wording, no
# drift.
REPLICAS_UNSUPPORTED_REASON = (
    "serving requests carry one trajectory each (replicas == 1); "
    "submit one request per seed and let the coalescer batch them"
)


def unbatchable_reason(config: ExperimentConfig) -> Optional[str]:
    """Why this config must run sequentially, or None when it can batch.

    Delegates to ``jax_backend.batch_unsupported_reason`` — the coalescer
    must agree with the executor about what the executor would reject.
    """
    from distributed_optimization_tpu.backends.jax_backend import (
        batch_unsupported_reason,
    )

    if config.replicas > 1:
        return REPLICAS_UNSUPPORTED_REASON
    return batch_unsupported_reason(config)


@dataclasses.dataclass
class CohortPlan:
    """One planned execution: either a coalesced ``run_batch`` cohort or a
    sequential singleton (``sequential_reason`` set)."""

    requests: list  # objects exposing a .config: ExperimentConfig
    base: ExperimentConfig  # the cohort's program config (first request's)
    seeds: list[int]
    sweep: dict[str, list]
    sequential_reason: Optional[str] = None

    @property
    def size(self) -> int:
        return len(self.requests)

    @property
    def coalesced(self) -> bool:
        return self.sequential_reason is None and self.size > 1


def plan_cohorts(requests, max_cohort: int) -> list[CohortPlan]:
    """Group pending requests into execution plans, submission order
    preserved within each group; groups are chunked at ``max_cohort``.

    ``requests`` are any objects with a ``.config`` attribute (the
    service's Request records, or configs wrapped in a shim for tests).
    """
    if max_cohort < 1:
        raise ValueError(f"max_cohort must be >= 1, got {max_cohort}")
    plans: list[CohortPlan] = []
    groups: dict[tuple, list] = {}
    order: list[tuple] = []
    for req in requests:
        reason = unbatchable_reason(req.config)
        if reason is not None:
            plans.append(CohortPlan(
                requests=[req], base=req.config,
                seeds=[req.config.seed], sweep={},
                sequential_reason=reason,
            ))
            continue
        key = structural_group_key(req.config)
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(req)
    for key in order:
        members = groups[key]
        for lo in range(0, len(members), max_cohort):
            chunk = members[lo:lo + max_cohort]
            base = chunk[0].config
            sweep = {
                f: [getattr(r.config, f) for r in chunk]
                for f in sweep_fields_for(base)
            }
            plans.append(CohortPlan(
                requests=chunk, base=base,
                seeds=[r.config.seed for r in chunk], sweep=sweep,
            ))
    return plans


def execute_plan(
    plan: CohortPlan, dataset, f_opt: float, *, executable_cache=None,
    collect_metrics: bool = True, progress_factory=None,
    cohort_progress_cb=None, progress_every: int = 1,
):
    """Run one plan; returns the per-request ``BackendRunResult`` list
    (plan order). Coalesced plans go through ``run_batch`` and slice per
    replica; sequential plans through ``run_algorithm`` one at a time.

    Progress streaming (ISSUE-10): ``progress_factory(request)`` builds a
    per-request heartbeat callback for sequential plans (jax, tp=1 only —
    the other entry points have no chunked form); ``cohort_progress_cb``
    receives the batched cohort's heartbeats (per-replica gaps attached —
    the service fans them out to each request's stream).
    """
    if plan.sequential_reason is not None:
        from distributed_optimization_tpu.backends.base import run_algorithm

        out = []
        for req in plan.requests:
            kwargs = {}
            if req.config.backend == "jax" and req.config.tp_degree == 1:
                # The sequential jax path still reuses identical-program
                # compiles; numpy/cpp/TP entry points take no cache.
                kwargs["executable_cache"] = executable_cache
                if progress_factory is not None:
                    cb = progress_factory(req)
                    if cb is not None:
                        kwargs["progress_cb"] = cb
                        kwargs["progress_every"] = progress_every
            out.append(run_algorithm(req.config, dataset, f_opt, **kwargs))
        return out
    from distributed_optimization_tpu.backends import jax_backend

    batch = jax_backend.run_batch(
        plan.base, dataset, f_opt,
        seeds=plan.seeds, sweep=plan.sweep,
        collect_metrics=collect_metrics,
        executable_cache=executable_cache,
        progress_cb=cohort_progress_cb,
        progress_every=progress_every,
    )
    return list(batch.results)
