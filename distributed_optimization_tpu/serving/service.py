"""The simulation service: submit configs, get RunTrace manifests back.

``SimulationService`` is the Python front end of the serving subsystem
(docs/SERVING.md) — the daemon (``serving/daemon.py``) is a thin HTTP shim
over it:

- ``submit(config)`` validates the request (strict field check + the
  frozen config's own cross-field validation; malformed requests raise
  ``ServingError`` with the validation message, they never enter the
  queue) and enqueues it. The queue is bounded (``max_pending``), and so
  is the finished-request history (``max_done`` — a long-lived daemon
  rotates out old results instead of retaining every payload forever).
- a scheduler loop (``start()`` / the daemon) or an explicit ``drain()``
  coalesces pending requests within a wait window into ``run_batch``
  cohorts (``serving/coalescer.py``), executes each cohort through the
  process executable cache, and resolves every request to its own
  per-replica slice.
- each finished request carries its ``BackendRunResult`` AND a
  schema-versioned ``RunTrace`` manifest whose health block records the
  serving facts (cache hit, compile seconds saved, cohort size, queue
  wait) — the JSONL the daemon streams back.

Failure isolation: an exception while executing one plan (e.g. a config
that passes field validation but is rejected by the backend, like a robust
budget exceeding the topology's min degree) fails THAT plan's requests
with a structured error and leaves the queue, other cohorts, and the
scheduler loop alive — tests/test_serving.py submits exactly such a poison
request next to a healthy cohort.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Optional

from distributed_optimization_tpu.config import ExperimentConfig
from distributed_optimization_tpu.log import get_logger
from distributed_optimization_tpu.observability.metrics_registry import (
    metrics_registry,
)
from distributed_optimization_tpu.observability.progress import (
    ProgressEvent,
    ProgressStream,
)
from distributed_optimization_tpu.observability.spans import Tracer
from distributed_optimization_tpu.serving.admission import (
    DEFAULT_PRIORITY,
    DEFAULT_TENANT,
    AdmissionError,
    ShedLoad,
    WeightedFairQueue,
    validate_priority,
    validate_tenant,
)
from distributed_optimization_tpu.serving.cache import (
    ExecutableCache,
    process_cache_enabled,
    process_executable_cache,
)
from distributed_optimization_tpu.serving.coalescer import (
    REPLICAS_UNSUPPORTED_REASON,
    execute_plan,
    plan_cohorts,
)

_log = get_logger("serving")

_CONFIG_FIELDS = frozenset(
    f.name for f in dataclasses.fields(ExperimentConfig)
)

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"


class ServingError(ValueError):
    """A rejected request — malformed JSON shape, unknown fields, or a
    config the validation layer refuses. The daemon maps it to a
    structured 400 response; it never kills in-flight work."""


class QueueFullError(ServingError):
    """Backpressure, not a bad request: the bounded queue is full and the
    submission should be RETRIED after in-flight work drains. The daemon
    maps it to 429 so clients can tell it apart from a permanently
    invalid config. Shed-load rejections (per-tenant or global caps,
    ISSUE-15) carry the admission controller's reason and tenant."""

    def __init__(self, detail, *, reason="global_cap", tenant=DEFAULT_TENANT):
        super().__init__(detail)
        self.reason = reason
        self.tenant = tenant


class DrainingError(ServingError):
    """The service is draining toward shutdown: in-flight work finishes,
    NEW submissions are refused. The daemon maps it to 503 — retryable by
    the client contract, because a drain usually precedes a restart that
    will accept the retry."""


@dataclasses.dataclass
class ServingOptions:
    """Scheduler knobs (the daemon exposes them as flags).

    ``window_s``: how long the scheduler waits after work arrives before
    cutting cohorts — the latency it trades for coalescing opportunity.
    ``max_cohort``: replica-axis cap per ``run_batch`` call. ``max_pending``
    bounds the queue (submits beyond it are rejected, not buffered without
    limit); in-flight work is additionally bounded by the scheduler being
    single-threaded — one cohort executes at a time on the one chip.
    ``max_done`` bounds the FINISHED-request history: a long-lived daemon
    must not retain every served result forever, so once more than
    ``max_done`` requests have completed, the oldest finished records (and
    their result payloads/manifests) are dropped — a later result poll for
    an evicted id gets "unknown request", the serving analogue of a log
    rotation. Pending/running requests are never evicted.
    ``progress_every`` is the heartbeat cadence (in eval-chunks) of the
    live progress streams (``/v1/progress/<id>``): every executed plan
    runs with progress on, in segments of this many eval-chunks — the
    continuation machinery, bitwise the one-shot program.
    ``monitors`` (ISSUE-13) attaches one anomaly ``MonitorBank`` per
    request to those heartbeats: detector firings surface as structured
    incidents in ``/v1/status`` and as ``kind='anomaly'`` events on the
    request's progress stream, and land in the response manifest's
    health block. Observation only — the serving plane never halts a
    paying request (``halt_on='never'``); it costs one Python callback
    per heartbeat.

    Admission/fairness (ISSUE-15): ``max_pending_per_tenant`` caps one
    tenant's queued depth (None = only the global bound), and
    ``tenant_weights`` biases the weighted-fair scheduler (unlisted
    tenants weigh 1.0). ``cut_budget`` bounds how many requests one
    scheduler cut dequeues (None = everything pending — the PR-7
    behavior); a bounded cut is what keeps a backlogged tenant from
    monopolizing execution order between cuts. ``workers`` > 0 runs
    cohorts on that many spawned worker processes (``serving/
    workers.py``) instead of the scheduler thread — the persistent store
    (``DOPT_EXEC_STORE``) is their shared warm tier.
    """

    window_s: float = 0.05
    max_cohort: int = 32
    max_pending: int = 1024
    max_done: int = 512
    # Heartbeats every 5 eval-chunks: the measured sweet spot on the
    # bench container (docs/perf/observatory.json — per-eval heartbeats
    # cost ~14% there, every-5 ~4%, and a served cohort's wall time is
    # dominated by its compile anyway).
    progress_every: int = 5
    monitors: bool = True
    max_pending_per_tenant: Optional[int] = None
    tenant_weights: Optional[dict] = None
    cut_budget: Optional[int] = None
    workers: int = 0
    # Autoscaling headroom (ISSUE-16): the dispatch executor is sized to
    # this many threads (None = ``workers``), so a fleet the autoscaler
    # grows past the initial ``workers`` can actually receive that many
    # concurrent plans — thread pools cannot be resized after the fact.
    max_workers: Optional[int] = None

    def __post_init__(self) -> None:
        if self.progress_every < 1:
            raise ValueError(
                f"progress_every must be >= 1, got {self.progress_every}"
            )
        if self.window_s < 0:
            raise ValueError(f"window_s must be >= 0, got {self.window_s}")
        if self.max_cohort < 1:
            raise ValueError(
                f"max_cohort must be >= 1, got {self.max_cohort}"
            )
        if self.max_pending < 1:
            raise ValueError(
                f"max_pending must be >= 1, got {self.max_pending}"
            )
        if self.max_done < 1:
            raise ValueError(
                f"max_done must be >= 1, got {self.max_done}"
            )
        if self.cut_budget is not None and self.cut_budget < 1:
            raise ValueError(
                f"cut_budget must be >= 1, got {self.cut_budget}"
            )
        if self.workers < 0:
            raise ValueError(
                f"workers must be >= 0, got {self.workers}"
            )
        if self.max_workers is not None and self.max_workers < max(
            self.workers, 1
        ):
            raise ValueError(
                f"max_workers ({self.max_workers}) must be >= "
                f"workers ({self.workers}) and >= 1"
            )


@dataclasses.dataclass
class Request:
    """One submitted simulation request and its lifecycle record."""

    id: str
    config: ExperimentConfig
    submitted_at: float
    # Admission facts (ISSUE-15): which tenant submitted it and at what
    # priority class — what the weighted-fair scheduler ordered on.
    tenant: str = DEFAULT_TENANT
    priority: str = DEFAULT_PRIORITY
    # Which worker process executed it (multi-worker plane); None when
    # the scheduler thread ran it in-process.
    worker: Optional[int] = None
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False
    )
    # Live heartbeat channel (ISSUE-10): lifecycle events (queued →
    # running → done/failed) plus the backend's per-chunk progress while
    # the request executes — what the daemon's ``/v1/progress/<id>``
    # streams. Closed when the request finishes.
    progress: ProgressStream = dataclasses.field(
        default_factory=ProgressStream, repr=False
    )
    status: str = QUEUED
    result: Any = None  # BackendRunResult when DONE
    manifest: Optional[dict] = None  # RunTrace dict when DONE
    error: Optional[str] = None  # message when FAILED
    cohort_size: int = 0
    coalesced: bool = False
    sequential_reason: Optional[str] = None
    cache_hit: Optional[bool] = None
    queue_wait_s: Optional[float] = None
    run_wall_s: Optional[float] = None
    # Anomaly-sentinel firings observed on this request's heartbeats
    # (ISSUE-13): compact anomaly dicts, appended live as detectors fire.
    incidents: list = dataclasses.field(default_factory=list)
    # Fleet remediation (ISSUE-16): what the policy engine did about this
    # request (halt/requeue attribution), and how many times remediation
    # requeued it (bounded — one clean re-run per sibling).
    remediation: Optional[dict] = None
    requeues: int = 0

    def status_dict(self) -> dict:
        """The JSON-safe view the daemon returns for status polls."""
        out = {
            "id": self.id,
            "status": self.status,
            "config_hash": self.config.structural_hash(),
            "tenant": self.tenant,
            "priority": self.priority,
        }
        if self.error is not None:
            out["error"] = self.error
        if self.incidents:
            out["incidents"] = [
                {
                    "detector": i["detector"],
                    "severity": i["severity"],
                    "onset_iteration": i["onset_iteration"],
                }
                for i in self.incidents
            ]
        if self.remediation is not None:
            out["remediation"] = self.remediation
        if self.status in (DONE, FAILED):
            out["serving"] = self.serving_block()
        return out

    def serving_block(self) -> dict:
        """The per-request serving facts recorded into the manifest's
        health block (telemetry satellite)."""
        return {
            "cache_hit": self.cache_hit,
            "cohort_size": self.cohort_size,
            "coalesced": self.coalesced,
            "sequential_reason": self.sequential_reason,
            "queue_wait_s": self.queue_wait_s,
            "run_wall_s": self.run_wall_s,
            "tenant": self.tenant,
            "priority": self.priority,
            "worker": self.worker,
        }


def parse_config(payload) -> ExperimentConfig:
    """Strict config parsing for the serving surface.

    Unlike ``ExperimentConfig.from_dict`` (which silently drops unknown
    keys — fine for reading old manifests, wrong for a request API where a
    typoed field would silently run the default), unknown keys are
    rejected, and every validation error surfaces with the config's own
    message.
    """
    if isinstance(payload, ExperimentConfig):
        return payload
    if not isinstance(payload, dict):
        raise ServingError(
            f"config must be a JSON object of ExperimentConfig fields, "
            f"got {type(payload).__name__}"
        )
    unknown = set(payload) - _CONFIG_FIELDS
    if unknown:
        raise ServingError(
            f"unknown config fields {sorted(unknown)}; valid fields are "
            f"the ExperimentConfig schema (docs/SERVING.md)"
        )
    try:
        return ExperimentConfig(**payload)
    except (TypeError, ValueError) as e:
        raise ServingError(f"invalid config: {e}") from e


class SimulationService:
    """Request-driven simulation with an executable cache and a request
    coalescer (see the module docstring)."""

    def __init__(
        self,
        options: Optional[ServingOptions] = None,
        *,
        cache: Optional[ExecutableCache] = None,
        max_datasets: int = 16,
    ):
        self.options = options or ServingOptions()
        # The service's compile amortization rides the process cache by
        # default so CLI/Simulator warm-up carries over; pass an explicit
        # instance to scope it (tests do). When the operator disabled the
        # process cache (DOPT_EXEC_CACHE=0) and no explicit cache was
        # given, the service honors the kill switch: it runs fully
        # uncached (``self.cache is None`` → ``executable_cache=False``
        # downstream) instead of silently substituting a private cache.
        self.cache = (
            cache if cache is not None else process_executable_cache()
        )
        self._max_datasets = max_datasets
        self._datasets: dict[tuple, tuple] = {}  # key -> (ds, f_opt)
        self._lock = threading.RLock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # The admission-controlled queue (ISSUE-15): per-(tenant,
        # priority) sub-queues under a deficit-round-robin scheduler.
        # Pushes and cuts happen under the SERVICE lock (the WFQ's own
        # lock is a leaf) so the QUEUED-before-RUNNING lifecycle ordering
        # survives: a cut can never interleave between a push and its
        # QUEUED publish.
        self._queue = WeightedFairQueue(
            max_pending=self.options.max_pending,
            max_pending_per_tenant=self.options.max_pending_per_tenant,
            tenant_weights=self.options.tenant_weights,
        )
        # Requests cut from the queue but not yet finished — what a
        # graceful drain waits out alongside the queue itself.
        self._inflight = 0
        self._draining = False
        # Multi-worker plane (options.workers > 0): created on demand so
        # a plain in-process service never spawns anything.
        self._pool = None
        self._executor = None
        # Fleet reflexes (ISSUE-16): the remediation engine consulted at
        # admission (quarantine) and cohort completion (review), and the
        # autoscaler that registered against this service — both None on
        # a plain service, and both attach from serving/fleet.py.
        self._fleet = None
        self._autoscaler = None
        self._gauge_lock = threading.Lock()
        self._gauge_tenants: set[str] = set()
        self._requests: dict[str, Request] = {}
        # Finished-request ids in completion order — the bounded history
        # (ServingOptions.max_done) a long-lived daemon rotates through.
        self._done_order: "deque[str]" = deque()
        self._counter = 0
        # Coalescing/queue statistics (telemetry satellite). Bounded like
        # every other long-lived buffer here: stats() reports over the
        # most recent window, counters cover the lifetime.
        self.cohort_sizes: "deque[int]" = deque(maxlen=4096)
        self.queue_waits: "deque[float]" = deque(maxlen=4096)
        self.n_done = 0
        self.n_failed = 0
        self.n_sequential = 0
        self.n_cohorts = 0
        # Anomaly-sentinel firings across all served requests (ISSUE-13).
        self.n_incidents = 0
        self.data_gen_seconds = 0.0
        self.oracle_seconds = 0.0
        # Span tracing (ISSUE-10): request → cohort → compile/run spans,
        # exportable as a Chrome trace; per-request subtrees land in the
        # response manifests.
        self.tracer = Tracer()
        # Metrics registry instrumentation: the process-wide families a
        # /metrics scrape reads. Counters accumulate across service
        # instances; the queue-depth gauge polls the NEWEST service
        # (gauge_fn re-registration replaces the callback).
        reg = metrics_registry()
        self._m_requests = reg.counter(
            "dopt_serving_requests_total",
            "Serving requests by terminal status",
        )
        self._m_cohort_size = reg.histogram(
            "dopt_serving_cohort_size",
            "Coalesced cohort sizes (requests per executed plan)",
            buckets=(1, 2, 4, 8, 16, 32, 64),
        )
        self._m_queue_wait = reg.histogram(
            "dopt_serving_queue_wait_seconds",
            "Submit-to-execution-start wait per request",
        )
        reg.gauge_fn(
            "dopt_serving_queue_depth",
            "Requests pending in the serving queue",
            self.queue_depth,
        )
        # Admission metrics (ISSUE-15 satellite): the shed counter is a
        # labeled family so dashboards split rejections by cause and
        # actor; registered here so a cold daemon renders it as a valid
        # zero series before any shed happens.
        self._m_shed = reg.counter(
            "dopt_serving_shed_total",
            "Submissions refused by admission control, by reason "
            "(tenant_cap/global_cap) and tenant",
        )
        self._m_tenant_depth = reg.gauge(
            "dopt_serving_tenant_queue_depth",
            "Requests pending in the serving queue, per tenant",
        )

    # -------------------------------------------------------------- fleet
    def attach_fleet(self, engine) -> None:
        """Bind a ``RemediationEngine`` (serving/fleet.py): submissions
        check its quarantine table, live anomalies feed it, completed
        plans pass through its policy review, and a lazily-built worker
        pool inherits its death hook. Callers use ``engine.attach(
        service)``, which also wires the store listener."""
        self._fleet = engine
        pool = self._pool
        if pool is not None:
            pool.set_death_hook(engine.on_worker_death)

    # ---------------------------------------------------------- submission
    def submit(self, config, *, tenant=None, priority=None) -> str:
        """Validate and enqueue one request; returns its id.

        Raises ``ServingError`` for malformed/invalid configs (including
        malformed tenant/priority fields), ``QueueFullError`` when
        admission sheds the request (per-tenant or global cap), and
        ``DrainingError`` while a graceful drain is in progress —
        rejected requests never enter the queue.
        """
        cfg = parse_config(config)
        if cfg.replicas > 1:
            raise ServingError(REPLICAS_UNSUPPORTED_REASON)
        try:
            tenant = validate_tenant(tenant)
            priority = validate_priority(priority)
        except AdmissionError as e:
            # Re-raise as the structured 400 the daemon already maps —
            # a malformed tenant field is a bad request, not a 500.
            raise ServingError(str(e)) from e
        fleet = self._fleet
        if fleet is not None:
            # Quarantine check (ISSUE-16): a (tenant, structural class)
            # pair under an active divergence quarantine sheds with a
            # machine-readable reason before touching the queue — the
            # same 429 + Retry-After contract the caps speak.
            qreason = fleet.quarantine_reason(cfg, tenant)
            if qreason is not None:
                self._m_shed.inc(reason="quarantined", tenant=tenant)
                raise QueueFullError(
                    qreason, reason="quarantined", tenant=tenant,
                )
        shed: Optional[ShedLoad] = None
        with self._lock:
            if self._draining:
                raise DrainingError(
                    "service is draining toward shutdown; new submissions "
                    "are refused (retry against the restarted instance)"
                )
            req = Request(
                id=f"req-{self._counter + 1:06d}",
                config=cfg,
                submitted_at=time.perf_counter(),
                tenant=tenant,
                priority=priority,
            )
            try:
                self._queue.push(req, tenant=tenant, priority=priority)
            except ShedLoad as e:
                shed = e
            else:
                self._counter += 1
                # QUEUED must hit the stream BEFORE the request becomes
                # visible to a scheduler cut: published after the lock
                # released, a scheduler thread already past its wait
                # could cut the request and publish RUNNING first,
                # handing subscribers an out-of-order lifecycle. (The
                # push above IS visibility, but cuts also take this
                # lock, so no cut can interleave before the publish.)
                # The stream lock is a leaf (publish never calls back
                # into the service), so publishing under the service
                # lock cannot invert an order.
                req.progress.publish(ProgressEvent(
                    kind="lifecycle", iteration=0,
                    n_iterations=cfg.n_iterations, wall_seconds=0.0,
                    status=QUEUED,
                ))
                self._requests[req.id] = req
        if shed is not None:
            # Registry counters outside the service lock (the gauge
            # callbacks re-enter the service under the registry lock —
            # the ABBA convention every instrumented path here follows).
            self._m_shed.inc(reason=shed.reason, tenant=shed.tenant)
            raise QueueFullError(
                f"shed ({shed.reason}): {shed}; retry with backoff",
                reason=shed.reason, tenant=shed.tenant,
            ) from shed
        self._publish_tenant_depths()
        self._wake.set()
        return req.id

    def _publish_tenant_depths(self) -> None:
        """Refresh the per-tenant depth gauge family from the queue's
        current state; tenants that drained to zero keep an explicit 0
        series (a vanished series reads as 'scrape lost it', a 0 reads
        as 'empty'). Never called under the service lock."""
        depths = self._queue.depths()
        with self._gauge_lock:
            for t in self._gauge_tenants - set(depths):
                self._m_tenant_depth.set(0, tenant=t)
            for t, d in depths.items():
                self._m_tenant_depth.set(d, tenant=t)
            self._gauge_tenants |= set(depths)

    # ------------------------------------------------------------- lookup
    def get(self, request_id: str) -> Request:
        with self._lock:
            req = self._requests.get(request_id)
        if req is None:
            raise KeyError(f"unknown request id {request_id!r}")
        return req

    def result(self, request_id: str, timeout: Optional[float] = None):
        """Block until the request finishes; returns the Request record
        (status DONE or FAILED), or raises TimeoutError."""
        req = self.get(request_id)
        if not req.done.wait(timeout):
            raise TimeoutError(
                f"request {request_id} still {req.status} after {timeout}s"
            )
        return req

    # ---------------------------------------------------------- scheduling
    def queue_depth(self) -> int:
        return len(self._queue)

    def process_once(self) -> int:
        """Cut a weighted-fair batch from the queue and execute it;
        returns the number of requests resolved. The scheduler loop calls
        this after the wait window; tests call it directly for
        determinism. The cut takes everything pending unless
        ``options.cut_budget`` bounds it (then a backlogged tenant's
        excess stays queued for later rounds — the fairness lever).

        With workers configured, the cut's plans run CONCURRENTLY across
        the worker processes (one executor thread per in-flight plan);
        in-process mode executes them serially on the calling thread,
        exactly the PR-7 behavior.
        """
        with self._lock:  # cut under the service lock — see submit()
            batch = self._queue.cut(self.options.cut_budget)
            self._inflight += len(batch)
        if not batch:
            return 0
        self._publish_tenant_depths()
        plans = plan_cohorts(batch, self.options.max_cohort)
        executor = self._ensure_workers()
        if executor is not None and len(plans) > 1:
            futures = [
                executor.submit(self._execute_tracked, p) for p in plans
            ]
            for f in futures:
                f.result()
        else:
            for plan in plans:
                self._execute_tracked(plan)
        return len(batch)

    def _execute_tracked(self, plan) -> None:
        try:
            self._execute(plan)
        finally:
            with self._lock:
                self._inflight -= plan.size

    def _ensure_workers(self):
        """Spawn the worker pool + dispatch executor on first use (when
        ``options.workers`` > 0); returns the executor or None."""
        if self.options.workers <= 0:
            return None
        with self._lock:
            if self._pool is None:
                from concurrent.futures import ThreadPoolExecutor

                from distributed_optimization_tpu.serving.workers import (
                    WorkerPool,
                )

                fleet = self._fleet
                self._pool = WorkerPool(
                    self.options.workers,
                    on_worker_death=(
                        fleet.on_worker_death if fleet is not None else None
                    ),
                )
                self._pool.start()
                self._executor = ThreadPoolExecutor(
                    # Autoscaling headroom: size the dispatch width to the
                    # fleet ceiling, not the initial fleet (ISSUE-16).
                    max_workers=(
                        self.options.max_workers or self.options.workers
                    ),
                    thread_name_prefix="serving-dispatch",
                )
            return self._executor

    def drain(self) -> int:
        """Process until the queue is empty (synchronous callers/tests)."""
        total = 0
        while self.queue_depth() > 0:
            total += self.process_once()
        return total

    # ------------------------------------------------------ graceful drain
    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def begin_drain(self) -> None:
        """Refuse new submissions from now on; in-flight and queued work
        keeps executing. ``/v1/shutdown?drain=1`` calls this, then
        ``wait_drained`` — requests already accepted survive the drain
        (tested with an in-flight cohort)."""
        with self._lock:
            self._draining = True
        self._wake.set()

    def wait_drained(self, timeout: float = 30.0) -> bool:
        """Block until queued + in-flight work is fully finished or
        ``timeout`` elapses; returns whether the service is empty. The
        scheduler loop (or explicit ``process_once`` calls) must be
        running for the queue to make progress."""
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            with self._lock:
                empty = len(self._queue) == 0 and self._inflight == 0
            if empty:
                return True
            self._wake.set()
            time.sleep(0.02)
        with self._lock:
            return len(self._queue) == 0 and self._inflight == 0

    def dataset_for(self, cfg: ExperimentConfig):
        """Public dataset access sharing the service memo: the scenario
        engine's direct backend runs (final-state and checkpoint
        invariants) must consume the SAME dataset instance its served
        cells ran on, or cross-run bitwise comparisons would compare
        different problems."""
        return self._dataset_for(cfg)

    def _dataset_for(self, cfg: ExperimentConfig):
        """Dataset + reference optimum for a request, memoized on the
        fields that determine them (bounded FIFO — datasets are cheap to
        regenerate, the memo just keeps cohort cuts snappy)."""
        from distributed_optimization_tpu.utils.data import (
            generate_synthetic_dataset,
        )
        from distributed_optimization_tpu.utils.oracle import (
            compute_reference_optimum,
        )

        key = (
            cfg.problem_type, cfg.n_samples, cfg.n_features,
            cfg.n_informative_features, cfg.classification_sep,
            cfg.n_classes, cfg.partition, cfg.n_workers,
            cfg.resolved_data_seed(), cfg.reg_param, cfg.huber_delta,
        )
        with self._lock:
            hit = self._datasets.get(key)
        if hit is not None:
            return hit
        t0 = time.perf_counter()
        ds = generate_synthetic_dataset(cfg)
        t1 = time.perf_counter()
        _, f_opt = compute_reference_optimum(
            ds, cfg.reg_param, huber_delta=cfg.huber_delta,
            n_classes=cfg.n_classes,
        )
        t2 = time.perf_counter()
        with self._lock:
            self.data_gen_seconds += t1 - t0
            self.oracle_seconds += t2 - t1
            while len(self._datasets) >= self._max_datasets:
                self._datasets.pop(next(iter(self._datasets)))
            self._datasets[key] = (ds, float(f_opt))
        return ds, float(f_opt)

    def _plan_progress(self, plan):
        """Heartbeat plumbing for one executed plan (ISSUE-10): sequential
        requests get their own backend callback; a batched cohort's
        heartbeats fan out to every member with ITS replica's gap swapped
        in (the cohort-level mean stays in ``extra``).

        Anomaly sentinel (ISSUE-13): with ``options.monitors`` on, every
        request gets its own ``MonitorBank`` watching exactly the
        heartbeats its stream carries; a firing is appended to
        ``req.incidents`` (surfaced by ``/v1/status``) and published as a
        ``kind='anomaly'`` event on the stream, so a follower sees the
        diagnosis inline with the progress it rode in on."""
        banks: dict[str, Any] = {}
        if self.options.monitors:
            from distributed_optimization_tpu.observability.monitors import (
                MonitorBank,
            )

            for req in plan.requests:
                # Observation only: the serving plane records and
                # surfaces, it never halts a request mid-flight.
                banks[req.id] = MonitorBank(
                    req.config, halt_on="never", label=req.id,
                )

        def deliver(req, ev):
            req.progress.publish(ev)
            bank = banks.get(req.id)
            if bank is None:
                return
            for anomaly in bank.observe(ev):
                req.incidents.append(anomaly.to_dict())
                with self._lock:
                    self.n_incidents += 1
                fleet = self._fleet
                if fleet is not None:
                    try:
                        # Live remediation hook (ISSUE-16): e.g. a fatal
                        # divergence quarantines its structural class
                        # MID-FLIGHT, before the cohort finishes.
                        fleet.on_anomaly(req, anomaly)
                    except Exception:
                        _log.exception("fleet anomaly hook failed")
                req.progress.publish(ProgressEvent(
                    kind="anomaly",
                    iteration=int(anomaly.onset_iteration),
                    n_iterations=req.config.n_iterations,
                    wall_seconds=ev.wall_seconds,
                    status=f"anomaly:{anomaly.detector}",
                    extra={
                        "detector": anomaly.detector,
                        "severity": anomaly.severity,
                        "message": anomaly.message,
                    },
                ))

        def progress_factory(req):
            return lambda ev: deliver(req, ev)

        def cohort_cb(ev):
            per_replica = ev.gap_per_replica
            for idx, req in enumerate(plan.requests):
                if per_replica is not None and idx < len(per_replica):
                    ev_r = dataclasses.replace(
                        ev, gap=per_replica[idx], gap_per_replica=None,
                        extra={"cohort_gap_mean": ev.gap,
                               "cohort_size": plan.size},
                    )
                else:
                    ev_r = ev
                deliver(req, ev_r)

        return progress_factory, cohort_cb, banks

    def _execute(self, plan) -> None:
        t_start = time.perf_counter()
        for req in plan.requests:
            req.status = RUNNING
            req.queue_wait_s = t_start - req.submitted_at
            req.cohort_size = plan.size
            req.coalesced = plan.coalesced
            req.sequential_reason = plan.sequential_reason
            req.progress.publish(ProgressEvent(
                kind="lifecycle", iteration=0,
                n_iterations=req.config.n_iterations, wall_seconds=0.0,
                status=RUNNING,
                extra={"cohort_size": plan.size,
                       "coalesced": plan.coalesced},
            ))
        progress_factory, cohort_cb, banks = self._plan_progress(plan)
        # Per-plan span tree (request → cohort → compile/run → the
        # backend's chunks): embedded in each member's manifest and
        # aggregated into the service tracer's flat phases.
        plan_tracer = Tracer()
        try:
            with plan_tracer.span(
                "cohort", aggregate=False, size=plan.size,
                coalesced=plan.coalesced,
                structural_hash=plan.base.structural_hash(),
            ):
                if self._pool is not None:
                    # Multi-worker plane: ship the plan to a worker
                    # process; its heartbeats route back into the same
                    # per-request streams the in-process path feeds.
                    deliverers = [
                        progress_factory(r) for r in plan.requests
                    ]

                    def on_progress(idx, ev_dict):
                        ev = ProgressEvent(**ev_dict)
                        if idx is None:
                            cohort_cb(ev)
                        else:
                            deliverers[idx](ev)

                    results, worker_id = self._pool.run_plan(
                        plan, on_progress,
                        progress_every=self.options.progress_every,
                    )
                    for req in plan.requests:
                        req.worker = worker_id
                else:
                    ds, f_opt = self._dataset_for(plan.base)
                    results = execute_plan(
                        plan, ds, f_opt,
                        # Honor the kill switch: no cache means COLD
                        # compiles, not a silently substituted private
                        # cache.
                        executable_cache=(
                            self.cache if self.cache is not None else False
                        ),
                        progress_factory=progress_factory,
                        cohort_progress_cb=cohort_cb,
                        progress_every=self.options.progress_every,
                    )
                wall = time.perf_counter() - t_start
                compile_s = min(
                    results[0].history.compile_seconds, wall
                ) if results else 0.0
                plan_tracer.add_span("compile", compile_s, start=t_start)
                plan_tracer.add_span(
                    "run", wall - compile_s, start=t_start + compile_s
                )
        except Exception as e:  # isolate the poison plan, keep serving
            msg = f"{type(e).__name__}: {e}"
            _log.warning("plan of %d request(s) failed: %s", plan.size, msg)
            with self._lock:
                self.n_failed += plan.size
            self._m_requests.inc(plan.size, status="failed")
            for req in plan.requests:
                req.status = FAILED
                req.error = msg
                self._finish(req)
            return
        with self._lock:
            self.n_cohorts += 1
            self.cohort_sizes.append(plan.size)
            self.queue_waits.extend(
                r.queue_wait_s for r in plan.requests
            )
            if plan.sequential_reason is not None:
                self.n_sequential += plan.size
            for name, secs in plan_tracer.phases.items():
                self.tracer.phases[name] = (
                    self.tracer.phases.get(name, 0.0) + secs
                )
        self._m_cohort_size.observe(plan.size)
        self._m_queue_wait.observe_many(
            [r.queue_wait_s for r in plan.requests]
        )
        jax_cached_path = (
            plan.base.backend == "jax" and plan.base.tp_degree == 1
            and (
                # Worker mode: each worker runs its own process cache,
                # governed by the same kill switch it inherited.
                process_cache_enabled() if self._pool is not None
                else self.cache is not None
            )
        )
        for req, res in zip(plan.requests, results):
            req.result = res
            bank = banks.get(req.id)
            if bank is not None and res.history.trace is not None:
                # Trace-derived detectors (screening saturation, the
                # non-finite state sentinel) see the flight recorder
                # buffers the request opted into.
                new = bank.scan_trace(
                    res.history.trace, res.history.eval_iterations
                )
                if new:
                    with self._lock:
                        self.n_incidents += len(new)
                req.incidents = [a.to_dict() for a in bank.anomalies]
            # Race-free per-request cache fact: the service always
            # measures compile, so zero compile seconds on a cached jax
            # path means this request's executable came from the cache —
            # no shared-counter delta that concurrent cache users could
            # skew. None when caching is off or the path has no reusable
            # jax compile (numpy/cpp/TP).
            req.cache_hit = (
                res.history.compile_seconds == 0.0
                if jax_cached_path else None
            )
            req.run_wall_s = wall
        # Fleet policy review (ISSUE-16): with an engine attached, a
        # fatal incident can override the default "everything completed
        # is DONE" — the offender fails with a policy-attributed error,
        # its innocent cohort siblings requeue for one clean re-run.
        verdicts: dict = {}
        fleet = self._fleet
        if fleet is not None:
            try:
                verdicts = fleet.review_plan(plan, banks)
            except Exception:
                _log.exception("fleet plan review failed; serving as-is")
                verdicts = {}
        n_done_now = n_failed_now = 0
        for req, res in zip(plan.requests, results):
            verdict = verdicts.get(req.id)
            if verdict is not None:
                req.remediation = verdict.get("remediation")
                if verdict["action"] == "requeue" and (
                    self._requeue_for_remediation(req)
                ):
                    continue  # back in the queue; not finished
                # "fail", or a requeue the admission layer shed:
                req.result = None
                req.status = FAILED
                req.error = verdict.get("error") or (
                    "failed by fleet remediation policy"
                )
                n_failed_now += 1
                self._finish(req)
                continue
            req.manifest = self._manifest(
                req, res, spans=plan_tracer.chrome_events(),
                bank=banks.get(req.id),
            )
            req.status = DONE
            n_done_now += 1
            self._finish(req)
        with self._lock:
            self.n_done += n_done_now
            self.n_failed += n_failed_now
        if n_done_now:
            self._m_requests.inc(n_done_now, status="done")
        if n_failed_now:
            self._m_requests.inc(n_failed_now, status="failed")

    def _requeue_for_remediation(self, req: Request) -> bool:
        """Push a cohort sibling back into the queue for a clean re-run
        (fleet policy action). Returns False when admission sheds the
        requeue — the caller then fails the request structurally instead
        of leaving it stuck."""
        shed = None
        with self._lock:
            req.requeues += 1
            req.status = QUEUED
            req.worker = None
            req.result = None
            req.cache_hit = None
            try:
                self._queue.push(
                    req, tenant=req.tenant, priority=req.priority,
                )
            except ShedLoad as e:
                shed = e
            else:
                req.progress.publish(ProgressEvent(
                    kind="lifecycle", iteration=0,
                    n_iterations=req.config.n_iterations,
                    wall_seconds=req.run_wall_s or 0.0,
                    status=QUEUED,
                    extra={"requeued_by": "fleet", "attempt":
                           req.requeues + 1},
                ))
        if shed is not None:
            self._m_shed.inc(reason=shed.reason, tenant=shed.tenant)
            return False
        self._publish_tenant_depths()
        self._wake.set()
        return True

    def _finish(self, req: Request) -> None:
        """Mark a request finished and rotate the bounded history: beyond
        ``max_done`` completed records, the oldest finished request (and
        its result payload) is dropped — later polls for its id get
        "unknown request". Pending/running requests are never evicted.
        The request's progress stream gets its terminal lifecycle event
        and closes — a ``/v1/progress`` follower unblocks here."""
        req.progress.publish(ProgressEvent(
            kind="lifecycle",
            iteration=(
                req.config.n_iterations if req.status == DONE else 0
            ),
            n_iterations=req.config.n_iterations,
            wall_seconds=req.run_wall_s or 0.0,
            status=req.status,
            extra={"error": req.error} if req.error else None,
        ))
        req.progress.close()
        req.done.set()
        with self._lock:
            self._done_order.append(req.id)
            while len(self._done_order) > self.options.max_done:
                self._requests.pop(self._done_order.popleft(), None)

    def _manifest(self, req: Request, res, spans=None, bank=None) -> dict:
        """The request's RunTrace manifest (the daemon's response body):
        config + hash, phases, trace buffers when the request asked for
        telemetry, the health block extended with the serving facts and
        any anomaly-sentinel incidents (ISSUE-13), and (schema v2) the
        plan's span tree."""
        from distributed_optimization_tpu import telemetry

        health = telemetry.health_summary(
            req.config, res.history, serving=req.serving_block(),
        )
        if bank is not None and bank.anomalies:
            health["incidents"] = bank.summary()
        return telemetry.build_run_trace(
            req.id, req.config, res.history,
            phases={
                "queue_wait": req.queue_wait_s or 0.0,
                "run": req.run_wall_s or 0.0,
            },
            health=health,
            spans=spans,
        ).to_dict()

    # ----------------------------------------------------- background loop
    def start(self) -> None:
        """Start the scheduler thread (the daemon's mode). Idempotent."""
        with self._lock:
            if self._thread is not None:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="simulation-service", daemon=True
            )
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            if not self._wake.wait(timeout=0.2):
                continue
            # The coalescing window: give concurrent submitters a beat to
            # land in the same cut before cohorts are formed.
            if self.options.window_s > 0:
                time.sleep(self.options.window_s)
            self._wake.clear()
            try:
                self.process_once()
            except Exception:  # pragma: no cover - belt and braces
                _log.exception("scheduler iteration failed; continuing")
            # A bounded cut (options.cut_budget) can leave work queued
            # with no further submit to wake us — re-arm so the backlog
            # drains round by round instead of stalling until the next
            # submission.
            if self.queue_depth() > 0:
                self._wake.set()

    def close(self) -> None:
        """Stop the scheduler loop (pending work stays queued) and tear
        down the worker plane when one was spawned."""
        autoscaler = self._autoscaler
        if autoscaler is not None:
            # The autoscaler must stop BEFORE the pool it scales dies.
            autoscaler.stop()
        self._stop.set()
        self._wake.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
            self._thread = None
        executor, pool = self._executor, self._pool
        self._executor = self._pool = None
        if executor is not None:
            executor.shutdown(wait=False)
        if pool is not None:
            pool.close()

    # ------------------------------------------------------------ telemetry
    def stats(self) -> dict:
        """Service-level counters: queue, cohorts, cache (JSON-safe).

        Shape contract (ISSUE-10 satellite, docs/SERVING.md): the
        ``cache`` and ``cohorts``/``queue_wait_s`` blocks are ALWAYS
        present with every counter key — zeros before any work, and the
        full counter set even when the executable cache is disabled
        (``disabled: true`` rides alongside) — so dashboards and the
        ``/metrics`` bridge never have to special-case a cold daemon.
        ``history`` documents the bounded (last-``max_done``) finished-
        request retention and lists the most recent completions.
        """
        import numpy as np

        if self.cache is not None:
            cache_stats = self.cache.stats()
        else:
            # The kill switch still answers with the full counter shape —
            # derived from the cache class itself so it cannot drift as
            # counters are added.
            from distributed_optimization_tpu.serving.cache import (
                ExecutableCache,
            )

            cache_stats = {"disabled": True, **ExecutableCache.empty_stats()}
        # Queue/pool stats outside the service lock (each has its own
        # leaf lock) — and the admission block is ALWAYS present with
        # every key, zeros cold, like the cache block.
        admission = {
            **self._queue.stats(),
            "depths": self._queue.depths(),
        }
        pool = self._pool
        workers_stats = pool.stats() if pool is not None else None
        # Fleet block (ISSUE-16): remediation-policy state + autoscaler
        # summary when attached, None on a plain service — computed
        # outside the service lock (both have their own leaf locks).
        fleet_block = None
        if self._fleet is not None or self._autoscaler is not None:
            fleet_block = {
                "remediation": (
                    self._fleet.status() if self._fleet is not None
                    else None
                ),
                "autoscaler": (
                    self._autoscaler.status()
                    if self._autoscaler is not None else None
                ),
            }
        with self._lock:
            admission["inflight"] = self._inflight
            draining = self._draining
            sizes = list(self.cohort_sizes)
            waits = list(self.queue_waits)
            recent = [
                self._requests[rid].status_dict()
                for rid in list(self._done_order)[-16:]
                if rid in self._requests
            ]
            out = {
                "queue_depth": len(self._queue),
                "draining": draining,
                "admission": admission,
                "workers": workers_stats,
                "fleet": fleet_block,
                "requests_total": self._counter,
                "requests_done": self.n_done,
                "requests_failed": self.n_failed,
                "requests_sequential_fallback": self.n_sequential,
                # Anomaly-sentinel firings over all served requests
                # (ISSUE-13); per-request details ride each request's
                # status_dict/manifest, this is the fleet-level count.
                "incidents_total": self.n_incidents,
                # count is lifetime; mean/max summarize the most recent
                # window (the deques are bounded — see __init__).
                "cohorts": {
                    "count": self.n_cohorts,
                    "mean_size": float(np.mean(sizes)) if sizes else None,
                    "max_size": int(max(sizes)) if sizes else None,
                },
                "queue_wait_s": {
                    "mean": float(np.mean(waits)) if waits else None,
                    "max": float(max(waits)) if waits else None,
                },
                "data_gen_seconds": self.data_gen_seconds,
                "oracle_seconds": self.oracle_seconds,
                "phases": {
                    k: float(v) for k, v in self.tracer.phases.items()
                },
                "cache": cache_stats,
                # Bounded per-request history: only the last ``bound``
                # finished requests are retained (older ids answer
                # "unknown request"); ``recent`` lists the newest 16.
                "history": {
                    "bound": self.options.max_done,
                    "retained": len(self._done_order),
                    "recent": recent,
                },
            }
        return out
