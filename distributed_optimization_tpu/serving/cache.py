"""AOT executable cache (ISSUE-7 tentpole; docs/SERVING.md).

Every jax execution path compiles the ENTIRE run into one XLA program and,
until this layer existed, re-traced and re-compiled it on every call — the
4–6 s line item docs/PERF.md §3 measures, paid per CLI invocation, per
``Simulator.run_one``, per bench variant. The compiled executable itself is
reusable: data shards, PRNG keys, fault timelines, Byzantine masks, swept
scalars and (on the batched path) f* are all traced INPUTS, so any request
whose config compiles to the same program can re-execute a cached
executable with its own inputs and get bit-for-bit the result a fresh
compile would have produced (tests/test_serving.py pins it).

What IS baked into a program — and therefore what a cache key must carry —
differs per path, so the key builders live here next to the cache:

- both paths bake the topology's realized constants (mixing weights,
  degrees, neighbor tables) and everything ``ExperimentConfig
  .structural_dict`` covers;
- the SEQUENTIAL program additionally bakes the run seed (its PRNG key is
  a closure constant), the unswept hyperparameter scalars, and f*, so its
  key is the full config hash — reuse means "the identical experiment
  again" (exactly the ``make smoke`` / repeated-CLI-invocation waste);
- the BATCHED program takes seeds/sweeps/f* as data, so its key is the
  STRUCTURAL hash plus call-level facts (cohort size R, t0, which rp
  inputs exist, data shapes) — reuse spans seed and sweep variants, which
  is what the serving coalescer trades on.

Entries are LRU-evicted by count AND estimated bytes; hits, misses,
evictions and compile-seconds-saved are counted for the serving telemetry
(``telemetry.health_summary(serving=...)``, ``format_report``).

A process-wide default instance is consulted by ``jax_backend.run`` /
``run_batch`` when the caller passes ``executable_cache=None`` (pass
``False`` to force a cold compile; set ``DOPT_EXEC_CACHE=0`` to disable
the default for a whole process).
"""

from __future__ import annotations

import dataclasses
import os
import threading
from collections import OrderedDict
from typing import Any, Optional

from distributed_optimization_tpu.config import SWEEPABLE_FIELDS

# Default LRU bounds: enough distinct programs for a bench/smoke session
# without letting a long-lived daemon accumulate unbounded compiled code.
DEFAULT_MAX_ENTRIES = 64
DEFAULT_MAX_BYTES = 2_000_000_000
# Conservative per-entry estimate when XLA's memory analysis is unavailable
# (CPU builds often report nothing): small-config CPU executables measure
# well under this, so the bytes bound stays a bound, not a fiction.
FALLBACK_ENTRY_BYTES = 8_000_000

_DISABLE_ENV = "DOPT_EXEC_CACHE"


def estimate_executable_bytes(executable) -> int:
    """Estimated resident size of a compiled executable.

    Prefers XLA's own ``memory_analysis`` (generated code + temp
    allocations); falls back to a fixed conservative estimate — eviction
    accounting is telemetry-adjacent, never control flow worth raising for.
    """
    try:
        ma = executable.memory_analysis()
        size = 0
        for attr in (
            "generated_code_size_in_bytes",
            "temp_size_in_bytes",
            "argument_size_in_bytes",
            "output_size_in_bytes",
        ):
            v = getattr(ma, attr, None)
            if v:
                size += int(v)
        if size > 0:
            return size
    except Exception:
        pass
    return FALLBACK_ENTRY_BYTES


@dataclasses.dataclass
class CacheEntry:
    """One cached compiled program + the provenance its reuse reports."""

    executable: Any
    cost: Optional[dict]  # telemetry.cost_from_lowered of the cold lowering
    compile_seconds: float  # what the cold compile cost (== what a hit saves)
    est_bytes: int
    hits: int = 0


class ExecutableCache:
    """LRU cache of compiled XLA executables, keyed by opaque tuples.

    Thread-safe (the serving daemon submits from HTTP handler threads).
    Keys are built by the ``sequential_cache_key``/``batch_cache_key``
    helpers below — the cache itself never inspects configs.
    """

    def __init__(
        self,
        max_entries: int = DEFAULT_MAX_ENTRIES,
        max_bytes: int = DEFAULT_MAX_BYTES,
        store=None,
    ):
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        if max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1, got {max_bytes}")
        self.max_entries = max_entries
        self.max_bytes = max_bytes
        # Optional persistent disk tier (serving/store.py, ISSUE-15):
        # get() falls through to it on a memory miss, put() writes
        # through to it — both outside the cache lock (disk I/O and
        # executable deserialization must not serialize lookups).
        self.store = store
        self._entries: "OrderedDict[tuple, CacheEntry]" = OrderedDict()
        self._lock = threading.RLock()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.store_hits = 0
        self.evictions = 0
        self.compile_seconds_saved = 0.0
        # Registry instrumentation (ISSUE-10): every cache instance feeds
        # the process-wide counters — a scrape sees the whole process's
        # compile amortization, whichever cache instances produced it.
        from distributed_optimization_tpu.observability.metrics_registry import (
            metrics_registry,
        )

        reg = metrics_registry()
        self._m_hits = reg.counter(
            "dopt_exec_cache_hits_total", "Executable-cache hits")
        self._m_misses = reg.counter(
            "dopt_exec_cache_misses_total", "Executable-cache misses")
        self._m_evictions = reg.counter(
            "dopt_exec_cache_evictions_total", "Executable-cache evictions")
        self._m_saved = reg.counter(
            "dopt_exec_cache_compile_seconds_saved_total",
            "Compile seconds avoided by executable-cache hits")

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get(self, key: tuple) -> Optional[CacheEntry]:
        """Look up a compiled program; counts a hit or a miss either way.

        On a memory miss, falls through to the persistent store tier
        (when one is attached): a store hit deserializes the executable,
        promotes it into memory, and counts as a hit AND a ``store_hit``
        — callers see exactly the contract a memory hit gives them
        (``compile_seconds == 0.0`` on the reuse path), which is what the
        restart-warm gate measures.

        Registry counters are bumped AFTER the cache lock is released:
        the registry's render/snapshot path calls back into the cache
        (the entries/bytes gauges) while holding the registry lock, so
        touching the registry while holding the cache lock would be the
        classic ABBA deadlock against a concurrent ``/metrics`` scrape.
        Store I/O (disk read + executable load) happens outside the lock
        too — a multi-ms deserialize must not serialize other lookups.
        """
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                entry.hits += 1
                self.compile_seconds_saved += entry.compile_seconds
        if entry is not None:
            self._m_hits.inc()
            self._m_saved.inc(entry.compile_seconds)
            return entry
        loaded = self.store.load(key) if self.store is not None else None
        if loaded is None:
            with self._lock:
                self.misses += 1
            self._m_misses.inc()
            return None
        # Store hit: promote into memory (no write-back — it came from
        # disk) and account it as a hit the moment it is served.
        n_evicted = self._insert(key, loaded)
        with self._lock:
            self.hits += 1
            self.store_hits += 1
            loaded.hits += 1
            self.compile_seconds_saved += loaded.compile_seconds
        if n_evicted:
            self._m_evictions.inc(n_evicted)
        self._m_hits.inc()
        self._m_saved.inc(loaded.compile_seconds)
        return loaded

    def _insert(self, key: tuple, entry: CacheEntry) -> int:
        """Insert under the lock with LRU eviction; returns the eviction
        count for the caller to report outside the lock (see get())."""
        n_evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.est_bytes
            self._entries[key] = entry
            self._bytes += entry.est_bytes
            while len(self._entries) > self.max_entries or (
                self._bytes > self.max_bytes and len(self._entries) > 1
            ):
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= evicted.est_bytes
                self.evictions += 1
                n_evicted += 1
        return n_evicted

    def put(
        self,
        key: tuple,
        executable,
        *,
        cost: Optional[dict] = None,
        compile_seconds: float = 0.0,
    ) -> CacheEntry:
        """Insert a freshly compiled program, evicting LRU entries past the
        count/bytes bounds (the newest entry itself is never evicted — an
        oversized program simply owns the cache until something replaces
        it). Write-through: when a persistent store is attached, the new
        program is serialized to disk so a future process starts warm."""
        entry = CacheEntry(
            executable=executable,
            cost=cost,
            compile_seconds=float(compile_seconds),
            est_bytes=estimate_executable_bytes(executable),
        )
        n_evicted = self._insert(key, entry)
        if n_evicted:  # outside the cache lock — see get()
            self._m_evictions.inc(n_evicted)
        if self.store is not None:
            # Outside the lock: serialization is slow and never
            # load-bearing (save() degrades to a warning on failure).
            self.store.save(key, entry)
        return entry

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def attach_store(self, store) -> None:
        """Attach (or replace) the persistent disk tier after
        construction — how the daemon wires ``--store`` into the
        process-wide default cache."""
        self.store = store

    def stats(self) -> dict:
        """Counters for the serving telemetry block (all plain scalars
        except ``store``, which is the attached store's own stats dict or
        None — the key is ALWAYS present so the status shape does not
        depend on deployment)."""
        store = self.store
        store_stats = store.stats() if store is not None else None
        with self._lock:
            lookups = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "est_bytes": int(self._bytes),
                "hits": int(self.hits),
                "misses": int(self.misses),
                "store_hits": int(self.store_hits),
                "evictions": int(self.evictions),
                "hit_rate": self.hits / lookups if lookups else None,
                "compile_seconds_saved": float(self.compile_seconds_saved),
                "store": store_stats,
            }

    @classmethod
    def empty_stats(cls) -> dict:
        """The zero-valued ``stats()`` shape, derived from a fresh
        instance so it CANNOT drift from the real one — the
        disabled-cache status block reuses it to keep the "counter keys
        always present" contract (docs/SERVING.md) as counters are
        added."""
        return cls().stats()


# ------------------------------------------------------- process-wide default

_process_cache: Optional[ExecutableCache] = None
_process_lock = threading.Lock()


def process_cache_enabled() -> bool:
    return os.environ.get(_DISABLE_ENV, "").lower() not in (
        "0", "off", "false", "no",
    )


def process_executable_cache() -> Optional[ExecutableCache]:
    """The process-wide default cache ``jax_backend`` consults when a caller
    passes ``executable_cache=None`` — what makes ``make smoke`` and
    repeated CLI invocations in one process compile each program once.
    ``DOPT_EXEC_CACHE=0`` disables it (returns None)."""
    if not process_cache_enabled():
        return None
    global _process_cache
    with _process_lock:
        if _process_cache is None:
            # ``DOPT_EXEC_STORE=<dir>`` attaches the persistent disk tier
            # (serving/store.py) to the process default — the env-var
            # form is what spawned serving workers inherit, so every
            # worker shares one warm store with zero plumbing.
            from distributed_optimization_tpu.serving.store import (
                process_executable_store,
            )

            _process_cache = ExecutableCache(
                store=process_executable_store()
            )
            # Scrape-time gauges for the process cache's current state
            # (entries/bytes are someone's source of truth, not events —
            # the registry polls them so they can never go stale).
            from distributed_optimization_tpu.observability.metrics_registry import (  # noqa: E501
                metrics_registry,
            )

            reg = metrics_registry()
            cache = _process_cache
            # The callbacks run under the REGISTRY lock (scrape time), so
            # they must not take the cache lock (ABBA vs get/put, which
            # bump registry counters) — plain attribute reads are atomic
            # enough for a gauge, and a one-entry-stale reading is fine.
            reg.gauge_fn(
                "dopt_exec_cache_entries",
                "Compiled programs resident in the process executable cache",
                lambda: len(cache._entries),
            )
            reg.gauge_fn(
                "dopt_exec_cache_bytes",
                "Estimated resident bytes of the process executable cache",
                lambda: cache._bytes,
            )
        return _process_cache


def resolve_cache(executable_cache) -> Optional[ExecutableCache]:
    """Resolve the backends' ``executable_cache`` argument: ``None`` → the
    process default, ``False`` → no caching (force a cold compile), an
    ``ExecutableCache`` → itself."""
    if executable_cache is None:
        return process_executable_cache()
    if executable_cache is False:
        return None
    return executable_cache


# ------------------------------------------------------------- key builders


def _full_config_hash(config) -> str:
    from distributed_optimization_tpu.telemetry import config_hash

    return config_hash(config.to_dict())


def _jax_env_signature() -> tuple:
    """The jax-global facts a trace bakes in beyond the config: the x64
    switch (weak-typed scalars promote under it) and the visible device
    set (platform, count, and identity — shardings bind to devices)."""
    import jax

    return (
        bool(jax.config.jax_enable_x64),
        tuple(str(d) for d in jax.devices()),
    )


def dataset_signature(device_data) -> tuple:
    """What a compiled program pins about its data INPUTS: shapes and
    dtypes — the values themselves are traced arguments — plus the
    per-worker valid counts, which feed host-side branch decisions
    (full-batch fast path, eval-cadence form selection)."""
    return (
        tuple(device_data.X.shape),
        str(device_data.X.dtype),
        str(device_data.y.dtype),
        tuple(int(v) for v in device_data.n_valid),
    )


def sequential_cache_key(
    config,
    f_opt: float,
    device_data,
    *,
    schedule_signature=None,
    collect_metrics: bool = True,
    mesh_signature=None,
    hoisted_min_ratio=None,
    eval_hoist_limit=None,
    segment=None,
) -> tuple:
    """Cache key for the sequential fused-scan program (``_run``'s
    no-checkpoint path). Everything per-run is baked there — the PRNG key,
    the hyperparameter scalars, f* — so the key is the FULL config hash
    plus the call-level knobs that alter the trace. ``segment`` carries
    the progress-streaming segmentation facts (segment size in evals):
    the segmented program takes its iteration offset as a TRACED argument
    where the one-shot program bakes t0=0, so the two must never share an
    executable."""
    return (
        "seq",
        _full_config_hash(config),
        float(f_opt),
        dataset_signature(device_data),
        schedule_signature,
        bool(collect_metrics),
        mesh_signature,
        hoisted_min_ratio,
        eval_hoist_limit,
        segment,
        _jax_env_signature(),
    )


def batch_cache_key(
    config,
    device_data,
    *,
    R: int,
    t0: int,
    rp_keys,
    sweep_fields,
    collect_metrics: bool = True,
    segment=None,
) -> tuple:
    """Cache key for the replica-batched program (``run_batch``).

    Seeds, swept scalars, fault timelines, Byzantine masks and f* are all
    traced inputs of that program, so they are NOT in the key — which is
    exactly why sweep/seed variants of one structural config hit the same
    cached executable. What remains baked: the structural hash (incl. the
    realized random-topology graph), the UNSWEPT sweepable scalars (closure
    constants when not on the replica axis), the set of per-replica inputs
    the trace was built with (``rp_keys`` — presence changes the input
    pytree), the cohort size R, the continuation offset t0 (timeline
    horizons are t0+T), and the data signature. ``segment`` carries the
    progress-streaming segmentation facts (the per-call trip count
    differs from the one-shot program's).
    """
    sweep_fields = set(sweep_fields)
    unswept = tuple(
        (f, getattr(config, f))
        for f in SWEEPABLE_FIELDS
        if f not in sweep_fields
    )
    return (
        "batch",
        config.structural_hash(),
        int(R),
        int(t0),
        tuple(sorted(rp_keys)),
        unswept,
        dataset_signature(device_data),
        bool(collect_metrics),
        segment,
        _jax_env_signature(),
    )
