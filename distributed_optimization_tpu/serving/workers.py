"""Multi-worker execution plane (ISSUE-15 tentpole part c).

PR 7's daemon executes every cohort on the scheduler thread of ONE
process — correct, but the GIL plus one-compile-at-a-time means distinct
structural classes serialize behind each other even on a many-core host.
This module adds N worker **processes** (stdlib ``multiprocessing``,
spawn context) behind the service:

- the parent ships a planned cohort to a worker as plain data (config
  dicts + the plan facts); the worker rebuilds the plan with the SAME
  coalescer code path (``plan_cohorts``/``execute_plan``) the in-process
  mode uses, so multi-worker execution cannot drift semantically from
  single-process execution — tests pin served-vs-direct parity at
  ≤ 1e-12 through this plane;
- the **persistent executable store** (``serving/store.py``) is the
  shared warm state: each worker keeps its own in-memory process cache,
  and the ``DOPT_EXEC_STORE`` env var (inherited through spawn) points
  them all at one store directory, so a program compiled by any worker —
  or by a previous daemon incarnation — is a disk hit for every other;
- progress heartbeats stream back over the result queue as
  ``ProgressEvent.to_dict()`` payloads and are re-published into each
  request's live stream — ``/v1/progress`` behaves identically in both
  modes;
- a health monitor detects a died worker (crash, OOM-kill), **requeues**
  its in-flight tasks onto surviving workers with a bounded attempt
  budget (then fails them structurally — the daemon's 500, which the
  RetryingClient contract treats as a terminal answer, while the shed/
  restart paths stay retryable), respawns the worker, and counts it all
  in the ``dopt_serving_worker_*`` metric families.

Spawn (not fork): jax runtimes do not survive forking, and spawn gives
each worker a clean interpreter whose env (platform pins, store path) is
applied before jax initializes. Module-level imports here stay stdlib-
only so the spawned child can bootstrap without dragging jax in before
``_worker_main`` sets its environment.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Optional

# Absolute cap on one task's wall time before the parent gives up on it.
# Generous: a cold whole-run compile is 4-6 s; minutes-long simulations
# ride serving only in benches. The health monitor usually fails tasks
# much sooner (dead-worker detection), this bounds the lost-message case.
DEFAULT_TASK_TIMEOUT_S = 900.0
# A task killed by a dying worker is retried on another worker this many
# times in total before it fails structurally.
MAX_TASK_ATTEMPTS = 2


class WorkerPlanError(RuntimeError):
    """A plan failed in (or with) its worker — carries the worker-side
    message; the service maps it to the same structured request failure
    an in-process execution error produces."""


# --------------------------------------------------------------- wire format


def _npify(obj):
    """Convert jax arrays (and any array-likes) to host numpy, leaving
    scalars/containers alone — the worker must never ship device arrays
    across the process boundary."""
    import numpy as np

    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return obj
    if isinstance(obj, dict):
        return {k: _npify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_npify(v) for v in obj)
    if isinstance(obj, np.ndarray):
        return obj
    if hasattr(obj, "__array__"):
        return np.asarray(obj)
    return obj


def encode_result(res) -> dict:
    """One ``BackendRunResult`` as a picklable payload (numpy + plain)."""
    return {
        "history": res.history,  # RunHistory is host-numpy by contract
        "final_models": _npify(res.final_models),
        "final_avg_model": _npify(res.final_avg_model),
        "final_state": _npify(res.final_state),
    }


def decode_result(payload: dict):
    from distributed_optimization_tpu.backends.base import BackendRunResult

    return BackendRunResult(
        history=payload["history"],
        final_models=payload["final_models"],
        final_avg_model=payload["final_avg_model"],
        final_state=payload["final_state"],
    )


def encode_plan(plan, *, progress_every: int) -> dict:
    """A ``CohortPlan`` as plain data the worker can rebuild exactly.

    Only the member configs travel: the worker re-derives grouping,
    sweep axes and the sequential fallback from them with the shared
    coalescer code, so there is exactly one source of plan semantics.
    """
    return {
        "configs": [r.config.to_dict() for r in plan.requests],
        "progress_every": int(progress_every),
    }


# ------------------------------------------------------------- worker child


@dataclasses.dataclass(eq=False)  # identity semantics — two requests may
class _Shim:                      # carry byte-identical configs
    """The coalescer's request duck type (it only reads ``.config``)."""

    config: Any


def _worker_run_plan(task: dict, datasets: dict, emit_progress) -> list:
    """Execute one shipped plan inside the worker; returns encoded
    results in request order."""
    from distributed_optimization_tpu.config import ExperimentConfig
    from distributed_optimization_tpu.serving.coalescer import (
        execute_plan,
        plan_cohorts,
    )
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import (
        compute_reference_optimum,
    )

    configs = [ExperimentConfig.from_dict(d) for d in task["configs"]]
    plans = plan_cohorts(
        [_Shim(c) for c in configs], max_cohort=max(len(configs), 1)
    )
    if len(plans) != 1:  # the parent ships one plan's members — see encode
        raise WorkerPlanError(
            f"shipped cohort re-planned into {len(plans)} plans; "
            "parent/worker coalescer disagree"
        )
    plan = plans[0]
    cfg = plan.base
    key = (
        cfg.problem_type, cfg.n_samples, cfg.n_features,
        cfg.n_informative_features, cfg.classification_sep,
        cfg.n_classes, cfg.partition, cfg.n_workers,
        cfg.resolved_data_seed(), cfg.reg_param, cfg.huber_delta,
    )
    hit = datasets.get(key)
    if hit is None:
        ds = generate_synthetic_dataset(cfg)
        _, f_opt = compute_reference_optimum(
            ds, cfg.reg_param, huber_delta=cfg.huber_delta,
            n_classes=cfg.n_classes,
        )
        hit = (ds, float(f_opt))
        if len(datasets) >= 16:  # same bound the service memo uses
            datasets.pop(next(iter(datasets)))
        datasets[key] = hit
    ds, f_opt = hit

    idx_of = {id(s): i for i, s in enumerate(plan.requests)}

    def progress_factory(shim):
        idx = idx_of[id(shim)]
        return lambda ev: emit_progress(idx, ev.to_dict())

    def cohort_cb(ev):
        emit_progress(None, ev.to_dict())

    results = execute_plan(
        plan, ds, f_opt,
        executable_cache=None,  # the worker's process cache (+ env store)
        progress_factory=progress_factory,
        cohort_progress_cb=cohort_cb,
        progress_every=task["progress_every"],
    )
    return [encode_result(r) for r in results]


def _worker_main(worker_id: int, task_q, result_q, env: dict) -> None:
    """Worker process entry point. Applies env overrides BEFORE any jax
    import (platform pins and the store path must precede backend init),
    then serves tasks until the ``None`` sentinel."""
    os.environ.update(env)
    result_q.put(("ready", worker_id, os.getpid()))
    datasets: dict = {}
    while True:
        task = task_q.get()
        if task is None:
            break
        if task.get("__retire__"):
            # Autoscaler scale-down (ISSUE-16): the retire sentinel is
            # only ever picked up BETWEEN tasks, so a retiring worker has
            # by construction finished its in-flight cohort — the drain
            # contract, *per worker*. Exactly one worker consumes each
            # sentinel; it announces and exits.
            result_q.put(("retired", worker_id))
            break
        task_id = task["task_id"]
        result_q.put(("start", task_id, worker_id))

        def emit(idx, ev_dict, _tid=task_id):
            result_q.put(("progress", _tid, idx, ev_dict))

        try:
            encoded = _worker_run_plan(task, datasets, emit)
        except BaseException as e:  # ship the failure, stay alive
            result_q.put((
                "error", task_id, worker_id,
                f"{type(e).__name__}: {e}",
            ))
        else:
            result_q.put(("done", task_id, worker_id, encoded))


# ------------------------------------------------------------- parent pool


@dataclasses.dataclass
class _Task:
    """Parent-side record of one in-flight plan."""

    task_id: int
    payload: dict
    progress_handler: Any  # callable(idx_or_None, ev_dict)
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )
    results: Optional[list] = None
    error: Optional[str] = None
    worker_id: Optional[int] = None
    attempts: int = 1


class WorkerPool:
    """N spawn-context worker processes + router/health threads.

    ``run_plan`` is thread-safe and blocking — the service calls it from
    its per-plan executor threads, so N plans execute truly concurrently
    across N processes while the parent keeps the bookkeeping.
    """

    def __init__(
        self,
        n_workers: int,
        *,
        env: Optional[dict] = None,
        max_task_attempts: int = MAX_TASK_ATTEMPTS,
        on_worker_death=None,
    ):
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        self.n_workers = n_workers  # current TARGET size (scale ops move it)
        self.env = dict(env or {})
        self.max_task_attempts = max_task_attempts
        # Fleet hook (ISSUE-16): called as fn(worker_id, requeued, lost)
        # when a worker dies unexpectedly; returns whether to respawn.
        # None keeps the PR-15 behavior: always respawn.
        self._on_death = on_worker_death
        self._ctx = None
        self._task_q = None
        self._result_q = None
        self._procs: dict[int, Any] = {}
        self._tasks: dict[int, _Task] = {}
        self._lock = threading.Lock()
        self._counter = 0
        self._next_wid = n_workers  # fresh ids for scale-up spawns
        self._pending_retires = 0
        self._stop = threading.Event()
        self._router: Optional[threading.Thread] = None
        self._monitor: Optional[threading.Thread] = None
        self.n_restarts = 0
        self.n_requeues = 0
        self.n_retired = 0
        from distributed_optimization_tpu.observability.metrics_registry import (  # noqa: E501
            metrics_registry,
        )

        reg = metrics_registry()
        self._m_tasks = reg.counter(
            "dopt_serving_worker_tasks_total",
            "Plans executed by the worker plane, by worker and result "
            "(done/error/requeued/lost)",
        )
        self._m_restarts = reg.counter(
            "dopt_serving_worker_restarts_total",
            "Worker processes respawned after dying with tasks in flight",
        )
        reg.gauge_fn(
            "dopt_serving_workers_alive",
            "Live worker processes in the execution plane",
            self.alive_count,
        )

    # -------------------------------------------------------------- lifecycle
    def start(self) -> None:
        import multiprocessing as mp

        if self._router is not None:
            return
        self._ctx = mp.get_context("spawn")
        self._task_q = self._ctx.Queue()
        self._result_q = self._ctx.Queue()
        for wid in range(self.n_workers):
            self._spawn(wid)
        self._stop.clear()
        self._router = threading.Thread(
            target=self._route, name="worker-pool-router", daemon=True
        )
        self._router.start()
        self._monitor = threading.Thread(
            target=self._watch, name="worker-pool-health", daemon=True
        )
        self._monitor.start()

    def _spawn(self, worker_id: int) -> None:
        proc = self._ctx.Process(
            target=_worker_main,
            args=(worker_id, self._task_q, self._result_q, self.env),
            name=f"serving-worker-{worker_id}",
            daemon=True,
        )
        proc.start()
        self._procs[worker_id] = proc

    def close(self) -> None:
        self._stop.set()
        if self._task_q is not None:
            for _ in self._procs:
                try:
                    self._task_q.put(None)
                except Exception:
                    pass
        for proc in self._procs.values():
            proc.join(timeout=5.0)
            if proc.is_alive():
                proc.terminate()
                proc.join(timeout=2.0)
        for t in (self._router, self._monitor):
            if t is not None:
                t.join(timeout=2.0)
        self._router = self._monitor = None
        self._procs.clear()

    def alive_count(self) -> int:
        return sum(1 for p in self._procs.values() if p.is_alive())

    def worker_ids(self) -> list[int]:
        """Ids of the workers currently in the fleet (retired ones are
        gone) — the autoscaler's per-worker gauge label universe."""
        with self._lock:
            return sorted(self._procs)

    # --------------------------------------------------------------- scaling
    def scale_up(self, k: int = 1) -> list[int]:
        """Spawn ``k`` additional workers (fresh ids, never reusing a
        retired id — label series stay unambiguous); returns the new ids.
        Requires a started pool."""
        if self._router is None:
            raise RuntimeError("scale_up on a pool that was never started")
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        new_ids = []
        with self._lock:
            for _ in range(k):
                wid = self._next_wid
                self._next_wid += 1
                new_ids.append(wid)
            self.n_workers += k
        for wid in new_ids:
            self._spawn(wid)
        return new_ids

    def scale_down(self, k: int = 1) -> None:
        """Retire ``k`` workers gracefully: a retire sentinel is posted
        on the shared task queue per retirement, and whichever worker
        picks one up finishes its in-flight cohort first (the sentinel is
        only read between tasks), announces, and exits. Never drops the
        target below 1 — a zero-worker pool cannot serve."""
        if self._router is None:
            raise RuntimeError("scale_down on a pool that was never started")
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        with self._lock:
            if self.n_workers - k < 1:
                raise ValueError(
                    f"scale_down({k}) would leave {self.n_workers - k} "
                    "workers; the pool floor is 1"
                )
            self.n_workers -= k
            self._pending_retires += k
        for _ in range(k):
            self._task_q.put({"__retire__": True})

    def _finish_retirement(self, worker_id: int) -> None:
        """Idempotent bookkeeping for a retired worker — reached from the
        router (the announced path) or the health monitor (announcement
        lost); whichever pops the proc record wins."""
        with self._lock:
            proc = self._procs.pop(worker_id, None)
            if proc is None:
                return
            self._pending_retires = max(0, self._pending_retires - 1)
            self.n_retired += 1
        proc.join(timeout=5.0)
        if proc.is_alive():
            proc.terminate()

    # ------------------------------------------------------------ dispatching
    def run_plan(
        self, plan, progress_handler, *, progress_every: int = 1,
        timeout: float = DEFAULT_TASK_TIMEOUT_S,
    ):
        """Execute one plan on some worker; returns (results, worker_id).

        Blocks until the task finishes, is requeued-to-death, or times
        out; raises ``WorkerPlanError`` on failure. ``progress_handler``
        receives ``(replica_idx_or_None, event_dict)`` live.
        """
        with self._lock:
            self._counter += 1
            task = _Task(
                task_id=self._counter,
                payload={
                    "task_id": self._counter,
                    **encode_plan(plan, progress_every=progress_every),
                },
                progress_handler=progress_handler,
            )
            self._tasks[task.task_id] = task
        self._task_q.put(task.payload)
        try:
            if not task.done.wait(timeout):
                raise WorkerPlanError(
                    f"worker task {task.task_id} timed out after {timeout}s"
                )
        finally:
            with self._lock:
                self._tasks.pop(task.task_id, None)
        if task.error is not None:
            raise WorkerPlanError(task.error)
        return [decode_result(p) for p in task.results], task.worker_id

    # ---------------------------------------------------------------- router
    def _route(self) -> None:
        import queue as queue_mod

        while not self._stop.is_set():
            try:
                msg = self._result_q.get(timeout=0.2)
            except (queue_mod.Empty, OSError, EOFError):
                continue
            kind = msg[0]
            if kind == "ready":
                continue
            if kind == "retired":
                self._finish_retirement(msg[1])
                continue
            if kind == "start":
                _, task_id, worker_id = msg
                with self._lock:
                    task = self._tasks.get(task_id)
                    if task is not None:
                        task.worker_id = worker_id
                continue
            if kind == "progress":
                _, task_id, idx, ev_dict = msg
                with self._lock:
                    task = self._tasks.get(task_id)
                if task is not None:
                    try:
                        task.progress_handler(idx, ev_dict)
                    except Exception:
                        pass  # a progress consumer must never kill routing
                continue
            if kind in ("done", "error"):
                _, task_id, worker_id, payload = msg
                with self._lock:
                    task = self._tasks.get(task_id)
                if task is None:
                    continue
                task.worker_id = worker_id
                if kind == "done":
                    task.results = payload
                else:
                    task.error = str(payload)
                self._m_tasks.inc(
                    worker=str(worker_id),
                    result="done" if kind == "done" else "error",
                )
                task.done.set()

    # ---------------------------------------------------------------- health
    def _watch(self) -> None:
        """Detect died workers: requeue their in-flight tasks (bounded
        attempts), respawn the process, count everything."""
        while not self._stop.is_set():
            time.sleep(0.3)
            for wid, proc in list(self._procs.items()):
                if proc.is_alive() or self._stop.is_set():
                    continue
                # Tasks assigned to the dead worker and not finished:
                with self._lock:
                    orphans = [
                        t for t in self._tasks.values()
                        if t.worker_id == wid and not t.done.is_set()
                    ]
                    pending_retire = self._pending_retires > 0
                if not orphans and pending_retire:
                    # A clean exit with retirements outstanding is almost
                    # certainly a retiring worker whose announcement the
                    # router has not drained yet — fold it into the
                    # retirement path (idempotent) instead of respawning
                    # a worker the autoscaler just asked to go away.
                    self._finish_retirement(wid)
                    continue
                n_requeued = n_lost = 0
                for task in orphans:
                    if task.attempts >= self.max_task_attempts:
                        task.error = (
                            f"worker {wid} died executing task "
                            f"{task.task_id} (attempt {task.attempts}/"
                            f"{self.max_task_attempts}); giving up"
                        )
                        self._m_tasks.inc(worker=str(wid), result="lost")
                        n_lost += 1
                        task.done.set()
                    else:
                        task.attempts += 1
                        task.worker_id = None
                        self.n_requeues += 1
                        n_requeued += 1
                        self._m_tasks.inc(
                            worker=str(wid), result="requeued")
                        self._task_q.put(task.payload)
                respawn = True
                if self._on_death is not None:
                    try:
                        respawn = bool(self._on_death(wid, n_requeued,
                                                      n_lost))
                    except Exception:
                        respawn = True  # a broken policy must not strand
                if respawn:
                    self.n_restarts += 1
                    self._m_restarts.inc(worker=str(wid))
                    self._spawn(wid)
                else:
                    # Policy vetoed the respawn (dead_worker rule
                    # disabled): drop the record so the monitor does not
                    # re-detect the same corpse every poll, and shrink
                    # the target to match reality.
                    with self._lock:
                        self._procs.pop(wid, None)
                        self.n_workers = max(1, self.n_workers - 1)

    # ------------------------------------------------------------- telemetry
    def stats(self) -> dict:
        with self._lock:
            in_flight = len(self._tasks)
        return {
            "workers": self.n_workers,
            "alive": self.alive_count(),
            "in_flight": in_flight,
            "restarts": int(self.n_restarts),
            "requeues": int(self.n_requeues),
            "retired": int(self.n_retired),
        }

    def set_death_hook(self, fn) -> None:
        """(Re)attach the dead-worker policy hook after construction —
        how a fleet engine binds to a pool the service built lazily."""
        self._on_death = fn
