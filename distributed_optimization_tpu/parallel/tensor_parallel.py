"""Tensor parallelism for the compute-bound softmax tier: DP × TP mesh.

The framework's base layout is 1-D data parallelism — worker rows sharded
over the ``'workers'`` mesh axis, gossip crossing chip boundaries as
collectives (``parallel/collectives.py``). The softmax family
(``models/softmax.py``) adds the second axis TPUs are built around: its
[d, K] classifier matrix shards column-blocks over a ``'model'`` mesh
axis, so a 2-D ``(workers, model)`` mesh runs BOTH parallelisms at once —
the execution layout of the scaling-book recipe (mesh + shardings +
XLA/explicit collectives), demonstrated here with explicit ``shard_map``
collectives so the communication pattern is auditable in compiled HLO:

- every FLOP-heavy tensor is sharded: X by worker rows, W/logits/grads by
  worker rows AND class columns — no device ever holds a full [d, K];
- the ONLY cross-model-shard traffic is the softmax normalization: a
  ``pmax`` + ``psum`` of [n_local, b] scalars per step (payload O(b) per
  worker, INDEPENDENT of K — asserted against compiled HLO in
  tests/test_tensor_parallel.py);
- ring gossip runs over the workers axis exactly as in the DP layout, but
  each device exchanges only its OWN class slice — boundary ppermute
  payload d·K/tp floats per device instead of d·K (TP shards the gossip
  traffic too, also HLO-asserted);
- the update rule is bitwise the same math as the replicated path: the
  three-tier oracles (numpy matrix recursion, single-mesh jax backend)
  pin the TP trajectory to fp tolerance in the tests.

Scope: D-SGD + softmax + ring, full local batches (the compute tier's
measured configuration — the per-iteration RNG of subsampling is a
data-parallel concern the DP path already covers). This module is the
multi-chip execution path for the tier `docs/perf/compute_bound.json`
measures single-chip; ``__graft_entry__.dryrun_multichip`` validates it
end-to-end on the virtual mesh (compile + execute + optimize).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_optimization_tpu.backends.base import x64_scope
from distributed_optimization_tpu.parallel._compat import shard_map
from distributed_optimization_tpu.parallel.mesh import WORKER_AXIS

MODEL_AXIS = "model"

# Metric evals run BETWEEN per-cadence scans (a Python-unrolled segment
# sequence — the backend's "hoisted" structure), so a run computes exactly
# n_evals full-dataset evaluations; the limit bounds traced program size.
EVAL_SEGMENT_LIMIT = 64


def make_dp_tp_mesh(dp: int, tp: int, devices=None) -> Mesh:
    """2-D ``(workers, model)`` mesh over dp·tp devices."""
    devices = list(devices if devices is not None else jax.devices())
    if dp * tp > len(devices):
        raise ValueError(
            f"dp*tp = {dp * tp} exceeds the {len(devices)} visible devices"
        )
    grid = np.asarray(devices[: dp * tp]).reshape(dp, tp)
    return Mesh(grid, (WORKER_AXIS, MODEL_AXIS))


def build_tp_softmax_dsgd(
    config,
    dataset,
    mesh: Mesh,
    *,
    collect_metrics: bool = True,
):
    """Build the jitted TP program and its sharded inputs.

    Returns ``(jitted_fn, args)`` with ``jitted_fn(*args) -> (W_final
    [N, d, K] sharded, per-cadence gaps [n_evals])`` — exposed separately
    from :func:`run_tp_softmax_dsgd` so tests can assert on the compiled
    HLO.
    """
    from distributed_optimization_tpu.utils.data import stack_shards

    if config.algorithm != "dsgd" or config.topology != "ring":
        raise ValueError("the TP demo path implements dsgd on a ring")
    if config.problem_type != "softmax":
        raise ValueError("tensor parallelism shards the softmax [d, K] tier")
    n, K, T = config.n_workers, config.n_classes, config.n_iterations
    dp, tp = mesh.devices.shape
    if n % dp != 0:
        raise ValueError(f"n_workers {n} must divide over dp={dp}")
    if K % tp != 0:
        raise ValueError(f"n_classes {K} must divide over tp={tp}")
    if n < 3:
        raise ValueError("ring gossip needs n_workers >= 3")
    max_shard = max(len(idx) for idx in dataset.shard_indices)
    if config.local_batch_size < max_shard:
        raise ValueError(
            f"the TP path runs FULL local batches (the compute tier's "
            f"measured configuration); local_batch_size="
            f"{config.local_batch_size} < shard size {max_shard} would "
            "silently train a different trajectory than the DP backend — "
            "set local_batch_size >= the shard size"
        )
    eval_every = config.eval_every
    n_evals = T // eval_every
    if collect_metrics and n_evals > EVAL_SEGMENT_LIMIT:
        raise ValueError(
            f"{n_evals} eval segments exceed EVAL_SEGMENT_LIMIT="
            f"{EVAL_SEGMENT_LIMIT} (each is a Python-unrolled scan in the "
            "traced program); coarsen eval_every or pass "
            "collect_metrics=False"
        )

    device_data = stack_shards(dataset, dtype=np.dtype(config.dtype))
    d = device_data.n_features
    reg = config.reg_param
    eta0 = config.learning_rate_eta0
    sqrt_decay = config.resolved_lr_schedule() == "sqrt_decay"
    total_rows = float(np.sum(device_data.n_valid))

    # Placement: X/y/n_valid worker-sharded, replicated over 'model';
    # W worker-sharded rows × class-sharded columns — no full [d, K] on
    # any device.
    def put(a, spec):
        return jax.device_put(jnp.asarray(a), NamedSharding(mesh, spec))

    X = put(device_data.X, P(WORKER_AXIS, None, None))
    y = put(device_data.y.astype(np.int32), P(WORKER_AXIS, None))
    n_valid = put(device_data.n_valid, P(WORKER_AXIS))
    W0 = put(
        np.zeros((n, d, K), dtype=device_data.X.dtype),
        P(WORKER_AXIS, None, MODEL_AXIS),
    )

    # The boundary-exchange ring stencil is the SAME operator the explicit
    # DP collectives use — _ring_block_mix works on axis 0 of any block
    # shape, so the [nw, d, Kp] TP slice reuses it unchanged.
    from distributed_optimization_tpu.parallel.collectives import (
        _ring_block_mix,
    )

    ring_mix, _ = _ring_block_mix(WORKER_AXIS, dp, 1.0 / 3.0)

    def block_body(Wb, Xb, yb, nvb):
        """Per-device block program. Shapes (local): Wb [nw, d, Kp],
        Xb [nw, L, d], yb [nw, L], nvb [nw]."""
        nw, L = Xb.shape[0], Xb.shape[1]
        Kp = Wb.shape[-1]
        k_off = jax.lax.axis_index(MODEL_AXIS) * Kp
        mask = (
            jnp.arange(L)[None, :] < nvb[:, None]
        ).astype(Xb.dtype)  # [nw, L]
        wts = mask / jnp.maximum(nvb[:, None].astype(Xb.dtype), 1.0)

        def logits_of(Wcur):
            return jnp.einsum("nld,ndk->nlk", Xb, Wcur)

        def softmax_parts(logits):
            """Globally-normalized P from K-sharded logits: the ONLY
            cross-model-shard traffic — [nw, L] scalars, K-independent."""
            m = jax.lax.pmax(
                jnp.max(logits, axis=-1), axis_name=MODEL_AXIS
            )  # [nw, L]
            e = jnp.exp(logits - m[..., None])
            se = jax.lax.psum(
                jnp.sum(e, axis=-1), axis_name=MODEL_AXIS
            )  # [nw, L]
            return e / se[..., None], m, se

        def grad(Wcur):
            logits = logits_of(Wcur)
            Pl, _, _ = softmax_parts(logits)
            ks = k_off + jnp.arange(Kp)
            Y = (yb[..., None] == ks[None, None, :]).astype(Xb.dtype)
            coef = wts[..., None] * (Pl - Y)  # masked mean weights
            return jnp.einsum("nld,nlk->ndk", Xb, coef) + reg * Wcur

        def eval_gap(Wcur):
            """Full-dataset objective of the worker-mean model."""
            xbar = (
                jax.lax.psum(jnp.sum(Wcur, axis=0), axis_name=WORKER_AXIS)
                / n
            )  # [d, Kp], same on every worker shard
            logits = jnp.einsum("nld,dk->nlk", Xb, xbar)
            _, m, se = softmax_parts(logits)
            true_local = jnp.where(
                (yb >= k_off) & (yb < k_off + Kp),
                jnp.take_along_axis(
                    logits, jnp.clip(yb - k_off, 0, Kp - 1)[..., None],
                    axis=-1,
                )[..., 0],
                0.0,
            )
            true = jax.lax.psum(true_local, axis_name=MODEL_AXIS)
            ce = (m + jnp.log(se)) - true
            data_term = (
                jax.lax.psum(
                    jnp.sum(mask * ce), axis_name=WORKER_AXIS
                )
                / total_rows
            )
            sq = jax.lax.psum(
                jax.lax.psum(jnp.sum(xbar * xbar), axis_name=MODEL_AXIS),
                axis_name=WORKER_AXIS,
            ) / dp  # xbar replicated over workers: divide the worker psum
            return data_term + 0.5 * reg * sq

        def step(Wcur, t):
            # t is an int32 scan index; the schedule is computed in the
            # carry dtype so f64 runs match the replicated backend's
            # eta0/sqrt(t+1) bit for bit (an f32 arange here drifted ~4e-8
            # relative per step against the f64 oracles — round-5 ADVICE).
            eta = (
                eta0 / jnp.sqrt((t + 1.0).astype(Wcur.dtype)) if sqrt_decay
                else jnp.asarray(eta0, dtype=Wcur.dtype)
            ).astype(Wcur.dtype)
            g = grad(Wcur)
            # D-PSGD: grads at the pre-mix models; boundary ppermutes
            # carry [1, d, Kp] rows — d·K/tp floats per device, 1/tp of
            # the DP-only payload (ring gossip on the LOCAL class slice).
            return ring_mix(Wcur) - eta * g, None

        # Exact-cadence metrics (the backend's "hoisted" structure): a
        # Python-unrolled sequence of eval-free scans with the
        # full-dataset eval computed BETWEEN them, so a run pays exactly
        # n_evals evaluations instead of one per step. Metrics off: one
        # flat scan, no segments.
        if not collect_metrics:
            Wcur, _ = jax.lax.scan(
                step, Wb, jnp.arange(T, dtype=jnp.int32)
            )
            return Wcur, jnp.zeros(n_evals, dtype=Wb.dtype)
        ts = jnp.arange(T, dtype=jnp.int32).reshape(n_evals, eval_every)
        outs = []
        Wcur = Wb
        for e in range(n_evals):
            Wcur, _ = jax.lax.scan(step, Wcur, ts[e])
            outs.append(eval_gap(Wcur))
        return Wcur, jnp.stack(outs)

    sharded = jax.jit(
        shard_map(
            block_body,
            mesh=mesh,
            in_specs=(
                P(WORKER_AXIS, None, MODEL_AXIS),
                P(WORKER_AXIS, None, None),
                P(WORKER_AXIS, None),
                P(WORKER_AXIS),
            ),
            out_specs=(P(WORKER_AXIS, None, MODEL_AXIS), P()),
            check_vma=False,
        )
    )
    return sharded, (W0, X, y, n_valid)


def run_tp_backend(
    config,
    dataset,
    f_opt: float,
    *,
    collect_metrics: bool = True,
    measure_compile: bool = True,
    **unsupported,
):
    """Config-driven entry for ``tp_degree > 1`` (``backends.run_algorithm``
    routes here): build the DP × TP mesh from the visible devices, run the
    sharded program, and report the same ``BackendRunResult`` every other
    backend returns — so the simulator, CLI, report, and JSON layers need
    no TP-specific code.

    Mesh shape: ``tp = config.tp_degree`` model shards; the workers axis
    takes the largest device count that divides ``n_workers`` within the
    remaining budget (1 is always valid — TP with a single worker-shard
    row is still class-sharded). Compile and run are AOT-split like the
    DP backend, so iters/sec is steady-state.
    """
    import time

    from distributed_optimization_tpu.backends.base import BackendRunResult
    from distributed_optimization_tpu.metrics import (
        RunHistory,
        decentralized_floats_per_iteration,
    )
    from distributed_optimization_tpu.parallel.topology import build_topology

    if unsupported:
        raise ValueError(
            f"tensor-parallel runs do not support {sorted(unsupported)}: "
            "the TP path has no checkpointing, measured-timestamp, or "
            "batch-schedule machinery — run those on the data-parallel "
            "backend (tp_degree=1)"
        )
    tp = config.tp_degree
    devices = jax.devices()
    if tp > len(devices):
        raise ValueError(
            f"tp_degree={tp} exceeds the {len(devices)} visible devices"
        )
    dp = len(devices) // tp
    while dp > 1 and config.n_workers % dp != 0:
        dp -= 1
    mesh = make_dp_tp_mesh(dp, tp)

    from distributed_optimization_tpu.backends.base import x64_scope

    T = config.n_iterations
    n_evals = T // config.eval_every
    with x64_scope(config):
        sharded, args = build_tp_softmax_dsgd(
            config, dataset, mesh, collect_metrics=collect_metrics
        )
        t0 = time.perf_counter()
        with jax.default_matmul_precision(config.matmul_precision):
            compiled = sharded.lower(*args).compile()
        compile_seconds = (
            time.perf_counter() - t0 if measure_compile else 0.0
        )
        t1 = time.perf_counter()
        W_final, gaps = compiled(*args)
        W_final = jax.block_until_ready(W_final)
        run_seconds = time.perf_counter() - t1

    n, K = config.n_workers, config.n_classes
    d = W_final.shape[1]
    final_models = np.asarray(
        jax.device_get(W_final), dtype=np.float64
    ).reshape(n, d * K)
    objective = (
        np.asarray(gaps, dtype=np.float64) - f_opt
        if collect_metrics else np.full(n_evals, np.nan)
    )
    # Comms accounting stays at the MODEL level (comparable with the DP
    # rows): Σ deg·d·K floats per iteration — TP shards each exchange to
    # d·K/tp per device, but the full model still crosses the ring.
    topo = build_topology("ring", n)
    history = RunHistory(
        objective=objective,
        consensus_error=None,
        time=np.linspace(
            run_seconds / max(n_evals, 1), run_seconds, n_evals
        ),
        time_measured=False,
        eval_iterations=np.arange(
            config.eval_every, T + 1, config.eval_every
        ),
        total_floats_transmitted=decentralized_floats_per_iteration(
            topo, d * K
        ) * T,
        iters_per_second=T / run_seconds if run_seconds > 0 else float("nan"),
        compile_seconds=compile_seconds,
        spectral_gap=topo.spectral_gap,
    )
    return BackendRunResult(
        history=history,
        final_models=final_models,
        final_avg_model=final_models.mean(axis=0),
    )


def run_tp_softmax_dsgd(
    config,
    dataset,
    mesh: Mesh,
    *,
    f_opt: float = 0.0,
    collect_metrics: bool = True,
):
    """Run D-SGD + softmax + ring on a 2-D (workers, model) mesh.

    Full local batches (b = shard size), sqrt-decay or constant eta per
    the config. Returns ``(final_models [N, d·K] numpy float64, gaps
    [n_evals] numpy)`` — the same quantities/layout the backends report,
    so the oracles compare directly.
    """
    with x64_scope(config):
        sharded, args = build_tp_softmax_dsgd(
            config, dataset, mesh, collect_metrics=collect_metrics
        )
        with jax.default_matmul_precision(config.matmul_precision):
            W_final, gaps = sharded(*args)
    n, K = config.n_workers, config.n_classes
    d = W_final.shape[1]
    W_np = np.asarray(jax.device_get(W_final), dtype=np.float64)
    if not collect_metrics:
        # No evals ran: an empty history, not placeholder zeros that would
        # read as (negative) gaps after the f_opt shift.
        return W_np.reshape(n, d * K), np.empty(0, dtype=np.float64)
    gaps_np = np.asarray(gaps, dtype=np.float64) - f_opt
    return W_np.reshape(n, d * K), gaps_np
