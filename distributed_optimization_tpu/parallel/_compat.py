"""JAX version compatibility shims.

The codebase is written against the modern spellings ``jax.shard_map`` and
``jax.enable_x64``; on JAX 0.4.x those live under ``jax.experimental`` (and
``shard_map`` takes ``check_rep`` where the modern API takes ``check_vma``).
This module is the single resolution point — every module and test that
needs either symbol imports it from here instead of probing ``jax``
directly, so a future JAX upgrade deletes this file and nothing else.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map_04

    def shard_map(f, *args, **kwargs):
        # 0.4.x spells the replication-check kwarg ``check_rep``.
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_04(f, *args, **kwargs)


if hasattr(jax, "enable_x64"):
    enable_x64 = jax.enable_x64
else:
    from jax.experimental import enable_x64  # noqa: F401
