"""Parallelism layer: topologies, device meshes, and collective mixing."""

from distributed_optimization_tpu.parallel.topology import Topology, build_topology  # noqa: F401
