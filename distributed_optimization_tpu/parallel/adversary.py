"""Byzantine adversary injection: workers that send WRONG models.

The fault layer (``parallel/faults.py``) covers benign failures — links
and workers that go silent. This module covers the adversarial dimension
the reference's report only alludes to (its parameter-server single point
of failure): a static, seed-deterministic set of Byzantine workers that
participates in every round but replaces its OUTGOING model with an
attack payload. Three canonical payloads (Blanchard et al. 2017; Baruch
et al. 2019; He-Karimireddy-Jaggi 2022):

- **sign_flip**: send −scale·x_i — pulls every neighbor away from descent
  along the attacker's own trajectory;
- **large_noise**: send x_i + scale·N(0, I), redrawn per (seed, t) — a
  variance attack that stalls consensus without an obvious direction;
- **alie** ("a little is enough"): the colluders compute the honest
  workers' per-coordinate mean and standard deviation (omniscient
  collusion — the strongest static threat model) and ALL send
  mean − scale·std, an outlier small enough to hide inside the honest
  spread and evade norm screens.

Payloads are pure functions of (seed, iteration, transmitted stack) —
like fault masks and batch sampling there is no carried RNG state, so
attack realizations are reproducible and checkpoint/resume-safe. Within
one iteration the corruption is applied per gossip round (gradient
tracking corrupts both its x and y exchanges); ``large_noise`` reuses the
(seed, t) draw across same-iteration rounds, which keeps resume exactness
without per-call counters. All adversarial math runs in at-least-float32
(the faults convention); only the corrupted stack is cast back to the run
dtype.

The Byzantine SET is sampled host-side from the config seed
(``byzantine_mask``) and shared verbatim by the jax backend, the numpy
oracle backend, and the honest-only metrics — all three must agree on who
is lying.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_optimization_tpu.config import ATTACKS

# Stream tags folded into the seed key, disjoint from the fault layer's
# (0x0FA17 edges, 0x57A66 stragglers, 0x3A7C4 matchings).
_BYZ_SET_TAG = 0xB12A
_BYZ_NOISE_TAG = 0xBAD0


def byzantine_mask(n_workers: int, n_byzantine: int, seed: int) -> np.ndarray:
    """Static Byzantine node set as a host [N] bool mask.

    Seed-deterministic (a fresh Generator keyed on (seed, tag)), so every
    layer that needs the honest/Byzantine split — backends, metrics,
    benches — reconstructs the identical set from the config alone.
    """
    if not 0 <= n_byzantine < n_workers:
        raise ValueError(
            f"n_byzantine must be in [0, n_workers), got {n_byzantine} "
            f"of {n_workers}"
        )
    mask = np.zeros(n_workers, dtype=bool)
    if n_byzantine > 0:
        rng = np.random.default_rng([seed, _BYZ_SET_TAG])
        mask[rng.choice(n_workers, size=n_byzantine, replace=False)] = True
    return mask


@dataclasses.dataclass(frozen=True)
class Adversary:
    """One attack bound to its static Byzantine set.

    ``corrupt(t, x)``: replace Byzantine rows of the [N, d] stack with the
    iteration-t payload (honest rows pass through untouched — a Byzantine
    worker lies to its neighbors; it cannot touch anyone else's state).
    """

    attack: str
    n_byzantine: int
    byzantine: np.ndarray  # host [N] bool, static for the whole run
    corrupt: Callable[[jax.Array, jax.Array], jax.Array]

    @property
    def honest(self) -> np.ndarray:
        return ~self.byzantine


def make_adversary(
    n_workers: int,
    attack: str,
    n_byzantine: int,
    attack_scale: float,
    seed: int,
    *,
    byz=None,
    noise_key=None,
) -> Optional[Adversary]:
    """Build the jit-compatible adversary for a config (None when benign).

    ``byz``/``noise_key`` override the seed-derived Byzantine set and
    large-noise stream — the replica-batched path
    (``jax_backend.run_batch``) derives both per replica host-side (the
    identical ``byzantine_mask``/fold-in formulas) and threads them
    through ``vmap``, so they may be tracers here.
    """
    if attack not in ATTACKS:
        raise ValueError(f"Unknown attack: {attack}")
    if attack == "none":
        return None
    if byz is None:
        byz = byzantine_mask(n_workers, n_byzantine, seed)
    byz_dev = jnp.asarray(byz, dtype=jnp.float32)
    if noise_key is None:
        noise_key = jax.random.fold_in(jax.random.key(seed), _BYZ_NOISE_TAG)

    def corrupt(t, x):
        acc = jnp.promote_types(jnp.float32, x.dtype)
        xa = x.astype(acc)
        m = byz_dev.astype(acc).reshape((-1,) + (1,) * (x.ndim - 1))
        if attack == "sign_flip":
            payload = -attack_scale * xa
        elif attack == "large_noise":
            key = jax.random.fold_in(noise_key, t)
            payload = xa + attack_scale * jax.random.normal(
                key, x.shape, dtype=acc
            )
        else:  # alie: colluders share honest_mean − scale·honest_std
            h = (1.0 - byz_dev).astype(acc)
            n_honest = jnp.sum(h)
            mu = jnp.sum(xa * h[:, None], axis=0) / n_honest
            var = (
                jnp.sum(h[:, None] * (xa - mu[None, :]) ** 2, axis=0)
                / n_honest
            )
            payload = jnp.broadcast_to(
                mu - attack_scale * jnp.sqrt(var), xa.shape
            )
        return jnp.where(m > 0, payload, xa).astype(x.dtype)

    return Adversary(
        attack=attack, n_byzantine=n_byzantine, byzantine=byz, corrupt=corrupt
    )


def make_byzantine_mixing(
    adversary: Optional[Adversary],
    base_mix: Callable[[jax.Array, jax.Array], jax.Array],
    *,
    aggregate_t=None,
) -> Callable[[jax.Array, jax.Array], jax.Array]:
    """Compose corruption and (robust) aggregation into one mix(t, x).

    ``base_mix(t, x)``: the benign time-varying gossip (static MixingOp or
    FaultyMixing) — used when no robust rule is active, i.e. the
    VULNERABLE baseline the breakdown benches measure. With
    ``aggregate_t(t, x)`` (an ``ops.robust_aggregation`` rule bound by the
    backend to its per-iteration graph source — the dense realized
    adjacency or the gather-form neighbor liveness, per ``robust_impl``)
    the mix instead screens the corrupted stack, so attacks, edge faults,
    and the defense all see the same per-iteration realization.
    ``adversary=None`` gives the pure-defense path (robust rule, no
    attackers).

    Byzantine ROWS keep the benign mix of the TRUE stack: the literature's
    threat model is an attacker that runs honest dynamics internally (so
    its transmitted lie — e.g. a flipped model — tracks a plausible
    trajectory) and lies only on the wire. Feeding attackers their own
    corrupted echo instead makes their state diverge exponentially under
    self-centered rules, overflowing to inf and poisoning the honest rows
    through NaN payloads — a simulation artifact, not an attack.
    """
    corrupt = (
        adversary.corrupt if adversary is not None else (lambda t, x: x)
    )

    def honest_view(t, x):
        xa = corrupt(t, x)
        if aggregate_t is not None:
            return aggregate_t(t, xa)
        return base_mix(t, xa)

    if adversary is None:
        return honest_view

    byz_col = jnp.asarray(adversary.byzantine, dtype=jnp.float32)

    def mix(t, x):
        m = byz_col.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
        return jnp.where(m > 0, base_mix(t, x), honest_view(t, x))

    return mix
