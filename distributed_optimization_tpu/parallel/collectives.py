"""Explicit-collective mixing operators: shard_map + ppermute/psum over ICI.

This is the north-star communication backend (SURVEY.md §5.8, C12): each
device holds a contiguous block of workers, and one gossip round exchanges
only the block-boundary rows with the neighboring devices via
``jax.lax.ppermute`` (ring/torus) or reduces with ``jax.lax.psum`` (fully
connected / centralized). This replaces the reference's simulated dense
``W @ models`` matmul (reference ``trainer.py:173``) with the real collective
traffic pattern: a ring of N workers on D devices moves exactly 2·d floats
per device per round over ICI, independent of N — enforced against the
compiled HLO (instruction kinds and payload element counts) by
``tests/test_collectives.py::test_ring_lowers_to_boundary_permutes_with_2d_floats``
and companions, for both this module's explicit ops and the GSPMD stencils.

The GSPMD stencils in ``ops/mixing.py`` compile to the same collectives
automatically; this module is the manually scheduled form — used when
``mixing_impl='shard_map'`` — and doubles as executable documentation of the
communication pattern. Property tests check both against the dense matrix.

Intra-block neighbor averaging is pure local compute; only the first/last
rows of each block cross device boundaries. Worker blocks are contiguous
(worker i lives at block row i % (N/D) on device i // (N/D)), matching the
``NamedSharding`` layout that ``mesh.shard_over_workers`` produces.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributed_optimization_tpu.ops.mixing import MixingOp
from distributed_optimization_tpu.parallel._compat import shard_map
from distributed_optimization_tpu.parallel.mesh import WORKER_AXIS
from distributed_optimization_tpu.parallel.topology import Topology


def _ring_block_mix(axis: str, n_devices: int, w: float):
    """Per-block ring stencil: local shifts + edge-row ppermutes."""
    fwd = [(i, (i + 1) % n_devices) for i in range(n_devices)]
    bwd = [(i, (i - 1) % n_devices) for i in range(n_devices)]

    def exchange(block):  # block: [per, d] on each device
        # Row arriving from the previous device (their last worker) and the
        # next device (their first worker).
        from_prev = jax.lax.ppermute(block[-1:], axis, fwd)
        from_next = jax.lax.ppermute(block[:1], axis, bwd)
        left = jnp.concatenate([from_prev, block[:-1]], axis=0)  # x_{i-1}
        right = jnp.concatenate([block[1:], from_next], axis=0)  # x_{i+1}
        return left, right

    def mix(block):
        left, right = exchange(block)
        return (w * (block + left + right)).astype(block.dtype)

    def nbr(block):
        left, right = exchange(block)
        return (left + right).astype(block.dtype)

    return mix, nbr


def _directed_ring_block_mix(axis: str, n_devices: int):
    """Per-block directed-ring stencil: ONE forward ppermute per round.

    The directed ring receives only from the predecessor, so each device
    ships exactly its last worker row forward — d floats per device per
    round, HALF the undirected ring's boundary traffic (asserted against
    compiled HLO by tests/test_push_sum.py)."""
    fwd = [(i, (i + 1) % n_devices) for i in range(n_devices)]

    def exchange(block):  # block: [per, d] on each device
        from_prev = jax.lax.ppermute(block[-1:], axis, fwd)
        return jnp.concatenate([from_prev, block[:-1]], axis=0)  # x_{i-1}

    def mix(block):
        return (0.5 * (block + exchange(block))).astype(block.dtype)

    def nbr(block):
        return exchange(block).astype(block.dtype)

    return mix, nbr


def _fc_block_ops(axis: str, n_total: int):
    def mix(block):
        total = jax.lax.psum(jnp.sum(block, axis=0, keepdims=True), axis)
        return jnp.broadcast_to(total / n_total, block.shape).astype(block.dtype)

    def nbr(block):
        total = jax.lax.psum(jnp.sum(block, axis=0, keepdims=True), axis)
        return (total - block).astype(block.dtype)

    return mix, nbr


def _grid_block_ops(axis: str, n_devices: int, rows: int, cols: int, w: float):
    """Torus stencil with the row axis blocked over devices.

    Each device holds rows_per_dev full grid rows ([rows_per_dev, cols, d]);
    column rolls are local, row rolls exchange one boundary grid-row (cols·d
    floats) with each neighboring device.
    """
    fwd = [(i, (i + 1) % n_devices) for i in range(n_devices)]
    bwd = [(i, (i - 1) % n_devices) for i in range(n_devices)]

    def shifts(block):  # [r_loc, cols, d]
        from_prev = jax.lax.ppermute(block[-1:], axis, fwd)
        from_next = jax.lax.ppermute(block[:1], axis, bwd)
        up = jnp.concatenate([from_prev, block[:-1]], axis=0)
        down = jnp.concatenate([block[1:], from_next], axis=0)
        lateral = jnp.roll(block, 1, axis=1) + jnp.roll(block, -1, axis=1)
        return up + down + lateral

    def mix(block):
        return (w * (block + shifts(block))).astype(block.dtype)

    def nbr(block):
        return shifts(block).astype(block.dtype)

    return mix, nbr


def make_shard_map_mixing_op(topo: Topology, mesh: Mesh) -> MixingOp:
    """Build the explicit shard_map collective mixing op for a topology.

    Supports the mesh-embeddable graphs (ring, torus grid, fully connected).
    Irregular graphs (Erdős–Rényi, chain, star) use the dense form instead
    (SURVEY.md §7 hard part (c)).
    """
    axis = WORKER_AXIS
    n_devices = mesh.shape[axis]
    n = topo.n
    if n % n_devices != 0:
        raise ValueError(f"n_workers={n} not divisible by mesh size {n_devices}")

    if topo.name == "ring":
        if n < 3:
            raise ValueError("shard_map ring mixing needs n >= 3")
        mix_block, nbr_block = _ring_block_mix(axis, n_devices, 1.0 / 3.0)
        spec_in = P(axis, None)
    elif topo.name == "directed_ring":
        if n < 3:
            raise ValueError("shard_map directed_ring mixing needs n >= 3")
        mix_block, nbr_block = _directed_ring_block_mix(axis, n_devices)
        spec_in = P(axis, None)
    elif topo.name == "fully_connected":
        mix_block, nbr_block = _fc_block_ops(axis, n)
        spec_in = P(axis, None)
    elif topo.name == "grid":
        rows, cols = topo.grid_shape  # type: ignore[misc]
        if min(rows, cols) < 3:
            raise ValueError("shard_map grid mixing needs a >=3x3 torus")
        if rows % n_devices != 0:
            raise ValueError(
                f"grid rows={rows} not divisible by mesh size {n_devices}"
            )
        mix_block, nbr_block = _grid_block_ops(axis, n_devices, rows, cols, 1.0 / 5.0)
        spec_in = P(axis, None, None)
    else:
        raise ValueError(
            f"No shard_map stencil for topology {topo.name!r}; use dense mixing"
        )

    def _wrap(block_fn):
        if topo.name == "grid":
            rows, cols = topo.grid_shape  # type: ignore[misc]

            def fn(x):  # x: [N, d] -> grid layout -> stencil -> back
                g = x.reshape(rows, cols, x.shape[-1])
                out = shard_map(
                    block_fn, mesh=mesh, in_specs=spec_in, out_specs=spec_in
                )(g)
                return out.reshape(x.shape)

            return fn
        return shard_map(block_fn, mesh=mesh, in_specs=spec_in, out_specs=spec_in)

    return MixingOp(topo.name, "shard_map", _wrap(mix_block), _wrap(nbr_block))
