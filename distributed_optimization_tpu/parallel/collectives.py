"""Explicit-collective mixing operators: shard_map + ppermute/psum over ICI.

This is the north-star communication backend (SURVEY.md §5.8, C12): each
device holds a contiguous block of workers, and one gossip round exchanges
only the block-boundary rows with the neighboring devices via
``jax.lax.ppermute`` (ring/torus) or reduces with ``jax.lax.psum`` (fully
connected / centralized). This replaces the reference's simulated dense
``W @ models`` matmul (reference ``trainer.py:173``) with the real collective
traffic pattern: a ring of N workers on D devices moves exactly 2·d floats
per device per round over ICI, independent of N — enforced against the
compiled HLO (instruction kinds and payload element counts) by
``tests/test_collectives.py::test_ring_lowers_to_boundary_permutes_with_2d_floats``
and companions, for both this module's explicit ops and the GSPMD stencils.

The GSPMD stencils in ``ops/mixing.py`` compile to the same collectives
automatically; this module is the manually scheduled form — used when
``mixing_impl='shard_map'`` — and doubles as executable documentation of the
communication pattern. Property tests check both against the dense matrix.

Intra-block neighbor averaging is pure local compute; only the first/last
rows of each block cross device boundaries. Worker blocks are contiguous
(worker i lives at block row i % (N/D) on device i // (N/D)), matching the
``NamedSharding`` layout that ``mesh.shard_over_workers`` produces.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import dataclasses

import numpy as np

from distributed_optimization_tpu.ops.mixing import MixingOp
from distributed_optimization_tpu.parallel._compat import shard_map
from distributed_optimization_tpu.parallel.mesh import WORKER_AXIS
from distributed_optimization_tpu.parallel.topology import (
    Topology,
    build_halo_plan,
    gather_mixing_weights,
    neighbor_tables_for,
)


def _ring_block_mix(axis: str, n_devices: int, w: float):
    """Per-block ring stencil: local shifts + edge-row ppermutes."""
    fwd = [(i, (i + 1) % n_devices) for i in range(n_devices)]
    bwd = [(i, (i - 1) % n_devices) for i in range(n_devices)]

    def exchange(block):  # block: [per, d] on each device
        # Row arriving from the previous device (their last worker) and the
        # next device (their first worker).
        from_prev = jax.lax.ppermute(block[-1:], axis, fwd)
        from_next = jax.lax.ppermute(block[:1], axis, bwd)
        left = jnp.concatenate([from_prev, block[:-1]], axis=0)  # x_{i-1}
        right = jnp.concatenate([block[1:], from_next], axis=0)  # x_{i+1}
        return left, right

    def mix(block):
        left, right = exchange(block)
        return (w * (block + left + right)).astype(block.dtype)

    def nbr(block):
        left, right = exchange(block)
        return (left + right).astype(block.dtype)

    return mix, nbr


def _directed_ring_block_mix(axis: str, n_devices: int):
    """Per-block directed-ring stencil: ONE forward ppermute per round.

    The directed ring receives only from the predecessor, so each device
    ships exactly its last worker row forward — d floats per device per
    round, HALF the undirected ring's boundary traffic (asserted against
    compiled HLO by tests/test_push_sum.py)."""
    fwd = [(i, (i + 1) % n_devices) for i in range(n_devices)]

    def exchange(block):  # block: [per, d] on each device
        from_prev = jax.lax.ppermute(block[-1:], axis, fwd)
        return jnp.concatenate([from_prev, block[:-1]], axis=0)  # x_{i-1}

    def mix(block):
        return (0.5 * (block + exchange(block))).astype(block.dtype)

    def nbr(block):
        return exchange(block).astype(block.dtype)

    return mix, nbr


def _fc_block_ops(axis: str, n_total: int):
    def mix(block):
        total = jax.lax.psum(jnp.sum(block, axis=0, keepdims=True), axis)
        return jnp.broadcast_to(total / n_total, block.shape).astype(block.dtype)

    def nbr(block):
        total = jax.lax.psum(jnp.sum(block, axis=0, keepdims=True), axis)
        return (total - block).astype(block.dtype)

    return mix, nbr


def _grid_block_ops(axis: str, n_devices: int, rows: int, cols: int, w: float):
    """Torus stencil with the row axis blocked over devices.

    Each device holds rows_per_dev full grid rows ([rows_per_dev, cols, d]);
    column rolls are local, row rolls exchange one boundary grid-row (cols·d
    floats) with each neighboring device.
    """
    fwd = [(i, (i + 1) % n_devices) for i in range(n_devices)]
    bwd = [(i, (i - 1) % n_devices) for i in range(n_devices)]

    def shifts(block):  # [r_loc, cols, d]
        from_prev = jax.lax.ppermute(block[-1:], axis, fwd)
        from_next = jax.lax.ppermute(block[:1], axis, bwd)
        up = jnp.concatenate([from_prev, block[:-1]], axis=0)
        down = jnp.concatenate([block[1:], from_next], axis=0)
        lateral = jnp.roll(block, 1, axis=1) + jnp.roll(block, -1, axis=1)
        return up + down + lateral

    def mix(block):
        return (w * (block + shifts(block))).astype(block.dtype)

    def nbr(block):
        return shifts(block).astype(block.dtype)

    return mix, nbr


def make_shard_map_mixing_op(topo: Topology, mesh: Mesh) -> MixingOp:
    """Build the explicit shard_map collective mixing op for a topology.

    Supports the mesh-embeddable graphs (ring, torus grid, fully connected).
    Irregular graphs (Erdős–Rényi, chain, star) use the dense form instead
    (SURVEY.md §7 hard part (c)).
    """
    axis = WORKER_AXIS
    n_devices = mesh.shape[axis]
    n = topo.n
    if n % n_devices != 0:
        raise ValueError(f"n_workers={n} not divisible by mesh size {n_devices}")

    if topo.name == "ring":
        if n < 3:
            raise ValueError("shard_map ring mixing needs n >= 3")
        mix_block, nbr_block = _ring_block_mix(axis, n_devices, 1.0 / 3.0)
        spec_in = P(axis, None)
    elif topo.name == "directed_ring":
        if n < 3:
            raise ValueError("shard_map directed_ring mixing needs n >= 3")
        mix_block, nbr_block = _directed_ring_block_mix(axis, n_devices)
        spec_in = P(axis, None)
    elif topo.name == "fully_connected":
        mix_block, nbr_block = _fc_block_ops(axis, n)
        spec_in = P(axis, None)
    elif topo.name == "grid":
        rows, cols = topo.grid_shape  # type: ignore[misc]
        if min(rows, cols) < 3:
            raise ValueError("shard_map grid mixing needs a >=3x3 torus")
        if rows % n_devices != 0:
            raise ValueError(
                f"grid rows={rows} not divisible by mesh size {n_devices}"
            )
        mix_block, nbr_block = _grid_block_ops(axis, n_devices, rows, cols, 1.0 / 5.0)
        spec_in = P(axis, None, None)
    else:
        raise ValueError(
            f"No shard_map stencil for topology {topo.name!r}; use dense mixing"
        )

    def _wrap(block_fn):
        if topo.name == "grid":
            rows, cols = topo.grid_shape  # type: ignore[misc]

            def fn(x):  # x: [N, d] -> grid layout -> stencil -> back
                g = x.reshape(rows, cols, x.shape[-1])
                out = shard_map(
                    block_fn, mesh=mesh, in_specs=spec_in, out_specs=spec_in
                )(g)
                return out.reshape(x.shape)

            return fn
        return shard_map(block_fn, mesh=mesh, in_specs=spec_in, out_specs=spec_in)

    return MixingOp(topo.name, "shard_map", _wrap(mix_block), _wrap(nbr_block))


# ---------------------------------------------------------------------------
# Sharded worker mesh (ISSUE-11 tentpole; docs/PERF.md §16): the k_max-
# bounded gather path of docs/PERF.md §14 lowered to REAL collectives.
# Each device owns a contiguous block of N/P worker rows — state [S, d],
# neighbor-table block [S, k_max] remapped to shard-local coordinates —
# and one gossip round ppermute-fetches only the boundary rows the block's
# table references (the halo), then runs the ordinary gather math locally.
# Per-row arithmetic is the EXACT op sequence of the single-device gather
# operators (same slot order, same accumulation dtype), so sharded and
# unsharded trajectories are bitwise identical at matched N
# (tests/test_worker_mesh.py pins it); the only cross-device traffic is
# the halo rows — O(boundary · d) per device per round, independent of N
# for ring/torus/chain and O(E/P² · d) per rotation for Erdős–Rényi.
# Single-process multi-device (the closures capture sharded tables, which
# multi-process jax forbids); on CPU hosts simulate the mesh via
# XLA_FLAGS=--xla_force_host_platform_device_count=P.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class HaloExchange:
    """A ``HaloPlan`` bound to a device mesh, ready to run under shard_map.

    ``run(body, *arrays)`` shard_maps ``body`` over row-sharded ``arrays``
    ([N, ...] leaves, axis 0 split over the mesh). The body receives
    ``(exchange, nbr_l [S, k_max], mask [S, k_max], *blocks)`` where
    ``exchange(buf [S, w]) -> ext [S + h_max + 1, w]`` performs the
    planned ppermute rotations — ``ext[nbr_l]`` then gathers exactly the
    values ``x_global[nbr_idx]`` gathers on one device. The body must
    return one ``[S, ...]`` array (row-sharded output).
    """

    mesh: Mesh
    plan: object                 # topology.HaloPlan
    nbr_l: jax.Array             # [P, S, k_max] int32 (shard-local coords)
    mask: jax.Array              # [P, S, k_max] float32 static liveness
    sends: tuple                 # per step [P, s_max] int32
    recvs: tuple                 # per step [P, s_max] int32
    perms: tuple                 # per step static ((src, dst), ...) pairs

    @property
    def n_shards(self) -> int:
        return self.plan.n_shards

    def run(self, body, *arrays):
        P_ = jax.sharding.PartitionSpec
        n_steps = len(self.perms)
        h_max = self.plan.h_max
        perms = self.perms

        def shard_body(nbr_lb, maskb, *rest):
            sends = rest[:n_steps]
            recvs = rest[n_steps:2 * n_steps]
            blocks = rest[2 * n_steps:]

            def exchange(buf):
                # buf [S, w] -> ext [S + h_max + 1, w]; the trailing halo
                # slot is the dump row padded traffic lands in.
                halo = jnp.zeros((h_max + 1, buf.shape[-1]), buf.dtype)
                for perm, s_idx, r_pos in zip(perms, sends, recvs):
                    got = jax.lax.ppermute(
                        buf[s_idx[0]], WORKER_AXIS, perm
                    )
                    halo = halo.at[r_pos[0]].set(got)
                return jnp.concatenate([buf, halo], axis=0)

            return body(exchange, nbr_lb[0], maskb[0], *blocks)

        table_spec = P_(WORKER_AXIS, None, None)
        step_spec = P_(WORKER_AXIS, None)
        arr_specs = tuple(
            P_(WORKER_AXIS, *([None] * (a.ndim - 1))) for a in arrays
        )
        return shard_map(
            shard_body,
            mesh=self.mesh,
            in_specs=(table_spec, table_spec)
            + tuple(step_spec for _ in range(2 * n_steps))
            + arr_specs,
            out_specs=P_(WORKER_AXIS, None),
        )(self.nbr_l, self.mask, *self.sends, *self.recvs, *arrays)


def make_halo_exchange(topo: Topology, mesh: Mesh) -> HaloExchange:
    """Build the device-ready halo plan for a topology over a 1-D mesh."""
    n_devices = mesh.shape[WORKER_AXIS]
    nbr_idx, nbr_mask = neighbor_tables_for(topo)
    if topo.n % n_devices:
        raise ValueError(
            f"n_workers={topo.n} not divisible by mesh size {n_devices}"
        )
    plan = build_halo_plan(nbr_idx, nbr_mask, n_devices)
    S, k_max = plan.shard_rows, nbr_idx.shape[1]
    return HaloExchange(
        mesh=mesh,
        plan=plan,
        nbr_l=jnp.asarray(
            plan.local_nbr.reshape(n_devices, S, k_max), dtype=jnp.int32
        ),
        mask=jnp.asarray(
            nbr_mask.reshape(n_devices, S, k_max), dtype=jnp.float32
        ),
        sends=tuple(
            jnp.asarray(st.send_idx, dtype=jnp.int32) for st in plan.steps
        ),
        recvs=tuple(
            jnp.asarray(st.recv_pos, dtype=jnp.int32) for st in plan.steps
        ),
        perms=tuple(
            tuple((p, (p + st.rotation) % n_devices)
                  for p in range(n_devices))
            for st in plan.steps
        ),
    )


def make_halo_mixing_op(topo: Topology, mesh: Mesh, dtype=jnp.float32) -> MixingOp:
    """Sharded twin of ``ops/mixing.py`` impl='gather' over real collectives.

    MH weights are the identical per-slot values ``gather_mixing_weights``
    derives (sharded per block); the apply/neighbor_sum bodies run the
    identical per-row op sequence as the single-device gather operator on
    the halo-extended buffer, so the two forms are BITWISE equal — with
    boundary rows arriving over ICI as ppermute traffic instead of being
    addressed in one device's HBM (the compiled-HLO payload test in
    tests/test_worker_mesh.py pins ring rounds to 2·d floats per device).
    """
    if topo.directed:
        raise ValueError(
            "halo gather mixing is undirected-only (MH weights per slot); "
            f"directed topology {topo.name!r} has no gather form"
        )
    hx = make_halo_exchange(topo, mesh)
    nbr_idx, nbr_mask = neighbor_tables_for(topo)
    w_nbr_np, w_self_np = gather_mixing_weights(
        nbr_idx, nbr_mask, topo.degrees
    )
    # Row-major [N, k_max] / [N] tables ride ``HaloExchange.run`` as
    # ordinary row-sharded arrays (each body sees its [S, ...] block) —
    # no second copy of the shard_map/exchange plumbing to keep in sync.
    w_nbr = jnp.asarray(w_nbr_np, dtype=dtype)
    w_self = jnp.asarray(w_self_np, dtype=dtype)
    mask_d = jnp.asarray(nbr_mask, dtype=dtype)

    def apply(x: jax.Array) -> jax.Array:
        def body(exchange, nbr_l, _mask_f32, wn, ws, xb):
            gathered = exchange(xb)[nbr_l]  # [S, k_max, d]
            out = ws[:, None] * xb + jnp.sum(
                wn[:, :, None] * gathered, axis=1
            )
            return out.astype(xb.dtype)

        x2 = x.reshape(x.shape[0], -1)
        return hx.run(body, w_nbr, w_self, x2).reshape(x.shape)

    def neighbor_sum(x: jax.Array) -> jax.Array:
        def body(exchange, nbr_l, _mask_f32, mb, xb):
            out = jnp.sum(mb[:, :, None] * exchange(xb)[nbr_l], axis=1)
            return out.astype(xb.dtype)

        x2 = x.reshape(x.shape[0], -1)
        return hx.run(body, mask_d, x2).reshape(x.shape)

    return MixingOp(topo.name, "halo_gather", apply, neighbor_sum)


def make_halo_robust_aggregator_t(
    name: str,
    budget: int,
    topo: Topology,
    mesh: Mesh,
    clip_tau: float = 0.0,
    active_fn=None,
):
    """Sharded robust screening: ``aggregate_t(t, x) -> x_new`` over the halo.

    The degree-bounded gather rules of ``ops/robust_aggregation.py``
    (coordinate-wise trimmed mean / median, self-centered clipping) run
    shard-locally on the halo-extended buffer: corrupted boundary rows
    arrive over ppermute exactly like benign gossip traffic, each shard
    screens its own [S, k_max+1, d] closed neighborhoods, and the per-row
    op sequence mirrors the unsharded gather twin term for term (same
    +inf padding, same accumulation floor, same identity-row
    degeneration) — sharded-vs-unsharded screening is BITWISE identical.
    ``active_fn(t) -> [N] float32`` composes node-process faults
    (stragglers/churn/participation) into the realized liveness through a
    1-float-per-row halo exchange; None = the static graph. The caller
    (``jax_backend._bind_byzantine``) applies the adversary's corruption
    BEFORE this aggregate, like every other robust binding.
    """
    from distributed_optimization_tpu.config import AGGREGATIONS

    if name not in AGGREGATIONS or name == "gossip":
        raise ValueError(
            f"no robust aggregator named {name!r}; plain gossip is the "
            "halo mixing op itself"
        )
    if budget < 1:
        raise ValueError(f"{name} needs a positive attack budget, got {budget}")
    hx = make_halo_exchange(topo, mesh)
    nbr_idx, _ = neighbor_tables_for(topo)
    k_max = nbr_idx.shape[1]
    n = topo.n
    adaptive_tau = isinstance(clip_tau, (int, float)) and clip_tau <= 0.0

    def _live(exchange, nbr_l, mask_f32, mb):
        m_ext = exchange(mb[:, None])[:, 0]
        return mask_f32 * mb[:, None] * m_ext[nbr_l]  # [S, k_max] f32

    def _closed_sorted(exchange, nbr_l, mask_f32, xb, mb):
        """Shard-local twin of the gather rules' closed-neighborhood sort
        (ops/robust_aggregation.py): same +inf padding on dead slots,
        same self-row prepend, same sort axis — the exact terms the
        BITWISE sharded-vs-unsharded parity contract depends on, kept in
        one place for both count rules below."""
        acc = jnp.promote_types(jnp.float32, xb.dtype)
        xa = xb.astype(acc)
        lv = _live(exchange, nbr_l, mask_f32, mb).astype(acc)
        ext = exchange(xa)
        vals = jnp.where(lv[:, :, None] > 0, ext[nbr_l], jnp.inf)
        closed = jnp.concatenate([xa[:, None, :], vals], axis=1)
        s = jnp.sort(closed, axis=1)
        counts = jnp.sum(lv, axis=1) + 1.0
        return acc, xa, s, counts

    if name == "trimmed_mean":

        def body(exchange, nbr_l, mask_f32, xb, mb):
            acc, xa, s, counts = _closed_sorted(
                exchange, nbr_l, mask_f32, xb, mb
            )
            pos = jnp.arange(k_max + 1, dtype=acc)
            keep = (pos[None, :] >= budget) & (
                pos[None, :] < (counts - budget)[:, None]
            )
            kept = jnp.maximum(counts - 2 * budget, 0.0)
            total = jnp.sum(jnp.where(keep[:, :, None], s, 0.0), axis=1)
            mean = total / jnp.maximum(kept, 1.0)[:, None]
            return jnp.where(
                (kept >= 1.0)[:, None], mean, xa
            ).astype(xb.dtype)

    elif name == "median":

        def body(exchange, nbr_l, mask_f32, xb, mb):
            _, _, s, counts = _closed_sorted(
                exchange, nbr_l, mask_f32, xb, mb
            )
            c = counts.astype(jnp.int32)
            lo = jnp.maximum((c - 1) // 2, 0)[:, None, None]
            hi = jnp.maximum(c // 2, 0)[:, None, None]
            med = 0.5 * (
                jnp.take_along_axis(s, lo, axis=1)
                + jnp.take_along_axis(s, hi, axis=1)
            )
            return med[:, 0, :].astype(xb.dtype)

    else:  # clipped_gossip

        def body(exchange, nbr_l, mask_f32, xb, mb):
            from distributed_optimization_tpu.ops.robust_aggregation import (
                _adaptive_clip_tau,
            )

            acc = jnp.promote_types(jnp.float32, xb.dtype)
            xa = xb.astype(acc)
            lv = _live(exchange, nbr_l, mask_f32, mb).astype(acc)
            deg = jnp.sum(lv, axis=1)
            d2 = xa.shape[-1]
            ext = exchange(jnp.concatenate([xa, deg[:, None]], axis=1))
            gathered = ext[nbr_l]
            diffs = gathered[:, :, :d2] - xa[:, None, :]
            norms = jnp.sqrt(jnp.sum(diffs * diffs, axis=-1))
            if not adaptive_tau:
                tau = jnp.full(xb.shape[0], clip_tau, dtype=acc)
            else:
                tau = _adaptive_clip_tau(lv, norms, budget, k_max)
            w = lv / (1.0 + jnp.maximum(deg[:, None], gathered[:, :, d2]))
            factor = jnp.minimum(
                1.0, tau[:, None] / jnp.maximum(norms, jnp.finfo(acc).tiny)
            )
            moved = jnp.sum(
                w[:, :, None] * diffs * factor[:, :, None], axis=1
            )
            return (xa + moved).astype(xb.dtype)

    def aggregate_t(t, x):
        m = (
            active_fn(t) if active_fn is not None
            else jnp.ones(n, dtype=jnp.float32)
        )
        return hx.run(body, x, m)

    return aggregate_t
