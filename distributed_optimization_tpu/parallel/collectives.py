"""Explicit-collective mixing operators: shard_map + ppermute/psum over ICI.

This is the north-star communication backend (SURVEY.md §5.8, C12): each
device holds a contiguous block of workers, and one gossip round exchanges
only the block-boundary rows with the neighboring devices via
``jax.lax.ppermute`` (ring/torus) or reduces with ``jax.lax.psum`` (fully
connected / centralized). This replaces the reference's simulated dense
``W @ models`` matmul (reference ``trainer.py:173``) with the real collective
traffic pattern: a ring of N workers on D devices moves exactly 2·d floats
per device per round over ICI, independent of N — enforced against the
compiled HLO (instruction kinds and payload element counts) by
``tests/test_collectives.py::test_ring_lowers_to_boundary_permutes_with_2d_floats``
and companions, for both this module's explicit ops and the GSPMD stencils.

The GSPMD stencils in ``ops/mixing.py`` compile to the same collectives
automatically; this module is the manually scheduled form — used when
``mixing_impl='shard_map'`` — and doubles as executable documentation of the
communication pattern. Property tests check both against the dense matrix.

Intra-block neighbor averaging is pure local compute; only the first/last
rows of each block cross device boundaries. Worker blocks are contiguous
(worker i lives at block row i % (N/D) on device i // (N/D)), matching the
``NamedSharding`` layout that ``mesh.shard_over_workers`` produces.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import dataclasses

import numpy as np

from distributed_optimization_tpu.ops.mixing import MixingOp
from distributed_optimization_tpu.parallel._compat import shard_map
from distributed_optimization_tpu.parallel.mesh import WORKER_AXIS
from distributed_optimization_tpu.parallel.topology import (
    Topology,
    build_halo_plan,
    gather_mixing_weights,
    neighbor_tables_for,
)


def _ring_block_mix(axis: str, n_devices: int, w: float):
    """Per-block ring stencil: local shifts + edge-row ppermutes."""
    fwd = [(i, (i + 1) % n_devices) for i in range(n_devices)]
    bwd = [(i, (i - 1) % n_devices) for i in range(n_devices)]

    def exchange(block):  # block: [per, d] on each device
        # Row arriving from the previous device (their last worker) and the
        # next device (their first worker).
        from_prev = jax.lax.ppermute(block[-1:], axis, fwd)
        from_next = jax.lax.ppermute(block[:1], axis, bwd)
        left = jnp.concatenate([from_prev, block[:-1]], axis=0)  # x_{i-1}
        right = jnp.concatenate([block[1:], from_next], axis=0)  # x_{i+1}
        return left, right

    def mix(block):
        left, right = exchange(block)
        return (w * (block + left + right)).astype(block.dtype)

    def nbr(block):
        left, right = exchange(block)
        return (left + right).astype(block.dtype)

    return mix, nbr


def _directed_ring_block_mix(axis: str, n_devices: int):
    """Per-block directed-ring stencil: ONE forward ppermute per round.

    The directed ring receives only from the predecessor, so each device
    ships exactly its last worker row forward — d floats per device per
    round, HALF the undirected ring's boundary traffic (asserted against
    compiled HLO by tests/test_push_sum.py)."""
    fwd = [(i, (i + 1) % n_devices) for i in range(n_devices)]

    def exchange(block):  # block: [per, d] on each device
        from_prev = jax.lax.ppermute(block[-1:], axis, fwd)
        return jnp.concatenate([from_prev, block[:-1]], axis=0)  # x_{i-1}

    def mix(block):
        return (0.5 * (block + exchange(block))).astype(block.dtype)

    def nbr(block):
        return exchange(block).astype(block.dtype)

    return mix, nbr


def _fc_block_ops(axis: str, n_total: int):
    def mix(block):
        total = jax.lax.psum(jnp.sum(block, axis=0, keepdims=True), axis)
        return jnp.broadcast_to(total / n_total, block.shape).astype(block.dtype)

    def nbr(block):
        total = jax.lax.psum(jnp.sum(block, axis=0, keepdims=True), axis)
        return (total - block).astype(block.dtype)

    return mix, nbr


def _grid_block_ops(axis: str, n_devices: int, rows: int, cols: int, w: float):
    """Torus stencil with the row axis blocked over devices.

    Each device holds rows_per_dev full grid rows ([rows_per_dev, cols, d]);
    column rolls are local, row rolls exchange one boundary grid-row (cols·d
    floats) with each neighboring device.
    """
    fwd = [(i, (i + 1) % n_devices) for i in range(n_devices)]
    bwd = [(i, (i - 1) % n_devices) for i in range(n_devices)]

    def shifts(block):  # [r_loc, cols, d]
        from_prev = jax.lax.ppermute(block[-1:], axis, fwd)
        from_next = jax.lax.ppermute(block[:1], axis, bwd)
        up = jnp.concatenate([from_prev, block[:-1]], axis=0)
        down = jnp.concatenate([block[1:], from_next], axis=0)
        lateral = jnp.roll(block, 1, axis=1) + jnp.roll(block, -1, axis=1)
        return up + down + lateral

    def mix(block):
        return (w * (block + shifts(block))).astype(block.dtype)

    def nbr(block):
        return shifts(block).astype(block.dtype)

    return mix, nbr


def make_shard_map_mixing_op(topo: Topology, mesh: Mesh) -> MixingOp:
    """Build the explicit shard_map collective mixing op for a topology.

    Supports the mesh-embeddable graphs (ring, torus grid, fully connected).
    Irregular graphs (Erdős–Rényi, chain, star) use the dense form instead
    (SURVEY.md §7 hard part (c)).
    """
    axis = WORKER_AXIS
    n_devices = mesh.shape[axis]
    n = topo.n
    if n % n_devices != 0:
        raise ValueError(f"n_workers={n} not divisible by mesh size {n_devices}")

    if topo.name == "ring":
        if n < 3:
            raise ValueError("shard_map ring mixing needs n >= 3")
        mix_block, nbr_block = _ring_block_mix(axis, n_devices, 1.0 / 3.0)
        spec_in = P(axis, None)
    elif topo.name == "directed_ring":
        if n < 3:
            raise ValueError("shard_map directed_ring mixing needs n >= 3")
        mix_block, nbr_block = _directed_ring_block_mix(axis, n_devices)
        spec_in = P(axis, None)
    elif topo.name == "fully_connected":
        mix_block, nbr_block = _fc_block_ops(axis, n)
        spec_in = P(axis, None)
    elif topo.name == "grid":
        rows, cols = topo.grid_shape  # type: ignore[misc]
        if min(rows, cols) < 3:
            raise ValueError("shard_map grid mixing needs a >=3x3 torus")
        if rows % n_devices != 0:
            raise ValueError(
                f"grid rows={rows} not divisible by mesh size {n_devices}"
            )
        mix_block, nbr_block = _grid_block_ops(axis, n_devices, rows, cols, 1.0 / 5.0)
        spec_in = P(axis, None, None)
    else:
        raise ValueError(
            f"No shard_map stencil for topology {topo.name!r}; use dense mixing"
        )

    def _wrap(block_fn):
        if topo.name == "grid":
            rows, cols = topo.grid_shape  # type: ignore[misc]

            def fn(x):  # x: [N, d] -> grid layout -> stencil -> back
                g = x.reshape(rows, cols, x.shape[-1])
                out = shard_map(
                    block_fn, mesh=mesh, in_specs=spec_in, out_specs=spec_in
                )(g)
                return out.reshape(x.shape)

            return fn
        return shard_map(block_fn, mesh=mesh, in_specs=spec_in, out_specs=spec_in)

    return MixingOp(topo.name, "shard_map", _wrap(mix_block), _wrap(nbr_block))


# ---------------------------------------------------------------------------
# Sharded worker mesh (ISSUE-11 tentpole; docs/PERF.md §16): the k_max-
# bounded gather path of docs/PERF.md §14 lowered to REAL collectives.
# Each device owns a contiguous block of N/P worker rows — state [S, d],
# neighbor-table block [S, k_max] remapped to shard-local coordinates —
# and one gossip round ppermute-fetches only the boundary rows the block's
# table references (the halo), then runs the ordinary gather math locally.
# Per-row arithmetic is the EXACT op sequence of the single-device gather
# operators (same slot order, same accumulation dtype), so sharded and
# unsharded trajectories are bitwise identical at matched N
# (tests/test_worker_mesh.py pins it); the only cross-device traffic is
# the halo rows — O(boundary · d) per device per round, independent of N
# for ring/torus/chain and O(E/P² · d) per rotation for Erdős–Rényi.
# Single-process multi-device (the closures capture sharded tables, which
# multi-process jax forbids); on CPU hosts simulate the mesh via
# XLA_FLAGS=--xla_force_host_platform_device_count=P.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class HaloExchange:
    """A ``HaloPlan`` bound to a device mesh, ready to run under shard_map.

    ``run(body, *arrays)`` shard_maps ``body`` over row-sharded ``arrays``
    ([N, ...] leaves, axis 0 split over the mesh). The body receives
    ``(exchange, nbr_l [S, k_max], mask [S, k_max], *blocks)`` where
    ``exchange(buf [S, w]) -> ext [S + h_max + 1, w]`` performs the
    planned ppermute rotations — ``ext[nbr_l]`` then gathers exactly the
    values ``x_global[nbr_idx]`` gathers on one device. The body must
    return one ``[S, ...]`` array (row-sharded output).
    """

    mesh: Mesh
    plan: object                 # topology.HaloPlan
    nbr_l: jax.Array             # [P, S, k_max] int32 (shard-local coords)
    mask: jax.Array              # [P, S, k_max] float32 static liveness
    sends: tuple                 # per step [P, s_max] int32
    recvs: tuple                 # per step [P, s_max] int32
    perms: tuple                 # per step static ((src, dst), ...) pairs

    @property
    def n_shards(self) -> int:
        return self.plan.n_shards

    def run(self, body, *arrays):
        P_ = jax.sharding.PartitionSpec
        n_steps = len(self.perms)
        h_max = self.plan.h_max
        perms = self.perms

        def shard_body(nbr_lb, maskb, *rest):
            sends = rest[:n_steps]
            recvs = rest[n_steps:2 * n_steps]
            blocks = rest[2 * n_steps:]

            def exchange(buf):
                # buf [S, w] -> ext [S + h_max + 1, w]; the trailing halo
                # slot is the dump row padded traffic lands in.
                halo = jnp.zeros((h_max + 1, buf.shape[-1]), buf.dtype)
                for perm, s_idx, r_pos in zip(perms, sends, recvs):
                    got = jax.lax.ppermute(
                        buf[s_idx[0]], WORKER_AXIS, perm
                    )
                    halo = halo.at[r_pos[0]].set(got)
                return jnp.concatenate([buf, halo], axis=0)

            return body(exchange, nbr_lb[0], maskb[0], *blocks)

        table_spec = P_(WORKER_AXIS, None, None)
        step_spec = P_(WORKER_AXIS, None)
        arr_specs = tuple(
            P_(WORKER_AXIS, *([None] * (a.ndim - 1))) for a in arrays
        )
        return shard_map(
            shard_body,
            mesh=self.mesh,
            in_specs=(table_spec, table_spec)
            + tuple(step_spec for _ in range(2 * n_steps))
            + arr_specs,
            out_specs=P_(WORKER_AXIS, None),
        )(self.nbr_l, self.mask, *self.sends, *self.recvs, *arrays)


def make_halo_exchange(
    topo: Topology, mesh: Mesh, *, overlap: str = "off"
) -> HaloExchange:
    """Build the device-ready halo plan for a topology over a 1-D mesh.

    ``overlap`` names the exchange form the plan serves (it is part of
    the plan's memoization identity — see ``build_halo_plan``); the
    device arrays are identical across modes today.
    """
    n_devices = mesh.shape[WORKER_AXIS]
    nbr_idx, nbr_mask = neighbor_tables_for(topo)
    if topo.n % n_devices:
        raise ValueError(
            f"n_workers={topo.n} not divisible by mesh size {n_devices}"
        )
    plan = build_halo_plan(
        nbr_idx, nbr_mask, n_devices, sampler=topo.sampler, overlap=overlap
    )
    S, k_max = plan.shard_rows, nbr_idx.shape[1]
    return HaloExchange(
        mesh=mesh,
        plan=plan,
        nbr_l=jnp.asarray(
            plan.local_nbr.reshape(n_devices, S, k_max), dtype=jnp.int32
        ),
        mask=jnp.asarray(
            nbr_mask.reshape(n_devices, S, k_max), dtype=jnp.float32
        ),
        sends=tuple(
            jnp.asarray(st.send_idx, dtype=jnp.int32) for st in plan.steps
        ),
        recvs=tuple(
            jnp.asarray(st.recv_pos, dtype=jnp.int32) for st in plan.steps
        ),
        perms=tuple(
            tuple((p, (p + st.rotation) % n_devices)
                  for p in range(n_devices))
            for st in plan.steps
        ),
    )


def make_halo_mixing_op(
    topo: Topology, mesh: Mesh, dtype=jnp.float32, *, overlap: str = "off"
) -> MixingOp:
    """Sharded twin of ``ops/mixing.py`` impl='gather' over real collectives.

    MH weights are the identical per-slot values ``gather_mixing_weights``
    derives (sharded per block); the apply/neighbor_sum bodies run the
    identical per-row op sequence as the single-device gather operator on
    the halo-extended buffer, so the two forms are BITWISE equal — with
    boundary rows arriving over ICI as ppermute traffic instead of being
    addressed in one device's HBM (the compiled-HLO payload test in
    tests/test_worker_mesh.py pins ring rounds to 2·d floats per device).

    ``overlap='double_buffer'`` (config.halo_overlap; docs/PERF.md §17)
    restructures ``apply`` into the stencil latency-hiding form: the
    boundary-row ppermutes are issued FIRST, the self + in-block partial
    sum computes while they are in flight (XLA schedules collectives
    concurrently with independent compute on async backends), and the
    halo contributions are added last. The summation ORDER differs from
    the gather body (in-block slots before halo slots instead of slot
    order), so double_buffer is a distinct structural program — NOT
    bitwise vs off; 'off' is byte-for-byte the PR 11 body, which is the
    gate tests/test_worker_mesh.py pins.
    """
    if topo.directed:
        raise ValueError(
            "halo gather mixing is undirected-only (MH weights per slot); "
            f"directed topology {topo.name!r} has no gather form"
        )
    if overlap not in ("off", "double_buffer"):
        raise ValueError(f"Unknown halo overlap mode: {overlap!r}")
    hx = make_halo_exchange(topo, mesh, overlap=overlap)
    nbr_idx, nbr_mask = neighbor_tables_for(topo)
    w_nbr_np, w_self_np = gather_mixing_weights(
        nbr_idx, nbr_mask, topo.degrees
    )
    # Row-major [N, k_max] / [N] tables ride ``HaloExchange.run`` as
    # ordinary row-sharded arrays (each body sees its [S, ...] block) —
    # no second copy of the shard_map/exchange plumbing to keep in sync.
    w_nbr = jnp.asarray(w_nbr_np, dtype=dtype)
    w_self = jnp.asarray(w_self_np, dtype=dtype)
    mask_d = jnp.asarray(nbr_mask, dtype=dtype)

    def apply(x: jax.Array) -> jax.Array:
        def body(exchange, nbr_l, _mask_f32, wn, ws, xb):
            gathered = exchange(xb)[nbr_l]  # [S, k_max, d]
            out = ws[:, None] * xb + jnp.sum(
                wn[:, :, None] * gathered, axis=1
            )
            return out.astype(xb.dtype)

        x2 = x.reshape(x.shape[0], -1)
        return hx.run(body, w_nbr, w_self, x2).reshape(x.shape)

    def apply_overlap(x: jax.Array) -> jax.Array:
        S = hx.plan.shard_rows
        h_max = hx.plan.h_max
        n_steps = len(hx.perms)
        perms = hx.perms
        P_ = jax.sharding.PartitionSpec

        def shard_body(nbr_lb, wn, ws, xb, *steps):
            sends = steps[:n_steps]
            recvs = steps[n_steps:]
            nbr_l = nbr_lb[0]
            # Issue every boundary-row send before touching the local
            # math: the downstream partial sum has no data dependence on
            # the permutes, so an async backend's scheduler runs the
            # collectives concurrently with it (CPU single-stream ties).
            got = [
                jax.lax.ppermute(xb[s[0]], WORKER_AXIS, perm)
                for perm, s in zip(perms, sends)
            ]
            in_block = nbr_l < S
            wl = jnp.where(in_block, wn, jnp.zeros((), wn.dtype))
            local = xb[jnp.where(in_block, nbr_l, 0)]
            partial = ws[:, None] * xb + jnp.sum(
                wl[:, :, None] * local, axis=1
            )
            halo = jnp.zeros((h_max + 1, xb.shape[-1]), xb.dtype)
            for g, r in zip(got, recvs):
                halo = halo.at[r[0]].set(g)
            wh = jnp.where(in_block, jnp.zeros((), wn.dtype), wn)
            hrows = halo[jnp.where(in_block, 0, nbr_l - S)]
            out = partial + jnp.sum(wh[:, :, None] * hrows, axis=1)
            return out.astype(xb.dtype)

        x2 = x.reshape(x.shape[0], -1)
        table_spec = P_(WORKER_AXIS, None, None)
        step_spec = P_(WORKER_AXIS, None)
        out = shard_map(
            shard_body,
            mesh=mesh,
            in_specs=(table_spec, step_spec, P_(WORKER_AXIS),
                      step_spec)
            + tuple(step_spec for _ in range(2 * n_steps)),
            out_specs=P_(WORKER_AXIS, None),
        )(hx.nbr_l, w_nbr, w_self, x2, *hx.sends, *hx.recvs)
        return out.reshape(x.shape)

    def neighbor_sum(x: jax.Array) -> jax.Array:
        def body(exchange, nbr_l, _mask_f32, mb, xb):
            out = jnp.sum(mb[:, :, None] * exchange(xb)[nbr_l], axis=1)
            return out.astype(xb.dtype)

        x2 = x.reshape(x.shape[0], -1)
        return hx.run(body, mask_d, x2).reshape(x.shape)

    return MixingOp(
        topo.name,
        "halo_gather",
        apply_overlap if overlap == "double_buffer" else apply,
        neighbor_sum,
    )


def make_halo_compressed_mixing_op(topo: Topology, mesh: Mesh, dtype=jnp.float32):
    """Compressed halo exchange: ship only the CHOCO increment's boundary rows.

    Returns ``compressed_mix(q, xhat_new, halo) -> (mixed, halo_new)`` for
    ``ops/compression.py::ErrorFeedbackGossip.exchange_sharded``: ``q`` is
    the compressed increment (row-sharded [N, d]), ``xhat_new = x̂ + q`` the
    already-updated local estimate, and ``halo`` the persistent receiver-side
    copy of the NEIGHBORS' estimate rows ([P·(h_max+1), d] row-sharded —
    h_max+1 rows per shard, the trailing one the dump row padded traffic
    lands in). One round ppermutes only the boundary rows of ``q`` and
    scatter-ADDS them into ``halo`` — the receiver replays the owner's
    ``x̂ ← x̂ + q`` update on its copy, the wire form Koloskova et al. '19
    rely on — then gathers the MH mix from the [block | halo] extension.

    Starting from the all-zeros halo the backend seeds, the receiver copy
    equals the owner row by induction (identical float adds on identical
    values), so ``mixed`` is bitwise the gather-form mix of the exact
    owner estimates. End-to-end sharded-vs-unsharded trajectories are
    BITWISE equal for the deterministic compressors (top_k — pinned by
    tests/test_worker_mesh.py); qsgd's stochastic rounding thresholds sit
    on a row-norm reduction XLA may fuse differently across the two
    compiled programs, so its parity gate is ~1e-12, not bitwise (the
    same caveat every cross-program reduction in this repo carries). The
    dump row is re-zeroed every round so padded-slot traffic (whose
    scatter-add order XLA does not define when several padded sends land
    together) can never leak into state.

    Wire accounting: physically each ppermute still ships dense-width rows
    (the analytic convention every comms number in this repo uses);
    ``telemetry.ici_summary`` prices the rows at the compressor's
    ``floats_per_edge`` — that is the committed byte cut in
    docs/perf/mesh_scale.json.
    """
    if topo.directed:
        raise ValueError(
            "compressed halo mixing is undirected-only (MH weights per "
            f"slot); directed topology {topo.name!r} has no gather form"
        )
    hx = make_halo_exchange(topo, mesh)
    nbr_idx, nbr_mask = neighbor_tables_for(topo)
    w_nbr_np, w_self_np = gather_mixing_weights(
        nbr_idx, nbr_mask, topo.degrees
    )
    w_nbr = jnp.asarray(w_nbr_np, dtype=dtype)
    w_self = jnp.asarray(w_self_np, dtype=dtype)
    S = hx.plan.shard_rows
    h_max = hx.plan.h_max
    n_steps = len(hx.perms)
    perms = hx.perms
    halo_rows = mesh.shape[WORKER_AXIS] * (h_max + 1)

    def compressed_mix(q: jax.Array, xhat_new: jax.Array, halo: jax.Array):
        P_ = jax.sharding.PartitionSpec

        def shard_body(nbr_lb, wn, ws, qb, xb, hb, *steps):
            sends = steps[:n_steps]
            recvs = steps[n_steps:]
            nbr_l = nbr_lb[0]
            hnew = hb
            for perm, s, r in zip(perms, sends, recvs):
                got = jax.lax.ppermute(qb[s[0]], WORKER_AXIS, perm)
                hnew = hnew.at[r[0]].add(got)
            # Padded steps all target the dump row; several adds landing
            # there have no defined order — zero it so nothing leaks.
            hnew = hnew.at[h_max].set(jnp.zeros((), hnew.dtype))
            ext = jnp.concatenate([xb, hnew], axis=0)
            out = ws[:, None] * xb + jnp.sum(
                wn[:, :, None] * ext[nbr_l], axis=1
            )
            return out.astype(xb.dtype), hnew

        q2 = q.reshape(q.shape[0], -1)
        x2 = xhat_new.reshape(xhat_new.shape[0], -1)
        h2 = halo.reshape(halo_rows, -1)
        table_spec = P_(WORKER_AXIS, None, None)
        step_spec = P_(WORKER_AXIS, None)
        mixed, halo_new = shard_map(
            shard_body,
            mesh=mesh,
            in_specs=(table_spec, step_spec, P_(WORKER_AXIS),
                      step_spec, step_spec, step_spec)
            + tuple(step_spec for _ in range(2 * n_steps)),
            out_specs=(P_(WORKER_AXIS, None), P_(WORKER_AXIS, None)),
        )(hx.nbr_l, w_nbr, w_self, q2, x2, h2, *hx.sends, *hx.recvs)
        return mixed.reshape(xhat_new.shape), halo_new.reshape(halo.shape)

    compressed_mix.halo_rows = halo_rows
    return compressed_mix


def make_halo_robust_aggregator_t(
    name: str,
    budget: int,
    topo: Topology,
    mesh: Mesh,
    clip_tau: float = 0.0,
    active_fn=None,
):
    """Sharded robust screening: ``aggregate_t(t, x) -> x_new`` over the halo.

    The degree-bounded gather rules of ``ops/robust_aggregation.py``
    (coordinate-wise trimmed mean / median, self-centered clipping) run
    shard-locally on the halo-extended buffer: corrupted boundary rows
    arrive over ppermute exactly like benign gossip traffic, each shard
    screens its own [S, k_max+1, d] closed neighborhoods, and the per-row
    op sequence mirrors the unsharded gather twin term for term (same
    +inf padding, same accumulation floor, same identity-row
    degeneration) — sharded-vs-unsharded screening is BITWISE identical.
    ``active_fn(t) -> [N] float32`` composes node-process faults
    (stragglers/churn/participation) into the realized liveness through a
    1-float-per-row halo exchange; None = the static graph. The caller
    (``jax_backend._bind_byzantine``) applies the adversary's corruption
    BEFORE this aggregate, like every other robust binding.
    """
    from distributed_optimization_tpu.config import AGGREGATIONS

    if name not in AGGREGATIONS or name == "gossip":
        raise ValueError(
            f"no robust aggregator named {name!r}; plain gossip is the "
            "halo mixing op itself"
        )
    if budget < 1:
        raise ValueError(f"{name} needs a positive attack budget, got {budget}")
    hx = make_halo_exchange(topo, mesh)
    nbr_idx, _ = neighbor_tables_for(topo)
    k_max = nbr_idx.shape[1]
    n = topo.n
    adaptive_tau = isinstance(clip_tau, (int, float)) and clip_tau <= 0.0

    def _live(exchange, nbr_l, mask_f32, mb):
        m_ext = exchange(mb[:, None])[:, 0]
        return mask_f32 * mb[:, None] * m_ext[nbr_l]  # [S, k_max] f32

    def _closed_sorted(exchange, nbr_l, mask_f32, xb, mb):
        """Shard-local twin of the gather rules' closed-neighborhood sort
        (ops/robust_aggregation.py): same +inf padding on dead slots,
        same self-row prepend, same sort axis — the exact terms the
        BITWISE sharded-vs-unsharded parity contract depends on, kept in
        one place for both count rules below."""
        acc = jnp.promote_types(jnp.float32, xb.dtype)
        xa = xb.astype(acc)
        lv = _live(exchange, nbr_l, mask_f32, mb).astype(acc)
        ext = exchange(xa)
        vals = jnp.where(lv[:, :, None] > 0, ext[nbr_l], jnp.inf)
        closed = jnp.concatenate([xa[:, None, :], vals], axis=1)
        s = jnp.sort(closed, axis=1)
        counts = jnp.sum(lv, axis=1) + 1.0
        return acc, xa, s, counts

    if name == "trimmed_mean":

        def body(exchange, nbr_l, mask_f32, xb, mb):
            acc, xa, s, counts = _closed_sorted(
                exchange, nbr_l, mask_f32, xb, mb
            )
            pos = jnp.arange(k_max + 1, dtype=acc)
            keep = (pos[None, :] >= budget) & (
                pos[None, :] < (counts - budget)[:, None]
            )
            kept = jnp.maximum(counts - 2 * budget, 0.0)
            total = jnp.sum(jnp.where(keep[:, :, None], s, 0.0), axis=1)
            mean = total / jnp.maximum(kept, 1.0)[:, None]
            return jnp.where(
                (kept >= 1.0)[:, None], mean, xa
            ).astype(xb.dtype)

    elif name == "median":

        def body(exchange, nbr_l, mask_f32, xb, mb):
            _, _, s, counts = _closed_sorted(
                exchange, nbr_l, mask_f32, xb, mb
            )
            c = counts.astype(jnp.int32)
            lo = jnp.maximum((c - 1) // 2, 0)[:, None, None]
            hi = jnp.maximum(c // 2, 0)[:, None, None]
            med = 0.5 * (
                jnp.take_along_axis(s, lo, axis=1)
                + jnp.take_along_axis(s, hi, axis=1)
            )
            return med[:, 0, :].astype(xb.dtype)

    else:  # clipped_gossip

        def body(exchange, nbr_l, mask_f32, xb, mb):
            from distributed_optimization_tpu.ops.robust_aggregation import (
                _adaptive_clip_tau,
            )

            acc = jnp.promote_types(jnp.float32, xb.dtype)
            xa = xb.astype(acc)
            lv = _live(exchange, nbr_l, mask_f32, mb).astype(acc)
            deg = jnp.sum(lv, axis=1)
            d2 = xa.shape[-1]
            ext = exchange(jnp.concatenate([xa, deg[:, None]], axis=1))
            gathered = ext[nbr_l]
            diffs = gathered[:, :, :d2] - xa[:, None, :]
            norms = jnp.sqrt(jnp.sum(diffs * diffs, axis=-1))
            if not adaptive_tau:
                tau = jnp.full(xb.shape[0], clip_tau, dtype=acc)
            else:
                tau = _adaptive_clip_tau(lv, norms, budget, k_max)
            w = lv / (1.0 + jnp.maximum(deg[:, None], gathered[:, :, d2]))
            factor = jnp.minimum(
                1.0, tau[:, None] / jnp.maximum(norms, jnp.finfo(acc).tiny)
            )
            moved = jnp.sum(
                w[:, :, None] * diffs * factor[:, :, None], axis=1
            )
            return (xa + moved).astype(xb.dtype)

    def aggregate_t(t, x):
        m = (
            active_fn(t) if active_fn is not None
            else jnp.ones(n, dtype=jnp.float32)
        )
        return hx.run(body, x, m)

    return aggregate_t
