"""Communication-graph topologies and Metropolis-Hastings mixing matrices.

Capability parity with the reference's topology + mixing-matrix builder
(reference ``trainer.py:91-136``): ring, periodic 2-D grid (torus), and
fully-connected graphs with Metropolis-Hastings gossip weights
``W_ij = 1/(1 + max(deg_i, deg_j))`` and self-weight = row remainder, plus the
same invariants (row-stochastic, symmetric) and the spectral gap ``1 - ρ``
from the second-largest absolute eigenvalue.

Extensions beyond the reference: Erdős–Rényi random graphs (the BASELINE.json
decentralized-ADMM config), chain (path), and star topologies; and a
*stencil* description (shift offsets + weights) for the topologies whose
mixing step maps onto TPU ICI as `ppermute` neighbor shifts instead of a dense
``W @ models`` matmul — ring/chain/torus are the cases where the communication
graph embeds directly into the pod mesh.

Round 4 adds DIRECTED graphs (``directed_ring``, ``directed_erdos_renyi``)
with column-stochastic uniform-out-weight mixing — the push-sum/SGP setting
(Nedić-Olshevsky 2016; Assran et al. 2019), where Metropolis-Hastings gossip
is undefined because asymmetric links admit no symmetric doubly stochastic
weight assignment. Convention: ``adjacency[i, j] = 1`` iff j sends to i
(row i = who i RECEIVES from), so ``mixing_matrix @ x`` aggregates received
messages for both directed and undirected graphs. The directed ring is the
ICI-friendly case: one gossip round is a single forward ``ppermute`` — half
the undirected ring's boundary traffic.

This module is host-side (numpy): topologies are built once per run, outside
``jit``. The compiled mixing operators that consume them live in
``ops/mixing.py`` and ``parallel/collectives.py``.

Round 8 adds the MATRIX-FREE representation (``build_topology(...,
impl='neighbor')``): ring/torus/chain/Erdős–Rényi built directly as a
static padded ``[N, k_max]`` neighbor table — the dense ``[N, N]``
adjacency and mixing matrix are never materialized (``adjacency`` /
``mixing_matrix`` are None; at N = 10k the dense float64 pair alone is
~1.6 GB, the cap docs/perf/sparse_mixing.json ran into around N≈4k).
Everything downstream that needs the graph reads the table: gather-form
MH mixing (``gather_mixing_weights`` + ``ops/mixing.py`` impl='gather',
O(N·k_max·d) per round), node-process fault composition
(``parallel/faults.py``), and the spectral gap via closed forms or
matrix-free power iteration. The ER constructor consumes the numpy
Generator stream row-by-row in exactly the order the dense sampler's one
``random((n, n))`` call does, so both representations of G(n, p, seed)
realize the IDENTICAL graph.

The million-worker round adds the SPARSE sampler
(``build_neighbor_topology(..., sampler='sparse')``): the bit-identical
ER constructor above replays the dense [N, N] uniform stream and is
therefore O(N²) draws — the recorded reason ER-at-100k was skipped in
docs/perf/worker_mesh.json. The sparse sampler draws O(N·k_max):
per-node forward-degree Binomial(n−1−i, p) counts, tail-sampled
partners, global dedupe + bounded top-up, and vectorized min-label
connectivity — the SAME G(n, p) law, a DIFFERENT realization per
(seed, p), so the sampler's identity is structural
(``config.structural_dict()['topology_sampler']``). Ring/torus/chain
tables are built by vectorized twins of the per-row list builders
(bitwise-identical tables, pinned by tests) so a 1M-node mesh builds
without any per-row Python loop or dense object.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import math
from typing import Optional

import numpy as np

# Mirrors config.NEIGHBOR_TOPOLOGIES / config.MATRIX_FREE_AUTO_N (config
# stays import-light; the single source of the AUTO policy is config.py —
# this module only needs to know which names have a constructor).
MATRIX_FREE_TOPOLOGIES = ("ring", "grid", "chain", "erdos_renyi")

# Power-iteration budget for the matrix-free spectral-gap estimate: the
# norm ratio converges to ρ geometrically in the (|λ3|/|λ2|) ratio, and
# 500 applications at O(N·k_max) each is still ~10^7 flops at N = 10k —
# cheaper than one dense [N, N] eigendecomposition at N = 1k.
_POWER_ITERS = 500


@dataclasses.dataclass(frozen=True)
class Topology:
    """A communication graph plus its gossip structure.

    Undirected graphs (``directed=False``) carry a Metropolis-Hastings
    mixing matrix (row-stochastic, symmetric — hence doubly stochastic);
    directed graphs carry a column-stochastic uniform-out-weight matrix
    (each node splits its mass equally over its out-neighbors and itself),
    the push-sum setting. ``adjacency[i, j] = 1`` iff j sends to i.

    MATRIX-FREE topologies (``impl='neighbor'``) set ``adjacency`` and
    ``mixing_matrix`` to None and carry the padded neighbor table instead:
    ``nbr_idx [N, k_max]`` int32 (row i = i's neighbors ascending, padded
    slots pointing at i) and ``nbr_mask [N, k_max]`` bool — exactly the
    layout ``neighbor_table`` derives from a dense adjacency, so dense and
    matrix-free builds of the same graph produce bit-identical tables.
    """

    name: str
    n: int
    # [N, N] 0/1, zero diagonal; row i = i's in-edges. None when the
    # topology is matrix-free (neighbor-table-native).
    adjacency: Optional[np.ndarray]
    # Out-degrees (== in-degrees for undirected graphs): how many neighbors
    # each node TRANSMITS to per gossip round — the comms-accounting side.
    degrees: np.ndarray  # [N]
    # [N, N]; MH (undirected) or column-stochastic. None when matrix-free.
    mixing_matrix: Optional[np.ndarray]
    grid_shape: Optional[tuple[int, int]] = None  # set for 'grid'
    directed: bool = False
    # Matrix-free neighbor table (None on the dense representation).
    nbr_idx: Optional[np.ndarray] = None   # [N, k_max] int32
    nbr_mask: Optional[np.ndarray] = None  # [N, k_max] bool
    # Which random-graph sampler realized the table: 'dense' (the
    # [N, N]-stream-replaying bitwise reference) or 'sparse' (the
    # O(N·k_max)-draw constructor). Always 'dense' for deterministic
    # topologies — the value is part of the graph's structural identity
    # and keys the halo-plan cache (``build_halo_plan``).
    sampler: str = "dense"

    @property
    def is_matrix_free(self) -> bool:
        return self.adjacency is None

    @property
    def spectral_gap(self) -> float:
        """1 - ρ where ρ is the second-largest |eigenvalue| of W.

        Parity: reference trainer.py:133-135. Closed-form values for the
        report setup: ring(25) ≈ 0.0209, 5x5 torus ≈ 0.2764, fc = 1.0.
        Directed mixing matrices are non-normal with a possibly complex
        spectrum; ρ is the second-largest eigenvalue MODULUS (the
        ergodicity coefficient of the column-stochastic chain — self-loops
        make it primitive, so ρ < 1 for strongly connected graphs).

        Matrix-free topologies never materialize W: ring and torus use
        their closed forms (exact — uniform MH weights by symmetry);
        chain/ER estimate ρ by power iteration on the mean-deflated
        gather-form operator v ↦ W v − v̄ (O(N·k_max) per application,
        deterministic start vector), accurate to the iteration budget's
        geometric tail — a diagnostic, like the dense eigensolve.
        """
        if self.n < 2:
            return 1.0
        if self.is_matrix_free:
            if self.name == "ring" and self.n >= 3:
                return ring_spectral_gap_closed_form(self.n)
            if (
                self.name == "grid"
                and self.grid_shape is not None
                and self.grid_shape[0] == self.grid_shape[1]
                and min(self.grid_shape) >= 3
            ):
                return torus_spectral_gap_closed_form(self.grid_shape[0])
            return self._power_iteration_gap()
        if self.directed:
            eigs = np.sort(np.abs(np.linalg.eigvals(self.mixing_matrix)))
        else:
            eigs = np.sort(np.abs(np.linalg.eigvalsh(self.mixing_matrix)))
        return float(1.0 - eigs[-2])

    def _power_iteration_gap(self) -> float:
        """ρ ≈ lim ‖B^k v‖ / ‖B^{k−1} v‖ for B = W − (1/n)𝟙𝟙ᵀ (symmetric,
        so the normalized-iterate norm converges to the largest
        |eigenvalue| of the deflated operator — i.e. ρ — even under
        eigenvalue multiplicity, the ring's generic case)."""
        w_nbr, w_self = gather_mixing_weights(
            self.nbr_idx, self.nbr_mask, self.degrees
        )
        v = np.random.default_rng(0).standard_normal(self.n)
        v -= v.mean()
        v /= np.linalg.norm(v)
        rho = 0.0
        for _ in range(_POWER_ITERS):
            v = w_self * v + np.sum(w_nbr * v[self.nbr_idx], axis=1)
            v -= v.mean()
            rho = np.linalg.norm(v)
            if rho < 1e-300:  # degenerate: W is exact averaging
                return 1.0
            v /= rho
        return float(1.0 - rho)

    @property
    def floats_per_iteration(self) -> float:
        """Analytic gossip cost in floats per iteration per model dimension.

        One gossip round sends each worker's model to each of its neighbors:
        Σ_i deg_i values per model coordinate (reference trainer.py:169-170).
        For directed graphs deg = out-degree, so the sum counts each directed
        edge once. Multiply by d (and by rounds-per-iteration for two-mix
        algorithms).
        """
        return float(np.sum(self.degrees))

    def validate(self) -> None:
        """Invariant checks (parity: reference trainer.py:128-131 asserts).

        Directed graphs swap the row-sum + symmetry invariants for the
        column-sum one: column-stochasticity is exactly mass conservation,
        the property push-sum's debiasing relies on (Σ_i (Ax)_i = Σ_j x_j).

        Matrix-free topologies validate the TABLE invariants instead:
        in-range indices, padded slots self-pointing, degrees matching the
        mask, and symmetry (every (i → j) slot has a (j → i) twin) — the
        property that makes gather-form MH mixing doubly stochastic.
        """
        if self.is_matrix_free:
            idx, mask = self.nbr_idx, self.nbr_mask
            if idx is None or mask is None or idx.shape != mask.shape:
                raise AssertionError(
                    f"matrix-free topology needs matching nbr_idx/nbr_mask "
                    f"tables ({self.name})"
                )
            if idx.min() < 0 or idx.max() >= self.n:
                raise AssertionError(
                    f"neighbor indices out of range ({self.name})"
                )
            if not np.all(idx[~mask] == np.nonzero(~mask)[0]):
                raise AssertionError(
                    f"padded neighbor slots must self-point ({self.name})"
                )
            if not np.array_equal(mask.sum(axis=1), self.degrees):
                raise AssertionError(
                    f"degrees disagree with the neighbor mask ({self.name})"
                )
            # Symmetry as a vectorized multiset identity: the directed
            # slot keys i·n + j must equal their swapped twins j·n + i
            # after sorting — every (i → j) slot has a (j → i) twin.
            # (O(E log E) numpy; the former per-edge Python set was the
            # validation bottleneck at N = 1M.)
            ii = np.broadcast_to(
                np.arange(self.n, dtype=np.int64)[:, None], idx.shape
            )[mask]
            jj = idx[mask].astype(np.int64)
            if not np.array_equal(
                np.sort(ii * self.n + jj), np.sort(jj * self.n + ii)
            ):
                raise AssertionError(
                    f"neighbor table must be symmetric ({self.name})"
                )
            return
        W = self.mixing_matrix
        if np.any(W < -1e-12):
            raise AssertionError(f"Mixing matrix must be nonnegative ({self.name})")
        if self.directed:
            if not np.allclose(W.sum(axis=0), 1.0):
                raise AssertionError(
                    f"Directed mixing matrix columns must sum to 1 ({self.name})"
                )
            return
        if not np.allclose(W.sum(axis=1), 1.0):
            raise AssertionError(f"Mixing matrix rows must sum to 1 ({self.name})")
        if not np.allclose(W, W.T):
            raise AssertionError(f"Mixing matrix must be symmetric ({self.name})")


def _ring_adjacency(n: int) -> np.ndarray:
    adj = np.zeros((n, n))
    ids = np.arange(n)
    adj[ids, (ids + 1) % n] = 1.0
    adj[ids, (ids - 1) % n] = 1.0
    np.fill_diagonal(adj, 0.0)  # n == 1, 2 edge cases
    return adj


def _chain_adjacency(n: int) -> np.ndarray:
    adj = np.zeros((n, n))
    ids = np.arange(n - 1)
    adj[ids, ids + 1] = 1.0
    adj[ids + 1, ids] = 1.0
    return adj


def _star_adjacency(n: int) -> np.ndarray:
    adj = np.zeros((n, n))
    adj[0, 1:] = 1.0
    adj[1:, 0] = 1.0
    return adj


def _torus_adjacency(rows: int, cols: int) -> np.ndarray:
    """Periodic 2-D grid. Worker (r, c) sits at index r*cols + c (row-major),
    matching the reference's sorted-node indexing of
    ``networkx.grid_2d_graph(periodic=True)`` (reference trainer.py:103-108)."""
    n = rows * cols
    adj = np.zeros((n, n))
    for r in range(rows):
        for c in range(cols):
            i = r * cols + c
            for rr, cc in ((r - 1, c), (r + 1, c), (r, c - 1), (r, c + 1)):
                j = (rr % rows) * cols + (cc % cols)
                if j != i:  # degenerate 1- or 2-length axes collapse neighbors
                    adj[i, j] = 1.0
    return adj


def _erdos_renyi_adjacency(n: int, p: float, seed: int) -> np.ndarray:
    """Connected Erdős–Rényi G(n, p): resample until connected."""
    rng = np.random.default_rng(seed)
    for _ in range(1000):
        upper = rng.random((n, n)) < p
        adj = np.triu(upper, k=1).astype(float)
        adj = adj + adj.T
        if _is_connected(adj):
            return adj
    raise RuntimeError(f"Could not sample a connected G({n}, {p}) in 1000 tries")


def _directed_ring_adjacency(n: int) -> np.ndarray:
    """Each node receives from its predecessor: edge (i-1) → i."""
    adj = np.zeros((n, n))
    ids = np.arange(n)
    adj[ids, (ids - 1) % n] = 1.0
    np.fill_diagonal(adj, 0.0)  # n == 1 edge case
    return adj


def _directed_erdos_renyi_adjacency(n: int, p: float, seed: int) -> np.ndarray:
    """Strongly connected directed G(n, p): each ORDERED pair (j → i) draws
    independently, resampled until every node reaches every other (checked
    as reachability from node 0 along both edge orientations)."""
    rng = np.random.default_rng(seed)
    for _ in range(1000):
        adj = (rng.random((n, n)) < p).astype(float)
        np.fill_diagonal(adj, 0.0)
        # Strong connectivity ⟺ node 0 reaches all (follow in-edges of the
        # receive convention = walk adj as "i reachable from j") and all
        # reach node 0 (same walk on the transpose).
        if _is_connected_directed(adj) and _is_connected_directed(adj.T):
            return adj
    raise RuntimeError(
        f"Could not sample a strongly connected directed G({n}, {p}) in 1000 tries"
    )


def _is_connected_directed(adj: np.ndarray) -> bool:
    """All nodes reachable from node 0 following edges j → i (adj[i, j])."""
    n = adj.shape[0]
    if n == 0:
        return False
    reached = np.zeros(n, dtype=bool)
    frontier = [0]
    reached[0] = True
    while frontier:
        j = frontier.pop()
        for i in np.nonzero(adj[:, j])[0]:
            if not reached[i]:
                reached[i] = True
                frontier.append(int(i))
    return bool(reached.all())


def _is_connected(adj: np.ndarray) -> bool:
    n = adj.shape[0]
    if n == 0:
        return False
    reached = np.zeros(n, dtype=bool)
    frontier = [0]
    reached[0] = True
    while frontier:
        i = frontier.pop()
        for j in np.nonzero(adj[i])[0]:
            if not reached[j]:
                reached[j] = True
                frontier.append(int(j))
    return bool(reached.all())


def neighbor_table(adjacency: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Static padded neighbor-index table of an undirected 0/1 adjacency.

    Returns ``(nbr_idx [N, k_max] int32, nbr_mask [N, k_max] bool)``: row i
    lists i's neighbors in ascending index order (the same order a dense
    axis-1 reduction visits them, so gather-form aggregations sum in the
    identical order as their dense twins); padded slots point at i itself
    (an always-in-bounds gather target) with mask False. ``k_max`` is the
    maximum degree — the whole point of the gather path is that sorts and
    reductions then run over k_max+1 values instead of N
    (``ops/robust_aggregation.py::make_gather_robust_aggregator``, and
    the single-kernel fused twin that consumes the same table entirely
    in VMEM, ``ops/pallas_kernels.py::make_fused_robust_aggregator``).

    Host-side like everything in this module: built once per run, outside
    ``jit``. Directed graphs are rejected — the degree-bounded screening
    path is undirected-only (robust aggregation composes only with MH
    gossip; the directed/push-sum family rejects Byzantine injection).
    """
    A = np.asarray(adjacency)
    if not np.array_equal(A, A.T):
        raise ValueError(
            "neighbor_table expects an undirected (symmetric) adjacency; "
            "the degree-bounded gather path has no directed form"
        )
    n = A.shape[0]
    k_max = max(int(A.sum(axis=1).max()), 1) if n else 1
    nbr_idx = np.tile(np.arange(n, dtype=np.int32)[:, None], (1, k_max))
    nbr_mask = np.zeros((n, k_max), dtype=bool)
    for i in range(n):
        nbrs = np.nonzero(A[i])[0]
        nbr_idx[i, : len(nbrs)] = nbrs
        nbr_mask[i, : len(nbrs)] = True
    return nbr_idx, nbr_mask


def incident_edge_slots(
    nbr_idx: np.ndarray, nbr_mask: np.ndarray, edge_index: np.ndarray
) -> np.ndarray:
    """[N, k_max] int32 map from (node, neighbor-slot) to undirected edge id.

    ``edge_index`` is the [E, 2] i<j edge list a fault timeline indexes
    (``parallel/faults.py``); entry (i, s) is the id of edge
    {i, nbr_idx[i, s]} — each edge appears in BOTH endpoints' rows, so a
    per-edge liveness bit gathered through this table lands symmetrically,
    exactly like the dense scatter ``A[ei, ej] = A[ej, ei] = up[e]``.
    Padded slots map to 0 (masked out by ``nbr_mask`` downstream).
    """
    edge_id = {
        (int(i), int(j)): e for e, (i, j) in enumerate(edge_index)
    }
    slots = np.zeros(nbr_idx.shape, dtype=np.int32)
    for i in range(nbr_idx.shape[0]):
        for s in range(nbr_idx.shape[1]):
            if nbr_mask[i, s]:
                j = int(nbr_idx[i, s])
                slots[i, s] = edge_id[(min(i, j), max(i, j))]
    return slots


def _pad_neighbor_lists(nbrs: list[np.ndarray], n: int):
    """Pack per-node ascending neighbor lists into the padded table
    (identical layout/convention to ``neighbor_table``: padded slots point
    at the node itself, mask False)."""
    k_max = max((len(v) for v in nbrs), default=0)
    k_max = max(k_max, 1)
    nbr_idx = np.tile(np.arange(n, dtype=np.int32)[:, None], (1, k_max))
    nbr_mask = np.zeros((n, k_max), dtype=bool)
    for i, v in enumerate(nbrs):
        nbr_idx[i, : len(v)] = np.sort(v).astype(np.int32)
        nbr_mask[i, : len(v)] = True
    return nbr_idx, nbr_mask


def _ring_neighbor_lists(n: int) -> list[np.ndarray]:
    if n <= 1:
        return [np.empty(0, dtype=np.int64) for _ in range(n)]
    if n == 2:
        return [np.array([1]), np.array([0])]
    return [
        np.unique(np.array([(i - 1) % n, (i + 1) % n]))
        for i in range(n)
    ]


def _chain_neighbor_lists(n: int) -> list[np.ndarray]:
    out = []
    for i in range(n):
        v = [j for j in (i - 1, i + 1) if 0 <= j < n]
        out.append(np.asarray(v, dtype=np.int64))
    return out


def _torus_neighbor_lists(rows: int, cols: int) -> list[np.ndarray]:
    """Same node indexing and neighbor set as ``_torus_adjacency`` (row-major
    (r, c) ↦ r·cols + c; degenerate short axes collapse duplicates)."""
    out = []
    for r in range(rows):
        for c in range(cols):
            i = r * cols + c
            js = {
                (rr % rows) * cols + (cc % cols)
                for rr, cc in ((r - 1, c), (r + 1, c), (r, c - 1), (r, c + 1))
            }
            js.discard(i)
            out.append(np.asarray(sorted(js), dtype=np.int64))
    return out


def _erdos_renyi_neighbor_lists(
    n: int, p: float, seed: int
) -> list[np.ndarray]:
    """Connected G(n, p) WITHOUT the [N, N] draw matrix.

    Bit-identical to ``_erdos_renyi_adjacency``: numpy's Generator fills
    ``random((n, n))`` row-major from one sequential stream, so drawing
    ``random(n)`` per row walks the same values in the same order — the
    same (seed, try) realizes the same graph in both representations
    (pinned by tests/test_federated.py). Memory is O(n) per row plus the
    O(E) adjacency lists; connectivity is union-find over the edges as
    they are drawn.
    """
    rng = np.random.default_rng(seed)
    for _ in range(1000):
        nbrs: list[list[int]] = [[] for _ in range(n)]
        parent = list(range(n))

        def find(a: int) -> int:
            while parent[a] != a:
                parent[a] = parent[parent[a]]
                a = parent[a]
            return a

        comps = n
        for i in range(n):
            row = rng.random(n)
            for j in np.nonzero(row[i + 1:] < p)[0]:
                j = int(i + 1 + j)
                nbrs[i].append(j)
                nbrs[j].append(i)
                ri, rj = find(i), find(j)
                if ri != rj:
                    parent[ri] = rj
                    comps -= 1
        if comps == 1:
            return [np.asarray(v, dtype=np.int64) for v in nbrs]
    raise RuntimeError(f"Could not sample a connected G({n}, {p}) in 1000 tries")


def _ring_neighbor_tables(n: int) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized twin of ``_ring_neighbor_lists`` + ``_pad_neighbor_lists``
    for n >= 3 (every node has the two distinct neighbors (i±1) mod n,
    listed ascending) — bitwise-identical tables without the per-row
    Python loop, the 1M-node path."""
    ids = np.arange(n, dtype=np.int64)
    left, right = (ids - 1) % n, (ids + 1) % n
    nbr_idx = np.stack(
        [np.minimum(left, right), np.maximum(left, right)], axis=1
    ).astype(np.int32)
    return nbr_idx, np.ones((n, 2), dtype=bool)


def _chain_neighbor_tables(n: int) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized twin of ``_chain_neighbor_lists`` + ``_pad_neighbor_lists``
    for n >= 3 (interior rows [i−1, i+1]; endpoint rows degree 1 with the
    padded slot self-pointing)."""
    ids = np.arange(n, dtype=np.int32)
    nbr_idx = np.tile(ids[:, None], (1, 2))
    nbr_mask = np.zeros((n, 2), dtype=bool)
    nbr_idx[1:-1, 0] = ids[1:-1] - 1
    nbr_idx[1:-1, 1] = ids[1:-1] + 1
    nbr_mask[1:-1] = True
    nbr_idx[0, 0] = 1
    nbr_mask[0, 0] = True
    nbr_idx[-1, 0] = n - 2
    nbr_mask[-1, 0] = True
    return nbr_idx, nbr_mask


def _torus_neighbor_tables(side: int) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized twin of ``_torus_neighbor_lists`` + ``_pad_neighbor_lists``
    for square tori with side >= 3 (all four wrap neighbors distinct,
    sorted ascending per row)."""
    r = np.repeat(np.arange(side, dtype=np.int64), side)
    c = np.tile(np.arange(side, dtype=np.int64), side)
    stacked = np.stack(
        [
            ((r - 1) % side) * side + c,
            ((r + 1) % side) * side + c,
            r * side + (c - 1) % side,
            r * side + (c + 1) % side,
        ],
        axis=1,
    )
    nbr_idx = np.sort(stacked, axis=1).astype(np.int32)
    return nbr_idx, np.ones((side * side, 4), dtype=bool)


def _pack_neighbor_tables(
    src: np.ndarray, dst: np.ndarray, n: int
) -> tuple[np.ndarray, np.ndarray]:
    """Pack forward undirected edges (src < dst, unique) into the padded
    table — vectorized counterpart of ``_pad_neighbor_lists`` (padded
    slots self-point, per-row neighbors ascending)."""
    si = np.concatenate([src, dst])
    di = np.concatenate([dst, src])
    order = np.lexsort((di, si))
    si, di = si[order], di[order]
    deg = np.bincount(si, minlength=n)
    k_max = max(int(deg.max()) if n else 0, 1)
    nbr_idx = np.tile(np.arange(n, dtype=np.int32)[:, None], (1, k_max))
    nbr_mask = np.zeros((n, k_max), dtype=bool)
    offs = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(deg, out=offs[1:])
    col = np.arange(si.size, dtype=np.int64) - offs[si]
    nbr_idx[si, col] = di.astype(np.int32)
    nbr_mask[si, col] = True
    return nbr_idx, nbr_mask


def _edges_connected(src: np.ndarray, dst: np.ndarray, n: int) -> bool:
    """Connectivity of an undirected edge list by vectorized min-label
    propagation with pointer jumping: each round every node takes the
    minimum label over its closed neighborhood, then labels chase labels
    (``lab[lab]``). At the fixed point labels are constant per component,
    so connected ⟺ all labels equal node 0's. O((E + N) · rounds) with
    rounds ~ log(diameter) — the union-find replacement that needs no
    per-edge Python loop at N = 1M."""
    if n == 0:
        return False
    lab = np.arange(n, dtype=np.int64)
    for _ in range(10_000):
        nxt = lab.copy()
        np.minimum.at(nxt, src, lab[dst])
        np.minimum.at(nxt, dst, lab[src])
        nxt = nxt[nxt]
        if np.array_equal(nxt, lab):
            break
        lab = nxt
    return bool((lab == 0).all())


# Bounded dedupe/top-up rounds for the sparse ER sampler. Each round
# redraws only the deficit (forward edges lost to duplicate tail draws);
# with k_max ≪ tail the per-draw collision probability is ~k_max/tail,
# so deficits shrink geometrically and the bound is never approached in
# practice — it exists so a pathological (n, p) fails loudly.
_SPARSE_TOPUP_ROUNDS = 200


def _erdos_renyi_forward_edges_sparse(
    n: int, p: float, seed: int
) -> tuple[np.ndarray, np.ndarray]:
    """Connected G(n, p) in O(N·k_max) draws: the million-node sampler.

    Decomposes the undirected upper-triangle draw by FORWARD tails: node
    i's edges into {i+1, …, n−1} are Binomial(n−1−i, p) in number and
    uniform without replacement in position. One vectorized
    ``rng.binomial`` draws every forward degree, one vectorized uniform
    draw proposes that many tail partners WITH replacement, and bounded
    top-up rounds redraw exactly the rows that lost proposals to
    duplicates — total work O(E) instead of the dense sampler's O(N²)
    stream replay. Connectivity is vectorized min-label propagation
    (``_edges_connected``); like every sampler here the generator stream
    is seed-pure (draws depend only on (n, p, seed) and the retry
    index), so a given seed realizes the same graph everywhere.

    Same G(n, p) law as ``_erdos_renyi_neighbor_lists``, a DIFFERENT
    realization per (seed, p) — which is why the sampler choice is part
    of a config's structural identity rather than a transparent
    implementation detail (``config.resolved_topology_sampler()``).

    Returns the forward edge list ``(src, dst)`` with src < dst, unique,
    for ``_pack_neighbor_tables``.
    """
    rng = np.random.default_rng(seed)
    ids = np.arange(n, dtype=np.int64)
    tail = (n - 1) - ids
    for _ in range(1000):
        counts = rng.binomial(tail, p)
        src = np.repeat(ids, counts)
        dst = src + 1 + np.floor(
            rng.random(src.size) * tail[src]
        ).astype(np.int64)
        keys = np.unique(src * n + dst)
        for _ in range(_SPARSE_TOPUP_ROUNDS):
            deficit = counts - np.bincount(keys // n, minlength=n)
            if not (deficit > 0).any():
                break
            src2 = np.repeat(ids, np.maximum(deficit, 0))
            dst2 = src2 + 1 + np.floor(
                rng.random(src2.size) * tail[src2]
            ).astype(np.int64)
            keys = np.unique(np.concatenate([keys, src2 * n + dst2]))
        else:
            raise RuntimeError(
                f"sparse G({n}, {p}) top-up did not converge in "
                f"{_SPARSE_TOPUP_ROUNDS} rounds"
            )
        src_f, dst_f = keys // n, keys % n
        if _edges_connected(src_f, dst_f, n):
            return src_f, dst_f
    raise RuntimeError(f"Could not sample a connected G({n}, {p}) in 1000 tries")


# Ceiling on the padded neighbor-table cell count (satellite guard): a
# topology whose k_max approaches N has no degree-bounded structure to
# exploit, and "matrix-free" would just reallocate the quadratic object
# under a different name. fully_connected/star are rejected by name with
# the specific message; this catches dense Erdős–Rényi draws.
NEIGHBOR_TABLE_MAX_CELLS = 64_000_000


def _guard_table_size(k_max: int, n: int) -> None:
    """The two degree guards of the matrix-free path, shared by every
    constructor branch: a k_max approaching N has no degree bound to
    exploit, and the padded table's cell count is capped so 'matrix-free'
    can never silently reallocate the quadratic object."""
    if n > 2 and k_max >= n - 1:
        raise ValueError(
            f"realized max degree {k_max} at N={n} leaves no degree bound "
            "to exploit — the neighbor table would match the dense "
            "adjacency's footprint; use the dense representation"
        )
    if max(k_max, 1) * n > NEIGHBOR_TABLE_MAX_CELLS:
        raise ValueError(
            f"neighbor table would hold {max(k_max, 1) * n:,} cells "
            f"(k_max={k_max}, N={n}) > NEIGHBOR_TABLE_MAX_CELLS "
            f"({NEIGHBOR_TABLE_MAX_CELLS:,}) — this graph is too dense "
            "for the degree-bounded path; use the dense representation "
            "or a sparser graph"
        )


def build_neighbor_topology(
    name: str,
    n: int,
    *,
    erdos_renyi_p: float = 0.4,
    seed: int = 0,
    sampler: str = "dense",
) -> Topology:
    """Matrix-free constructor: the [N, k_max] neighbor table IS the graph.

    Supports ``MATRIX_FREE_TOPOLOGIES`` (undirected, degree-bounded).
    fully_connected and star are rejected loudly — k_max = N−1 makes the
    padded table the very [N, N] allocation this path exists to avoid —
    and any draw whose table would exceed ``NEIGHBOR_TABLE_MAX_CELLS``
    (or whose k_max reaches N−1) routes the caller back to dense with the
    reason.

    ``sampler`` selects the Erdős–Rényi constructor: 'dense' replays the
    [N, N] uniform stream bit-for-bit (O(N²) draws — the historical
    reference), 'sparse' draws O(N·k_max)
    (``_erdos_renyi_forward_edges_sparse`` — the million-node path, a
    different realization of the same law). Deterministic topologies
    ignore it (their tables are unique); callers resolve 'auto' policy
    via ``config.resolved_topology_sampler()`` before calling.
    """
    if name in ("fully_connected", "star"):
        raise ValueError(
            f"topology {name!r} has k_max = N-1: its neighbor table IS the "
            "dense [N, N] object the matrix-free path avoids — use the "
            "dense representation (impl='dense')"
        )
    if sampler not in ("dense", "sparse"):
        raise ValueError(
            f"unknown topology sampler {sampler!r} (expected 'dense' or "
            "'sparse')"
        )
    grid_shape: Optional[tuple[int, int]] = None
    sampler_used = "dense"
    if name == "ring":
        tables = (
            _ring_neighbor_tables(n)
            if n > 2
            else _pad_neighbor_lists(_ring_neighbor_lists(n), n)
        )
    elif name == "chain":
        tables = (
            _chain_neighbor_tables(n)
            if n > 2
            else _pad_neighbor_lists(_chain_neighbor_lists(n), n)
        )
    elif name == "grid":
        side = int(math.isqrt(n))
        if side * side != n:
            raise ValueError(f"grid topology requires a perfect square, got {n}")
        tables = (
            _torus_neighbor_tables(side)
            if side >= 3
            else _pad_neighbor_lists(_torus_neighbor_lists(side, side), n)
        )
        grid_shape = (side, side)
    elif name == "erdos_renyi":
        sampler_used = sampler
        if sampler == "sparse":
            src, dst = _erdos_renyi_forward_edges_sparse(
                n, erdos_renyi_p, seed
            )
            # Guard on the realized degrees BEFORE allocating the padded
            # table — at this scale the table is the dominant allocation.
            deg = np.bincount(
                np.concatenate([src, dst]), minlength=max(n, 1)
            )
            _guard_table_size(int(deg.max()) if n else 0, n)
            tables = _pack_neighbor_tables(src, dst, n)
        else:
            nbrs = _erdos_renyi_neighbor_lists(n, erdos_renyi_p, seed)
            _guard_table_size(max((len(v) for v in nbrs), default=0), n)
            tables = _pad_neighbor_lists(nbrs, n)
    else:
        raise ValueError(
            f"no matrix-free constructor for topology {name!r} "
            f"(supported: {MATRIX_FREE_TOPOLOGIES})"
        )
    nbr_idx, nbr_mask = tables
    _guard_table_size(int(nbr_mask.sum(axis=1).max()) if n else 0, n)
    topo = Topology(
        name=name,
        n=n,
        adjacency=None,
        degrees=nbr_mask.sum(axis=1).astype(np.float64),
        mixing_matrix=None,
        grid_shape=grid_shape,
        nbr_idx=nbr_idx,
        nbr_mask=nbr_mask,
        sampler=sampler_used,
    )
    topo.validate()
    return topo


def neighbor_tables_for(topo: Topology) -> tuple[np.ndarray, np.ndarray]:
    """The (nbr_idx, nbr_mask) tables of any undirected topology: native
    for matrix-free builds, derived via ``neighbor_table`` from the dense
    adjacency otherwise (both produce the identical layout)."""
    if topo.nbr_idx is not None:
        return topo.nbr_idx, topo.nbr_mask
    return neighbor_table(topo.adjacency)


@dataclasses.dataclass(frozen=True)
class HaloStep:
    """One ppermute rotation of the halo exchange (devices p → (p+r) mod P).

    At rotation ``r`` every shard p ships to shard (p+r) mod P exactly the
    block rows that destination's neighbor table references, padded to the
    rotation's max count so the collective is shape-uniform. ``send_idx``
    [P, s_max] holds SENDER-local row indices (pad 0 — a harmless real
    row); ``recv_pos`` [P, s_max] the receiver's halo-buffer positions
    (pad = h_max, the dump slot past the real halo). ``counts`` [P] are
    the realized (unpadded) row counts — the per-device ICI accounting.
    """

    rotation: int
    send_idx: np.ndarray  # [P, s_max] int32
    recv_pos: np.ndarray  # [P, s_max] int32
    counts: np.ndarray    # [P] int64


@dataclasses.dataclass(frozen=True)
class HaloPlan:
    """Static sharding plan of a padded neighbor table over P row blocks.

    Shard p owns the contiguous global rows [p·S, (p+1)·S). ``local_nbr``
    is the whole table remapped to SHARD-LOCAL coordinates: entry (i, s)
    of shard p's block indexes into that shard's extended buffer
    ``ext = concat([block [S], halo [h_max + 1]])`` — in-block neighbors
    map to their block row, boundary neighbors to S + (position in the
    shard's sorted halo list), so ``ext[local_nbr]`` gathers exactly the
    values ``x[nbr_idx]`` gathers globally (the bitwise-parity contract
    of the sharded gather path). The extra halo slot (index S + h_max)
    is the dump row padded exchange traffic lands in — never referenced
    by ``local_nbr``. ``sent_rows``/``recv_rows`` [P] count the realized
    boundary rows each device ships/receives per exchange: the
    bytes-over-ICI accounting is ``sent_rows · payload_width · itemsize``.
    """

    n_shards: int
    shard_rows: int
    h_max: int
    local_nbr: np.ndarray     # [N, k_max] int32, values in [0, S + h_max)
    halo_idx: list            # per-shard sorted GLOBAL boundary rows
    steps: tuple              # tuple[HaloStep, ...] — empty rotations dropped
    sent_rows: np.ndarray     # [P] int64
    recv_rows: np.ndarray     # [P] int64


# One sharded faulty+robust run consults the identical plan up to five
# times (mixing op, fault layer, robust aggregator, /metrics gauges,
# health_summary) and each build is an O(N·k_max) host pass with
# per-shard Python loops — memoize by content digest so the plan is
# built once per (table, P). Plans are treated read-only by every
# consumer (they are lowered straight into device arrays).
_HALO_PLAN_CACHE: "collections.OrderedDict[tuple, HaloPlan]" = (
    collections.OrderedDict()
)
_HALO_PLAN_CACHE_MAX = 8


def build_halo_plan(
    nbr_idx: np.ndarray,
    nbr_mask: np.ndarray,
    n_shards: int,
    *,
    sampler: str = "dense",
    overlap: str = "off",
) -> HaloPlan:
    """Shard a padded neighbor table into P contiguous row blocks + halo maps.

    Host-side like every builder in this module: runs once per run
    (memoized by table digest — see ``_HALO_PLAN_CACHE``). The
    exchange schedule enumerates rotations r = 1..P−1 and keeps only the
    ones some shard actually needs (a ring's contiguous blocks keep r ∈
    {1, P−1} with one row each — the classic boundary exchange; an
    Erdős–Rényi graph keeps every rotation with ~E/P² rows). Both sides
    of a rotation enumerate the shared rows in ascending global order, so
    the sender's packing and the receiver's halo positions agree by
    construction (asserted against the realized adjacency in
    tests/test_worker_mesh.py).

    ``sampler`` and ``overlap`` name the exchange form the plan serves
    (the topology's sampler identity and the ``halo_overlap`` mode).
    Today's plan layout is identical across both, but they are part of
    the memoization key so a cache hit can never serve a plan built for
    the other exchange form if the layouts ever diverge.
    """
    n, k_max = nbr_idx.shape
    if n_shards < 2:
        raise ValueError(f"halo plans need >= 2 shards, got {n_shards}")
    if n % n_shards:
        raise ValueError(
            f"n_shards={n_shards} must divide the worker count ({n})"
        )
    digest = hashlib.sha1()
    digest.update(np.ascontiguousarray(nbr_idx).tobytes())
    digest.update(np.ascontiguousarray(nbr_mask).tobytes())
    cache_key = (
        digest.hexdigest(), nbr_idx.shape, int(n_shards),
        str(sampler), str(overlap),
    )
    cached = _HALO_PLAN_CACHE.get(cache_key)
    if cached is not None:
        _HALO_PLAN_CACHE.move_to_end(cache_key)
        return cached
    S = n // n_shards
    halo_idx: list[np.ndarray] = []
    for p in range(n_shards):
        rows = nbr_idx[p * S:(p + 1) * S]
        mask = nbr_mask[p * S:(p + 1) * S]
        ref = np.unique(rows[mask])
        halo_idx.append(ref[(ref < p * S) | (ref >= (p + 1) * S)])
    h_max = max((len(h) for h in halo_idx), default=0)

    local_nbr = np.empty_like(nbr_idx, dtype=np.int32)
    for p in range(n_shards):
        block = nbr_idx[p * S:(p + 1) * S].astype(np.int64)
        in_block = (block >= p * S) & (block < (p + 1) * S)
        pos = np.searchsorted(halo_idx[p], block)
        local_nbr[p * S:(p + 1) * S] = np.where(
            in_block, block - p * S, S + pos
        ).astype(np.int32)
        # Padded slots self-point globally, hence in-block locally — the
        # searchsorted values on them are never selected.
        if (~in_block).any():
            h = halo_idx[p]
            clipped = np.minimum(pos, len(h) - 1)
            bad = ~in_block & (
                (pos >= len(h)) | (np.take(h, clipped) != block)
            )
            if bad.any():
                raise AssertionError(
                    f"shard {p}: neighbor rows missing from the halo list"
                )

    steps = []
    sent = np.zeros(n_shards, dtype=np.int64)
    recv = np.zeros(n_shards, dtype=np.int64)
    for r in range(1, n_shards):
        # Receiver view: shard p receives from src = (p - r) mod P the
        # subset of its halo that lives in src's block.
        needed = []
        for p in range(n_shards):
            src = (p - r) % n_shards
            h = halo_idx[p]
            needed.append(h[(h >= src * S) & (h < (src + 1) * S)])
        counts = np.array([len(v) for v in needed], dtype=np.int64)
        if not counts.any():
            continue
        s_max = int(counts.max())
        send_idx = np.zeros((n_shards, s_max), dtype=np.int32)
        recv_pos = np.full((n_shards, s_max), h_max, dtype=np.int32)
        for p in range(n_shards):
            dest = (p + r) % n_shards
            ship = needed[dest]  # global rows dest needs from p
            send_idx[p, : len(ship)] = (ship - p * S).astype(np.int32)
            mine = needed[p]     # global rows p receives this rotation
            recv_pos[p, : len(mine)] = np.searchsorted(
                halo_idx[p], mine
            ).astype(np.int32)
            sent[p] += len(ship)
            recv[p] += len(mine)
        steps.append(
            HaloStep(rotation=r, send_idx=send_idx, recv_pos=recv_pos,
                     counts=counts)
        )
    plan = HaloPlan(
        n_shards=n_shards, shard_rows=S, h_max=h_max, local_nbr=local_nbr,
        halo_idx=halo_idx, steps=tuple(steps), sent_rows=sent,
        recv_rows=recv,
    )
    _HALO_PLAN_CACHE[cache_key] = plan
    while len(_HALO_PLAN_CACHE) > _HALO_PLAN_CACHE_MAX:
        _HALO_PLAN_CACHE.popitem(last=False)
    return plan


def gather_mixing_weights(
    nbr_idx: np.ndarray, nbr_mask: np.ndarray, degrees: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Metropolis-Hastings weights in gather (per-slot) form.

    Returns ``(w_nbr [N, k_max], w_self [N])`` float64 with
    ``w_nbr[i, s] = 1/(1 + max(deg_i, deg_{nbr[i, s]}))`` on live slots
    (0 on padding) and ``w_self = 1 − Σ_s w_nbr`` — elementwise the same
    values as ``metropolis_hastings_weights`` read at (i, nbr[i, s]) and
    (i, i), never materializing the [N, N] matrix. ``W x`` is then
    ``w_self·x + Σ_s w_nbr[:, s]·x[nbr[:, s]]``: O(N·k_max·d).
    """
    deg = np.asarray(degrees, dtype=np.float64)
    pair = np.maximum(deg[:, None], deg[nbr_idx])
    w_nbr = np.where(nbr_mask, 1.0 / (1.0 + pair), 0.0)
    w_self = 1.0 - w_nbr.sum(axis=1)
    return w_nbr, w_self


def metropolis_hastings_weights(adjacency: np.ndarray) -> np.ndarray:
    """Metropolis-Hastings mixing matrix from an adjacency matrix.

    W_ij = 1 / (1 + max(deg_i, deg_j)) for edges, W_ii = 1 - Σ_j W_ij.
    Parity: reference trainer.py:118-126. Vectorized instead of the
    reference's per-neighbor Python loops.
    """
    degrees = adjacency.sum(axis=1)
    pairwise_max = np.maximum(degrees[:, None], degrees[None, :])
    W = adjacency / (1.0 + pairwise_max)
    np.fill_diagonal(W, 0.0)
    np.fill_diagonal(W, 1.0 - W.sum(axis=1))
    return W


def column_stochastic_weights(adjacency: np.ndarray) -> np.ndarray:
    """Uniform-out-weight column-stochastic mixing matrix (push-sum gossip).

    Node j splits its mass equally over its out-neighbors and itself:
    A_ij = 1/(1 + outdeg_j) for every edge j → i and for i = j. Columns sum
    to 1 by construction (mass conservation — the invariant push-sum's
    weight debiasing rests on, Nedić-Olshevsky 2016 §II). This is the
    standard construction when nodes know only their OUT-degree, the honest
    information model for asymmetric links.
    """
    out_degrees = adjacency.sum(axis=0)
    A = adjacency / (1.0 + out_degrees[None, :])
    np.fill_diagonal(A, 1.0 / (1.0 + out_degrees))
    return A


def build_topology(
    name: str,
    n: int,
    *,
    erdos_renyi_p: float = 0.4,
    seed: int = 0,
    impl: str = "dense",
    sampler: str = "dense",
) -> Topology:
    """Build a named topology over ``n`` workers.

    Undirected names get MH mixing weights; directed names
    (``directed_ring``, ``directed_erdos_renyi``) get column-stochastic
    uniform-out weights (the push-sum setting).

    ``impl``: 'dense' materializes the [N, N] adjacency + mixing matrix
    (the historical representation); 'neighbor' builds the matrix-free
    padded neighbor table instead (``build_neighbor_topology`` — the
    federated-scale route, docs/PERF.md §14). Callers resolve 'auto'
    policy via ``config.resolved_topology_impl()`` before calling.

    ``sampler`` (matrix-free Erdős–Rényi only) picks the 'dense'
    bitwise-reference or 'sparse' O(N·k_max) constructor; callers resolve
    'auto' via ``config.resolved_topology_sampler()``. The dense [N, N]
    representation has exactly one sampler — requesting 'sparse' with
    ``impl='dense'`` is a contradiction and raises.
    """
    if impl == "neighbor":
        return build_neighbor_topology(
            name, n, erdos_renyi_p=erdos_renyi_p, seed=seed, sampler=sampler
        )
    if impl != "dense":
        raise ValueError(f"Unknown topology impl: {impl!r}")
    if sampler != "dense":
        raise ValueError(
            "the dense [N, N] representation replays its own uniform "
            f"stream — sampler={sampler!r} only exists on the matrix-free "
            "path (impl='neighbor')"
        )
    if name in ("directed_ring", "directed_erdos_renyi"):
        adj = (
            _directed_ring_adjacency(n)
            if name == "directed_ring"
            else _directed_erdos_renyi_adjacency(n, erdos_renyi_p, seed)
        )
        topo = Topology(
            name=name,
            n=n,
            adjacency=adj,
            degrees=adj.sum(axis=0),  # out-degrees (column sums)
            mixing_matrix=column_stochastic_weights(adj),
            directed=True,
        )
        topo.validate()
        return topo

    grid_shape: Optional[tuple[int, int]] = None
    if name == "ring":
        adj = _ring_adjacency(n)
    elif name == "grid":
        side = int(math.isqrt(n))
        if side * side != n:
            # Parity: reference trainer.py:100-102 raises for non-square N.
            raise ValueError(f"grid topology requires a perfect square, got {n}")
        adj = _torus_adjacency(side, side)
        grid_shape = (side, side)
    elif name == "fully_connected":
        adj = np.ones((n, n)) - np.eye(n)
    elif name == "erdos_renyi":
        adj = _erdos_renyi_adjacency(n, erdos_renyi_p, seed)
    elif name == "chain":
        adj = _chain_adjacency(n)
    elif name == "star":
        adj = _star_adjacency(n)
    else:
        raise ValueError(f"Unknown topology: {name!r}")

    topo = Topology(
        name=name,
        n=n,
        adjacency=adj,
        degrees=adj.sum(axis=1),
        mixing_matrix=metropolis_hastings_weights(adj),
        grid_shape=grid_shape,
    )
    topo.validate()
    return topo


def ring_spectral_gap_closed_form(n: int) -> float:
    """Closed-form spectral gap of the MH ring (all degrees 2 ⇒ W_ij = 1/3).

    Eigenvalues of W are (1 + 2cos(2πk/n))/3; ρ = max_{k≠0} |λ_k|.
    Matches the report's §III-A value 0.0209 for n = 25.
    """
    if n < 3:
        return 1.0
    lambdas = (1.0 + 2.0 * np.cos(2.0 * np.pi * np.arange(1, n) / n)) / 3.0
    return float(1.0 - np.max(np.abs(lambdas)))


def directed_ring_spectral_gap_closed_form(n: int) -> float:
    """Closed-form spectral gap of the uniform-out directed ring.

    Out-degree 1 everywhere ⇒ A = (I + P)/2 with P the cyclic shift.
    Eigenvalues are (1 + e^{2πik/n})/2 with modulus cos(πk/n), so
    ρ = cos(π/n) and the gap is 1 − cos(π/n) ≈ π²/(2n²).
    """
    if n < 2:
        return 1.0
    return float(1.0 - np.cos(np.pi / n))


def torus_spectral_gap_closed_form(side: int) -> float:
    """Closed-form spectral gap of the MH torus (degree 4 ⇒ off-diag 1/5).

    Eigenvalues are (1 + 2cos(2πj/s) + 2cos(2πk/s))/5 over j,k.
    Matches the report's §III-A value 0.2764 for s = 5.
    """
    js = np.arange(side)
    cj = 2.0 * np.cos(2.0 * np.pi * js / side)
    lam = (1.0 + cj[:, None] + cj[None, :]) / 5.0
    lam = lam.ravel()
    lam_sorted = np.sort(np.abs(lam))
    return float(1.0 - lam_sorted[-2])
