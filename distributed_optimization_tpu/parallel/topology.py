"""Communication-graph topologies and Metropolis-Hastings mixing matrices.

Capability parity with the reference's topology + mixing-matrix builder
(reference ``trainer.py:91-136``): ring, periodic 2-D grid (torus), and
fully-connected graphs with Metropolis-Hastings gossip weights
``W_ij = 1/(1 + max(deg_i, deg_j))`` and self-weight = row remainder, plus the
same invariants (row-stochastic, symmetric) and the spectral gap ``1 - ρ``
from the second-largest absolute eigenvalue.

Extensions beyond the reference: Erdős–Rényi random graphs (the BASELINE.json
decentralized-ADMM config), chain (path), and star topologies; and a
*stencil* description (shift offsets + weights) for the topologies whose
mixing step maps onto TPU ICI as `ppermute` neighbor shifts instead of a dense
``W @ models`` matmul — ring/chain/torus are the cases where the communication
graph embeds directly into the pod mesh.

Round 4 adds DIRECTED graphs (``directed_ring``, ``directed_erdos_renyi``)
with column-stochastic uniform-out-weight mixing — the push-sum/SGP setting
(Nedić-Olshevsky 2016; Assran et al. 2019), where Metropolis-Hastings gossip
is undefined because asymmetric links admit no symmetric doubly stochastic
weight assignment. Convention: ``adjacency[i, j] = 1`` iff j sends to i
(row i = who i RECEIVES from), so ``mixing_matrix @ x`` aggregates received
messages for both directed and undirected graphs. The directed ring is the
ICI-friendly case: one gossip round is a single forward ``ppermute`` — half
the undirected ring's boundary traffic.

This module is host-side (numpy): topologies are built once per run, outside
``jit``. The compiled mixing operators that consume them live in
``ops/mixing.py`` and ``parallel/collectives.py``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class Topology:
    """A communication graph plus its gossip structure.

    Undirected graphs (``directed=False``) carry a Metropolis-Hastings
    mixing matrix (row-stochastic, symmetric — hence doubly stochastic);
    directed graphs carry a column-stochastic uniform-out-weight matrix
    (each node splits its mass equally over its out-neighbors and itself),
    the push-sum setting. ``adjacency[i, j] = 1`` iff j sends to i.
    """

    name: str
    n: int
    adjacency: np.ndarray  # [N, N] 0/1, zero diagonal; row i = i's in-edges
    # Out-degrees (== in-degrees for undirected graphs): how many neighbors
    # each node TRANSMITS to per gossip round — the comms-accounting side.
    degrees: np.ndarray  # [N]
    mixing_matrix: np.ndarray  # [N, N]; MH (undirected) or column-stochastic
    grid_shape: Optional[tuple[int, int]] = None  # set for 'grid'
    directed: bool = False

    @property
    def spectral_gap(self) -> float:
        """1 - ρ where ρ is the second-largest |eigenvalue| of W.

        Parity: reference trainer.py:133-135. Closed-form values for the
        report setup: ring(25) ≈ 0.0209, 5x5 torus ≈ 0.2764, fc = 1.0.
        Directed mixing matrices are non-normal with a possibly complex
        spectrum; ρ is the second-largest eigenvalue MODULUS (the
        ergodicity coefficient of the column-stochastic chain — self-loops
        make it primitive, so ρ < 1 for strongly connected graphs).
        """
        if self.n < 2:
            return 1.0
        if self.directed:
            eigs = np.sort(np.abs(np.linalg.eigvals(self.mixing_matrix)))
        else:
            eigs = np.sort(np.abs(np.linalg.eigvalsh(self.mixing_matrix)))
        return float(1.0 - eigs[-2])

    @property
    def floats_per_iteration(self) -> float:
        """Analytic gossip cost in floats per iteration per model dimension.

        One gossip round sends each worker's model to each of its neighbors:
        Σ_i deg_i values per model coordinate (reference trainer.py:169-170).
        For directed graphs deg = out-degree, so the sum counts each directed
        edge once. Multiply by d (and by rounds-per-iteration for two-mix
        algorithms).
        """
        return float(np.sum(self.degrees))

    def validate(self) -> None:
        """Invariant checks (parity: reference trainer.py:128-131 asserts).

        Directed graphs swap the row-sum + symmetry invariants for the
        column-sum one: column-stochasticity is exactly mass conservation,
        the property push-sum's debiasing relies on (Σ_i (Ax)_i = Σ_j x_j).
        """
        W = self.mixing_matrix
        if np.any(W < -1e-12):
            raise AssertionError(f"Mixing matrix must be nonnegative ({self.name})")
        if self.directed:
            if not np.allclose(W.sum(axis=0), 1.0):
                raise AssertionError(
                    f"Directed mixing matrix columns must sum to 1 ({self.name})"
                )
            return
        if not np.allclose(W.sum(axis=1), 1.0):
            raise AssertionError(f"Mixing matrix rows must sum to 1 ({self.name})")
        if not np.allclose(W, W.T):
            raise AssertionError(f"Mixing matrix must be symmetric ({self.name})")


def _ring_adjacency(n: int) -> np.ndarray:
    adj = np.zeros((n, n))
    ids = np.arange(n)
    adj[ids, (ids + 1) % n] = 1.0
    adj[ids, (ids - 1) % n] = 1.0
    np.fill_diagonal(adj, 0.0)  # n == 1, 2 edge cases
    return adj


def _chain_adjacency(n: int) -> np.ndarray:
    adj = np.zeros((n, n))
    ids = np.arange(n - 1)
    adj[ids, ids + 1] = 1.0
    adj[ids + 1, ids] = 1.0
    return adj


def _star_adjacency(n: int) -> np.ndarray:
    adj = np.zeros((n, n))
    adj[0, 1:] = 1.0
    adj[1:, 0] = 1.0
    return adj


def _torus_adjacency(rows: int, cols: int) -> np.ndarray:
    """Periodic 2-D grid. Worker (r, c) sits at index r*cols + c (row-major),
    matching the reference's sorted-node indexing of
    ``networkx.grid_2d_graph(periodic=True)`` (reference trainer.py:103-108)."""
    n = rows * cols
    adj = np.zeros((n, n))
    for r in range(rows):
        for c in range(cols):
            i = r * cols + c
            for rr, cc in ((r - 1, c), (r + 1, c), (r, c - 1), (r, c + 1)):
                j = (rr % rows) * cols + (cc % cols)
                if j != i:  # degenerate 1- or 2-length axes collapse neighbors
                    adj[i, j] = 1.0
    return adj


def _erdos_renyi_adjacency(n: int, p: float, seed: int) -> np.ndarray:
    """Connected Erdős–Rényi G(n, p): resample until connected."""
    rng = np.random.default_rng(seed)
    for _ in range(1000):
        upper = rng.random((n, n)) < p
        adj = np.triu(upper, k=1).astype(float)
        adj = adj + adj.T
        if _is_connected(adj):
            return adj
    raise RuntimeError(f"Could not sample a connected G({n}, {p}) in 1000 tries")


def _directed_ring_adjacency(n: int) -> np.ndarray:
    """Each node receives from its predecessor: edge (i-1) → i."""
    adj = np.zeros((n, n))
    ids = np.arange(n)
    adj[ids, (ids - 1) % n] = 1.0
    np.fill_diagonal(adj, 0.0)  # n == 1 edge case
    return adj


def _directed_erdos_renyi_adjacency(n: int, p: float, seed: int) -> np.ndarray:
    """Strongly connected directed G(n, p): each ORDERED pair (j → i) draws
    independently, resampled until every node reaches every other (checked
    as reachability from node 0 along both edge orientations)."""
    rng = np.random.default_rng(seed)
    for _ in range(1000):
        adj = (rng.random((n, n)) < p).astype(float)
        np.fill_diagonal(adj, 0.0)
        # Strong connectivity ⟺ node 0 reaches all (follow in-edges of the
        # receive convention = walk adj as "i reachable from j") and all
        # reach node 0 (same walk on the transpose).
        if _is_connected_directed(adj) and _is_connected_directed(adj.T):
            return adj
    raise RuntimeError(
        f"Could not sample a strongly connected directed G({n}, {p}) in 1000 tries"
    )


def _is_connected_directed(adj: np.ndarray) -> bool:
    """All nodes reachable from node 0 following edges j → i (adj[i, j])."""
    n = adj.shape[0]
    if n == 0:
        return False
    reached = np.zeros(n, dtype=bool)
    frontier = [0]
    reached[0] = True
    while frontier:
        j = frontier.pop()
        for i in np.nonzero(adj[:, j])[0]:
            if not reached[i]:
                reached[i] = True
                frontier.append(int(i))
    return bool(reached.all())


def _is_connected(adj: np.ndarray) -> bool:
    n = adj.shape[0]
    if n == 0:
        return False
    reached = np.zeros(n, dtype=bool)
    frontier = [0]
    reached[0] = True
    while frontier:
        i = frontier.pop()
        for j in np.nonzero(adj[i])[0]:
            if not reached[j]:
                reached[j] = True
                frontier.append(int(j))
    return bool(reached.all())


def neighbor_table(adjacency: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Static padded neighbor-index table of an undirected 0/1 adjacency.

    Returns ``(nbr_idx [N, k_max] int32, nbr_mask [N, k_max] bool)``: row i
    lists i's neighbors in ascending index order (the same order a dense
    axis-1 reduction visits them, so gather-form aggregations sum in the
    identical order as their dense twins); padded slots point at i itself
    (an always-in-bounds gather target) with mask False. ``k_max`` is the
    maximum degree — the whole point of the gather path is that sorts and
    reductions then run over k_max+1 values instead of N
    (``ops/robust_aggregation.py::make_gather_robust_aggregator``, and
    the single-kernel fused twin that consumes the same table entirely
    in VMEM, ``ops/pallas_kernels.py::make_fused_robust_aggregator``).

    Host-side like everything in this module: built once per run, outside
    ``jit``. Directed graphs are rejected — the degree-bounded screening
    path is undirected-only (robust aggregation composes only with MH
    gossip; the directed/push-sum family rejects Byzantine injection).
    """
    A = np.asarray(adjacency)
    if not np.array_equal(A, A.T):
        raise ValueError(
            "neighbor_table expects an undirected (symmetric) adjacency; "
            "the degree-bounded gather path has no directed form"
        )
    n = A.shape[0]
    k_max = max(int(A.sum(axis=1).max()), 1) if n else 1
    nbr_idx = np.tile(np.arange(n, dtype=np.int32)[:, None], (1, k_max))
    nbr_mask = np.zeros((n, k_max), dtype=bool)
    for i in range(n):
        nbrs = np.nonzero(A[i])[0]
        nbr_idx[i, : len(nbrs)] = nbrs
        nbr_mask[i, : len(nbrs)] = True
    return nbr_idx, nbr_mask


def incident_edge_slots(
    nbr_idx: np.ndarray, nbr_mask: np.ndarray, edge_index: np.ndarray
) -> np.ndarray:
    """[N, k_max] int32 map from (node, neighbor-slot) to undirected edge id.

    ``edge_index`` is the [E, 2] i<j edge list a fault timeline indexes
    (``parallel/faults.py``); entry (i, s) is the id of edge
    {i, nbr_idx[i, s]} — each edge appears in BOTH endpoints' rows, so a
    per-edge liveness bit gathered through this table lands symmetrically,
    exactly like the dense scatter ``A[ei, ej] = A[ej, ei] = up[e]``.
    Padded slots map to 0 (masked out by ``nbr_mask`` downstream).
    """
    edge_id = {
        (int(i), int(j)): e for e, (i, j) in enumerate(edge_index)
    }
    slots = np.zeros(nbr_idx.shape, dtype=np.int32)
    for i in range(nbr_idx.shape[0]):
        for s in range(nbr_idx.shape[1]):
            if nbr_mask[i, s]:
                j = int(nbr_idx[i, s])
                slots[i, s] = edge_id[(min(i, j), max(i, j))]
    return slots


def metropolis_hastings_weights(adjacency: np.ndarray) -> np.ndarray:
    """Metropolis-Hastings mixing matrix from an adjacency matrix.

    W_ij = 1 / (1 + max(deg_i, deg_j)) for edges, W_ii = 1 - Σ_j W_ij.
    Parity: reference trainer.py:118-126. Vectorized instead of the
    reference's per-neighbor Python loops.
    """
    degrees = adjacency.sum(axis=1)
    pairwise_max = np.maximum(degrees[:, None], degrees[None, :])
    W = adjacency / (1.0 + pairwise_max)
    np.fill_diagonal(W, 0.0)
    np.fill_diagonal(W, 1.0 - W.sum(axis=1))
    return W


def column_stochastic_weights(adjacency: np.ndarray) -> np.ndarray:
    """Uniform-out-weight column-stochastic mixing matrix (push-sum gossip).

    Node j splits its mass equally over its out-neighbors and itself:
    A_ij = 1/(1 + outdeg_j) for every edge j → i and for i = j. Columns sum
    to 1 by construction (mass conservation — the invariant push-sum's
    weight debiasing rests on, Nedić-Olshevsky 2016 §II). This is the
    standard construction when nodes know only their OUT-degree, the honest
    information model for asymmetric links.
    """
    out_degrees = adjacency.sum(axis=0)
    A = adjacency / (1.0 + out_degrees[None, :])
    np.fill_diagonal(A, 1.0 / (1.0 + out_degrees))
    return A


def build_topology(
    name: str,
    n: int,
    *,
    erdos_renyi_p: float = 0.4,
    seed: int = 0,
) -> Topology:
    """Build a named topology over ``n`` workers.

    Undirected names get MH mixing weights; directed names
    (``directed_ring``, ``directed_erdos_renyi``) get column-stochastic
    uniform-out weights (the push-sum setting).
    """
    if name in ("directed_ring", "directed_erdos_renyi"):
        adj = (
            _directed_ring_adjacency(n)
            if name == "directed_ring"
            else _directed_erdos_renyi_adjacency(n, erdos_renyi_p, seed)
        )
        topo = Topology(
            name=name,
            n=n,
            adjacency=adj,
            degrees=adj.sum(axis=0),  # out-degrees (column sums)
            mixing_matrix=column_stochastic_weights(adj),
            directed=True,
        )
        topo.validate()
        return topo

    grid_shape: Optional[tuple[int, int]] = None
    if name == "ring":
        adj = _ring_adjacency(n)
    elif name == "grid":
        side = int(math.isqrt(n))
        if side * side != n:
            # Parity: reference trainer.py:100-102 raises for non-square N.
            raise ValueError(f"grid topology requires a perfect square, got {n}")
        adj = _torus_adjacency(side, side)
        grid_shape = (side, side)
    elif name == "fully_connected":
        adj = np.ones((n, n)) - np.eye(n)
    elif name == "erdos_renyi":
        adj = _erdos_renyi_adjacency(n, erdos_renyi_p, seed)
    elif name == "chain":
        adj = _chain_adjacency(n)
    elif name == "star":
        adj = _star_adjacency(n)
    else:
        raise ValueError(f"Unknown topology: {name!r}")

    topo = Topology(
        name=name,
        n=n,
        adjacency=adj,
        degrees=adj.sum(axis=1),
        mixing_matrix=metropolis_hastings_weights(adj),
        grid_shape=grid_shape,
    )
    topo.validate()
    return topo


def ring_spectral_gap_closed_form(n: int) -> float:
    """Closed-form spectral gap of the MH ring (all degrees 2 ⇒ W_ij = 1/3).

    Eigenvalues of W are (1 + 2cos(2πk/n))/3; ρ = max_{k≠0} |λ_k|.
    Matches the report's §III-A value 0.0209 for n = 25.
    """
    if n < 3:
        return 1.0
    lambdas = (1.0 + 2.0 * np.cos(2.0 * np.pi * np.arange(1, n) / n)) / 3.0
    return float(1.0 - np.max(np.abs(lambdas)))


def directed_ring_spectral_gap_closed_form(n: int) -> float:
    """Closed-form spectral gap of the uniform-out directed ring.

    Out-degree 1 everywhere ⇒ A = (I + P)/2 with P the cyclic shift.
    Eigenvalues are (1 + e^{2πik/n})/2 with modulus cos(πk/n), so
    ρ = cos(π/n) and the gap is 1 − cos(π/n) ≈ π²/(2n²).
    """
    if n < 2:
        return 1.0
    return float(1.0 - np.cos(np.pi / n))


def torus_spectral_gap_closed_form(side: int) -> float:
    """Closed-form spectral gap of the MH torus (degree 4 ⇒ off-diag 1/5).

    Eigenvalues are (1 + 2cos(2πj/s) + 2cos(2πk/s))/5 over j,k.
    Matches the report's §III-A value 0.2764 for s = 5.
    """
    js = np.arange(side)
    cj = 2.0 * np.cos(2.0 * np.pi * js / side)
    lam = (1.0 + cj[:, None] + cj[None, :]) / 5.0
    lam = lam.ravel()
    lam_sorted = np.sort(np.abs(lam))
    return float(1.0 - lam_sorted[-2])
