"""Communication-graph topologies and Metropolis-Hastings mixing matrices.

Capability parity with the reference's topology + mixing-matrix builder
(reference ``trainer.py:91-136``): ring, periodic 2-D grid (torus), and
fully-connected graphs with Metropolis-Hastings gossip weights
``W_ij = 1/(1 + max(deg_i, deg_j))`` and self-weight = row remainder, plus the
same invariants (row-stochastic, symmetric) and the spectral gap ``1 - ρ``
from the second-largest absolute eigenvalue.

Extensions beyond the reference: Erdős–Rényi random graphs (the BASELINE.json
decentralized-ADMM config), chain (path), and star topologies; and a
*stencil* description (shift offsets + weights) for the topologies whose
mixing step maps onto TPU ICI as `ppermute` neighbor shifts instead of a dense
``W @ models`` matmul — ring/chain/torus are the cases where the communication
graph embeds directly into the pod mesh.

This module is host-side (numpy): topologies are built once per run, outside
``jit``. The compiled mixing operators that consume them live in
``ops/mixing.py`` and ``parallel/collectives.py``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class Topology:
    """An undirected communication graph plus its gossip structure."""

    name: str
    n: int
    adjacency: np.ndarray  # [N, N] 0/1, zero diagonal
    degrees: np.ndarray  # [N]
    mixing_matrix: np.ndarray  # [N, N] Metropolis-Hastings, row-stochastic, symmetric
    grid_shape: Optional[tuple[int, int]] = None  # set for 'grid'

    @property
    def spectral_gap(self) -> float:
        """1 - ρ where ρ is the second-largest |eigenvalue| of W.

        Parity: reference trainer.py:133-135. Closed-form values for the
        report setup: ring(25) ≈ 0.0209, 5x5 torus ≈ 0.2764, fc = 1.0.
        """
        if self.n < 2:
            return 1.0
        eigs = np.sort(np.abs(np.linalg.eigvalsh(self.mixing_matrix)))
        return float(1.0 - eigs[-2])

    @property
    def floats_per_iteration(self) -> float:
        """Analytic gossip cost in floats per iteration per model dimension.

        One gossip round sends each worker's model to each of its neighbors:
        Σ_i deg_i values per model coordinate (reference trainer.py:169-170).
        Multiply by d (and by rounds-per-iteration for two-mix algorithms).
        """
        return float(np.sum(self.degrees))

    def validate(self) -> None:
        """Invariant checks (parity: reference trainer.py:128-131 asserts)."""
        W = self.mixing_matrix
        if not np.allclose(W.sum(axis=1), 1.0):
            raise AssertionError(f"Mixing matrix rows must sum to 1 ({self.name})")
        if not np.allclose(W, W.T):
            raise AssertionError(f"Mixing matrix must be symmetric ({self.name})")
        if np.any(W < -1e-12):
            raise AssertionError(f"Mixing matrix must be nonnegative ({self.name})")


def _ring_adjacency(n: int) -> np.ndarray:
    adj = np.zeros((n, n))
    ids = np.arange(n)
    adj[ids, (ids + 1) % n] = 1.0
    adj[ids, (ids - 1) % n] = 1.0
    np.fill_diagonal(adj, 0.0)  # n == 1, 2 edge cases
    return adj


def _chain_adjacency(n: int) -> np.ndarray:
    adj = np.zeros((n, n))
    ids = np.arange(n - 1)
    adj[ids, ids + 1] = 1.0
    adj[ids + 1, ids] = 1.0
    return adj


def _star_adjacency(n: int) -> np.ndarray:
    adj = np.zeros((n, n))
    adj[0, 1:] = 1.0
    adj[1:, 0] = 1.0
    return adj


def _torus_adjacency(rows: int, cols: int) -> np.ndarray:
    """Periodic 2-D grid. Worker (r, c) sits at index r*cols + c (row-major),
    matching the reference's sorted-node indexing of
    ``networkx.grid_2d_graph(periodic=True)`` (reference trainer.py:103-108)."""
    n = rows * cols
    adj = np.zeros((n, n))
    for r in range(rows):
        for c in range(cols):
            i = r * cols + c
            for rr, cc in ((r - 1, c), (r + 1, c), (r, c - 1), (r, c + 1)):
                j = (rr % rows) * cols + (cc % cols)
                if j != i:  # degenerate 1- or 2-length axes collapse neighbors
                    adj[i, j] = 1.0
    return adj


def _erdos_renyi_adjacency(n: int, p: float, seed: int) -> np.ndarray:
    """Connected Erdős–Rényi G(n, p): resample until connected."""
    rng = np.random.default_rng(seed)
    for _ in range(1000):
        upper = rng.random((n, n)) < p
        adj = np.triu(upper, k=1).astype(float)
        adj = adj + adj.T
        if _is_connected(adj):
            return adj
    raise RuntimeError(f"Could not sample a connected G({n}, {p}) in 1000 tries")


def _is_connected(adj: np.ndarray) -> bool:
    n = adj.shape[0]
    if n == 0:
        return False
    reached = np.zeros(n, dtype=bool)
    frontier = [0]
    reached[0] = True
    while frontier:
        i = frontier.pop()
        for j in np.nonzero(adj[i])[0]:
            if not reached[j]:
                reached[j] = True
                frontier.append(int(j))
    return bool(reached.all())


def metropolis_hastings_weights(adjacency: np.ndarray) -> np.ndarray:
    """Metropolis-Hastings mixing matrix from an adjacency matrix.

    W_ij = 1 / (1 + max(deg_i, deg_j)) for edges, W_ii = 1 - Σ_j W_ij.
    Parity: reference trainer.py:118-126. Vectorized instead of the
    reference's per-neighbor Python loops.
    """
    degrees = adjacency.sum(axis=1)
    pairwise_max = np.maximum(degrees[:, None], degrees[None, :])
    W = adjacency / (1.0 + pairwise_max)
    np.fill_diagonal(W, 0.0)
    np.fill_diagonal(W, 1.0 - W.sum(axis=1))
    return W


def build_topology(
    name: str,
    n: int,
    *,
    erdos_renyi_p: float = 0.4,
    seed: int = 0,
) -> Topology:
    """Build a named topology over ``n`` workers, with MH mixing weights."""
    grid_shape: Optional[tuple[int, int]] = None
    if name == "ring":
        adj = _ring_adjacency(n)
    elif name == "grid":
        side = int(math.isqrt(n))
        if side * side != n:
            # Parity: reference trainer.py:100-102 raises for non-square N.
            raise ValueError(f"grid topology requires a perfect square, got {n}")
        adj = _torus_adjacency(side, side)
        grid_shape = (side, side)
    elif name == "fully_connected":
        adj = np.ones((n, n)) - np.eye(n)
    elif name == "erdos_renyi":
        adj = _erdos_renyi_adjacency(n, erdos_renyi_p, seed)
    elif name == "chain":
        adj = _chain_adjacency(n)
    elif name == "star":
        adj = _star_adjacency(n)
    else:
        raise ValueError(f"Unknown topology: {name!r}")

    topo = Topology(
        name=name,
        n=n,
        adjacency=adj,
        degrees=adj.sum(axis=1),
        mixing_matrix=metropolis_hastings_weights(adj),
        grid_shape=grid_shape,
    )
    topo.validate()
    return topo


def ring_spectral_gap_closed_form(n: int) -> float:
    """Closed-form spectral gap of the MH ring (all degrees 2 ⇒ W_ij = 1/3).

    Eigenvalues of W are (1 + 2cos(2πk/n))/3; ρ = max_{k≠0} |λ_k|.
    Matches the report's §III-A value 0.0209 for n = 25.
    """
    if n < 3:
        return 1.0
    lambdas = (1.0 + 2.0 * np.cos(2.0 * np.pi * np.arange(1, n) / n)) / 3.0
    return float(1.0 - np.max(np.abs(lambdas)))


def torus_spectral_gap_closed_form(side: int) -> float:
    """Closed-form spectral gap of the MH torus (degree 4 ⇒ off-diag 1/5).

    Eigenvalues are (1 + 2cos(2πj/s) + 2cos(2πk/s))/5 over j,k.
    Matches the report's §III-A value 0.2764 for s = 5.
    """
    js = np.arange(side)
    cj = 2.0 * np.cos(2.0 * np.pi * js / side)
    lam = (1.0 + cj[:, None] + cj[None, :]) / 5.0
    lam = lam.ravel()
    lam_sorted = np.sort(np.abs(lam))
    return float(1.0 - lam_sorted[-2])
