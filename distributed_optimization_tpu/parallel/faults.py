"""Failure injection: time-varying gossip over dropped edges and stragglers.

The reference has no failure model — its synchronous lockstep loop cannot
lose a worker (SURVEY.md §5.3); its report only *discusses* the parameter
server as a single point of failure. Here two failure modes are first-class,
jit-compatible simulations:

- **link failure** (``drop_prob``): each iteration, every edge of the base
  topology independently drops with probability p (a symmetric draw — both
  endpoints agree the link is down);
- **stragglers / node failure** (``straggler_prob``): each iteration, every
  node independently sits the round out with probability q — it exchanges
  nothing (all incident edges drop) and, in the backend, its state is frozen
  for the iteration (no local gradient step either).

Both of those are MEMORYLESS per-iteration coin flips. Real decentralized
systems fail in *bursts*: links flap for stretches and nodes crash, stay
down for many rounds, then rejoin with stale state. Two PERSISTENT
(temporally-correlated) fault processes share the same interface:

- **bursty link failures** (``burst_len >= 1``): each edge follows an
  independent two-state Markov chain (Gilbert '60 / Elliott '63 channel
  model) parameterized by the MARGINAL drop rate ``drop_prob`` plus the
  burst-length multiplier ``burst_len``.  The transition thresholds are
  P(down_t | up_{t-1}) = p/B and P(down_t | down_{t-1}) = 1 − (1−p)/B, so
  the stationary drop rate is exactly p for EVERY B (matched-marginal by
  construction) while the mean burst length is B/(1−p) — B times the iid
  chain's.  B = 1 makes both thresholds p, i.e. next-state independent of
  current state: the chain consumes the SAME uniform draws as the iid
  sampler and compares them against the SAME threshold, so ``burst_len=1``
  reduces *bitwise* to today's iid edge drops.
- **crash–recovery churn** (``mttf``/``mttr``): each node follows a
  two-state Markov chain with geometric holding times — mean up-time
  ``mttf`` rounds (P(crash) = 1/mttf) and mean outage ``mttr`` rounds
  (P(stay down) = 1 − 1/mttr); stationary downtime = mttr/(mttf+mttr).
  Down nodes exchange nothing and take no local step (the straggler freeze,
  now spanning whole outages).  The iid straggler model is the point
  mttf = 1/q, mttr = 1/(1−q) — both thresholds collapse to q, consuming
  the same draws as the iid straggler sampler, so those values reduce
  *bitwise* to ``straggler_prob=q``.  The ``rejoin`` policy decides what a
  node resumes with after an outage: ``'frozen'`` keeps its stale
  pre-crash state (the staleness stress test — this is what plain freeze
  gives for free), ``'neighbor_restart'`` warm-restarts its model row from
  the realized-neighborhood average on the rejoin round (trading exact
  average preservation for a consensus reset after long outages).

Persistent processes are realized as PRECOMPUTED ``[horizon]``-indexed
fault timelines (``build_fault_timeline``): the per-(iteration, edge/node)
uniform draws still come purely from (seed, t) via counter-based keys —
identical to the on-the-fly samplers — but the chain state is unrolled once
at setup into host arrays, so per-iteration access is a jit-gatherable
``timeline[t]`` with NO carried RNG or chain state.  Checkpoint/resume
therefore stays exact (a resumed run rebuilds the identical timeline from
the config), and the numpy oracle backend consumes the SAME timeline while
implementing all mask/weight math independently.  Why correlated faults
matter at the same marginal rate: the time-varying-gossip analyses this
repo leans on (Koloskova et al. '20 undirected; Nedić–Olshevsky '16
directed) bound convergence by WINDOWED connectivity (every B-window's
union graph connected), not by the marginal drop rate — bursts stretch the
effective window B̂ (see ``windowed_connectivity``), so convergence
degrades with burst length even though the average number of dropped edges
is identical.  ``examples/bench_churn.py`` measures exactly that.

A third *scheduling* mode shares the machinery:

- **one-peer randomized gossip** (``one_peer=True``): instead of averaging
  with ALL surviving neighbors, each node proposes one uniformly random
  neighbor and an edge activates iff the proposal is mutual (Boyd et al.
  '06 randomized gossip, pairwise-averaging form). The realized W_t is
  0.5·(I + P_t) for the involution P_t of matched pairs — each node
  exchanges at most ONE model per iteration, the extreme
  communication-frugality point of the gossip spectrum.

Synchronous gossip runs over the surviving graph with Metropolis–Hastings
weights recomputed on realized degrees; an isolated or inactive node's row
collapses to identity. DIRECTED topologies (round 5) instead drop each
one-way link independently and renormalize each node's surviving
OUT-weights column-stochastically (``column_stochastic_weights``) — the
Nedić-Olshevsky time-varying directed setting push-sum is analyzed under;
every realization conserves total mass (columns sum to 1), which is the
invariant push-sum's debiasing needs, in place of the undirected case's
doubly stochastic average preservation. For UNDIRECTED topologies (synchronous MH recomputation and every matching
schedule) this is the time-varying-graph setting of Koloskova et al. '20
(reference report ref [13]): W_t stays symmetric and doubly stochastic for
every realization, so the network average is preserved and D-SGD and
DIGing-style gradient tracking remain convergent under their
time-varying-gossip analyses — the directed path above intentionally trades
that invariant for column-stochastic mass conservation. For gradient tracking this is not just the
citation: the tracking invariant mean(y_t) = mean(g_t) survives every fault
mode because (a) each realized W_t is doubly stochastic and (b) the
backend's straggler freeze covers ALL state leaves with the frozen node's
mixing row collapsed to identity — verified numerically to accumulation
roundoff through the real backend paths
(tests/test_faults.py::test_gt_tracking_invariant_survives_faults) and
measured on-chip (examples/bench_faults.py gt_* rows). EXTRA does NOT
compose (its fixed-point argument needs a static W — it is rejected
alongside ADMM/CHOCO, see ``Algorithm.supports_edge_faults``).

Fault masks, realized adjacencies, MH weights, and the realized-floats
accounting are always computed in float32 regardless of the run dtype:
under bfloat16 (8 mantissa bits) edge counts above ~256 quantize and MH row
sums pick up off-by-ulp mass, corrupting both the mixing invariants and the
"honest" comms metric. Only the mixed MODEL values are cast to the run
dtype.

Masks are derived purely from (fault key, iteration) — like batch sampling,
fault realizations are reproducible and checkpoint/resume-safe with no
carried RNG state.  The underlying uniform draws are EXPLICIT float32
(independent of the run dtype and of x64 mode), so the same (seed, t)
yields the same fault realization on every backend and in every precision —
the property the timeline precompute and the numpy-oracle parity rely on.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_optimization_tpu.parallel.topology import Topology

# Allowed rejoin policies after a crash-recovery outage (config and CLI
# derive from this constant): 'frozen' resumes the stale pre-crash state,
# 'neighbor_restart' warm-restarts the model row from the realized-
# neighborhood average on the rejoin round.
REJOIN_POLICIES = ("frozen", "neighbor_restart")


@dataclasses.dataclass(frozen=True)
class FaultyMixing:
    """Per-iteration mixing operators over a randomly failing topology.

    ``mix(t, x)``: W_t x with W_t the MH matrix of the surviving graph.
    ``neighbor_sum(t, x)``: A_t x over surviving edges.
    ``realized_degree_sum(t)``: Σ realized deg_i at iteration t (multiply by
    the per-edge payload downstream for the floats-transmitted metric).
    ``active(t)``: [N] 0/1 node-participation mask (all-ones when
    straggler_prob == 0); the backend freezes inactive rows for the step.
    """

    mix: Callable[[jax.Array, jax.Array], jax.Array]
    neighbor_sum: Callable[[jax.Array, jax.Array], jax.Array]
    realized_degree_sum: Callable[[jax.Array], jax.Array]
    active: Callable[[jax.Array], jax.Array]
    drop_prob: float
    straggler_prob: float
    # ``realized_adjacency(t)``: the surviving [N, N] 0/1 graph at t —
    # consumed by the Byzantine robust-aggregation layer so attacks and
    # defenses run over the same per-iteration graph as the mixing. None
    # for matching schedules (one_peer/round_robin), whose single-partner
    # exchanges cannot realize a screening budget (config rejects the
    # combination).
    realized_adjacency: Optional[Callable[[jax.Array], jax.Array]] = None
    # ``make_neighbor_liveness(nbr_idx, nbr_mask)``: build the GATHER form
    # of the realized adjacency for the degree-bounded robust-aggregation
    # path — returns ``live(t) -> [N, k_max]`` float32 per-incident-edge
    # liveness bits over the topology's static padded neighbor table
    # (``parallel/topology.py::neighbor_table``). Bit-for-bit the same
    # realization as ``realized_adjacency(t)`` gathered per slot: the
    # timeline path indexes the precomputed [horizon, E] edge chains
    # through a (node, slot) → edge-id table instead of scattering a dense
    # [N, N] matrix; the memoryless path consumes the SAME counter-based
    # (seed, t) uniform draw as the dense sampler, gathered at the slot's
    # (i, j) entry. None for matching schedules (no screening budget is
    # realizable) and directed graphs (no gather screening path).
    make_neighbor_liveness: Optional[Callable[..., Callable]] = None
    # --- persistent fault processes (None/0/False when memoryless) ---
    # Crash-recovery churn is active (the backend must freeze DOWN nodes'
    # state, exactly like stragglers, for the whole outage).
    churn_active: bool = False
    # Rejoin policy in force ('frozen' needs no machinery beyond the
    # freeze; 'neighbor_restart' supplies ``rejoin_restart``).
    rejoin: str = "frozen"
    # ``rejoin_restart(t, x)``: on rejoin rounds, replace a rejoining
    # node's model row with its realized-neighborhood average (rows of
    # nodes that are not rejoining — or have no realized neighbors — pass
    # through untouched). None unless rejoin == 'neighbor_restart'.
    rejoin_restart: Optional[Callable[[jax.Array, jax.Array], jax.Array]] = None
    # Per-round partial participation (client sampling, docs/PERF.md §14)
    # is active: ``active(t)`` composes the presampled participation mask
    # into the node-availability row, and the backend must freeze
    # sampled-out nodes' state exactly like stragglers.
    participation_active: bool = False
    # The host-side precomputed timeline backing this mixing (None on the
    # memoryless on-the-fly path) — exposed for diagnostics
    # (``node_downtime``, ``windowed_connectivity``) and tests.
    timeline: Optional["FaultTimeline"] = None


@dataclasses.dataclass(frozen=True)
class FaultTimeline:
    """Precomputed ``[horizon]``-indexed fault realizations (host arrays).

    Pure function of (topology, horizon, seed, fault params): the uniform
    draw at (t, edge/node) is the same counter-based float32 draw the
    on-the-fly samplers consume, with the Markov chain state unrolled once
    at build time — so lookups are jit-gatherable and resume-exact with no
    carried RNG.  ``edge_up[t, e]`` indexes the base topology's edge list
    ``edge_index`` ([E, 2]; i<j rows for undirected graphs, ordered (i, j)
    receiver/sender pairs for directed ones).  ``node_up[t, i]`` is node
    availability; ``rejoin[t, i]`` marks the first up-round after an
    outage.  Entries are None for fault modes that are not active.
    """

    horizon: int
    directed: bool
    edge_index: Optional[np.ndarray] = None  # [E, 2] int32
    edge_up: Optional[np.ndarray] = None     # [horizon, E] bool
    node_up: Optional[np.ndarray] = None     # [horizon, N] bool
    rejoin: Optional[np.ndarray] = None      # [horizon, N] bool
    # Per-round participation mask (client sampling, iid per (round,
    # node) at rate ``participation_rate`` from its own key stream;
    # docs/PERF.md §14). Composes with ``node_up`` by AND: a round's
    # realized availability is churn-up AND sampled-in. Sampling is NOT
    # an outage — no rejoin events — so ``rejoin`` stays a pure
    # crash-recovery record.
    part_up: Optional[np.ndarray] = None     # [horizon, N] bool


def sample_surviving_adjacency(key, adjacency: jax.Array, drop_prob: float):
    """Symmetric iid edge-drop mask applied to a 0/1 adjacency matrix.

    Draws are explicit float32 regardless of x64 mode, so the realization
    is a function of (key, shape) alone — the timeline precompute and the
    numpy oracle reproduce it bit-for-bit under any run dtype."""
    n = adjacency.shape[0]
    u = jax.random.uniform(key, (n, n), dtype=jnp.float32)
    u = jnp.triu(u, 1)
    u = u + u.T  # symmetric: both endpoints see the same draw
    return jnp.where(u >= drop_prob, adjacency, jnp.zeros_like(adjacency))


def sample_surviving_directed_adjacency(
    key, adjacency: jax.Array, drop_prob: float
):
    """Independent iid drop per DIRECTED edge (no symmetrization).

    Unlike the undirected sampler, the j→i and i→j links (when both exist)
    fail independently — one-way links are exactly what the directed fault
    setting models (Nedić-Olshevsky 2016 time-varying directed graphs)."""
    u = jax.random.uniform(key, adjacency.shape, dtype=jnp.float32)
    return jnp.where(u >= drop_prob, adjacency, jnp.zeros_like(adjacency))


def column_stochastic_weights(adjacency: jax.Array) -> jax.Array:
    """Uniform-out-weight column-stochastic matrix for a realized directed
    graph (jit-compatible).

    Each node j re-splits its mass equally over its SURVIVING out-neighbors
    and itself: W_ij = 1/(1 + outdeg_j) on realized edges, diagonal = the
    column remainder (exactly 1/(1 + outdeg_j), so an isolated node keeps
    all its mass). Convention matches ``parallel/topology.py``:
    ``adjacency[i, j] = 1`` iff j sends to i, so out-degrees are COLUMN
    sums and ``W @ x`` aggregates received mass. This is the same rule the
    static directed topology builder uses, recomputed per realization — the
    sender-side renormalization push-sum's time-varying-directed analysis
    assumes (each node knows which of its out-links delivered). Columns sum
    to 1 for every realization, so Σ_i (Wx)_i = Σ_j x_j: the mass
    conservation push-sum's debiasing relies on survives every fault draw.
    """
    out_deg = jnp.sum(adjacency, axis=0)
    W = adjacency / (1.0 + out_deg)[None, :]
    return W + jnp.diag(1.0 - jnp.sum(W, axis=0))


def metropolis_hastings_weights(adjacency: jax.Array) -> jax.Array:
    """MH mixing matrix for an arbitrary 0/1 adjacency (jit-compatible).

    W_ij = 1/(1 + max(d_i, d_j)) on edges, diagonal = row remainder — the
    same rule the static topology builder uses (reference
    ``trainer.py:118-126``), but recomputed on-device for each realization.
    Symmetric and doubly stochastic for any undirected graph, including
    isolated nodes (row collapses to W_ii = 1).
    """
    deg = jnp.sum(adjacency, axis=1)
    pair = 1.0 / (1.0 + jnp.maximum(deg[:, None], deg[None, :]))
    W = adjacency * pair
    return W + jnp.diag(1.0 - jnp.sum(W, axis=1))


def _matching_ops(partner_fn):
    """Mixing closures for any matching schedule given partner_fn(t).

    W_t = 0.5 (I + P_t): pairwise averaging with the matched peer (identity
    row for unmatched nodes). Shared by the one-peer randomized and
    round-robin deterministic schedules.
    """

    def mix(t, x):
        return (0.5 * (x + x[partner_fn(t)])).astype(x.dtype)

    def neighbor_sum(t, x):
        p = partner_fn(t)
        matched = (p != jnp.arange(p.shape[0])).astype(x.dtype)
        return (x[p] * matched.reshape((-1,) + (1,) * (x.ndim - 1))).astype(
            x.dtype
        )

    def realized_degree_sum(t):
        # float32 regardless of run dtype: the downstream floats accounting
        # multiplies by the payload and sums over chunks, which overflows
        # int32 at scale and quantizes above ~256 in bfloat16.
        p = partner_fn(t)
        return jnp.sum((p != jnp.arange(p.shape[0])).astype(jnp.float32))

    return mix, neighbor_sum, realized_degree_sum


def make_round_robin_mixing(topo: Topology) -> FaultyMixing:
    """Deterministic matching schedule (``parallel/matchings.py`` phases) as
    time-varying mixing ops, same interface as ``make_faulty_mixing``."""
    from distributed_optimization_tpu.parallel.matchings import (
        round_robin_partners,
    )

    partners = jnp.asarray(round_robin_partners(topo), dtype=jnp.int32)
    n_phases, n = partners.shape
    mix, neighbor_sum, realized_degree_sum = _matching_ops(
        lambda t: partners[t % n_phases]
    )
    return FaultyMixing(
        mix=mix,
        neighbor_sum=neighbor_sum,
        realized_degree_sum=realized_degree_sum,
        active=lambda t: jnp.ones(n, dtype=jnp.float32),
        drop_prob=0.0,
        straggler_prob=0.0,
    )


def sample_one_peer_matching(key, adjacency: jax.Array) -> jax.Array:
    """Mutual-proposal random matching: partner[i] (an involution; self if
    unmatched). Each node proposes a uniformly random neighbor; an edge
    activates iff both endpoints proposed each other."""
    n = adjacency.shape[0]
    idx = jnp.arange(n)
    scores = (
        jax.random.uniform(key, adjacency.shape, dtype=jnp.float32)
        * adjacency
    )
    prop = jnp.argmax(scores, axis=1)
    # Isolated rows (all-zero scores) would spuriously propose node 0.
    prop = jnp.where(jnp.sum(adjacency, axis=1) > 0, prop, idx)
    mutual = prop[prop] == idx
    return jnp.where(mutual, prop, idx)


def iid_equivalent_churn(straggler_prob: float) -> tuple[float, float]:
    """The (mttf, mttr) point at which crash-recovery churn reduces bitwise
    to iid stragglers at rate q: both chain thresholds collapse to q when
    mttf = 1/q and mttr = 1/(1−q) (stationary downtime exactly q)."""
    if not 0.0 < straggler_prob < 1.0:
        raise ValueError(
            f"straggler_prob must be in (0, 1), got {straggler_prob}"
        )
    return 1.0 / straggler_prob, 1.0 / (1.0 - straggler_prob)


def _edge_list(topo: Topology) -> np.ndarray:
    """[E, 2] int32 edge list of the base topology: one row per undirected
    edge (i < j — the triu entry whose draw both endpoints share in the iid
    sampler), or per one-way link (i, j) for directed graphs.

    Matrix-free topologies enumerate the same i < j rows from the
    neighbor table without touching a dense [N, N] array (used by the
    connectivity diagnostics; per-edge fault PROCESSES stay dense-only).
    """
    if topo.is_matrix_free:
        rows, slots = np.nonzero(topo.nbr_mask)
        js = topo.nbr_idx[rows, slots]
        keep = rows < js  # each undirected edge once, i < j
        return np.stack([rows[keep], js[keep]], axis=1).astype(np.int32)
    A = np.asarray(topo.adjacency)
    src = np.triu(A, 1) if not topo.directed else A
    ei, ej = np.nonzero(src)
    return np.stack([ei, ej], axis=1).astype(np.int32)


def config_faults_active(config) -> bool:
    """Whether this config runs ANY synchronous node/edge fault process —
    the single definition shared by every consumer that decides to
    rebuild a timeline from a config (live-B̂ heartbeats, the health
    block's realized B̂, incident forensics)."""
    return (
        config.edge_drop_prob > 0.0
        or config.straggler_prob > 0.0
        or config.mttf > 0.0
        or config.participation_rate < 1.0
    )


def timeline_for_config(config, topo: Topology, horizon: int,
                        seed=None) -> FaultTimeline:
    """The canonical config → ``build_fault_timeline`` parameter mapping.

    This mapping IS the bitwise purity contract: the timeline a consumer
    rebuilds host-side (telemetry's realized B̂, the live-B̂ heartbeat
    probe, incident forensics, the replica-batched stacker) must be the
    realization the backend executed, so the burst clamp and the
    straggler-vs-churn exclusivity rule live in exactly one place.
    ``seed`` overrides ``config.seed`` (the replica-batched path passes
    per-replica seeds).
    """
    return build_fault_timeline(
        topo, horizon, config.seed if seed is None else seed,
        edge_drop_prob=config.edge_drop_prob,
        burst_len=config.burst_len if config.burst_len >= 1.0 else 1.0,
        straggler_prob=(
            0.0 if config.mttf > 0.0 else config.straggler_prob
        ),
        mttf=config.mttf, mttr=config.mttr,
        participation_rate=config.participation_rate,
    )


def build_fault_timeline(
    topo: Topology,
    horizon: int,
    seed: int,
    *,
    edge_drop_prob: float = 0.0,
    burst_len: float = 1.0,
    straggler_prob: float = 0.0,
    mttf: float = 0.0,
    mttr: float = 0.0,
    participation_rate: float = 1.0,
) -> FaultTimeline:
    """Unroll the per-edge / per-node fault chains into host arrays.

    The uniform draw at iteration t is the SAME counter-based float32 draw
    the on-the-fly samplers consume (same key derivation, same shape), so
    chains whose thresholds are state-independent — burst_len == 1, or
    churn at the ``iid_equivalent_churn`` point, or plain ``straggler_prob``
    — reproduce the iid samplers bit for bit.  Survival convention matches
    the samplers: alive iff u >= threshold, where

        edge thresholds:  P(down | up) = p/B,  P(down | down) = 1 − (1−p)/B
        node thresholds:  P(down | up) = 1/mttf, P(down | down) = 1 − 1/mttr
        (or both = q for iid stragglers)

    with the t = 0 state drawn from the stationary marginal (p, resp.
    mttr/(mttf+mttr)) so every burst level is matched-marginal from the
    first iteration.  Memory: one byte per (iteration, edge) plus one per
    (iteration, node) — [horizon, E] + [horizon, N] bool.
    """
    if horizon <= 0:
        raise ValueError(f"timeline horizon must be positive, got {horizon}")
    if burst_len < 1.0:
        raise ValueError(f"burst_len must be >= 1, got {burst_len}")
    if (mttf > 0.0) != (mttr > 0.0):
        raise ValueError("mttf and mttr must be set together")
    if mttf > 0.0 and (mttf < 1.0 or mttr < 1.0):
        raise ValueError(
            f"mttf/mttr are mean holding times in rounds and must be >= 1 "
            f"(got mttf={mttf}, mttr={mttr})"
        )
    if mttf > 0.0 and straggler_prob > 0.0:
        raise ValueError(
            "crash-recovery churn replaces iid stragglers; set one of "
            "(mttf, mttr) / straggler_prob, not both"
        )
    if not 0.0 < participation_rate <= 1.0:
        raise ValueError(
            f"participation_rate must be in (0, 1], got {participation_rate}"
        )
    n = topo.n
    fault_key = jax.random.fold_in(jax.random.key(seed), 0x0FA17)
    node_key = jax.random.fold_in(jax.random.key(seed), 0x57A66)
    ts = jnp.arange(horizon, dtype=jnp.int32)

    edge_index = None
    edge_up = None
    if edge_drop_prob > 0.0:
        edge_index = _edge_list(topo)
        p = edge_drop_prob
        if burst_len == 1.0:
            # State-independent thresholds — EXACTLY the iid comparison
            # (u >= p), guaranteeing the bitwise reduction regardless of
            # float rounding in the general-B formulas below.
            t_enter = t_stay = t_init = np.float32(p)
        else:
            t_enter = np.float32(p / burst_len)            # P(down | up)
            t_stay = np.float32(1.0 - (1.0 - p) / burst_len)  # P(down|down)
            t_init = np.float32(p)                          # stationary

        if topo.is_matrix_free:
            # Matrix-free edge chains (ISSUE-9 satellite): draw ONE
            # float32 uniform per edge per round — the dense path's
            # (n, n) matrix draw IS the quadratic object this
            # representation exists to avoid, so the matrix-free stream
            # is a different (equally seed-pure) realization of the same
            # chain; dense-vs-matrix-free parity tests inject one shared
            # timeline rather than relying on shared draws.
            n_edges = edge_index.shape[0]

            def edge_draw(t):
                return jax.random.uniform(
                    jax.random.fold_in(fault_key, t), (n_edges,),
                    dtype=jnp.float32,
                )
        else:
            ei = jnp.asarray(edge_index[:, 0])
            ej = jnp.asarray(edge_index[:, 1])

            def edge_draw(t):
                # The SAME symmetric (seed, t) matrix draw the on-the-fly
                # iid sampler consumes, read at the edge entries — what
                # makes burst_len=1 reduce bitwise to the memoryless path.
                return jax.random.uniform(
                    jax.random.fold_in(fault_key, t), (n, n),
                    dtype=jnp.float32,
                )[ei, ej]

        def edge_step(up_prev, t):
            u = edge_draw(t)
            thresh = jnp.where(
                t == 0, t_init, jnp.where(up_prev, t_enter, t_stay)
            )
            up = u >= thresh
            return up, up

        _, ups = jax.lax.scan(
            edge_step, jnp.ones(edge_index.shape[0], dtype=bool), ts
        )
        edge_up = np.asarray(ups)

    node_up = None
    rejoin = None
    if mttf > 0.0 or straggler_prob > 0.0:
        if mttf > 0.0:
            n_crash = np.float32(1.0 / mttf)           # P(down | up)
            n_stay = np.float32(1.0 - 1.0 / mttr)      # P(down | down)
            n_init = np.float32(mttr / (mttf + mttr))  # stationary downtime
        else:
            n_crash = n_stay = n_init = np.float32(straggler_prob)

        def node_step(up_prev, t):
            u = jax.random.uniform(
                jax.random.fold_in(node_key, t), (n,), dtype=jnp.float32
            )
            thresh = jnp.where(
                t == 0, n_init, jnp.where(up_prev, n_crash, n_stay)
            )
            up = u >= thresh
            return up, up

        _, nups = jax.lax.scan(node_step, jnp.ones(n, dtype=bool), ts)
        node_up = np.asarray(nups)
        prev_up = np.concatenate(
            [np.ones((1, n), dtype=bool), node_up[:-1]], axis=0
        )
        rejoin = node_up & ~prev_up

    part_up = None
    if participation_rate < 1.0:
        # Client sampling (docs/PERF.md §14): iid per (round, node) at the
        # configured rate, from its OWN counter-based stream — distinct
        # from the churn/straggler chain, so participation composes with
        # (never perturbs) every other fault realization. Survival
        # convention matches the node chain: in iff u >= 1 − rate.
        part_key = jax.random.fold_in(jax.random.key(seed), 0x9AC70)
        p_out = np.float32(1.0 - participation_rate)

        def part_step(_, t):
            u = jax.random.uniform(
                jax.random.fold_in(part_key, t), (n,), dtype=jnp.float32
            )
            return None, u >= p_out

        _, pups = jax.lax.scan(part_step, None, ts)
        part_up = np.asarray(pups)

    return FaultTimeline(
        horizon=horizon,
        directed=topo.directed,
        edge_index=edge_index,
        edge_up=edge_up,
        node_up=node_up,
        rejoin=rejoin,
        part_up=part_up,
    )


# --- availability / staleness diagnostics (host-side, over a timeline) ----


def node_downtime(timeline: FaultTimeline) -> np.ndarray:
    """Per-node fraction of rounds spent down over the timeline horizon."""
    if timeline.node_up is None:
        raise ValueError("timeline has no node fault process")
    return 1.0 - timeline.node_up.mean(axis=0)


def outage_stats(timeline: FaultTimeline) -> dict:
    """Aggregate outage statistics: count, mean and max outage length (in
    rounds) across all nodes — the staleness a ``frozen`` rejoin carries."""
    if timeline.node_up is None:
        raise ValueError("timeline has no node fault process")
    lengths: list[int] = []
    for i in range(timeline.node_up.shape[1]):
        run = 0
        for up in timeline.node_up[:, i]:
            if not up:
                run += 1
            elif run:
                lengths.append(run)
                run = 0
        if run:
            lengths.append(run)  # outage still open at the horizon
    return {
        "n_outages": len(lengths),
        "mean_outage_rounds": float(np.mean(lengths)) if lengths else 0.0,
        "max_outage_rounds": int(max(lengths)) if lengths else 0,
    }


def _realized_edge_alive(
    timeline: FaultTimeline, topo: Topology
) -> tuple[np.ndarray, np.ndarray]:
    """([T, E] bool alive-mask, [E, 2] edge list) of per-round realized
    edges: an edge is alive iff its link is up AND both endpoints are up."""
    edges = (
        timeline.edge_index
        if timeline.edge_index is not None
        else _edge_list(topo)
    )
    T = timeline.horizon
    alive = (
        timeline.edge_up.copy()
        if timeline.edge_up is not None
        else np.ones((T, edges.shape[0]), dtype=bool)
    )
    if timeline.node_up is not None:
        alive &= (
            timeline.node_up[:, edges[:, 0]]
            & timeline.node_up[:, edges[:, 1]]
        )
    if timeline.part_up is not None:
        # A sampled-out client exchanges nothing: its incident edges are
        # not realized that round, exactly like a down node's.
        alive &= (
            timeline.part_up[:, edges[:, 0]]
            & timeline.part_up[:, edges[:, 1]]
        )
    return alive, edges


def _union_connected(present: np.ndarray, edges: np.ndarray, n: int) -> bool:
    """Union-find connectivity of the graph with ``edges[present]`` (weak
    connectivity for directed edge lists)."""
    parent = list(range(n))

    def find(a: int) -> int:
        while parent[a] != a:
            parent[a] = parent[parent[a]]
            a = parent[a]
        return a

    comps = n
    for i, j in edges[present]:
        ri, rj = find(int(i)), find(int(j))
        if ri != rj:
            parent[ri] = rj
            comps -= 1
    return comps == 1


def windowed_connectivity(
    timeline: FaultTimeline, topo: Topology
) -> Optional[int]:
    """B̂: the smallest window length B such that EVERY length-B window's
    union of realized graphs is connected (Koloskova et al. '20
    B-connectivity; weak connectivity for directed graphs).

    This is the quantity the time-varying-gossip rates depend on — NOT the
    marginal drop rate — so at matched marginal, B̂ grows with burst length
    and with outage duration.  Returns None if even the full horizon's
    union graph is disconnected (no finite B exists).  Host-side
    diagnostic: O(T · E · log T) worst case via binary search over B with
    a prefix-count sliding union per candidate.
    """
    alive, edges = _realized_edge_alive(timeline, topo)
    n = topo.n
    T = timeline.horizon
    # Prefix counts: window [s, s+B) contains edge e iff counts differ.
    csum = np.concatenate(
        [np.zeros((1, edges.shape[0]), dtype=np.int64),
         np.cumsum(alive, axis=0, dtype=np.int64)],
        axis=0,
    )

    def all_windows_connected(B: int) -> bool:
        for s in range(T - B + 1):
            present = (csum[s + B] - csum[s]) > 0
            if not _union_connected(present, edges, n):
                return False
        return True

    if not all_windows_connected(T):
        return None
    lo, hi = 1, T  # predicate is monotone in B (bigger window ⊇ union)
    while lo < hi:
        mid = (lo + hi) // 2
        if all_windows_connected(mid):
            hi = mid
        else:
            lo = mid + 1
    return lo


def stack_fault_timelines(timelines: list[FaultTimeline]) -> FaultTimeline:
    """Stack per-replica timelines into one with [R, ...] leading axes.

    The replica-batched execution path (``jax_backend.run_batch``) builds
    one timeline per replica seed host-side, stacks them here, threads the
    stacked arrays through ``vmap`` (in_axes=0), and reconstitutes a
    per-replica ``FaultTimeline`` view inside the traced program — so the
    batched fault realizations are the SAME host arrays the sequential
    runs gather from.  ``edge_index`` is topology-static and shared; the
    fault-process structure (which arrays are present) must match across
    replicas (same config, different seeds).
    """
    if not timelines:
        raise ValueError("need at least one timeline to stack")
    t0 = timelines[0]
    for t in timelines[1:]:
        if (
            t.horizon != t0.horizon
            or t.directed != t0.directed
            or (t.edge_up is None) != (t0.edge_up is None)
            or (t.node_up is None) != (t0.node_up is None)
            or (t.part_up is None) != (t0.part_up is None)
        ):
            raise ValueError(
                "timelines disagree in structure (horizon / fault modes); "
                "replica stacking requires one config over many seeds"
            )

    def _stack(field):
        vals = [getattr(t, field) for t in timelines]
        return np.stack(vals) if vals[0] is not None else None

    return FaultTimeline(
        horizon=t0.horizon,
        directed=t0.directed,
        edge_index=t0.edge_index,
        edge_up=_stack("edge_up"),
        node_up=_stack("node_up"),
        rejoin=_stack("rejoin"),
        part_up=_stack("part_up"),
    )


def make_faulty_mixing(
    topo: Topology,
    drop_prob: float,
    seed: int,
    straggler_prob: float = 0.0,
    one_peer: bool = False,
    burst_len: float = 0.0,
    mttf: float = 0.0,
    mttr: float = 0.0,
    rejoin: str = "frozen",
    horizon: Optional[int] = None,
    keys: Optional[tuple] = None,
    timeline: Optional[FaultTimeline] = None,
    participation_rate: float = 1.0,
    mesh=None,
) -> FaultyMixing:
    """Build time-varying mixing operators for a base topology.

    ``mesh`` (ISSUE-11, docs/PERF.md §16): a 1-D worker ``Mesh`` — the
    matrix-free node-process route then runs SHARDED: timeline columns
    are placed per-shard, and the realized-MH gossip round becomes a
    ppermute halo exchange (``make_halo_faulty_mixing``), bitwise the
    unsharded gather realization. Dense topologies reject a mesh here
    (the sharded path is neighbor-table-native).

    All internal fault machinery (masks, realized adjacency, MH weights,
    degree accounting) runs in float32; only ``mix``/``neighbor_sum`` outputs
    are cast back to the input's dtype.

    Memoryless faults (``drop_prob``/``straggler_prob`` alone) sample masks
    on the fly from (seed, t).  Persistent processes — bursty links
    (``burst_len >= 1``) and crash-recovery churn (``mttf``/``mttr``) —
    require ``horizon`` and route through a precomputed
    ``build_fault_timeline`` (gathered per iteration; bitwise-identical to
    the on-the-fly path at burst_len=1 / the iid-equivalent churn point).

    Replica-batched callers (``jax_backend.run_batch``) override the
    seed-derived randomness per replica: ``keys`` = (fault_key, node_key,
    match_key) pre-derived typed PRNG keys (may be vmap tracers), and
    ``timeline`` = a prebuilt per-replica ``FaultTimeline`` whose arrays
    may be traced [horizon, ...] slices of a stacked replica axis.
    ``drop_prob`` may then also be a traced scalar (a swept axis); traced
    values skip the host-side range validation — the batch caller
    validates per-replica configs before tracing — and always take the
    sampling path (a draw ``u >= p`` with p = 0 keeps every edge, so the
    realization stays correct for any in-range value).
    """
    drop_concrete = isinstance(drop_prob, (int, float))
    if drop_concrete and not 0.0 <= drop_prob < 1.0:
        raise ValueError(f"drop_prob must be in [0, 1), got {drop_prob}")
    # Host-side activity flags: traced drop probabilities always run the
    # sampling math (correct for any value — see the docstring).
    drop_active = (not drop_concrete) or drop_prob > 0.0
    strag_active = straggler_prob > 0.0
    if not 0.0 <= straggler_prob < 1.0:
        raise ValueError(
            f"straggler_prob must be in [0, 1), got {straggler_prob}"
        )
    if topo.directed and one_peer:
        raise ValueError(
            "one_peer gossip is a mutual-matching (undirected) schedule; "
            f"topology {topo.name!r} has one-way links, so a pairwise "
            "exchange cannot be realized"
        )
    if burst_len != 0.0 and burst_len < 1.0:
        raise ValueError(
            f"burst_len must be 0 (iid sampler) or >= 1, got {burst_len}"
        )
    if rejoin not in REJOIN_POLICIES:
        raise ValueError(
            f"Unknown rejoin policy: {rejoin!r}; known: {REJOIN_POLICIES}"
        )
    churn_active = mttf > 0.0 or mttr > 0.0
    if churn_active and one_peer:
        raise ValueError(
            "crash-recovery churn requires the synchronous schedule: rejoin "
            "policies act on the realized neighborhood, which a one-peer "
            "matching (at most one partner per round) cannot supply"
        )
    if not 0.0 < participation_rate <= 1.0:
        raise ValueError(
            f"participation_rate must be in (0, 1], got {participation_rate}"
        )
    participation_active = participation_rate < 1.0
    if participation_active and one_peer:
        raise ValueError(
            "participation sampling requires the synchronous schedule: the "
            "sampled subgraph reweights the whole realized neighborhood, "
            "which a one-peer matching cannot supply"
        )
    use_timeline = (
        burst_len >= 1.0 or churn_active or participation_active
        or timeline is not None
        # Matrix-free faults always route through the precomputed
        # timeline (iid stragglers' chains are bitwise the on-the-fly
        # draws, and iid edge drops are the burst_len=1 point of the
        # per-edge chains, so nothing changes semantically — one code
        # path with no dense [N, N] draw anywhere).
        or (topo.is_matrix_free and (strag_active or drop_active))
    )
    if use_timeline and timeline is None:
        if horizon is None:
            raise ValueError(
                "persistent fault processes (burst_len >= 1, mttf/mttr, or "
                "participation_rate < 1) precompute a [horizon]-indexed "
                "timeline; pass horizon=n_iterations"
            )
        timeline = build_fault_timeline(
            topo, horizon, seed,
            edge_drop_prob=drop_prob,
            burst_len=burst_len if burst_len >= 1.0 else 1.0,
            straggler_prob=0.0 if churn_active else straggler_prob,
            mttf=mttf, mttr=mttr,
            participation_rate=participation_rate,
        )
    if mesh is not None and not topo.is_matrix_free:
        raise ValueError(
            "sharded (worker_mesh) fault mixing is neighbor-table-native: "
            f"dense topology {topo.name!r} has no halo form — build the "
            "graph with topology_impl='neighbor'"
        )
    if topo.is_matrix_free:
        # Matrix-free (neighbor-table-native) route: node-process faults
        # (participation sampling, iid stragglers, crash-recovery churn)
        # AND per-edge drop processes (iid / bursty Gilbert-Elliott
        # chains, ISSUE-9 satellite) — all realized in gather form over
        # the static [N, k_max] table, the [horizon, E] edge chains
        # indexed through the (node, slot) → edge-id map. Matching
        # schedules still need the dense adjacency (partner sampling is
        # an [N, N] argmax) and are rejected upstream and here.
        if one_peer or topo.directed:
            raise ValueError(
                "matrix-free topologies support synchronous fault "
                "processes only; matching schedules and directed graphs "
                "need the dense adjacency — use topology_impl='dense'"
            )
        if mesh is not None:
            if timeline is not None and timeline.edge_up is not None:
                raise ValueError(
                    "sharded (worker_mesh) fault mixing composes node "
                    "processes only; per-edge chains need per-shard "
                    "slicing of the [horizon, E] timeline — run edge "
                    "faults unsharded"
                )
            return make_halo_faulty_mixing(
                topo, mesh, timeline,
                drop_prob=drop_prob, straggler_prob=straggler_prob,
                churn_active=churn_active,
                participation_active=participation_active, rejoin=rejoin,
            )
        return _make_gather_faulty_mixing(
            topo, timeline, drop_prob=drop_prob,
            straggler_prob=straggler_prob, churn_active=churn_active,
            participation_active=participation_active, rejoin=rejoin,
        )
    base_A = jnp.asarray(topo.adjacency, dtype=jnp.float32)
    # Distinct streams from batch sampling: fold tags into the seed key
    # (or take the caller's pre-derived per-replica keys verbatim).
    if keys is None:
        fault_key = jax.random.fold_in(jax.random.key(seed), 0x0FA17)
        node_key = jax.random.fold_in(jax.random.key(seed), 0x57A66)
    else:
        fault_key, node_key, _ = keys

    if use_timeline:
        node_up_dev = (
            jnp.asarray(timeline.node_up)
            if timeline.node_up is not None else None
        )
        part_up_dev = (
            jnp.asarray(timeline.part_up)
            if timeline.part_up is not None else None
        )
        edge_up_dev = (
            jnp.asarray(timeline.edge_up)
            if timeline.edge_up is not None else None
        )
        if edge_up_dev is not None:
            ei = jnp.asarray(timeline.edge_index[:, 0], dtype=jnp.int32)
            ej = jnp.asarray(timeline.edge_index[:, 1], dtype=jnp.int32)
        node_masked = node_up_dev is not None or part_up_dev is not None

        def active(t) -> jax.Array:
            # Realized availability: churn/straggler-up AND sampled-in
            # (participation). Either alone is the mask verbatim.
            if not node_masked:
                return jnp.ones(base_A.shape[0], dtype=jnp.float32)
            if node_up_dev is None:
                return part_up_dev[t].astype(jnp.float32)
            m = node_up_dev[t].astype(jnp.float32)
            if part_up_dev is not None:
                m = m * part_up_dev[t].astype(jnp.float32)
            return m

        def realized_adjacency(t) -> jax.Array:
            if edge_up_dev is not None:
                e = edge_up_dev[t].astype(jnp.float32)
                half = jnp.zeros_like(base_A).at[ei, ej].set(e)
                A_t = half if topo.directed else half + half.T
            else:
                A_t = base_A
            if node_masked:
                m = active(t)
                A_t = A_t * m[:, None] * m[None, :]  # down: exchanges nothing
            return A_t
    else:

        def active(t) -> jax.Array:
            if not strag_active:
                return jnp.ones(base_A.shape[0], dtype=jnp.float32)
            key = jax.random.fold_in(node_key, t)
            u = jax.random.uniform(
                key, (base_A.shape[0],), dtype=jnp.float32
            )
            return (u >= straggler_prob).astype(jnp.float32)

        def realized_adjacency(t) -> jax.Array:
            if not drop_active and not strag_active:
                return base_A  # no fault sampling on the fault-free fast path
            key = jax.random.fold_in(fault_key, t)
            if topo.directed:
                A_t = sample_surviving_directed_adjacency(
                    key, base_A, drop_prob
                )
            else:
                A_t = sample_surviving_adjacency(key, base_A, drop_prob)
            if strag_active:
                m = active(t)
                A_t = A_t * m[:, None] * m[None, :]  # exchanges nothing
            return A_t

    def make_neighbor_liveness(nbr_idx: np.ndarray, nbr_mask: np.ndarray):
        """Gather-form realized adjacency (see the FaultyMixing field doc).

        Host tables come from the caller (built once when the gather
        screening path is selected); the returned ``live(t)`` is
        jit-gatherable and consumes exactly the draws/chains the dense
        ``realized_adjacency`` consumes, so the two forms realize the
        identical graph at every t in every precision.
        """
        n = base_A.shape[0]
        nbr_dev = jnp.asarray(nbr_idx, dtype=jnp.int32)
        mask_dev = jnp.asarray(nbr_mask, dtype=jnp.float32)
        if use_timeline:
            slot_dev = None
            if timeline.edge_up is not None:
                from distributed_optimization_tpu.parallel.topology import (
                    incident_edge_slots,
                )

                slot_dev = jnp.asarray(
                    incident_edge_slots(
                        nbr_idx, nbr_mask, timeline.edge_index
                    ),
                    dtype=jnp.int32,
                )
                edge_up_gather = jnp.asarray(timeline.edge_up)

            def live(t) -> jax.Array:
                out = mask_dev
                if slot_dev is not None:
                    out = out * edge_up_gather[t].astype(jnp.float32)[
                        slot_dev
                    ]
                if timeline.node_up is not None or timeline.part_up is not None:
                    m = active(t)
                    out = out * m[:, None] * m[nbr_dev]
                return out
        else:

            def live(t) -> jax.Array:
                if not drop_active and not strag_active:
                    return mask_dev  # fault-free fast path: static table
                out = mask_dev
                if drop_active:
                    # The SAME symmetric (seed, t) draw as
                    # sample_surviving_adjacency, gathered per slot — the
                    # O(N²) uniform matrix carries no d factor, so the
                    # degree-bounded complexity claim is untouched.
                    key = jax.random.fold_in(fault_key, t)
                    u = jax.random.uniform(key, (n, n), dtype=jnp.float32)
                    u = jnp.triu(u, 1)
                    u = u + u.T
                    out = out * (
                        jnp.take_along_axis(u, nbr_dev, axis=1) >= drop_prob
                    ).astype(jnp.float32)
                if strag_active:
                    m = active(t)
                    out = out * m[:, None] * m[nbr_dev]
                return out

        return live

    rejoin_restart = None
    if churn_active and rejoin == "neighbor_restart":
        rejoin_dev = jnp.asarray(timeline.rejoin)

        def rejoin_restart(t, x) -> jax.Array:
            # Warm restart: a rejoining node replaces its (stale) model row
            # with the average of its REALIZED neighbors' current rows —
            # exactly the neighborhood it can actually hear from on the
            # rejoin round.  Isolated rejoiners (no surviving realized
            # neighbor) keep their stale state.  float32 accumulation floor
            # like all fault machinery; output cast back to the run dtype.
            acc = jnp.promote_types(jnp.float32, x.dtype)
            A_t = realized_adjacency(t).astype(acc)
            deg = jnp.sum(A_t, axis=1)
            nbr_avg = jnp.tensordot(A_t, x.astype(acc), axes=1) / jnp.maximum(
                deg, 1.0
            )[:, None]
            take = rejoin_dev[t] & (deg > 0)
            return jnp.where(
                take[:, None], nbr_avg, x.astype(acc)
            ).astype(x.dtype)

    match_key = (
        jax.random.fold_in(jax.random.key(seed), 0x3A7C4)
        if keys is None else keys[2]
    )

    def partner(t) -> jax.Array:
        key = jax.random.fold_in(match_key, t)
        return sample_one_peer_matching(key, realized_adjacency(t))

    exposed_adjacency = None
    if one_peer:
        mix, neighbor_sum, realized_degree_sum = _matching_ops(partner)
    else:
        exposed_adjacency = realized_adjacency
        # Accumulate in at-least-float32: bf16 inputs get the f32 upcast the
        # accounting needs, while float64 fidelity runs keep full precision
        # (the 0/1 adjacency is exact in any dtype, so casting it up first
        # makes the MH weights exact in the accumulation dtype). Directed
        # graphs renormalize the surviving OUT-weights column-stochastically
        # (the push-sum fault model); undirected graphs recompute MH weights
        # on realized degrees (doubly stochastic for every draw).
        realized_weights = (
            column_stochastic_weights if topo.directed
            else metropolis_hastings_weights
        )

        def mix(t, x):
            acc = jnp.promote_types(jnp.float32, x.dtype)
            W = realized_weights(realized_adjacency(t).astype(acc))
            return jnp.tensordot(W, x.astype(acc), axes=1).astype(x.dtype)

        def neighbor_sum(t, x):
            acc = jnp.promote_types(jnp.float32, x.dtype)
            return jnp.tensordot(
                realized_adjacency(t).astype(acc), x.astype(acc), axes=1
            ).astype(x.dtype)

        def realized_degree_sum(t):
            return jnp.sum(realized_adjacency(t))

    return FaultyMixing(
        mix=mix,
        neighbor_sum=neighbor_sum,
        realized_degree_sum=realized_degree_sum,
        active=active,
        drop_prob=drop_prob,
        straggler_prob=straggler_prob,
        realized_adjacency=exposed_adjacency,
        make_neighbor_liveness=(
            make_neighbor_liveness
            if exposed_adjacency is not None and not topo.directed
            else None
        ),
        churn_active=churn_active,
        rejoin=rejoin,
        rejoin_restart=rejoin_restart,
        participation_active=participation_active,
        timeline=timeline,
    )


def _make_gather_faulty_mixing(
    topo: Topology,
    timeline: FaultTimeline,
    *,
    drop_prob: float,
    straggler_prob: float,
    churn_active: bool,
    participation_active: bool,
    rejoin: str,
) -> FaultyMixing:
    """Node-process faults over a matrix-free (neighbor-table) topology.

    The realized graph at round t is the static table masked by the
    composed node-availability row m_t (churn/straggler-up AND
    sampled-in): ``live_t[i, s] = mask[i, s] · m_t[i] · m_t[nbr[i, s]]``.
    Realized MH weights come straight from the live slots —
    ``w = live / (1 + max(deg_i, deg_{nbr}))`` with the row remainder on
    the diagonal, the identical per-entry formula the dense
    ``metropolis_hastings_weights`` computes on the realized adjacency
    (a fully-masked row degenerates to identity the same way) — so the
    whole time-varying gossip round stays O(N·k_max·d) with no [N, N]
    object anywhere. Same float32 mask/weight convention as the dense
    path; only the mixed model values are cast back to the input dtype.
    """
    n = topo.n
    nbr_dev = jnp.asarray(topo.nbr_idx, dtype=jnp.int32)
    mask_dev = jnp.asarray(topo.nbr_mask, dtype=jnp.float32)
    node_up_dev = (
        jnp.asarray(timeline.node_up)
        if timeline is not None and timeline.node_up is not None else None
    )
    part_up_dev = (
        jnp.asarray(timeline.part_up)
        if timeline is not None and timeline.part_up is not None else None
    )
    # Per-edge chains in gather form (ISSUE-9 satellite): the [horizon, E]
    # liveness bits land on both endpoints' rows through the static
    # (node, slot) → edge-id table — the same symmetric composition the
    # dense path realizes by scattering A[ei, ej] = A[ej, ei] = up[e],
    # with no [N, N] object anywhere.
    edge_up_dev = None
    slot_dev = None
    if timeline is not None and timeline.edge_up is not None:
        from distributed_optimization_tpu.parallel.topology import (
            incident_edge_slots,
        )

        edge_up_dev = jnp.asarray(timeline.edge_up)
        slot_dev = jnp.asarray(
            incident_edge_slots(
                topo.nbr_idx, topo.nbr_mask, timeline.edge_index
            ),
            dtype=jnp.int32,
        )

    def active(t) -> jax.Array:
        if node_up_dev is None and part_up_dev is None:
            return jnp.ones(n, dtype=jnp.float32)
        if node_up_dev is None:
            return part_up_dev[t].astype(jnp.float32)
        m = node_up_dev[t].astype(jnp.float32)
        if part_up_dev is not None:
            m = m * part_up_dev[t].astype(jnp.float32)
        return m

    def live(t) -> jax.Array:
        out = mask_dev
        if edge_up_dev is not None:
            out = out * edge_up_dev[t].astype(jnp.float32)[slot_dev]
        m = active(t)
        return out * m[:, None] * m[nbr_dev]

    def _wshape(x: jax.Array):
        return (n, nbr_dev.shape[1]) + (1,) * (x.ndim - 1)

    def mix(t, x):
        acc = jnp.promote_types(jnp.float32, x.dtype)
        lv = live(t).astype(acc)
        deg = jnp.sum(lv, axis=1)
        w = lv / (1.0 + jnp.maximum(deg[:, None], deg[nbr_dev]))
        w_self = 1.0 - jnp.sum(w, axis=1)
        xa = x.astype(acc)
        out = w_self.reshape((-1,) + (1,) * (x.ndim - 1)) * xa + jnp.sum(
            w.reshape(_wshape(x)) * xa[nbr_dev], axis=1
        )
        return out.astype(x.dtype)

    def neighbor_sum(t, x):
        acc = jnp.promote_types(jnp.float32, x.dtype)
        lv = live(t).astype(acc)
        return jnp.sum(
            lv.reshape(_wshape(x)) * x.astype(acc)[nbr_dev], axis=1
        ).astype(x.dtype)

    def realized_degree_sum(t):
        return jnp.sum(live(t))

    rejoin_restart = None
    if churn_active and rejoin == "neighbor_restart":
        rejoin_dev = jnp.asarray(timeline.rejoin)

        def rejoin_restart(t, x) -> jax.Array:
            # Gather twin of the dense warm restart: a rejoining node's
            # model row becomes its realized-neighborhood average;
            # isolated rejoiners keep their stale state.
            acc = jnp.promote_types(jnp.float32, x.dtype)
            lv = live(t).astype(acc)
            deg = jnp.sum(lv, axis=1)
            nbr_avg = jnp.sum(
                lv[:, :, None] * x.astype(acc)[nbr_dev], axis=1
            ) / jnp.maximum(deg, 1.0)[:, None]
            take = rejoin_dev[t] & (deg > 0)
            return jnp.where(
                take[:, None], nbr_avg, x.astype(acc)
            ).astype(x.dtype)

    def make_neighbor_liveness(nbr_idx: np.ndarray, nbr_mask: np.ndarray):
        # Same contract as the dense path's: live(t) over the CALLER's
        # tables (which, for a matrix-free topology, are the topology's
        # own — there is exactly one table), composing the edge chains
        # through the caller-table slot map plus the node availability.
        caller_nbr = jnp.asarray(nbr_idx, dtype=jnp.int32)
        caller_mask = jnp.asarray(nbr_mask, dtype=jnp.float32)
        caller_slots = None
        if timeline is not None and timeline.edge_up is not None:
            if nbr_idx is topo.nbr_idx and nbr_mask is topo.nbr_mask:
                # The usual case: the caller's tables ARE the topology's
                # own (neighbor_tables_for on a matrix-free topology
                # returns them verbatim) — reuse the slot map computed
                # above instead of redoing the O(N·k_max) Python walk.
                caller_slots = slot_dev
            else:
                from distributed_optimization_tpu.parallel.topology import (
                    incident_edge_slots,
                )

                caller_slots = jnp.asarray(
                    incident_edge_slots(
                        np.asarray(nbr_idx), np.asarray(nbr_mask),
                        timeline.edge_index,
                    ),
                    dtype=jnp.int32,
                )

        def live_fn(t) -> jax.Array:
            out = caller_mask
            if caller_slots is not None:
                out = out * edge_up_dev[t].astype(jnp.float32)[caller_slots]
            m = active(t)
            return out * m[:, None] * m[caller_nbr]

        return live_fn

    return FaultyMixing(
        mix=mix,
        neighbor_sum=neighbor_sum,
        realized_degree_sum=realized_degree_sum,
        active=active,
        drop_prob=drop_prob if isinstance(drop_prob, (int, float)) else 0.0,
        straggler_prob=straggler_prob,
        realized_adjacency=None,
        make_neighbor_liveness=make_neighbor_liveness,
        churn_active=churn_active,
        rejoin=rejoin,
        rejoin_restart=rejoin_restart,
        participation_active=participation_active,
        timeline=timeline,
    )


def make_halo_faulty_mixing(
    topo: Topology,
    mesh,
    timeline: Optional[FaultTimeline],
    *,
    drop_prob: float,
    straggler_prob: float,
    churn_active: bool,
    participation_active: bool,
    rejoin: str,
) -> FaultyMixing:
    """Sharded (worker-mesh) twin of ``_make_gather_faulty_mixing``.

    Node-process faults (iid stragglers, crash-recovery churn, client
    sampling) over a matrix-free topology with the worker axis split into
    contiguous blocks over ``mesh`` (docs/PERF.md §16). The [horizon, N]
    timeline masks are device-placed with their NODE axis sharded — each
    device holds only its own [horizon, N/P] timeline slice — and one
    realized-MH gossip round runs as TWO halo exchanges inside shard_map:
    first the per-node availability bit (1 float per boundary row, so
    each shard can realize its live slots and degrees locally), then the
    model rows with the realized degree riding as one extra column (the
    neighbor-degree term of the MH weight). Per-row arithmetic mirrors
    the unsharded gather form term for term — f32 liveness, accumulation
    dtype floor, identity-row degeneration — so sharded and unsharded
    realizations are BITWISE identical (tests/test_worker_mesh.py).

    Not yet sharded (rejected upstream with the missing piece named):
    per-edge chains (need per-shard [horizon, E] slicing) and the
    ``neighbor_restart`` rejoin policy (needs the halo-averaged warm
    restart).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_optimization_tpu.parallel.collectives import (
        make_halo_exchange,
    )
    from distributed_optimization_tpu.parallel.mesh import WORKER_AXIS

    if timeline is not None and timeline.edge_up is not None:
        raise ValueError(
            "sharded fault mixing composes node processes only (see "
            "make_faulty_mixing)"
        )
    if churn_active and rejoin == "neighbor_restart":
        raise ValueError(
            "rejoin='neighbor_restart' has no sharded form yet (the warm "
            "restart needs the halo-averaged neighborhood) — use 'frozen'"
        )
    n = topo.n
    hx = make_halo_exchange(topo, mesh)
    nbr_global = jnp.asarray(topo.nbr_idx, dtype=jnp.int32)

    def _col_sharded(host_arr):
        # [horizon, N] bool → device array with the NODE axis sharded:
        # the per-shard timeline slice of the tentpole contract.
        return jax.device_put(
            jnp.asarray(host_arr),
            NamedSharding(mesh, P(None, WORKER_AXIS)),
        )

    node_up_dev = (
        _col_sharded(timeline.node_up)
        if timeline is not None and timeline.node_up is not None else None
    )
    part_up_dev = (
        _col_sharded(timeline.part_up)
        if timeline is not None and timeline.part_up is not None else None
    )

    def active(t) -> jax.Array:
        if node_up_dev is None and part_up_dev is None:
            return jnp.ones(n, dtype=jnp.float32)
        if node_up_dev is None:
            return part_up_dev[t].astype(jnp.float32)
        m = node_up_dev[t].astype(jnp.float32)
        if part_up_dev is not None:
            m = m * part_up_dev[t].astype(jnp.float32)
        return m

    def _mix_body(exchange, nbr_l, mask_f32, xb, mb):
        # The unsharded gather form, shard-local: live in f32, weights and
        # models in the accumulation dtype, neighbor degrees fetched
        # through the second exchange's extra column.
        acc = jnp.promote_types(jnp.float32, xb.dtype)
        m_ext = exchange(mb[:, None])[:, 0]               # [S + h + 1] f32
        lv = (mask_f32 * mb[:, None] * m_ext[nbr_l]).astype(acc)
        deg = jnp.sum(lv, axis=1)                          # [S] acc
        xa = xb.astype(acc)
        d2 = xa.shape[-1]
        ext = exchange(jnp.concatenate([xa, deg[:, None]], axis=1))
        gathered = ext[nbr_l]                              # [S, k, d2 + 1]
        w = lv / (1.0 + jnp.maximum(deg[:, None], gathered[:, :, d2]))
        w_self = 1.0 - jnp.sum(w, axis=1)
        out = w_self[:, None] * xa + jnp.sum(
            w[:, :, None] * gathered[:, :, :d2], axis=1
        )
        return out.astype(xb.dtype)

    def _nbr_body(exchange, nbr_l, mask_f32, xb, mb):
        acc = jnp.promote_types(jnp.float32, xb.dtype)
        m_ext = exchange(mb[:, None])[:, 0]
        lv = (mask_f32 * mb[:, None] * m_ext[nbr_l]).astype(acc)
        xa = xb.astype(acc)
        ext = exchange(xa)
        out = jnp.sum(lv[:, :, None] * ext[nbr_l], axis=1)
        return out.astype(xb.dtype)

    def mix(t, x):
        shape = x.shape
        x2 = x.reshape(shape[0], -1)
        out = hx.run(_mix_body, x2, active(t))
        return out.reshape(shape)

    def neighbor_sum(t, x):
        shape = x.shape
        x2 = x.reshape(shape[0], -1)
        out = hx.run(_nbr_body, x2, active(t))
        return out.reshape(shape)

    def realized_degree_sum(t):
        # Observability path (floats accounting / trace): the [N] mask
        # gathered over the global table is a cheap GSPMD gather of N
        # floats — the model-payload traffic stays on the halo path.
        m = active(t)
        lv = (
            jnp.asarray(topo.nbr_mask, dtype=jnp.float32)
            * m[:, None] * m[nbr_global]
        )
        return jnp.sum(lv)

    return FaultyMixing(
        mix=mix,
        neighbor_sum=neighbor_sum,
        realized_degree_sum=realized_degree_sum,
        active=active,
        drop_prob=drop_prob if isinstance(drop_prob, (int, float)) else 0.0,
        straggler_prob=straggler_prob,
        realized_adjacency=None,
        make_neighbor_liveness=None,
        churn_active=churn_active,
        rejoin=rejoin,
        rejoin_restart=None,
        participation_active=participation_active,
        timeline=timeline,
    )
