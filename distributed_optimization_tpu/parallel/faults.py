"""Failure injection: time-varying gossip over dropped edges and stragglers.

The reference has no failure model — its synchronous lockstep loop cannot
lose a worker (SURVEY.md §5.3); its report only *discusses* the parameter
server as a single point of failure. Here two failure modes are first-class,
jit-compatible simulations:

- **link failure** (``drop_prob``): each iteration, every edge of the base
  topology independently drops with probability p (a symmetric draw — both
  endpoints agree the link is down);
- **stragglers / node failure** (``straggler_prob``): each iteration, every
  node independently sits the round out with probability q — it exchanges
  nothing (all incident edges drop) and, in the backend, its state is frozen
  for the iteration (no local gradient step either).

A third *scheduling* mode shares the machinery:

- **one-peer randomized gossip** (``one_peer=True``): instead of averaging
  with ALL surviving neighbors, each node proposes one uniformly random
  neighbor and an edge activates iff the proposal is mutual (Boyd et al.
  '06 randomized gossip, pairwise-averaging form). The realized W_t is
  0.5·(I + P_t) for the involution P_t of matched pairs — each node
  exchanges at most ONE model per iteration, the extreme
  communication-frugality point of the gossip spectrum.

Synchronous gossip runs over the surviving graph with Metropolis–Hastings
weights recomputed on realized degrees; an isolated or inactive node's row
collapses to identity. DIRECTED topologies (round 5) instead drop each
one-way link independently and renormalize each node's surviving
OUT-weights column-stochastically (``column_stochastic_weights``) — the
Nedić-Olshevsky time-varying directed setting push-sum is analyzed under;
every realization conserves total mass (columns sum to 1), which is the
invariant push-sum's debiasing needs, in place of the undirected case's
doubly stochastic average preservation. For UNDIRECTED topologies (synchronous MH recomputation and every matching
schedule) this is the time-varying-graph setting of Koloskova et al. '20
(reference report ref [13]): W_t stays symmetric and doubly stochastic for
every realization, so the network average is preserved and D-SGD and
DIGing-style gradient tracking remain convergent under their
time-varying-gossip analyses — the directed path above intentionally trades
that invariant for column-stochastic mass conservation. For gradient tracking this is not just the
citation: the tracking invariant mean(y_t) = mean(g_t) survives every fault
mode because (a) each realized W_t is doubly stochastic and (b) the
backend's straggler freeze covers ALL state leaves with the frozen node's
mixing row collapsed to identity — verified numerically to accumulation
roundoff through the real backend paths
(tests/test_faults.py::test_gt_tracking_invariant_survives_faults) and
measured on-chip (examples/bench_faults.py gt_* rows). EXTRA does NOT
compose (its fixed-point argument needs a static W — it is rejected
alongside ADMM/CHOCO, see ``Algorithm.supports_edge_faults``).

Fault masks, realized adjacencies, MH weights, and the realized-floats
accounting are always computed in float32 regardless of the run dtype:
under bfloat16 (8 mantissa bits) edge counts above ~256 quantize and MH row
sums pick up off-by-ulp mass, corrupting both the mixing invariants and the
"honest" comms metric. Only the mixed MODEL values are cast to the run
dtype.

Masks are derived purely from (fault key, iteration) — like batch sampling,
fault realizations are reproducible and checkpoint/resume-safe with no
carried RNG state.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from distributed_optimization_tpu.parallel.topology import Topology


@dataclasses.dataclass(frozen=True)
class FaultyMixing:
    """Per-iteration mixing operators over a randomly failing topology.

    ``mix(t, x)``: W_t x with W_t the MH matrix of the surviving graph.
    ``neighbor_sum(t, x)``: A_t x over surviving edges.
    ``realized_degree_sum(t)``: Σ realized deg_i at iteration t (multiply by
    the per-edge payload downstream for the floats-transmitted metric).
    ``active(t)``: [N] 0/1 node-participation mask (all-ones when
    straggler_prob == 0); the backend freezes inactive rows for the step.
    """

    mix: Callable[[jax.Array, jax.Array], jax.Array]
    neighbor_sum: Callable[[jax.Array, jax.Array], jax.Array]
    realized_degree_sum: Callable[[jax.Array], jax.Array]
    active: Callable[[jax.Array], jax.Array]
    drop_prob: float
    straggler_prob: float
    # ``realized_adjacency(t)``: the surviving [N, N] 0/1 graph at t —
    # consumed by the Byzantine robust-aggregation layer so attacks and
    # defenses run over the same per-iteration graph as the mixing. None
    # for matching schedules (one_peer/round_robin), whose single-partner
    # exchanges cannot realize a screening budget (config rejects the
    # combination).
    realized_adjacency: Optional[Callable[[jax.Array], jax.Array]] = None


def sample_surviving_adjacency(key, adjacency: jax.Array, drop_prob: float):
    """Symmetric iid edge-drop mask applied to a 0/1 adjacency matrix."""
    n = adjacency.shape[0]
    u = jax.random.uniform(key, (n, n))
    u = jnp.triu(u, 1)
    u = u + u.T  # symmetric: both endpoints see the same draw
    return jnp.where(u >= drop_prob, adjacency, jnp.zeros_like(adjacency))


def sample_surviving_directed_adjacency(
    key, adjacency: jax.Array, drop_prob: float
):
    """Independent iid drop per DIRECTED edge (no symmetrization).

    Unlike the undirected sampler, the j→i and i→j links (when both exist)
    fail independently — one-way links are exactly what the directed fault
    setting models (Nedić-Olshevsky 2016 time-varying directed graphs)."""
    u = jax.random.uniform(key, adjacency.shape)
    return jnp.where(u >= drop_prob, adjacency, jnp.zeros_like(adjacency))


def column_stochastic_weights(adjacency: jax.Array) -> jax.Array:
    """Uniform-out-weight column-stochastic matrix for a realized directed
    graph (jit-compatible).

    Each node j re-splits its mass equally over its SURVIVING out-neighbors
    and itself: W_ij = 1/(1 + outdeg_j) on realized edges, diagonal = the
    column remainder (exactly 1/(1 + outdeg_j), so an isolated node keeps
    all its mass). Convention matches ``parallel/topology.py``:
    ``adjacency[i, j] = 1`` iff j sends to i, so out-degrees are COLUMN
    sums and ``W @ x`` aggregates received mass. This is the same rule the
    static directed topology builder uses, recomputed per realization — the
    sender-side renormalization push-sum's time-varying-directed analysis
    assumes (each node knows which of its out-links delivered). Columns sum
    to 1 for every realization, so Σ_i (Wx)_i = Σ_j x_j: the mass
    conservation push-sum's debiasing relies on survives every fault draw.
    """
    out_deg = jnp.sum(adjacency, axis=0)
    W = adjacency / (1.0 + out_deg)[None, :]
    return W + jnp.diag(1.0 - jnp.sum(W, axis=0))


def metropolis_hastings_weights(adjacency: jax.Array) -> jax.Array:
    """MH mixing matrix for an arbitrary 0/1 adjacency (jit-compatible).

    W_ij = 1/(1 + max(d_i, d_j)) on edges, diagonal = row remainder — the
    same rule the static topology builder uses (reference
    ``trainer.py:118-126``), but recomputed on-device for each realization.
    Symmetric and doubly stochastic for any undirected graph, including
    isolated nodes (row collapses to W_ii = 1).
    """
    deg = jnp.sum(adjacency, axis=1)
    pair = 1.0 / (1.0 + jnp.maximum(deg[:, None], deg[None, :]))
    W = adjacency * pair
    return W + jnp.diag(1.0 - jnp.sum(W, axis=1))


def _matching_ops(partner_fn):
    """Mixing closures for any matching schedule given partner_fn(t).

    W_t = 0.5 (I + P_t): pairwise averaging with the matched peer (identity
    row for unmatched nodes). Shared by the one-peer randomized and
    round-robin deterministic schedules.
    """

    def mix(t, x):
        return (0.5 * (x + x[partner_fn(t)])).astype(x.dtype)

    def neighbor_sum(t, x):
        p = partner_fn(t)
        matched = (p != jnp.arange(p.shape[0])).astype(x.dtype)
        return (x[p] * matched.reshape((-1,) + (1,) * (x.ndim - 1))).astype(
            x.dtype
        )

    def realized_degree_sum(t):
        # float32 regardless of run dtype: the downstream floats accounting
        # multiplies by the payload and sums over chunks, which overflows
        # int32 at scale and quantizes above ~256 in bfloat16.
        p = partner_fn(t)
        return jnp.sum((p != jnp.arange(p.shape[0])).astype(jnp.float32))

    return mix, neighbor_sum, realized_degree_sum


def make_round_robin_mixing(topo: Topology) -> FaultyMixing:
    """Deterministic matching schedule (``parallel/matchings.py`` phases) as
    time-varying mixing ops, same interface as ``make_faulty_mixing``."""
    from distributed_optimization_tpu.parallel.matchings import (
        round_robin_partners,
    )

    partners = jnp.asarray(round_robin_partners(topo), dtype=jnp.int32)
    n_phases, n = partners.shape
    mix, neighbor_sum, realized_degree_sum = _matching_ops(
        lambda t: partners[t % n_phases]
    )
    return FaultyMixing(
        mix=mix,
        neighbor_sum=neighbor_sum,
        realized_degree_sum=realized_degree_sum,
        active=lambda t: jnp.ones(n, dtype=jnp.float32),
        drop_prob=0.0,
        straggler_prob=0.0,
    )


def sample_one_peer_matching(key, adjacency: jax.Array) -> jax.Array:
    """Mutual-proposal random matching: partner[i] (an involution; self if
    unmatched). Each node proposes a uniformly random neighbor; an edge
    activates iff both endpoints proposed each other."""
    n = adjacency.shape[0]
    idx = jnp.arange(n)
    scores = jax.random.uniform(key, adjacency.shape) * adjacency
    prop = jnp.argmax(scores, axis=1)
    # Isolated rows (all-zero scores) would spuriously propose node 0.
    prop = jnp.where(jnp.sum(adjacency, axis=1) > 0, prop, idx)
    mutual = prop[prop] == idx
    return jnp.where(mutual, prop, idx)


def make_faulty_mixing(
    topo: Topology,
    drop_prob: float,
    seed: int,
    straggler_prob: float = 0.0,
    one_peer: bool = False,
) -> FaultyMixing:
    """Build time-varying mixing operators for a base topology.

    All internal fault machinery (masks, realized adjacency, MH weights,
    degree accounting) runs in float32; only ``mix``/``neighbor_sum`` outputs
    are cast back to the input's dtype.
    """
    if not 0.0 <= drop_prob < 1.0:
        raise ValueError(f"drop_prob must be in [0, 1), got {drop_prob}")
    if not 0.0 <= straggler_prob < 1.0:
        raise ValueError(
            f"straggler_prob must be in [0, 1), got {straggler_prob}"
        )
    if topo.directed and one_peer:
        raise ValueError(
            "one_peer gossip is a mutual-matching (undirected) schedule; "
            f"topology {topo.name!r} has one-way links, so a pairwise "
            "exchange cannot be realized"
        )
    base_A = jnp.asarray(topo.adjacency, dtype=jnp.float32)
    # Distinct streams from batch sampling: fold tags into the seed key.
    fault_key = jax.random.fold_in(jax.random.key(seed), 0x0FA17)
    node_key = jax.random.fold_in(jax.random.key(seed), 0x57A66)

    def active(t) -> jax.Array:
        if straggler_prob == 0.0:
            return jnp.ones(base_A.shape[0], dtype=jnp.float32)
        key = jax.random.fold_in(node_key, t)
        u = jax.random.uniform(key, (base_A.shape[0],))
        return (u >= straggler_prob).astype(jnp.float32)

    def realized_adjacency(t) -> jax.Array:
        if drop_prob == 0.0 and straggler_prob == 0.0:
            return base_A  # no fault sampling on the fault-free fast path
        key = jax.random.fold_in(fault_key, t)
        if topo.directed:
            A_t = sample_surviving_directed_adjacency(key, base_A, drop_prob)
        else:
            A_t = sample_surviving_adjacency(key, base_A, drop_prob)
        if straggler_prob > 0.0:
            m = active(t)
            A_t = A_t * m[:, None] * m[None, :]  # straggler exchanges nothing
        return A_t

    match_key = jax.random.fold_in(jax.random.key(seed), 0x3A7C4)

    def partner(t) -> jax.Array:
        key = jax.random.fold_in(match_key, t)
        return sample_one_peer_matching(key, realized_adjacency(t))

    exposed_adjacency = None
    if one_peer:
        mix, neighbor_sum, realized_degree_sum = _matching_ops(partner)
    else:
        exposed_adjacency = realized_adjacency
        # Accumulate in at-least-float32: bf16 inputs get the f32 upcast the
        # accounting needs, while float64 fidelity runs keep full precision
        # (the 0/1 adjacency is exact in any dtype, so casting it up first
        # makes the MH weights exact in the accumulation dtype). Directed
        # graphs renormalize the surviving OUT-weights column-stochastically
        # (the push-sum fault model); undirected graphs recompute MH weights
        # on realized degrees (doubly stochastic for every draw).
        realized_weights = (
            column_stochastic_weights if topo.directed
            else metropolis_hastings_weights
        )

        def mix(t, x):
            acc = jnp.promote_types(jnp.float32, x.dtype)
            W = realized_weights(realized_adjacency(t).astype(acc))
            return jnp.tensordot(W, x.astype(acc), axes=1).astype(x.dtype)

        def neighbor_sum(t, x):
            acc = jnp.promote_types(jnp.float32, x.dtype)
            return jnp.tensordot(
                realized_adjacency(t).astype(acc), x.astype(acc), axes=1
            ).astype(x.dtype)

        def realized_degree_sum(t):
            return jnp.sum(realized_adjacency(t))

    return FaultyMixing(
        mix=mix,
        neighbor_sum=neighbor_sum,
        realized_degree_sum=realized_degree_sum,
        active=active,
        drop_prob=drop_prob,
        straggler_prob=straggler_prob,
        realized_adjacency=exposed_adjacency,
    )
