"""Failure injection: time-varying gossip over randomly dropped edges.

The reference has no failure model — its synchronous lockstep loop cannot
lose a worker (SURVEY.md §5.3); its report only *discusses* the parameter
server as a single point of failure. Here link failure is a first-class,
jit-compatible simulation: each iteration, every edge of the base topology
independently drops with probability ``drop_prob`` (a symmetric draw — both
endpoints agree the link is down), and gossip runs over the surviving graph
with Metropolis–Hastings weights recomputed on the realized degrees. This is
the time-varying-graph setting of Koloskova et al. '20 (reference report
ref [13]): W_t stays symmetric and doubly stochastic for every realization,
so the network average is preserved and D-SGD/GT/EXTRA remain convergent
under their time-varying-gossip analyses.

Edge masks are derived purely from (fault key, iteration) — like batch
sampling, fault realizations are reproducible and checkpoint/resume-safe with
no carried RNG state.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

from distributed_optimization_tpu.parallel.topology import Topology


@dataclasses.dataclass(frozen=True)
class FaultyMixing:
    """Per-iteration mixing operators over a randomly failing topology.

    ``mix(t, x)``: W_t x with W_t the MH matrix of the surviving graph.
    ``neighbor_sum(t, x)``: A_t x over surviving edges.
    ``realized_floats(t)``: floats a simulator would count as transmitted at
    iteration t (Σ realized deg_i · d is the caller's job — this returns
    Σ realized deg_i; multiply by d and gossip rounds downstream).
    """

    mix: Callable[[jax.Array, jax.Array], jax.Array]
    neighbor_sum: Callable[[jax.Array, jax.Array], jax.Array]
    realized_degree_sum: Callable[[jax.Array], jax.Array]
    drop_prob: float


def sample_surviving_adjacency(key, adjacency: jax.Array, drop_prob: float):
    """Symmetric iid edge-drop mask applied to a 0/1 adjacency matrix."""
    n = adjacency.shape[0]
    u = jax.random.uniform(key, (n, n))
    u = jnp.triu(u, 1)
    u = u + u.T  # symmetric: both endpoints see the same draw
    return jnp.where(u >= drop_prob, adjacency, jnp.zeros_like(adjacency))


def metropolis_hastings_weights(adjacency: jax.Array) -> jax.Array:
    """MH mixing matrix for an arbitrary 0/1 adjacency (jit-compatible).

    W_ij = 1/(1 + max(d_i, d_j)) on edges, diagonal = row remainder — the
    same rule the static topology builder uses (reference
    ``trainer.py:118-126``), but recomputed on-device for each realization.
    Symmetric and doubly stochastic for any undirected graph, including
    isolated nodes (row collapses to W_ii = 1).
    """
    deg = jnp.sum(adjacency, axis=1)
    pair = 1.0 / (1.0 + jnp.maximum(deg[:, None], deg[None, :]))
    W = adjacency * pair
    return W + jnp.diag(1.0 - jnp.sum(W, axis=1))


def make_faulty_mixing(
    topo: Topology, drop_prob: float, seed: int, dtype=jnp.float32
) -> FaultyMixing:
    """Build time-varying mixing operators for a base topology."""
    if not 0.0 <= drop_prob < 1.0:
        raise ValueError(f"drop_prob must be in [0, 1), got {drop_prob}")
    base_A = jnp.asarray(topo.adjacency, dtype=dtype)
    # Distinct stream from batch sampling: fold a tag into the seed key.
    fault_key = jax.random.fold_in(jax.random.key(seed), 0x0FA17)

    def realized_adjacency(t) -> jax.Array:
        key = jax.random.fold_in(fault_key, t)
        return sample_surviving_adjacency(key, base_A, drop_prob)

    def mix(t, x):
        W = metropolis_hastings_weights(realized_adjacency(t))
        return jnp.tensordot(W, x, axes=1).astype(x.dtype)

    def neighbor_sum(t, x):
        return jnp.tensordot(realized_adjacency(t), x, axes=1).astype(x.dtype)

    def realized_degree_sum(t):
        return jnp.sum(realized_adjacency(t))

    return FaultyMixing(
        mix=mix,
        neighbor_sum=neighbor_sum,
        realized_degree_sum=realized_degree_sum,
        drop_prob=drop_prob,
    )
