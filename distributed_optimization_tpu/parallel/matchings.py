"""Deterministic round-robin matching schedules.

The third gossip schedule (besides synchronous all-neighbor averaging and
one-peer *randomized* matchings, ``parallel/faults.py``): cycle through a
fixed sequence of matchings that together cover the topology's edge set —
the deterministic time-varying-graph setting (every edge is exercised every
P iterations, so the union graph over any window of P steps is the full
topology, the connectivity condition of Koloskova et al. '20 / Nedić-Olshevsky
time-varying analyses).

Phases (each phase is a partner involution; unpaired nodes idle):

- **ring** (any N ≥ 3): 2 phases — even pairs (0,1)(2,3)…, odd pairs
  (1,2)(3,4)…; for even N the odd phase wraps (N−1, 0).
- **chain**: same 2 phases without the wrap.
- **grid** (toroidal, even side lengths): 4 phases — horizontal even/odd
  column pairs, vertical even/odd row pairs (the classic torus edge
  4-coloring).

Every W_t = ½(I + P_t) is symmetric and doubly stochastic.
"""

from __future__ import annotations

import numpy as np

from distributed_optimization_tpu.parallel.topology import Topology


def _pair_phase(n: int, start: int, wrap: bool) -> np.ndarray:
    """Partner array pairing (i, i+1 mod n) for i = start, start+2, …"""
    p = np.arange(n)
    stop = n if wrap else n - 1
    for i in range(start, stop, 2):
        j = (i + 1) % n
        p[i], p[j] = j, i
    return p


def round_robin_partners(topo: Topology) -> np.ndarray:
    """[P, N] partner involutions cycling through the topology's edges."""
    n = topo.n
    if topo.name in ("ring", "chain"):
        wrap = topo.name == "ring" and n % 2 == 0
        phases = [_pair_phase(n, 0, wrap=False), _pair_phase(n, 1, wrap=wrap)]
        if topo.name == "ring" and n % 2 == 1:
            # Odd cycles have chromatic index 3: the wrap edge (n−1, 0)
            # needs its own phase.
            p = np.arange(n)
            p[n - 1], p[0] = 0, n - 1
            phases.append(p)
        return np.stack(phases)
    if topo.name == "grid":
        rows, cols = topo.grid_shape  # type: ignore[misc]
        if rows % 2 or cols % 2:
            raise ValueError(
                "round_robin on a toroidal grid needs even side lengths "
                f"(got {rows}x{cols}): odd sides admit no 4-phase edge "
                "coloring with wraparound"
            )
        idx = np.arange(n).reshape(rows, cols)
        phases = []
        for axis, start in ((1, 0), (1, 1), (0, 0), (0, 1)):
            p = np.arange(n).reshape(rows, cols).copy()
            if axis == 1:
                for c in range(start, cols, 2):
                    c2 = (c + 1) % cols
                    p[:, c], p[:, c2] = idx[:, c2], idx[:, c]
            else:
                for r in range(start, rows, 2):
                    r2 = (r + 1) % rows
                    p[r, :], p[r2, :] = idx[r2, :], idx[r, :]
            phases.append(p.reshape(n))
        return np.stack(phases)
    raise ValueError(
        f"round_robin matchings are defined for ring/chain/grid topologies, "
        f"not {topo.name!r}"
    )


def validate_partners(partners: np.ndarray, topo: Topology) -> None:
    """Invariants: involutions, edges of the graph, full edge coverage."""
    n = topo.n
    idx = np.arange(n)
    covered = set()
    for p in partners:
        assert np.array_equal(p[p], idx), "phase is not an involution"
        matched = p != idx
        assert np.all(topo.adjacency[idx[matched], p[matched]] == 1), (
            "phase pairs a non-edge"
        )
        covered.update(
            (min(i, j), max(i, j)) for i, j in zip(idx[matched], p[matched])
        )
    edges = {
        (i, j)
        for i in range(n)
        for j in range(i + 1, n)
        if topo.adjacency[i, j]
    }
    assert covered == edges, "phases do not cover the edge set exactly"
