"""Precomputed event timelines for asynchronous (AD-PSGD-style) gossip.

Everything else in the repo is bulk-synchronous: every round ends at a
barrier, so one straggling worker stalls all N — wall-clock per round is
the MAX of N compute-time draws, which under heavy-tailed latency grows
like the extreme value of the distribution while the mean stays put.
Asynchronous decentralized SGD (Lian et al. '17, AD-PSGD; the
overlap-communication execution of Assran et al. '19) removes the barrier:
each worker fires its own gradient+gossip events at its own pace, so
per-event cost stops depending on the slowest worker while the convergence
rate (per gradient step) matches the synchronous analysis under bounded
staleness.

The jit-ability trick is the one that made bursty faults scannable
(``parallel/faults.py::build_fault_timeline``): because the event ORDER
depends only on presampled per-worker compute-time draws — never on the
optimization state — the whole asynchronous execution can be unrolled once
at setup into a static, totally ordered EVENT SCHEDULE (host arrays), and
the backend then scans over events instead of rounds. The schedule is a
pure function of (topology, horizon, seed, latency model): rebuilt
identically on every backend and after every resume, with NO carried RNG.

Event model (one event = one worker finishing a gradient computation):

- Worker i draws compute durations ``dur[k, i]`` from the configured
  latency distribution (``latency_model`` / ``latency_mean`` /
  ``latency_tail``) and finishes its k-th gradient at virtual time
  ``T_i(k) = Σ_{r<=k} dur[r, i]`` — it starts its next computation
  immediately after its own event completes (communication is modeled as
  instantaneous against compute, the AD-PSGD atomic-average abstraction).
- At its event, worker i holds a gradient computed at the SNAPSHOT it read
  when the computation started (its model right after its previous own
  event). Its live model may have moved since: initiating peers average
  into it, and a pairwise average writes BOTH rows. That gap is the
  event's realized STALENESS — recorded per event as the number of times
  row i was written between read and fire.
- Gossip pairings come from the SAME Boyd et al. '06 mutual-matching
  machinery the synchronous one-peer schedule samples
  (``parallel/faults.py::sample_one_peer_matching``, identical key
  stream): round k has an involution P_k over the static topology, and
  the pair {i, j = P_k[i]} exchanges ONCE per round, at the event of its
  INITIATOR min(i, j) — whenever that worker reaches its k-th event,
  regardless of how far its partner's clock has drifted. The initiator's
  event applies the D-PSGD-ordered update

      x_i, x_j <- (x_i + x_j)/2        (pairwise average, atomic)
      x_i      <- x_i - eta_k * g_i(x_read_i)

  while a non-initiating or unmatched worker's event is a solo local step
  ``x_i <- x_i - eta_k * g_i(x_read_i)`` (its exchange happens passively
  at its initiator's event). Then worker i re-reads
  (``x_read_i <- x_i``) and starts its next gradient. ``eta_k`` follows
  the worker's OWN step count k, so every worker walks the same LR
  schedule the synchronous run walks per round, and per-round comms is
  EXACTLY the synchronous one-peer schedule's (one exchange per matched
  pair).

Events are merged across workers by virtual finish time (ties broken by
worker id, then step — stable, so the degenerate constant-latency schedule
fires workers 0..N-1 in order at every tick). Over any window of N events
every worker fires about once, so "round" comparisons against synchronous
runs use N events per round: a horizon of T rounds is exactly N*T events,
the same total gradient budget as T synchronous iterations.

Why the degenerate case IS synchronous one-peer gossip: at constant
latency every tick fires workers in id order, the initiator (pair min)
fires before its partner, matchings are disjoint, and every gradient was
read at the previous tick's boundary — so tick k realizes exactly
``x' = 0.5 (I + P_k) x − η_k G(x)`` with G at the pre-mix models, the
synchronous one-peer D-PSGD round on the identical matching draws
(bench_async asserts the trajectories agree ≤ 1e-12 f64 under injected
shared batches; the only difference left is XLA program shape).

Why async wins wall-clock: synchronous round r costs ``max_i dur[r, i]``
(``sync_round_times``) — the extreme value of N draws — while
asynchronous progress is paced by each worker's OWN draws; the gap is the
straggler tax, measured in ``examples/bench_async.py``
(docs/perf/async.json).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from distributed_optimization_tpu.parallel.topology import Topology

# Latency models for per-worker compute-time draws. All are normalized so
# the MEAN duration is exactly ``latency_mean`` (the tail knob changes the
# shape, never the mean — matched-mean by construction, so sync and async
# runs burn the same expected compute per gradient step and the measured
# gap is purely the barrier's straggler tax):
# - 'constant':    every draw == latency_mean (the degenerate sync gate);
# - 'exponential': Exp with mean latency_mean (memoryless jitter);
# - 'lognormal':   exp(sigma Z - sigma^2/2) * latency_mean with
#                  sigma = latency_tail (heavy upper tail for sigma >~ 1);
# - 'pareto':      Pareto(alpha = latency_tail > 1) scaled to the mean
#                  (the extreme-tail stress case; alpha <= 1 has no mean).
LATENCY_MODELS = ("constant", "exponential", "lognormal", "pareto")

# Derivation tag for the duration stream. Drawing [horizon, N] row-major
# from a dedicated Generator keeps the timeline PREFIX-STABLE in the
# horizon: the first H rounds of a longer build are bit-identical to a
# shorter build's — the same contract build_fault_timeline gets from
# per-t fold_in keys. (Matchings use the synchronous one-peer sampler's
# jax key stream verbatim — see ``_round_matchings`` — so the degenerate
# constant-latency schedule realizes the IDENTICAL pairings a sync
# one_peer run realizes.)
_DURATION_TAG = 0xE7D7


@dataclasses.dataclass(frozen=True)
class EventTimeline:
    """Precomputed, totally ordered asynchronous event schedule (host arrays).

    Pure function of (topology, horizon, seed, latency params) — see
    ``build_event_timeline``. All per-event arrays are indexed by the
    global event order; ``durations`` keeps the raw [horizon, N] draws so
    synchronous wall-clock twins (``sync_round_times``) price the SAME
    realization.
    """

    n_workers: int
    n_rounds: int            # per-worker gradient steps (the horizon, T)
    latency_model: str
    latency_mean: float
    latency_tail: float
    worker: np.ndarray       # [E] int32 firing worker, E = N * T
    partner: np.ndarray      # [E] int32 gossip partner (== worker: solo)
    local_step: np.ndarray   # [E] int32 firing worker's own step index k
    t_virtual: np.ndarray    # [E] float64 event times, nondecreasing
    staleness: np.ndarray    # [E] int32 writes to row i between read & fire
    durations: np.ndarray    # [T, N] float64 per-(round, worker) draws

    @property
    def n_events(self) -> int:
        return self.worker.shape[0]

    def matched(self) -> np.ndarray:
        """[E] bool — initiator events, each realizing ONE pairwise
        exchange (2·d floats); non-initiator/unmatched events are solo
        local steps and move nothing. Per round the matched count is the
        round's matching size — exactly the synchronous one-peer comms
        budget."""
        return self.partner != self.worker

    def worker_clocks(self) -> np.ndarray:
        """[N] float64 per-worker final virtual clocks — Σ of each
        worker's own durations (passive participations cost nothing)."""
        return self.durations.sum(axis=0)


def _uniforms(seed: int, tag: int, horizon: int, n: int) -> np.ndarray:
    """[horizon, n] float64 open-interval uniforms from a dedicated
    counter-style stream. Row-major fill from a per-purpose Generator
    makes each stream prefix-stable in the horizon; nextafter keeps draws
    strictly inside (0, 1) so every inverse-CDF below is finite."""
    rng = np.random.default_rng([seed & 0xFFFFFFFF, tag])
    u = rng.random((horizon, n))
    return np.clip(u, np.nextafter(0.0, 1.0), np.nextafter(1.0, 0.0))


def sample_durations(
    horizon: int,
    n: int,
    seed: int,
    *,
    latency_model: str,
    latency_mean: float,
    latency_tail: float,
) -> np.ndarray:
    """[horizon, n] float64 compute-time draws, mean == latency_mean.

    Every model is realized by an explicit inverse-CDF over exactly one
    (lognormal: two, Box-Muller) uniform per cell, so the draw count per
    cell is fixed and the stream stays prefix-stable — numpy's ziggurat
    samplers consume a data-dependent number of uniforms and would break
    that contract.
    """
    if horizon <= 0:
        raise ValueError(f"event horizon must be positive, got {horizon}")
    if latency_mean <= 0.0:
        raise ValueError(
            f"latency_mean must be positive, got {latency_mean}"
        )
    if latency_model == "constant":
        return np.full((horizon, n), float(latency_mean))
    u = _uniforms(seed, _DURATION_TAG, horizon, n)
    if latency_model == "exponential":
        return -latency_mean * np.log1p(-u)
    if latency_model == "lognormal":
        sigma = float(latency_tail)
        if sigma <= 0.0:
            raise ValueError(
                "latency_model='lognormal' needs latency_tail > 0 "
                f"(the log-std tail knob), got {latency_tail}"
            )
        u2 = _uniforms(seed, _DURATION_TAG + 1, horizon, n)
        z = np.sqrt(-2.0 * np.log(u)) * np.cos(2.0 * np.pi * u2)
        return latency_mean * np.exp(sigma * z - 0.5 * sigma * sigma)
    if latency_model == "pareto":
        alpha = float(latency_tail)
        if alpha <= 1.0:
            raise ValueError(
                "latency_model='pareto' needs latency_tail > 1 (the "
                f"shape alpha; alpha <= 1 has no finite mean), got "
                f"{latency_tail}"
            )
        x_m = latency_mean * (alpha - 1.0) / alpha
        return x_m / np.power(u, 1.0 / alpha)
    raise ValueError(
        f"Unknown latency model: {latency_model!r}; known: {LATENCY_MODELS}"
    )


def _round_matchings(
    topo: Topology, horizon: int, seed: int,
    schedule: str = "one_peer",
) -> np.ndarray:
    """[horizon, N] per-round partner involutions P_k — the EXACT draws the
    synchronous one-peer schedule realizes.

    Precomputed host-side through the same sampler and key stream
    (``sample_one_peer_matching`` under ``fold_in(key(seed), 0x3A7C4)``,
    the match-key tag ``make_faulty_mixing`` derives), the
    build_fault_timeline convention: schedules may be unrolled with jax,
    math twins stay independent. Per-t fold_in keys make the array
    prefix-stable in the horizon.

    ``schedule``: the event axis realizes the same matching schedules the
    synchronous paths run — ``'one_peer'`` (and ``'synchronous'``, the
    config default, which on the event axis NAMES the same sampled
    matchings) draws the mutual random matching per round;
    ``'round_robin'`` cycles the deterministic edge-coloring phases
    (``parallel/matchings.py::round_robin_partners``), identical to the
    synchronous round-robin realization round for round.
    """
    import jax
    import jax.numpy as jnp

    from distributed_optimization_tpu.parallel.faults import (
        sample_one_peer_matching,
    )

    if schedule == "round_robin":
        from distributed_optimization_tpu.parallel.matchings import (
            round_robin_partners,
        )

        phases = np.asarray(round_robin_partners(topo), dtype=np.int64)
        reps = -(-horizon // phases.shape[0])  # ceil-div
        return np.tile(phases, (reps, 1))[:horizon]
    if schedule not in ("one_peer", "synchronous"):
        raise ValueError(
            f"unknown event matching schedule {schedule!r}; known: "
            "'synchronous'/'one_peer' (sampled mutual matchings) and "
            "'round_robin' (deterministic phases)"
        )
    if topo.is_matrix_free:
        # Unreachable from the shipped async path (config rejects
        # execution='async' with topology_impl='neighbor'); densifying
        # the table here would silently allocate the [N, N] object the
        # matrix-free representation exists to avoid — refuse instead.
        raise ValueError(
            "event timelines sample one-peer matchings from the dense "
            "adjacency; build the topology with impl='dense' (the event "
            "scan's regime is modest N, not the matrix-free axis)"
        )
    A = np.asarray(topo.adjacency, dtype=np.float32)
    A_dev = jnp.asarray(A)
    match_key = jax.random.fold_in(jax.random.key(seed), 0x3A7C4)

    def one(t):
        return sample_one_peer_matching(
            jax.random.fold_in(match_key, t), A_dev
        )

    batched = jax.jit(jax.vmap(one))
    # Chunk the vmap so the per-t (N, N) score draws never materialize as
    # one [horizon, N, N] tensor (at N = 1024 and a few thousand rounds
    # that would be gigabytes of host allocation for draws the sync path
    # streams one round at a time).
    n_nodes = A.shape[0]
    chunk = max(1, 2**22 // max(n_nodes * n_nodes, 1))
    out = np.empty((horizon, n_nodes), dtype=np.int64)
    for s in range(0, horizon, chunk):
        e = min(s + chunk, horizon)
        ts = jnp.arange(s, e, dtype=jnp.int32)
        out[s:e] = np.asarray(batched(ts))
    return out


def build_event_timeline(
    topo: Topology,
    horizon: int,
    seed: int,
    *,
    latency_model: str = "constant",
    latency_mean: float = 1.0,
    latency_tail: float = 0.0,
    gossip_schedule: str = "one_peer",
) -> EventTimeline:
    """Unroll the asynchronous execution into a static event schedule.

    ``horizon`` counts per-worker gradient steps (rounds): the schedule
    holds exactly ``horizon * N`` events. Pure in (topology, horizon,
    seed, latency params) and prefix-stable in the horizon — the first
    H rounds' draws of a longer build are bit-identical — so a resumed or
    re-twinned run rebuilds the identical schedule from the config alone
    (the ``build_fault_timeline`` contract).

    The O(E) host pass below merges the per-worker event streams, assigns
    each round's mutual matching to its initiator events, and replays the
    write counts that define realized staleness. Directed topologies are
    rejected: the pairwise average is a mutual exchange.
    """
    if topo.directed:
        raise ValueError(
            "asynchronous pairwise gossip is an undirected exchange; "
            f"topology {topo.name!r} has one-way links"
        )
    n = topo.n
    durations = sample_durations(
        horizon, n, seed,
        latency_model=latency_model, latency_mean=latency_mean,
        latency_tail=latency_tail,
    )
    finish = np.cumsum(durations, axis=0)  # [T, N] worker i's event times

    # Per-round mutual matchings, shared with the synchronous one-peer
    # sampler; the pair's exchange rides on its INITIATOR's (pair min's)
    # k-th event, so each matched pair exchanges exactly once per round —
    # the one-peer comms budget — while non-initiators fire solo local
    # steps at their own pace.
    P = _round_matchings(topo, horizon, seed, schedule=gossip_schedule)
    idx = np.arange(n, dtype=np.int64)[None, :]
    initiates = (P != idx) & (idx < P)
    partner_kn = np.where(initiates, P, idx)

    # Global order: by virtual finish time, ties by worker id then step —
    # stable and deterministic, so the constant-latency degenerate case
    # fires workers 0..N-1 in id order at every tick.
    step_f = np.repeat(np.arange(horizon, dtype=np.int64), n)
    worker_f = np.tile(np.arange(n, dtype=np.int64), horizon)
    time_f = finish.reshape(-1)
    partner_f = partner_kn.reshape(-1)
    order = np.lexsort((step_f, worker_f, time_f))

    worker = worker_f[order].astype(np.int32)
    partner = partner_f[order].astype(np.int32)
    local_step = step_f[order].astype(np.int32)
    t_virtual = time_f[order]

    # Realized staleness: writes to the firing worker's row between its
    # read (right after its previous own event) and this event. Row i is
    # written only at its own events and at initiator events whose
    # partner is i, so the staleness of i's k-th event is the count of
    # PASSIVE writes strictly between consecutive own events. One stable
    # grouping of own/passive event ids by row (O(E log E) total — never
    # a per-row scan of the full [E] arrays) feeds a per-row
    # searchsorted over small contiguous segments.
    E_total = worker.shape[0]
    staleness = np.zeros(E_total, dtype=np.int32)
    o_order = np.argsort(worker, kind="stable")  # ascending ids per row
    o_bounds = np.searchsorted(worker[o_order], np.arange(n + 1))
    pas_ids = np.flatnonzero(partner != worker)
    p_order = np.argsort(partner[pas_ids], kind="stable")
    pas_sorted = pas_ids[p_order]
    p_bounds = np.searchsorted(partner[pas_sorted], np.arange(n + 1))
    for i in range(n):
        own_idx = o_order[o_bounds[i]:o_bounds[i + 1]]
        pas_idx = pas_sorted[p_bounds[i]:p_bounds[i + 1]]
        before = np.searchsorted(pas_idx, own_idx)
        staleness[own_idx] = np.diff(before, prepend=0).astype(np.int32)

    return EventTimeline(
        n_workers=n,
        n_rounds=horizon,
        latency_model=latency_model,
        latency_mean=float(latency_mean),
        latency_tail=float(latency_tail),
        worker=worker,
        partner=partner,
        local_step=local_step,
        t_virtual=t_virtual,
        staleness=staleness,
        durations=durations,
    )


def sync_round_times(timeline: EventTimeline) -> np.ndarray:
    """[T] float64 cumulative virtual clock of the BULK-SYNCHRONOUS twin.

    A synchronous round ends when its slowest worker finishes, so round r
    costs ``max_i durations[r, i]`` — priced on the SAME latency draws the
    asynchronous schedule consumed, which is what makes sync-vs-async
    wall-clock-to-ε comparisons an apples-to-apples statement about the
    barrier, not about the draw realization.
    """
    return np.cumsum(timeline.durations.max(axis=1))


def staleness_histogram(
    timeline: EventTimeline, max_bucket: int = 8, *, events=None,
) -> dict:
    """Realized-staleness summary: counts per staleness value (values
    >= max_bucket collapsed into one tail bucket), plus mean and max —
    the health_summary/RunTrace block (docs/ASYNC.md). ``events``: an
    optional (start, stop) event window, so a continuation slice's
    health describes the events it actually executed."""
    sl = slice(*events) if events is not None else slice(None)
    s = np.asarray(timeline.staleness[sl], dtype=np.int64)
    buckets: dict[str, int] = {}
    for v in range(max_bucket):
        c = int(np.sum(s == v))
        if c:
            buckets[str(v)] = c
    tail = int(np.sum(s >= max_bucket))
    if tail:
        buckets[f"{max_bucket}+"] = tail
    return {
        "buckets": buckets,
        "mean": float(s.mean()) if s.size else 0.0,
        "max": int(s.max()) if s.size else 0,
    }


# --- event-indexed fault processes (ISSUE-17 tentpole) ---------------------
#
# The round-clock fault chains (``parallel/faults.py::FaultTimeline``) are
# realized ON THE EVENT AXIS by indexing every [horizon, N]/[horizon, E]
# chain at the firing worker's OWN local step: worker i's k-th event
# consults ``node_up[k, i]``, its partner's liveness at ``node_up[k, j]``,
# and the pair's edge chain at row k. Because each worker walks rounds at
# its own pace, this is exactly "the round clock, experienced locally" —
# and at constant latency (where local step == global round for every
# event) the realization collapses BITWISE onto the round-clock arrays
# (tests/test_async_faults.py pins it).


@dataclasses.dataclass(frozen=True)
class EventFaultRealization:
    """Per-event realization of a round-indexed fault timeline (host arrays).

    Semantics (docs/ASYNC.md "Faults on the event clock"):

    - ``fire[e]`` False — the firing worker was crashed (mid-flight loss:
      the in-progress gradient is discarded, nothing is written) or
      sampled out by participation thinning (the event is skipped at the
      matched rate). The event is a total no-op.
    - ``partner[e]`` — the EFFECTIVE partner: the schedule's partner when
      the exchange is alive (both endpoints up and sampled in, edge chain
      up), else the worker itself — the pairing degrades to the solo
      local-step path the schedule already has for unmatched workers.
    - ``rejoin[e]`` True — the worker's first fired event after an outage
      (the round-clock ``FaultTimeline.rejoin`` record, experienced at the
      worker's own pace): the re-entry point where the ``frozen`` /
      ``neighbor_restart`` rejoin policies apply.

    Diagnostics: ``n_inflight_lost`` counts crash no-ops (gradients lost
    mid-flight), ``n_thinned`` participation skips, ``n_degraded`` fired
    matched events whose exchange died (solo fallback);
    ``matched_fired[e]`` marks the events that realized a live pairwise
    exchange — the realized comms accounting bills exactly these.
    """

    fire: np.ndarray           # [E] bool
    partner: np.ndarray        # [E] int32 effective partner (== worker: solo)
    rejoin: np.ndarray         # [E] bool
    matched_fired: np.ndarray  # [E] bool
    n_inflight_lost: int
    n_thinned: int
    n_degraded: int

    @property
    def availability(self) -> float:
        """Realized per-event availability: fired events / all events."""
        return float(self.fire.mean()) if self.fire.size else 1.0


def _edge_id_table(n: int, edge_index: np.ndarray) -> np.ndarray:
    """[N, N] int64 symmetric (i, j) -> edge-chain row lookup (-1: no edge)."""
    eid = np.full((n, n), -1, dtype=np.int64)
    rows = np.arange(edge_index.shape[0], dtype=np.int64)
    eid[edge_index[:, 0], edge_index[:, 1]] = rows
    eid[edge_index[:, 1], edge_index[:, 0]] = rows
    return eid


def realize_event_faults(timeline, faults) -> EventFaultRealization:
    """Realize a round-indexed ``FaultTimeline`` on the event axis.

    Every chain is indexed at the firing worker's LOCAL step (its own
    round count), so the realization is a pure host-side function of the
    two timelines — both backends, the diagnostics, and the incident
    forensics consume the identical arrays (the ``build_fault_timeline``
    purity contract, lifted to events). ``faults.horizon`` must cover the
    schedule's per-worker rounds.
    """
    if faults.horizon < timeline.n_rounds:
        raise ValueError(
            f"fault timeline horizon {faults.horizon} does not cover the "
            f"event schedule's {timeline.n_rounds} per-worker rounds"
        )
    E = timeline.n_events
    n = timeline.n_workers
    k = timeline.local_step.astype(np.int64)
    i = timeline.worker.astype(np.int64)
    j = timeline.partner.astype(np.int64)

    def alive(node):
        """Up AND sampled-in at the node's row of the event's step."""
        a = np.ones(E, dtype=bool)
        if faults.node_up is not None:
            a &= faults.node_up[k, node]
        if faults.part_up is not None:
            a &= faults.part_up[k, node]
        return a

    worker_up = (
        faults.node_up[k, i] if faults.node_up is not None
        else np.ones(E, dtype=bool)
    )
    worker_in = (
        faults.part_up[k, i] if faults.part_up is not None
        else np.ones(E, dtype=bool)
    )
    fire = worker_up & worker_in
    matched = j != i
    exchange = fire & matched & alive(j)
    if faults.edge_up is not None:
        eid = _edge_id_table(n, faults.edge_index)
        ids = eid[i, j]
        exchange &= (ids >= 0) & faults.edge_up[k, np.maximum(ids, 0)]
    partner_eff = np.where(exchange, j, i).astype(np.int32)
    rejoin = (
        (faults.rejoin[k, i] & fire) if faults.rejoin is not None
        else np.zeros(E, dtype=bool)
    )
    return EventFaultRealization(
        fire=fire,
        partner=partner_eff,
        rejoin=rejoin,
        matched_fired=exchange,
        n_inflight_lost=int(np.sum(~worker_up)),
        n_thinned=int(np.sum(worker_up & ~worker_in)),
        n_degraded=int(np.sum(fire & matched & ~exchange)),
    )


def all_up_realization(timeline) -> EventFaultRealization:
    """The degenerate fault-free realization: every event fires, every
    scheduled exchange is live. Exists for the crash-free bitwise gate —
    threading THESE masks through the fault-aware program must reproduce
    the unmasked program's trajectory exactly."""
    matched = timeline.partner != timeline.worker
    return EventFaultRealization(
        fire=np.ones(timeline.n_events, dtype=bool),
        partner=timeline.partner.copy(),
        rejoin=np.zeros(timeline.n_events, dtype=bool),
        matched_fired=matched,
        n_inflight_lost=0,
        n_thinned=0,
        n_degraded=0,
    )


def rejoin_restart_rows(
    timeline, faults, realization: EventFaultRealization, topo: Topology,
) -> np.ndarray:
    """[E, N] float64 warm-restart weight rows for ``neighbor_restart``.

    Zero rows except at rejoin events, where the row is the normalized
    indicator of the rejoining worker's ALIVE realized neighborhood at
    its re-entry step (base-topology neighbors that are up, sampled in,
    and — when an edge chain is active — connected by a live edge). A
    rejoiner with no alive neighbor gets the one-hot self row, i.e. it
    keeps its frozen state — the same fallback the synchronous
    ``rejoin_restart`` path applies. The backend applies
    ``x_i <- w_e @ x`` (and re-reads) at rejoin events BEFORE the update;
    tracker leaves are never restarted, preserving the gradient-tracking
    invariant through every outage.
    """
    E = timeline.n_events
    n = timeline.n_workers
    W = np.zeros((E, n))
    ev_ids = np.flatnonzero(realization.rejoin)
    if ev_ids.size == 0:
        return W
    A = np.asarray(topo.adjacency, dtype=np.float64)
    eid = (
        _edge_id_table(n, faults.edge_index)
        if faults.edge_up is not None else None
    )
    for e in ev_ids:
        kk = int(timeline.local_step[e])
        ii = int(timeline.worker[e])
        row = A[ii].copy()
        if faults.node_up is not None:
            row *= faults.node_up[kk]
        if faults.part_up is not None:
            row *= faults.part_up[kk]
        if eid is not None:
            ids = eid[ii]
            live = (ids >= 0) & faults.edge_up[kk, np.maximum(ids, 0)]
            row *= live
        deg = row.sum()
        if deg > 0:
            W[e] = row / deg
        else:
            W[e, ii] = 1.0
    return W


def clock_skew(timeline: EventTimeline, *, rounds=None) -> dict:
    """Per-worker virtual-clock spread at the horizon (or over an
    optional (start, stop) ROUND window): the realized clock drift a
    barrier would have flattened every round."""
    if rounds is not None:
        clocks = timeline.durations[slice(*rounds)].sum(axis=0)
    else:
        clocks = timeline.worker_clocks()
    mean = float(clocks.mean())
    return {
        "mean": mean,
        "min": float(clocks.min()),
        "max": float(clocks.max()),
        "rel_spread": float((clocks.max() - clocks.min()) / mean)
        if mean > 0 else 0.0,
    }
