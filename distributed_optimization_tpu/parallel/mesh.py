"""Device-mesh construction and worker-axis sharding.

The worker dimension N is the framework's parallel axis: models ``[N, d]``,
stacked data ``[N, L, d]``, and every algorithm-state leaf shard over a 1-D
``Mesh`` along ``'workers'``. Workers-per-device packing (N > number of chips)
is just the block size of that sharding — e.g. 256 workers on a v5e-8 puts 32
worker rows on each chip, and the per-worker math vectorizes across the block
while gossip shifts cross chip boundaries as ICI collectives (SURVEY.md §7
step 8).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

WORKER_AXIS = "workers"


def usable_device_count(n_workers: int, n_devices: int) -> int:
    """Largest device count <= n_devices that divides n_workers evenly."""
    for k in range(min(n_workers, n_devices), 0, -1):
        if n_workers % k == 0:
            return k
    return 1


def make_worker_mesh(
    n_workers: int, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """1-D mesh over the devices that can evenly split ``n_workers``."""
    devices = list(devices if devices is not None else jax.devices())
    k = usable_device_count(n_workers, len(devices))
    return Mesh(devices[:k], (WORKER_AXIS,))


def make_sized_worker_mesh(n_devices: int) -> Mesh:
    """1-D worker mesh of EXACTLY ``n_devices`` devices.

    The ``worker_mesh`` config axis (docs/PERF.md §16) pins the shard
    count as a contract — the halo plan, the per-shard timeline slices
    and the bytes-over-ICI accounting are all built for that exact P —
    so unlike ``make_worker_mesh`` there is no best-effort shrink: too
    few visible devices is an error naming the CPU-host simulation
    escape hatch.
    """
    devices = jax.devices()
    if len(devices) < n_devices:
        raise ValueError(
            f"worker_mesh={n_devices} needs that many devices; only "
            f"{len(devices)} visible — on CPU hosts set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=P before "
            "importing jax"
        )
    return Mesh(devices[:n_devices], (WORKER_AXIS,))


def worker_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Sharding that splits axis 0 (workers) and replicates the rest."""
    return NamedSharding(mesh, P(WORKER_AXIS, *([None] * (ndim - 1))))


def shard_over_workers(mesh: Optional[Mesh], tree):
    """device_put every array leaf with axis 0 split over the worker axis."""
    if mesh is None:
        return jax.tree.map(jax.numpy.asarray, tree)
    return jax.tree.map(
        lambda a: jax.device_put(a, worker_sharding(mesh, a.ndim)), tree
    )


def replicate(mesh: Optional[Mesh], tree):
    """device_put array leaves fully replicated across the mesh."""
    if mesh is None:
        return jax.tree.map(jax.numpy.asarray, tree)
    return jax.tree.map(
        lambda a: jax.device_put(a, NamedSharding(mesh, P())), tree
    )
