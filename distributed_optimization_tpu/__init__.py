"""TPU-native framework for distributed and decentralized stochastic optimization.

Built from scratch in JAX/XLA with the capability surface of
``scavenx/distributed-optimization`` (see SURVEY.md): worker abstraction, graph
topologies + Metropolis-Hastings mixing matrices, centralized and decentralized
optimization algorithms (SGD, D-SGD/DGD, gradient tracking, EXTRA, decentralized
ADMM), convex objective library, synthetic non-IID data generation, reference
optimum computation, and suboptimality / consensus-error / communication-cost
metrics — re-architected TPU-first:

- each *worker* is a shard on a ``jax.sharding.Mesh`` (``[N, d]`` model array,
  ``[N, n_local, d]`` stacked data), not a Python object;
- one training iteration is a pure jitted function and a whole run is a single
  ``jax.lax.scan``;
- the gossip/mixing step compiles to real XLA collectives
  (``jax.lax.ppermute`` for ring/torus neighbor exchange, ``psum`` for
  fully-connected / centralized all-reduce) over ICI, instead of the
  reference's simulated dense ``W @ models`` matmul (reference
  ``trainer.py:173``);
- a numpy backend is retained as the fidelity oracle.
"""

__version__ = "0.1.0"

from distributed_optimization_tpu.config import ExperimentConfig  # noqa: F401
