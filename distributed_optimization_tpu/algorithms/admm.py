"""Decentralized linearized ADMM (DLM; Ling-Shi-Wu-Ribeiro 2015).

Not present in the reference (planned capability from BASELINE.json, which
names "Decentralized ADMM, logistic objective, 16-worker Erdős–Rényi graph").

Edge-consensus formulation: min Σ_i f_i(x_i) s.t. x_i = z_e = x_j per edge
e = (i, j). With zero-initialized duals the auxiliary z eliminates to the
edge midpoint and, linearizing f_i at x_i^k with proximal weight ρ, the
closed-form node updates become (derivation in the class docstring of the
accompanying tests):

    x_i^{k+1} = [ρ x_i^k + (c/2)(d_i x_i^k + Σ_{j∈N_i} x_j^k)
                 − g_i(x_i^k) − α_i^k] / (ρ + c d_i)
    α_i^{k+1} = α_i^k + (c/2)(d_i x_i^{k+1} − Σ_{j∈N_i} x_j^{k+1})

Everything is expressible with the ``neighbor_sum`` collective (A x), so the
same update runs on dense adjacency contractions for irregular Erdős–Rényi
graphs or ppermute stencils for ring/torus. One model-sized exchange per
iteration: the x-update reuses the neighbor sum carried from the previous
iteration's dual update.

The initial neighbor sum A x_0 is materialized once at ``init`` time (the
backend passes its ``neighbor_sum`` collective eagerly, outside the scan), so
warm starts with x_0 ≠ 0 are handled correctly without any per-iteration
guard — the hot loop performs exactly one model-sized exchange, matching the
``gossip_rounds=1`` communication accounting.
"""

from __future__ import annotations

import jax.numpy as jnp

from distributed_optimization_tpu.algorithms.base import (
    Algorithm,
    State,
    StepContext,
    register_algorithm,
)


def _init(x0, config, *, neighbor_sum=None) -> State:
    zeros = jnp.zeros_like(x0)
    nbr_x = neighbor_sum(x0) if neighbor_sum is not None else zeros
    return {"x": x0, "alpha": zeros, "nbr_x": nbr_x}


def _step(state: State, ctx: StepContext) -> State:
    x, alpha, nbr_x = state["x"], state["alpha"], state["nbr_x"]
    c = ctx.config.admm_c
    rho = ctx.config.admm_rho
    deg = ctx.degrees  # [N, 1]
    g = ctx.grad(x, 0)
    x_new = (rho * x + 0.5 * c * (deg * x + nbr_x) - g - alpha) / (rho + c * deg)
    nbr_new = ctx.neighbor_sum(x_new)
    alpha_new = alpha + 0.5 * c * (deg * x_new - nbr_new)
    return {"x": x_new, "alpha": alpha_new, "nbr_x": nbr_new}


ADMM = register_algorithm(
    Algorithm(
        name="admm",
        init=_init,
        step=_step,
        gossip_rounds=1,
        # The dual update pairs neighbor_sum with the STATIC degree d_i; a
        # dropped edge would inject a spurious (c/2)·x_i into alpha each
        # iteration and shift the fixed point.
        supports_edge_faults=False,
    )
)
