"""Algorithm abstraction: a pure init/step pair over an [N, d] model stack.

The reference hard-wires its two algorithms as stateful trainer classes with
Python worker loops (reference ``trainer.py:7-74`` centralized,
``trainer.py:154-197`` D-SGD). Here an algorithm is a *pure step rule* over a
pytree state whose leaves are ``[N, d]``-stacked arrays, so the same rule

- runs inside ``jax.lax.scan`` under ``jit`` on the TPU path,
- runs step-at-a-time under numpy on the fidelity path, and
- is agnostic to how its collectives are realized (the ``StepContext``
  carries ``mix``/``neighbor_sum`` closures that may be a dense matmul, a
  GSPMD stencil, or explicit shard_map ppermute/psum collectives).

Every state pytree has an ``x: [N, d]`` leaf (per-worker models). The
centralized algorithm keeps all rows identical — its "mixing" is the exact
all-reduce mean a parameter server performs, which on the mesh compiles to a
single ``psum`` (SURVEY.md C3).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax

Array = Any  # jax.Array or np.ndarray — algorithms are backend-polymorphic
State = Dict[str, Array]


@dataclasses.dataclass(frozen=True)
class StepContext:
    """Everything a step rule may touch, with backend-supplied semantics.

    ``grad(params, slot)``: stochastic gradient of the local objective at
    ``params`` ([N, d] -> [N, d]); ``slot`` (an int) distinguishes multiple
    independent batch draws within one iteration, so algorithms that need two
    gradient evaluations stay reproducible.
    ``mix``: x -> W x (gossip averaging).
    ``neighbor_sum``: x -> A x (sum over graph neighbors, for ADMM).
    ``eta``: learning rate for this iteration (scalar).
    ``degrees``: [N, 1] node degrees.
    ``config``: the ExperimentConfig (static hyperparameters only).
    ``fused_mix_step``: optional backend-provided fusion of the canonical
    gossip-SGD update, (x, g, eta) -> W x − eta g in one kernel (the pallas
    fast path); algorithms whose update IS that form may use it when present.
    ``compressed_mix``: optional sharded wire form of the error-feedback
    exchange, (q, x̂⁺, halo) -> (W x̂⁺, halo⁺)
    (``collectives.make_halo_compressed_mixing_op``) — present only on the
    worker-mesh path with compression, where the state carries the
    persistent receiver-side halo leaves; algorithms route their
    ``ErrorFeedbackGossip`` exchanges through ``exchange_sharded`` with it.
    """

    grad: Callable[[Array, int], Array]
    mix: Callable[[Array], Array]
    neighbor_sum: Callable[[Array], Array]
    eta: Array
    t: Array
    degrees: Array
    config: Any
    fused_mix_step: Any = None
    compressed_mix: Any = None


@dataclasses.dataclass(frozen=True)
class Algorithm:
    """A named pure step rule.

    ``init(x0, config, *, neighbor_sum=None) -> state``: build the state
    pytree from the [N, d] init; ``neighbor_sum`` (x -> A x), when supplied by
    the backend, lets algorithms that carry a neighbor aggregate (ADMM)
    materialize it for arbitrary x0 once, eagerly, outside the scanned loop.
    ``step(state, ctx) -> state``: one synchronous iteration.
    ``gossip_rounds``: model-sized gossip exchanges per iteration (for the
    analytic floats-transmitted metric, reference trainer.py:169-170).
    ``is_decentralized``: False for the parameter-server pattern (its comms
    cost is 2·N·d per iteration instead, reference trainer.py:44-61).
    """

    name: str
    init: Callable[..., State]
    step: Callable[[State, StepContext], State]
    gossip_rounds: int = 1
    is_decentralized: bool = True
    # Whether the step rule stays correct when the graph varies over time
    # (edge-failure injection). True for mix-based rules — any doubly
    # stochastic W_t preserves the average. False for rules that combine
    # ``neighbor_sum`` with static degree constants (ADMM's dual update),
    # which a dropped edge would bias.
    supports_edge_faults: bool = True
    # Whether the step rule tolerates crash-recovery churn (mttf/mttr:
    # multi-round outages with frozen state and a rejoin policy —
    # parallel/faults.py). Opt-in and STRICTER than supports_edge_faults:
    # beyond per-round doubly stochastic realizations, the rule must stay
    # meaningful when a node's whole state is frozen for many consecutive
    # rounds and may be warm-restarted from the neighborhood average on
    # rejoin. True for D-SGD and gradient tracking (the freeze covers
    # every leaf and each realized W_t keeps the frozen row at identity,
    # so GT's tracking invariant mean(y)=mean(g_prev) survives outages of
    # any length; neighbor_restart touches only the model row). False for
    # push-sum — a warm restart of z cannot be split consistently across
    # its (num, w) mass pair, so rejoin policies would silently break the
    # debiasing — and for EXTRA/ADMM/CHOCO, which already reject
    # time-varying graphs.
    supports_churn: bool = False
    # Whether the step rule tolerates Byzantine injection + robust
    # neighbor aggregation (docs/BYZANTINE.md). Opt-in: only rules whose
    # updates go through ``ctx.mix`` alone and whose analyses cover
    # screened (non-doubly-stochastic) aggregation qualify — D-SGD and
    # gradient tracking (He-Karimireddy-Jaggi 2022). False for EXTRA
    # (fixed point needs the static linear W), ADMM (dual updates pair
    # neighbor sums with static degrees), CHOCO (shared compressed
    # estimates cannot represent screened-out updates), push-sum (clipping
    # breaks the column-stochastic mass conservation its debiasing needs),
    # and the centralized pattern (no peer edges to attack).
    supports_byzantine: bool = False
    # Whether the step rule accepts ``config.local_steps`` > 1 — τ gradient
    # descents per gossip round, the federated local-update regime
    # (Koloskova et al. '20; docs/PERF.md §14). True only for rules whose
    # round structure survives extra purely-local descents: D-SGD (plain
    # local SGD between gossips) and gradient tracking (tracker-corrected
    # local steps). config.LOCAL_STEP_ALGORITHMS mirrors this flag so
    # validation stays jax-free.
    supports_local_steps: bool = False
    # Optional override of the per-edge float payload for comms accounting:
    # (config, d) -> floats per edge per iteration. None = d · gossip_rounds
    # (full-vector exchange). Compressed-gossip algorithms set this.
    comm_payload: Optional[Callable[[Any, int], float]] = None


# Python-unroll budget for the τ−1 extra local descents inside one scan
# trip: beyond it the jax path switches to ``lax.fori_loop`` so program
# size stays bounded (the numpy oracle always takes the Python loop).
LOCAL_UNROLL_MAX = 8


def local_descent_loop(v: Array, ctx: "StepContext", direction) -> Array:
    """Run the round's τ−1 extra LOCAL descents (``config.local_steps``).

    ``direction(v, s)`` maps the current iterate and the in-round slot
    index s ∈ [1, τ) to the descent direction for that local step (plain
    ``ctx.grad(v, s)`` for D-SGD; the tracker-corrected direction for
    gradient tracking). τ = 1 returns ``v`` untouched — ZERO added ops,
    which is what makes the τ=1 reduction bitwise. Unrolled in Python up
    to ``LOCAL_UNROLL_MAX`` (also the only form the backend-polymorphic
    numpy path takes); larger τ on the jax backend runs a ``fori_loop``
    (the slot index reaches ``grad`` as traced data — counter-based batch
    keys fold it in like any other integer).
    """
    tau = ctx.config.local_steps
    if tau <= 1:
        return v
    if ctx.config.backend == "jax" and tau - 1 > LOCAL_UNROLL_MAX:
        from jax import lax

        return lax.fori_loop(
            1, tau, lambda s, vv: vv - ctx.eta * direction(vv, s), v
        )
    for s in range(1, tau):
        v = v - ctx.eta * direction(v, s)
    return v


_REGISTRY: dict[str, Algorithm] = {}


def register_algorithm(algo: Algorithm) -> Algorithm:
    _REGISTRY[algo.name] = algo
    return algo


def get_algorithm(name: str) -> Algorithm:
    from distributed_optimization_tpu.algorithms import (  # noqa: F401
        admm,
        centralized,
        choco,
        dsgd,
        extra,
        gradient_tracking,
        push_sum,
    )

    if name not in _REGISTRY:
        raise ValueError(f"Unknown algorithm: {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name]
