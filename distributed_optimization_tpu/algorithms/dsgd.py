"""Decentralized SGD (D-SGD / DGD, D-PSGD form of Lian et al. 2017).

Capability parity with reference ``trainer.py:154-197``: each iteration every
worker computes its stochastic gradient at its *local, pre-mix* model
(trainer.py:166 — the D-PSGD ordering), gossips models through the mixing
matrix, and steps:

    x_{i,t+1} = Σ_j W_ij x_{j,t} − η_t g_i(x_{i,t})

Communication cost is Σ_i deg_i · d floats per iteration (trainer.py:169-170).

TPU-native form: the gossip Σ_j W_ij x_j is ``ctx.mix`` — a ppermute stencil
(ring/torus), an all-reduce mean (fully connected), or a dense contraction
(irregular graphs) — instead of the reference's simulated ``W @ models``.
"""

from __future__ import annotations

from distributed_optimization_tpu.algorithms.base import (
    Algorithm,
    State,
    StepContext,
    register_algorithm,
)


def _init(x0, config, *, neighbor_sum=None) -> State:
    return {"x": x0}


def _step(state: State, ctx: StepContext) -> State:
    x = state["x"]
    grads = ctx.grad(x, 0)  # at the local pre-mix models (D-PSGD ordering)
    if ctx.fused_mix_step is not None:
        # Backend-fused W x − eta g (single pallas kernel, one HBM pass).
        return {"x": ctx.fused_mix_step(x, grads, ctx.eta)}
    x_new = ctx.mix(x) - ctx.eta * grads
    return {"x": x_new}


DSGD = register_algorithm(
    Algorithm(name="dsgd", init=_init, step=_step, gossip_rounds=1,
              supports_byzantine=True, supports_churn=True)
)
