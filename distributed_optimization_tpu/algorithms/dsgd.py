"""Decentralized SGD (D-SGD / DGD, D-PSGD form of Lian et al. 2017).

Capability parity with reference ``trainer.py:154-197``: each iteration every
worker computes its stochastic gradient at its *local, pre-mix* model
(trainer.py:166 — the D-PSGD ordering), gossips models through the mixing
matrix, and steps:

    x_{i,t+1} = Σ_j W_ij x_{j,t} − η_t g_i(x_{i,t})

Communication cost is Σ_i deg_i · d floats per iteration (trainer.py:169-170).

TPU-native form: the gossip Σ_j W_ij x_j is ``ctx.mix`` — a ppermute stencil
(ring/torus), an all-reduce mean (fully connected), or a dense contraction
(irregular graphs) — instead of the reference's simulated ``W @ models``.

Compressed gossip (``config.compression != 'none'``, ISSUE-6 tentpole): the
exchange routes through the shared error-feedback machinery
(``ops/compression.py::ErrorFeedbackGossip`` — generalized out of CHOCO):
the state carries a per-worker estimate x̂ and each round transmits only
Q(x_half − x̂), the adapt-then-combine recursion

    x_{t+1/2} = x_t − η g(x_t);   x̂⁺ = x̂ + Q(x_{t+1/2} − x̂)
    x_{t+1}   = x_{t+1/2} + γ (W − I) X̂⁺

— i.e. compressed D-SGD IS CHOCO-SGD run under the D-SGD registration,
which is exactly the point: the algorithm the production gather path runs
gains the bytes-per-round knob without changing rule. ``comm_payload``
feeds the compressor's per-edge float cost into the analytic and realized
comms accounting (what the bytes-vs-gap benches measure).
"""

from __future__ import annotations

from distributed_optimization_tpu.algorithms.base import (
    Algorithm,
    State,
    StepContext,
    local_descent_loop,
    register_algorithm,
)


def _init(x0, config, *, neighbor_sum=None) -> State:
    if config.compression != "none":
        from distributed_optimization_tpu.ops.compression import (
            make_error_feedback,
        )

        ef = make_error_feedback(
            config.compression, x0.shape[-1], config.compression_k,
            config.choco_gamma,
        )
        return {"x": x0, "xhat": ef.init(x0)}
    return {"x": x0}


def _step(state: State, ctx: StepContext) -> State:
    x = state["x"]
    if "xhat" in state:
        # Error-feedback compressed gossip (see the module docstring).
        from distributed_optimization_tpu.ops.compression import (
            compression_key,
            make_error_feedback,
        )

        cfg = ctx.config
        ef = make_error_feedback(
            cfg.compression, x.shape[-1], cfg.compression_k,
            cfg.choco_gamma,
        )
        g = ctx.grad(x, 0)
        x_half = x - ctx.eta * g
        if ctx.compressed_mix is not None:
            # Worker-mesh wire form (collectives.make_halo_compressed_
            # mixing_op): q's boundary rows over ppermute, receiver copies
            # in the xhat_halo leaf. Same local algebra — bitwise vs the
            # unsharded branch below at matched N.
            x_new, xhat_new, halo_new = ef.exchange_sharded(
                compression_key(cfg.seed, ctx.t), x_half, state["xhat"],
                state["xhat_halo"], ctx.compressed_mix,
            )
            return {"x": x_new, "xhat": xhat_new, "xhat_halo": halo_new}
        x_new, xhat_new = ef.exchange(
            compression_key(cfg.seed, ctx.t), x_half, state["xhat"],
            ctx.mix,
        )
        return {"x": x_new, "xhat": xhat_new}
    grads = ctx.grad(x, 0)  # at the local pre-mix models (D-PSGD ordering)
    if ctx.fused_mix_step is not None:
        # Backend-fused W x − eta g (single pallas kernel, one HBM pass).
        x_new = ctx.fused_mix_step(x, grads, ctx.eta)
    else:
        x_new = ctx.mix(x) - ctx.eta * grads
    # Federated local updates (config.local_steps = τ; docs/PERF.md §14):
    # the gossip-fused first descent above is local step 0 of the round;
    # τ−1 purely-local SGD descents follow, each on its own batch draw
    # (slot s) at the round's step size — Koloskova et al. '20's
    # local-update regime with the D-PSGD ordering kept for step 0, so
    # τ = 1 is bitwise the historical one-step round.
    x_new = local_descent_loop(x_new, ctx, lambda v, s: ctx.grad(v, s))
    return {"x": x_new}


def _comm_payload(config, d: int) -> float:
    # Per-edge floats per iteration: the compressor's payload (== d for
    # compression='none', so uncompressed accounting is unchanged).
    from distributed_optimization_tpu.ops.compression import make_compressor

    return make_compressor(
        config.compression, d, config.compression_k
    ).floats_per_edge


DSGD = register_algorithm(
    Algorithm(name="dsgd", init=_init, step=_step, gossip_rounds=1,
              supports_byzantine=True, supports_churn=True,
              supports_local_steps=True, comm_payload=_comm_payload)
)
