"""Push-sum stochastic gradient (SGP) over directed graphs.

Not present in the reference, whose Metropolis-Hastings construction
(reference ``trainer.py:118-126``) requires symmetric links. Push-sum
(Kempe-Dobra-Gehrke 2003; Nedić-Olshevsky 2016; stochastic-gradient form
SGP, Assran-Loizou-Markopoulos-Rabbat 2019, Algorithm 1) is the directed
continuation of that family: with only a COLUMN-stochastic mixing matrix A
(each node splits its mass over its out-neighbors — all a node can control
when links are one-way), plain gossip converges to the Perron-weighted
average instead of the true one. Push-sum tracks the induced mass imbalance
with a scalar weight per node and divides it back out:

    num_{t+1} = A (num_t − η_t ∇F(z_t))     — gradient-push on the numerator
    w_{t+1}   = A w_t                        — same chain on the mass, w_0 = 1
    z_{t+1}   = num_{t+1} / w_{t+1}          — the de-biased estimate

Because columns of A sum to 1, Σ_i num_i and Σ_i w_i = N are conserved by
every mix, so mean(num_t) tracks the exact average trajectory and
z_i → mean(num) for every node (A primitive via self-loops). Gradients are
evaluated at the de-biased z (SGP), not the raw numerator.

State layout: ``x`` holds z — the per-worker ESTIMATES — so every metric,
checkpoint, and ``final_models`` consumer sees the quantity that means
"model" here, uniformly with the other algorithms; ``num``/``w`` carry the
push-sum recursion. On a doubly stochastic W (undirected topologies) w
stays exactly 1 and the rule reduces to adapt-then-combine D-SGD — a
degenerate case the tests pin.

Comms: one gossip round transmits the numerator (d floats) plus the scalar
mass (1 float) per directed edge, i.e. ``comm_payload = d + 1`` — the +1 is
push-sum's entire bandwidth overhead over plain gossip.

``supports_edge_faults=True`` (round 5): the failure-injection machinery
(``parallel/faults.py``) realizes the faithful model for BOTH link
orientations. On directed topologies each directed edge drops
independently and every node re-splits its mass column-stochastically over
its SURVIVING out-links (``column_stochastic_weights``) — exactly the
time-varying directed setting of Nedić-Olshevsky 2016, whose analysis is
push-sum's convergence guarantee here; mass conservation Σ_i w_i = N holds
for every realization because every realized matrix is column-stochastic
(pinned through the real backend fault paths by
tests/test_push_sum.py::test_push_sum_mass_conserved_under_directed_faults).
On undirected topologies the realized MH matrices are doubly stochastic,
so w stays exactly 1 and faulty push-sum degenerates to faulty D-SGD.
Stragglers compose: an inactive node's column collapses to identity (it
keeps its mass) and the backend freezes all three state leaves.
"""

from __future__ import annotations

import jax.numpy as jnp

from distributed_optimization_tpu.algorithms.base import (
    Algorithm,
    State,
    StepContext,
    register_algorithm,
)


def _init(x0, config, *, neighbor_sum=None) -> State:
    # ones_like of a column slice inherits x0's worker-axis sharding, so the
    # mass vector lives where its worker's rows live on a mesh.
    w0 = jnp.ones_like(x0[:, :1])
    return {"x": x0, "num": x0, "w": w0}


def _step(state: State, ctx: StepContext) -> State:
    z, num, w = state["x"], state["num"], state["w"]
    g = ctx.grad(z, 0)  # SGP: gradient at the de-biased estimate
    num_new = ctx.mix(num - ctx.eta * g)
    w_new = ctx.mix(w)
    return {"x": num_new / w_new, "num": num_new, "w": w_new}


PUSH_SUM = register_algorithm(
    Algorithm(
        name="push_sum",
        init=_init,
        step=_step,
        gossip_rounds=1,
        supports_edge_faults=True,
        # d model floats + the scalar push-sum mass per edge per round.
        comm_payload=lambda config, d: float(d + 1),
    )
)
