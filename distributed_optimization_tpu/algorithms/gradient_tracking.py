"""Gradient tracking (DIGing; Nedić-Olshevsky-Shi 2017, Koloskova et al. 2020).

Not present in the reference (SURVEY.md §0 lists it as a planned capability
from BASELINE.json). Each worker maintains a tracker y_i estimating the
*network-average* gradient alongside its model:

    x_{t+1} = W x_t − η y_t
    y_{t+1} = W y_t + g(x_{t+1}) − g_prev

which preserves the tracking invariant  mean(y_t) = mean(g_t)  and removes the
non-IID bias floor that plain D-SGD suffers under heterogeneous data — the
setting this study's sorted-partition data generator creates on purpose.

Initialization: y_0 = 0, g_prev = 0, so iteration 0 performs a pure gossip
step and y_1 = g_1 exactly; the invariant mean(y_t) = mean(g_t) holds for all
t ≥ 1 by induction. This avoids needing a batch draw before the scan starts.

Costs two gossip rounds per iteration (x and y), i.e. 2·Σdeg·d floats —
reflected in ``gossip_rounds=2`` for the comms metric.

Fault tolerance (``supports_edge_faults=True``, the default) is
evidence-backed, not assumed: the tracking invariant is an algebraic
identity whenever every realized W_t is doubly stochastic and a straggler's
freeze covers all three state leaves — pinned through the real backend
fault paths in tests/test_faults.py (invariant to ~1e-10 over 400 faulty
float64 iterations) and measured in docs/perf/faults.json.

Byzantine injection (``supports_byzantine=True``): both gossip rounds go
through the corrupt/screen composition. Note the caveat in
docs/BYZANTINE.md — robust (screened) aggregation is not doubly
stochastic, so the tracking invariant above holds only on the
plain-gossip attack path; with a robust rule GT composes mechanically but
the invariant (and with it GT's bias-removal guarantee) is lost, and the
breakdown benches use D-SGD.
"""

from __future__ import annotations

import jax.numpy as jnp

from distributed_optimization_tpu.algorithms.base import (
    Algorithm,
    State,
    StepContext,
    local_descent_loop,
    register_algorithm,
)


def _init(x0, config, *, neighbor_sum=None) -> State:
    zeros = jnp.zeros_like(x0)
    state = {"x": x0, "y": zeros, "g_prev": zeros}
    if config.compression != "none":
        from distributed_optimization_tpu.ops.compression import (
            make_error_feedback,
        )

        ef = make_error_feedback(
            config.compression, x0.shape[-1], config.compression_k,
            config.choco_gamma,
        )
        # One estimate memory per gossiped leaf: both the model and the
        # tracker exchange compressed differences (see _step).
        state["xhat"] = ef.init(x0)
        state["yhat"] = ef.init(x0)
    return state


def _step(state: State, ctx: StepContext) -> State:
    x, y, g_prev = state["x"], state["y"], state["g_prev"]
    if "xhat" in state:
        # Error-feedback compressed gossip (ISSUE-6 tentpole), applied to
        # BOTH gossip rounds through the shared machinery generalized out
        # of CHOCO (ops/compression.py): each round's W-mix is replaced by
        # v + γ(W − I)X̂⁺ over the per-leaf estimate carries, transmitting
        # only Q(v − x̂) per edge — the compressed-gradient-tracking family
        # (CHOCO-style memory on x and y; rounds 0/1 draw distinct
        # compressor keys so the two exchanges never share randomness).
        from distributed_optimization_tpu.ops.compression import (
            compression_key,
            make_error_feedback,
        )

        cfg = ctx.config
        ef = make_error_feedback(
            cfg.compression, x.shape[-1], cfg.compression_k,
            cfg.choco_gamma,
        )
        if ctx.compressed_mix is not None:
            # Worker-mesh wire form: both rounds ship only q boundary rows
            # over ppermute; each gossiped leaf carries its own persistent
            # receiver-side halo (xhat_halo / yhat_halo, zero-seeded by
            # the backend). Local algebra matches the unsharded branch
            # below term for term — bitwise at matched N.
            x_mixed, xhat_new, xh_halo = ef.exchange_sharded(
                compression_key(cfg.seed, ctx.t, round=0), x,
                state["xhat"], state["xhat_halo"], ctx.compressed_mix,
            )
            x_new = x_mixed - ctx.eta * y
            g_new = ctx.grad(x_new, 0)
            y_mixed, yhat_new, yh_halo = ef.exchange_sharded(
                compression_key(cfg.seed, ctx.t, round=1), y,
                state["yhat"], state["yhat_halo"], ctx.compressed_mix,
            )
            return {
                "x": x_new, "y": y_mixed + g_new - g_prev,
                "g_prev": g_new, "xhat": xhat_new, "yhat": yhat_new,
                "xhat_halo": xh_halo, "yhat_halo": yh_halo,
            }
        x_mixed, xhat_new = ef.exchange(
            compression_key(cfg.seed, ctx.t, round=0), x, state["xhat"],
            ctx.mix,
        )
        x_new = x_mixed - ctx.eta * y
        g_new = ctx.grad(x_new, 0)
        y_mixed, yhat_new = ef.exchange(
            compression_key(cfg.seed, ctx.t, round=1), y, state["yhat"],
            ctx.mix,
        )
        return {
            "x": x_new, "y": y_mixed + g_new - g_prev, "g_prev": g_new,
            "xhat": xhat_new, "yhat": yhat_new,
        }
    x_new = ctx.mix(x) - ctx.eta * y
    g_new = ctx.grad(x_new, 0)
    y_new = ctx.mix(y) + g_new - g_prev
    # Federated local updates (config.local_steps = τ; docs/PERF.md §14):
    # τ−1 extra LOCAL descents along the tracker-corrected direction
    # y_new + (g(v, s) − g_new) — the K-GT-style drift correction: the
    # tracker supplies the network-average gradient estimate and the
    # local term only contributes its deviation from the round's base
    # gradient, so local steps keep GT's heterogeneity correction
    # instead of re-introducing client drift. The tracker recursion
    # itself is untouched (y_new above), so the tracking invariant
    # mean(y_t) = mean(g_prev_t) holds for every τ, and τ = 1 adds zero
    # ops — bitwise the historical round.
    v = local_descent_loop(
        x_new, ctx, lambda vv, s: y_new + ctx.grad(vv, s) - g_new
    )
    return {"x": v, "y": y_new, "g_prev": g_new}


def _comm_payload(config, d: int) -> float:
    # Two compressed exchanges per iteration (x and y); == 2d for
    # compression='none', so uncompressed accounting is unchanged.
    from distributed_optimization_tpu.ops.compression import make_compressor

    return 2.0 * make_compressor(
        config.compression, d, config.compression_k
    ).floats_per_edge


GRADIENT_TRACKING = register_algorithm(
    Algorithm(name="gradient_tracking", init=_init, step=_step,
              gossip_rounds=2, supports_byzantine=True, supports_churn=True,
              supports_local_steps=True, comm_payload=_comm_payload)
)
