"""CHOCO-SGD: decentralized SGD with compressed gossip.

Not in the reference (full d-vectors on every edge, reference
``trainer.py:169-173``); this is the compressed-communication capability from
Koloskova, Stich & Jaggi '19 ("Decentralized Stochastic Optimization and
Gossip Algorithms with Compressed Communication" — the report's ref [13]
authors), which trades gossip bandwidth for a consensus step size:

    x_i^{t+1/2} = x_i^t − η_t g_i(x_i^t)
    q_i^t       = Q(x_i^{t+1/2} − x̂_i^t)          ← the ONLY bits transmitted
    x̂_i^{t+1}   = x̂_i^t + q_i^t                    (neighbors update copies)
    x_i^{t+1}   = x_i^{t+1/2} + γ Σ_j W_ij (x̂_j^{t+1} − x̂_i^{t+1}·δ_ij…)
                = x_i^{t+1/2} + γ [(W − I) X̂^{t+1}]_i

With identity compression and γ = 1 this is exactly D-SGD in its
"adapt-then-combine" form, x^{t+1} = W (x^t − η g) (the property the tests
pin down). The stacked form keeps X and X̂ as two [N, d] leaves; the estimate
update is local, and (W − I) X̂ reuses the standard ``mix`` collective, so
compression composes with every mixing implementation. Edge-failure
injection is rejected for CHOCO: a dropped edge means the neighbor's copy of
x̂_j goes stale (it never received q_j), which the single shared X̂ leaf
cannot represent — faithful modeling needs per-edge [N, N, d] staleness
state, so rather than report fault-free convergence with fault-discounted
bandwidth, the combination raises.

Comms accounting: each edge carries the compressor's payload instead of d
floats per iteration (``comm_payload``, consumed by the backends' float
accounting) — top-k/random-k count k values + k indices.
"""

from __future__ import annotations

from distributed_optimization_tpu.algorithms.base import (
    Algorithm,
    State,
    StepContext,
    register_algorithm,
)
from distributed_optimization_tpu.ops.compression import (
    compression_key,
    make_compressor,
    make_error_feedback,
)


def _init(x0, config, *, neighbor_sum=None) -> State:
    ef = make_error_feedback(
        config.compression, x0.shape[-1], config.compression_k,
        config.choco_gamma,
    )
    return {"x": x0, "xhat": ef.init(x0)}


def _step(state: State, ctx: StepContext) -> State:
    # The original CHOCO recursion, now phrased through the SHARED
    # error-feedback exchange (ops/compression.py::ErrorFeedbackGossip —
    # the same machinery compressed dsgd/gradient_tracking run): ops and
    # the counter-based compressor stream are term-for-term the
    # pre-refactor step, so trajectories are bitwise-unchanged
    # (tests/test_choco.py pins the identity-compression == D-SGD
    # equivalence and the refactor parity).
    cfg = ctx.config
    x, xhat = state["x"], state["xhat"]
    ef = make_error_feedback(
        cfg.compression, x.shape[-1], cfg.compression_k, cfg.choco_gamma
    )
    g = ctx.grad(x, 0)
    x_half = x - ctx.eta * g
    if ctx.compressed_mix is not None:
        # Worker-mesh wire form: only q's boundary rows cross devices; the
        # persistent receiver-side copy rides the xhat_halo state leaf
        # (seeded to zeros by the backend). Local algebra is term-for-term
        # the branch below — bitwise vs unsharded at matched N.
        x_new, xhat_new, halo_new = ef.exchange_sharded(
            compression_key(cfg.seed, ctx.t), x_half, xhat,
            state["xhat_halo"], ctx.compressed_mix,
        )
        return {"x": x_new, "xhat": xhat_new, "xhat_halo": halo_new}
    x_new, xhat_new = ef.exchange(
        compression_key(cfg.seed, ctx.t), x_half, xhat, ctx.mix
    )
    return {"x": x_new, "xhat": xhat_new}


def _comm_payload(config, d: int) -> float:
    return make_compressor(config.compression, d, config.compression_k).floats_per_edge


CHOCO = register_algorithm(
    Algorithm(
        name="choco",
        init=_init,
        step=_step,
        gossip_rounds=1,
        comm_payload=_comm_payload,
        # See module docstring: lost q deliveries imply per-neighbor stale
        # estimate copies the shared-X̂ simulation cannot represent.
        supports_edge_faults=False,
    )
)
