"""Optimization algorithms: centralized SGD, D-SGD, gradient tracking, EXTRA,
decentralized (linearized) ADMM, CHOCO-SGD, and push-sum SGP — as pure,
jittable step rules."""

from distributed_optimization_tpu.algorithms.base import Algorithm, get_algorithm  # noqa: F401
