"""EXTRA (Shi, Ling, Wu, Yin 2015): exact first-order decentralized method.

Not present in the reference (planned capability from BASELINE.json). EXTRA
corrects D-SGD's constant-stepsize bias with a one-step memory:

    x_1     = W x_0 − η g(x_0)
    x_{t+1} = (I + W) x_t − W̃ x_{t-1} − η (g(x_t) − g(x_{t-1})),  W̃ = (I+W)/2

With a constant step size it converges to the exact consensus optimum on
convex problems where DGD stalls at a bias floor. One model-sized gossip per
iteration: x_t is mixed once; the W̃ x_{t-1} term reuses the *previous*
iteration's mix result, so no extra communication round is needed
(``mix_x_prev`` is carried in the state).
"""

from __future__ import annotations

import jax.numpy as jnp

from distributed_optimization_tpu.algorithms.base import (
    Algorithm,
    State,
    StepContext,
    register_algorithm,
)


def _init(x0, config, *, neighbor_sum=None) -> State:
    zeros = jnp.zeros_like(x0)
    return {"x": x0, "x_prev": x0, "mix_x_prev": zeros, "g_prev": zeros}


def _step(state: State, ctx: StepContext) -> State:
    x, x_prev = state["x"], state["x_prev"]
    g = ctx.grad(x, 0)
    mix_x = ctx.mix(x)
    # W̃ x_{t-1} = (x_{t-1} + W x_{t-1}) / 2, reusing last iteration's mix.
    w_tilde_x_prev = 0.5 * (x_prev + state["mix_x_prev"])
    general = x + mix_x - w_tilde_x_prev - ctx.eta * (g - state["g_prev"])
    first = mix_x - ctx.eta * g  # the special t = 0 step
    x_new = jnp.where(ctx.t == 0, first, general)
    return {"x": x_new, "x_prev": x, "mix_x_prev": mix_x, "g_prev": g}


EXTRA = register_algorithm(
    Algorithm(
        name="extra",
        init=_init,
        step=_step,
        gossip_rounds=1,
        # EXTRA pairs this iteration's W_t x_t with the CARRIED previous mix
        # W_{t-1} x_{t-1}; its exactness/fixed-point argument requires a
        # static W. Unlike D-SGD and DIGing-style gradient tracking it has no
        # time-varying-graph guarantee, so composing it with edge drops /
        # matching schedules could silently converge to a biased point.
        supports_edge_faults=False,
    )
)
