"""Centralized synchronous mini-batch SGD (parameter-server pattern).

Capability parity with reference ``trainer.py:7-74``: every worker evaluates
its stochastic gradient at the shared global model, the server averages the N
gradients and takes a step with the η₀/√(t+1) schedule. Communication cost is
2·N·d floats per iteration (N uploads + N broadcasts, trainer.py:44-61).

TPU-native form: the model stack keeps all N rows identical; "gather + average
+ broadcast" is one all-reduce mean over the worker mesh axis — the
``fully_connected`` mixing stencil's ``jnp.mean`` compiles to exactly that
``psum``. The step rule only needs the gradient mean, so it uses the mean
directly (no mixing of models required).
"""

from __future__ import annotations

from distributed_optimization_tpu.algorithms.base import (
    Algorithm,
    State,
    StepContext,
    register_algorithm,
)


def _init(x0, config, *, neighbor_sum=None) -> State:
    return {"x": x0}


def _step(state: State, ctx: StepContext) -> State:
    x = state["x"]  # [N, d], all rows identical (invariant)
    grads = ctx.grad(x, 0)  # [N, d] per-worker stochastic grads at the shared model
    avg_grad = grads.mean(axis=0, keepdims=True)  # the all-reduce / psum step
    x_new = x - ctx.eta * avg_grad  # broadcast back: rows stay identical
    return {"x": x_new}


CENTRALIZED = register_algorithm(
    Algorithm(
        name="centralized",
        init=_init,
        step=_step,
        gossip_rounds=0,
        is_decentralized=False,
    )
)
